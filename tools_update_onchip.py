#!/usr/bin/env python
"""Fold /tmp/onchip_results.jsonl (tools_onchip_capture.sh output) into
LAST_ONCHIP.json with provenance. Run after a successful capture:

    python tools_update_onchip.py [results_path]

Keeps only recognized measurement fields (the bench workers' headline
keys), stamps the capture date and git commit, and overwrites
LAST_ONCHIP.json — the provenance-marked fallback bench.py surfaces when
the relay is down at bench time.
"""

import json
import os
import subprocess
import sys
import time

KEEP_PREFIXES = (
    "transformer_", "resnet50_", "lstm_", "googlenet_", "smallnet_",
    "alexnet_", "attention_", "moe_", "matmul_", "batch", "device_kind",
    "peak_tflops_assumed", "flops_source", "pipeline_",
)


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/onchip_results.jsonl"
    if not os.path.exists(path):
        print(f"no capture file at {path}", file=sys.stderr)
        return 1
    merged = {}
    for line in open(path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        for k, v in rec.items():
            if any(k.startswith(p) for p in KEEP_PREFIXES):
                merged[k] = v
    if not merged:
        print("no measurement fields found — not touching LAST_ONCHIP.json",
              file=sys.stderr)
        return 1
    commit = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                            capture_output=True, text=True,
                            cwd=os.path.dirname(os.path.abspath(__file__))
                            ).stdout.strip()
    out = {
        "note": "Numbers measured on the real TPU chip in an earlier "
                "capture window, NOT from the bench run that surfaced "
                "them. bench.py attaches this block when the relay is "
                "unreachable at bench time OR some workers could not "
                "run within its deadline; per-worker fields the run DID "
                "measure fresh appear at top level and take precedence.",
        "measured_on": time.strftime("%Y-%m-%d"),
        "code_state": f"commit {commit}",
        **merged,
    }
    dst = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "LAST_ONCHIP.json")
    with open(dst, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {dst} with {len(merged)} fields from {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
