#!/usr/bin/env bash
# Tier-1 verify wrapper: runs the ROADMAP.md tier-1 command verbatim and
# prints DOTS_PASSED, so the verify line is one script instead of a paste.
#
#   ./tools_tier1.sh            # exit code = pytest's; last line DOTS_PASSED=N
set -o pipefail
cd "$(dirname "$0")"
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --durations=10 \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# flight-recorder surfacing (paddle_tpu.obs): when a conservation
# invariant trips with tracing on, the engine/fleet dumps the recent
# event ring to a postmortem file and stamps its path into the log —
# print those paths next to ANY ladder exit >= 3 so the leak report
# arrives with the event history that produced it
print_postmortems() {
    grep -ao 'OBS-POSTMORTEM: .*' /tmp/_t1.log | sort -u
}
# the serving page-leak invariant checker stamps PAGE-LEAK into any
# failure it raises: a leak anywhere in the suite is a loud, distinct
# failure (exit 3), not one more red test to skim past
if grep -aq 'PAGE-LEAK' /tmp/_t1.log; then
    echo 'PAGE-LEAK: serving free-list conservation violated (see log above)'
    print_postmortems
    exit 3
fi
# same contract for the refcount invariant: a page reference that no
# running/queued request (or fault-plan pressure window) accounts for —
# prefix sharing, COW forks, preemption-unref or eviction went unbalanced
if grep -aq 'REF-LEAK' /tmp/_t1.log; then
    echo 'REF-LEAK: serving page-refcount conservation violated (see log above)'
    print_postmortems
    exit 4
fi
# int8 KV quantization parity (round 12): the parity harness
# (serving/decode_attention.py check_quant_drift, exercised by the
# ragged suite) stamps QUANT-DRIFT into any failure where the int8
# roundtrip exceeds its logit-error bound — a quantization regression
# is a loud, distinct failure (exit 7 extends the ladder), not one
# more red test to skim past
if grep -aq 'QUANT-DRIFT' /tmp/_t1.log; then
    echo 'QUANT-DRIFT: int8 KV parity exceeded its logit-error bound (see log above)'
    print_postmortems
    exit 7
fi
# repo-invariant linter (paddle_tpu.analysis.lint): wall-clock in
# serving/master, unseeded global RNG, per-tick host syncs, mutable
# defaults, import-time FLAGS reads.  Findings print a LINT-FAIL tag;
# exit 5 keeps the loud-failure ladder (PAGE-LEAK=3, REF-LEAK=4).
# The linter's own exit status is checked too: a crash (import error,
# unknown rule) must fail the gate loudly, not fall through as green.
# branch on the linter's OWN exit status, not a grep of the shared log:
# a failing pytest whose captured output happens to contain the literal
# tag must not masquerade as a lint failure
env JAX_PLATFORMS=cpu python -m paddle_tpu.analysis lint 2>&1 | tee -a /tmp/_t1.log
lint_rc=${PIPESTATUS[0]}
if [ "$lint_rc" -eq 1 ]; then
    echo 'LINT-FAIL: repo-invariant lint findings (see log above)'
    print_postmortems
    exit 5
elif [ "$lint_rc" -ne 0 ]; then
    echo "LINT-FAIL: linter itself exited $lint_rc without running to completion"
    print_postmortems
    exit 5
fi
# fleet conservation gate (paddle_tpu.serving.fleet): replays a seeded
# replica-kill chaos trace and checks every fleet rid reached exactly
# one terminal status, nothing completed twice, and no replica pool —
# dead ones included — leaked a page or a ref.  Exit 6 extends the
# ladder (PAGE-LEAK=3, REF-LEAK=4, LINT-FAIL=5); same contract as the
# lint step: branch on the checker's OWN exit status (findings=1,
# crash=2), never on a grep of the shared log.  Run via -c, not -m:
# runpy would execute a second copy of fleet.py next to the one the
# serving package already imported (RuntimeWarning + duplicate classes)
env JAX_PLATFORMS=cpu python -c 'import sys; from paddle_tpu.serving.fleet import main; sys.exit(main(["check"]))' 2>&1 | tee -a /tmp/_t1.log
fleet_rc=${PIPESTATUS[0]}
if [ "$fleet_rc" -eq 1 ]; then
    echo 'FLEET-LEAK: serving-fleet conservation violated (see log above)'
    print_postmortems
    exit 6
elif [ "$fleet_rc" -ne 0 ]; then
    echo "FLEET-LEAK: fleet checker itself exited $fleet_rc without running to completion"
    print_postmortems
    exit 6
fi
# jaxpr compiled-path audit (paddle_tpu.analysis.xla): drives a sealed
# mixed serving steady state (int8 KV, prefix cache on) plus one train
# step under FLAGS.jit_audit, then rule-checks every captured site's
# ClosedJaxpr — donation contracts, dtype promotion drift, host
# callbacks, const-captured weights, collective placement, per-site
# memory/FLOP budgets.  Exit 8 extends the ladder (3/4/5/6/7); same
# contract as the lint/fleet gates: branch on the auditor's OWN exit
# status (findings=1, crash=2), never on a grep of the shared log.
env JAX_PLATFORMS=cpu python -m paddle_tpu.analysis xla 2>&1 | tee -a /tmp/_t1.log
xla_rc=${PIPESTATUS[0]}
if [ "$xla_rc" -eq 1 ]; then
    echo 'XLA-AUDIT: compiled-path contract violated (see log above)'
    print_postmortems
    exit 8
elif [ "$xla_rc" -ne 0 ]; then
    echo "XLA-AUDIT: jaxpr auditor itself exited $xla_rc without running to completion"
    print_postmortems
    exit 8
fi
# static sharding-propagation audit (paddle_tpu.analysis.sharding):
# drives the same sealed serving+trainer steady states as the xla gate
# plus the ZeRO placement jits on a virtual-8 mesh, then checks every
# captured site's declared PartitionSpec contract — contract mismatch,
# implicit all-gathers, accidental replication, axis collisions, and
# the per-tick collective-bytes budget.  Exit 9 extends the ladder
# (3/4/5/6/7/8); same contract as the lint/fleet/xla gates: branch on
# the auditor's OWN exit status (findings=1, crash=2), never on a grep
# of the shared log.
env JAX_PLATFORMS=cpu python -m paddle_tpu.analysis sharding 2>&1 | tee -a /tmp/_t1.log
shard_rc=${PIPESTATUS[0]}
if [ "$shard_rc" -eq 1 ]; then
    echo 'SHARD-AUDIT: sharding-propagation contract violated (see log above)'
    print_postmortems
    exit 9
elif [ "$shard_rc" -ne 0 ]; then
    echo "SHARD-AUDIT: sharding auditor itself exited $shard_rc without running to completion"
    print_postmortems
    exit 9
fi
# checkpoint/resume chaos gate (paddle_tpu.resilience): replays the
# seeded kill+NaN+slow+torn-save training chaos plan under the resume
# supervisor and checks every invariant — final params bit-identical to
# the uninterrupted control, every death resumed from a verified
# checkpoint, injected non-finite steps skipped with optimizer slots
# untouched, zero CKPT-CORRUPT on surviving artifacts, and a kill
# between blob write and meta commit leaving the previous checkpoint
# loadable.  Exit 10 extends the ladder (3/4/5/6/7/8/9); same contract
# as the lint/fleet/xla/shard gates: branch on the checker's OWN exit
# status (findings=1, crash=2), never on a grep of the shared log —
# tests intentionally corrupt checkpoints and print CKPT-CORRUPT lines.
env JAX_PLATFORMS=cpu python -m paddle_tpu.resilience check 2>&1 | tee -a /tmp/_t1.log
resil_rc=${PIPESTATUS[0]}
if [ "$resil_rc" -eq 1 ]; then
    echo 'CKPT-CORRUPT: training checkpoint/resume chaos invariants violated (see log above)'
    print_postmortems
    exit 10
elif [ "$resil_rc" -ne 0 ]; then
    echo "CKPT-CORRUPT: resilience checker itself exited $resil_rc without running to completion"
    print_postmortems
    exit 10
fi
# page-migration conservation gate (paddle_tpu.serving.migrate): replays
# a seeded disaggregated 2-prefill/2-decode fleet with live chain
# handoffs, an injected blob drop (fallback re-prefill), a decode-replica
# kill (prefix re-adoption) and cross-replica prefix seeds, then checks
# the migration ledger balances (started == applied + fallbacks +
# aborted), no transfer is left pending after drain, every replica's O(1)
# prefill-backlog probe matches a from-scratch recompute, and both pools
# conserve pages/refs.  Exit 11 extends the ladder (3/4/5/6/7/8/9/10);
# same contract as the lint/fleet/xla/shard/resilience gates: branch on
# the checker's OWN exit status (findings=1, crash=2), never on a grep of
# the shared log — migration tests intentionally print MIGRATE-LEAK
# lines.  Run via -c, not -m: runpy would execute a second copy of
# migrate.py next to the one the serving package already imported.
env JAX_PLATFORMS=cpu python -c 'import sys; from paddle_tpu.serving.migrate import main; sys.exit(main(["check"]))' 2>&1 | tee -a /tmp/_t1.log
mig_rc=${PIPESTATUS[0]}
if [ "$mig_rc" -eq 1 ]; then
    echo 'MIGRATE-LEAK: page-migration conservation violated (see log above)'
    print_postmortems
    exit 11
elif [ "$mig_rc" -ne 0 ]; then
    echo "MIGRATE-LEAK: migration checker itself exited $mig_rc without running to completion"
    print_postmortems
    exit 11
fi
# multi-tenant control-plane gate (paddle_tpu.serving.control): replays
# a seeded tenant-storm + autoscale + replica-kill trace (WFQ on, SLO
# classes + quotas live, the autoscaler growing then shrinking the
# fleet across the swing) and checks the admission ledger partitions
# per tenant (submitted == admitted + quota_deferred + shed), no
# non-storming tenant missed a deadline, the storming tenant's quota
# bucket actually deferred work, the WFQ drained empty, every token
# stream stayed exactly-once through every scaling event, and every
# replica — killed and drained ones included — conserved pages/refs.
# Exit 12 extends the ladder (3/4/5/6/7/8/9/10/11); same contract as
# the other gates: branch on the checker's OWN exit status (findings=1,
# crash=2), never on a grep of the shared log.  Run via -c, not -m:
# runpy would execute a second copy of control.py next to the one the
# serving package already imported.
env JAX_PLATFORMS=cpu python -c 'import sys; from paddle_tpu.serving.control import main; sys.exit(main(["check"]))' 2>&1 | tee -a /tmp/_t1.log
ctl_rc=${PIPESTATUS[0]}
if [ "$ctl_rc" -eq 1 ]; then
    echo 'CONTROL-LEAK: multi-tenant control-plane invariants violated (see log above)'
    print_postmortems
    exit 12
elif [ "$ctl_rc" -ne 0 ]; then
    echo "CONTROL-LEAK: control checker itself exited $ctl_rc without running to completion"
    print_postmortems
    exit 12
fi
# hierarchical KV-cache gate (paddle_tpu.serving.kv_cache): replays a
# seeded host-tier trace — a clean spill/swap-in round trip must be
# token-identical to a cold prefill, an injected torn spill AND a
# seeded bit-flip must both be caught by the per-page checksum at
# swap-in (degrading to a miss, never a wrong-KV hit), and a
# kill + restart_replica warm restart must re-adopt verified host
# pages with zero duplicate completions — then checks the three-state
# page ledger (device/host/dropped) balances on every engine.  Exit 13
# extends the ladder (3..12); same contract as the other gates: branch
# on the checker's OWN exit status (findings=1, crash=2), never on a
# grep of the shared log.  Run via -c, not -m: runpy would execute a
# second copy of kv_cache.py next to the one the serving package
# already imported.
env JAX_PLATFORMS=cpu python -c 'import sys; from paddle_tpu.serving.kv_cache import main; sys.exit(main(["check"]))' 2>&1 | tee -a /tmp/_t1.log
kv_rc=${PIPESTATUS[0]}
if [ "$kv_rc" -eq 1 ]; then
    echo 'HOSTTIER-LEAK: hierarchical KV-cache invariants violated (see log above)'
    print_postmortems
    exit 13
elif [ "$kv_rc" -ne 0 ]; then
    echo "HOSTTIER-LEAK: kv-cache checker itself exited $kv_rc without running to completion"
    print_postmortems
    exit 13
fi
# concurrency-auditor gate (paddle_tpu.analysis.concurrency): the
# guarded_by lock-discipline checker over every annotated threaded
# module, the declared lifecycle state machines checked statically
# (assignment-site extraction) and dynamically (transition recorder
# during the chaos drives), and the schedule-permutation model checker
# replaying each seeded chaos drive under permuted intra-tick schedules
# — any terminal-fingerprint divergence is a reproducible interleaving
# bug and dumps an OBS-POSTMORTEM for its minimal schedule prefix.
# Exit 14 extends the ladder (3..13); same contract as the other
# gates: branch on the auditor's OWN exit status (findings=1,
# crash=2), never on a grep of the shared log — the conc tests
# intentionally print CONC-AUDIT/PROTO-AUDIT/SCHED-AUDIT lines.
env JAX_PLATFORMS=cpu python -m paddle_tpu.analysis concurrency 2>&1 | tee -a /tmp/_t1.log
conc_rc=${PIPESTATUS[0]}
if [ "$conc_rc" -eq 1 ]; then
    echo 'CONC-AUDIT: concurrency invariants violated (see log above)'
    print_postmortems
    exit 14
elif [ "$conc_rc" -ne 0 ]; then
    echo "CONC-AUDIT: concurrency auditor itself exited $conc_rc without running to completion"
    print_postmortems
    exit 14
fi
exit $rc
