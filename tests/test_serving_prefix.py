"""Automatic prefix caching + chunked prefill (round 9).

Covers the tentpole contract end to end: refcounted PagePool with the
set-backed double-free guard, chained-hash PrefixCache (verified
collisions, LRU eviction), cache-on/off greedy parity against the
non-paged oracle, the copy-on-write fork on full-cover hits, refcount
conservation (REF-LEAK) at every drain, LRU eviction under fault-plan
page pressure and eviction storms, and decode ticks interleaving with a
chunked prefill.  Deterministic throughout — injected clocks, no sleeps.
"""

import numpy as np
import jax
import pytest

from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (DecoderLM, FaultPlan, ManualClock,
                                PageLeakError, PagePool, PrefixCache,
                                RequestStatus, ServingEngine,
                                greedy_decode_reference)

from conftest import assert_serving_drained as assert_drained  # noqa: E402

serving = pytest.mark.serving
prefix = pytest.mark.prefix

pytestmark = [serving, prefix]


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def _small_model(seed=0, **kw):
    kw.setdefault("vocab_size", 50)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("head_dim", 8)
    kw.setdefault("max_positions", 128)
    model = DecoderLM(**kw)
    return model, model.init_params(jax.random.PRNGKey(seed))


# ---------------------------------------------------------------------------
# PagePool: refcounts + set-backed free list
# ---------------------------------------------------------------------------


def test_page_pool_refcounts_and_set_backed_guard():
    pool = PagePool(8)                    # 7 usable
    got = pool.alloc(3)
    assert [pool.refcount(p) for p in got] == [1, 1, 1]
    assert pool.total_refs == 3 and pool.num_live == 3
    pool.ref(got[:2])                     # share two pages
    assert pool.refcount(got[0]) == 2
    pool.free(got)                        # first holder drops all three
    assert pool.num_free == 5             # got[2] hit zero and freed
    assert pool.refcount(got[0]) == 1
    pool.free(got[:2])                    # second holder drops the shared
    assert pool.num_free == 7 and pool.total_refs == 0
    # double free is refused in O(1) via the set mirror
    with pytest.raises(Exception, match="double free"):
        pool.free([got[0]])
    # the mirror agrees with the list and LIFO grant order is preserved:
    # the most recently freed page comes back first
    assert set(pool._free) == pool._free_set
    last_freed = pool._free[-1]
    assert pool.alloc(1) == [last_freed]


def test_page_pool_cached_pages_park_and_release():
    pool = PagePool(6)
    (p,) = pool.alloc(1)
    pool.mark_cached(p)
    pool.free([p])                        # refcount 0 but cached: parked
    assert pool.num_free == 4 and pool.num_reclaimable == 1
    assert pool.refcount(p) == 0 and p not in pool._free_set
    pool.ref([p])                         # a later prefix hit revives it
    assert pool.refcount(p) == 1
    pool.free([p])                        # parked again
    pool.release_cached(p)                # eviction returns it for real
    assert pool.num_free == 5 and pool.num_cached == 0
    assert set(pool._free) == pool._free_set


# ---------------------------------------------------------------------------
# PrefixCache: chained lookup, verification, LRU eviction
# ---------------------------------------------------------------------------


def test_prefix_cache_chain_lookup_and_lru_eviction():
    pool = PagePool(10)
    cache = PrefixCache(pool, page_size=4)
    pages = pool.alloc(3)
    toks = list(range(100, 112))          # 3 full blocks
    cache.insert(toks, pages, upto=12)
    assert len(cache) == 3
    hit, n = cache.lookup(toks)
    assert hit == pages and n == 12
    # a diverging third block stops the chain after two pages
    hit, n = cache.lookup(toks[:8] + [1, 2, 3, 4])
    assert hit == pages[:2] and n == 8
    # partial last block is never matched (full pages only)
    hit, n = cache.lookup(toks[:7])
    assert hit == pages[:1] and n == 4
    # eviction skips pages with live holders...
    pool.free([pages[2]])                 # only block 2 reaches refcount 0
    assert cache.evict(3) == 1
    assert len(cache) == 2 and pool.refcount(pages[0]) == 1
    # ...and frees the rest once their holders are gone
    pool.free(pages[:2])
    assert cache.flush() == 2
    assert pool.num_free == pool.num_usable and len(cache) == 0


def test_prefix_cache_collisions_are_verified_away():
    pool = PagePool(10)
    cache = PrefixCache(pool, page_size=2, hash_fn=lambda prev, blk: 7)
    a = pool.alloc(1)
    cache.insert([5, 6], a, upto=2)
    # same degenerate key, different tokens: verified away, no hit, no
    # second entry clobbering the first
    hit, n = cache.lookup([8, 9])
    assert hit == [] and n == 0
    b = pool.alloc(1)
    cache.insert([8, 9], b, upto=2)
    assert len(cache) == 1                # existing entry wins
    hit, n = cache.lookup([5, 6])
    assert hit == a and n == 2            # the original still verifies


# ---------------------------------------------------------------------------
# engine: cache-on/off parity, sharing, COW forks
# ---------------------------------------------------------------------------


def _engine(model, params, **kw):
    kw.setdefault("eos_id", 1)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 48)
    kw.setdefault("max_pages_per_seq", 10)
    kw.setdefault("max_slots", 4)
    kw.setdefault("buckets", (4, 8, 16))
    return ServingEngine(model, params, **kw)


def test_cache_on_off_parity_with_shared_prefix(rng):
    model, params = _small_model()
    system = rng.randint(2, 50, size=8).tolist()   # page-aligned prefix
    prompts = [system + rng.randint(2, 50, size=k).tolist()
               for k in (3, 1, 5, 2, 4, 6)]
    results = {}
    for pc in (False, True):
        eng = _engine(model, params, prefix_cache=pc)
        rids = [eng.submit(p, max_tokens=8) for p in prompts]
        res = eng.run(max_ticks=400)
        results[pc] = [res[r] for r in rids]
        snap = eng.metrics.snapshot()
        if pc:
            assert snap["prefill_tokens_saved"] > 0
            assert snap["prefix_hit_rate"] > 0
            # cached-prefix requests forwarded fewer prompt tokens
            assert snap["prefill_tokens"] < sum(len(p) for p in prompts)
        else:
            assert snap["prefill_tokens_saved"] == 0
        assert_drained(eng)
    # token-identical with and without the cache, and both match the
    # non-paged oracle
    assert results[True] == results[False]
    for p, toks in zip(prompts, results[True]):
        assert toks == greedy_decode_reference(model, params, p, 8, 1)


def test_cow_fork_full_cover_hit_and_divergence(rng):
    model, params = _small_model()
    eng = _engine(model, params)
    prompt = rng.randint(2, 50, size=8).tolist()   # exactly 2 full pages
    a = eng.submit(prompt, max_tokens=6)
    eng.run(max_ticks=100)                         # prompt pages now cached
    assert eng.metrics.cow_forks == 0
    # identical prompt: full-cover hit -> COW fork, only the last token
    # is recomputed
    b = eng.submit(prompt, max_tokens=6)
    # shares the first page, diverges inside the second block: the
    # divergent tail must not corrupt the pages b reads
    c = eng.submit(prompt[:7] + [49 if prompt[7] != 49 else 48],
                   max_tokens=6)
    res = eng.run(max_ticks=100)
    assert eng.metrics.cow_forks == 1
    assert eng.metrics.prefill_tokens_saved >= (len(prompt) - 1) + 4
    want = greedy_decode_reference(model, params, prompt, 6, 1)
    assert eng.result(a) == want and res[b] == want
    assert res[c] == greedy_decode_reference(
        model, params, prompt[:7] + [49 if prompt[7] != 49 else 48], 6, 1)
    # a fourth identical request after b decoded PAST the forked page
    # proves b's appends landed in private pages, not the shared prefix
    d = eng.submit(prompt, max_tokens=6)
    res = eng.run(max_ticks=100)
    assert res[d] == want
    assert_drained(eng)


def test_mid_prompt_hit_partial_page_tail(rng):
    model, params = _small_model()
    eng = _engine(model, params)
    base = rng.randint(2, 50, size=10).tolist()    # 2 full pages + 2 tail
    a = eng.submit(base, max_tokens=5)
    eng.run(max_ticks=100)
    # same first 8 tokens (the cached full pages), different tail: the
    # mid-prompt-hit path — stitch 8, prefill from position 8
    other = base[:8] + rng.randint(2, 50, size=4).tolist()
    saved_before = eng.metrics.prefill_tokens_saved
    b = eng.submit(other, max_tokens=5)
    res = eng.run(max_ticks=100)
    assert eng.metrics.prefill_tokens_saved - saved_before == 8
    assert res[b] == greedy_decode_reference(model, params, other, 5, 1)
    assert_drained(eng)


def test_preempted_request_reprefills_from_its_own_cache(rng):
    model, params = _small_model(num_layers=1)
    # the known-thrashing geometry: growth must preempt, and the re-
    # prefill should hit the pages the victim itself cached
    eng = _engine(model, params, num_pages=8, max_pages_per_seq=4,
                  max_slots=3)
    prompts = [rng.randint(2, 50, size=4).tolist() for _ in range(3)]
    rids = [eng.submit(p, max_tokens=12) for p in prompts]
    res = eng.run(max_ticks=500)
    assert eng.metrics.preemptions > 0
    assert eng.metrics.prefill_tokens_saved > 0    # re-prefill was cheap
    for p, rid in zip(prompts, rids):
        assert res[rid] == greedy_decode_reference(model, params, p, 12, 1)
    assert_drained(eng)


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------


def test_chunked_prefill_parity_and_decode_interleave(rng):
    model, params = _small_model()
    long_p = rng.randint(2, 50, size=26).tolist()
    short_p = rng.randint(2, 50, size=3).tolist()
    eng = _engine(model, params, prefill_chunk=8, buckets=(4, 8),
                  prefix_cache=False)
    ticks_at_emit = []
    srid = eng.submit(short_p, max_tokens=12,
                      on_token=lambda t: ticks_at_emit.append(eng._tick))
    eng.step()                             # short request starts decoding
    lrid = eng.submit(long_p, max_tokens=4)
    res = eng.run(max_ticks=200)
    assert res[srid] == greedy_decode_reference(model, params, short_p,
                                                12, 1)
    assert res[lrid] == greedy_decode_reference(model, params, long_p,
                                                4, 1)
    # the long prompt needed ceil(26/8)=4 chunk ticks, and the short
    # request kept emitting one token EVERY tick through all of them —
    # chunked prefill interleaves instead of stalling the decode batch.
    # (the first two emissions share a tick: prefill's first token and
    # the same tick's decode — pre-existing single-tick pipelining)
    gaps = np.diff(ticks_at_emit[1:])
    assert (gaps == 1).all()
    assert_drained(eng)


def test_chunked_prefill_with_cached_prefix_positions_offset(rng):
    # cached prefix + chunked tail in one request: prefill starts at the
    # stitched offset and still chunks the remainder
    model, params = _small_model()
    system = rng.randint(2, 50, size=12).tolist()  # 3 full pages
    eng = _engine(model, params, prefill_chunk=4, buckets=(4, 8))
    a = eng.submit(system + rng.randint(2, 50, size=2).tolist(),
                   max_tokens=4)
    eng.run(max_ticks=100)
    tail = rng.randint(2, 50, size=9).tolist()
    b = eng.submit(system + tail, max_tokens=6)    # 12 cached + 9 chunked
    saved_before = eng.metrics.prefill_tokens_saved
    res = eng.run(max_ticks=100)
    assert eng.metrics.prefill_tokens_saved - saved_before == 12
    assert res[b] == greedy_decode_reference(model, params, system + tail,
                                             6, 1)
    assert_drained(eng)


# ---------------------------------------------------------------------------
# eviction under pressure + fault injection
# ---------------------------------------------------------------------------


def test_lru_eviction_under_fault_plan_page_pressure(rng):
    model, params = _small_model(num_layers=1)
    plan = FaultPlan(clock=ManualClock(tick_s=0.01),
                     page_pressure=(2, 30, 10))
    # warm the cache first so the pressure window finds reclaimable pages
    eng = ServingEngine(model, params, eos_id=1, page_size=4, num_pages=16,
                        max_pages_per_seq=4, max_slots=2, buckets=(4, 8),
                        faults=plan)
    warm = [rng.randint(2, 50, size=8).tolist() for _ in range(3)]
    wrids = [eng.submit(p, max_tokens=3) for p in warm]
    eng.run(max_ticks=60)
    assert eng.pool.num_reclaimable > 0
    # under pressure, admissions must evict cached pages instead of
    # stalling or preempting forever
    fresh = [rng.randint(2, 50, size=8).tolist() for _ in range(3)]
    frids = [eng.submit(p, max_tokens=3) for p in fresh]
    res = eng.run(max_ticks=200)
    assert eng.cache.evictions > 0
    for p, rid in zip(warm + fresh, wrids + frids):
        assert res[rid] == greedy_decode_reference(model, params, p, 3, 1)
    assert plan.held_pages == []
    assert_drained(eng)


def test_cache_eviction_storm_keeps_parity(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01), cache_storm=(0, 1000))
    eng = _engine(model, params, faults=plan)
    system = rng.randint(2, 50, size=8).tolist()
    prompts = [system + rng.randint(2, 50, size=k).tolist()
               for k in (2, 3, 4)]
    # staggered max_tokens: completions park pages while peers still
    # run, so the storm has something to flush mid-flight
    rids = [eng.submit(p, max_tokens=m)
            for p, m in zip(prompts, (2, 6, 10))]
    res = eng.run(max_ticks=200)
    # the storm flushes every reclaimable page every tick: hits become
    # rare-to-impossible but nothing corrupts and nothing leaks
    assert eng.cache.evictions > 0
    for p, rid, m in zip(prompts, rids, (2, 6, 10)):
        assert res[rid] == greedy_decode_reference(model, params, p, m, 1)
    assert_drained(eng)
    hz = eng.healthz()
    assert hz["ok"] is True and hz["pages_cached"] == hz["pages_reclaimable"]


def test_hash_collision_fault_degrades_to_miss_not_corruption(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01), hash_collisions=True)
    eng = _engine(model, params, faults=plan)
    system = rng.randint(2, 50, size=8).tolist()
    prompts = [system + rng.randint(2, 50, size=k).tolist()
               for k in (2, 3, 4)]
    rids = [eng.submit(p, max_tokens=6) for p in prompts]
    res = eng.run(max_ticks=200)
    # with EVERY block hashing identically, token verification caps the
    # cache at one entry: at most the first shared block can ever hit
    assert len(eng.cache) <= 1
    assert eng.metrics.prefill_tokens_saved <= 4 * len(prompts)
    for p, rid in zip(prompts, rids):
        assert res[rid] == greedy_decode_reference(model, params, p, 6, 1)
    assert_drained(eng)


# ---------------------------------------------------------------------------
# conservation + healthz
# ---------------------------------------------------------------------------


def test_ref_leak_checker_counts_refs_and_tags_ref_leak(rng):
    model, params = _small_model()
    eng = _engine(model, params)
    rid = eng.submit(rng.randint(2, 50, size=6).tolist(), max_tokens=4)
    eng.step()
    eng.check_page_conservation()          # balanced while running
    req = eng.scheduler.running_requests()[0]
    eng.pool.ref([req.pages[0]])           # a ref nobody accounts for
    with pytest.raises(PageLeakError, match="REF-LEAK"):
        eng.check_page_conservation()
    assert eng.healthz()["page_leak"] is True
    eng.pool.free([req.pages[0]])
    eng.check_page_conservation()
    eng.run(max_ticks=100)
    assert eng.status(rid) is RequestStatus.COMPLETED
    assert_drained(eng)


@pytest.mark.parametrize("chunk", [0, 4])
def test_failed_prefill_never_caches_poisoned_pages(rng, chunk):
    # a prompt whose forward pass produces non-finite logits must not
    # leave its (suspect) K/V pages hittable: one overflowing prompt
    # would otherwise poison every future request sharing the prefix.
    # chunk=4 exercises the per-chunk guard — the poisoned first chunk
    # is caught BEFORE its pages are indexed, so there is no multi-tick
    # window in which a sharer could stitch them
    model, params = _small_model()
    params = dict(params)
    params["emb"] = params["emb"].at[7].set(np.inf)    # token 7 poisons
    eng = _engine(model, params, prefill_chunk=chunk)
    bad = [7] + rng.randint(8, 50, size=9).tolist()    # 2 full pages
    b1 = eng.submit(bad, max_tokens=4)
    eng.run(max_ticks=50)
    assert eng.status(b1) is RequestStatus.FAILED
    assert len(eng.cache) == 0                 # nothing hittable
    # a resubmit finds NO cached prefix (saved stays 0) and fails on its
    # own forward pass, not on stitched poisoned pages
    b2 = eng.submit(bad, max_tokens=4)
    eng.run(max_ticks=50)
    assert eng.status(b2) is RequestStatus.FAILED
    assert eng.metrics.prefill_tokens_saved == 0
    # forgotten pages skipped the reclaimable park: everything is free
    assert eng.pool.num_free == eng.pool.num_usable
    assert_drained(eng)


def test_sharer_of_mid_prefill_chunks_survives_late_poison(rng):
    # A's early chunks pass the finite guard and are cached mid-prefill;
    # B stitches them while A is STILL prefilling; A's LATER chunk then
    # overflows.  The rollback/scrub must be scoped to the failing chunk
    # — wiping A's earlier vouched pages would zero K/V that B is
    # reading, and B would complete with silently wrong tokens
    model, params = _small_model()
    params = dict(params)
    params["emb"] = params["emb"].at[7].set(np.inf)
    eng = _engine(model, params, prefill_chunk=4, buckets=(4, 8),
                  max_slots=2)
    clean8 = rng.randint(8, 50, size=8).tolist()
    a = eng.submit(clean8 + [7, 8], max_tokens=4)  # chunk 3 poisons
    eng.step()                                      # A chunk 1 cached
    eng.step()                                      # A chunk 2 cached
    assert len(eng.cache) == 2 and eng.status(a) is RequestStatus.RUNNING
    bprompt = clean8 + rng.randint(8, 50, size=3).tolist()
    b = eng.submit(bprompt, max_tokens=6)
    res = eng.run(max_ticks=100)    # B stitches 8; A fails on chunk 3
    assert eng.status(a) is RequestStatus.FAILED
    assert eng.status(b) is RequestStatus.COMPLETED
    assert eng._requests[b].cached_len == 8         # it really stitched
    assert res[b] == greedy_decode_reference(model, params, bprompt, 6, 1)
    assert len(eng.cache) >= 2                      # vouched pages kept
    assert_drained(eng)


def test_failed_tail_keeps_shared_prefix_cached(rng):
    # rollback scope: a request whose UNIQUE TAIL overflows forgets only
    # the pages it wrote — the shared system prompt it stitched was
    # finite-vouched by its original owner and must stay hittable
    model, params = _small_model()
    params = dict(params)
    params["emb"] = params["emb"].at[7].set(np.inf)
    eng = _engine(model, params)
    system = rng.randint(8, 50, size=8).tolist()       # 2 clean pages
    a = eng.submit(system + rng.randint(8, 50, size=2).tolist(),
                   max_tokens=4)
    eng.run(max_ticks=50)
    assert eng.status(a) is RequestStatus.COMPLETED
    cached_before = len(eng.cache)
    assert cached_before == 2
    bad = eng.submit(system + [7, 8], max_tokens=4)    # poisoned tail
    eng.run(max_ticks=50)
    assert eng.status(bad) is RequestStatus.FAILED
    assert len(eng.cache) == cached_before             # prefix survived
    # and it still serves hits
    saved_before = eng.metrics.prefill_tokens_saved
    c = eng.submit(system + rng.randint(8, 50, size=3).tolist(),
                   max_tokens=4)
    eng.run(max_ticks=50)
    assert eng.status(c) is RequestStatus.COMPLETED
    assert eng.metrics.prefill_tokens_saved - saved_before == 8
    assert_drained(eng)


def test_healthz_exposes_cache_occupancy_and_drains_steady(rng):
    model, params = _small_model()
    eng = _engine(model, params)
    rids = [eng.submit(rng.randint(2, 50, size=9).tolist(), max_tokens=4)
            for _ in range(3)]
    eng.step()
    hz = eng.healthz()
    assert hz["pages_in_use"] > 0          # live holders mid-run
    eng.run(max_ticks=200)
    assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
    hz = eng.healthz()
    # steady state: no live pages, the cache fully reclaimable, free +
    # cached covering the whole pool
    assert hz["ok"] is True and hz["pages_in_use"] == 0
    assert hz["pages_cached"] > 0
    assert hz["pages_cached"] == hz["pages_reclaimable"]
    assert hz["pages_free"] + hz["pages_cached"] == eng.pool.num_usable
    # flushing the cache returns every page to the free list
    eng.cache.flush()
    assert eng.healthz()["pages_cached"] == 0
    assert eng.pool.num_free == eng.pool.num_usable
