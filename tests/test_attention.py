"""Flash attention kernel vs plain-JAX oracle (interpret mode on CPU).

Mirrors the reference's CPU-vs-GPU parity strategy
(paddle/math/tests/test_matrixCompare.cpp): same op, two execution paths,
outputs and gradients compared.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import attention


def _mk(rng, b, s, h, d):
    q = rng.randn(b, s, h, d).astype(np.float32)
    k = rng.randn(b, s, h, d).astype(np.float32)
    v = rng.randn(b, s, h, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def _segments(rng, b, s, n_seq):
    # packed segments: random cut points
    out = np.zeros((b, s), np.int32)
    for i in range(b):
        cuts = np.sort(rng.choice(np.arange(1, s), n_seq - 1, replace=False))
        seg = 0
        prev = 0
        for c in list(cuts) + [s]:
            out[i, prev:c] = seg
            seg += 1
            prev = c
    return jnp.asarray(out)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(rng, causal):
    q, k, v = _mk(rng, 2, 128, 2, 32)
    out = attention.flash_attention(q, k, v, causal=causal, block_q=64,
                                    block_k=64)
    ref = attention.mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_segment_masking(rng, causal):
    q, k, v = _mk(rng, 2, 128, 2, 32)
    seg = _segments(rng, 2, 128, 4)
    out = attention.flash_attention(q, k, v, segment_ids=seg, causal=causal,
                                    block_q=64, block_k=64)
    ref = attention.mha_reference(q, k, v, segment_ids=seg, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_grad_matches_reference(rng):
    q, k, v = _mk(rng, 1, 64, 2, 16)
    seg = _segments(rng, 1, 64, 3)

    def loss_flash(q, k, v):
        o = attention.flash_attention(q, k, v, segment_ids=seg, causal=True,
                                      block_q=32, block_k=32)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = attention.mha_reference(q, k, v, segment_ids=seg, causal=True)
        return jnp.sum(o * o)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_flash_cross_attention(rng):
    q = jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 128, 2, 16).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 128, 2, 16).astype(np.float32))
    out = attention.flash_attention(q, k, v, block_q=32, block_k=64)
    ref = attention.mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_causal_cross_attention_grads(rng):
    """Causal CROSS-attention with seq_k > seq_q through the backward pass:
    the dK/dV kernel's streamed q-tile index (kj*block_k)//block_q exceeds
    the last q block for late key blocks, which an earlier clamp let
    through as an out-of-range block index (ADVICE r5 item 1). Forward and
    all three grads must match the oracle."""
    q = jnp.asarray(rng.randn(2, 32, 2, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 128, 2, 16).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 128, 2, 16).astype(np.float32))

    out = attention.flash_attention(q, k, v, causal=True, block_q=32,
                                    block_k=32)
    ref = attention.mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    g1 = jax.grad(lambda q, k, v: jnp.sum(attention.flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: jnp.sum(attention.mha_reference(
        q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   err_msg=f"d{name}")


def test_flash_pv_f32_matches_default_in_f32(rng):
    """FLAGS.attn_pv_f32 only changes the PV/dS operand dtype: in an f32
    model both paths are identical math (the flag's effect is bf16-only)."""
    from paddle_tpu.platform.flags import FLAGS

    q, k, v = _mk(rng, 2, 64, 2, 16)
    seg = _segments(rng, 2, 64, 3)

    def loss(q, k, v):
        o = attention.flash_attention(q, k, v, segment_ids=seg, causal=True,
                                      block_q=32, block_k=32)
        return jnp.sum(o * o)

    old = FLAGS.attn_pv_f32
    try:
        FLAGS.attn_pv_f32 = False
        o0 = attention.flash_attention(q, k, v, segment_ids=seg, causal=True,
                                       block_q=32, block_k=32)
        g0 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        FLAGS.attn_pv_f32 = True
        o1 = attention.flash_attention(q, k, v, segment_ids=seg, causal=True,
                                       block_q=32, block_k=32)
        g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        FLAGS.attn_pv_f32 = old
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o0))
    for a, b in zip(g1, g0):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_matches_plain_jax_backward(rng, causal):
    """The pallas dQ/dK/dV kernels and the plain-JAX blockwise fallback
    must produce identical gradients (FLAGS.use_pallas toggles the path)."""
    from paddle_tpu.platform.flags import FLAGS

    q, k, v = _mk(rng, 2, 128, 2, 32)
    seg = _segments(rng, 2, 128, 3)

    def loss(q, k, v):
        o = attention.flash_attention(q, k, v, segment_ids=seg,
                                      causal=causal, block_q=32, block_k=64)
        return jnp.sum(jnp.sin(o))

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        g_pallas = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        FLAGS.use_pallas = False
        g_plain = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        FLAGS.use_pallas = old
    for a, b in zip(g_pallas, g_plain):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_block_segment_skip_parity(rng, causal):
    """Segments aligned to block boundaries (the packed-LM bench layout):
    most (q, k) block pairs are cross-segment and take the runtime
    disjoint-range skip; output and grads must still match the oracle."""
    b, s, h, d = 1, 256, 2, 32
    q, k, v = _mk(rng, b, s, h, d)
    # 4 segments of 64 = exactly 2 blocks each at block 32
    seg = jnp.asarray(np.repeat(np.arange(4, dtype=np.int32), 64)[None, :])

    def loss_flash(q, k, v):
        o = attention.flash_attention(q, k, v, segment_ids=seg,
                                      causal=causal, block_q=32, block_k=32)
        return jnp.sum(jnp.cos(o))

    def loss_ref(q, k, v):
        o = attention.mha_reference(q, k, v, segment_ids=seg, causal=causal)
        return jnp.sum(jnp.cos(o))

    np.testing.assert_allclose(
        np.asarray(loss_flash(q, k, v)), np.asarray(loss_ref(q, k, v)),
        rtol=1e-5)
    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-4)


def test_flash_bf16_inputs_match_oracle(rng):
    """bf16 tiles ride the MXU natively (no f32 upcast before the dots);
    outputs and grads must match the f32 oracle within bf16 tolerance."""
    q, k, v = _mk(rng, 1, 128, 2, 32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    seg = _segments(rng, 1, 128, 2)

    out = attention.flash_attention(qb, kb, vb, segment_ids=seg,
                                    causal=True, block_q=64, block_k=64)
    ref = attention.mha_reference(q, k, v, segment_ids=seg, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), atol=3e-2)

    def loss_flash(q_, k_, v_):
        o = attention.flash_attention(q_, k_, v_, segment_ids=seg,
                                      causal=True, block_q=64, block_k=64)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_ref(q_, k_, v_):
        o = attention.mha_reference(q_, k_, v_, segment_ids=seg, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(qb, kb, vb)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b_), atol=0.15)
