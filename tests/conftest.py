"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's in-process multi-node simulation strategy
(pserver/test/test_ParameterServer2.cpp spins servers+clients in one process):
we give XLA 8 virtual CPU devices so every mesh/collective path is exercised
without TPU hardware.

NOTE: the environment pre-imports jax (sitecustomize), so JAX_PLATFORMS set
here would be too late — we switch platform via jax.config instead, and set
XLA_FLAGS before the first backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


# ---------------------------------------------------------------------------
# fast/slow split: `-m "not slow"` is the <8-minute iteration gate; the
# plain full run (CI) is unchanged and runs everything. Centralized here by
# test id (parametrized ids included) so sweep cases can be marked without
# touching their case tables; names measured via --durations on this host.
# ---------------------------------------------------------------------------

_SLOW_TESTS = {
    "test_pipeline_over_transformer_blocks",
    "test_googlenet_geometry_and_step",
    "test_srl_trains_and_shares_params",
    "test_srl_conll05_dataset_compatible",
    "test_compare_sparse_training_parity",
    "test_transformer_generate_matches_iterative_forward",
    "test_mdlstm_forward_shape_and_grad",
    "test_transformer_trains_on_mesh8_zero",
    "test_ring_attention_grads",
    "test_transformer_bf16_dense_activations",
    "test_detection_suite",
    "test_transformer_lm_trains",
    "test_vgg_16_network_builds_and_runs",
    "test_fused_head_trains_on_mesh8_zero",
    "test_remat_training_parity",
    "test_seq2seq_trains_and_generates",
    "test_two_process_by_four_device_hybrid_mesh",
    "test_two_process_mesh_and_train_step",
    "test_seq2seq_transformer_learns_copy_task",
    "test_pipeline_grads_match_sequential",
    "test_moe_transformer_trains",
    "test_sequence_tagging_crf_trains_and_decodes",
    "test_layer[multibox_loss]",
    "test_layer[StaticInput+lstm_step+lstm_step_output+lstm_step_state]",
    "test_layer[gru_step+memory+recurrent_group]",
    "test_layer[detection_output]",
    "test_layer[lstmemory]",
    "test_layer[moe_ffn]",
    "test_layer[mdlstmemory]",
    "test_layer[grumemory]",
    "test_remat_moe_trains",
    "test_lenet_conv_one_batch",
    "test_sharded_matches_oracle_multiple_experts_per_shard",
    "test_transformer_causality",
    "test_model_parallel_weights_are_distributed",
    "test_fused_head_training_parity",
    "test_beam_finds_higher_likelihood_than_greedy",
    "test_beam_generate_control_hooks",
    "test_beam1_matches_greedy",
    "test_smallnet_trains",
    "test_quick_start_arch_trains[db_lstm]",
    "test_quick_start_arch_trains[resnet_lstm]",
    "test_quick_start_arch_trains[bidi_lstm]",
    "test_moe_trains_toward_balanced_experts",
    "test_grad_recurrent_layers",
    "test_elastic_multipass_and_periodic_checkpoint_parity",
    "test_kill_trainer_resume_parity",
    "test_mha_layer_trains",
    "test_hierarchical_group_trains_end_to_end",
    "test_simple_lstm_vs_explicit_fc_lstmemory",
    "test_gradient_check_passes_and_catches_corruption",
    "test_flash_vs_plain_attention_kernels",
    "test_lstmemory_vs_recurrent_group_lstm_step",
    "test_lm_head_cost_vs_unfused_pair",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)


def assert_serving_drained(eng):
    """Shared post-drain pool invariant for the serving suites: zero
    live refs — every usable page is either free or parked reclaimable
    (refcount 0) in the prefix cache — and the REF-LEAK/PAGE-LEAK
    conservation checks pass.  Lives here so the three serving test
    files assert ONE definition of "nothing leaked"."""
    assert eng.pool.total_refs == 0
    assert eng.pool.num_free + eng.pool.num_reclaimable == \
        eng.pool.num_usable
    eng.check_page_conservation()
