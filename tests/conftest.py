"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's in-process multi-node simulation strategy
(pserver/test/test_ParameterServer2.cpp spins servers+clients in one process):
we give XLA 8 virtual CPU devices so every mesh/collective path is exercised
without TPU hardware.

NOTE: the environment pre-imports jax (sitecustomize), so JAX_PLATFORMS set
here would be too late — we switch platform via jax.config instead, and set
XLA_FLAGS before the first backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
