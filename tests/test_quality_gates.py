"""Real-data convergence QUALITY gates.

Reference bar: test_TrainerOnePass.cpp:80-122 trains on real bundled
mini-data, and the demos reproduce published accuracy — quality-relative
gates, not chance-relative. Offline CI keeps the synthetic chance-relative
gates (test_mnist_e2e); these egress-gated slow tests pin ABSOLUTE quality
on the true datasets: LeNet >= 97% on real MNIST, linear regression under a
pinned RMSE on real uci_housing.
"""

import socket

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import evaluator, layer, optimizer, trainer


def _has_egress(host="storage.googleapis.com", timeout=3.0):
    try:
        socket.create_connection((host, 80), timeout=timeout).close()
        return True
    except OSError:
        return False


pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not _has_egress(), reason="no network egress"),
]


def test_mnist_lenet_real_accuracy():
    """LeNet on REAL MNIST must reach >= 97% test accuracy in two passes
    (the reference mnist demo's ballpark; far above the synthetic gate)."""
    from paddle_tpu.models import lenet

    train_r = paddle.dataset.mnist.train()
    n_train = sum(1 for _ in train_r())
    # guard against the offline synthetic fallback silently passing
    assert n_train == 60000, f"real MNIST expected, got {n_train} samples"

    paddle.topology.reset_name_scope()
    images, label, logits, cost = lenet.build()
    err = evaluator.classification_error(input=logits, label=label,
                                         name="err")
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost, err]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-3),
                      extra_layers=[err])
    reader = paddle.batch(paddle.reader.shuffle(train_r, buf_size=8192),
                          batch_size=64)
    sgd.train(reader, num_passes=2)
    result = sgd.test(paddle.batch(paddle.dataset.mnist.test(),
                                   batch_size=256))
    acc = 1.0 - float(result.metrics["err"])
    assert acc >= 0.97, f"LeNet real-MNIST test accuracy {acc:.4f} < 0.97"


def test_uci_housing_real_rmse():
    """Linear regression on REAL uci_housing (normalized features) must
    reach test RMSE <= 5.5 (the fit_a_line demo's ballpark — ~4.8-5.2
    for plain least squares on the 80/20 split)."""
    train_r = paddle.dataset.uci_housing.train()
    test_samples = [(f, [t]) for f, t in paddle.dataset.uci_housing.test()()]
    assert len(test_samples) == 102, \
        f"real uci_housing expected, got {len(test_samples)} test rows"

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(13))
    y = layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = layer.fc(input=x, size=1, name="fit_pred")
    cost = layer.square_error_cost(input=pred, label=y)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))

    def reader():
        for f, t in train_r():
            yield f, [t]

    sgd.train(paddle.batch(paddle.reader.shuffle(reader, buf_size=512),
                           batch_size=32), num_passes=60)

    feats = np.stack([f for f, _ in test_samples])
    targets = np.asarray([t[0] for _, t in test_samples], np.float32)
    out = paddle.infer(output_layer=pred, parameters=sgd.parameters,
                       input=[(f,) for f in feats],
                       feeding={"x": 0})
    rmse = float(np.sqrt(np.mean((np.asarray(out).ravel() - targets) ** 2)))
    assert rmse <= 5.5, f"uci_housing test RMSE {rmse:.3f} > 5.5"
