"""Beam-search generation tests.

Strategy (reference analog: test_recurrent_machine_generation.cpp compares
generated output against a golden file): generate with a decoder whose
step is a pure token->logits map with named weights, then replicate beam
search in numpy from the same weights and require identical tokens/scores.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.attr import ParamAttr
from paddle_tpu.generation import GeneratedInput, beam_search
from paddle_tpu.platform.flags import FLAGS


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


V, E, B, K, T = 7, 5, 2, 3, 4
BOS, EOS = 0, 1


def _build(**beam_kwargs):
    paddle.topology.reset_name_scope()
    start = layer.data(name="start", type=paddle.data_type.dense_vector(E))

    def step(token_emb, static_start):
        h = layer.memory(name="h", size=E, boot_layer=start)
        merged = layer.addto(input=[token_emb, h], name="h")
        probs = layer.fc(input=merged, size=V, act="softmax", bias_attr=False,
                         param_attr=ParamAttr(name="out_w"), name="probs")
        return probs

    beam = beam_search(step=step,
                       input=[GeneratedInput(size=V, embedding_name="tok_emb",
                                             embedding_size=E),
                              layer.StaticInput(start)],
                       bos_id=BOS, eos_id=EOS, beam_size=K, max_length=T,
                       name="gen", **beam_kwargs)
    return start, beam


def _numpy_reference(emb, out_w, start_vec, adjust=None, drop=None,
                     stop=None):
    """Replicate the exact beam search in numpy. ``adjust(logp [K,V], t,
    tokens, lengths)``, ``drop(tokens [K], t) -> keep [K]`` and
    ``stop(t, lengths) -> bool`` mirror the user control hooks."""
    def soft(x):
        e = np.exp(x - x.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    NEG = -1e9
    scores = np.array([0.0] + [NEG] * (K - 1))
    tokens = np.full((K,), BOS, np.int64)
    mems = np.tile(start_vec, (K, 1))
    finished = np.zeros(K, bool)
    lengths = np.zeros(K, np.int64)
    chains = [[] for _ in range(K)]
    stopped = False
    for t in range(T):
        if stopped:
            break
        new_h = emb[tokens] + mems
        logp = np.log(np.clip(soft(new_h @ out_w), 1e-20, 1.0))
        if adjust is not None:
            logp = adjust(logp, t, tokens, lengths)
        cont = np.where(finished[:, None],
                        np.where(np.arange(V)[None, :] == EOS, 0.0, NEG), logp)
        total = scores[:, None] + cont
        flat = total.reshape(-1)
        idx = np.argsort(-flat, kind="stable")[:K]
        parent, tok = idx // V, idx % V
        scores = flat[idx]
        new_chains = [chains[p] + [int(tk)] for p, tk in zip(parent, tok)]
        lengths = np.array([lengths[p] + (0 if finished[p] else 1)
                            for p in parent])
        new_fin = np.array([finished[p] or tk == EOS
                            for p, tk in zip(parent, tok)])
        mems = np.stack([mems[p] if finished[p] else new_h[p] for p in parent])
        tokens = tok
        finished = new_fin
        chains = new_chains
        if drop is not None:
            keep = np.asarray(drop(tokens, t))
            scores = np.where(keep, scores, NEG)
        if stop is not None and stop(t, lengths):
            stopped = True
    out = np.full((K, T), EOS, np.int64)
    for k in range(K):
        seq = chains[k][: lengths[k]]
        out[k, : len(seq)] = seq
    return out, lengths, scores


def test_beam_matches_numpy_reference():
    start_node, beam = _build()
    topo = paddle.topology.Topology([beam])
    params = paddle.Parameters.from_topology(topo, seed=42)

    rng = np.random.RandomState(0)
    start_val = rng.randn(B, E).astype(np.float32)

    outs, _ = topo.forward(params.as_dict(), topo.init_state(),
                           {"start": jnp.asarray(start_val)})
    tokens, lengths, scores = outs[0]
    tokens, lengths, scores = map(np.asarray, (tokens, lengths, scores))
    assert tokens.shape == (B, K, T)

    emb = np.asarray(params["tok_emb"])
    out_w = np.asarray(params["out_w"])
    for b in range(B):
        ref_toks, ref_lens, ref_scores = _numpy_reference(emb, out_w, start_val[b])
        np.testing.assert_allclose(scores[b], ref_scores, rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(lengths[b], ref_lens)
        np.testing.assert_array_equal(tokens[b], ref_toks)


def test_beam_scores_sorted_and_finite():
    _, beam = _build()
    topo = paddle.topology.Topology([beam])
    params = paddle.Parameters.from_topology(topo, seed=7)
    start_val = jnp.asarray(np.random.RandomState(1).randn(B, E).astype(np.float32))
    outs, _ = topo.forward(params.as_dict(), topo.init_state(),
                           {"start": start_val})
    tokens, lengths, scores = outs[0]
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-5).all(), "beams not sorted best-first"
    assert np.isfinite(s).all()
    assert ((np.asarray(tokens) >= 0) & (np.asarray(tokens) < V)).all()


def test_beam_under_jit():
    _, beam = _build()
    topo = paddle.topology.Topology([beam])
    params = paddle.Parameters.from_topology(topo, seed=7)

    @jax.jit
    def gen(p, start):
        outs, _ = topo.forward(p, topo.init_state(), {"start": start})
        return outs[0]

    start_val = jnp.asarray(np.random.RandomState(2).randn(B, E).astype(np.float32))
    tokens, lengths, scores = gen(params.as_dict(), start_val)
    assert tokens.shape == (B, K, T)


# ---------------------------------------------------------------------------
# user control hooks (reference: RecurrentGradientMachine.h:73-148 beam
# callbacks — candidate adjust / drop / early stop — and the host-loop
# SequenceGenerator escape hatch)
# ---------------------------------------------------------------------------


def _run(beam, start_val, seed=42):
    topo = paddle.topology.Topology([beam])
    params = paddle.Parameters.from_topology(topo, seed=seed)
    outs, _ = topo.forward(params.as_dict(), topo.init_state(),
                           {"start": jnp.asarray(start_val)})
    tokens, lengths, scores = map(np.asarray, outs[0])
    emb = np.asarray(params["tok_emb"])
    out_w = np.asarray(params["out_w"])
    return tokens, lengths, scores, emb, out_w


def test_candidate_adjust_forbids_token():
    """A traced candidate_adjust that bans token 3 must match the numpy
    oracle with the same ban — and token 3 must never be generated."""
    FORBID = 3

    def adj(logp, beam):
        return logp.at[:, :, FORBID].set(-1e9)

    _, beam = _build(candidate_adjust=adj)
    start_val = np.random.RandomState(0).randn(B, E).astype(np.float32)
    tokens, lengths, scores, emb, out_w = _run(beam, start_val)
    assert (tokens != FORBID).all()

    def np_adj(logp, t, toks, lens):
        logp = logp.copy()
        logp[:, FORBID] = -1e9
        return logp

    for b in range(B):
        ref_toks, ref_lens, ref_scores = _numpy_reference(
            emb, out_w, start_val[b], adjust=np_adj)
        np.testing.assert_array_equal(tokens[b], ref_toks)
        np.testing.assert_array_equal(lengths[b], ref_lens)
        np.testing.assert_allclose(scores[b], ref_scores, rtol=1e-4,
                                   atol=1e-4)


def test_candidate_adjust_length_reward():
    """Hooks see the BeamState: reward continuing (discourage EOS) using
    beam.lengths — generations must get longer than unadjusted ones."""
    def adj(logp, beam):
        bonus = jnp.where(beam.lengths < T, 2.0, 0.0)   # anti-EOS pressure
        return logp.at[:, :, EOS].add(-bonus)

    start_val = np.random.RandomState(3).randn(B, E).astype(np.float32)
    _, plain = _build()
    t0, l0, s0, emb, out_w = _run(plain, start_val)
    _, pushed = _build(candidate_adjust=adj)
    t1, l1, s1, _, _ = _run(pushed, start_val)
    assert l1.sum() >= l0.sum()

    def np_adj(logp, t, toks, lens):
        logp = logp.copy()
        logp[:, EOS] -= np.where(lens < T, 2.0, 0.0)
        return logp

    for b in range(B):
        ref_toks, ref_lens, _ = _numpy_reference(emb, out_w, start_val[b],
                                                 adjust=np_adj)
        np.testing.assert_array_equal(t1[b], ref_toks)
        np.testing.assert_array_equal(l1[b], ref_lens)


def test_host_candidate_adjust_matches_traced():
    """The pure_callback escape hatch gives identical results to the traced
    hook for the same (pure) adjustment."""
    FORBID = 2

    def traced(logp, beam):
        return logp.at[:, :, FORBID].set(-1e9)

    def hosted(logp, tokens, t):
        out = np.array(logp)
        out[:, :, FORBID] = -1e9
        return out

    start_val = np.random.RandomState(5).randn(B, E).astype(np.float32)
    _, beam_t = _build(candidate_adjust=traced)
    tt, lt, st, _, _ = _run(beam_t, start_val)
    _, beam_h = _build(host_candidate_adjust=hosted)
    th, lh, sh, _, _ = _run(beam_h, start_val)
    np.testing.assert_array_equal(tt, th)
    np.testing.assert_array_equal(lt, lh)
    np.testing.assert_allclose(st, sh, rtol=1e-5)
    assert (th != FORBID).all()


def test_path_filter_drops_beams():
    """Dropping every beam whose last token is 4 must match the oracle and
    leave no surviving (finite-score) path through token 4."""
    BAD = 4

    def filt(beam):
        return beam.tokens != BAD

    start_val = np.random.RandomState(7).randn(B, E).astype(np.float32)
    _, beam = _build(path_filter=filt)
    tokens, lengths, scores, emb, out_w = _run(beam, start_val)

    def np_drop(toks, t):
        return toks != BAD

    for b in range(B):
        ref_toks, ref_lens, ref_scores = _numpy_reference(
            emb, out_w, start_val[b], drop=np_drop)
        np.testing.assert_array_equal(tokens[b], ref_toks)
        np.testing.assert_allclose(scores[b], ref_scores, rtol=1e-4,
                                   atol=1e-4)
    # any beam that still has a finite score never passed through BAD
    for b in range(B):
        for k in range(K):
            if scores[b, k] > -1e8:
                assert BAD not in tokens[b, k, : lengths[b, k]]


def test_stop_condition_freezes_early():
    """stop_condition at t>=1 must equal the oracle that breaks after two
    expansions: lengths never exceed 2 even with max_length=4."""
    def stop(beam):
        return beam.t >= 1

    start_val = np.random.RandomState(9).randn(B, E).astype(np.float32)
    _, beam = _build(stop_condition=stop)
    tokens, lengths, scores, emb, out_w = _run(beam, start_val)
    assert (lengths <= 2).all()

    for b in range(B):
        ref_toks, ref_lens, ref_scores = _numpy_reference(
            emb, out_w, start_val[b], stop=lambda t, lens: t >= 1)
        np.testing.assert_array_equal(tokens[b], ref_toks)
        np.testing.assert_array_equal(lengths[b], ref_lens)
        np.testing.assert_allclose(scores[b], ref_scores, rtol=1e-4,
                                   atol=1e-4)
