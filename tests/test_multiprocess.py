"""TRUE multi-process distributed tests: two OS processes join via the
JAX coordination service (paddle.init(coordinator_address=...)), form one
global 2-device CPU mesh with gloo collectives, and train the same step.

Reference analog: the in-process multi-node simulations
(pserver/test/test_ParameterServer2.cpp:554-560 spins pservers + several
ParameterClient2 in one process) — here the processes are REAL, so the
coordinator handshake, global device view, and cross-process psum are the
actual multi-host code path (SURVEY §2.3), not a virtual-mesh stand-in.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import numpy as np
pid = int(sys.argv[1]); port = sys.argv[2]
import paddle_tpu as paddle
paddle.init(coordinator_address=f"127.0.0.1:{port}", num_processes=2,
            process_id=pid, platform="cpu")
import jax, jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 2, devs
mesh = Mesh(np.array(devs), ("data",))

# --- collective sanity: global sum sees BOTH processes' contributions ---
local = jnp.full((1, 4), float(pid + 1))
garr = jax.make_array_from_single_device_arrays(
    (2, 4), NamedSharding(mesh, P("data")),
    [jax.device_put(local, jax.local_devices()[0])])
total = jax.jit(lambda x: jnp.sum(x),
                out_shardings=NamedSharding(mesh, P()))(garr)
assert float(total) == 12.0, float(total)
print(f"pid{pid} psum OK", flush=True)

# --- distributed sync-SGD step: per-process batch shards, psum'd grads ---
from paddle_tpu import layer
from paddle_tpu.topology import Topology
paddle.topology.reset_name_scope()
x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
lab = layer.data(name="lab", type=paddle.data_type.integer_value(3))
cost = layer.classification_cost(input=layer.fc(x, size=3), label=lab)
topo = Topology([cost])
params = {k: np.asarray(v) for k, v in
          paddle.Parameters.from_topology(topo, seed=0).as_dict().items()}
state = topo.init_state()

rng = np.random.RandomState(7)          # same stream on both processes:
gx = rng.randn(4, 6).astype(np.float32)  # the GLOBAL batch
glab = rng.randint(0, 3, (4,)).astype(np.int32)
repl = NamedSharding(mesh, P())
batch_sh = NamedSharding(mesh, P("data"))

def to_global(host, sharding):
    return jax.make_array_from_process_local_data(sharding, host)

feeds = {"x": to_global(gx[pid * 2:(pid + 1) * 2], batch_sh),
         "lab": to_global(glab[pid * 2:(pid + 1) * 2], batch_sh)}
gparams = {k: to_global(v, repl) for k, v in params.items()}

def loss_fn(p, f):
    outs, _ = topo.forward(p, state, f, train=False)
    return jnp.mean(outs[0])

loss, grads = jax.jit(jax.value_and_grad(loss_fn))(gparams, feeds)
# grads are replicated after the automatic cross-process psum: every
# process must hold the identical global gradient
g0 = np.asarray(grads["fc_0.w0"])
print(f"pid{pid} loss={float(loss):.6f} gsum={float(np.abs(g0).sum()):.6f}",
      flush=True)
print(f"pid{pid} TRAIN OK", flush=True)

# --- the v2 API end-to-end across processes: SGD.train on a global mesh ---
from paddle_tpu import optimizer, trainer
paddle.topology.reset_name_scope()
x2 = layer.data(name="x", type=paddle.data_type.dense_vector(6))
lab2 = layer.data(name="label", type=paddle.data_type.integer_value(2))
cost2 = layer.classification_cost(input=layer.fc(x2, size=2), label=lab2)
params2 = paddle.Parameters.from_topology(Topology([cost2]), seed=1)
sgd = trainer.SGD(cost=cost2, parameters=params2,
                  update_equation=optimizer.Sgd(learning_rate=0.2),
                  mesh=mesh)

def local_reader():
    # each process reads ITS half of a deterministic global stream
    r = np.random.RandomState(11)
    for i in range(32):
        v = r.randn(6).astype(np.float32)
        y = int(v[:3].sum() > v[3:].sum())
        if i % 2 == pid:   # disjoint halves
            yield v, y

costs = []
sgd.train(paddle.batch(local_reader, 4), num_passes=3,
          event_handler=lambda ev: costs.append(float(ev.cost))
          if isinstance(ev, paddle.event.EndIteration) else None)
assert costs[-1] < costs[0], (costs[0], costs[-1])
w = np.asarray(sgd.parameters["fc_0.w0"])
print(f"pid{pid} SGD OK wsum={float(np.abs(w).sum()):.6f}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.skipif(sys.platform != "linux", reason="gloo CPU collectives")
def test_two_process_mesh_and_train_step(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "PYTHONPATH": repo,          # NO ambient sitecustomize (axon hook)
        "JAX_PLATFORMS": "cpu",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
    }
    procs = [subprocess.Popen([sys.executable, str(worker), str(i),
                               str(port)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid{i} failed:\n{out[-2500:]}"
        assert f"pid{i} psum OK" in out
        assert f"pid{i} TRAIN OK" in out
        assert f"pid{i} SGD OK" in out
    # both processes computed the IDENTICAL loss and global gradient —
    # the sync-SGD invariant (pserver addGradient analog)
    line0 = [l for l in outs[0].splitlines() if "loss=" in l][0]
    line1 = [l for l in outs[1].splitlines() if "loss=" in l][0]
    assert line0.split("loss=")[1] == line1.split("loss=")[1], (line0, line1)
    # after SGD.train, both ranks hold the identical synced weights
    w0 = [l for l in outs[0].splitlines() if "wsum=" in l][0]
    w1 = [l for l in outs[1].splitlines() if "wsum=" in l][0]
    assert w0.split("wsum=")[1] == w1.split("wsum=")[1], (w0, w1)
