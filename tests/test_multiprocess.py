"""TRUE multi-process distributed tests: two OS processes join via the
JAX coordination service (paddle.init(coordinator_address=...)), form one
global 2-device CPU mesh with gloo collectives, and train the same step.

Reference analog: the in-process multi-node simulations
(pserver/test/test_ParameterServer2.cpp:554-560 spins pservers + several
ParameterClient2 in one process) — here the processes are REAL, so the
coordinator handshake, global device view, and cross-process psum are the
actual multi-host code path (SURVEY §2.3), not a virtual-mesh stand-in.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import numpy as np
pid = int(sys.argv[1]); port = sys.argv[2]
import paddle_tpu as paddle
paddle.init(coordinator_address=f"127.0.0.1:{port}", num_processes=2,
            process_id=pid, platform="cpu")
import jax, jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 2, devs
mesh = Mesh(np.array(devs), ("data",))

# --- collective sanity: global sum sees BOTH processes' contributions ---
local = jnp.full((1, 4), float(pid + 1))
garr = jax.make_array_from_single_device_arrays(
    (2, 4), NamedSharding(mesh, P("data")),
    [jax.device_put(local, jax.local_devices()[0])])
total = jax.jit(lambda x: jnp.sum(x),
                out_shardings=NamedSharding(mesh, P()))(garr)
assert float(total) == 12.0, float(total)
print(f"pid{pid} psum OK", flush=True)

# --- distributed sync-SGD step: per-process batch shards, psum'd grads ---
from paddle_tpu import layer
from paddle_tpu.topology import Topology
paddle.topology.reset_name_scope()
x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
lab = layer.data(name="lab", type=paddle.data_type.integer_value(3))
cost = layer.classification_cost(input=layer.fc(x, size=3), label=lab)
topo = Topology([cost])
params = {k: np.asarray(v) for k, v in
          paddle.Parameters.from_topology(topo, seed=0).as_dict().items()}
state = topo.init_state()

rng = np.random.RandomState(7)          # same stream on both processes:
gx = rng.randn(4, 6).astype(np.float32)  # the GLOBAL batch
glab = rng.randint(0, 3, (4,)).astype(np.int32)
repl = NamedSharding(mesh, P())
batch_sh = NamedSharding(mesh, P("data"))

def to_global(host, sharding):
    return jax.make_array_from_process_local_data(sharding, host)

feeds = {"x": to_global(gx[pid * 2:(pid + 1) * 2], batch_sh),
         "lab": to_global(glab[pid * 2:(pid + 1) * 2], batch_sh)}
gparams = {k: to_global(v, repl) for k, v in params.items()}

def loss_fn(p, f):
    outs, _ = topo.forward(p, state, f, train=False)
    return jnp.mean(outs[0])

loss, grads = jax.jit(jax.value_and_grad(loss_fn))(gparams, feeds)
# grads are replicated after the automatic cross-process psum: every
# process must hold the identical global gradient
g0 = np.asarray(grads["fc_0.w0"])
print(f"pid{pid} loss={float(loss):.6f} gsum={float(np.abs(g0).sum()):.6f}",
      flush=True)
print(f"pid{pid} TRAIN OK", flush=True)

# --- the v2 API end-to-end across processes: SGD.train on a global mesh ---
from paddle_tpu import optimizer, trainer
paddle.topology.reset_name_scope()
x2 = layer.data(name="x", type=paddle.data_type.dense_vector(6))
lab2 = layer.data(name="label", type=paddle.data_type.integer_value(2))
cost2 = layer.classification_cost(input=layer.fc(x2, size=2), label=lab2)
params2 = paddle.Parameters.from_topology(Topology([cost2]), seed=1)
sgd = trainer.SGD(cost=cost2, parameters=params2,
                  update_equation=optimizer.Sgd(learning_rate=0.2),
                  mesh=mesh)

def local_reader():
    # each process reads ITS half of a deterministic global stream
    r = np.random.RandomState(11)
    for i in range(32):
        v = r.randn(6).astype(np.float32)
        y = int(v[:3].sum() > v[3:].sum())
        if i % 2 == pid:   # disjoint halves
            yield v, y

costs = []
sgd.train(paddle.batch(local_reader, 4), num_passes=3,
          event_handler=lambda ev: costs.append(float(ev.cost))
          if isinstance(ev, paddle.event.EndIteration) else None)
assert costs[-1] < costs[0], (costs[0], costs[-1])
w = np.asarray(sgd.parameters["fc_0.w0"])
print(f"pid{pid} SGD OK wsum={float(np.abs(w).sum()):.6f}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.skipif(sys.platform != "linux", reason="gloo CPU collectives")
def test_two_process_mesh_and_train_step(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "PYTHONPATH": repo,          # NO ambient sitecustomize (axon hook)
        "JAX_PLATFORMS": "cpu",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
    }
    procs = [subprocess.Popen([sys.executable, str(worker), str(i),
                               str(port)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid{i} failed:\n{out[-2500:]}"
        assert f"pid{i} psum OK" in out
        assert f"pid{i} TRAIN OK" in out
        assert f"pid{i} SGD OK" in out
    # both processes computed the IDENTICAL loss and global gradient —
    # the sync-SGD invariant (pserver addGradient analog)
    line0 = [l for l in outs[0].splitlines() if "loss=" in l][0]
    line1 = [l for l in outs[1].splitlines() if "loss=" in l][0]
    assert line0.split("loss=")[1] == line1.split("loss=")[1], (line0, line1)
    # after SGD.train, both ranks hold the identical synced weights
    w0 = [l for l in outs[0].splitlines() if "wsum=" in l][0]
    w1 = [l for l in outs[1].splitlines() if "wsum=" in l][0]
    assert w0.split("wsum=")[1] == w1.split("wsum=")[1], (w0, w1)


_WORKER_2X4 = r"""
import os, sys
import numpy as np
pid = int(sys.argv[1]); port = sys.argv[2]
import paddle_tpu as paddle
paddle.init(coordinator_address=f"127.0.0.1:{port}", num_processes=2,
            process_id=pid, platform="cpu")
import jax, jax.numpy as jnp
from jax.sharding import Mesh

assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 8, devs
assert len(jax.local_devices()) == 4, jax.local_devices()
# hybrid mesh: dp over the PROCESS boundary (the DCN analog), tp+ZeRO
# over the 4 in-process virtual devices (the ICI analog) — the
# dryrun_multichip hybrid layout across a real process boundary
mesh = Mesh(np.array(devs).reshape(2, 4), ("data", "model"))

from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.parallel import model_parallel_mlp
from paddle_tpu.topology import Topology

IN_DIM, N_CLS, STEPS = 16, 4, 5
W = np.random.RandomState(99).randn(IN_DIM, N_CLS)
rng = np.random.RandomState(5)
gx = rng.randn(8, IN_DIM).astype(np.float32)
gy = np.argmax(gx @ W, 1).astype(np.int32)

def build():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(IN_DIM))
    y = layer.data(name="y", type=paddle.data_type.integer_value(N_CLS))
    logits = model_parallel_mlp(x, [32, 32], N_CLS, axis="model")
    return layer.classification_cost(input=logits, label=y)

def run(mesh_arg, rows):
    cost = build()
    params = paddle.Parameters.from_topology(Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=3e-3),
                      mesh=mesh_arg,
                      **({"zero_axis": "model"} if mesh_arg else {}))
    feeder = sgd._make_feeder({"x": 0, "y": 1})
    feeds = feeder.feed([(gx[i], int(gy[i])) for i in rows])
    feeds = sgd._shard_feeds(feeds)
    step = sgd._build_step()
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(STEPS):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    return losses, p, o

# distributed: each process feeds ITS half; global batch = concat
d_losses, p, o = run(mesh, range(pid * 4, pid * 4 + 4))
w = p["mp_fc0.w0"]
assert w.addressable_shards[0].data.size < w.size, "weight not sharded"
slot = next(iter(o["slots"].values()))["mp_fc0.w0"]
assert slot.addressable_shards[0].data.size < slot.size, "slot not sharded"

# serial oracle IN the same process: same init, the FULL global batch,
# no mesh — the hybrid dp x tp run must follow the same trajectory
s_losses, _, _ = run(None, range(8))
assert np.allclose(d_losses, s_losses, rtol=2e-4, atol=1e-6), (
    d_losses, s_losses)
assert d_losses[-1] < d_losses[0], d_losses
print(f"pid{pid} HYBRID24 OK losses=" +
      ",".join(f"{v:.6f}" for v in d_losses), flush=True)
"""


@pytest.mark.skipif(sys.platform != "linux", reason="gloo CPU collectives")
def test_two_process_by_four_device_hybrid_mesh(tmp_path):
    """2 processes x 4 virtual CPU devices each: the dryrun_multichip
    hybrid layout (dp over the process boundary, tp+ZeRO inside) across a
    REAL process boundary, with sharded-weight training parity against a
    serial oracle (test_ParameterServer2.cpp:554-560's role, scaled up)."""
    worker = tmp_path / "worker24.py"
    worker.write_text(_WORKER_2X4)
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "PYTHONPATH": repo,          # NO ambient sitecustomize (axon hook)
        "JAX_PLATFORMS": "cpu",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    }
    procs = [subprocess.Popen([sys.executable, str(worker), str(i),
                               str(port)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid{i} failed:\n{out[-2500:]}"
        assert f"pid{i} HYBRID24 OK" in out
    # both ranks computed the IDENTICAL loss trajectory (sync-SGD invariant)
    l0 = [l for l in outs[0].splitlines() if "losses=" in l][0]
    l1 = [l for l in outs[1].splitlines() if "losses=" in l][0]
    assert l0.split("losses=")[1] == l1.split("losses=")[1], (l0, l1)
