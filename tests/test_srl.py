"""SRL db_lstm model: conll05 9-slot samples -> stacked LSTM + CRF.

Trains on a learnable synthetic SRL task (tags derived from mark/context
pattern) and checks the shared embedding/CRF parameter wiring.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer, trainer
from paddle_tpu.models import srl

WORD, LABEL, PRED = 60, 7, 10


def _sample(rng):
    """Tags depend on mark (predicate window) + word class — learnable."""
    length = int(rng.randint(4, 10))
    words = rng.randint(0, WORD, size=length)
    v = int(rng.randint(length))
    mark = [1 if abs(i - v) <= 2 else 0 for i in range(length)]
    pred = int(rng.randint(PRED))
    tags = [(2 + w % 3) if m else (w % 2) for w, m in zip(words, mark)]

    def bcast(x):
        return [int(x)] * length

    ctx = lambda off: bcast(words[min(max(v + off, 0), length - 1)])
    return ([int(w) for w in words], ctx(-2), ctx(-1), ctx(0), ctx(1),
            ctx(2), bcast(pred), [int(m) for m in mark],
            [int(t) for t in tags])


def test_srl_trains_and_shares_params():
    paddle.topology.reset_name_scope()
    data_layers, cost, decoded = srl.build(
        word_dict_len=WORD, label_dict_len=LABEL, pred_dict_len=PRED,
        word_dim=8, mark_dim=3, hidden_dim=16, depth=2)
    topo = paddle.topology.Topology([cost])
    keys = set(topo.param_specs())
    assert "word_emb.w" in keys, "context embeddings must share the table"
    assert "srl_crf.transitions" in keys
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=5e-3))

    rng = np.random.RandomState(0)
    data = [_sample(rng) for _ in range(256)]

    def reader():
        for i in range(0, len(data), 32):
            yield data[i:i + 32]

    costs = []
    sgd.train(reader, num_passes=4,
              event_handler=lambda ev: costs.append(float(ev.cost))
              if isinstance(ev, paddle.event.EndIteration) else None)
    assert np.mean(costs[-4:]) < np.mean(costs[:4]) / 2, \
        f"SRL failed to learn: {np.mean(costs[:4])} -> {np.mean(costs[-4:])}"

    # decode through the shared transitions: beats chance comfortably
    test_data = [_sample(rng) for _ in range(16)]
    dec_topo = paddle.topology.Topology([decoded])
    feeder = sgd._make_feeder(None)
    feeds = feeder.feed(test_data)
    feeds.pop("label")
    outs, _ = dec_topo.forward(sgd.parameters.as_dict(), sgd.model_state,
                               feeds, train=False)
    sb = outs[0]
    pred = np.asarray(sb.data).reshape(-1)
    mask = np.asarray(sb.valid_mask)
    truth = np.concatenate([np.asarray(s[-1]) for s in test_data])
    assert mask.sum() == len(truth)
    acc = (pred[mask] == truth).mean()
    assert acc > 0.5, f"SRL viterbi accuracy {acc}"


def test_srl_conll05_dataset_compatible(monkeypatch):
    """The model's feed order matches the conll05 dataset's 9-slot samples
    (downloads forced off so CI stays hermetic — the synthetic fallback
    shares the real pipeline's sample shape)."""
    from paddle_tpu.dataset import common, conll05

    def no_net(*a, **k):
        raise IOError("offline test")

    monkeypatch.setattr(common, "download", no_net)

    paddle.topology.reset_name_scope()
    data_layers, cost, decoded = srl.build(
        word_dict_len=conll05.WORD_DIM, label_dict_len=conll05.LABEL_DIM,
        pred_dict_len=conll05.PRED_DIM, word_dim=8, mark_dim=3,
        hidden_dim=16, depth=2)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Sgd(learning_rate=1e-3))
    batch = list(__import__("itertools").islice(conll05.test()(), 8))
    feeder = sgd._make_feeder(None)
    feeds = feeder.feed(batch)
    assert set(f.name for f in data_layers) == set(feeds)
    loss, *_ = sgd._build_step()(
        sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state,
        __import__("jax").random.PRNGKey(0), feeds)
    assert np.isfinite(float(loss))
