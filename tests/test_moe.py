"""Expert-parallel MoE FFN tests: sharded dispatch/combine vs the dense
single-device oracle, capacity semantics, gradients through all_to_all.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.moe import (MoEParams, aux_load_balance_loss,
                                     init_moe_params, moe_ffn,
                                     moe_ffn_reference)

T, D, H, E = 64, 8, 16, 8


@pytest.fixture(scope="module")
def params():
    return init_moe_params(jax.random.PRNGKey(0), D, H, E, scale=0.5)


@pytest.fixture(scope="module")
def tokens():
    return jax.random.normal(jax.random.PRNGKey(1), (T, D), jnp.float32)


def test_sharded_matches_dense_oracle(params, tokens):
    """With generous capacity (nothing drops anywhere) the expert-parallel
    all_to_all formulation computes EXACTLY the dense result per token."""
    mesh = make_mesh((8,), ("expert",))
    y_ref, aux_ref = moe_ffn_reference(tokens, params, capacity_factor=8.0)
    y_ep, aux_ep = jax.jit(
        lambda x, p: moe_ffn(mesh, x, p, capacity_factor=8.0))(
        tokens, params)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(aux_ep), float(aux_ref), rtol=1e-5)


def test_capacity_drops_pass_through_as_zero(params, tokens):
    """Tiny capacity: over-capacity tokens emit zeros (Switch drop)."""
    y, _ = moe_ffn_reference(tokens, params, capacity_factor=0.125)
    zero_rows = np.where(np.abs(np.asarray(y)).sum(-1) == 0)[0]
    assert len(zero_rows) > 0
    y_full, _ = moe_ffn_reference(tokens, params, capacity_factor=8.0)
    kept = np.abs(np.asarray(y)).sum(-1) > 0
    np.testing.assert_allclose(np.asarray(y)[kept],
                               np.asarray(y_full)[kept], rtol=1e-5)


def test_sharded_matches_oracle_multiple_experts_per_shard(tokens):
    """E=16 on 8 shards (two experts per shard): the combine path must
    keep the [owner, local] -> global expert order straight."""
    p16 = init_moe_params(jax.random.PRNGKey(4), D, H, 16, scale=0.5)
    mesh = make_mesh((8,), ("expert",))
    y_ref, _ = moe_ffn_reference(tokens, p16, capacity_factor=16.0)
    y_ep, _ = jax.jit(lambda x, p: moe_ffn(mesh, x, p,
                                           capacity_factor=16.0))(
        tokens, p16)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=2e-5, atol=2e-6)


def test_capacity_is_ceil():
    """docstring promise: ceil(T/E * factor), not floor: 10 tokens over 8
    experts at factor 1.25 -> cap ceil(1.5625)=2; deterministic routing
    puts 2 tokens on experts 0/1, so NOTHING drops (floor cap 1 would
    drop two tokens)."""
    p = init_moe_params(jax.random.PRNGKey(0), D, H, 8, scale=0.5)
    p = p._replace(router=jnp.eye(D, 8) * 10.0)
    x = jnp.eye(8, D)[jnp.arange(10) % 8] * 5.0   # token i -> expert i%8
    y, _ = moe_ffn_reference(x, p, capacity_factor=1.25)
    dropped = int((np.abs(np.asarray(y)).sum(-1) == 0).sum())
    assert dropped == 0


def test_gradients_flow_through_all_to_all(params, tokens):
    mesh = make_mesh((8,), ("expert",))

    def loss(p, x):
        y, aux = moe_ffn(mesh, x, p, capacity_factor=8.0)
        return jnp.sum(y ** 2) + 0.01 * aux

    grads = jax.jit(jax.grad(loss))(params, tokens)
    for leaf in jax.tree.leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()
    assert float(jnp.abs(grads.w1).sum()) > 0
    assert float(jnp.abs(grads.router).sum()) > 0


def test_aux_loss_uniform_is_one():
    probs = jnp.full((32, E), 1.0 / E)
    expert = jnp.arange(32, dtype=jnp.int32) % E   # perfectly balanced
    assert abs(float(aux_load_balance_loss(probs, expert)) - 1.0) < 1e-6


def test_moe_trains_toward_balanced_experts(params):
    """A few steps of aux-weighted training reduce routing imbalance."""
    x = jax.random.normal(jax.random.PRNGKey(3), (T, D)) * 2.0
    p = params

    def imbalance(p):
        from paddle_tpu.parallel.moe import _route
        _, _, probs = _route(x, p.router)
        expert = jnp.argmax(probs, -1)
        counts = jnp.bincount(expert, length=E)
        return float(counts.max() - counts.min())

    def loss(p):
        _, aux = moe_ffn_reference(x, p, capacity_factor=8.0)
        return aux

    before = imbalance(p)
    g = jax.jit(jax.grad(loss))(p)
    p2 = jax.tree.map(lambda a, b: a - 0.5 * b, p, g)
    for _ in range(10):
        g = jax.jit(jax.grad(loss))(p2)
        p2 = jax.tree.map(lambda a, b: a - 0.5 * b, p2, g)
    assert float(loss(p2)) <= float(loss(p)) + 1e-6


def test_moe_transformer_trains():
    """transformer.build(moe_experts=4): multi-cost training (xent + aux)
    converges on tiny shapes; aux stays finite and bounded."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer, trainer
    from paddle_tpu.models import transformer

    vocab, d = 61, 16
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, costs = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=2, n_heads=2, max_len=32,
        moe_experts=4)
    assert isinstance(costs, list) and len(costs) == 3  # xent + 2 aux
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology(costs), seed=0)
    sgd = trainer.SGD(cost=costs, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))
    step = sgd._build_step()
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(4):
        t = rng.randint(0, vocab, size=12)
        samples.append((t.tolist(), list(range(12)),
                        np.roll(t, -1).tolist()))
    feeds = sgd._make_feeder(
        {"tokens": 0, "pos": 1, "target": 2}).feed(samples)
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(25):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
