"""Native C++ runtime tests: recordio interop, async shuffle pool, C ABI.

Reference analog: gserver/dataproviders tests + paddle/capi/tests. Tests
build the shared libraries with g++ on first run (skipped if no
toolchain).
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.master import recordio as py_rio

HAVE_GXX = shutil.which("g++") is not None

pytestmark = pytest.mark.skipif(not HAVE_GXX, reason="no g++ toolchain")


@pytest.fixture(scope="module")
def native():
    from paddle_tpu import native as nat

    if not nat.available():
        pytest.skip(f"native build failed: {nat._load_error}")
    return nat


def test_recordio_cpp_python_interop(native, tmp_path):
    """C++ writes → Python reads, and Python writes → C++ reads."""
    recs = [f"record-{i}".encode() * (i + 1) for i in range(20)]

    p1 = str(tmp_path / "cpp.rio")
    assert native.write_records(p1, recs) == 20
    assert py_rio.recordio_read_chunk(p1, 0, 20) == recs
    offs_py = py_rio.recordio_index(p1)
    assert native.index(p1) == offs_py

    p2 = str(tmp_path / "py.rio")
    py_rio.recordio_write(p2, recs)
    assert native.read_chunk(p2, 0, 20) == recs
    # seek into the middle
    assert native.read_chunk(p2, offs_py[5], 3) == recs[5:8]


def test_shuffle_pool_streams_all_records(native, tmp_path):
    files = []
    all_recs = set()
    for fi in range(3):
        recs = [f"f{fi}-r{i}".encode() for i in range(50)]
        all_recs.update(recs)
        p = str(tmp_path / f"part-{fi}.rio")
        native.write_records(p, recs)
        files.append(p)

    got = list(native.recordio_reader(files, window=16, seed=7)())
    assert len(got) == 150
    assert set(got) == all_recs
    # shuffled: not the sequential order
    sequential = [f"f{fi}-r{i}".encode() for fi in range(3)
                  for i in range(50)]
    assert got != sequential


def test_shuffle_pool_as_trainer_reader(native, tmp_path):
    """Native pool feeding the SGD trainer end to end (records are
    'x0,...,x7,label' text lines — the DataProvider parse analog)."""
    import json

    from paddle_tpu import layer, optimizer, trainer

    rng = np.random.RandomState(0)
    rows = []
    for _ in range(128):
        y = int(rng.randint(0, 2))
        x = (rng.randn(8) * 0.2).astype(np.float32)
        x[y * 4:(y + 1) * 4] += 1.0
        rows.append(json.dumps({"x": x.tolist(), "y": y}).encode())
    path = str(tmp_path / "train.rio")
    native.write_records(path, rows)

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    cost = layer.classification_cost(
        input=layer.fc(x, size=2), label=y)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=0.05))

    def parse(reader):
        def r():
            for rec in reader():
                o = json.loads(rec)
                yield np.asarray(o["x"], np.float32), o["y"]
        return r

    costs = []

    def handler(ev):
        from paddle_tpu import event
        if isinstance(ev, event.EndIteration):
            costs.append(ev.cost)

    raw = native.recordio_reader(path, window=32, seed=1)
    sgd.train(paddle.batch(parse(raw), 32), num_passes=6,
              event_handler=handler)
    assert costs[-1] < 0.5 * costs[0]


C_TEST = r"""
#include <stdio.h>
#include <stdlib.h>

extern void* ptpu_model_load(const char* path);
extern int ptpu_infer(void* h, const char* name, const float* data,
                      long long batch, long long dim, float* out,
                      long long cap, long long* rows, long long* cols);
extern void ptpu_model_release(void* h);

int main(int argc, char** argv) {
  void* m = ptpu_model_load(argv[1]);
  if (!m) { fprintf(stderr, "load failed\n"); return 1; }
  float in[2 * 8];
  for (int i = 0; i < 16; ++i) in[i] = (float)i / 16.0f;
  float out[64];
  long long rows = 0, cols = 0;
  if (ptpu_infer(m, "x", in, 2, 8, out, 64, &rows, &cols) != 0) {
    fprintf(stderr, "infer failed\n");
    return 2;
  }
  printf("%lld %lld", rows, cols);
  for (long long i = 0; i < rows * cols; ++i) printf(" %.6f", out[i]);
  printf("\n");
  ptpu_model_release(m);
  return 0;
}
"""


def test_c_inference_abi(native, tmp_path):
    """Build the capi .so + a C client, run inference from pure C, and
    compare against the python forward (paddle/capi/tests analog)."""
    import sysconfig

    from paddle_tpu import export as pexport
    from paddle_tpu import layer

    # a merged model to serve
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    out = layer.fc(layer.fc(x, size=16, act="relu"), size=3,
                   act="softmax")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)
    model_path = str(tmp_path / "model.ptm")
    pexport.merge_model(out, params, model_path)

    capi_so = native.build_capi()

    csrc = tmp_path / "ctest.c"
    csrc.write_text(C_TEST)
    exe = str(tmp_path / "ctest")
    libdir = sysconfig.get_config_var("LIBDIR")
    subprocess.run(["gcc", "-o", exe, str(csrc), capi_so,
                    f"-Wl,-rpath,{os.path.dirname(capi_so)}",
                    f"-Wl,-rpath,{libdir}"],
                   check=True, capture_output=True)

    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # ONLY the repo: the ambient PYTHONPATH may carry a sitecustomize
    # that registers a TPU backend the embedded interpreter can't reach
    env["PYTHONPATH"] = repo
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([exe, model_path], capture_output=True,
                          text=True, env=env, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    vals = proc.stdout.split()
    rows, cols = int(vals[0]), int(vals[1])
    got = np.asarray([float(v) for v in vals[2:]]).reshape(rows, cols)

    xb = (np.arange(16, dtype=np.float32) / 16.0).reshape(2, 8)
    state = topo.init_state()
    expect, _ = topo.forward(params.as_dict(), state, {"x": xb},
                             train=False)
    np.testing.assert_allclose(got, np.asarray(expect[0]), atol=1e-4)


C_AOT_TEST = r"""
#include <stdio.h>
#include <stdlib.h>

extern void* ptpu_aot_load(const char* path);
extern int ptpu_aot_infer(void* h, const char* name, const float* data,
                          long long batch, long long dim, float* out,
                          long long cap, long long* rows, long long* cols);
extern void ptpu_aot_release(void* h);

int main(int argc, char** argv) {
  long long batch = atoll(argv[2]);
  long long dim = atoll(argv[3]);
  void* m = ptpu_aot_load(argv[1]);
  if (!m) { fprintf(stderr, "load failed\n"); return 1; }
  float* in = (float*)malloc(sizeof(float) * batch * dim);
  for (long long i = 0; i < batch * dim; ++i)
    in[i] = (float)((i * 37 % 100) - 50) / 100.0f;
  float out[4096];
  long long rows = 0, cols = 0;
  int rc = ptpu_aot_infer(m, argv[4], in, batch, dim, out, 4096, &rows,
                          &cols);
  if (rc != 0) { fprintf(stderr, "infer rc=%d\n", rc); return 2; }
  printf("%lld %lld", rows, cols);
  for (long long i = 0; i < rows * cols; ++i) printf(" %.6f", out[i]);
  printf("\n");
  ptpu_aot_release(m);
  return 0;
}
"""


def _run_aot_client(native, tmp_path, out_node, topo, params, feed_name,
                    batch, dim):
    from paddle_tpu import export as pexport

    model_path = str(tmp_path / "model.ptnm")
    pexport.export_aot_program(out_node, params, model_path,
                               batch_size=batch)
    aot_so = native.build_aot()

    # the AOT runtime must be PYTHON-FREE: its shared library may not pull
    # in libpython (the interpreter-free deployment property, paddle/capi
    # gradient_machine.h:36-112 / Dockerfile.android analog)
    ldd = subprocess.run(["ldd", aot_so], capture_output=True, text=True)
    assert "libpython" not in ldd.stdout, ldd.stdout

    csrc = tmp_path / "aot_client.c"
    csrc.write_text(C_AOT_TEST)
    exe = str(tmp_path / "aot_client")
    subprocess.run(["gcc", "-o", exe, str(csrc), aot_so,
                    f"-Wl,-rpath,{os.path.dirname(aot_so)}"],
                   check=True, capture_output=True)
    # NO PYTHONPATH / python env needed by the client process at all
    proc = subprocess.run([exe, model_path, str(batch), str(dim), feed_name],
                          capture_output=True, text=True, env={},
                          timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    vals = proc.stdout.split()
    rows, cols = int(vals[0]), int(vals[1])
    got = np.asarray([float(v) for v in vals[2:]]).reshape(rows, cols)

    xb = ((np.arange(batch * dim) * 37 % 100 - 50) / 100.0).astype(
        np.float32).reshape(batch, dim)
    state = topo.init_state()
    from paddle_tpu.platform.flags import FLAGS
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        expect, _ = topo.forward(params.as_dict(), state, {feed_name: xb},
                                 train=False)
    finally:
        FLAGS.use_bf16 = old
    np.testing.assert_allclose(got, np.asarray(expect[0]).reshape(rows, cols),
                               atol=1e-5)


def test_aot_c_inference_mlp(native, tmp_path):
    """Interpreter-free C inference: MLP+softmax via the .ptnm AOT program,
    client process has NO python — parity vs the jax forward."""
    from paddle_tpu import layer

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    out = layer.fc(layer.fc(x, size=16, act="relu"), size=3, act="softmax")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)
    _run_aot_client(native, tmp_path, out, topo, params, "x", 2, 8)


def test_aot_c_inference_cnn(native, tmp_path):
    """Interpreter-free C inference of a conv+bn+pool+fc graph."""
    from paddle_tpu import layer

    paddle.topology.reset_name_scope()
    x = layer.data(name="img", type=paddle.data_type.dense_vector(2 * 6 * 6),
                   height=6, width=6)
    c = layer.img_conv(x, filter_size=3, num_filters=4, num_channels=2,
                       padding=1, act="relu")
    bn = layer.batch_norm(c, act="relu")
    p = layer.img_pool(bn, pool_size=2)
    out = layer.fc(p, size=3, act="softmax")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=1)
    _run_aot_client(native, tmp_path, out, topo, params, "img", 3, 72)


def test_aot_rejects_unsupported_graphs(tmp_path):
    """Graphs beyond the AOT op set fail loudly at EXPORT time, pointing
    at the CPython merged-model fallback."""
    from paddle_tpu import export as pexport
    from paddle_tpu import layer
    from paddle_tpu.platform.enforce import EnforceError

    paddle.topology.reset_name_scope()
    s = layer.data(name="s",
                   type=paddle.data_type.dense_vector_sequence(4))
    out = layer.pooling(s)
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)
    with pytest.raises(EnforceError):
        pexport.export_aot_program(out, params, str(tmp_path / "x.ptnm"),
                                   batch_size=2)


C_PJRT_TEST = r"""
#include <stdio.h>
#include <stdlib.h>

extern void* ptpu_pjrt_load(const char* model, const char* plugin);
extern int ptpu_pjrt_infer(void* h, const char* name, const float* data,
                           long long batch, long long dim, float* out,
                           long long cap, long long* rows, long long* cols);
extern void ptpu_pjrt_release(void* h);
extern const char* ptpu_pjrt_last_error(void);

int main(int argc, char** argv) {
  void* m = ptpu_pjrt_load(argv[1], argv[2]);
  if (!m) {
    fprintf(stderr, "load failed: %s\n", ptpu_pjrt_last_error());
    return 3;  // distinct rc: load failed but GRACEFULLY (no crash)
  }
  long long batch = atoll(argv[3]);
  long long dim = atoll(argv[4]);
  float* in = (float*)malloc(sizeof(float) * batch * dim);
  for (long long i = 0; i < batch * dim; ++i)
    in[i] = (float)((i * 37 % 100) - 50) / 100.0f;
  float out[4096];
  long long rows = 0, cols = 0;
  int rc = ptpu_pjrt_infer(m, argv[5], in, batch, dim, out, 4096, &rows,
                           &cols);
  if (rc != 0) {
    fprintf(stderr, "infer rc=%d: %s\n", rc, ptpu_pjrt_last_error());
    return 2;
  }
  printf("%lld %lld", rows, cols);
  for (long long i = 0; i < rows * cols; ++i) printf(" %.6f", out[i]);
  printf("\n");
  ptpu_pjrt_release(m);
  return 0;
}
"""


def _build_pjrt_client(native, tmp_path):
    pjrt_so = native.build_pjrt()
    # python-free like the AOT runtime
    ldd = subprocess.run(["ldd", pjrt_so], capture_output=True, text=True)
    assert "libpython" not in ldd.stdout, ldd.stdout
    csrc = tmp_path / "pjrt_client.c"
    csrc.write_text(C_PJRT_TEST)
    exe = str(tmp_path / "pjrt_client")
    subprocess.run(["gcc", "-o", exe, str(csrc), pjrt_so,
                    f"-Wl,-rpath,{os.path.dirname(pjrt_so)}"],
                   check=True, capture_output=True)
    return exe


def _export_pjrt_mlp(tmp_path):
    from paddle_tpu import export as pexport
    from paddle_tpu import layer

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    out = layer.fc(layer.fc(x, size=16, act="relu"), size=3, act="softmax")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)
    model_path = str(tmp_path / "model.ptpj")
    pexport.export_pjrt_model(out, params, model_path, batch_size=2)
    return model_path, topo, params


def test_pjrt_c_loader_graceful_without_device(native, tmp_path):
    """The PJRT C path compiles, parses the .ptpj artifact, dlopens the
    plugin, and — on a host whose TPU sits behind the axon relay rather
    than libtpu — fails GRACEFULLY with an error string, never a crash.
    (The full execute path runs on real TPU hosts; see
    test_pjrt_c_inference_real_plugin.)"""
    model_path, _, _ = _export_pjrt_mlp(tmp_path)
    exe = _build_pjrt_client(native, tmp_path)

    libtpu = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(np.__file__))), "libtpu", "libtpu.so")
    if not os.path.exists(libtpu):
        pytest.skip("no libtpu.so in site-packages")
    proc = subprocess.run([exe, model_path, libtpu, "2", "8", "x"],
                          capture_output=True, text=True, timeout=300,
                          env={"TPU_SKIP_MDS_QUERY": "1"})
    # rc 3 = graceful load failure (expected here: no local TPU devices);
    # rc 0 = an actual TPU was present and inference worked end to end
    assert proc.returncode in (0, 3), (proc.returncode, proc.stderr[-1500:])
    if proc.returncode == 3:
        assert "load failed" in proc.stderr

    # a bogus plugin path must also fail gracefully with a clear message
    proc2 = subprocess.run([exe, model_path, "/nonexistent/plugin.so",
                            "2", "8", "x"],
                           capture_output=True, text=True, timeout=60,
                           env={})
    assert proc2.returncode == 3
    assert "dlopen" in proc2.stderr


@pytest.mark.skipif(not os.environ.get("PTPU_PJRT_PLUGIN"),
                    reason="set PTPU_PJRT_PLUGIN=/path/to/plugin.so on a "
                           "host with a local PJRT device")
def test_pjrt_c_inference_real_plugin(native, tmp_path):
    """Full C-side PJRT inference vs the python forward (real hardware)."""
    model_path, topo, params = _export_pjrt_mlp(tmp_path)
    exe = _build_pjrt_client(native, tmp_path)
    proc = subprocess.run(
        [exe, model_path, os.environ["PTPU_PJRT_PLUGIN"], "2", "8", "x"],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    vals = proc.stdout.split()
    rows, cols = int(vals[0]), int(vals[1])
    got = np.asarray([float(v) for v in vals[2:]]).reshape(rows, cols)
    xb = ((np.arange(16) * 37 % 100 - 50) / 100.0).astype(
        np.float32).reshape(2, 8)
    state = topo.init_state()
    expect, _ = topo.forward(params.as_dict(), state, {"x": xb},
                             train=False)
    np.testing.assert_allclose(got, np.asarray(expect[0]), atol=1e-4)


def test_aot_c_inference_embedding(native, tmp_path):
    """Interpreter-free C inference of an embedding text model: integer-id
    feed rides as floats through the C ABI (exact below 2^24), the
    translated gather does the table lookup."""
    from paddle_tpu import layer

    paddle.topology.reset_name_scope()
    ids = layer.data(name="ids", type=paddle.data_type.integer_value(50))
    emb = layer.embedding(ids, size=8)
    out = layer.fc(emb, size=3, act="softmax")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=2)

    from paddle_tpu import export as pexport

    model_path = str(tmp_path / "emb.ptnm")
    pexport.export_aot_program(out, params, model_path, batch_size=4)
    aot_so = native.build_aot()
    csrc = tmp_path / "emb_client.c"
    csrc.write_text(C_AOT_TEST)
    exe = str(tmp_path / "emb_client")
    subprocess.run(["gcc", "-o", exe, str(csrc), aot_so,
                    f"-Wl,-rpath,{os.path.dirname(aot_so)}"],
                   check=True, capture_output=True)
    # C_AOT_TEST feeds in[i] = ((i*37) % 100 - 50)/100 — NOT valid ids;
    # drive with explicit id floats instead via a tiny custom client
    client = tmp_path / "emb_main.c"
    client.write_text(r"""
#include <stdio.h>
extern void* ptpu_aot_load(const char* path);
extern int ptpu_aot_infer(void* h, const char* name, const float* data,
                          long long batch, long long dim, float* out,
                          long long cap, long long* rows, long long* cols);
extern void ptpu_aot_release(void* h);
int main(int argc, char** argv) {
  void* m = ptpu_aot_load(argv[1]);
  if (!m) return 1;
  float ids[4] = {3.0f, 11.0f, 49.0f, 0.0f};
  float out[64]; long long rows = 0, cols = 0;
  int rc = ptpu_aot_infer(m, "ids", ids, 4, 1, out, 64, &rows, &cols);
  if (rc != 0) { fprintf(stderr, "rc=%d\n", rc); return 2; }
  printf("%lld %lld", rows, cols);
  for (long long i = 0; i < rows * cols; ++i) printf(" %.6f", out[i]);
  printf("\n");
  ptpu_aot_release(m);
  return 0;
}
""")
    exe2 = str(tmp_path / "emb_main")
    subprocess.run(["gcc", "-o", exe2, str(client), aot_so,
                    f"-Wl,-rpath,{os.path.dirname(aot_so)}"],
                   check=True, capture_output=True)
    proc = subprocess.run([exe2, model_path], capture_output=True,
                          text=True, env={}, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1500:]
    vals = proc.stdout.split()
    got = np.asarray([float(v) for v in vals[2:]]).reshape(4, 3)

    from paddle_tpu.platform.flags import FLAGS
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        expect, _ = topo.forward(params.as_dict(), topo.init_state(),
                                 {"ids": np.array([3, 11, 49, 0], np.int32)},
                                 train=False)
    finally:
        FLAGS.use_bf16 = old
    np.testing.assert_allclose(got, np.asarray(expect[0]), atol=1e-5)


def test_pjrt_export_int_feed_specs(tmp_path):
    """.ptpj v2 input specs must match the traced StableHLO signature:
    integer feeds (embedding models) declare i32 rank-1 [B], dense feeds
    f32 rank-2 [B, size] (ADVICE r4: v1 declared everything f32 rank-2)."""
    import struct

    from paddle_tpu import export as pexport
    from paddle_tpu import layer

    paddle.topology.reset_name_scope()
    ids = layer.data(name="ids", type=paddle.data_type.integer_value(50))
    x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
    emb = layer.embedding(input=ids, size=6, name="tbl")
    out = layer.fc(layer.addto(input=[emb, x]), size=3, act="softmax")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)
    path = str(tmp_path / "emb.ptpj")
    pexport.export_pjrt_model(out, params, path, batch_size=4)

    with open(path, "rb") as f:
        assert f.read(4) == b"PTPJ"
        version, ni = struct.unpack("<II", f.read(8))
        assert version == 2
        specs = {}
        for _ in range(ni):
            (nl,) = struct.unpack("<H", f.read(2))
            name = f.read(nl).decode()
            dtype, rank = struct.unpack("<BB", f.read(2))
            dims = struct.unpack(f"<{rank}q", f.read(8 * rank))
            specs[name] = (dtype, rank, dims)
    assert specs["ids"] == (1, 1, (4,))
    assert specs["x"] == (0, 2, (4, 6))


def _write_ptnm(path, tensors, inputs, outputs, consts, ops):
    """Hand-rolled .ptnm writer for crafting adversarial programs (same
    layout as export.export_aot_program's writer)."""
    import struct

    with open(path, "wb") as f:
        w = f.write
        w(b"PTNM")
        w(struct.pack("<I", 1))
        w(struct.pack("<I", len(tensors)))
        for dtype, dims in tensors:
            w(struct.pack("<BB", dtype, len(dims)))
            w(struct.pack(f"<{len(dims)}q", *dims))
        w(struct.pack("<I", len(inputs)))
        for tid, name in inputs:
            nm = name.encode()
            w(struct.pack("<IH", tid, len(nm)))
            w(nm)
        w(struct.pack("<I", len(outputs)))
        for tid in outputs:
            w(struct.pack("<I", tid))
        w(struct.pack("<I", len(consts)))
        for tid, arr in consts:
            raw = np.asarray(arr, np.float32).tobytes()
            w(struct.pack("<IQ", tid, len(raw)))
            w(raw)
        w(struct.pack("<I", len(ops)))
        for opcode, ins, out, attrs in ops:
            w(struct.pack("<II", opcode, len(ins)))
            w(struct.pack(f"<{len(ins)}I", *ins))
            w(struct.pack("<II", out, len(attrs)))
            w(struct.pack(f"<{len(attrs)}q", *attrs))


def test_aot_validator_rejects_malicious_programs(native, tmp_path):
    """validate_program must refuse crafted .ptnm files whose shapes would
    drive OOB reads/writes or null derefs in the executor (ADVICE r4):
    gather width mismatch, undersized DOT output, def-before-use
    violations, negative dims, shrinking RESHAPE, CONCAT overflow."""
    import ctypes

    from paddle_tpu.export import (OP_CONCAT, OP_DOT, OP_GATHER_ROWS,
                                   OP_IDENT, OP_RESHAPE)

    lib = ctypes.CDLL(native.build_aot())
    lib.ptpu_aot_load.restype = ctypes.c_void_p
    lib.ptpu_aot_load.argtypes = [ctypes.c_char_p]

    def load(name, *spec):
        path = str(tmp_path / name)
        _write_ptnm(path, *spec)
        return lib.ptpu_aot_load(path.encode())

    # sanity: a well-formed program loads (validator not over-rejecting)
    ok = load("ok.ptnm",
              [(0, (2, 3)), (0, (3, 4)), (0, (2, 4))],
              [(0, "x")], [2], [(1, np.zeros((3, 4)))],
              [(OP_DOT, [0, 1], 2, [])])
    assert ok
    lib.ptpu_aot_release(ctypes.c_void_p(ok))

    # GATHER_ROWS: out width 8 vs table width 4 -> heap overflow write
    assert not load("gather.ptnm",
                    [(0, (5, 4)), (0, (3, 1)), (0, (3, 8))],
                    [(1, "ids")], [2], [(0, np.zeros((5, 4)))],
                    [(OP_GATHER_ROWS, [0, 1], 2, [])])
    # DOT writes M*N=8 floats into a 4-float output
    assert not load("dot.ptnm",
                    [(0, (2, 3)), (0, (3, 4)), (0, (2, 2))],
                    [(0, "x")], [2], [(1, np.zeros((3, 4)))],
                    [(OP_DOT, [0, 1], 2, [])])
    # op reads tensor 1 which is neither const, input, nor produced
    assert not load("undef.ptnm",
                    [(0, (2, 3)), (0, (2, 3)), (0, (2, 3))],
                    [(0, "x")], [2], [],
                    [(OP_IDENT, [1], 2, [])])
    # negative dim -> size() underflow
    assert not load("negdim.ptnm",
                    [(0, (-4, 2)), (0, (2, 2))],
                    [(0, "x")], [1], [],
                    [(OP_IDENT, [0], 1, [])])
    # RESHAPE copies out.size()=16 elements from a 4-element input
    assert not load("reshape.ptnm",
                    [(0, (2, 2)), (0, (4, 4))],
                    [(0, "x")], [1], [],
                    [(OP_RESHAPE, [0], 1, [])])
    # CONCAT axis dims sum to 4 but out claims 5 rows
    assert not load("concat.ptnm",
                    [(0, (2, 3)), (0, (2, 3)), (0, (5, 3))],
                    [(0, "x"), (1, "y")], [2], [],
                    [(OP_CONCAT, [0, 1], 2, [0])])
    # output id never defined by any op
    assert not load("outundef.ptnm",
                    [(0, (2, 3)), (0, (2, 3))],
                    [(0, "x")], [1], [], [])
    # an op clobbering a weight const
    assert not load("clobber.ptnm",
                    [(0, (2, 3)), (0, (2, 3))],
                    [(0, "x")], [1], [(1, np.zeros((2, 3)))],
                    [(OP_IDENT, [0], 1, [])])


C_AOT_SHARED_TEST = r"""
#include <pthread.h>
#include <stdio.h>
#include <string.h>

extern void* ptpu_aot_load(const char* path);
extern void* ptpu_aot_create_shared(void* origin);
extern int ptpu_aot_infer(void* h, const char* name, const float* data,
                          long long batch, long long dim, float* out,
                          long long cap, long long* rows, long long* cols);
extern void ptpu_aot_release(void* h);

static float g_in[16];
static float g_expect[64];
static long long g_n = 0;

static void* worker(void* arg) {
  void* h = arg;
  float out[64];
  long long r = 0, c = 0;
  for (int it = 0; it < 50; ++it) {
    int rc = ptpu_aot_infer(h, "x", g_in, 2, 8, out, 64, &r, &c);
    if (rc != 0 || r * c != g_n ||
        memcmp(out, g_expect, g_n * sizeof(float)) != 0)
      return (void*)1;
  }
  return (void*)0;
}

int main(int argc, char** argv) {
  void* origin = ptpu_aot_load(argv[1]);
  if (!origin) return 1;
  void* s1 = ptpu_aot_create_shared(origin);
  void* s2 = ptpu_aot_create_shared(origin);
  if (!s1 || !s2) return 2;
  /* shared instances must outlive the origin handle (refcounted) */
  ptpu_aot_release(origin);
  for (int i = 0; i < 16; ++i) g_in[i] = (float)((i * 37 % 100) - 50) / 100.0f;
  long long r = 0, c = 0;
  if (ptpu_aot_infer(s1, "x", g_in, 2, 8, g_expect, 64, &r, &c) != 0)
    return 3;
  g_n = r * c;
  pthread_t t1, t2;
  pthread_create(&t1, 0, worker, s1);
  pthread_create(&t2, 0, worker, s2);
  void *r1 = 0, *r2 = 0;
  pthread_join(t1, &r1);
  pthread_join(t2, &r2);
  ptpu_aot_release(s1);
  ptpu_aot_release(s2);
  if (r1 || r2) return 4;
  printf("OK %lld\n", g_n);
  return 0;
}
"""


def test_aot_c_shared_param_concurrent(native, tmp_path):
    """create_shared (the paddle_gradient_machine_create_shared_param
    analog, capi/gradient_machine.h:88): two threads infer concurrently
    through shared handles over ONE weight copy, with the origin handle
    released first (refcounted lifetime) — outputs bit-identical to the
    single-thread run."""
    from paddle_tpu import export as pexport
    from paddle_tpu import layer

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    out = layer.fc(layer.fc(x, size=16, act="relu"), size=3, act="softmax")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)
    model_path = str(tmp_path / "shared.ptnm")
    pexport.export_aot_program(out, params, model_path, batch_size=2)

    aot_so = native.build_aot()
    csrc = tmp_path / "shared_client.c"
    csrc.write_text(C_AOT_SHARED_TEST)
    exe = str(tmp_path / "shared_client")
    subprocess.run(["gcc", "-pthread", "-o", exe, str(csrc), aot_so,
                    f"-Wl,-rpath,{os.path.dirname(aot_so)}"],
                   check=True, capture_output=True)
    proc = subprocess.run([exe, model_path], capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, (proc.returncode, proc.stderr)
    assert proc.stdout.startswith("OK")


def test_merged_model_create_shared(tmp_path):
    """MergedModel.create_shared: clone shares the compiled executable,
    infers identically, and concurrent inference from two python threads
    agrees with the single-thread result."""
    import threading

    from paddle_tpu import export as pexport
    from paddle_tpu import layer

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
    out = layer.fc(x, size=4, act="softmax")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=1)
    path = str(tmp_path / "m.ptmodel")
    pexport.merge_model(out, params, path, batch_size=3)

    m = pexport.load_merged_model(path)
    clone = m.create_shared()
    assert clone._exported is m._exported  # one executable, one weight copy
    fx = np.random.RandomState(0).randn(3, 6).astype(np.float32)
    want = m.infer({"x": fx})[0]
    np.testing.assert_array_equal(clone.infer({"x": fx})[0], want)

    results = {}

    def run(tag, inst):
        for _ in range(10):
            results[tag] = inst.infer({"x": fx})[0]

    ts = [threading.Thread(target=run, args=("a", m)),
          threading.Thread(target=run, args=("b", clone))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    np.testing.assert_array_equal(results["a"], want)
    np.testing.assert_array_equal(results["b"], want)
