"""paddle_tpu.obs tests: span tracer, Chrome-trace export determinism,
flight-recorder postmortems, unified metrics registry, trainer bridge,
and the obs-off zero-overhead contract.

Marker ``obs``.  Everything runs on injected clocks — no sleeps — and
the chaos scenarios reuse the ONE seeded replay definition in
``paddle_tpu.obs.cli.seeded_chaos`` (also the CLI's and the acceptance
criterion's), so "byte-identical across two replays" is tested against
the same trace a human would export.
"""

import json
import threading
from collections import Counter
from pathlib import Path

import jax
import pytest

import paddle_tpu.obs as obs
from paddle_tpu import event as v2_event
from paddle_tpu.analysis.lint import lint_source, run_lint
from paddle_tpu.analysis.retrace import auditor
from paddle_tpu.obs import (NULL_TRACER, Event, MetricsRegistry, Tracer,
                            chrome_trace, dumps_chrome, load_events,
                            trainer_event_bridge)
from paddle_tpu.obs.cli import main as obs_main
from paddle_tpu.obs.cli import seeded_chaos
from paddle_tpu.platform import stats as pstats
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (DecoderLM, FleetFaultPlan, FleetRouter,
                                ManualClock, PageLeakError, RequestStatus,
                                ServingEngine)
from paddle_tpu.master.service import LeaseTable

pytestmark = pytest.mark.obs


@pytest.fixture(scope="module")
def small_model():
    model = DecoderLM(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    return model, params


def make_engine(model, params, clock, **kw):
    return ServingEngine(model, params, eos_id=1, page_size=4,
                         num_pages=32, max_pages_per_seq=8, max_slots=4,
                         buckets=(8, 16), time_fn=clock, **kw)


@pytest.fixture
def dump_dir(tmp_path):
    old = FLAGS.obs_dump_dir
    FLAGS.obs_dump_dir = str(tmp_path)
    yield tmp_path
    FLAGS.obs_dump_dir = old


@pytest.fixture(scope="module")
def chaos_pair():
    """Two replays of the seeded acceptance chaos (kill + partition +
    slow on 4 replicas) — shared by the root-span and determinism
    tests so the expensive replays run once."""
    return seeded_chaos(), seeded_chaos()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("reqs", "requests").inc()
    reg.counter("reqs").labels(replica=1).inc(2)
    reg.gauge("depth").set(7)
    reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.05)
    reg.histogram("lat").observe(5.0)
    snap = reg.snapshot()
    assert snap["reqs"] == 1
    assert snap["reqs{replica=1}"] == 2
    assert snap["depth"] == 7
    assert snap["lat_count"] == 2
    assert snap["lat_sum"] == pytest.approx(5.05)
    assert snap["lat_max"] == 5.0
    text = reg.to_text()
    assert "# TYPE reqs counter" in text
    assert 'lat_bucket{le="+Inf"} 2' in text
    # exposition format: label VALUES are double-quoted
    assert 'reqs{replica="1"} 2' in text
    # a name keeps its kind
    with pytest.raises(TypeError):
        reg.gauge("reqs")
    # snapshot order is deterministic
    assert list(snap) == list(reg.snapshot())


def test_serving_and_fleet_metrics_publish_into_registry():
    from paddle_tpu.serving.metrics import FleetMetrics, ServingMetrics

    reg = MetricsRegistry()
    sm = ServingMetrics(pool_pages=8)
    sm.on_submit(0.0, True)
    sm.on_complete()
    sm.publish(reg, replica=0)
    fm = FleetMetrics()
    fm.on_submit(0.0)
    fm.publish(reg)
    snap = reg.snapshot()
    assert snap["serving_requests_submitted{replica=0}"] == 1
    assert snap["serving_requests_completed{replica=0}"] == 1
    assert snap["fleet_submitted"] == 1


# ---------------------------------------------------------------------------
# StatSet satellite: locked get/iteration + publish
# ---------------------------------------------------------------------------


def test_statset_get_locked_and_copied_under_concurrency():
    ss = pstats.StatSet()
    stop = threading.Event()
    errors = []

    def writer():
        while not stop.is_set():
            ss.add("hot", 0.001)

    def reader():
        try:
            while not stop.is_set():
                e = ss.get("hot")
                if e is not None:
                    # a torn read (count bumped before total) would make
                    # avg wildly off; a copied entry never mutates
                    c0, t0 = e.count, e.total
                    assert e.count == c0 and e.total == t0
                ss.report()
                ss.snapshot()
        except Exception as exc:               # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=writer) for _ in range(2)] + \
              [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    import time as _time
    _time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    got = ss.get("hot")
    assert got is not None and got.count > 0
    # the returned entry is a COPY: mutating it cannot corrupt the set
    got.count = -1
    assert ss.get("hot").count > 0
    assert ss.get("missing") is None


def test_statset_publish_into_registry():
    ss = pstats.StatSet()
    ss.add("trainOneBatch", 0.25)
    ss.add("trainOneBatch", 0.75)
    reg = MetricsRegistry()
    ss.publish(reg, prefix="trainer_")
    snap = reg.snapshot()
    assert snap["trainer_seconds_total{name=trainOneBatch}"] == \
        pytest.approx(1.0)
    assert snap["trainer_calls{name=trainOneBatch}"] == 2
    assert snap["trainer_seconds_max{name=trainOneBatch}"] == \
        pytest.approx(0.75)


# ---------------------------------------------------------------------------
# tracer + exporter units
# ---------------------------------------------------------------------------


def test_tracer_spans_instants_async_and_export_shape():
    clk = ManualClock(tick_s=0.01)
    t = Tracer(time_fn=clk, ring_size=64)
    with t.span("decode_tick", replica=0, tick=3, n=2):
        clk.advance(0.02)
    t.instant("admit", rid=5, slot=1, replica=0)
    t.async_begin("fleet_request", id=17, id_space="frid")
    t.async_end("fleet_request", id=17, id_space="frid", status="completed")
    trace = chrome_trace(t.events)
    evs = trace["traceEvents"]
    # metadata names replicas/slots
    assert {"ph": "M", "name": "process_name", "pid": 0,
            "args": {"name": "replica 0"}} in evs
    assert any(e.get("args", {}).get("name") == "slot 1" for e in evs
               if e.get("ph") == "M" and e.get("name") == "thread_name")
    span = next(e for e in evs if e.get("ph") == "X")
    assert span["name"] == "decode_tick" and span["dur"] == 20000
    inst = next(e for e in evs if e.get("ph") == "i")
    assert inst["s"] == "t" and inst["args"]["rid"] == 0   # normalized
    b = next(e for e in evs if e.get("ph") == "b")
    e = next(e for e in evs if e.get("ph") == "e")
    assert b["id"] == e["id"] == 0                          # normalized
    assert json.loads(dumps_chrome(t.events))["traceEvents"]


def test_event_roundtrip_and_jsonl(tmp_path):
    ev = Event(kind="i", name="route", ts=1.25, cat="fleet", replica=2,
               id=4, id_space="frid", args={"pages": (3, 4), "ok": True})
    back = Event.from_dict(json.loads(json.dumps(ev.to_dict())))
    assert back.name == "route" and back.replica == 2
    assert back.args["pages"] == [3, 4]
    t = Tracer(time_fn=ManualClock())
    t.instant("a")
    t.instant("b", rid=1)
    p = t.save(str(tmp_path / "ev.jsonl"))
    assert [e.name for e in load_events(p)] == ["a", "b"]


def test_flight_recorder_ring_bounded():
    t = Tracer(time_fn=ManualClock(), ring_size=4, keep_all=False)
    for i in range(10):
        t.instant("tick", tick=i)
    assert len(t.ring) == 4
    assert t.dropped == 6
    assert [e.args["tick"] for e in t.ring] == [6, 7, 8, 9]
    # keep_all=True counts ring displacement identically: a postmortem's
    # dropped_before_ring is honest about the ring window either way
    t2 = Tracer(time_fn=ManualClock(), ring_size=4, keep_all=True)
    for i in range(10):
        t2.instant("tick", tick=i)
    assert len(t2.events) == 10 and t2.dropped == 6


def test_obs_keep_all_flag_bounds_flag_built_tracers():
    from paddle_tpu.obs.trace import tracer_for
    old_trace, old_keep = FLAGS.obs_trace, FLAGS.obs_keep_all
    try:
        FLAGS.obs_trace = True
        FLAGS.obs_keep_all = False
        clk = ManualClock()
        t = tracer_for(clk)
        for i in range(FLAGS.obs_ring_size + 5):
            t.instant("tick", tick=i)
        assert t.events == []            # bounded: only the ring retained
        assert len(t.ring) == FLAGS.obs_ring_size
    finally:
        FLAGS.obs_trace, FLAGS.obs_keep_all = old_trace, old_keep


def test_begin_end_keep_the_opening_category():
    clk = ManualClock(tick_s=0.01)
    t = Tracer(time_fn=clk, ring_size=16)
    t.begin("phase", key=1, cat="train", replica=2)
    clk.advance(0.01)
    t.end("phase", key=1)                # no cat: begin's wins
    assert t.events[-1].cat == "train" and t.events[-1].replica == 2
    t.begin("phase", key=2, cat="train")
    t.end("phase", key=2, cat="fleet")   # explicit end cat overrides
    assert t.events[-1].cat == "fleet"


def test_null_tracer_is_inert(dump_dir):
    assert not NULL_TRACER.enabled
    with NULL_TRACER.span("x", rid=1):
        pass
    NULL_TRACER.instant("y")
    NULL_TRACER.async_begin("z", id=1)
    assert NULL_TRACER.scoped(replica=3) is NULL_TRACER
    assert NULL_TRACER.dump_postmortem("PAGE-LEAK") is None
    assert list(dump_dir.iterdir()) == []


# ---------------------------------------------------------------------------
# engine lifecycle tracing
# ---------------------------------------------------------------------------


def test_engine_trace_covers_request_lifecycle(small_model):
    model, params = small_model
    clk = ManualClock(tick_s=0.01)
    tracer = Tracer(time_fn=clk, registry=MetricsRegistry())
    eng = make_engine(model, params, clk, tracer=tracer,
                      registry=tracer.registry)
    rid = eng.submit([2, 3, 4, 5, 6], max_tokens=4)
    eng.run()
    assert eng.status(rid) is RequestStatus.COMPLETED
    names = Counter(e.name for e in tracer.events)
    for expected in ("submit", "admit", "prefill_chunk", "decode_tick",
                     "first_token", "terminal", "page_alloc", "page_free"):
        assert names[expected] >= 1, (expected, names)
    term = next(e for e in tracer.events if e.name == "terminal")
    assert term.args["status"] == "completed"
    # per-stage histograms observed on the same injected clock
    snap = tracer.registry.snapshot()
    assert snap["serving_stage_seconds{stage=queue}_count"] >= 1
    assert snap["serving_stage_seconds{stage=prefill}_count"] >= 1
    assert snap["serving_stage_seconds{stage=decode}_count"] >= 1


def test_engine_healthz_exposes_registry(small_model):
    model, params = small_model
    clk = ManualClock(tick_s=0.01)
    eng = make_engine(model, params, clk)
    eng.submit([2, 3, 4], max_tokens=2)
    eng.run()
    hz = eng.healthz()
    assert hz["ok"]
    assert hz["metrics"]["serving_requests_completed"] == 1
    assert "serving_stage_seconds{stage=queue}_count" in hz["metrics"]


# ---------------------------------------------------------------------------
# fleet chaos: root spans + deterministic export (acceptance criterion)
# ---------------------------------------------------------------------------


def _root_span_counts(events):
    per = Counter()
    for e in events:
        if e.name == "fleet_request":
            per[(e.kind, e.id)] += 1
    return per


def test_chaos_exactly_one_root_span_per_fleet_rid(chaos_pair):
    (tracer, fleet, frids), _ = chaos_pair
    assert not fleet.has_work
    # chaos actually happened: an injected kill AND a lease-expiry
    # death, with resubmits to survivors
    reasons = [r.dead_reason for r in fleet.replicas]
    assert "injected kill @ tick 8" in reasons
    assert "lease expired" in reasons
    assert fleet.metrics.resubmits > 0
    per = _root_span_counts(tracer.events)
    begun = {i for (k, i), _ in per.items() if k == "b"}
    ended = {i for (k, i), _ in per.items() if k == "e"}
    assert begun == ended == set(frids)
    assert all(c == 1 for c in per.values()), per
    # resubmit edges are on the timeline, tied to their fleet rid
    resubs = [e for e in tracer.events if e.name == "resubmit"]
    assert len(resubs) == fleet.metrics.resubmits
    assert all(e.args["frid"] in frids for e in resubs)
    # every root span closes with the request's terminal status
    for e in tracer.events:
        if e.kind == "e" and e.name == "fleet_request":
            assert e.args["status"] == str(fleet.status(e.id))


def test_chaos_export_is_byte_identical_across_replays(chaos_pair):
    (t1, fleet1, _), (t2, fleet2, _) = chaos_pair
    b1 = dumps_chrome(t1.events)
    b2 = dumps_chrome(t2.events)
    assert b1 == b2
    # and it is valid Chrome-trace JSON Perfetto accepts: a traceEvents
    # list whose entries all carry a phase, with matched async pairs
    trace = json.loads(b1)
    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    assert all("ph" in e for e in trace["traceEvents"])
    asyncs = Counter((e["ph"], e["id"]) for e in trace["traceEvents"]
                     if e["ph"] in ("b", "e"))
    bs = sorted(i for (ph, i) in asyncs if ph == "b")
    es = sorted(i for (ph, i) in asyncs if ph == "e")
    assert bs == es == list(range(len(bs)))    # dense normalized ids
    # replica processes are named
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert {"replica 0", "replica 1", "replica 2", "replica 3"} <= names


def test_budget_exhausted_failed_still_gets_one_root_span(small_model):
    """A fleet rid that dies with its replica and has NO resubmit budget
    ends FAILED — and still closes exactly one root span."""
    model, params = small_model
    clock = ManualClock(tick_s=0.01)
    plan = FleetFaultPlan(seed=0, clock=clock, kill_at={3: 0})
    tracer = Tracer(time_fn=clock)

    def mk(i, time_fn):
        return make_engine(model, params, time_fn)

    fleet = FleetRouter(mk, 1, heartbeat_s=0.05, resubmit_budget=0,
                        faults=plan, tracer=tracer)
    frid = fleet.submit([2, 3, 4, 5], max_tokens=8)
    fleet.run(max_ticks=50)
    assert fleet.status(frid) is RequestStatus.FAILED
    per = _root_span_counts(tracer.events)
    assert per == {("b", frid): 1, ("e", frid): 1}
    end = next(e for e in tracer.events
               if e.kind == "e" and e.name == "fleet_request")
    assert end.args["status"] == "failed"
    assert end.args["resubmits"] == 0


# ---------------------------------------------------------------------------
# flight recorder: postmortem on a forced REF-LEAK
# ---------------------------------------------------------------------------


def test_flight_recorder_dumps_postmortem_on_ref_leak(small_model, dump_dir,
                                                      capsys):
    model, params = small_model
    clk = ManualClock(tick_s=0.01)
    tracer = Tracer(time_fn=clk)
    eng = make_engine(model, params, clk, tracer=tracer)
    rid = eng.submit([2, 3, 4, 5], max_tokens=3)
    eng.run()
    assert eng.status(rid) is RequestStatus.COMPLETED
    # force a REF-LEAK: a page held by nobody the engine accounts for
    eng.pool.alloc(1)
    with pytest.raises(PageLeakError, match="REF-LEAK"):
        eng.check_page_conservation()
    path = tracer.last_postmortem
    assert path is not None and Path(path).exists()
    assert str(dump_dir) in path and "ref-leak" in Path(path).name
    assert "OBS-POSTMORTEM: " + path in capsys.readouterr().out
    payload = json.loads(Path(path).read_text())
    assert payload["reason"] == "REF-LEAK"
    names = {e["name"] for e in payload["events"]}
    # the dump carries the history that produced the leak — including
    # the rogue allocation itself
    assert {"submit", "terminal", "page_alloc"} <= names
    # the postmortem file round-trips through the exporter
    evs = load_events(path)
    assert json.loads(dumps_chrome(evs))["traceEvents"]
    # once per reason per engine: a healthz probe of the still-leaky
    # pool must not spray one dump per probe
    assert not eng.healthz()["ok"]
    assert tracer.last_postmortem == path
    assert len(list(dump_dir.iterdir())) == 1


# ---------------------------------------------------------------------------
# obs off == zero overhead (sealed-auditor run, the acceptance pin)
# ---------------------------------------------------------------------------


@pytest.fixture
def audit():
    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    auditor().reset()
    yield auditor()
    FLAGS.jit_audit = old
    auditor().reset()


def _steady_traffic(eng, clock, n=6):
    rids = [eng.submit([2, 3, 4, 5], max_tokens=4),
            eng.submit([3, 4, 5, 6], max_tokens=4)]
    eng.run()
    for _ in range(n - 2):
        rids.append(eng.submit([2, 3, 4, 5], max_tokens=4))
        eng.run()
    return [eng.result(r) for r in rids]


def test_obs_off_adds_zero_compiles_to_sealed_decode(small_model, audit):
    """FLAGS.obs_trace off: the engine runs on the NULL_TRACER, records
    nothing, and a sealed steady-state run of the unified step stays
    at EXACTLY one compile per (decode_bucket, prefill_bucket) pair
    with zero retraces — the same per-pair budget the pre-obs engine
    pinned.  Then the same traffic with tracing ON still holds the
    budget and produces token-identical outputs: instrumentation adds
    zero compiles and zero host syncs to the tick either way (the
    linter's host-sync rule over obs/ proves the syncs side
    statically)."""
    model, params = small_model
    assert not FLAGS.obs_trace
    clk = ManualClock(tick_s=0.01)
    eng = make_engine(model, params, clk, prefix_cache=False)
    assert eng._tracer is NULL_TRACER
    assert eng.pool.tracer is None and eng.scheduler.tracer is None
    out_off = _steady_traffic(eng, clk)
    pairs = audit.compile_count("serving.step")
    assert pairs == len(eng._step_fns)       # one compile per pair
    audit.seal()
    out_off += _steady_traffic(eng, clk)     # steady state: no compiles
    audit.assert_budget("serving.step", pairs)
    audit.assert_no_retraces()
    assert NULL_TRACER.events == [] and len(NULL_TRACER.ring) == 0

    auditor().reset()
    clk2 = ManualClock(tick_s=0.01)
    tracer = Tracer(time_fn=clk2)
    eng2 = make_engine(model, params, clk2, prefix_cache=False,
                       tracer=tracer)
    out_on = _steady_traffic(eng2, clk2)
    pairs_on = auditor().compile_count("serving.step")
    auditor().seal()
    out_on += _steady_traffic(eng2, clk2)
    assert pairs_on == pairs
    auditor().assert_budget("serving.step", pairs_on)
    auditor().assert_no_retraces()
    assert out_on == out_off
    assert any(e.name == "decode_tick" for e in tracer.events)


def test_obs_trace_flag_gates_at_construction(small_model):
    model, params = small_model
    clk = ManualClock(tick_s=0.01)
    old = FLAGS.obs_trace
    try:
        FLAGS.obs_trace = True
        eng = make_engine(model, params, clk)
        assert eng._tracer.enabled
        rid = eng.submit([2, 3, 4], max_tokens=2)
        eng.run()
        assert eng.status(rid) is RequestStatus.COMPLETED
        assert any(e.name == "decode_tick" for e in eng._tracer.events)
    finally:
        FLAGS.obs_trace = old


# ---------------------------------------------------------------------------
# jit_compile events via the retrace auditor
# ---------------------------------------------------------------------------


def test_auditor_compiles_land_on_the_timeline(small_model, audit):
    model, params = small_model
    clk = ManualClock(tick_s=0.01)
    tracer = Tracer(time_fn=clk)
    eng = make_engine(model, params, clk, tracer=tracer)
    assert audit.tracer is tracer            # set_tracer attached it
    eng.submit([2, 3, 4, 5], max_tokens=3)
    eng.run()
    sites = [e.args["site"] for e in tracer.events
             if e.name == "jit_compile"]
    assert "serving.step" in sites
    assert audit.compile_count("serving.step") == \
        sites.count("serving.step")


# ---------------------------------------------------------------------------
# lease transitions on the timeline
# ---------------------------------------------------------------------------


def test_lease_table_transitions_traced():
    clk = ManualClock(tick_s=0.0)
    tracer = Tracer(time_fn=clk)
    lt = LeaseTable(1.0, time_fn=clk, tracer=tracer)
    slot, token = lt.register()
    assert lt.heartbeat(slot, token)
    clk.advance(2.0)                       # past TTL: expires on sweep
    assert not lt.heartbeat(slot, token)   # zombie renewal rejected
    slot2, token2 = lt.register()
    assert lt.drop(slot2, token2)
    names = [e.name for e in tracer.events]
    assert names.count("lease_register") == 2
    assert "lease_expire" in names and "lease_reject" in names
    assert "lease_drop" in names
    # tokens never reach the timeline
    assert all(token not in str(e.args) and token2 not in str(e.args)
               for e in tracer.events)


# ---------------------------------------------------------------------------
# trainer event bridge
# ---------------------------------------------------------------------------


def test_trainer_event_bridge_mirrors_events_as_spans():
    clk = ManualClock(tick_s=0.0)
    reg = MetricsRegistry()
    tracer = Tracer(time_fn=clk, registry=reg)
    seen = []
    handler = trainer_event_bridge(tracer, seen.append)
    handler(v2_event.BeginPass(0))
    for b in range(3):
        handler(v2_event.BeginIteration(0, b))
        clk.advance(0.01)
        handler(v2_event.EndIteration(0, b, cost=0.5))
    handler(v2_event.EndPass(0))
    assert len(seen) == 8                      # inner handler still runs
    spans = [e for e in tracer.events if e.kind == "X"]
    assert len(spans) == 3
    assert all(e.name == "train_iteration" and
               e.dur == pytest.approx(0.01) for e in spans)
    roots = [(e.kind, e.id) for e in tracer.events
             if e.name == "train_pass"]
    assert roots == [("b", 0), ("e", 0)]
    snap = reg.snapshot()
    assert snap["train_iterations_total"] == 3
    assert snap["train_passes_total"] == 1
    # serving + training share one export pipeline
    assert json.loads(dumps_chrome(tracer.events))["traceEvents"]


def test_bridge_never_forces_the_lazy_cost_sync():
    class Exploding:
        """A device-scalar stand-in whose float() is the sync."""

        def __float__(self):
            raise AssertionError("bridge forced a host sync")

    tracer = Tracer(time_fn=ManualClock())
    handler = trainer_event_bridge(tracer)
    handler(v2_event.BeginIteration(0, 0))
    handler(v2_event.EndIteration(0, 0, cost=Exploding()))


# ---------------------------------------------------------------------------
# lint coverage over obs/ (satellite)
# ---------------------------------------------------------------------------


def test_lint_wall_clock_and_host_sync_cover_obs_dir():
    wall = lint_source("import time\n\ndef f():\n    return time.time()\n",
                       path="paddle_tpu/obs/bad.py", rules=["wall-clock"])
    assert len(wall) == 1 and "wall-clock" in wall[0].code
    sync = lint_source(
        "import numpy as np\n\ndef f(xs):\n    for x in xs:\n"
        "        np.asarray(x)\n",
        path="paddle_tpu/obs/bad.py", rules=["host-sync"])
    assert len(sync) == 1 and "host-sync" in sync[0].code
    # ...and an unrelated dir still skips the dir-scoped rules
    assert lint_source("import time\n\ndef f():\n    return time.time()\n",
                       path="paddle_tpu/models/x.py",
                       rules=["wall-clock"]) == []


def test_obs_package_lints_clean():
    obs_dir = Path(obs.__file__).resolve().parent
    assert run_lint([str(obs_dir)]) == []


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_export_jsonl_and_postmortem(tmp_path, capsys):
    clk = ManualClock()
    t = Tracer(time_fn=clk)
    t.instant("submit", rid=1)
    with t.span("decode_tick", tick=0):
        clk.advance(0.01)
    src = t.save(str(tmp_path / "events.jsonl"))
    out = str(tmp_path / "trace.json")
    assert obs_main(["export", src, "-o", out]) == 0
    trace = json.loads(Path(out).read_text())
    assert any(e.get("name") == "decode_tick"
               for e in trace["traceEvents"])
    pm = t.dump_postmortem("PAGE-LEAK", dump_dir=str(tmp_path))
    out2 = str(tmp_path / "pm.json")
    assert obs_main(["export", pm, "-o", out2]) == 0
    assert json.loads(Path(out2).read_text())["traceEvents"]
    assert obs_main([]) == 2
    assert obs_main(["nope"]) == 2
    # a trailing flag with no value falls back to the default instead of
    # an IndexError traceback
    assert obs_main(["export", src, "-o"]) == 0
    capsys.readouterr()
