"""Checkpoint/resume (incl. optimizer state) + merged-model export tests.

Reference analog: ParamUtil save/load (pass-%05d dirs), go/pserver
md5-verified checkpoints, and MergeModel.cpp single-file inference.
"""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import checkpoint as ckpt
from paddle_tpu import export as pexport
from paddle_tpu import layer, optimizer, trainer


def build_model():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = layer.data(name="y", type=paddle.data_type.integer_value(3))
    h = layer.fc(x, size=16, act="relu")
    logits = layer.fc(h, size=3)
    cost = layer.classification_cost(input=logits, label=y)
    return x, y, logits, cost


def make_reader(rng, n=96):
    data = []
    for _ in range(n):
        yv = rng.randint(0, 3)
        xv = rng.randn(8).astype(np.float32) * 0.1
        xv[yv * 2] += 1.0
        data.append((xv, yv))
    return lambda: iter(data)


def test_checkpoint_roundtrip_with_optimizer_state(tmp_path, rng):
    x, y, logits, cost = build_model()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Momentum(momentum=0.9,
                                                         learning_rate=0.05))
    reader = paddle.batch(make_reader(rng), 32)
    sgd.train(reader, num_passes=2, save_dir=str(tmp_path))

    assert ckpt.latest_pass(str(tmp_path)) == 1
    p2, opt2, mst2, meta = ckpt.load_checkpoint(str(tmp_path))
    assert meta["pass_id"] == 1
    for k in params.names():
        np.testing.assert_allclose(np.asarray(p2[k]),
                                   np.asarray(params[k]), atol=1e-6)
    # optimizer slots (momentum velocity) must round-trip non-trivially
    flat = []
    def walk(t):
        if isinstance(t, dict):
            for v in t.values():
                walk(v)
        elif isinstance(t, (list, tuple)):
            for v in t:
                walk(v)
        elif hasattr(t, "shape"):
            flat.append(np.asarray(t))
    walk(opt2)
    assert any(np.abs(a).sum() > 0 for a in flat if a.size > 1)


def test_resume_continues_identically(tmp_path, rng):
    """Train 4 passes straight vs 2 + checkpoint + resume 2: same params
    (the --start_pass resume semantics)."""
    reader_data = make_reader(rng)

    def run(passes_a, passes_b, save_dir):
        x, y, logits, cost = build_model()
        params = paddle.Parameters.from_topology(
            paddle.topology.Topology([cost]), seed=3)
        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Momentum(
                              momentum=0.9, learning_rate=0.05))
        reader = paddle.batch(reader_data, 32)
        sgd.train(reader, num_passes=passes_a, save_dir=save_dir)
        if passes_b:
            # fresh trainer, resume from checkpoint
            x2, y2, logits2, cost2 = build_model()
            params2 = paddle.Parameters.from_topology(
                paddle.topology.Topology([cost2]), seed=99)  # junk init
            sgd2 = trainer.SGD(cost=cost2, parameters=params2,
                               update_equation=optimizer.Momentum(
                                   momentum=0.9, learning_rate=0.05))
            # num_passes is the TOTAL pass count (reference --num_passes)
            sgd2.train(reader, num_passes=passes_a + passes_b,
                       save_dir=save_dir, start_pass=passes_a)
            return params2
        return params

    d1 = str(tmp_path / "straight")
    d2 = str(tmp_path / "resumed")
    p_straight = run(4, 0, d1)
    p_resumed = run(2, 2, d2)
    for k in p_straight.names():
        np.testing.assert_allclose(np.asarray(p_resumed[k]),
                                   np.asarray(p_straight[k]),
                                   atol=1e-5, rtol=1e-5)


def test_checkpoint_corruption_detected(tmp_path, rng):
    x, y, logits, cost = build_model()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    ckpt.save_checkpoint(str(tmp_path), 0, params)
    with open(os.path.join(str(tmp_path), "pass-00000", "params.tar"),
              "r+b") as f:
        f.seek(100)
        f.write(b"XXXX")
    with pytest.raises(Exception):
        ckpt.load_checkpoint(str(tmp_path), 0)


def test_merge_model_roundtrip(tmp_path, rng):
    x, y, logits, cost = build_model()
    topo = paddle.topology.Topology([logits])
    params = paddle.Parameters.from_topology(topo, seed=0)
    path = str(tmp_path / "model.ptm")
    pexport.merge_model(logits, params, path)

    m = pexport.load_merged_model(path)
    assert m.input_names == ["x"]
    xb = rng.randn(4, 8).astype(np.float32)
    (got,) = m.infer({"x": xb})

    state = topo.init_state()
    expect, _ = topo.forward(params.as_dict(), state, {"x": xb},
                             train=False)
    np.testing.assert_allclose(got, np.asarray(expect[0]), atol=1e-5)

    # symbolic batch: different batch size works on the same artifact
    xb2 = rng.randn(9, 8).astype(np.float32)
    (got2,) = m.infer({"x": xb2})
    assert got2.shape == (9, 3)
