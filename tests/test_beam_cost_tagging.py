"""cross_entropy_over_beam + sequence_tagging CRF demo.

Reference bars: CrossEntropyOverBeam.cpp semantics (globally-normalized
path softmax, gold-as-extra-path when it falls off the beam at step t),
checked against a numpy oracle and by numeric gradients; and the
v1_api_demo/sequence_tagging linear_crf demo trained end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.models import sequence_tagging
from paddle_tpu.ops import losses as ploss
from paddle_tpu.platform.flags import FLAGS


@pytest.fixture(autouse=True)
def f32_math():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


# ---------------------------------------------------------------------------
# cross_entropy_over_beam op
# ---------------------------------------------------------------------------


def _oracle_one(beams, b):
    """Reference semantics (CrossEntropyOverBeam.cpp:131-162) for one
    sequence: shared path prefixes cancel, so the cost is the softmax at
    the decisive expansion over [beam scores (gold copy removed), gold]."""
    t_fall = None
    for t, (scores, selected, gold) in enumerate(beams):
        if gold[b] not in list(selected[b]):
            t_fall = t
            break
    f = t_fall if t_fall is not None else len(beams) - 1
    scores, selected, gold = beams[f]
    logits = [scores[b, j] for j in selected[b] if j != gold[b]]
    logits.append(scores[b, gold[b]])
    logits = np.asarray(logits, np.float64)
    e = np.exp(logits - logits.max())
    return -np.log(e[-1] / e.sum())


def _mk_beams(rng, batch=4, t=3, n=12, k=4):
    beams = []
    for _ in range(t):
        scores = rng.randn(batch, n).astype(np.float32)
        selected = np.stack([rng.choice(n, size=k, replace=False)
                             for _ in range(batch)]).astype(np.int32)
        gold = rng.randint(0, n, size=batch).astype(np.int32)
        beams.append((scores, selected, gold))
    return beams


def test_beam_cost_matches_oracle():
    rng = np.random.RandomState(0)
    beams = _mk_beams(rng)
    # force specific regimes: seq0 gold in beam everywhere; seq1 falls off
    # at step 0; seq2 at step 1
    for t, (scores, selected, gold) in enumerate(beams):
        gold[0] = selected[0][0]
        if t == 0:
            gold[1] = [j for j in range(12) if j not in selected[1]][0]
        gold[2] = (selected[2][1] if t < 1
                   else [j for j in range(12) if j not in selected[2]][0])
    got = np.asarray(ploss.cross_entropy_over_beam(
        [(jnp.asarray(s), jnp.asarray(c), jnp.asarray(g))
         for s, c, g in beams]))
    for b in range(4):
        assert got[b] == pytest.approx(_oracle_one(beams, b), rel=1e-5), b


def test_beam_cost_mixed_beam_sizes_and_grad():
    rng = np.random.RandomState(1)
    b1 = _mk_beams(rng, t=1, n=10, k=3)[0]
    b2 = _mk_beams(rng, t=1, n=16, k=5)[0]
    beams = [b1, b2]

    def loss_fn(s1, s2):
        return jnp.sum(ploss.cross_entropy_over_beam(
            [(s1, jnp.asarray(b1[1]), jnp.asarray(b1[2])),
             (s2, jnp.asarray(b2[1]), jnp.asarray(b2[2]))]))

    g1, g2 = jax.grad(loss_fn, argnums=(0, 1))(jnp.asarray(b1[0]),
                                               jnp.asarray(b2[0]))
    # numeric check on a few coordinates of each expansion's scores
    for (arr, grad, idx) in [(b1[0], g1, (0, 2)), (b2[0], g2, (3, 7))]:
        eps = 1e-3
        up, dn = arr.copy(), arr.copy()
        up[idx] += eps
        dn[idx] -= eps
        if arr is b1[0]:
            num = (loss_fn(jnp.asarray(up), jnp.asarray(b2[0])) -
                   loss_fn(jnp.asarray(dn), jnp.asarray(b2[0]))) / (2 * eps)
        else:
            num = (loss_fn(jnp.asarray(b1[0]), jnp.asarray(up)) -
                   loss_fn(jnp.asarray(b1[0]), jnp.asarray(dn))) / (2 * eps)
        assert float(num) == pytest.approx(float(grad[idx]), abs=2e-3)


def test_beam_cost_linked_paths_full_oracle_and_grad():
    """With parents links, the loss must match a full path-enumeration
    oracle (reference semantics: path scores SUM across expansions,
    CrossEntropyOverBeam.cpp:137-156) and EARLIER expansions' scores
    must receive nonzero gradient."""
    rng = np.random.RandomState(5)
    B, N0, K0, N1, K1 = 3, 8, 3, 10, 3
    s0 = rng.randn(B, N0).astype(np.float32)
    sel0 = np.stack([rng.choice(N0, K0, replace=False)
                     for _ in range(B)]).astype(np.int32)
    g0 = np.array([sel0[b][b % K0] for b in range(B)], np.int32)  # in beam
    s1 = rng.randn(B, N1).astype(np.float32)
    sel1 = np.stack([rng.choice(N1, K1, replace=False)
                     for _ in range(B)]).astype(np.int32)
    par1 = np.stack([rng.randint(0, K0, K1) for _ in range(B)]).astype(np.int32)
    g1 = np.array([sel1[b][0] for b in range(B)], np.int32)
    # make candidate 0's ancestry the gold slot so the gold path is IN
    # the final beam for seq 0; push it off ancestry for seq 1
    gold_slot0 = np.array([int(np.where(sel0[b] == g0[b])[0][0])
                           for b in range(B)])
    par1[0, 0] = gold_slot0[0]
    par1[1, 0] = (gold_slot0[1] + 1) % K0   # wrong ancestry -> falls off
    g1[2] = [j for j in range(N1) if j not in sel1[2]][0]  # id falls off

    beams = [(jnp.asarray(s0), jnp.asarray(sel0), jnp.asarray(g0)),
             (jnp.asarray(s1), jnp.asarray(sel1), jnp.asarray(g1),
              jnp.asarray(par1))]
    got = np.asarray(ploss.cross_entropy_over_beam(beams))

    for b in range(B):
        gold_path_score = s0[b, g0[b]] + s1[b, g1[b]]
        gold_in_final = any(
            sel1[b][k] == g1[b] and par1[b][k] == gold_slot0[b]
            for k in range(K1))
        if gold_in_final or b == 0:
            # decisive expansion = final: normalize over full paths
            logits = [s0[b, sel0[b][par1[b][k]]] + s1[b, sel1[b][k]]
                      for k in range(K1)
                      if not (sel1[b][k] == g1[b]
                              and par1[b][k] == gold_slot0[b])]
        else:
            logits = [s0[b, sel0[b][par1[b][k]]] + s1[b, sel1[b][k]]
                      for k in range(K1)]
        logits.append(gold_path_score)
        logits = np.asarray(logits, np.float64)
        e = np.exp(logits - logits.max())
        want = -np.log(e[-1] / e.sum())
        assert got[b] == pytest.approx(want, rel=1e-5), b

    # earlier-expansion gradient is NONZERO (the single-step
    # simplification this replaced gave exactly zero here)
    def loss_fn(s0_):
        return jnp.sum(ploss.cross_entropy_over_beam(
            [(s0_, jnp.asarray(sel0), jnp.asarray(g0)),
             (jnp.asarray(s1), jnp.asarray(sel1), jnp.asarray(g1),
              jnp.asarray(par1))]))

    g = np.asarray(jax.grad(loss_fn)(jnp.asarray(s0)))
    assert np.abs(g).max() > 1e-3, "no gradient to expansion 0"
    # and numerically correct
    idx = (0, int(sel0[0][par1[0, 1]]))
    eps = 1e-3
    up, dn = s0.copy(), s0.copy()
    up[idx] += eps
    dn[idx] -= eps
    num = (loss_fn(jnp.asarray(up)) - loss_fn(jnp.asarray(dn))) / (2 * eps)
    assert float(num) == pytest.approx(float(g[idx]), abs=2e-3)


def test_beam_cost_layer_trains():
    """Learning-to-search e2e: scores come from a trainable fc; training
    must raise the gold path's probability."""
    paddle.topology.reset_name_scope()
    n_cand, k = 8, 3
    feat = layer.data(name="feat", type=paddle.data_type.dense_vector(16))
    sel = layer.data(name="sel",
                     type=paddle.data_type.dense_vector(k))
    gold = layer.data(name="gold", type=paddle.data_type.integer_value(n_cand))
    scores = layer.fc(input=feat, size=n_cand, name="scorer")
    cost = layer.cross_entropy_over_beam(layer.BeamInput(
        candidate_scores=scores, selected_candidates=sel, gold=gold))
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))
    rng = np.random.RandomState(0)
    proj = rng.randn(16, n_cand)

    def reader():
        for _ in range(40):
            batch = []
            for _ in range(16):
                x = rng.randn(16).astype(np.float32)
                g = int(np.argmax(x @ proj))
                s = rng.choice(n_cand, size=k, replace=False).astype(np.float32)
                batch.append((x, s, g))
            yield batch

    costs = []
    sgd.train(reader, num_passes=2,
              event_handler=lambda ev: costs.append(float(ev.cost))
              if isinstance(ev, paddle.event.EndIteration) else None)
    assert np.mean(costs[-5:]) < np.mean(costs[:5]) / 2


# ---------------------------------------------------------------------------
# sequence_tagging (linear CRF) demo
# ---------------------------------------------------------------------------


def _tag_data(rng, n_seqs, vocab, n_tags):
    """Learnable tagging: tag = f(token class, previous token class) — a
    2nd-order pattern a linear CRF with context features can fit."""
    for _ in range(n_seqs):
        length = int(rng.randint(4, 12))
        toks = rng.randint(0, vocab, size=length)
        tags = []
        prev = 0
        for t in toks:
            cls = t % 3
            tags.append((cls + 2 * prev) % n_tags)
            prev = cls
        yield [int(t) for t in toks], [int(t) for t in tags]


def test_crf_viterbi_matches_bruteforce():
    """Every tag path enumerated: viterbi must return the arg-max path
    (caught a backtrack off-by-one that dropped position 0)."""
    from itertools import product

    from paddle_tpu.layer import _crf_viterbi

    rng = np.random.RandomState(3)
    B, T, K = 3, 5, 4
    em = rng.randn(B, T, K).astype(np.float32)
    tr = rng.randn(K, K).astype(np.float32)
    start = rng.randn(K).astype(np.float32)
    stop = rng.randn(K).astype(np.float32)
    mask = np.ones((B, T), bool)
    mask[1, 3:] = False  # one shorter sequence

    got = np.asarray(_crf_viterbi(jnp.asarray(em), jnp.asarray(mask),
                                  jnp.asarray(tr), jnp.asarray(start),
                                  jnp.asarray(stop)))
    for b in range(B):
        length = int(mask[b].sum())
        best, best_s = None, -np.inf
        for path in product(range(K), repeat=length):
            s = start[path[0]] + em[b, 0, path[0]]
            for t in range(1, length):
                s += tr[path[t - 1], path[t]] + em[b, t, path[t]]
            s += stop[path[-1]]
            if s > best_s:
                best, best_s = path, s
        assert tuple(got[b, :length]) == best, \
            f"seq {b}: {tuple(got[b, :length])} != {best}"


def test_sequence_tagging_crf_trains_and_decodes():
    paddle.topology.reset_name_scope()
    vocab, n_tags = 50, 5
    word, label, cost, decoded = sequence_tagging.build(
        vocab_size=vocab, num_tags=n_tags, emb_dim=16, hidden=32)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    # shared params: crf cost and decoding read the same storage
    keys = set(topo.param_specs())
    assert "crf_tag.transitions" in keys and "crf_tag.start" in keys
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=5e-3))

    rng = np.random.RandomState(0)
    data = list(_tag_data(rng, 512, vocab, n_tags))

    def reader():
        for i in range(0, len(data), 32):
            yield data[i:i + 32]

    costs = []
    sgd.train(reader, num_passes=6,
              event_handler=lambda ev: costs.append(float(ev.cost))
              if isinstance(ev, paddle.event.EndIteration) else None)
    assert np.mean(costs[-8:]) < np.mean(costs[:8]) / 3, \
        f"CRF failed to learn: {np.mean(costs[:8])} -> {np.mean(costs[-8:])}"

    # viterbi decode through the SHARED transitions: token accuracy
    test_data = list(_tag_data(rng, 32, vocab, n_tags))
    dec_topo = paddle.topology.Topology([decoded])
    feeder = sgd._make_feeder({"word": 0, "label": 1})
    feeds = feeder.feed(test_data)
    outs, _ = dec_topo.forward(sgd.parameters.as_dict(), sgd.model_state,
                               {"word": feeds["word"]}, train=False)
    sb = outs[0]
    pred = np.asarray(sb.data).reshape(-1)
    mask = np.asarray(sb.valid_mask)
    truth = np.concatenate([np.asarray(t) for _, t in test_data])
    assert mask.sum() == len(truth)
    acc = (pred[mask] == truth).mean()
    assert acc > 0.8, f"viterbi decode accuracy {acc}"
