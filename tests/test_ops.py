"""Op-level parity tests — the paddle/math/tests + function/tests analog.

Strategy mirrors the reference's TensorCheck.h harness: compare framework
kernels against straightforward numpy formulations.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu
from paddle_tpu.ops import math as pmath
from paddle_tpu.ops import conv as pconv
from paddle_tpu.ops import pool as ppool
from paddle_tpu.ops import norm as pnorm
from paddle_tpu.ops import losses, sequence_ops, rnn
from paddle_tpu.ops.embedding import embedding_lookup
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.platform.flags import FLAGS


@pytest.fixture(autouse=True)
def f32_math():
    # exact-parity tests run in f32; bf16 policy is benchmarked separately
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def test_matmul_fc(rng):
    x = rng.randn(4, 8).astype(np.float32)
    w = rng.randn(8, 5).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    np.testing.assert_allclose(pmath.fc(jnp.array(x), jnp.array(w), jnp.array(b)),
                               x @ w + b, rtol=1e-5, atol=1e-5)


def test_conv2d_matches_manual(rng):
    x = rng.randn(2, 5, 5, 3).astype(np.float32)
    w = rng.randn(3, 3, 3, 4).astype(np.float32)
    y = np.asarray(pconv.conv2d(jnp.array(x), jnp.array(w), stride=1, padding=0))
    assert y.shape == (2, 3, 3, 4)
    # manual reference at one output position
    ref = np.sum(x[0, 1:4, 2:5, :, None] * w, axis=(0, 1, 2))
    np.testing.assert_allclose(y[0, 1, 2], ref, rtol=1e-4, atol=1e-4)


def test_conv2d_transpose_shape(rng):
    x = rng.randn(2, 4, 4, 3).astype(np.float32)
    w = rng.randn(3, 3, 3, 5).astype(np.float32)
    y = pconv.conv2d_transpose(jnp.array(x), jnp.array(w), stride=2, padding=1)
    assert y.shape == (2, 7, 7, 5)


def test_depthwise(rng):
    x = rng.randn(1, 6, 6, 4).astype(np.float32)
    w = rng.randn(3, 3, 4, 1).astype(np.float32)
    y = pconv.depthwise_conv2d(jnp.array(x), jnp.array(w), padding=1)
    assert y.shape == (1, 6, 6, 4)
    ref = np.sum(x[0, 0:3, 0:3, 1] * w[:, :, 1, 0])
    np.testing.assert_allclose(y[0, 1, 1, 1], ref, rtol=1e-4, atol=1e-4)


def test_pools(rng):
    x = rng.randn(2, 4, 4, 3).astype(np.float32)
    mx = ppool.max_pool2d(jnp.array(x), 2)
    av = ppool.avg_pool2d(jnp.array(x), 2)
    np.testing.assert_allclose(mx[0, 0, 0], x[0, :2, :2].max((0, 1)), rtol=1e-6)
    np.testing.assert_allclose(av[0, 0, 0], x[0, :2, :2].mean((0, 1)), rtol=1e-5)


def test_maxout_spp(rng):
    x = rng.randn(2, 4, 4, 8).astype(np.float32)
    mo = ppool.maxout(jnp.array(x), 2)
    assert mo.shape == (2, 4, 4, 4)
    spp = ppool.spatial_pyramid_pool(jnp.array(x), 2)
    assert spp.shape == (2, (1 + 4) * 8)


def test_batch_norm_train_and_infer(rng):
    x = rng.randn(16, 5).astype(np.float32)
    g = np.ones(5, np.float32); b = np.zeros(5, np.float32)
    mm = np.zeros(5, np.float32); mv = np.ones(5, np.float32)
    y, nm, nv = pnorm.batch_norm(jnp.array(x), jnp.array(g), jnp.array(b),
                                 jnp.array(mm), jnp.array(mv), train=True)
    np.testing.assert_allclose(np.asarray(y).mean(0), 0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(0), 1, atol=1e-2)
    y2, _, _ = pnorm.batch_norm(jnp.array(x), jnp.array(g), jnp.array(b),
                                jnp.array(mm), jnp.array(mv), train=False)
    np.testing.assert_allclose(np.asarray(y2), x, atol=1e-4)


def test_losses(rng):
    logits = rng.randn(6, 10).astype(np.float32)
    labels = rng.randint(0, 10, 6)
    got = np.asarray(losses.softmax_cross_entropy(jnp.array(logits), jnp.array(labels)))
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(6), labels])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    err = losses.classification_error(jnp.array(logits), jnp.array(labels))
    ref_err = (logits.argmax(-1) != labels).astype(np.float32)
    np.testing.assert_allclose(np.asarray(err), ref_err)


def test_sequence_batch_roundtrip():
    seqs = [np.arange(3 * 2).reshape(3, 2), np.arange(5 * 2).reshape(5, 2) + 10]
    sb = SequenceBatch.from_list(seqs, capacity=10)
    padded, mask = sb.to_padded()
    assert padded.shape[0] == 2
    np.testing.assert_allclose(np.asarray(padded)[0, :3], seqs[0])
    np.testing.assert_allclose(np.asarray(padded)[1, :5], seqs[1])
    assert np.asarray(mask).sum() == 8
    sb2 = SequenceBatch.from_padded(padded, sb.lengths, capacity=10)
    np.testing.assert_allclose(np.asarray(sb2.data)[:8], np.asarray(sb.data)[:8])


def test_seq_pools():
    seqs = [np.array([[1.0, 2], [3, 4]]), np.array([[10.0, 20], [30, 40], [50, 60]])]
    sb = SequenceBatch.from_list(seqs, capacity=8)
    np.testing.assert_allclose(np.asarray(sequence_ops.seq_pool_sum(sb)),
                               [[4, 6], [90, 120]])
    np.testing.assert_allclose(np.asarray(sequence_ops.seq_pool_avg(sb)),
                               [[2, 3], [30, 40]])
    np.testing.assert_allclose(np.asarray(sequence_ops.seq_pool_max(sb)),
                               [[3, 4], [50, 60]])
    np.testing.assert_allclose(np.asarray(sequence_ops.seq_first(sb)),
                               [[1, 2], [10, 20]])
    np.testing.assert_allclose(np.asarray(sequence_ops.seq_last(sb)),
                               [[3, 4], [50, 60]])


def test_sequence_softmax():
    seqs = [np.array([1.0, 2.0]), np.array([1.0, 1.0, 1.0])]
    sb = SequenceBatch.from_list(seqs, capacity=6)
    out = sequence_ops.sequence_softmax(sb)
    d = np.asarray(out.data)
    np.testing.assert_allclose(d[0] + d[1], 1.0, rtol=1e-5)
    np.testing.assert_allclose(d[2:5], [1 / 3] * 3, rtol=1e-5)
    np.testing.assert_allclose(d[5], 0.0, atol=1e-6)


def test_seq_expand():
    per_seq = jnp.array([[1.0], [2.0]])
    long = SequenceBatch.from_list([np.zeros((2, 1)), np.zeros((3, 1))], capacity=6)
    out = sequence_ops.seq_expand(per_seq, long)
    np.testing.assert_allclose(np.asarray(out.data).ravel()[:5], [1, 1, 2, 2, 2])


def test_seq_concat():
    a = SequenceBatch.from_list([np.array([[1.0]]), np.array([[2.0], [3.0]])], capacity=4)
    b = SequenceBatch.from_list([np.array([[4.0], [5.0]]), np.array([[6.0]])], capacity=4)
    out = sequence_ops.seq_concat(a, b)
    padded, mask = out.to_padded()
    p = np.asarray(padded)[..., 0]
    np.testing.assert_allclose(p[0, :3], [1, 4, 5])
    np.testing.assert_allclose(p[1, :3], [2, 3, 6])


def test_lstm_gru_scan_shapes_and_mask(rng):
    B, T, D, H = 2, 5, 3, 4
    x = jnp.array(rng.randn(B, T, D).astype(np.float32))
    mask = jnp.array((np.arange(T)[None, :] < np.array([[3], [5]])).reshape(B, T))
    w_x = jnp.array(rng.randn(D, 4 * H).astype(np.float32) * 0.1)
    w_h = jnp.array(rng.randn(H, 4 * H).astype(np.float32) * 0.1)
    b = jnp.zeros(4 * H)
    hs, final = rnn.lstm_scan(x, mask, w_x, w_h, b)
    assert hs.shape == (B, T, H)
    # masked steps must not change state: h at t=3,4 for seq 0 equals h at t=2
    np.testing.assert_allclose(np.asarray(hs)[0, 3], np.asarray(hs)[0, 2])
    np.testing.assert_allclose(np.asarray(final.h)[0], np.asarray(hs)[0, 2])

    w_x3 = jnp.array(rng.randn(D, 3 * H).astype(np.float32) * 0.1)
    w_h3 = jnp.array(rng.randn(H, 3 * H).astype(np.float32) * 0.1)
    hs_g, fin_g = rnn.gru_scan(x, mask, w_x3, w_h3, jnp.zeros(3 * H))
    assert hs_g.shape == (B, T, H)
    np.testing.assert_allclose(np.asarray(hs_g)[0, 4], np.asarray(hs_g)[0, 2])


def test_embedding(rng):
    table = jnp.array(rng.randn(10, 4).astype(np.float32))
    ids = jnp.array([[1, 2], [3, 0]])
    out = embedding_lookup(table, ids)
    assert out.shape == (2, 2, 4)
    np.testing.assert_allclose(np.asarray(out)[0, 1], np.asarray(table)[2])
