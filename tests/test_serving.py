"""paddle_tpu.serving tests: paged decode attention vs the mha_reference
oracle (ragged lengths, page-boundary crossings), scheduler invariants
(no page leaks, admission control, preemption), end-to-end greedy parity
of the ServingEngine against the non-paged oracle AND against
``beam_search`` with ``beam_size=1``, plus the Inference.infer
tail-padding satellites.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.attr import ParamAttr
from paddle_tpu.generation import GeneratedInput, beam_search
from paddle_tpu.ops.attention import mha_reference
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (DecoderLM, PagePool, PagedKVConfig, Request,
                                SchedulerConfig, ServingEngine,
                                append_token, bucket_for,
                                ContinuousBatchingScheduler, gather_kv,
                                greedy_decode_reference, init_kv_pages,
                                paged_decode_attention,
                                paged_decode_attention_reference)
from paddle_tpu.serving.decode_attention import _paged_decode_pallas
from paddle_tpu.topology import LayerOutput, ParamSpec

from conftest import assert_serving_drained as assert_drained  # noqa: E402

serving = pytest.mark.serving


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


# ---------------------------------------------------------------------------
# paged decode attention vs oracle
# ---------------------------------------------------------------------------


def _scatter_into_pages(rng, lens, page, pm, num_pages, h, d):
    """Build contiguous ground-truth K/V and scatter them into a shuffled
    page pool; returns (q, k_contig, v_contig, k_pages, v_pages, table)."""
    b = len(lens)
    kc = rng.randn(b, pm * page, h, d).astype(np.float32)
    vc = rng.randn(b, pm * page, h, d).astype(np.float32)
    k_pages = rng.randn(num_pages, page, h, d).astype(np.float32)  # garbage
    v_pages = rng.randn(num_pages, page, h, d).astype(np.float32)
    table = np.zeros((b, pm), np.int32)
    free = list(range(1, num_pages))
    rng.shuffle(free)
    for i, n in enumerate(lens):
        for j in range(-(-int(n) // page)):
            pg = free.pop()
            table[i, j] = pg
            k_pages[pg] = kc[i, j * page:(j + 1) * page]
            v_pages[pg] = vc[i, j * page:(j + 1) * page]
    q = rng.randn(b, h, d).astype(np.float32)
    return q, kc, vc, k_pages, v_pages, table


@serving
@pytest.mark.parametrize("lens", [
    (1, 8, 27),      # sub-page, exact page boundary, mid-page crossing
    (32, 3, 16),     # full table, tiny, exact two pages
])
def test_paged_decode_attention_matches_oracle(rng, lens):
    page, pm, num_pages, h, d = 8, 4, 16, 2, 16
    lens = np.asarray(lens, np.int32)
    q, kc, vc, kp, vp, table = _scatter_into_pages(
        rng, lens, page, pm, num_pages, h, d)

    # oracle: contiguous layout + mha_reference with length masking
    pos = np.arange(pm * page)[None]
    kv_seg = jnp.asarray((pos >= lens[:, None]).astype(np.int32))
    q_seg = jnp.zeros((len(lens), 1), jnp.int32)
    want = np.asarray(mha_reference(
        jnp.asarray(q)[:, None], jnp.asarray(kc), jnp.asarray(vc),
        segment_ids=q_seg, kv_segment_ids=kv_seg)[:, 0])

    ref = np.asarray(paged_decode_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(lens)))
    np.testing.assert_allclose(ref, want, rtol=1e-5, atol=1e-5)

    # pallas kernel, interpret mode (the ragged page-table path)
    ker = np.asarray(_paged_decode_pallas(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(lens), float(d) ** -0.5, True))
    np.testing.assert_allclose(ker, want, rtol=1e-5, atol=1e-5)

    # public entry, kernel forced
    pub = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(lens), use_kernel=True))
    np.testing.assert_allclose(pub, want, rtol=1e-5, atol=1e-5)


@serving
def test_append_token_and_gather_roundtrip(rng):
    cfg = PagedKVConfig(num_layers=2, num_heads=2, head_dim=4, page_size=4,
                        num_pages=6, max_pages_per_seq=3)
    kv = init_kv_pages(cfg)
    table = np.array([[1, 2, 3], [4, 5, 0]], np.int32)
    toks = rng.randn(2, 2, 10, 2, 4).astype(np.float32)  # [kv, B, T, H, D]
    for t in range(10):
        # seq 0 appends all 10 tokens; seq 1 stops at 7 (null page after)
        page_ids = np.array([table[0, t // 4],
                             table[1, t // 4] if t < 7 else 0], np.int32)
        kv = append_token(kv, 1, jnp.asarray(toks[0, :, t]),
                          jnp.asarray(toks[1, :, t]), jnp.asarray(page_ids),
                          jnp.asarray([t % 4, t % 4], np.int32))
    k, v = gather_kv(kv, 1, jnp.asarray(table))
    np.testing.assert_allclose(np.asarray(k)[0, :10], toks[0, 0], atol=0)
    np.testing.assert_allclose(np.asarray(v)[0, :10], toks[1, 0], atol=0)
    np.testing.assert_allclose(np.asarray(k)[1, :7], toks[0, 1, :7], atol=0)
    # layer 0 untouched
    assert float(jnp.abs(kv.k[0]).max()) == 0.0


# ---------------------------------------------------------------------------
# pool + scheduler invariants
# ---------------------------------------------------------------------------


@serving
def test_page_pool_all_or_nothing_and_null_page():
    pool = PagePool(6)
    assert pool.num_usable == 5
    got = pool.alloc(5)
    assert got is not None and 0 not in got and len(set(got)) == 5
    assert pool.alloc(1) is None          # empty: refuse
    assert pool.num_free == 0
    pool.free(got[:2])
    assert pool.alloc(3) is None          # all-or-nothing: 2 < 3
    assert pool.num_free == 2             # refusal didn't consume
    pool.free(got[2:])
    assert pool.num_free == 5


@serving
def test_bucket_ladder():
    assert bucket_for(3, (4, 8, 16), 64) == 4
    assert bucket_for(8, (4, 8, 16), 64) == 8
    assert bucket_for(9, (4, 8, 16), 64) == 16
    assert bucket_for(17, (4, 8, 16), 64) == 32   # rounds up by top bucket
    assert bucket_for(60, (4, 8, 16), 64) == 64   # capped at max_seq_len


@serving
def test_scheduler_admission_refuses_when_pool_full():
    pool = PagePool(5)  # 4 usable pages
    sched = ContinuousBatchingScheduler(
        pool, SchedulerConfig(max_slots=4, page_size=4, max_pages_per_seq=4,
                              max_queue=2))
    # 7 prompt tokens + the 1-token decode margin = 8 -> 2 pages each
    a = Request(prompt=list(range(7)), max_tokens=4)
    b = Request(prompt=list(range(7)), max_tokens=4)
    c = Request(prompt=list(range(7)), max_tokens=4)
    assert sched.submit(a, now=0.0) and sched.submit(b, now=1.0)
    admitted = sched.admit()
    assert [r.rid for r in admitted] == [a.rid, b.rid]
    assert pool.num_free == 0
    # pool exhausted: c queues but is NOT admitted
    assert sched.submit(c, now=2.0)
    assert sched.admit() == []
    assert c.status == "queued" and sched.queue_depth == 1
    # backpressure: queue is at max_queue=2 after d... submit d, e
    d = Request(prompt=[1, 2], max_tokens=2)
    assert sched.submit(d, now=3.0)
    e = Request(prompt=[1, 2], max_tokens=2)
    assert not sched.submit(e, now=4.0)   # queue full -> rejected
    assert e.status == "rejected"
    # infeasible requests are rejected outright, not queued
    f = Request(prompt=list(range(15)), max_tokens=4)  # 19 > 16 max_seq
    assert not sched.submit(f, now=5.0)
    # completion returns pages; c then fits
    sched.release(a)
    assert pool.num_free == 2
    assert [r.rid for r in sched.admit()] == [c.rid]


# ---------------------------------------------------------------------------
# end-to-end engine
# ---------------------------------------------------------------------------


def _small_model(seed=0, **kw):
    kw.setdefault("vocab_size", 50)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 2)
    kw.setdefault("head_dim", 8)
    kw.setdefault("max_positions", 128)
    model = DecoderLM(**kw)
    return model, model.init_params(jax.random.PRNGKey(seed))


@serving
def test_engine_parity_vs_nonpaged_oracle(rng):
    model, params = _small_model()
    eng = ServingEngine(model, params, eos_id=1, page_size=4, num_pages=40,
                        max_pages_per_seq=10, max_slots=4, buckets=(4, 8, 16))
    prompts = [rng.randint(2, 50, size=n).tolist()
               for n in (3, 4, 7, 11, 5, 2)]   # ragged; > max_slots
    rids = [eng.submit(p, max_tokens=10) for p in prompts]
    assert all(r is not None for r in rids)
    streamed = {}
    # exercise the streaming callback on one request
    rids[0] = eng.submit(prompts[0], max_tokens=10,
                         on_token=lambda t: streamed.setdefault("toks", []).append(t))
    res = eng.run(max_ticks=300)
    for p, rid in zip(prompts, rids):
        assert res[rid] == greedy_decode_reference(model, params, p, 10, 1)
    assert streamed["toks"] == res[rids[0]]
    # invariant: every page back (free or cached-reclaimable), no refs
    assert_drained(eng)
    snap = eng.metrics.snapshot()
    assert snap["requests_completed"] == len(prompts) + 1
    assert snap["tokens_generated"] >= len(prompts) + 1
    assert snap["page_occupancy"] == 0.0 and snap["page_occupancy_peak"] > 0


@serving
def test_engine_parity_with_pallas_kernel(rng):
    model, params = _small_model(num_layers=1)
    eng = ServingEngine(model, params, eos_id=1, page_size=8, num_pages=16,
                        max_pages_per_seq=4, max_slots=2, buckets=(4, 8),
                        use_kernel=True)   # force the kernel (interpret on CPU)
    prompts = [rng.randint(2, 50, size=n).tolist() for n in (3, 9)]
    rids = [eng.submit(p, max_tokens=6) for p in prompts]
    res = eng.run(max_ticks=100)
    for p, rid in zip(prompts, rids):
        assert res[rid] == greedy_decode_reference(model, params, p, 6, 1)


@serving
def test_engine_preemption_recovers_and_frees_pages(rng):
    model, params = _small_model(num_layers=1)
    # 7 usable pages of 4 tokens; 3 concurrent requests growing to
    # ceil((4+12)/4)=4 pages each -> growth must preempt
    eng = ServingEngine(model, params, eos_id=1, page_size=4, num_pages=8,
                        max_pages_per_seq=4, max_slots=3, buckets=(4, 8))
    prompts = [rng.randint(2, 50, size=4).tolist() for _ in range(3)]
    rids = [eng.submit(p, max_tokens=12) for p in prompts]
    res = eng.run(max_ticks=500)
    for p, rid in zip(prompts, rids):
        assert res[rid] == greedy_decode_reference(model, params, p, 12, 1)
    assert eng.metrics.preemptions > 0          # the pool actually thrashed
    assert_drained(eng)                         # nothing leaked


# ---------------------------------------------------------------------------
# greedy parity vs beam_search(beam_size=1)
# ---------------------------------------------------------------------------

V_B, H_B, D_B, T_B = 13, 2, 4, 6
E_B = H_B * D_B
BOS, EOS = 0, 1


class _OneLayerAttnLM:
    """Single attention layer, no positions, no residual/FFN: the exact
    math the beam-search cell below implements, as a DecodeModel."""

    num_layers, num_heads, head_dim, vocab_size = 1, H_B, D_B, V_B

    def embed(self, params, tokens, positions):
        return params["srv_emb"][tokens]

    def qkv(self, params, layer, x):
        shape = x.shape[:-1] + (H_B, D_B)
        return ((x @ params["srv_wq"]).reshape(shape),
                (x @ params["srv_wk"]).reshape(shape),
                (x @ params["srv_wv"]).reshape(shape))

    def attn_out(self, params, layer, ctx, x):
        return ctx.reshape(x.shape[:-1] + (E_B,))

    def logits(self, params, x):
        return x @ params["srv_wout"]


def _attn_beam_cell(token_emb, mem):
    """beam_search step layer: the memory carries the cell's whole output
    [probs | position | flattened K cache | flattened V cache] so
    single-layer causal attention decode is expressible as a dense
    recurrent memory — the in-graph twin of the serving engine's paged
    cache.  The memory links to the cell itself (so it sits on the
    probability layer's path) and the cell ignores the probs slice."""

    def cell_fn(ctx, p, ins):
        emb, m = ins
        n = emb.shape[0]
        pos = m[:, V_B].astype(jnp.int32)
        kv = m[:, V_B + 1:].reshape(n, 2, T_B, H_B, D_B)
        q = (emb @ p["wq"]).reshape(n, H_B, D_B)
        k = (emb @ p["wk"]).reshape(n, H_B, D_B)
        v = (emb @ p["wv"]).reshape(n, H_B, D_B)
        onehot = (jnp.arange(T_B)[None, :] == pos[:, None])
        kv = kv.at[:, 0].set(jnp.where(onehot[:, :, None, None],
                                       k[:, None], kv[:, 0]))
        kv = kv.at[:, 1].set(jnp.where(onehot[:, :, None, None],
                                       v[:, None], kv[:, 1]))
        s = jnp.einsum("nhd,nthd->nht", q, kv[:, 0]) * D_B ** -0.5
        live = jnp.arange(T_B)[None, None, :] <= pos[:, None, None]
        s = jnp.where(live, s, -1e30)
        attn = jax.nn.softmax(s, axis=-1)
        ctx_v = jnp.einsum("nht,nthd->nhd", attn, kv[:, 1])
        probs = jax.nn.softmax(ctx_v.reshape(n, E_B) @ p["wout"], axis=-1)
        return jnp.concatenate(
            [probs, (pos + 1)[:, None].astype(jnp.float32),
             kv.reshape(n, -1)], axis=1)

    cell = LayerOutput(
        name="srv_attn_cell",
        layer_type="serving_cell", inputs=[token_emb, mem], fn=cell_fn,
        params={
            "wq": ParamSpec((E_B, E_B), ParamAttr(name="srv_wq")),
            "wk": ParamSpec((E_B, E_B), ParamAttr(name="srv_wk")),
            "wv": ParamSpec((E_B, E_B), ParamAttr(name="srv_wv")),
            "wout": ParamSpec((E_B, V_B), ParamAttr(name="srv_wout")),
        },
        size=V_B + 1 + 2 * T_B * E_B)
    probs = layer.mixed(input=[layer.identity_projection(cell, offset=0,
                                                         size=V_B)],
                        size=V_B, name="srv_probs")
    return probs


@serving
def test_engine_greedy_matches_beam_size_1():
    paddle.topology.reset_name_scope()
    start = layer.data(name="start", type=paddle.data_type.dense_vector(E_B))

    def step(token_emb, _static_start):
        mem = layer.memory(name="srv_attn_cell",
                           size=V_B + 1 + 2 * T_B * E_B)
        return _attn_beam_cell(token_emb, mem)

    beam = beam_search(
        step=step,
        input=[GeneratedInput(size=V_B, embedding_name="srv_emb",
                              embedding_size=E_B),
               layer.StaticInput(start)],
        bos_id=BOS, eos_id=EOS, beam_size=1, max_length=T_B, name="srv_gen")
    topo = paddle.topology.Topology([beam])
    params = paddle.Parameters.from_topology(topo, seed=7)

    outs, _ = topo.forward(params.as_dict(), topo.init_state(),
                           {"start": jnp.zeros((1, E_B), jnp.float32)})
    tokens, lengths, _scores = (np.asarray(o) for o in outs[0])
    beam_tokens = tokens[0, 0, :int(lengths[0, 0])].tolist()

    # the serving engine decodes the same weights from prompt [BOS]
    model = _OneLayerAttnLM()
    eng = ServingEngine(model, params.as_dict(), eos_id=EOS, page_size=2,
                        num_pages=8, max_pages_per_seq=4, max_slots=2,
                        buckets=(2, 4))
    rid = eng.submit([BOS], max_tokens=T_B)
    res = eng.run(max_ticks=50)
    assert res[rid] == beam_tokens
    # and both match the non-paged oracle
    assert res[rid] == greedy_decode_reference(model, params.as_dict(),
                                               [BOS], T_B, EOS)


# ---------------------------------------------------------------------------
# Inference.infer tail padding + model_state forwarding (satellites)
# ---------------------------------------------------------------------------


def test_infer_pads_partial_tail_batch(rng):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = layer.fc(input=x, size=3, act="softmax", name="y")
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([y]), seed=0)
    data = [(rng.randn(4).astype(np.float32),) for _ in range(11)]
    inf = paddle.Inference(y, params)
    out = inf.infer(data, batch_size=4)       # 4+4+3: tail padded to 4
    assert out.shape == (11, 3)
    ref = inf.infer(data[:4], batch_size=4)   # full batch, no padding
    np.testing.assert_allclose(out[:4], ref, rtol=1e-6)
    # single short batch pads to a power of two and still slices back
    out3 = inf.infer(data[:3], batch_size=256)
    assert out3.shape == (3, 3)
    np.testing.assert_allclose(out3, out[:3], rtol=1e-6)


def test_module_infer_forwards_model_state(rng):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    h = layer.fc(input=x, size=4, act="relu", name="h")
    hb = layer.batch_norm(input=h, name="hb")
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([hb]), seed=0)
    data = [(rng.randn(4).astype(np.float32),) for _ in range(3)]
    # fake trained moving stats: shift the mean, make variance tiny
    state = paddle.topology.Topology([hb]).init_state()
    assert "hb" in state
    state = {"hb": {k: v + 0.5 for k, v in state["hb"].items()}}
    base = paddle.infer(output_layer=hb, parameters=params, input=data)
    shifted = paddle.infer(output_layer=hb, parameters=params, input=data,
                           model_state=state)
    assert not np.allclose(base, shifted)
