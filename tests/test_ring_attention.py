"""Ring / Ulysses attention on the virtual 8-device CPU mesh vs the oracle.

The in-process multi-device strategy mirrors the reference's
test_ParameterServer2.cpp (servers + clients in one process).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import attention
from paddle_tpu.parallel import mesh as pmesh
from paddle_tpu.parallel.ring import ring_attention, ulysses_attention


def _mk(rng, b, s, h, d):
    return (jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)),
            jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)),
            jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)))


def _seg(rng, b, s, n):
    out = np.zeros((b, s), np.int32)
    for i in range(b):
        cuts = np.sort(rng.choice(np.arange(1, s), n - 1, replace=False))
        prev, sid = 0, 0
        for c in list(cuts) + [s]:
            out[i, prev:c] = sid
            sid += 1
            prev = c
    return jnp.asarray(out)


@pytest.fixture
def seq_mesh():
    return pmesh.make_mesh((4,), ("seq",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches(rng, seq_mesh, causal):
    q, k, v = _mk(rng, 2, 64, 4, 16)
    seg = _seg(rng, 2, 64, 3)
    out = ring_attention(q, k, v, seq_mesh, segment_ids=seg, causal=causal)
    ref = attention.mha_reference(q, k, v, segment_ids=seg, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_grads(rng, seq_mesh):
    q, k, v = _mk(rng, 1, 32, 2, 8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention.mha_reference(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches(rng, seq_mesh, causal):
    q, k, v = _mk(rng, 2, 64, 4, 16)
    seg = _seg(rng, 2, 64, 3)
    out = ulysses_attention(q, k, v, seq_mesh, segment_ids=seg, causal=causal,
                            block_q=16, block_k=16)
    ref = attention.mha_reference(q, k, v, segment_ids=seg, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
