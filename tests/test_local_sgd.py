"""Local-SGD (async analog) tests on the 8-device CPU mesh.

Reference analog: the async_sgd algorithm knob + staleness control
(TrainerConfig.proto:23,132-134; ParameterServer2::asyncSGD).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.local_sgd import LocalSGD


def quad_grad_fn(true_w):
    def f(params, feeds):
        x, y = feeds["x"], feeds["y"]
        pred = x @ params["w"]
        loss = jnp.mean(jnp.square(pred - y))
        grads = jax.grad(
            lambda p: jnp.mean(jnp.square(x @ p["w"] - y)))(params)
        return loss, grads
    return f


@pytest.fixture
def mesh():
    return make_mesh((8,), ("data",))


def test_sync_period_one_matches_synchronous(mesh, rng):
    """sync_period=1 must equal plain synchronous DP-SGD bit-for-bit-ish."""
    D = 4
    true_w = rng.randn(D, 1).astype(np.float32)
    w0 = np.zeros((D, 1), np.float32)
    steps = []
    for _ in range(6):
        x = rng.randn(32, D).astype(np.float32)
        steps.append((x, x @ true_w))

    # baseline: single-device synchronous SGD
    w = jnp.asarray(w0)
    lr = 0.1
    for x, y in steps:
        g = jax.grad(lambda p: jnp.mean(jnp.square(x @ p - y)))(w)
        w = w - lr * g

    # local SGD with per-step sync: per-worker grads are over 1/8 of the
    # batch; pmean at sync reproduces... the AVERAGE of locally-updated
    # replicas, equal to w - lr * mean_k(grad_k). mean of shard grads ==
    # full-batch grad for a mean loss, so trajectories match.
    ls = LocalSGD(mesh, sync_period=1, learning_rate=lr)
    stacked = ls.replicate({"w": jnp.asarray(w0)})
    step_fn = ls.make_step(quad_grad_fn(true_w))
    for i, (x, y) in enumerate(steps):
        stacked, loss = step_fn(stacked, jnp.asarray(i),
                                {"x": jnp.asarray(x), "y": jnp.asarray(y)})
    got = np.asarray(ls.average(stacked)["w"])
    np.testing.assert_allclose(got, np.asarray(w), atol=1e-5, rtol=1e-5)


def test_local_sgd_converges_with_period(mesh, rng):
    D = 4
    true_w = rng.randn(D, 1).astype(np.float32)
    ls = LocalSGD(mesh, sync_period=4, learning_rate=0.1)
    stacked = ls.replicate({"w": jnp.zeros((D, 1), jnp.float32)})
    step_fn = ls.make_step(quad_grad_fn(true_w))
    losses = []
    for i in range(40):
        x = rng.randn(64, D).astype(np.float32)
        stacked, loss = step_fn(stacked, jnp.asarray(i),
                                {"x": jnp.asarray(x),
                                 "y": jnp.asarray(x @ true_w)})
        losses.append(float(loss))
    assert losses[-1] < 1e-2 * losses[0]
    # replicas are in sync right after a sync step (i=39 -> (39+1)%4==0)
    w_all = np.asarray(stacked["w"])
    for k in range(1, 8):
        np.testing.assert_allclose(w_all[k], w_all[0], atol=1e-6)


def test_lagged_grad_discard(mesh, rng):
    """A shard with an outlier-gradient batch is rejected by the discard
    ratio: its poisoned batch must not move the average."""
    D = 2

    def grad_fn(params, feeds):
        x, y = feeds["x"], feeds["y"]
        loss = jnp.mean(jnp.square(x @ params["w"] - y))
        g = jax.grad(lambda p: jnp.mean(jnp.square(x @ p["w"] - y)))(params)
        return loss, g

    x = rng.randn(64, D).astype(np.float32)
    y = np.zeros((64, 1), np.float32)
    # poison shard 3's slice with a huge-magnitude batch
    x_bad = x.copy()
    x_bad[24:32] *= 1000.0

    def run(ratio, xs):
        ls = LocalSGD(mesh, sync_period=1, learning_rate=0.01,
                      lagged_grad_discard_ratio=ratio)
        stacked = ls.replicate({"w": jnp.ones((D, 1), jnp.float32)})
        fn = ls.make_step(grad_fn)
        stacked, _ = fn(stacked, jnp.asarray(0),
                        {"x": jnp.asarray(xs), "y": jnp.asarray(y)})
        return np.asarray(ls.average(stacked)["w"])

    w_clean = run(0.0, x)
    w_poisoned = run(0.0, x_bad)
    w_guarded = run(3.0, x_bad)
    # without the guard the poisoned batch blows up the step
    assert np.abs(w_poisoned).max() > 10 * np.abs(w_clean).max()
    assert np.abs(w_guarded - w_clean).max() < np.abs(
        w_poisoned - w_clean).max() * 0.01
