"""The testLayerGrad sweep: numeric-vs-analytic gradients across the
layer registry.

Reference analog: paddle/gserver/tests/test_LayerGrad.cpp (2.4k lines,
every layer type gradient-checked by perturbation, LayerGradUtil.h:298).
Here jax.grad supplies the analytic side; central differences on a few
sampled coordinates of every parameter and input supply the numeric side.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import Topology

RNG = np.random.RandomState(11)


@pytest.fixture(autouse=True)
def f32_math():
    # numeric-vs-analytic comparison needs f32 kernels; the bf16 MXU
    # policy is benchmarked separately (test_ops.py does the same)
    from paddle_tpu.platform.flags import FLAGS
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def check_layer_grad(out_node, feeds, check_inputs=(), delta=1e-3,
                     rtol=4e-2, atol=4e-3, seed=5, coords=8):
    """Mean-of-output loss; numeric grad on sampled coords of every param
    (and named float inputs) vs jax.grad.

    ``coords`` per tensor (reference perturbs systematically,
    LayerGradUtil.h:203; 8 spread coords is the fast CI gate)."""
    topo = Topology([out_node])
    params = paddle.Parameters.from_topology(topo, seed=seed)
    state = topo.init_state()
    pdict = {k: np.asarray(v, np.float32) for k, v in
             params.as_dict().items()}

    def loss_fn(p, f):
        outs, _ = topo.forward(p, state, f, train=False)
        o = outs[0]
        d = o.data if isinstance(o, SequenceBatch) else o
        return jnp.mean(d)

    loss = jax.jit(loss_fn)
    ana_p = jax.grad(lambda p: loss(p, feeds))(pdict)

    def sample_coords(arr, k=None):
        flat = arr.size
        k = coords if k is None else k
        return np.unique(np.linspace(0, flat - 1, min(k, flat)).astype(int))

    for name, val in pdict.items():
        for i in sample_coords(val):
            up = {k: v.copy() for k, v in pdict.items()}
            up[name].ravel()[i] += delta
            down = {k: v.copy() for k, v in pdict.items()}
            down[name].ravel()[i] -= delta
            num = (float(loss(up, feeds)) - float(loss(down, feeds))) \
                / (2 * delta)
            ana = float(np.asarray(ana_p[name]).ravel()[i])
            assert abs(num - ana) <= atol + rtol * abs(num), \
                (out_node.layer_type, name, i, num, ana)

    for fname in check_inputs:
        base = np.asarray(feeds[fname], np.float32)
        ana_f = jax.grad(
            lambda x: loss(pdict, {**feeds, fname: x}))(jnp.asarray(base))
        for i in sample_coords(base):
            up = base.copy()
            up.ravel()[i] += delta
            down = base.copy()
            down.ravel()[i] -= delta
            num = (float(loss(pdict, {**feeds, fname: up}))
                   - float(loss(pdict, {**feeds, fname: down}))) / (2 * delta)
            ana = float(np.asarray(ana_f).ravel()[i])
            assert abs(num - ana) <= atol + rtol * abs(num), \
                (out_node.layer_type, fname, i, num, ana)


def dense(name, dim, n=4):
    v = layer.data(name=name, type=paddle.data_type.dense_vector(dim))
    feed = RNG.randn(n, dim).astype(np.float32)
    return v, feed


def make_seq(name, dim, lengths):
    v = layer.data(name=name,
                   type=paddle.data_type.dense_vector_sequence(dim))
    total = sum(lengths)
    seg = np.concatenate([np.full(L, i, np.int32)
                          for i, L in enumerate(lengths)])
    sb = SequenceBatch(
        jnp.asarray(RNG.randn(total, dim).astype(np.float32)),
        jnp.asarray(seg),
        jnp.asarray(np.asarray(lengths, np.int32)),
        max_len=max(lengths))
    return v, sb


def test_grad_fc_family():
    paddle.topology.reset_name_scope()
    x, fx = dense("x", 6)
    check_layer_grad(layer.fc(x, size=5, act="tanh"), {"x": fx},
                     check_inputs=["x"])

    paddle.topology.reset_name_scope()
    x, fx = dense("x", 6)
    check_layer_grad(layer.selective_fc(x, size=5), {"x": fx})


def test_grad_mixed_projections():
    paddle.topology.reset_name_scope()
    x, fx = dense("x", 6)
    y, fy = dense("y", 4)
    out = layer.mixed(size=5, input=[
        layer.full_matrix_projection(x, size=5),
        layer.full_matrix_projection(y, size=5)], act="sigmoid")
    check_layer_grad(out, {"x": fx, "y": fy}, check_inputs=["x", "y"])

    paddle.topology.reset_name_scope()
    x, fx = dense("x", 6)
    out = layer.mixed(size=6, input=[layer.dotmul_projection(x),
                                     layer.scaling_projection(x)])
    check_layer_grad(out, {"x": fx})


def test_grad_conv_pool_norm():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(6 * 6 * 2),
                   height=6, width=6)
    fx = RNG.randn(3, 72).astype(np.float32)
    c = layer.img_conv(input=x, filter_size=3, num_filters=3,
                       num_channels=2, padding=1, act="relu")
    p = layer.img_pool(c, pool_size=2)
    check_layer_grad(p, {"x": fx}, delta=5e-3)

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4 * 4 * 2),
                   height=4, width=4)
    fx = RNG.randn(3, 32).astype(np.float32)
    bn = layer.batch_norm(layer.img_conv(
        input=x, filter_size=3, num_filters=2, num_channels=2, padding=1))
    check_layer_grad(bn, {"x": fx}, delta=5e-3, rtol=8e-2)

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4 * 4 * 2),
                   height=4, width=4)
    fx = RNG.randn(2, 32).astype(np.float32)
    check_layer_grad(layer.img_cmrnorm(x, size=3), {"x": fx},
                     check_inputs=["x"])


def test_grad_recurrent_layers():
    paddle.topology.reset_name_scope()
    s, fs = make_seq("s", 4, [3, 2])
    check_layer_grad(layer.lstmemory(layer.fc(s, size=4 * 4)),
                     {"s": fs}, delta=5e-3, rtol=8e-2)

    paddle.topology.reset_name_scope()
    s, fs = make_seq("s", 4, [3, 2])
    check_layer_grad(layer.grumemory(layer.fc(s, size=4 * 3)),
                     {"s": fs}, delta=5e-3, rtol=8e-2)

    paddle.topology.reset_name_scope()
    s, fs = make_seq("s", 4, [4, 2])
    check_layer_grad(layer.recurrent(s), {"s": fs}, delta=5e-3)


def test_grad_sequence_layers():
    for make in [lambda s: layer.pooling(s),
                 lambda s: layer.first_seq(s),
                 lambda s: layer.last_seq(s),
                 lambda s: layer.expand(layer.pooling(s), s)]:
        paddle.topology.reset_name_scope()
        s, fs = make_seq("s", 3, [3, 2])
        check_layer_grad(make(s), {"s": fs})


def test_grad_cost_layers():
    paddle.topology.reset_name_scope()
    x, fx = dense("x", 5)
    lab = layer.data(name="lab", type=paddle.data_type.integer_value(5))
    flab = RNG.randint(0, 5, (4,)).astype(np.int32)
    out = layer.classification_cost(input=layer.fc(x, size=5), label=lab)
    check_layer_grad(out, {"x": fx, "lab": flab}, check_inputs=["x"])

    paddle.topology.reset_name_scope()
    x, fx = dense("x", 5)
    t, ft = dense("t", 5)
    check_layer_grad(layer.square_error_cost(input=x, label=t),
                     {"x": fx, "t": ft}, check_inputs=["x"])

    paddle.topology.reset_name_scope()
    x, fx = dense("x", 1)
    t, _ = dense("t", 1)
    ft = (RNG.rand(4, 1) > 0.5).astype(np.float32)
    check_layer_grad(layer.huber_regression_cost(input=x, label=t),
                     {"x": fx, "t": ft}, check_inputs=["x"])


def test_grad_misc_new_layers():
    paddle.topology.reset_name_scope()
    x, fx = dense("x", 8)
    check_layer_grad(layer.prelu(x, partial_sum=2), {"x": fx},
                     check_inputs=["x"])

    paddle.topology.reset_name_scope()
    a, fa = dense("a", 3)
    b, fb = dense("b", 4)
    check_layer_grad(layer.tensor(a, b, size=3), {"a": fa, "b": fb},
                     check_inputs=["a", "b"])

    paddle.topology.reset_name_scope()
    s, fs = make_seq("s", 3, [3, 2])
    check_layer_grad(layer.row_conv(s, context_len=2), {"s": fs})

    paddle.topology.reset_name_scope()
    x, fx = dense("x", 4)
    check_layer_grad(layer.scale_shift(x), {"x": fx}, check_inputs=["x"])


def test_grad_crf():
    paddle.topology.reset_name_scope()
    s, fs = make_seq("s", 3, [3, 2])
    lab = layer.data(name="lab",
                     type=paddle.data_type.integer_value_sequence(3))
    total = 5
    flab = SequenceBatch(
        jnp.asarray(RNG.randint(0, 3, (total,)).astype(np.int32)),
        fs.segment_ids, fs.lengths, max_len=fs.max_len)
    feat = layer.fc(s, size=3)
    out = layer.crf(input=feat, label=lab, size=3)
    check_layer_grad(out, {"s": fs, "lab": flab}, delta=5e-3, rtol=8e-2)
