"""Elastic membership + kill-a-trainer failure injection.

Reference analogs: go/pserver/etcd_client.go:67-166 (Register under a TTL
lease, idx-slot transaction), go/master/service.go:313-448 (timeout
requeue), and the fault-tolerance design docs' kill/recover story. The
reference tests these with in-process servers
(paddle/pserver/test/test_ParameterServer2.cpp:554-560); we do the same
with an injectable clock.
"""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import checkpoint as ckpt
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.master.client import MasterClient
from paddle_tpu.master.recordio import recordio_write
from paddle_tpu.master.service import Service


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# membership protocol
# ---------------------------------------------------------------------------


def test_register_assigns_smallest_free_slot():
    clk = Clock()
    svc = Service(time_fn=clk)
    (a, tok_a), (b, _), (c, tok_c) = (svc.register(), svc.register(),
                                      svc.register())
    assert (a, b, c) == (0, 1, 2)
    # b dies -> slot 1 frees after lease; next register reclaims it
    clk.t += 1.0
    assert svc.heartbeat(0, tok_a, ttl_s=1e6)
    assert svc.heartbeat(2, tok_c, ttl_s=1e6)
    clk.t += svc.lease_ttl_s  # b's lease lapses (0/2 renewed long)
    assert svc.heartbeat(0, tok_a, ttl_s=1e6)
    assert svc.heartbeat(2, tok_c, ttl_s=1e6)
    assert svc.members() == [0, 2]
    slot, token = svc.register()
    assert slot == 1
    assert not svc.heartbeat(5, "bogus"), "unknown slot must not heartbeat"
    # a stale token on a live slot must also be rejected
    assert not svc.heartbeat(1, "stale-token")
    assert svc.heartbeat(1, token)


def test_dead_trainer_tasks_requeue_to_front(tmp_path):
    clk = Clock()
    svc = Service(chunks_per_task=2, timeout_s=1e6, time_fn=clk)
    p = str(tmp_path / "data")
    recordio_write(p, [f"r{i}".encode() for i in range(8)])  # 4 tasks
    svc.set_dataset([p])

    dead, _ = svc.register(ttl_s=10.0)
    live, _ = svc.register(ttl_s=1e6)
    t0 = svc.get_task(owner=dead)       # dead trainer holds task 0
    t1 = svc.get_task(owner=live)
    assert t0.id == 0 and t1.id == 1

    clk.t += 11.0                        # dead's lease lapses
    nxt = svc.get_task(owner=live)       # requeued task 0 comes FIRST
    assert nxt.id == 0, "dead trainer's task must be redelivered first"
    assert svc.members() == [live]
    # the task timeout itself did NOT fire (timeout_s huge): this was
    # lease-driven requeue, the faster path
    assert t1.id in svc._pending


# ---------------------------------------------------------------------------
# end-to-end: kill a trainer mid-pass, resume from checkpoint, converge
# ---------------------------------------------------------------------------


def _build_model():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    cost = layer.classification_cost(
        input=layer.fc(input=layer.fc(input=x, size=16, act="relu"), size=2),
        label=y)
    return cost


def _make_sgd():
    cost = _build_model()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=5)
    return trainer.SGD(cost=cost, parameters=params,
                       update_equation=optimizer.Momentum(
                           momentum=0.9, learning_rate=0.1))


def _write_dataset(path, rng, n=96):
    """Linearly-separable records 'x1,...,x8|label'."""
    w = rng.randn(8)
    recs = []
    for _ in range(n):
        x = rng.randn(8).astype(np.float32)
        recs.append((",".join(f"{v:.6f}" for v in x)
                     + f"|{int(x @ w > 0)}").encode())
    recordio_write(path, recs)


def _parse(rec):
    xs, label = rec.decode().split("|")
    return (np.asarray([float(v) for v in xs.split(",")], np.float32),
            int(label))


class _Crash(Exception):
    """Injected trainer crash (fault injection, go/master
    service_internal_test.go style)."""


def _crash_at(event_type, batch_id):
    """Event handler that raises when the given event fires."""
    def handler(ev):
        if isinstance(ev, event_type) and ev.batch_id == batch_id:
            raise _Crash()

    return handler


def _run_straight(svc, num_passes=1):
    """One trainer, whole pass(es), public API; returns final params."""
    c = MasterClient(service=svc)
    sgd = _make_sgd()
    sgd.train(master=c, record_parser=_parse, num_passes=num_passes,
              heartbeat_ttl_s=1e9)
    return {k: np.asarray(sgd.parameters[k]) for k in sgd.parameters.names()}


def _crash_resume_case(tmp_path, clk, svc, crash_event, crash_batch,
                       num_passes=1, saving_period=1, tag=""):
    """Trainer A crashes at the given event; lease lapses; trainer B
    resumes from checkpoint via the SAME public entry point."""
    ck_dir = str(tmp_path /
                 f"ckpt_{crash_event.__name__}_{crash_batch}_{tag}")
    sgd_a = _make_sgd()
    with np.testing.assert_raises(_Crash):
        sgd_a.train(master=MasterClient(service=svc), record_parser=_parse,
                    num_passes=num_passes, save_dir=ck_dir,
                    heartbeat_ttl_s=10.0, saving_period=saving_period,
                    event_handler=_crash_at(crash_event, crash_batch))

    clk.t += 11.0   # A's lease lapses -> its in-flight task refronts

    sgd_b = _make_sgd()
    sgd_b.train(master=MasterClient(service=svc), record_parser=_parse,
                num_passes=num_passes, save_dir=ck_dir,
                heartbeat_ttl_s=1e9, saving_period=saving_period)
    return {k: np.asarray(sgd_b.parameters[k])
            for k in sgd_b.parameters.names()}


def test_kill_trainer_resume_parity(tmp_path):
    """Crash/resume through the PUBLIC API (SGD.train(master=...)):
    trainer A dies mid-pass, its lease lapses, trainer B re-registers and
    auto-resumes from checkpoint. Final params must EQUAL a straight
    single-trainer run (test_TrainerOnePass.cpp determinism bar extended
    to the crash path). Covers BOTH crash windows:

    - holding a task it never stepped (BeginIteration): the task refronts
      and B re-runs it;
    - after the checkpoint was written but before the task was acked
      (EndIteration): the task refronts but B recognizes it from the
      checkpoint meta and skips, avoiding double-application.
    """
    rng = np.random.RandomState(0)
    data_path = str(tmp_path / "train.recordio")
    _write_dataset(data_path, rng)
    clk = Clock()

    def fresh():
        svc = Service(chunks_per_task=16, timeout_s=1e6, time_fn=clk)
        svc.set_dataset([data_path])   # 96 recs / 16 = 6 tasks
        return svc

    ref = _run_straight(fresh())

    # crash window 1: fetched task 2, never stepped it
    got = _crash_resume_case(tmp_path, clk, fresh(),
                             paddle.event.BeginIteration, 2)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=2e-5, atol=2e-6,
                                   err_msg=f"begin-crash {k}")

    # crash window 2: stepped + checkpointed task 1, never acked it
    got = _crash_resume_case(tmp_path, clk, fresh(),
                             paddle.event.EndIteration, 1)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=2e-5, atol=2e-6,
                                   err_msg=f"end-crash {k}")


def test_elastic_multipass_and_periodic_checkpoint_parity(tmp_path):
    """Crash mid pass 1 of a 2-pass run (replacement must NOT re-run pass
    0 or add an extra pass), and crash under saving_period=2 (unacked
    tasks requeue and replay from the last durable checkpoint)."""
    rng = np.random.RandomState(1)
    data_path = str(tmp_path / "train.recordio")
    _write_dataset(data_path, rng)
    clk = Clock()

    def fresh():
        svc = Service(chunks_per_task=16, timeout_s=1e6, time_fn=clk)
        svc.set_dataset([data_path])   # 6 tasks/pass
        return svc

    ref2 = _run_straight(fresh(), num_passes=2)

    # crash in pass 1 (2nd pass), batch 1: resume must finish exactly
    # passes {0,1} worth of updates
    svc = fresh()
    crashes = {"n": 0}

    def crash_in_pass1(ev):
        if isinstance(ev, paddle.event.BeginIteration) \
                and ev.pass_id == 1 and ev.batch_id == 1:
            crashes["n"] += 1
            raise _Crash()

    ck_dir = str(tmp_path / "ckpt_mp")
    sgd_a = _make_sgd()
    with np.testing.assert_raises(_Crash):
        sgd_a.train(master=MasterClient(service=svc), record_parser=_parse,
                    num_passes=2, save_dir=ck_dir, heartbeat_ttl_s=10.0,
                    event_handler=crash_in_pass1)
    clk.t += 11.0
    sgd_b = _make_sgd()
    sgd_b.train(master=MasterClient(service=svc), record_parser=_parse,
                num_passes=2, save_dir=ck_dir, heartbeat_ttl_s=1e9)
    for k in ref2:
        np.testing.assert_allclose(np.asarray(sgd_b.parameters[k]), ref2[k],
                                   rtol=2e-5, atol=2e-6,
                                   err_msg=f"multipass {k}")

    # saving_period=2: crash holding task 3 with task 2 completed but
    # NOT yet checkpointed/acked -> both replay from the last checkpoint
    ref1 = _run_straight(fresh(), num_passes=1)
    got = _crash_resume_case(tmp_path, clk, fresh(),
                             paddle.event.BeginIteration, 3,
                             saving_period=2, tag="sp2")
    for k in ref1:
        np.testing.assert_allclose(got[k], ref1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=f"period2 {k}")
