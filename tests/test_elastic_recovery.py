"""Elastic membership + kill-a-trainer failure injection.

Reference analogs: go/pserver/etcd_client.go:67-166 (Register under a TTL
lease, idx-slot transaction), go/master/service.go:313-448 (timeout
requeue), and the fault-tolerance design docs' kill/recover story. The
reference tests these with in-process servers
(paddle/pserver/test/test_ParameterServer2.cpp:554-560); we do the same
with an injectable clock.
"""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import checkpoint as ckpt
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.master.client import MasterClient
from paddle_tpu.master.recordio import recordio_write
from paddle_tpu.master.service import Service


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# membership protocol
# ---------------------------------------------------------------------------


def test_register_assigns_smallest_free_slot():
    clk = Clock()
    svc = Service(time_fn=clk)
    (a, tok_a), (b, _), (c, tok_c) = (svc.register(), svc.register(),
                                      svc.register())
    assert (a, b, c) == (0, 1, 2)
    # b dies -> slot 1 frees after lease; next register reclaims it
    clk.t += 1.0
    assert svc.heartbeat(0, tok_a, ttl_s=1e6)
    assert svc.heartbeat(2, tok_c, ttl_s=1e6)
    clk.t += svc.lease_ttl_s  # b's lease lapses (0/2 renewed long)
    assert svc.heartbeat(0, tok_a, ttl_s=1e6)
    assert svc.heartbeat(2, tok_c, ttl_s=1e6)
    assert svc.members() == [0, 2]
    slot, token = svc.register()
    assert slot == 1
    assert not svc.heartbeat(5, "bogus"), "unknown slot must not heartbeat"
    # a stale token on a live slot must also be rejected
    assert not svc.heartbeat(1, "stale-token")
    assert svc.heartbeat(1, token)


def test_dead_trainer_tasks_requeue_to_front(tmp_path):
    clk = Clock()
    svc = Service(chunks_per_task=2, timeout_s=1e6, time_fn=clk)
    p = str(tmp_path / "data")
    recordio_write(p, [f"r{i}".encode() for i in range(8)])  # 4 tasks
    svc.set_dataset([p])

    dead, _ = svc.register(ttl_s=10.0)
    live, _ = svc.register(ttl_s=1e6)
    t0 = svc.get_task(owner=dead)       # dead trainer holds task 0
    t1 = svc.get_task(owner=live)
    assert t0.id == 0 and t1.id == 1

    clk.t += 11.0                        # dead's lease lapses
    nxt = svc.get_task(owner=live)       # requeued task 0 comes FIRST
    assert nxt.id == 0, "dead trainer's task must be redelivered first"
    assert svc.members() == [live]
    # the task timeout itself did NOT fire (timeout_s huge): this was
    # lease-driven requeue, the faster path
    assert t1.id in svc._pending


# ---------------------------------------------------------------------------
# end-to-end: kill a trainer mid-pass, resume from checkpoint, converge
# ---------------------------------------------------------------------------


def _build_model():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    cost = layer.classification_cost(
        input=layer.fc(input=layer.fc(input=x, size=16, act="relu"), size=2),
        label=y)
    return cost


def _make_sgd():
    cost = _build_model()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=5)
    return trainer.SGD(cost=cost, parameters=params,
                       update_equation=optimizer.Momentum(
                           momentum=0.9, learning_rate=0.1))


def _write_dataset(path, rng, n=96):
    """Linearly-separable records 'x1,...,x8|label'."""
    w = rng.randn(8)
    recs = []
    for _ in range(n):
        x = rng.randn(8).astype(np.float32)
        recs.append((",".join(f"{v:.6f}" for v in x)
                     + f"|{int(x @ w > 0)}").encode())
    recordio_write(path, recs)


def _parse(rec):
    xs, label = rec.decode().split("|")
    return (np.asarray([float(v) for v in xs.split(",")], np.float32),
            int(label))


def _train_tasks(sgd, client, max_tasks=None,
                 save_dir=None, die_after=None):
    """Consume master tasks; one SGD step per task-chunk batch. Returns
    the number of tasks completed. ``die_after`` stops WITHOUT reporting
    task_finished (the crash)."""
    import jax

    done = 0
    while True:
        if max_tasks is not None and done >= max_tasks:
            return done
        if not client._fetch_task():
            return done
        batch = [_parse(r) for r in client._records]
        client._records = []
        if die_after is not None and done >= die_after:
            return done  # crash: in-flight task never reported
        feeder = sgd._make_feeder(None)
        feeds = feeder.feed(batch)
        if sgd._step_fn is None:
            sgd._step_fn = sgd._build_step()
        p = sgd.parameters.as_dict()
        loss, p, sgd.opt_state, sgd.model_state, _ = sgd._step_fn(
            p, sgd.opt_state, sgd.model_state, jax.random.PRNGKey(done),
            feeds)
        sgd.parameters.update_from(p)
        done += 1
        if save_dir is not None:
            sgd.save_checkpoint(save_dir, done - 1)


def test_kill_trainer_resume_parity(tmp_path):
    """Trainer A processes 2 tasks (checkpointing each), crashes holding
    task 3; its lease lapses; trainer B registers, restores A's last
    checkpoint, and finishes the pass. Final params must EQUAL a straight
    single-trainer run over the same task sequence (the
    test_TrainerOnePass.cpp determinism bar, extended to the crash path)."""
    rng = np.random.RandomState(0)
    data_path = str(tmp_path / "train.recordio")
    _write_dataset(data_path, rng)

    clk = Clock()

    def fresh(save_dir=None):
        svc = Service(chunks_per_task=16, timeout_s=1e6, time_fn=clk)
        svc.set_dataset([data_path])   # 96 recs / 16 = 6 tasks
        return svc

    # ---- straight run: one trainer, whole pass ----
    svc = fresh()
    c = MasterClient(service=svc)
    c.register(ttl_s=1e9)
    sgd_ref = _make_sgd()
    n = _train_tasks(sgd_ref, c)
    assert n == 6
    ref = {k: np.asarray(sgd_ref.parameters[k])
           for k in sgd_ref.parameters.names()}

    # ---- crash run ----
    svc = fresh()
    ck_dir = str(tmp_path / "ckpt")
    ca = MasterClient(service=svc)
    ca.register(ttl_s=10.0)
    sgd_a = _make_sgd()
    # A: completes tasks 0,1 (checkpointing), takes task 2 and dies
    done_a = _train_tasks(sgd_a, ca, max_tasks=3, save_dir=ck_dir,
                          die_after=2)
    assert done_a == 2

    clk.t += 11.0   # A's lease lapses -> task 2 requeues to the front

    cb = MasterClient(service=svc)
    cb.register(ttl_s=1e9)
    sgd_b = _make_sgd()
    sgd_b.load_checkpoint(ck_dir)      # latest = after A's task 1
    # B's step counter must continue where A stopped (rng stream parity);
    # replay continuation: tasks 2..5 with step ids 2..5
    import jax
    done = 2
    while True:
        if not cb._fetch_task():
            break
        batch = [_parse(r) for r in cb._records]
        cb._records = []
        if sgd_b._step_fn is None:
            sgd_b._step_fn = sgd_b._build_step()
        p = sgd_b.parameters.as_dict()
        loss, p, sgd_b.opt_state, sgd_b.model_state, _ = sgd_b._step_fn(
            p, sgd_b.opt_state, sgd_b.model_state, jax.random.PRNGKey(done),
            feeds=sgd_b._make_feeder(None).feed(batch))
        sgd_b.parameters.update_from(p)
        done += 1
    assert done == 6, f"B finished at {done}, expected 6 tasks total"

    for k in ref:
        np.testing.assert_allclose(np.asarray(sgd_b.parameters[k]), ref[k],
                                   rtol=2e-5, atol=2e-6, err_msg=k)
