"""Serving SLO guardrails under deterministic fault injection.

Every terminal status (TIMED_OUT / CANCELLED / REJECTED / FAILED) and
every injected fault (slow ticks, decode-step exceptions, NaN logits,
page-pool pressure) is reached here via a seeded
:class:`~paddle_tpu.serving.FaultPlan` and the injectable
:class:`~paddle_tpu.serving.ManualClock` — no sleeps, no wall-clock
dependence, mirroring how ``tests/test_master.py`` drives lease expiry
with a fake ``time_fn``.
"""

import numpy as np
import jax
import pytest

from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (ContinuousBatchingScheduler, DecoderLM,
                                FaultPlan, ManualClock, PageLeakError,
                                PagePool, Request, RequestStatus,
                                SchedulerConfig, ServingEngine,
                                greedy_decode_reference)

from conftest import assert_serving_drained as assert_drained  # noqa: E402

serving = pytest.mark.serving
faults = pytest.mark.faults

pytestmark = [serving, faults]


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def _small_model(seed=0, **kw):
    kw.setdefault("vocab_size", 50)
    kw.setdefault("num_layers", 1)
    kw.setdefault("num_heads", 2)
    kw.setdefault("head_dim", 8)
    kw.setdefault("max_positions", 128)
    model = DecoderLM(**kw)
    return model, model.init_params(jax.random.PRNGKey(seed))


def _engine(model, params, plan=None, **kw):
    kw.setdefault("eos_id", 1)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 24)
    kw.setdefault("max_pages_per_seq", 6)
    kw.setdefault("max_slots", 2)
    kw.setdefault("buckets", (4, 8))
    return ServingEngine(model, params, faults=plan, **kw)


# ---------------------------------------------------------------------------
# deadlines: TIMED_OUT in queue and while running, load shedding
# ---------------------------------------------------------------------------


def test_queue_deadline_times_out_waiting_request(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=1.0))
    eng = _engine(model, params, plan, max_slots=1)
    a = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=6)
    b = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=6,
                   queue_deadline_s=3.0)
    res = eng.run(max_ticks=50)
    assert eng.status(a) is RequestStatus.COMPLETED
    assert eng.status(b) is RequestStatus.TIMED_OUT
    assert eng.result(b) is None and a in res and b not in res
    assert eng.metrics.timed_out == 1
    assert_drained(eng)


def test_total_deadline_times_out_running_request_and_frees_pages(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=1.0))
    eng = _engine(model, params, plan)
    rid = eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=20,
                     deadline_s=3.0)
    eng.step()                      # prefill, first token
    assert eng.status(rid) is RequestStatus.RUNNING
    eng.step()                      # clock 2.0: still running
    eng.step()                      # clock 3.0 >= deadline: timed out
    assert eng.status(rid) is RequestStatus.TIMED_OUT
    # the slot and pages came back IMMEDIATELY, not at drain
    assert_drained(eng)
    assert not eng.has_work
    assert eng.metrics.timed_out == 1
    eng.check_page_conservation()


def test_zero_total_deadline_means_expired_not_unbounded(rng):
    # deadline_s = max(0, slo - elapsed) hitting exactly 0.0 must time
    # out immediately, not silently disable the deadline
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=1.0))
    eng = _engine(model, params, plan)
    rid = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=8,
                     deadline_s=0.0)
    qrid = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=8,
                      queue_deadline_s=0.0)     # same semantic per-request
    eng.run(max_ticks=10)
    assert eng.status(rid) is RequestStatus.TIMED_OUT
    assert eng.status(qrid) is RequestStatus.TIMED_OUT
    assert eng.metrics.prefill_tokens == 0      # never even prefilled


def test_unmeetable_deadline_is_shed_not_prefilled(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=1.0))
    eng = _engine(model, params, plan, max_slots=1)
    a = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=8)
    # needs 20 decode ticks but the deadline allows ~5 at the observed
    # 1s/tick rate -> shed as REJECTED before any prefill work
    b = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=20,
                   deadline_s=5.0)
    eng.run(max_ticks=50)
    assert eng.status(a) is RequestStatus.COMPLETED
    assert eng.status(b) is RequestStatus.REJECTED
    assert eng.metrics.shed == 1 and eng.metrics.timed_out == 0
    assert eng.metrics.prefill_tokens == 3      # only a's prompt
    snap = eng.metrics.snapshot()
    assert snap["requests_shed"] == 1
    assert snap["deadline_miss_rate"] == 0.5    # 1 shed / (1 done + 1 shed)


def test_queue_deadline_is_admission_only_preemption_does_not_retrigger(rng):
    # a queue deadline is satisfied at admission: a request preempted
    # long after must NOT be timed out against it on requeue
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=1.0))
    eng = _engine(model, params, plan, queue_deadline_s=2.0)
    p = rng.randint(2, 50, size=3).tolist()
    rid = eng.submit(p, max_tokens=8)
    eng.step()                      # admitted at clock 1.0, within SLO
    eng.step()
    eng.step()                      # clock 3.0: queue deadline long past
    req = eng.scheduler.running_requests()[0]
    assert req.rid == rid
    eng.scheduler._preempt(req)     # evicted for pages, requeued
    res = eng.run(max_ticks=60)
    assert eng.status(rid) is RequestStatus.COMPLETED
    assert res[rid] == greedy_decode_reference(model, params, p, 8, 1)
    assert eng.metrics.timed_out == 0
    # queue wait is a first-admission stat: the re-admission after the
    # preemption must not record a second (running-time-inflated) sample
    assert len(eng.metrics.queue_wait_s) == 1
    # submitted_at == 0.0 (clock origin) is a real timestamp, not a
    # missing one: wait and TTFT are the true 1.0s, not zeroed
    assert eng.metrics.queue_wait_s[0] == pytest.approx(1.0)
    assert eng.metrics.ttft_s[0] == pytest.approx(1.0)


def test_idle_ticks_do_not_inflate_shed_estimator(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=1.0))
    eng = _engine(model, params, plan)
    rid = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    eng.run(max_ticks=20)
    assert eng.status(rid) is RequestStatus.COMPLETED
    busy_ema = eng._tick_dur_ema
    assert busy_ema > 0.0
    for _ in range(10):                 # a server polling an idle engine
        eng.step()
    assert eng._tick_dur_ema == busy_ema    # idle gaps learned nothing
    # so a burst arriving after the idle stretch is NOT spuriously shed
    rid2 = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4,
                      deadline_s=30.0)
    eng.run(max_ticks=20)
    assert eng.status(rid2) is RequestStatus.COMPLETED
    assert eng.metrics.shed == 0


# ---------------------------------------------------------------------------
# cancellation
# ---------------------------------------------------------------------------


def test_cancel_running_and_queued(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01))
    eng = _engine(model, params, plan, max_slots=1)
    a = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=8)
    b = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=8)
    eng.step()
    assert eng.status(a) is RequestStatus.RUNNING
    assert eng.status(b) is RequestStatus.QUEUED
    assert eng.cancel(b)            # queued: leaves the queue
    assert eng.cancel(a)            # running: slot + pages freed now
    assert_drained(eng)
    assert not eng.cancel(a)        # already terminal
    assert eng.status(a) is RequestStatus.CANCELLED
    assert eng.status(b) is RequestStatus.CANCELLED
    assert eng.metrics.cancelled == 2
    assert not eng.has_work
    eng.check_page_conservation()


def test_cancel_from_own_on_token_wins_over_completion(rng):
    # a streaming consumer cancelling from its own callback — even on
    # the token that would have completed the request — sticks
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01))
    eng = _engine(model, params, plan)
    box = {}
    toks = []

    def cb(tok):
        toks.append(tok)
        if len(toks) == 3:          # 3 == max_tokens: the final emit
            eng.cancel(box["rid"])

    box["rid"] = eng.submit(rng.randint(2, 50, size=3).tolist(),
                            max_tokens=3, on_token=cb)
    res = eng.run(max_ticks=50)
    assert eng.status(box["rid"]) is RequestStatus.CANCELLED
    assert box["rid"] not in res and eng.result(box["rid"]) is None
    assert eng.metrics.cancelled == 1 and eng.metrics.completed == 0
    assert_drained(eng)
    eng.check_page_conservation()


# ---------------------------------------------------------------------------
# submit/result/status disambiguation (satellite regression)
# ---------------------------------------------------------------------------


def test_submit_returns_rejected_rid_and_result_disambiguates(rng):
    model, params = _small_model()
    eng = _engine(model, params, max_slots=1, max_queue=1)
    # infeasible: longer than max_seq_len -> rid with REJECTED status,
    # not a bare None sentinel
    huge = eng.submit(rng.randint(2, 50, size=30).tolist(), max_tokens=30)
    assert isinstance(huge, int)
    assert eng.status(huge) is RequestStatus.REJECTED
    assert eng.result(huge) is None
    # in flight: result None but status says QUEUED
    a = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    assert eng.result(a) is None
    assert eng.status(a) is RequestStatus.QUEUED
    # backpressure rejection also gets a rid
    eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    bp = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    assert eng.status(bp) is RequestStatus.REJECTED
    # unknown rid: KeyError from all three, never a silent None
    with pytest.raises(KeyError):
        eng.status(10 ** 9)
    with pytest.raises(KeyError):
        eng.result(10 ** 9)
    with pytest.raises(KeyError):
        eng.cancel(10 ** 9)
    res = eng.run(max_ticks=100)
    assert eng.status(a) is RequestStatus.COMPLETED
    assert res[a] == eng.result(a)


# ---------------------------------------------------------------------------
# failure isolation: NaN guard, transient retry, watchdog
# ---------------------------------------------------------------------------


def test_nan_guard_fails_only_poisoned_slot_batchmates_keep_parity(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01))
    eng = _engine(model, params, plan)
    p_ok = rng.randint(2, 50, size=5).tolist()
    p_bad = rng.randint(2, 50, size=4).tolist()
    ok = eng.submit(p_ok, max_tokens=8)
    bad = eng.submit(p_bad, max_tokens=8)
    plan.poison_nan(bad)
    res = eng.run(max_ticks=100)
    assert eng.status(bad) is RequestStatus.FAILED
    assert bad not in res
    # the fused batchmate decoded through the poisoned tick untouched
    assert res[ok] == greedy_decode_reference(model, params, p_ok, 8, 1)
    assert eng.metrics.failed == 1
    assert_drained(eng)


def test_transient_error_set_is_configurable(rng):
    # an empty transient set means injected errors are NOT absorbed:
    # they propagate like any real unlisted device failure would
    from paddle_tpu.serving import InjectedDeviceError

    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01),
                     decode_errors={0: 1})
    eng = _engine(model, params, plan, transient_errors=())
    eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    with pytest.raises(InjectedDeviceError):
        eng.step()


def test_terminal_requests_evicted_past_retention_bound(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01))
    eng = _engine(model, params, plan, max_retained=2)
    rids = [eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=2)
            for _ in range(4)]
    eng.run(max_ticks=100)
    # only the 2 most recently retired survive; older rids are evicted
    with pytest.raises(KeyError):
        eng.status(rids[0])
    with pytest.raises(KeyError):
        eng.result(rids[1])
    assert eng.status(rids[3]) is RequestStatus.COMPLETED
    assert eng.result(rids[3]) is not None
    assert len(eng._requests) == 2


def test_transient_decode_error_is_retried_same_tick(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01),
                     decode_errors={1: 1, 3: 1})   # one failing attempt each
    eng = _engine(model, params, plan)
    p = rng.randint(2, 50, size=4).tolist()
    rid = eng.submit(p, max_tokens=8)
    res = eng.run(max_ticks=100)
    # retries absorbed the injected errors: full parity, no failure
    assert res[rid] == greedy_decode_reference(model, params, p, 8, 1)
    assert eng.metrics.retries == 2
    assert eng.metrics.failed == 0


def test_persistent_decode_errors_trip_watchdog(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01),
                     decode_errors={t: 99 for t in range(1, 40)})
    eng = _engine(model, params, plan, watchdog_ticks=5, decode_retries=2)
    rid = eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=8)
    eng.run(max_ticks=60)
    assert eng.status(rid) is RequestStatus.FAILED
    assert eng.metrics.failed == 1
    assert eng.metrics.retries > 0          # it did try before giving up
    assert not eng.has_work
    assert_drained(eng)


def test_page_pressure_forces_preemption_but_everyone_finishes(rng):
    # the known-thrashing geometry (7 usable pages, 3 requests growing to
    # 4 pages each) with a fault-plan pressure window squeezing it harder
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01),
                     page_pressure=(2, 10, 2))
    eng = _engine(model, params, plan, num_pages=8, max_pages_per_seq=4,
                  max_slots=3)
    prompts = [rng.randint(2, 50, size=4).tolist() for _ in range(3)]
    rids = [eng.submit(p, max_tokens=12) for p in prompts]
    pressure_seen = 0
    while eng.has_work:
        eng.step()
        pressure_seen = max(pressure_seen, len(plan.held_pages))
        assert eng.metrics.ticks < 500
    res = eng.run(max_ticks=10)             # drained: runs the leak check
    for p, rid in zip(prompts, rids):
        assert res[rid] == greedy_decode_reference(model, params, p, 12, 1)
    assert eng.metrics.preemptions > 0      # the pool really thrashed
    assert pressure_seen > 0                # the pressure window engaged
    assert plan.held_pages == []            # pressure pages returned
    assert_drained(eng)


def test_page_pressure_engages_late_when_pool_busy_at_window_start():
    # a fully-busy pool at the start tick must still get squeezed as
    # pages free up inside the window (unit-level, no engine)
    pool = PagePool(5)              # 4 usable
    busy = pool.alloc(4)
    plan = FaultPlan(page_pressure=(0, 5, 2))
    plan.apply_page_pressure(0, pool)
    assert plan.held_pages == []    # nothing free yet
    pool.free(busy[:1])
    plan.apply_page_pressure(1, pool)
    assert len(plan.held_pages) == 1
    pool.free(busy[1:])
    plan.apply_page_pressure(2, pool)
    assert len(plan.held_pages) == 2        # accumulates up to n, no more
    plan.apply_page_pressure(5, pool)       # window over: all returned
    assert plan.held_pages == []
    assert pool.num_free == pool.num_usable


# ---------------------------------------------------------------------------
# preemption budget + escalation (scheduler-level, no jax)
# ---------------------------------------------------------------------------


def _sched_request(prompt_len, max_tokens, now, sched):
    req = Request(prompt=list(range(2, 2 + prompt_len)),
                  max_tokens=max_tokens)
    assert sched.submit(req, now=now)
    return req


def test_victim_selection_skips_budget_exhausted_requests():
    pool = PagePool(13)   # 12 usable
    sched = ContinuousBatchingScheduler(pool, SchedulerConfig(
        max_slots=3, page_size=2, max_pages_per_seq=6, preempt_budget=2))
    a = _sched_request(2, 4, 0.0, sched)
    b = _sched_request(2, 4, 1.0, sched)
    c = _sched_request(2, 4, 2.0, sched)
    assert len(sched.admit()) == 3
    # c is the youngest but has burned its budget: it must never be the
    # victim again
    c.preemptions, c.escalated = 2, True
    pressure = pool.alloc(pool.num_free)    # dry pool
    a.cache_len = len(a.pages) * 2          # a's next append needs a page
    preempted = sched.ensure_decode_pages()
    assert preempted == [b]                 # b evicted, c protected
    assert b.status is RequestStatus.PREEMPTED
    assert c.status is RequestStatus.RUNNING
    pool.free(pressure)


def test_escalated_request_requeues_ahead_and_grower_self_preempts():
    pool = PagePool(9)    # 8 usable
    sched = ContinuousBatchingScheduler(pool, SchedulerConfig(
        max_slots=2, page_size=2, max_pages_per_seq=4, preempt_budget=1))
    a = _sched_request(2, 4, 0.0, sched)
    b = _sched_request(2, 4, 1.0, sched)
    assert len(sched.admit()) == 2
    pressure = pool.alloc(pool.num_free)
    # first eviction: b pays, burns its whole budget (1), escalates
    a.cache_len = len(a.pages) * 2
    assert sched.ensure_decode_pages() == [b]
    assert b.escalated and b.preemptions == 1
    # b jumped the queue ahead of a later normal requeue
    queued_later = _sched_request(2, 4, 2.0, sched)
    assert list(sched.queue)[0] is b and list(sched.queue)[1] is queued_later
    # second growth with nobody eligible: the grower preempts ITSELF
    # rather than evicting the protected b
    leftover = pool.alloc(pool.num_free)    # re-dry the pool
    a.cache_len = len(a.pages) * 2
    assert sched.ensure_decode_pages() == [a]
    assert a.status is RequestStatus.PREEMPTED
    # a was preempted past its budget too -> escalated, queue head
    assert list(sched.queue)[0] is a
    pool.free(pressure)
    pool.free(leftover)
    assert pool.num_free + pool.num_in_use == pool.num_usable


# ---------------------------------------------------------------------------
# PagePool free-list conservation: randomized stress (satellite)
# ---------------------------------------------------------------------------


def test_page_pool_conservation_randomized_stress():
    # round 9: the scheduler runs WITH a prefix cache, prompts draw from
    # a tiny alphabet so hits/stitching/COW actually occur, and two new
    # ops exercise cache insertion and LRU eviction.  Every op asserts
    # refcount conservation AND free-list/set agreement.
    from paddle_tpu.serving import PrefixCache

    rng = np.random.RandomState(7)
    pool = PagePool(17)   # 16 usable
    cfg = SchedulerConfig(max_slots=4, page_size=4, max_pages_per_seq=4,
                          max_queue=32, preempt_budget=3)
    cache = PrefixCache(pool, page_size=cfg.page_size)
    sched = ContinuousBatchingScheduler(pool, cfg, cache=cache)

    def conserve():
        assert pool.num_free + pool.num_in_use == pool.num_usable
        # the double-free guard's set mirror never drifts from the list
        assert set(pool._free) == pool._free_set
        assert len(pool._free) == len(pool._free_set)
        live = list(sched.running.values()) + list(sched.queue)
        held = sum(len(r.pages) for r in live)
        held += sum(1 for r in live if r.cow_src is not None)
        assert held == pool.total_refs, "REF-LEAK: orphaned references"

    n_ops = 600
    for i in range(n_ops):
        op = rng.randint(7)
        if op == 0:       # submit (sometimes infeasible -> rejected);
            # 4-token alphabet, page-multiple lengths (prefix hits,
            # full-cover COW stitches) MIXED with unaligned tails
            # (partial last page never indexed, no COW) so both
            # accounting paths stay exercised
            size = 4 * rng.randint(1, 4) + rng.randint(0, 4)
            sched.submit(Request(
                prompt=list(rng.randint(2, 6, size=size)),
                max_tokens=int(rng.randint(1, 8))), now=float(i))
        elif op == 1:     # admit (stitches cached prefixes, pins COW src)
            sched.admit()
        elif op == 2:     # grow a running request at a page boundary
            running = sched.running_requests()
            if running:
                r = running[rng.randint(len(running))]
                if len(r.pages) < cfg.max_pages_per_seq:
                    r.cache_len = len(r.pages) * cfg.page_size
                    sched.ensure_decode_pages()
        elif op == 3:     # complete a random running request
            running = sched.running_requests()
            if running:
                sched.release(running[rng.randint(len(running))],
                              RequestStatus.COMPLETED)
        elif op == 4:     # cancel a random queued request
            if sched.queue:
                sched.drop_queued(
                    sched.queue[rng.randint(len(sched.queue))],
                    RequestStatus.CANCELLED)
        elif op == 5:     # a "prefill" indexes a request's full pages
            running = sched.running_requests()
            if running:
                r = running[rng.randint(len(running))]
                upto = min(len(r.prompt), len(r.pages) * cfg.page_size)
                cache.insert(r.prompt, r.pages, upto)
        elif op == 6:     # pressure: evict some reclaimable pages
            cache.evict(int(rng.randint(1, 4)))
        conserve()
    # drain everything: zero refs, free + reclaimable covers the pool
    for r in list(sched.running.values()):
        sched.release(r, RequestStatus.COMPLETED)
    while sched.queue:
        sched.drop_queued(sched.queue[0], RequestStatus.CANCELLED)
    conserve()
    assert pool.total_refs == 0
    assert pool.num_free + pool.num_reclaimable == pool.num_usable
    cache.flush()
    assert pool.num_free == pool.num_usable


# ---------------------------------------------------------------------------
# leak checker + healthz
# ---------------------------------------------------------------------------


def test_leak_checker_raises_on_orphaned_pages(rng):
    model, params = _small_model()
    eng = _engine(model, params)
    eng.check_page_conservation()           # clean engine passes
    orphan = eng.pool.alloc(2)              # pages nobody accounts for
    with pytest.raises(PageLeakError):
        eng.check_page_conservation()
    assert eng.healthz()["page_leak"] is True
    eng.pool.free(orphan)
    eng.check_page_conservation()


def test_healthz_snapshot_fields(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=0.01))
    eng = _engine(model, params, plan)
    rid = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    bad = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    plan.poison_nan(bad)
    eng.run(max_ticks=50)
    hz = eng.healthz()
    assert hz["ok"] is True and hz["page_leak"] is False
    assert hz["queue_depth"] == 0 and hz["running"] == 0
    assert hz["pages_in_use"] == 0 and hz["pages_free"] > 0
    assert hz["tick"] > 0
    assert hz["status_counts"] == {"completed": 1, "failed": 1}
    assert eng.status(rid) is RequestStatus.COMPLETED


# ---------------------------------------------------------------------------
# all four terminal statuses in ONE engine, fault injection only
# ---------------------------------------------------------------------------


def test_every_terminal_status_reachable_in_one_run(rng):
    model, params = _small_model()
    plan = FaultPlan(clock=ManualClock(tick_s=1.0))
    eng = _engine(model, params, plan, max_slots=2, num_pages=24,
                  max_pages_per_seq=6)
    done = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    poisoned = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    plan.poison_nan(poisoned)
    rejected = eng.submit(rng.randint(2, 50, size=40).tolist(),
                          max_tokens=40)          # infeasible
    late = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4,
                      queue_deadline_s=1.0)       # slots busy, will lapse
    cancelled = eng.submit(rng.randint(2, 50, size=3).tolist(),
                           max_tokens=4)
    eng.cancel(cancelled)
    eng.run(max_ticks=100)
    got = {s: eng.status(r) for s, r in [
        ("completed", done), ("failed", poisoned), ("rejected", rejected),
        ("timed_out", late), ("cancelled", cancelled)]}
    assert got == {
        "completed": RequestStatus.COMPLETED,
        "failed": RequestStatus.FAILED,
        "rejected": RequestStatus.REJECTED,
        "timed_out": RequestStatus.TIMED_OUT,
        "cancelled": RequestStatus.CANCELLED,
    }
    assert_drained(eng)
    eng.check_page_conservation()
    snap = eng.metrics.snapshot()
    for key in ("requests_timed_out", "requests_cancelled",
                "requests_failed", "requests_shed", "retries",
                "deadline_miss_rate", "queue_wait_ms_p95"):
        assert key in snap


def test_submit_during_drain_rejected_running_finishes(rng):
    """drain(): new submits REJECT immediately, but queued AND running
    requests finish normally, and drain(False) reopens admission — the
    engine-side half of a fleet replica's DRAINING state."""
    model, params = _small_model()
    clock = ManualClock(tick_s=0.01)
    eng = _engine(model, params, FaultPlan(clock=clock))
    running = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    queued = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    eng.step()                      # `running` holds a slot now
    assert not eng.draining
    eng.drain()
    assert eng.draining and eng.healthz()["draining"]
    refused = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=4)
    assert eng.status(refused) is RequestStatus.REJECTED
    assert eng.metrics.rejected == 1
    eng.run(max_ticks=100)
    # accepted work all finished despite the drain
    assert eng.status(running) is RequestStatus.COMPLETED
    assert eng.status(queued) is RequestStatus.COMPLETED
    assert_drained(eng)
    eng.drain(False)                # rejoin: admission reopens
    accepted = eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=2)
    eng.run(max_ticks=100)
    assert eng.status(accepted) is RequestStatus.COMPLETED
    assert_drained(eng)


def test_healthz_first_class_load_signals(rng):
    """queue_depth and free_pages are first-class healthz fields (the
    fleet router balances on them without reaching into internals)."""
    model, params = _small_model()
    eng = _engine(model, params, FaultPlan(clock=ManualClock(tick_s=0.01)))
    hz = eng.healthz()
    assert hz["queue_depth"] == 0
    assert hz["free_pages"] == eng.pool.num_free == hz["pages_free"]
    assert hz["draining"] is False
    for _ in range(4):
        eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=6)
    hz = eng.healthz()              # max_slots=2: the rest queue up
    assert hz["queue_depth"] == 4   # nothing admitted before a step
    eng.step()
    hz = eng.healthz()
    assert hz["queue_depth"] == 2 and hz["running"] == 2
    assert hz["free_pages"] < eng.pool.num_usable
    eng.run(max_ticks=100)
    assert_drained(eng)
