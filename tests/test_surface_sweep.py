"""Remaining user-surface sweep: reader decorators, event types, pooling
types, initializers, image utils, checkpoint helpers, sequence helpers,
data_type constructors — every exported helper of the small user-facing
modules exercised against hand oracles (the v2 API's unit-test breadth:
python/paddle/v2/tests + v2/reader/tests in the reference).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import event, image, initializer, layer, pooling
from paddle_tpu import reader as preader
from paddle_tpu.reader import decorator
from paddle_tpu.sequence import (SequenceBatch, lengths_to_segment_ids,
                                 position_in_sequence)


# ---------------------------------------------------------------------------
# reader decorators (reference: v2/reader/decorator.py:26-233 + its tests)
# ---------------------------------------------------------------------------


def _r(vals):
    def reader():
        yield from vals
    return reader


def test_map_readers():
    got = list(preader.map_readers(lambda a, b: a + b,
                                   _r([1, 2, 3]), _r([10, 20, 30]))())
    assert got == [11, 22, 33]


def test_chain():
    assert list(preader.chain(_r([1, 2]), _r([3]), _r([4, 5]))()) == \
        [1, 2, 3, 4, 5]


def test_compose_flattens_and_checks_alignment():
    got = list(preader.compose(_r([(1, 2), (3, 4)]), _r(["a", "b"]))())
    assert got == [(1, 2, "a"), (3, 4, "b")]
    with pytest.raises(decorator.ComposeNotAligned):
        list(preader.compose(_r([1, 2, 3]), _r([1]))())
    # alignment check off: stops at the shortest (zip semantics)
    got2 = list(preader.compose(_r([1, 2, 3]), _r([10]),
                                check_alignment=False)())
    assert got2 == [(1, 10)]


def test_buffered_and_firstn():
    assert sorted(preader.buffered(_r(range(10)), size=3)()) == \
        list(range(10))
    assert list(preader.firstn(_r(range(100)), 4)()) == [0, 1, 2, 3]


def test_shuffle_is_permutation():
    import random
    random.seed(3)
    got = list(preader.shuffle(_r(range(20)), buf_size=8)())
    assert sorted(got) == list(range(20))


def test_xmap_readers_parallel_map():
    got = sorted(preader.xmap_readers(lambda x: x * x, _r(range(12)),
                                      process_num=3, buffer_size=8)())
    assert got == [i * i for i in range(12)]
    # order-preserving variant if supported via order flag
    try:
        ordered = list(preader.xmap_readers(lambda x: x + 1, _r(range(6)),
                                            process_num=2, buffer_size=4,
                                            order=True)())
        assert ordered == [1, 2, 3, 4, 5, 6]
    except TypeError:
        pass  # no order kwarg in this signature


# ---------------------------------------------------------------------------
# events: the full lifecycle fires (reference: v2/event.py + trainer tests)
# ---------------------------------------------------------------------------


def test_event_lifecycle_and_test_result():
    from paddle_tpu import optimizer, trainer
    from paddle_tpu.dataset import _synth

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    lab = layer.data(name="label", type=paddle.data_type.integer_value(2))
    cost = layer.classification_cost(input=layer.fc(x, size=2), label=lab)
    params = paddle.Parameters.from_topology(paddle.topology.Topology([cost]))
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Sgd(learning_rate=0.1))

    seen = []

    def handler(ev):
        seen.append(type(ev).__name__)
        if isinstance(ev, event.EndIteration):
            assert isinstance(ev, event.WithMetric)
            assert np.isfinite(ev.cost)

    def rdr():
        rng = np.random.RandomState(0)
        for _ in range(8):
            v = rng.randn(4).astype(np.float32)
            yield v, int(v.sum() > 0)

    sgd.train(paddle.batch(rdr, 4), num_passes=2, event_handler=handler)
    for name in ("BeginPass", "BeginIteration", "EndIteration", "EndPass"):
        assert name in seen, (name, set(seen))

    res = sgd.test(paddle.batch(rdr, 4))
    assert isinstance(res, event.TestResult)
    assert isinstance(res, event.WithMetric)
    assert np.isfinite(res.cost)


# ---------------------------------------------------------------------------
# pooling types through layer.pooling (reference: pooling.py + SequencePool)
# ---------------------------------------------------------------------------


def _pool_seq():
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(2))
    data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.], [9., 10.]],
                    np.float32)
    sb = SequenceBatch(jnp.asarray(data),
                       jnp.asarray([0, 0, 0, 1, 1], np.int32),
                       jnp.asarray([3, 2], np.int32), max_len=3)
    return s, sb, data


@pytest.mark.parametrize("ptype,reduce_fn", [
    (pooling.MaxPooling, lambda rows: rows.max(0)),
    (pooling.AvgPooling, lambda rows: rows.mean(0)),
    (pooling.SumPooling, lambda rows: rows.sum(0)),
    (pooling.SqrtNPooling, lambda rows: rows.sum(0) / np.sqrt(len(rows))),
])
def test_pooling_types(ptype, reduce_fn):
    paddle.topology.reset_name_scope()
    s, sb, data = _pool_seq()
    node = layer.pooling(input=s, pooling_type=ptype())
    topo = paddle.topology.Topology([node])
    params = paddle.Parameters.from_topology(topo)
    outs, _ = topo.forward(params.as_dict(), topo.init_state(), {"s": sb})
    want = np.stack([reduce_fn(data[:3]), reduce_fn(data[3:])])
    np.testing.assert_allclose(np.asarray(outs[0]), want, rtol=1e-5)
    assert isinstance(ptype(), pooling.BasePoolingType)
    assert isinstance(pooling.get(ptype()), ptype)


# ---------------------------------------------------------------------------
# initializers (reference: ParameterConfig initial_strategy/initial_std)
# ---------------------------------------------------------------------------


def test_initializer_statistics_and_dispatch():
    import jax

    key = jax.random.PRNGKey(0)
    shape = (400, 300)
    u = np.asarray(initializer.Uniform(-0.2, 0.2)(key, shape))
    assert abs(u.mean()) < 0.01 and u.min() >= -0.2 and u.max() <= 0.2
    n = np.asarray(initializer.Normal(std=0.5)(key, shape))
    assert abs(n.std() - 0.5) < 0.02
    xv = np.asarray(initializer.XavierUniform()(key, shape))
    bound = np.sqrt(6.0 / (shape[0] + shape[1]))
    assert xv.max() <= bound + 1e-6 and xv.min() >= -bound - 1e-6
    fi = np.asarray(initializer.FanInNormal()(key, shape))
    assert abs(fi.std() - 1.0 / np.sqrt(shape[0])) < 0.005
    c = np.asarray(initializer.Constant(1.5)(key, (7,)))
    np.testing.assert_allclose(c, 1.5)
    assert isinstance(initializer.default_weight_init(),
                      initializer.Initializer)
    assert isinstance(initializer.default_bias_init(),
                      initializer.Initializer)
    assert isinstance(initializer.to_initializer(0.3),
                      initializer.Constant)
    assert isinstance(initializer.to_initializer(initializer.Normal()),
                      initializer.Normal)


# ---------------------------------------------------------------------------
# image utils (reference: python/paddle/v2/image.py)
# ---------------------------------------------------------------------------


def test_image_pipeline_helpers(tmp_path):
    im = (np.arange(40 * 30 * 3) % 255).reshape(40, 30, 3).astype(np.uint8)
    short = image.resize_short(im, 24)
    assert min(short.shape[:2]) == 24
    cc = image.center_crop(short, 16)
    assert cc.shape[:2] == (16, 16)
    rc = image.random_crop(short, 16)
    assert rc.shape[:2] == (16, 16)
    fl = image.left_right_flip(im)
    np.testing.assert_array_equal(fl, im[:, ::-1])
    chw = image.to_chw(im)
    assert chw.shape == (3, 40, 30)
    np.testing.assert_array_equal(image.to_hwc(chw), im)

    # encoded round trip (PIL or cv2 backend, else skip)
    try:
        from PIL import Image as PILImage
        p = tmp_path / "t.png"
        PILImage.fromarray(im).save(p)
    except ImportError:
        pytest.skip("no PIL to encode a test image")
    loaded = image.load_image(str(p))
    assert loaded.shape[2] == 3
    lt = image.load_and_transform(str(p), resize_size=24, crop_size=16,
                                  is_train=False) \
        if hasattr(image, "load_and_transform") else None
    if lt is not None:
        assert 16 in lt.shape

    # tar batching
    import tarfile
    tar = tmp_path / "imgs.tar"
    with tarfile.open(tar, "w") as t:
        t.add(p, arcname="a.png")
        t.add(p, arcname="b.png")
    if hasattr(image, "batch_images_from_tar"):
        out = image.batch_images_from_tar(
            str(tar), "train", img2label={"a.png": 0, "b.png": 1},
            num_per_batch=2) if "img2label" in \
            image.batch_images_from_tar.__code__.co_varnames else None
        # presence + callable shape is enough; heavy paths covered above


# ---------------------------------------------------------------------------
# checkpoint helpers + sequence index helpers
# ---------------------------------------------------------------------------


def test_checkpoint_pass_dir_and_prune(tmp_path):
    from paddle_tpu import checkpoint as ckpt

    assert ckpt.pass_dir("/x", 7).endswith("pass-00007")
    root = str(tmp_path)
    for i in range(5):
        os.makedirs(ckpt.pass_dir(root, i))
    ckpt.prune_checkpoints(root, keep=2)
    left = sorted(os.listdir(root))
    assert left == ["pass-00003", "pass-00004"]


def test_sequence_index_helpers():
    seg = jnp.asarray([0, 0, 0, 1, 1, 2, 3, 3], jnp.int32)
    pos = np.asarray(position_in_sequence(seg))
    np.testing.assert_array_equal(pos, [0, 1, 2, 0, 1, 0, 0, 1])
    lens = jnp.asarray([3, 2, 1], jnp.int32)
    seg2 = np.asarray(lengths_to_segment_ids(lens, 8))
    np.testing.assert_array_equal(seg2[:6], [0, 0, 0, 1, 1, 2])
    assert (seg2[6:] >= 3).all()  # padding slots get an out-of-range id


# ---------------------------------------------------------------------------
# data_type constructors land correct slot/seq kinds
# ---------------------------------------------------------------------------


def test_data_type_constructors():
    dt = paddle.data_type
    assert dt.dense_vector(8).dim == 8
    assert "INDEX" in str(dt.integer_value(5).slot).upper()
    assert "NO_SEQUENCE" in str(dt.dense_vector(8).seq).upper()
    assert "SEQUENCE" in str(dt.dense_vector_sequence(8).seq).upper()
    for ctor in ("sparse_binary_vector", "sparse_float_vector",
                 "dense_array"):
        t = getattr(dt, ctor)(16)
        assert t.dim == 16
    for ctor in ("sparse_binary_vector_sequence",
                 "sparse_float_vector_sequence",
                 "dense_vector_sub_sequence", "integer_value_sub_sequence"):
        t = getattr(dt, ctor)(16)
        assert "SEQUENCE" in str(t.seq).upper()


def test_attr_aliases():
    from paddle_tpu import attr

    assert attr.ParameterAttribute is attr.ParamAttr
    assert attr.ExtraLayerAttribute is attr.ExtraAttr
    assert attr.HookAttribute is attr.HookAttr


def test_forward_errors_name_the_failing_layer():
    """The CustomStackTrace analog: a crash inside a layer's compute names
    the layer (utils/CustomStackTrace.h printed the layer stack)."""
    import traceback

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    node = layer.fc(x, size=3, name="culprit")
    topo = paddle.topology.Topology([node])
    params = paddle.Parameters.from_topology(topo)
    bad = np.zeros((2, 7), np.float32)  # wrong feature dim -> matmul error
    try:
        topo.forward(params.as_dict(), topo.init_state(), {"x": bad})
        assert False, "expected a shape error"
    except Exception as e:
        text = "".join(traceback.format_exception(e))
        assert "culprit" in text and "type=fc" in text
