"""Evaluator tests (reference: gserver/tests/test_Evaluator.cpp)."""

import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import evaluator, layer
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import Topology


def run_metric(nodes, feeds):
    topo = Topology(nodes if isinstance(nodes, list) else [nodes])
    params = paddle.Parameters.from_topology(topo, seed=0)
    state = topo.init_state()
    outs, _ = topo.forward(params.as_dict(), state, feeds, train=False)
    return [np.asarray(o.data if isinstance(o, SequenceBatch) else o)
            for o in outs]


def make_seq(data, lengths):
    data = np.asarray(data, np.float32)
    seg = np.concatenate([np.full(L, i, np.int32)
                          for i, L in enumerate(lengths)])
    return SequenceBatch(jnp.asarray(data), jnp.asarray(seg),
                         jnp.asarray(np.asarray(lengths, np.int32)),
                         max_len=max(lengths))


def test_rankauc_perfect_and_random():
    paddle.topology.reset_name_scope()
    s = layer.data(name="s", type=paddle.data_type.dense_vector(1))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    m = evaluator.rankauc(s, y)
    score = np.array([[0.9], [0.8], [0.2], [0.1]], np.float32)
    lab = np.array([1, 1, 0, 0], np.int32)
    (auc,) = run_metric(m, {"s": score, "y": lab})
    assert abs(float(auc) - 1.0) < 1e-5
    lab2 = np.array([0, 1, 0, 1], np.int32)
    (auc2,) = run_metric(m, {"s": score, "y": lab2})
    assert 0.0 <= float(auc2) <= 1.0 and float(auc2) < 1.0


def test_chunk_f1_exact_match():
    paddle.topology.reset_name_scope()
    # IOB with 1 chunk type: B=0, I=1, O=2
    pred = layer.data(name="p",
                      type=paddle.data_type.integer_value_sequence(3))
    lab = layer.data(name="l",
                     type=paddle.data_type.integer_value_sequence(3))
    m = evaluator.chunk(pred, lab, num_chunk_types=1)
    tags = np.array([0, 1, 2, 0, 2], np.float32)  # [B I O B O]
    sb_p = make_seq(tags, [5])
    sb_l = make_seq(tags, [5])
    (f1,) = run_metric(m, {"p": sb_p, "l": sb_l})
    assert abs(float(f1) - 1.0) < 1e-5

    # one of two chunks wrong
    tags_bad = np.array([0, 2, 2, 0, 2], np.float32)   # first chunk truncated
    (f1b,) = run_metric(m, {"p": make_seq(tags_bad, [5]), "l": sb_l})
    assert float(f1b) < 1.0


def test_ctc_edit_distance_zero_and_nonzero():
    paddle.topology.reset_name_scope()
    C = 4  # 3 symbols + blank(3)
    probs = layer.data(name="probs",
                       type=paddle.data_type.dense_vector_sequence(C))
    lab = layer.data(name="lab",
                     type=paddle.data_type.integer_value_sequence(3))
    m = evaluator.ctc_edit_distance(probs, lab)

    def onehot(ids):
        x = np.full((len(ids), C), -5.0, np.float32)
        for i, t in enumerate(ids):
            x[i, t] = 5.0
        return x

    # path [1, blank, 2, 2] decodes to [1, 2]; label [1, 2] → distance 0
    p = make_seq(onehot([1, 3, 2, 2]), [4])
    l = make_seq(np.array([1, 2], np.float32), [2])
    (d0,) = run_metric(m, {"probs": p, "lab": l})
    assert abs(float(d0)) < 1e-5

    l2 = make_seq(np.array([1, 0], np.float32), [2])
    (d1,) = run_metric(m, {"probs": p, "lab": l2})
    assert abs(float(d1) - 0.5) < 1e-5  # one substitution / len 2


def test_detection_map_perfect():
    paddle.topology.reset_name_scope()
    K, MB = 4, 2
    det = layer.data(name="det", type=paddle.data_type.dense_vector(K * 6))
    gt = layer.data(name="gt", type=paddle.data_type.dense_vector(MB * 5))
    m = evaluator.detection_map(det, gt, num_classes=3, keep_top_k=K,
                                max_boxes=MB)
    det_rows = np.full((1, K, 6), -1, np.float32)
    det_rows[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
    det_rows[0, 1] = [2, 0.8, 0.5, 0.5, 0.9, 0.9]
    gt_rows = np.array([[[1, 0.1, 0.1, 0.4, 0.4],
                         [2, 0.5, 0.5, 0.9, 0.9]]], np.float32)
    (mp,) = run_metric(m, {"det": det_rows.reshape(1, -1),
                           "gt": gt_rows.reshape(1, -1)})
    assert abs(float(np.ravel(mp)[0]) - 1.0) < 1e-4

    # wrong class detection → mAP drops
    det_rows[0, 1, 0] = 1
    (mp2,) = run_metric(m, {"det": det_rows.reshape(1, -1),
                            "gt": gt_rows.reshape(1, -1)})
    assert float(np.ravel(mp2)[0]) < 1.0


def test_printers_run(capsys):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = layer.data(name="y", type=paddle.data_type.integer_value(4))
    nodes = [evaluator.classification_error_printer(x, y),
             evaluator.seq_text_printer(y),
             evaluator.max_frame_printer(x)]
    outs = run_metric(nodes, {"x": np.eye(4, dtype=np.float32),
                              "y": np.arange(4, dtype=np.int32)})
    for o in outs:
        assert o.shape == (1,)


def test_gradient_printer_passthrough():
    import jax

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3))
    gp = evaluator.gradient_printer(x)
    out = layer.fc(gp, size=1, bias_attr=False)
    topo = Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)
    state = topo.init_state()

    def loss(p, xb):
        outs, _ = topo.forward(p, state, {"x": xb}, train=False)
        return jnp.sum(outs[0])

    xb = np.ones((2, 3), np.float32)
    g = jax.grad(loss)(params.as_dict(), xb)
    w = np.asarray(params[out.name + ".w0"])
    np.testing.assert_allclose(np.asarray(g[out.name + ".w0"]),
                               np.full_like(w, 2.0), atol=1e-5)


def test_column_sum_and_sum():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3))
    fx = np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], np.float32)
    (got,) = run_metric(evaluator.column_sum(x), {"x": fx})
    np.testing.assert_allclose(got, fx.mean(-1))
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3))
    (got2,) = run_metric(evaluator.sum(x), {"x": fx})
    np.testing.assert_allclose(got2, fx.sum(-1))


def test_precision_recall_f1():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(2))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    # preds: 1,1,0,0 ; labels: 1,0,1,0 -> tp=1 fp=1 fn=1 -> P=R=F1=0.5
    logits = np.array([[0., 1.], [0., 1.], [1., 0.], [1., 0.]], np.float32)
    lab = np.array([1, 0, 1, 0], np.int32)
    (f1,) = run_metric(evaluator.precision_recall(x, y),
                       {"x": logits, "y": lab})
    assert abs(float(f1) - 0.5) < 1e-6


def test_pnpair_ratio():
    paddle.topology.reset_name_scope()
    s = layer.data(name="s", type=paddle.data_type.dense_vector(1))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    q = layer.data(name="q", type=paddle.data_type.integer_value(10))
    # query 0: pos scored above neg (correct); query 1: pos below neg
    score = np.array([[0.9], [0.1], [0.2], [0.8]], np.float32)
    lab = np.array([1, 0, 1, 0], np.int32)
    qid = np.array([0, 0, 1, 1], np.int32)
    (ratio,) = run_metric(evaluator.pnpair(s, y, q),
                          {"s": score, "y": lab, "q": qid})
    assert abs(float(ratio) - 0.5) < 1e-6


def test_seq_classification_error():
    paddle.topology.reset_name_scope()
    p = layer.data(name="p", type=paddle.data_type.dense_vector_sequence(3))
    y = layer.data(name="y", type=paddle.data_type.integer_value_sequence(3))
    # seq0: both tokens right; seq1: one token wrong -> errors [0, 1]
    logits = np.eye(3, dtype=np.float32)[[0, 2, 1, 1]]
    sb = make_seq(logits, [2, 2])
    lab = make_seq(np.array([0, 2, 1, 0], np.float32), [2, 2])
    lab = SequenceBatch(lab.data.astype(jnp.int32), lab.segment_ids,
                        lab.lengths, max_len=lab.max_len)
    (err,) = run_metric(evaluator.seq_classification_error(p, y),
                        {"p": sb, "y": lab})
    np.testing.assert_allclose(err[:2], [0.0, 1.0])


def test_value_and_maxid_printers_pass_through(capfd):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3))
    fx = np.array([[0.1, 0.9, 0.0]], np.float32)
    (v,) = run_metric(evaluator.value_printer(x), {"x": fx})
    assert v.shape == (1,)  # printers emit via jax.debug.print
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3))
    (m,) = run_metric(evaluator.maxid_printer(x), {"x": fx})
    assert m.shape == (1,)
    printed = capfd.readouterr().out + capfd.readouterr().err
    assert "0.9" in printed or "1" in printed


def test_auc_mann_whitney():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(2))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    probs = np.array([[0.1, 0.9], [0.2, 0.8], [0.8, 0.2], [0.9, 0.1]],
                     np.float32)
    lab = np.array([1, 1, 0, 0], np.int32)
    (a,) = run_metric(evaluator.auc(x, y), {"x": probs, "y": lab})
    assert abs(float(a) - 1.0) < 1e-6  # perfectly separated
    lab2 = np.array([0, 1, 1, 0], np.int32)
    (a2,) = run_metric(evaluator.auc(x, y), {"x": probs, "y": lab2})
    assert abs(float(a2) - 0.5) < 1e-6  # one concordant, one discordant


def test_every_public_evaluator_is_exercised():
    """Breadth gate: every public evaluator fn must be named by a test
    (reference: test_Evaluator.cpp covers the registered evaluator set)."""
    import inspect
    import os

    from paddle_tpu import evaluator as ev

    names = [n for n, o in vars(ev).items()
             if not n.startswith("_") and inspect.isfunction(o)
             and o.__module__ == "paddle_tpu.evaluator"]
    corpus = open(os.path.abspath(__file__)).read()
    missing = [n for n in names if f"evaluator.{n}" not in corpus]
    assert not missing, f"evaluators with no test: {missing}"
