"""Full optimizer/LR-schedule sweep: every optimizer class pinned to a
numpy oracle of its reference update rule, every LR schedule pinned to
hand-computed values, regularizers/averaging/clipping semantics checked.

Reference analog: paddle/parameter/FirstOrderOptimizer.h (the optimizer
registry) + LearningRateScheduler.cpp:50-172 + the per-op optimizer tests
in python/paddle/v2/framework/tests (test_adam_op.py etc.).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt_mod

RNG = np.random.RandomState(13)


def run_steps(opt, p0, grads_per_step):
    """Drive Optimizer.apply directly on a single parameter tensor."""
    params = {"w": jnp.asarray(p0)}
    state = opt.init_state(params)
    hist = []
    for g in grads_per_step:
        params, state = opt.apply(params, {"w": jnp.asarray(g)}, state)
        hist.append(np.asarray(params["w"]))
    return hist, state


P0 = RNG.randn(5).astype(np.float32)
GRADS = [RNG.randn(5).astype(np.float32) for _ in range(3)]
LR = 0.1


def _oracle(update_fn, slots_init):
    """Run the numpy update rule for 3 steps; returns param history."""
    p = P0.astype(np.float64).copy()
    slots = {k: np.zeros_like(p) if v is None else v
             for k, v in slots_init.items()}
    hist = []
    for t, g in enumerate(GRADS):
        p, slots = update_fn(p, g.astype(np.float64), slots, t)
        hist.append(p.copy())
    return hist


def check(opt, oracle_hist, rtol=1e-5, atol=1e-6):
    hist, _ = run_steps(opt, P0, GRADS)
    for got, want in zip(hist, oracle_hist):
        np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)


def test_sgd_oracle():
    def up(p, g, s, t):
        return p - LR * g, s
    check(opt_mod.Sgd(learning_rate=LR), _oracle(up, {}))


def test_momentum_oracle():
    mu = 0.9

    def up(p, g, s, t):
        v = mu * s["v"] - LR * g
        return p + v, {"v": v}
    check(opt_mod.Momentum(momentum=mu, learning_rate=LR),
          _oracle(up, {"v": None}))


def test_adagrad_oracle():
    eps = 1e-6

    def up(p, g, s, t):
        acc = s["a"] + g * g
        return p - LR * g / (np.sqrt(acc) + eps), {"a": acc}
    check(opt_mod.Adagrad(learning_rate=LR), _oracle(up, {"a": None}))


def test_decayed_adagrad_oracle():
    rho, eps = 0.95, 1e-6

    def up(p, g, s, t):
        acc = rho * s["a"] + (1 - rho) * g * g
        return p - LR * g / np.sqrt(acc + eps), {"a": acc}
    check(opt_mod.DecayedAdagrad(learning_rate=LR), _oracle(up, {"a": None}))


def test_adadelta_oracle():
    rho, eps = 0.95, 1e-6

    def up(p, g, s, t):
        ag = rho * s["ag"] + (1 - rho) * g * g
        dx = -np.sqrt((s["adx"] + eps) / (ag + eps)) * g
        adx = rho * s["adx"] + (1 - rho) * dx * dx
        return p + LR * dx, {"ag": ag, "adx": adx}
    check(opt_mod.AdaDelta(learning_rate=LR),
          _oracle(up, {"ag": None, "adx": None}))


def test_rmsprop_oracle():
    rho, eps = 0.95, 1e-6

    def up(p, g, s, t):
        ag = rho * s["ag"] + (1 - rho) * g * g
        am = rho * s["am"] + (1 - rho) * g
        return p - LR * g / np.sqrt(ag - am * am + eps), {"ag": ag, "am": am}
    check(opt_mod.RMSProp(learning_rate=LR),
          _oracle(up, {"ag": None, "am": None}))


def test_adam_oracle():
    b1, b2, eps = 0.9, 0.999, 1e-8

    def up(p, g, s, t):
        m = b1 * s["m"] + (1 - b1) * g
        v = b2 * s["v"] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** (t + 1))
        vhat = v / (1 - b2 ** (t + 1))
        return p - LR * mhat / (np.sqrt(vhat) + eps), {"m": m, "v": v}
    check(opt_mod.Adam(learning_rate=LR), _oracle(up, {"m": None, "v": None}))


def test_adamax_oracle():
    b1, b2 = 0.9, 0.999

    def up(p, g, s, t):
        m = b1 * s["m"] + (1 - b1) * g
        u = np.maximum(b2 * s["u"], np.abs(g))
        return p - (LR / (1 - b1 ** (t + 1))) * m / (u + 1e-12), \
            {"m": m, "u": u}
    check(opt_mod.Adamax(learning_rate=LR), _oracle(up, {"m": None, "u": None}))


def test_all_optimizers_reduce_quadratic():
    """Every optimizer must make progress on min ||w - w*||^2."""
    target = np.full(5, 3.0, np.float32)
    # AdaDelta is conventionally run at lr~1.0 (its own ratio sets the
    # scale and warms up from sqrt(eps)); everyone else at a common 0.05
    for cls, kw, lr in ((opt_mod.Sgd, {}, 0.05),
                        (opt_mod.Momentum, {"momentum": 0.9}, 0.05),
                        (opt_mod.Adagrad, {}, 0.5),
                        (opt_mod.AdaDelta, {}, 1.0),
                        (opt_mod.RMSProp, {}, 0.05),
                        (opt_mod.DecayedAdagrad, {}, 0.05),
                        (opt_mod.Adam, {}, 0.05),
                        (opt_mod.Adamax, {}, 0.05)):
        opt = cls(learning_rate=lr, **kw)
        params = {"w": jnp.zeros(5)}
        state = opt.init_state(params)
        for _ in range(200):
            g = {"w": 2 * (params["w"] - target)}
            params, state = opt.apply(params, g, state)
        final = float(jnp.sum((params["w"] - target) ** 2))
        assert final < 0.5 * 9.0 * 5, (cls.__name__, final)


# ---------------------------------------------------------------------------
# LR schedules (LearningRateScheduler.cpp:50-172)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("args,step,want", [
    ({}, 7.0, 1.0),
    ({"learning_rate_schedule": "poly", "learning_rate_decay_a": 0.5,
      "learning_rate_decay_b": 2.0}, 6.0, (1 + 0.5 * 6) ** -2),
    ({"learning_rate_schedule": "caffe_poly", "learning_rate_decay_a": 100.0,
      "learning_rate_decay_b": 2.0}, 50.0, (1 - 50 / 100) ** 2),
    ({"learning_rate_schedule": "exp", "learning_rate_decay_a": 0.5,
      "learning_rate_decay_b": 10.0}, 20.0, 0.5 ** 2),
    ({"learning_rate_schedule": "discexp", "learning_rate_decay_a": 0.5,
      "learning_rate_decay_b": 10.0}, 25.0, 0.5 ** 2),
    ({"learning_rate_schedule": "linear", "learning_rate_decay_a": 0.01,
      "learning_rate_decay_b": 0.1}, 50.0, 0.5),
    ({"learning_rate_schedule": "linear", "learning_rate_decay_a": 0.01,
      "learning_rate_decay_b": 0.1}, 500.0, 0.1),
    ({"learning_rate_schedule": "manual",
      "learning_rate_args": "100:1.0,200:0.5,300:0.25"}, 150.0, 0.5),
    ({"learning_rate_schedule": "manual",
      "learning_rate_args": "100:1.0,200:0.5,300:0.25"}, 999.0, 0.25),
])
def test_lr_schedule_values(args, step, want):
    sched = opt_mod.make_lr_schedule(args)
    assert abs(float(sched(jnp.asarray(step))) - want) < 1e-6


def test_lr_schedule_unknown_raises():
    from paddle_tpu.platform.enforce import EnforceError
    with pytest.raises(EnforceError):
        opt_mod.make_lr_schedule({"learning_rate_schedule": "nope"})


# ---------------------------------------------------------------------------
# regularizers / clipping / model averaging
# ---------------------------------------------------------------------------


def test_regularizers_change_update():
    g = [np.zeros(5, np.float32)]
    # with zero gradient, the whole update IS the decay term
    hist_l2, _ = run_steps(
        opt_mod.Sgd(learning_rate=LR,
                    regularization=opt_mod.L2Regularization(rate=0.1)),
        P0, g)
    np.testing.assert_allclose(hist_l2[0], P0 - LR * 0.1 * P0, rtol=1e-6)
    hist_l1, _ = run_steps(
        opt_mod.Sgd(learning_rate=LR,
                    regularization=opt_mod.L1Regularization(rate=0.1)),
        P0, g)
    np.testing.assert_allclose(hist_l1[0], P0 - LR * 0.1 * np.sign(P0),
                               rtol=1e-6)
    both = opt_mod.L1L2Regularization(l1=0.1, l2=0.2)
    hist_12, _ = run_steps(opt_mod.Sgd(learning_rate=LR,
                                       regularization=both), P0, g)
    np.testing.assert_allclose(
        hist_12[0], P0 - LR * (0.1 * np.sign(P0) + 0.2 * P0), rtol=1e-6)


def test_global_clip_scales_update():
    big = np.full(5, 100.0, np.float32)
    clip = opt_mod.Sgd(learning_rate=1.0, gradient_clipping_threshold=1.0)
    hist, _ = run_steps(clip, P0, [big])
    norm = np.linalg.norm(big)
    np.testing.assert_allclose(hist[0], P0 - big / norm, rtol=1e-5)


def test_model_average_tracks_params():
    ma = opt_mod.ModelAverage(average_window=0.1)
    opt = opt_mod.Sgd(learning_rate=LR, model_average=ma)
    hist, state = run_steps(opt, P0, GRADS)
    avg = np.asarray(state["avg"]["w"])
    assert state["avg_count"] == 3
    # the average lags the raw parameter but moves the same direction
    assert np.isfinite(avg).all()
    assert not np.allclose(avg, hist[-1])


def test_every_public_optimizer_name_is_exercised():
    """Breadth gate over the optimizer module's public surface."""
    import inspect
    import os

    names = [n for n, o in vars(opt_mod).items()
             if not n.startswith("_") and inspect.isclass(o)
             and o.__module__ == "paddle_tpu.optimizer"] + ["make_lr_schedule"]
    here = os.path.dirname(os.path.abspath(__file__))
    import glob
    corpus = "".join(open(p).read() for p in
                     glob.glob(os.path.join(here, "test_optimizer*.py")))
    missing = [n for n in names if n not in corpus]
    assert not missing, f"optimizer surface with no test: {missing}"
