"""Fault-tolerant training runtime (paddle_tpu.resilience, ISSUE 14).

Deterministic chaos for the TRAINING side, mirroring what
test_serving_robustness.py does for serving: every recovery path runs
on seeded injection — no sleeps, no real kills.

- TrainFaultPlan: order-independent draws, fire-once kills, the control
  twin contract;
- bad-step guard: in-graph skip leaves params/slots/model-state
  bit-untouched, counters ride the lazy sync contract, ONE compile with
  the fused reduction (sealed retrace pin), rollback hysteresis +
  postmortem + supervisor recovery;
- checkpoint commit protocol: kill between blob write and meta commit
  leaves the previous checkpoint as latest; CKPT-CORRUPT fallback on
  meta-bearing-but-torn dirs; verified-aware pruning never reaps the
  only good artifact;
- AsyncCheckpointer: durable pipelined writes, writer errors surface at
  the next wait;
- step-granular resume: reader-path kill mid-pass resumes to a
  bit-identical trajectory (sync and async saves), elastic path ditto
  with pipelined acks.
"""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import checkpoint as ckpt
from paddle_tpu.platform.enforce import EnforceError
from paddle_tpu.resilience import (AsyncCheckpointer, BadStepGuard,
                                   BadStepRollback, InjectedTrainerDeath,
                                   ManualClock, TrainFaultPlan,
                                   run_supervised)

pytestmark = pytest.mark.resilience


# ---------------------------------------------------------------------------
# helpers — the model/dataset/snapshotters are the chaos scenario's own
# (ONE definition of the pinned model across gate, bench and tests)
# ---------------------------------------------------------------------------

from paddle_tpu.resilience.chaos import (_build_trainer as _build,  # noqa: E402
                                         _dataset as _data,
                                         _slots, _snap as _params)


def _reader(data, batch=8):
    return paddle.batch(lambda: iter(data), batch)


def _assert_tree_equal(a, b, msg=""):
    assert set(a) == set(b), msg
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=f"{msg} {k}")


# ---------------------------------------------------------------------------
# TrainFaultPlan
# ---------------------------------------------------------------------------


def test_fault_plan_draws_are_order_independent():
    """Injection decisions are pure in (seed, step): a resumed run
    re-drawing steps in any order replays the same schedule, and the
    control twin poisons exactly the same steps."""
    a = TrainFaultPlan(seed=7, bad_rate=0.3)
    b = a.control_twin()
    fwd = [a.grad_inject(s) for s in range(40)]
    rev = [a.grad_inject(s) for s in reversed(range(40))][::-1]
    twin = [b.grad_inject(s) for s in range(40)]
    assert fwd == rev == twin
    assert any(v != 0.0 for v in fwd), "rate 0.3 over 40 steps must hit"
    assert not b.kill_at and b.kill_rate == 0.0 and not b.kill_save_at


def test_fault_plan_kills_fire_once():
    plan = TrainFaultPlan(kill_at={3})
    plan.step_begin(2)
    with pytest.raises(InjectedTrainerDeath):
        plan.step_begin(3)
    plan.step_begin(3)   # the resumed re-run of step 3 survives
    plan.step_begin(4)


def test_fault_plan_clock_and_slow_steps():
    clk = ManualClock(tick_s=0.5)
    plan = TrainFaultPlan(clock=clk, slow_steps={1: 4.0})
    plan.step_begin(0)
    assert clk() == 0.5
    plan.step_begin(1)
    assert clk() == 5.0


def test_fault_plan_requires_guard_for_poison():
    with pytest.raises(EnforceError):
        _build(guard=None, faults=TrainFaultPlan(bad_steps={1}))


def test_guard_rejects_nonpositive_rollback_window():
    with pytest.raises(ValueError):
        BadStepGuard(policy="rollback", rollback_after=0)


# ---------------------------------------------------------------------------
# bad-step guard
# ---------------------------------------------------------------------------


def test_skip_leaves_params_slots_and_state_untouched():
    """A poisoned step is a bit-exact no-op on params, optimizer slots
    AND the step counter — the 'NaN never poisons slots' contract."""
    data = _data(n=24)
    plan = TrainFaultPlan(bad_steps={1})
    sgd = _build(guard=BadStepGuard(), faults=plan)
    sgd.train(_reader(data), num_passes=1)
    assert sgd.bad_steps_total == 1

    # twin: identical run whose reader simply omits batch 1 — if the
    # skipped step were anything but a bit-exact no-op (params, slots,
    # step counter), the two trajectories would diverge
    twin = _build(guard=BadStepGuard())
    twin.train(paddle.batch(lambda: iter(data[0:8] + data[16:24]), 8),
               num_passes=1)
    _assert_tree_equal(_params(sgd), _params(twin), "params")
    _assert_tree_equal(_slots(sgd), _slots(twin), "slots")
    assert int(sgd.opt_state["step"]) == int(twin.opt_state["step"]) == 2


def test_guard_max_norm_skips_finite_spikes():
    data = _data(n=16)
    sgd = _build(guard=BadStepGuard(max_norm=1e-9))
    before = _params(sgd)
    sgd.train(_reader(data), num_passes=1)
    assert sgd.bad_steps_total == 2, "every step exceeds a 1e-9 norm cap"
    _assert_tree_equal(_params(sgd), before, "params moved past the cap")


def test_guarded_step_is_one_compile_under_seal():
    """The acceptance pin: the guarded train step — fused bad-step
    reduction included — compiles ONCE; varying the inject scalar across
    steps (0.0 vs NaN) is a value change, not a signature change, so the
    sealed replay adds zero compiles and zero RETRACE diagnostics."""
    from paddle_tpu.analysis.retrace import auditor
    from paddle_tpu.platform.flags import FLAGS

    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    aud = auditor()
    aud.reset()
    try:
        data = _data(n=16)                          # 2 steps per pass
        plan = TrainFaultPlan(bad_steps={1, 3})     # one poison per pass
        sgd = _build(guard=BadStepGuard(), faults=plan)
        sgd.train(_reader(data), num_passes=1)      # warmup: compiles once
        aud.seal("trainer.train_step")
        # steady-state replay, INCLUDING an injection (global step 3):
        # flipping inject 0.0 <-> NaN is a value change, never a compile
        sgd.train(_reader(data), num_passes=1)
        assert aud.compile_count("trainer.train_step") == 1
        aud.assert_no_retraces()
        assert sgd.bad_steps_total == 2
    finally:
        FLAGS.jit_audit = old
        aud.reset()


def test_rollback_policy_raises_and_dumps_postmortem(tmp_path, capsys):
    from paddle_tpu.obs.trace import Tracer

    data = _data(n=40)
    # a persistent bad window >= K
    plan = TrainFaultPlan(bad_steps={1, 2, 3})
    tracer = Tracer(time_fn=lambda: 0.0)
    sgd = _build(guard=BadStepGuard(policy="rollback", rollback_after=3,
                                    check_every=1),
                 faults=plan, tracer=tracer)
    with pytest.raises(BadStepRollback):
        sgd.train(_reader(data), num_passes=1)
    out = capsys.readouterr().out
    assert "OBS-POSTMORTEM" in out
    names = [e.name for e in tracer.events] + [e.name for e in tracer.ring]
    assert "bad_step_rollback" in names


def test_supervisor_recovers_from_rollback(tmp_path):
    """Rollback-to-last-good end to end: the supervisor restarts from
    the newest verified checkpoint; once the transient fault window is
    cleared (on_restart), the run completes with finite params."""
    data = _data(n=40)
    plan = TrainFaultPlan(bad_steps={2, 3, 4})
    save = str(tmp_path / "ck")

    def attempt(i):
        sgd = _build(guard=BadStepGuard(policy="rollback",
                                        rollback_after=3, check_every=1),
                     faults=plan)
        sgd.train(_reader(data), num_passes=2, save_dir=save,
                  save_period_steps=2, resume=True, async_save=False)
        return sgd

    def clear_fault(attempt_no, exc):
        plan.bad_steps.clear()   # the glitch passed

    report, sgd = run_supervised(attempt, max_restarts=3,
                                 on_restart=clear_fault)
    assert report.completed and report.rollbacks == 1
    for k, v in _params(sgd).items():
        assert np.isfinite(v).all(), k


# ---------------------------------------------------------------------------
# checkpoint commit protocol + graceful degradation
# ---------------------------------------------------------------------------


def _save_n(root, n, seed=5):
    sgd = _build(seed=seed)
    for i in range(n):
        ckpt.save_checkpoint(str(root), i, sgd.parameters,
                             opt_state=sgd.opt_state,
                             model_state=sgd.model_state,
                             extra_meta={"tag": i})
    return sgd


def test_kill_between_blob_and_meta_keeps_previous_latest(tmp_path):
    sgd = _save_n(tmp_path, 1)

    class Boom(RuntimeError):
        pass

    def hook(phase):
        if phase == "meta":
            raise Boom()

    with pytest.raises(Boom):
        ckpt.save_checkpoint(str(tmp_path), 1, sgd.parameters,
                             opt_state=sgd.opt_state, commit_hook=hook)
    # both blobs of pass-00001 are durable, meta is not: every reader
    # must keep treating pass-00000 as latest, silently (no corruption)
    assert os.path.exists(ckpt.pass_dir(str(tmp_path), 1) + "/state.pkl")
    assert not os.path.exists(ckpt.pass_dir(str(tmp_path), 1)
                              + "/meta.json")
    assert ckpt.latest_pass(str(tmp_path)) == 0
    _, _, _, meta = ckpt.load_checkpoint(str(tmp_path))
    assert meta["tag"] == 0
    assert ckpt.verify_pass_dir(str(tmp_path), 1) == "missing meta.json"


def test_load_latest_falls_back_over_corrupt_dirs(tmp_path, capsys):
    _save_n(tmp_path, 3)
    # newest: torn blob (the kill-mid-prune / partial-copy case)
    os.remove(ckpt.pass_dir(str(tmp_path), 2) + "/state.pkl")
    # middle: flipped bytes (md5 mismatch)
    with open(ckpt.pass_dir(str(tmp_path), 1) + "/params.tar", "r+b") as f:
        f.seek(40)
        f.write(b"XXXX")
    _, _, _, meta = ckpt.load_checkpoint(str(tmp_path))   # pass_id=None
    assert meta["tag"] == 0, "must fall back to the oldest intact dir"
    out = capsys.readouterr().out
    assert out.count("CKPT-CORRUPT") == 2
    assert "missing state.pkl" in out and "md5 mismatch" in out


def test_explicit_corrupt_load_raises_with_tag(tmp_path, capsys):
    _save_n(tmp_path, 1)
    with open(ckpt.pass_dir(str(tmp_path), 0) + "/state.pkl", "r+b") as f:
        f.write(b"ZZ")
    with pytest.raises(EnforceError, match="CKPT-CORRUPT"):
        ckpt.load_checkpoint(str(tmp_path), 0)


def test_prune_never_reaps_newest_verified(tmp_path):
    """Two corrupt young dirs must not count toward keep: the only good
    artifact survives pruning."""
    _save_n(tmp_path, 3)
    for pid in (1, 2):
        with open(ckpt.pass_dir(str(tmp_path), pid) + "/params.tar",
                  "r+b") as f:
            f.seek(10)
            f.write(b"CORRUPT!")
    ckpt.prune_checkpoints(str(tmp_path), keep=2)
    assert ckpt.verify_pass_dir(str(tmp_path), 0) is None, \
        "the only verified checkpoint was reaped"
    # and with enough verified dirs, old ones (corrupt or not) go
    _save_n(tmp_path, 5)
    ckpt.prune_checkpoints(str(tmp_path), keep=2)
    left = sorted(os.listdir(str(tmp_path)))
    assert left == ["pass-00003", "pass-00004"]


def test_async_checkpointer_durability_and_error_surface(tmp_path):
    sgd = _build()
    ck = AsyncCheckpointer(keep=0)
    ck.save(str(tmp_path), 0, sgd.parameters, opt_state=sgd.opt_state)
    ck.wait()
    assert ck.commits == 1
    assert ckpt.verify_pass_dir(str(tmp_path), 0) is None

    def hook(phase):
        if phase == "state":
            raise InjectedTrainerDeath("writer killed")

    ck.save(str(tmp_path), 1, sgd.parameters, commit_hook=hook)
    with pytest.raises(InjectedTrainerDeath):
        ck.wait()
    ck.wait()   # error is consumed, not sticky
    assert ckpt.latest_pass(str(tmp_path)) == 0


# ---------------------------------------------------------------------------
# step-granular resume parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("async_save", [False, True],
                         ids=["sync", "async"])
def test_midpass_kill_resume_bit_identical(tmp_path, async_save):
    """Kill mid-pass between step checkpoints; resume re-runs the lost
    window with the restored rng + data cursor: final params are
    BIT-identical to an uninterrupted run (the elastic determinism bar
    on the reader path)."""
    data = _data(n=48)                       # 6 steps/pass
    ref = _build()
    ref.train(_reader(data), num_passes=2)

    save = str(tmp_path / f"ck_{async_save}")
    plan = TrainFaultPlan(kill_at={4, 9})

    def attempt(i):
        sgd = _build(faults=plan)
        sgd.train(_reader(data), num_passes=2, save_dir=save,
                  save_period_steps=2, resume=True, async_save=async_save)
        return sgd

    report, got = run_supervised(attempt, max_restarts=4)
    assert report.deaths == 2
    _assert_tree_equal(_params(got), _params(ref), "resume parity")
    _assert_tree_equal(_slots(got), _slots(ref), "slot parity")


def test_async_save_false_overrides_previous_async_train(tmp_path):
    """A later train(async_save=False) on the SAME trainer must not
    silently keep using the previous call's background writer (or its
    old keep budget): the checkpointer is rebuilt per call."""
    data = _data(n=16)
    save = str(tmp_path / "ck")
    sgd = _build()
    sgd.train(_reader(data), num_passes=1, save_dir=save,
              save_period_steps=1, resume=True, async_save=True)
    assert sgd._async_ckpt is not None
    sgd.train(_reader(data), num_passes=1, save_dir=save,
              save_period_steps=1, resume=True, async_save=False)
    assert sgd._async_ckpt is None, "stale async writer leaked"
    assert ckpt.load_latest(save) is not None


def test_exact_boundary_resume_does_not_refire_pass_events(tmp_path):
    """A torn PASS-END save leaves the cursor at (p, steps_per_pass):
    the resumed run must not replay an empty pass p — no duplicate
    BeginPass/EndPass with zeroed metrics — it repairs the boundary
    cursor and continues at pass p+1, bit-identical to a straight run."""
    data = _data(n=48)                      # 6 steps/pass
    save = str(tmp_path / "ck")
    # saves: ck0 after b2, ck1 after b5 (cursor (0, 6) — the exact
    # boundary), then the pass-end ck2 dies between state and meta
    plan = TrainFaultPlan(kill_save_at={2: "meta"})
    sgd_a = _build(faults=plan)
    with pytest.raises(InjectedTrainerDeath):
        sgd_a.train(_reader(data), num_passes=2, save_dir=save,
                    save_period_steps=3, resume=True, async_save=False)

    events = []

    def rec(ev):
        if isinstance(ev, (paddle.event.BeginPass, paddle.event.EndPass)):
            events.append((type(ev).__name__, ev.pass_id))

    sgd_b = _build()
    sgd_b.train(_reader(data), num_passes=2, save_dir=save,
                save_period_steps=3, resume=True, async_save=False,
                event_handler=rec)
    assert events == [("BeginPass", 1), ("EndPass", 1)], events
    ref = _build()
    ref.train(_reader(data), num_passes=2)
    _assert_tree_equal(_params(sgd_b), _params(ref), "boundary resume")


def test_resume_and_start_pass_are_exclusive(tmp_path):
    sgd = _build()
    with pytest.raises(EnforceError):
        sgd.train(_reader(_data()), num_passes=2, resume=True,
                  start_pass=1, save_dir=str(tmp_path))
    # silently ignoring these would restart a supervised run from
    # scratch on every death — they must error like the elastic path
    with pytest.raises(EnforceError):
        sgd.train(_reader(_data()), num_passes=1, resume=True)
    with pytest.raises(EnforceError):
        sgd.train(_reader(_data()), num_passes=1, save_period_steps=2)


# ---------------------------------------------------------------------------
# elastic path: injected deaths + pipelined async acks
# ---------------------------------------------------------------------------


def _write_recordio(tmp_path, data):
    from paddle_tpu.master.recordio import recordio_write

    p = str(tmp_path / "train.recordio")
    recordio_write(p, [(",".join(f"{v:.6f}" for v in x) + f"|{y}").encode()
                       for x, y in data])
    return p


def _parse(rec):
    xs, label = rec.decode().split("|")
    return (np.asarray([float(v) for v in xs.split(",")], np.float32),
            int(label))


def test_elastic_injected_death_resume_parity_async(tmp_path):
    """The kill/resume e2e driven by a TrainFaultPlan instead of an
    event-handler crash, with ASYNC pipelined checkpoints: acks only
    ever cover durable writes, so the replacement trainer's final params
    equal a straight run's."""
    from paddle_tpu.master.client import MasterClient
    from paddle_tpu.master.service import Service

    class Clock:
        t = 1000.0

        def __call__(self):
            return self.t

    clk = Clock()
    data = _data(n=64, seed=3)
    path = _write_recordio(tmp_path, data)

    def fresh():
        svc = Service(chunks_per_task=8, timeout_s=1e6, time_fn=clk)
        svc.set_dataset([path])              # 8 tasks
        return svc

    ref = _build(seed=9)
    ref.train(master=MasterClient(service=fresh()), record_parser=_parse,
              num_passes=1, heartbeat_ttl_s=1e9)

    svc = fresh()
    save = str(tmp_path / "ck")
    plan = TrainFaultPlan(kill_at={5})
    sgd_a = _build(seed=9, faults=plan)
    with pytest.raises(InjectedTrainerDeath):
        sgd_a.train(master=MasterClient(service=svc), record_parser=_parse,
                    num_passes=1, save_dir=save, heartbeat_ttl_s=10.0,
                    saving_period=2, async_save=True)
    assert svc.progress()["pending"] > 0, "the dead trainer holds tasks"
    clk.t += 11.0                            # lease lapses -> requeue

    sgd_b = _build(seed=9)
    sgd_b.train(master=MasterClient(service=svc), record_parser=_parse,
                num_passes=1, save_dir=save, heartbeat_ttl_s=1e9,
                saving_period=2, async_save=True)
    _assert_tree_equal(_params(sgd_b), _params(ref), "elastic parity")
    prog = svc.progress()
    assert prog["pending"] == 0 and prog["todo"] == 0


# ---------------------------------------------------------------------------
# seeded chaos acceptance replay (the bench/gate scenario, pinned here)
# ---------------------------------------------------------------------------


def test_seeded_chaos_acceptance(tmp_path):
    from paddle_tpu.resilience.chaos import seeded_chaos, torn_save_probe

    out = seeded_chaos(str(tmp_path / "chaos"))
    assert out["problems"] == []
    assert out["train_chaos_parity_ok"] == 1
    assert out["train_chaos_deaths"] == 4
    assert out["train_chaos_ckpt_corrupt_surviving"] == 0
    probe = torn_save_probe(str(tmp_path / "torn"))
    assert probe["problems"] == [] and probe["torn_save_ok"] == 1
    # the recovery history landed on the unified scrape surface
    from paddle_tpu.obs import default_registry

    snap = default_registry().snapshot()
    assert snap.get("train_supervised_restarts{kind=death}") == 4.0
    assert snap.get("train_supervised_completed") == 1.0
