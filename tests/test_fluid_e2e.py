"""Fluid end-to-end model tests.

Reference: python/paddle/v2/framework/tests/test_fit_a_line.py,
test_recognize_digits_mlp.py / test_recognize_digits_conv.py,
test_recurrent_op.py — small models trained a few steps must converge.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer


def test_fit_a_line():
    rng = np.random.RandomState(0)
    true_w = rng.randn(13, 1).astype(np.float32)

    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [13])
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1, bias_attr=True)
        cost = layers.square_error_cost(pred, y)
        loss = layers.mean(cost)
        optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)

    exe = fluid.Executor()
    scope = fluid.Scope()
    losses = []
    for step in range(60):
        xb = rng.randn(32, 13).astype(np.float32)
        yb = xb @ true_w
        (l,) = exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss],
                       scope=scope)
        losses.append(float(l))
    assert losses[-1] < 0.05 * losses[0], losses[::10]


def test_recognize_digits_mlp():
    rng = np.random.RandomState(1)

    prog = fluid.Program()
    with fluid.program_guard(prog):
        img = layers.data("img", [64])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(img, size=32, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(logits, label)
        optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)

    # separable synthetic "digits": class leaves a signature block
    def batch(n=64):
        y = rng.randint(0, 4, (n, 1)).astype(np.int64)
        x = rng.randn(n, 64).astype(np.float32) * 0.3
        for i in range(n):
            x[i, y[i, 0] * 16:(y[i, 0] + 1) * 16] += 1.5
        return x, y

    exe = fluid.Executor()
    scope = fluid.Scope()
    acc_v = 0.0
    for step in range(80):
        xb, yb = batch()
        l, acc_v = exe.run(prog, feed={"img": xb, "label": yb},
                           fetch_list=[loss, acc], scope=scope)
    assert float(acc_v) > 0.9, float(acc_v)


def test_recognize_digits_conv():
    rng = np.random.RandomState(2)
    prog = fluid.Program()
    with fluid.program_guard(prog):
        img = layers.data("img", [1, 8, 8])
        label = layers.data("label", [1], dtype="int64")
        c = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                          act="relu")
        p = layers.pool2d(c, pool_size=2)
        logits = layers.fc(p, size=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        optimizer.MomentumOptimizer(learning_rate=0.05,
                                    momentum=0.9).minimize(loss)

    def batch(n=32):
        y = rng.randint(0, 2, (n, 1)).astype(np.int64)
        x = rng.randn(n, 1, 8, 8).astype(np.float32) * 0.2
        x[y[:, 0] == 1, :, 2:6, 2:6] += 1.0
        return x, y

    exe = fluid.Executor()
    scope = fluid.Scope()
    first = None
    for step in range(40):
        xb, yb = batch()
        (l,) = exe.run(prog, feed={"img": xb, "label": yb},
                       fetch_list=[loss], scope=scope)
        if first is None:
            first = float(l)
    assert float(l) < 0.6 * first, (first, float(l))


def test_static_rnn_forward_and_grad():
    """StaticRNN (recurrent op → lax.scan) computes a running sum RNN and
    trains parameters through the scan (test_recurrent_op.py analog)."""
    rng = np.random.RandomState(3)
    T, B, D, H = 5, 4, 3, 6

    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [T, B, D], append_batch_size=False)
        target = layers.data("target", [B, H], append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h_prev = rnn.memory(shape=(B, H), init_value=0.0)
            h = layers.fc([xt, h_prev], size=H, act="tanh",
                          bias_attr=True, name="rnn_fc")
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        outs = rnn()
        # last frame
        last = layers.crop(outs, offsets=[T - 1, 0, 0], shape=[1, B, H])
        last = layers.reshape(last, [B, H])
        loss = layers.mean(layers.square_error_cost(last, target))
        optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    exe = fluid.Executor()
    scope = fluid.Scope()
    xb = rng.randn(T, B, D).astype(np.float32)
    tb = rng.rand(B, H).astype(np.float32) * 0.5
    losses = []
    for _ in range(50):
        (l,) = exe.run(prog, feed={"x": xb, "target": tb},
                       fetch_list=[loss], scope=scope)
        losses.append(float(l))
    assert losses[-1] < 0.2 * losses[0], losses[::10]


def test_uniform_gaussian_random_ops():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        u = prog.global_block().create_var()
        prog.global_block().append_op(
            "uniform_random", outputs={"Out": u},
            attrs={"shape": [1000], "min": -1.0, "max": 1.0})
        g = prog.global_block().create_var()
        prog.global_block().append_op(
            "gaussian_random", outputs={"Out": g},
            attrs={"shape": [1000], "mean": 0.0, "std": 1.0})
    exe = fluid.Executor()
    uv, gv = exe.run(prog, fetch_list=[u, g], scope=fluid.Scope(), seed=42)
    assert -1.0 <= uv.min() and uv.max() <= 1.0
    assert abs(float(gv.mean())) < 0.2 and 0.7 < float(gv.std()) < 1.3


def test_program_printing_and_prune():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [4])
        h = layers.fc(x, size=3, act="relu")
        loss = layers.mean(h)
    s = prog.to_string()
    assert "mul" in s and "param" in s


def test_program_prune_drops_backward():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [4])
        h = layers.fc(x, size=3, act="relu")
        loss = layers.mean(h)
        optimizer.SGDOptimizer(0.1).minimize(loss)
    n_ops_full = len(prog.global_block().ops)
    from paddle_tpu.fluid.framework import prune
    inf = prune(prog, [h])
    kinds = [op.type for op in inf.global_block().ops]
    assert "sgd" not in kinds and not any(k.endswith("_grad") for k in kinds)
    assert len(kinds) < n_ops_full
    # pruned program still runs
    exe = fluid.Executor()
    import numpy as _np
    (out,) = exe.run(inf, feed={"x": _np.ones((2, 4), _np.float32)},
                     fetch_list=[h], scope=fluid.Scope())
    assert out.shape == (2, 3)


def test_ploter_headless():
    from paddle_tpu.plot import Ploter
    pl = Ploter("train", "test")
    pl.append("train", 0, 1.0)
    pl.append("train", 1, 0.5)
    pl.plot()
    assert pl.data["train"][1] == [1.0, 0.5]
    pl.reset()
    assert pl.data["train"][0] == []
