"""recurrent_group tests — the reference's RNN-equivalence strategy
(test_RecurrentGradientMachine.cpp: nested/unrolled configs must match the
dedicated recurrent layers)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.attr import ParamAttr
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.platform.flags import FLAGS


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def _seq_feed(dim, lens, seed=0):
    rng = np.random.RandomState(seed)
    return SequenceBatch.from_list(
        [rng.randn(l, dim).astype(np.float32) * 0.5 for l in lens], capacity=16)


def test_group_matches_recurrent_layer():
    """An Elman RNN written as a recurrent_group must equal layer.recurrent
    when weights are shared by parameter name."""
    paddle.topology.reset_name_scope()
    H = 6
    x = layer.data(name="x", type=paddle.data_type.dense_vector_sequence(H))

    ref = layer.recurrent(input=x, size=H, act="tanh", bias_attr=False,
                          param_attr=ParamAttr(name="shared_w"),
                          name="ref_rnn")

    def step(frame):
        m = layer.memory(name="h_out", size=H)
        proj = layer.fc(input=m, size=H, bias_attr=False,
                        param_attr=ParamAttr(name="shared_w"), name="h_proj")
        return layer.addto(input=[frame, proj], act="tanh", name="h_out")

    grp = layer.recurrent_group(step=step, input=x, name="rg")

    topo = paddle.topology.Topology([ref, grp])
    params = paddle.Parameters.from_topology(topo, seed=11)
    sb = _seq_feed(H, [3, 5])
    outs, _ = topo.forward(params.as_dict(), topo.init_state(), {"x": sb})
    ref_out, grp_out = outs
    np.testing.assert_allclose(np.asarray(ref_out.data)[:8],
                               np.asarray(grp_out.data)[:8],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref_out.lengths),
                               np.asarray(grp_out.lengths))


def test_group_gru_step_matches_grumemory():
    paddle.topology.reset_name_scope()
    H = 4
    x = layer.data(name="x", type=paddle.data_type.dense_vector_sequence(3 * H))

    ref = layer.grumemory(input=x, size=H, name="ref_gru",
                          param_attr=ParamAttr(name="gru_w"), bias_attr=False)

    def step(frame):
        m = layer.memory(name="h", size=H)
        return layer.gru_step(input=frame, output_mem=m, size=H,
                              param_attr=ParamAttr(name="gru_w"),
                              bias_attr=False, name="h")

    grp = layer.recurrent_group(step=step, input=x, name="rg_gru")

    topo = paddle.topology.Topology([ref, grp])
    params = paddle.Parameters.from_topology(topo, seed=3)
    sb = _seq_feed(3 * H, [2, 4], seed=5)
    outs, _ = topo.forward(params.as_dict(), topo.init_state(), {"x": sb})
    np.testing.assert_allclose(np.asarray(outs[0].data)[:6],
                               np.asarray(outs[1].data)[:6],
                               rtol=1e-5, atol=1e-5)


def test_group_with_static_and_boot():
    """Static inputs are visible every frame; boot layer initializes memory."""
    paddle.topology.reset_name_scope()
    H = 4
    x = layer.data(name="x", type=paddle.data_type.dense_vector_sequence(H))
    ctx_in = layer.data(name="ctx", type=paddle.data_type.dense_vector(H))

    def step(frame, static_ctx):
        m = layer.memory(name="acc", size=H, boot_layer=ctx_in)
        s = layer.addto(input=[frame, m], name="acc_pre")
        out = layer.addto(input=[s, static_ctx], name="acc")
        return out

    grp = layer.recurrent_group(
        step=step, input=[x, layer.StaticInput(ctx_in)], name="rg_static")

    topo = paddle.topology.Topology([grp])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sb = SequenceBatch.from_list(
        [np.ones((2, H), np.float32), np.ones((3, H), np.float32)], capacity=8)
    ctx_val = jnp.full((2, H), 10.0)
    outs, _ = topo.forward(params.as_dict(), topo.init_state(),
                           {"x": sb, "ctx": ctx_val})
    out = outs[0]
    padded, mask = out.to_padded()
    got = np.asarray(padded)[..., 0]
    # recurrence: m_0 = 10; acc_t = (x + m) + ctx = prev + 11
    np.testing.assert_allclose(got[0, :2], [21.0, 32.0])
    np.testing.assert_allclose(got[1, :3], [21.0, 32.0, 43.0])


def test_group_trains_with_grad():
    """Gradients flow through the scan (autodiff through recurrent_group)."""
    import jax

    paddle.topology.reset_name_scope()
    H = 4
    x = layer.data(name="x", type=paddle.data_type.dense_vector_sequence(H))
    lab = layer.data(name="label", type=paddle.data_type.integer_value(2))

    def step(frame):
        m = layer.memory(name="h", size=H)
        proj = layer.fc(input=[frame, m], size=H, act="tanh", name="h")
        return proj

    grp = layer.recurrent_group(step=step, input=x, name="rg_t")
    last = layer.last_seq(input=grp)
    logits = layer.fc(input=last, size=2, name="out_fc")
    cost = layer.classification_cost(input=logits, label=lab)

    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=1)
    sb = _seq_feed(H, [3, 4], seed=9)
    labels = jnp.array([0, 1])

    def loss_fn(p):
        outs, _ = topo.forward(p, topo.init_state(), {"x": sb, "label": labels},
                               train=True, rng=jax.random.PRNGKey(0))
        return jnp.mean(outs[0])

    grads = jax.grad(loss_fn)(params.as_dict())
    gnorms = {k: float(jnp.linalg.norm(v)) for k, v in grads.items()}
    # the RECURRENT fc weights specifically must receive gradient — a broken
    # scan carry would still give out_fc a gradient from the last frame
    rec_keys = [k for k in gnorms if "h.w" in k or k.endswith("h.w0")]
    assert rec_keys, gnorms
    assert any(gnorms[k] > 1e-8 for k in rec_keys), gnorms
    assert all(np.isfinite(list(gnorms.values())))


def test_group_state_shared_with_generation_host():
    """Batch-norm moving stats learned inside a training recurrent_group must
    flow into an inference host built from the same stably-named step (the
    state analog of pinned param names)."""
    paddle.topology.reset_name_scope()
    D = 4

    def make_step():
        def step(frame):
            h = layer.fc(input=frame, size=D, act="linear", name="gs_fc",
                         param_attr=ParamAttr(name="gs_w"), bias_attr=False)
            return layer.batch_norm(input=h, name="gs_bn")
        return step

    x = layer.data(name="gx", type=paddle.data_type.dense_vector_sequence(D))
    lab = layer.data(name="glab",
                     type=paddle.data_type.dense_vector_sequence(D))
    out = layer.recurrent_group(step=make_step(), input=x, name="train_grp")
    cost = layer.square_error_cost(input=out, label=lab, name="gs_cost")

    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    state = topo.init_state()
    assert "gs_bn" in state, "bn state must live under the sub-layer name"

    feeds = {"gx": _seq_feed(D, [3, 5], seed=1),
             "glab": _seq_feed(D, [3, 5], seed=2)}
    _, state = topo.forward(params.as_dict(), state, feeds, train=True)
    moved = np.asarray(state["gs_bn"]["moving_mean"])
    assert np.abs(moved).sum() > 0, "training did not update moving stats"

    # fresh trace of the same step hosted by a new group (generation-style)
    x2 = layer.data(name="gx2", type=paddle.data_type.dense_vector_sequence(D))
    gen_out = layer.recurrent_group(step=make_step(), input=x2, name="gen_grp")
    inf = paddle.inference.Inference(gen_out, params, model_state=state)
    assert np.allclose(np.asarray(inf.model_state["gs_bn"]["moving_mean"]),
                       moved), "trained stats must reach the generation host"
    got = inf._fn(params.as_dict(), inf.model_state,
                  {"gx2": _seq_feed(D, [4], seed=3)})
    assert np.all(np.isfinite(np.asarray(got[0].data)))


def test_group_unequal_inlink_lengths_masked():
    """Frames past a sample's shortest in-link must be zeroed and excluded
    from the output lengths (combined-mask semantics)."""
    paddle.topology.reset_name_scope()
    D = 3
    a = layer.data(name="ua", type=paddle.data_type.dense_vector_sequence(D))
    b = layer.data(name="ub", type=paddle.data_type.dense_vector_sequence(D))

    def step(fa, fb):
        return layer.addto(input=[fa, fb], name="u_add")

    out = layer.recurrent_group(step=step, input=[a, b], name="u_grp")
    topo = paddle.topology.Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)

    fa = _seq_feed(D, [5, 2], seed=4)
    fb = _seq_feed(D, [3, 5], seed=5)
    (res,), _ = topo.forward(params.as_dict(), topo.init_state(),
                             {"ua": fa, "ub": fb})
    lens = np.asarray(res.lengths)
    assert list(lens[:2]) == [3, 2], f"combined lengths wrong: {lens}"
    padded, _ = res.to_padded()
    padded = np.asarray(padded)
    assert np.all(padded[0, 3:] == 0) and np.all(padded[1, 2:] == 0)


def test_hierarchical_group_matches_numpy_oracle():
    """SubsequenceInput: the outer loop steps over INNER sequences; the
    step pools each sentence and runs an Elman recurrence over sentence
    vectors (reference: sequence_nest_rnn configs,
    test_RecurrentGradientMachine.cpp). Compared against a numpy oracle."""
    import jax.numpy as jnp

    paddle.topology.reset_name_scope()
    D, H = 3, 3
    x = layer.data(name="x",
                   type=paddle.data_type.dense_vector_sub_sequence(D))

    def step(sentence):
        pooled = layer.pooling(input=sentence,
                               pooling_type=paddle.pooling.AvgPooling())
        m = layer.memory(name="h_out", size=H)
        proj = layer.fc(input=m, size=H, bias_attr=False,
                        param_attr=ParamAttr(name="nest_w"), name="h_proj")
        return layer.addto(input=[pooled, proj], act="tanh", name="h_out")

    grp = layer.recurrent_group(
        step=step, input=layer.SubsequenceInput(x, max_inner=3,
                                                max_inner_len=4),
        name="rg_nest")
    topo = paddle.topology.Topology([grp])
    params = paddle.Parameters.from_topology(topo, seed=4)

    rng = np.random.RandomState(2)
    toks = rng.randn(7, D).astype(np.float32) * 0.5
    # outer0: sentences [0:2], [2:5]; outer1: sentence [5:7]
    sb = SequenceBatch(
        jnp.asarray(toks), jnp.asarray([0, 0, 0, 0, 0, 1, 1], np.int32),
        jnp.asarray([5, 2], np.int32),
        sub_segment_ids=jnp.asarray([0, 0, 1, 1, 1, 0, 0], np.int32),
        max_len=5)
    outs, _ = topo.forward(params.as_dict(), topo.init_state(), {"x": sb})
    got = outs[0]
    np.testing.assert_array_equal(np.asarray(got.lengths), [2, 1])

    W = np.asarray(params["nest_w"])

    def oracle(sentences):
        h = np.zeros(H, np.float32)
        res = []
        for s in sentences:
            h = np.tanh(s.mean(0) + h @ W)
            res.append(h.copy())
        return np.stack(res)

    want0 = oracle([toks[0:2], toks[2:5]])
    want1 = oracle([toks[5:7]])
    d = np.asarray(got.data)
    seg = np.asarray(got.segment_ids)
    np.testing.assert_allclose(d[seg == 0], want0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(d[seg == 1], want1, rtol=1e-5, atol=1e-6)


def test_hierarchical_group_trains_with_grad():
    """Gradients flow through the nested scan (autodiff through the
    hierarchical group), incl. the recurrent weight."""
    import jax
    import jax.numpy as jnp

    paddle.topology.reset_name_scope()
    D, H = 3, 3
    x = layer.data(name="x",
                   type=paddle.data_type.dense_vector_sub_sequence(D))
    lab = layer.data(name="label", type=paddle.data_type.integer_value(2))

    def step(sentence):
        pooled = layer.pooling(input=sentence)
        m = layer.memory(name="h2", size=H)
        nh = layer.fc(input=[pooled, m], size=H, act="tanh", name="h2")
        return nh

    grp = layer.recurrent_group(
        step=step, input=layer.SubsequenceInput(x, max_inner=3,
                                                max_inner_len=4),
        name="rg_nest_t")
    logits = layer.fc(input=layer.last_seq(grp), size=2, name="out_fc")
    cost = layer.classification_cost(input=logits, label=lab)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=1)

    rng = np.random.RandomState(3)
    toks = rng.randn(7, D).astype(np.float32)
    sb = SequenceBatch(
        jnp.asarray(toks), jnp.asarray([0, 0, 0, 0, 0, 1, 1], np.int32),
        jnp.asarray([5, 2], np.int32),
        sub_segment_ids=jnp.asarray([0, 0, 1, 1, 1, 0, 0], np.int32),
        max_len=5)
    labels = jnp.asarray([0, 1], jnp.int32)

    def loss_fn(p):
        outs, _ = topo.forward(p, topo.init_state(),
                               {"x": sb, "label": labels}, train=True,
                               rng=jax.random.PRNGKey(0))
        return jnp.mean(outs[0])

    grads = jax.grad(loss_fn)(params.as_dict())
    rec = [k for k in grads if "h2.w" in k]
    assert rec, list(grads)
    for k in rec:
        assert float(jnp.linalg.norm(grads[k])) > 0


def test_hierarchical_group_trains_end_to_end():
    """Full v2 path for a hierarchical model: reader yields nested lists
    (document = list of sentences), the feeder builds the nested
    SequenceBatch, SGD.train converges on a separable document task."""
    from paddle_tpu import optimizer, trainer

    paddle.topology.reset_name_scope()
    D, H = 4, 6
    x = layer.data(name="x",
                   type=paddle.data_type.dense_vector_sub_sequence(D))
    lab = layer.data(name="label", type=paddle.data_type.integer_value(2))

    def step(sentence):
        pooled = layer.pooling(input=sentence,
                               pooling_type=paddle.pooling.AvgPooling())
        m = layer.memory(name="hdoc", size=H)
        return layer.fc(input=[pooled, m], size=H, act="tanh", name="hdoc")

    grp = layer.recurrent_group(
        step=step, input=layer.SubsequenceInput(x, max_inner=4,
                                                max_inner_len=6),
        name="rg_doc")
    logits = layer.fc(input=layer.last_seq(grp), size=2)
    cost = layer.classification_cost(input=logits, label=lab)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=3e-2))

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(96):
            label = int(rng.randint(2))
            mean = 0.8 if label else -0.8
            n_sent = rng.randint(1, 4)
            doc = [(rng.randn(rng.randint(2, 6), D) * 0.3 + mean).tolist()
                   for _ in range(n_sent)]
            yield doc, label

    costs = []
    sgd.train(paddle.batch(reader, 8), num_passes=4,
              event_handler=lambda ev: costs.append(float(ev.cost))
              if isinstance(ev, paddle.event.EndIteration) else None)
    assert costs[-1] < 0.35 * costs[0], (costs[0], costs[-1])


def test_hierarchical_group_nested_sequence_output():
    """NEST_SEQUENCE output mode: the step returns the TRANSFORMED inner
    sequence (tokenwise fc conditioned on the previous sentence's pooled
    memory); the group's output is a nested SequenceBatch mirroring the
    input structure. Oracle-matched."""
    import jax.numpy as jnp

    paddle.topology.reset_name_scope()
    D = 3
    x = layer.data(name="x",
                   type=paddle.data_type.dense_vector_sub_sequence(D))

    def step(sentence):
        m = layer.memory(name="sent_pool", size=D)
        # tokenwise: every word of this sentence + previous sentence's mean
        shifted = layer.addto(
            input=[sentence, layer.expand(m, sentence)], name="tok_out")
        pooled = layer.pooling(input=sentence,
                               pooling_type=paddle.pooling.AvgPooling(),
                               name="sent_pool")
        return [shifted, pooled]

    outs = layer.recurrent_group(
        step=step, input=layer.SubsequenceInput(x, max_inner=3,
                                                max_inner_len=4),
        name="rg_nest_seq")
    tok_out = outs[0]
    topo = paddle.topology.Topology([tok_out])
    params = paddle.Parameters.from_topology(topo, seed=0)

    rng = np.random.RandomState(5)
    toks = rng.randn(7, D).astype(np.float32)
    sb = SequenceBatch(
        jnp.asarray(toks), jnp.asarray([0, 0, 0, 0, 0, 1, 1], np.int32),
        jnp.asarray([5, 2], np.int32),
        sub_segment_ids=jnp.asarray([0, 0, 1, 1, 1, 0, 0], np.int32),
        max_len=5)
    got, _ = topo.forward(params.as_dict(), topo.init_state(), {"x": sb})
    got = got[0]
    assert got.sub_segment_ids is not None
    np.testing.assert_array_equal(np.asarray(got.lengths), [5, 2])
    np.testing.assert_array_equal(np.asarray(got.segment_ids)[:7],
                                  [0, 0, 0, 0, 0, 1, 1])
    np.testing.assert_array_equal(np.asarray(got.sub_segment_ids)[:7],
                                  [0, 0, 1, 1, 1, 0, 0])

    # oracle: sentence s tokens + mean of sentence s-1 (zero for s=0)
    def oracle(sentences):
        prev = np.zeros(D, np.float32)
        rows = []
        for s in sentences:
            rows.append(s + prev)
            prev = s.mean(0)
        return np.concatenate(rows)

    want = np.concatenate([oracle([toks[0:2], toks[2:5]]),
                           oracle([toks[5:7]])])
    np.testing.assert_allclose(np.asarray(got.data)[:7], want, rtol=1e-5,
                               atol=1e-6)


def test_sequence_memory_carries_previous_sentence():
    """memory(is_seq=True): the step sees the PREVIOUS inner sequence as a
    sequence (reference: seq-level memory in nested configs) — here each
    sentence output is its own mean plus max-pool of the previous raw
    sentence."""
    import jax.numpy as jnp

    paddle.topology.reset_name_scope()
    D = 3
    x = layer.data(name="x",
                   type=paddle.data_type.dense_vector_sub_sequence(D))

    def step(sentence):
        prev_seq = layer.memory(name="raw_out", size=D, is_seq=True)
        prev_max = layer.pooling(input=prev_seq,
                                 pooling_type=paddle.pooling.MaxPooling())
        cur_mean = layer.pooling(input=sentence,
                                 pooling_type=paddle.pooling.AvgPooling())
        out = layer.addto(input=[cur_mean, prev_max], name="vec_out")
        # expose the raw sentence as the memory's link target
        raw = layer.get_output(sentence, name="raw_out")
        return [out, raw]

    outs = layer.recurrent_group(
        step=step, input=layer.SubsequenceInput(x, max_inner=3,
                                                max_inner_len=4),
        name="rg_seqmem")
    vec = outs[0]
    topo = paddle.topology.Topology([vec])
    params = paddle.Parameters.from_topology(topo, seed=0)

    rng = np.random.RandomState(8)
    toks = rng.randn(7, D).astype(np.float32)
    sb = SequenceBatch(
        jnp.asarray(toks), jnp.asarray([0, 0, 0, 0, 0, 1, 1], np.int32),
        jnp.asarray([5, 2], np.int32),
        sub_segment_ids=jnp.asarray([0, 0, 1, 1, 1, 0, 0], np.int32),
        max_len=5)
    got, _ = topo.forward(params.as_dict(), topo.init_state(), {"x": sb})
    got = got[0]
    np.testing.assert_array_equal(np.asarray(got.lengths), [2, 1])

    def oracle(sentences):
        prev = np.zeros((1, D), np.float32)
        res = []
        for s in sentences:
            res.append(s.mean(0) + prev.max(0))
            prev = s
        return np.stack(res)

    d = np.asarray(got.data)
    seg = np.asarray(got.segment_ids)
    np.testing.assert_allclose(d[seg == 0],
                               oracle([toks[0:2], toks[2:5]]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(d[seg == 1], oracle([toks[5:7]]),
                               rtol=1e-5, atol=1e-6)
