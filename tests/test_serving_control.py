"""Multi-tenant SLO control plane (round 17).

Tenant classes / quotas / preemption precedence, weighted-fair
admission isolating a seeded tenant storm, role-aware autoscaling with
scale-up-under-kill chaos, tenant identity across resubmit/migration,
per-tenant scrape labels, and the CONTROL-LEAK admission-ledger
conservation — all on ONE injected clock, no wall-clock sleeps.
"""

import jax
import numpy as np
import pytest

from paddle_tpu.obs.registry import MetricsRegistry
from paddle_tpu.platform.enforce import EnforceError
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (AdmissionLedger, AutoscalePolicy, DecoderLM,
                                FleetFaultPlan, FleetRouter, ManualClock,
                                ReplicaState, RequestStatus, ServingEngine,
                                TenantRegistry, WeightedFairQueue,
                                check_control_conservation, export_chain,
                                import_chain)
from paddle_tpu.serving.scheduler import Request

from conftest import assert_serving_drained as assert_drained  # noqa: E402

serving = pytest.mark.serving
faults = pytest.mark.faults
fleet_mark = pytest.mark.fleet
control = pytest.mark.control

pytestmark = [serving, faults, fleet_mark, control]

PAGE = 4
EOS = 1


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


@pytest.fixture(scope="module")
def model_params():
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=128)
    return model, model.init_params(jax.random.PRNGKey(0))


def _make_fleet(model, params, n=2, plan=None, **kw):
    if plan is None:
        plan = FleetFaultPlan(clock=ManualClock(tick_s=0.01))
    engine_kw = dict(eos_id=EOS, page_size=PAGE, num_pages=32,
                     max_pages_per_seq=8, max_slots=2, buckets=(4, 8))
    engine_kw.update(kw.pop("engine_kw", {}))
    kw.setdefault("heartbeat_s", 0.05)
    kw.setdefault("resubmit_budget", 2)

    def mk(i, time_fn):
        return ServingEngine(model, params, time_fn=time_fn, **engine_kw)

    return FleetRouter(mk, n, faults=plan, **kw), plan


def _prompts(rng, n, shared=0, lo=3, hi=9):
    sysp = rng.randint(2, 50, size=shared).tolist() if shared else []
    return [sysp + rng.randint(2, 50, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]


def _drain_all(fl, max_ticks=400):
    out = fl.run(max_ticks=max_ticks)
    assert not fl.has_work, "fleet failed to drain"
    return out


# ---------------------------------------------------------------------------
# tenant registry: classes, overrides, quotas on the injected clock
# ---------------------------------------------------------------------------


def test_default_classes_and_auto_register():
    reg = TenantRegistry()
    reg.register("alice", "interactive")
    reg.register("bulk", "batch")
    assert reg.deadline_s("alice") == 0.5
    assert reg.deadline_s("bulk") is None          # batch: no deadline
    assert reg.weight("alice") > reg.weight("bulk")
    assert reg.precedence("bulk") > reg.precedence("alice")
    # unknown tenants auto-register as standard on first touch
    assert reg.deadline_s("nobody") == 2.0
    assert "nobody" in reg.tenants()


def test_per_tenant_deadline_override_beats_class_default():
    reg = TenantRegistry()
    reg.register("vip", "interactive", deadline_s=0.1)
    assert reg.deadline_s("vip") == 0.1


def test_registry_from_flag_parses_pairs_and_bare_names():
    reg = TenantRegistry.from_flag("alice:interactive, bulk:batch, eve")
    assert reg.deadline_s("alice") == 0.5
    assert reg.deadline_s("bulk") is None
    assert reg.spec("eve").cls.name == "standard"
    with pytest.raises(EnforceError):
        TenantRegistry.from_flag("x:warp9")


def test_token_bucket_refills_on_injected_clock_and_caps_at_burst():
    reg = TenantRegistry()
    reg.register("m", "standard", quota_tokens_per_s=10.0, burst_tokens=20.0)
    # bucket starts full (burst): two 10-token takes pass, a third fails
    assert reg.admit_quota("m", 10, now=0.0)
    assert reg.admit_quota("m", 10, now=0.0)
    assert not reg.admit_quota("m", 10, now=0.0)
    # 0.5s at 10 tok/s refills 5 — still short of 10
    assert not reg.admit_quota("m", 10, now=0.5)
    # long idle refills to the burst cap, no further
    assert reg.admit_quota("m", 20, now=100.0)
    assert not reg.admit_quota("m", 1, now=100.0)
    # unmetered tenants always pass
    assert reg.admit_quota("free", 10 ** 9, now=0.0)


# ---------------------------------------------------------------------------
# WFQ: virtual-time order, storm isolation, removal
# ---------------------------------------------------------------------------


def test_wfq_serves_by_weighted_virtual_time():
    q = WeightedFairQueue()
    # equal cost, alice at 4x bob's weight: alice's finish tags pack 4x
    # denser, so she gets ~4 of every 5 service slots
    for i in range(8):
        q.push("alice", 8, 4.0, ("a", i))
        q.push("bob", 8, 1.0, ("b", i))
    order = [q.pop()[0] for _ in range(10)]
    assert order.count("alice") >= 6
    # both make progress — WFQ never starves the light tenant entirely
    assert order.count("bob") >= 1


def test_wfq_storm_backlogs_only_the_storming_tenant():
    q = WeightedFairQueue()
    for i in range(50):
        q.push("storm", 8, 1.0, ("s", i))      # 10x the polite tenants
    for i in range(5):
        q.push("alice", 8, 1.0, ("a", i))
        q.push("bob", 8, 1.0, ("b", i))
    served = [q.pop() for _ in range(20)]
    tenants = [t for t, _ in served]
    # every polite item clears within the first 20 slots; the storm's
    # backlog is entirely its own
    assert tenants.count("alice") == 5 and tenants.count("bob") == 5
    assert set(q.backlog()) == {"storm"}


def test_wfq_remove_and_expire_return_their_tenants():
    q = WeightedFairQueue()
    q.push("a", 4, 1.0, "x")
    q.push("a", 4, 1.0, "y")
    q.push("b", 4, 1.0, "z")
    assert q.remove("y") == "a"
    assert q.remove("y") is None
    gone = q.expire(lambda item: item == "z")
    assert gone == [("b", "z")]
    assert len(q) == 1 and q.pop() == ("a", "x")


def test_admission_ledger_flags_an_unbalanced_partition():
    led = AdmissionLedger()
    led.on_submit("t")
    led.on_submit("t")
    led.on_admit("t")
    assert led.problems()                       # 2 != 1 + 0 + 0
    led.on_shed("t")
    assert not led.problems()
    assert led.snapshot()["t"]["shed"] == 1


# ---------------------------------------------------------------------------
# fleet integration: quotas, class deadlines, WFQ isolation under storm
# ---------------------------------------------------------------------------


def test_fleet_quota_defers_over_budget_submits(model_params):
    reg = TenantRegistry()
    reg.register("metered", "batch", quota_tokens_per_s=1.0,
                 burst_tokens=12.0)
    fl, _ = _make_fleet(*model_params, n=1, tenants=reg)
    ok = fl.submit([2, 3, 4, 5], max_tokens=4, tenant="metered")   # 8 <= 12
    over = fl.submit([2, 3, 4, 5], max_tokens=4, tenant="metered")
    assert fl.status(over) is RequestStatus.REJECTED
    assert fl.ledger.quota_deferred["metered"] == 1
    _drain_all(fl)
    assert fl.status(ok) is RequestStatus.COMPLETED
    check_control_conservation(fl)


def test_class_deadline_stamped_when_submit_has_none(model_params):
    reg = TenantRegistry()
    reg.register("vip", "interactive")
    reg.register("bulk", "batch")
    fl, _ = _make_fleet(*model_params, n=1, tenants=reg)
    t0 = fl._time()
    a = fl.submit([2, 3, 4], max_tokens=2, tenant="vip")
    b = fl.submit([2, 3, 4], max_tokens=2, tenant="bulk")
    c = fl.submit([2, 3, 4], max_tokens=2, tenant="vip", deadline_s=9.0)
    assert fl._requests[a].deadline_at == pytest.approx(t0 + 0.5)
    assert fl._requests[b].deadline_at is None     # batch: unbounded
    assert fl._requests[c].deadline_at == pytest.approx(t0 + 9.0)
    _drain_all(fl)


def test_wfq_isolates_non_storming_tenants_deadlines(model_params):
    """The tentpole behavior: under a one-tenant prompt storm, WFQ-on
    keeps every NON-storming tenant's deadline misses at zero — the
    storm's backlog is charged to the storming tenant alone."""
    model, params = model_params
    reg = TenantRegistry()
    reg.register("alice", "interactive", deadline_s=0.6)
    reg.register("bob", "standard", deadline_s=0.6)
    reg.register("storm", "batch")
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.02),
                          tenant_storm=("storm", 0, 6, 10))
    fl, _ = _make_fleet(model, params, n=2, plan=plan, tenants=reg,
                        wfq=True)
    rng = np.random.RandomState(0)
    tick = 0
    while tick < 6 or fl.has_work:
        if tick < 6 and tick % 2 == 0:
            for tenant in ("alice", "bob", "storm"):
                for _ in range(plan.storm_factor(tick, tenant)):
                    fl.submit(rng.randint(2, 50, size=6).tolist(),
                              max_tokens=3, tenant=tenant)
        fl.step()
        tick += 1
        assert tick < 600, "fleet failed to drain"
    check_control_conservation(fl)
    tenants = fl.healthz()["tenants"]
    assert tenants["alice"]["deadline_misses"] == 0
    assert tenants["bob"]["deadline_misses"] == 0
    led = fl.ledger.snapshot()
    assert led["storm"]["submitted"] > led["alice"]["submitted"] * 5


def test_wfq_buffered_requests_expire_and_cancel_balance_ledger(
        model_params):
    reg = TenantRegistry()
    fl, plan = _make_fleet(*model_params, n=1, tenants=reg, wfq=True)
    # saturate the engine so later submits stay buffered in the WFQ
    busy = [fl.submit([2, 3, 4, 5], max_tokens=6, tenant="t")
            for _ in range(4)]
    fl.step()
    doomed = fl.submit([2, 3, 4], max_tokens=2, tenant="t", deadline_s=0.01)
    victim = fl.submit([2, 3, 4, 5], max_tokens=2, tenant="t")
    assert len(fl.wfq) >= 2
    assert fl.cancel(victim) is True
    assert fl.status(victim) is RequestStatus.CANCELLED
    for _ in range(3):                  # past doomed's 0.01s deadline
        fl.step()
    assert fl.status(doomed) is RequestStatus.TIMED_OUT
    _drain_all(fl)
    check_control_conservation(fl)      # ledger: shed covers both exits
    assert fl.ledger.shed["t"] == 2
    assert all(fl.status(f) is RequestStatus.COMPLETED for f in busy)


# ---------------------------------------------------------------------------
# preemption precedence: batch slots are victimized before interactive
# ---------------------------------------------------------------------------


def test_precedence_fn_bound_to_every_replica_incl_late_joins(model_params):
    reg = TenantRegistry()
    fl, _ = _make_fleet(*model_params, n=1, tenants=reg)
    assert fl.replicas[0].engine.scheduler.precedence_fn == reg.precedence
    idx = fl.add_replica()
    assert fl.replicas[idx].engine.scheduler.precedence_fn == reg.precedence


def test_victim_selection_prefers_batch_over_older_interactive(
        model_params):
    reg = TenantRegistry()
    fl, _ = _make_fleet(*model_params, n=1, tenants=reg)
    sched = fl.replicas[0].engine.scheduler
    # batch request is OLDER — pure youngest-first would pick the
    # interactive one; precedence must override
    batch = Request(prompt=[2, 3], max_tokens=2, tenant="bulk")
    batch.submitted_at, batch.slot = 1.0, 0
    inter = Request(prompt=[2, 3], max_tokens=2, tenant="vip")
    inter.submitted_at, inter.slot = 2.0, 1
    reg.register("bulk", "batch")
    reg.register("vip", "interactive")
    sched.running = {0: batch, 1: inter}
    probe = Request(prompt=[2], max_tokens=1, tenant="vip")
    assert sched._youngest_victim(exclude=probe) is batch
    # without a control plane, classic youngest-first returns
    sched.precedence_fn = None
    assert sched._youngest_victim(exclude=probe) is inter


# ---------------------------------------------------------------------------
# tenant identity survives resubmit and migration
# ---------------------------------------------------------------------------


def test_tenant_survives_death_resubmit(model_params):
    model, params = model_params
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                          kill_at={3: 0})
    fl, _ = _make_fleet(model, params, n=2, plan=plan)
    rng = np.random.RandomState(0)
    frids = [fl.submit(rng.randint(2, 50, size=5).tolist(), max_tokens=4,
                       tenant="carol") for _ in range(3)]
    _drain_all(fl)
    assert fl.metrics.resubmits >= 1
    for frid in frids:
        assert fl._requests[frid].tenant == "carol"
    # the SURVIVOR's engine billed carol, not default
    survivor = fl.replicas[1].engine
    assert set(survivor.tenant_counts()) <= {"carol"}
    assert fl.metrics.tenant_tokens.get("carol", 0) > 0
    check_control_conservation(fl)


def test_tenant_rides_the_migration_blob(model_params):
    model, params = model_params
    clock = ManualClock(tick_s=0.01)
    src = ServingEngine(model, params, eos_id=EOS, page_size=PAGE,
                        num_pages=32, max_pages_per_seq=8, max_slots=2,
                        buckets=(4, 8), time_fn=clock)
    dst = ServingEngine(model, params, eos_id=EOS, page_size=PAGE,
                        num_pages=32, max_pages_per_seq=8, max_slots=2,
                        buckets=(4, 8), time_fn=clock)
    rid = src.submit([2, 3, 4, 5, 6], max_tokens=6, tenant="mover")
    for _ in range(30):
        clock.advance(clock.tick_s)
        src.step()
        if rid in src.migratable_rids():
            break
    blob = export_chain(src, rid)
    assert blob.tenant == "mover"
    rid2 = import_chain(dst, blob)
    assert rid2 is not None
    assert dst._requests[rid2].tenant == "mover"
    src.cancel(rid)
    while dst.has_work:
        clock.advance(clock.tick_s)
        dst.step()
    assert_drained(dst)


# ---------------------------------------------------------------------------
# per-tenant observability: counters and labeled exposition
# ---------------------------------------------------------------------------


def test_per_tenant_counters_in_load_and_healthz(model_params):
    fl, _ = _make_fleet(*model_params, n=1)
    fl.submit([2, 3, 4, 5], max_tokens=4, tenant="alice")
    fl.submit([6, 7, 8, 9], max_tokens=4, tenant="bob")
    fl.step()
    ld = fl.replicas[0].engine.load()
    assert set(ld["tenants"]) == {"alice", "bob"}
    live = sum(c["running"] + c["queued"] for c in ld["tenants"].values())
    assert live == 2
    running = [t for t, c in ld["tenants"].items() if c["running"]]
    for t in running:
        assert ld["tenants"][t]["pages_in_use"] > 0
    hz = fl.healthz()
    assert set(hz["tenants"]) == {"alice", "bob"}
    assert hz["admission_ledger"]["alice"]["admitted"] == 1
    _drain_all(fl)


def test_tenant_labels_quoted_in_prometheus_exposition(model_params):
    model, params = model_params
    reg = MetricsRegistry()
    fl, _ = _make_fleet(model, params, n=1, registry=reg)
    fl.submit([2, 3, 4, 5], max_tokens=3, tenant="team-a")
    fl.submit([2, 3, 4, 5], max_tokens=3, tenant="team-b",
              deadline_s=0.0)                     # times out immediately
    _drain_all(fl)
    text = fl.metrics_text()
    assert 'fleet_tokens_total{tenant="team-a"}' in text
    assert 'serving_deadline_miss_total{' in text
    assert 'tenant="team-b"' in text
    assert 'serving_queue_wait_ms{' in text
    # snapshot (unquoted keys) and to_text (quoted) agree on the value
    snap = reg.snapshot()
    assert snap["fleet_tokens_total{tenant=team-a}"] > 0


# ---------------------------------------------------------------------------
# drain/join interplay with roles; autoscaler
# ---------------------------------------------------------------------------


def test_draining_last_prefill_replica_is_refused(model_params):
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=2, roles=["prefill", "decode"])
    with pytest.raises(EnforceError, match="last prefill-capable"):
        fl.drain_replica(0)
    assert fl.replicas[0].state is ReplicaState.READY   # untouched
    # a second prefill-capable replica lifts the refusal
    idx = fl.add_replica(role="prefill")
    fl.step()
    assert fl.replica_state(idx) is ReplicaState.READY
    fl.drain_replica(0)
    assert fl.replicas[0].state is ReplicaState.DRAINING


def test_drain_refusal_never_blocks_unified_fleets(model_params):
    fl, _ = _make_fleet(*model_params, n=2)
    fl.drain_replica(0)                 # classic fleet: no role guard
    assert fl.replicas[0].state is ReplicaState.DRAINING


def test_autoscaler_grows_under_storm_and_shrinks_after(model_params):
    model, params = model_params
    reg = TenantRegistry()
    reg.register("storm", "batch")
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.02),
                          tenant_storm=("storm", 0, 6, 10))
    fl, _ = _make_fleet(
        model, params, n=1, plan=plan, tenants=reg, wfq=True,
        autoscale=AutoscalePolicy(min_replicas=1, max_replicas=3,
                                  buffered_hi=2, cooldown_ticks=2))
    rng = np.random.RandomState(0)
    tick = 0
    while tick < 6 or fl.has_work:
        if tick < 6 and tick % 2 == 0:
            for _ in range(plan.storm_factor(tick, "storm")):
                fl.submit(rng.randint(2, 50, size=6).tolist(),
                          max_tokens=3, tenant="storm")
        fl.step()
        tick += 1
        assert tick < 600, "fleet failed to drain"
    for _ in range(10):                 # idle tail: cold path + cooldowns
        fl.step()
    scaler = fl.autoscaler
    assert scaler.scale_ups >= 1
    assert scaler.scale_downs >= 1
    alive = [r for r in fl.replicas
             if r.state in (ReplicaState.READY, ReplicaState.JOINING)]
    assert 1 <= len(alive) <= 3
    check_control_conservation(fl)
    snap = fl.snapshot()
    assert snap["control_replica_ticks"] > 0


def test_autoscaler_never_drains_last_prefill_replica(model_params):
    model, params = model_params
    fl, _ = _make_fleet(
        model, params, n=2, roles=["prefill", "decode"],
        autoscale=AutoscalePolicy(min_replicas=1, max_replicas=3,
                                  cooldown_ticks=0))
    for _ in range(8):                  # idle from the start: cold ticks
        fl.step()
    # the decode replica may drain; the lone prefill replica never does
    assert fl.replicas[0].role == "prefill"
    assert fl.replicas[0].state in (ReplicaState.READY, ReplicaState.JOINING)


def test_scale_up_under_kill_is_exactly_once(model_params):
    """Chaos pin: a replica joins (autoscale) while another dies
    mid-decode on the same trace — every stream exactly-once, ledger
    balanced, zero leaks on every replica including the killed one."""
    model, params = model_params
    reg = TenantRegistry()
    reg.register("a", "standard")
    reg.register("b", "standard")
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.02),
                          kill_at={4: 0},
                          tenant_storm=("b", 0, 6, 6))
    fl, _ = _make_fleet(
        model, params, n=2, plan=plan, tenants=reg, wfq=True,
        autoscale=AutoscalePolicy(min_replicas=2, max_replicas=4,
                                  buffered_hi=2, cooldown_ticks=2))
    rng = np.random.RandomState(0)
    streams = {}
    tick = 0
    while tick < 6 or fl.has_work:
        if tick < 6 and tick % 2 == 0:
            for tenant in ("a", "b"):
                for _ in range(plan.storm_factor(tick, tenant)):
                    toks = []
                    frid = fl.submit(rng.randint(2, 50, size=6).tolist(),
                                     max_tokens=3, tenant=tenant,
                                     on_token=toks.append)
                    streams[frid] = toks
        fl.step()
        tick += 1
        assert tick < 800, "fleet failed to drain"
    assert fl.metrics.replicas_dead >= 1
    assert fl.autoscaler.scale_ups >= 1
    assert fl.metrics.duplicate_completions == 0
    for frid, toks in streams.items():
        if fl.status(frid) is RequestStatus.COMPLETED:
            # the exactly-once fence: the callback stream IS the result
            assert toks == fl.result(frid)
    check_control_conservation(fl)
