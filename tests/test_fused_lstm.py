"""Fused pallas LSTM cell vs the plain-JAX cell (hl_cuda_lstm.cu analog).

Same-op-two-paths parity (the reference's CPU-vs-GPU strategy,
math/tests/test_matrixCompare.cpp): values and gradients must match with
FLAGS.use_pallas on/off.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import rnn
from paddle_tpu.platform.flags import FLAGS


@pytest.fixture(autouse=True)
def f32_math():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def _data(rng, B=4, T=7, D=6, H=8):
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    lengths = rng.randint(2, T + 1, size=B)
    mask = jnp.asarray(np.arange(T)[None, :] < lengths[:, None])
    w_x = jnp.asarray(rng.randn(D, 4 * H).astype(np.float32) * 0.3)
    w_h = jnp.asarray(rng.randn(H, 4 * H).astype(np.float32) * 0.3)
    bias = jnp.asarray(rng.randn(4 * H).astype(np.float32) * 0.1)
    return x, mask, w_x, w_h, bias


@pytest.mark.parametrize("reverse", [False, True])
def test_fused_matches_plain(rng, reverse):
    x, mask, w_x, w_h, bias = _data(rng)

    def run():
        hs, final = rnn.lstm_scan(x, mask, w_x, w_h, bias, reverse=reverse)
        return hs, final

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        hs_f, fin_f = run()
        FLAGS.use_pallas = False
        hs_p, fin_p = run()
    finally:
        FLAGS.use_pallas = old
    np.testing.assert_allclose(np.asarray(hs_f), np.asarray(hs_p), atol=1e-5)
    np.testing.assert_allclose(np.asarray(fin_f.c), np.asarray(fin_p.c),
                               atol=1e-5)


def test_fused_grads_match_plain(rng):
    x, mask, w_x, w_h, bias = _data(rng)

    def loss(x, w_x, w_h, bias):
        hs, _ = rnn.lstm_scan(x, mask, w_x, w_h, bias)
        return jnp.sum(jnp.tanh(hs))

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        g_f = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w_x, w_h, bias)
        FLAGS.use_pallas = False
        g_p = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w_x, w_h, bias)
    finally:
        FLAGS.use_pallas = old
    for a, b in zip(g_f, g_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_fused_no_bias_and_custom_acts_fallback(rng):
    """bias=None works on the fused path; non-default activations fall
    back to the plain cell (identical API either way)."""
    x, mask, w_x, w_h, _ = _data(rng)
    hs1, _ = rnn.lstm_scan(x, mask, w_x, w_h, None)
    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = False
        hs2, _ = rnn.lstm_scan(x, mask, w_x, w_h, None)
    finally:
        FLAGS.use_pallas = old
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2), atol=1e-5)
    # custom activation -> plain path, still correct
    hs3, _ = rnn.lstm_scan(x, mask, w_x, w_h, None, cell_act=jax.nn.relu)
    assert np.isfinite(np.asarray(hs3)).all()


def test_vmem_guard_falls_back_for_large_hidden():
    """Hidden sizes whose weights exceed the per-kernel VMEM budget must
    take the plain-XLA path instead of failing to compile."""
    big_wh = jnp.zeros((2048, 4 * 2048), jnp.float32)
    assert not rnn._use_fused(64, big_wh, jax.nn.sigmoid, jnp.tanh, jnp.tanh)
    small_wh = jnp.zeros((128, 4 * 128), jnp.float32)
    assert rnn._use_fused(64, small_wh, jax.nn.sigmoid, jnp.tanh, jnp.tanh)


@pytest.mark.parametrize("reverse", [False, True])
def test_fused_gru_matches_plain(rng, reverse):
    B, T, D, H = 4, 6, 5, 8
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    lengths = rng.randint(2, T + 1, size=B)
    mask = jnp.asarray(np.arange(T)[None, :] < lengths[:, None])
    w_x = jnp.asarray(rng.randn(D, 3 * H).astype(np.float32) * 0.3)
    w_h = jnp.asarray(rng.randn(H, 3 * H).astype(np.float32) * 0.3)
    bias = jnp.asarray(rng.randn(3 * H).astype(np.float32) * 0.1)

    def loss(x, w_x, w_h, bias):
        hs, _ = rnn.gru_scan(x, mask, w_x, w_h, bias, reverse=reverse)
        return jnp.sum(jnp.tanh(hs))

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        hs_f, fin_f = rnn.gru_scan(x, mask, w_x, w_h, bias, reverse=reverse)
        g_f = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w_x, w_h, bias)
        FLAGS.use_pallas = False
        hs_p, fin_p = rnn.gru_scan(x, mask, w_x, w_h, bias, reverse=reverse)
        g_p = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w_x, w_h, bias)
    finally:
        FLAGS.use_pallas = old
    np.testing.assert_allclose(np.asarray(hs_f), np.asarray(hs_p), atol=1e-5)
    np.testing.assert_allclose(np.asarray(fin_f), np.asarray(fin_p),
                               atol=1e-5)
    for a, b in zip(g_f, g_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
