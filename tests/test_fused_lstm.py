"""Fused pallas LSTM cell vs the plain-JAX cell (hl_cuda_lstm.cu analog).

Same-op-two-paths parity (the reference's CPU-vs-GPU strategy,
math/tests/test_matrixCompare.cpp): values and gradients must match with
FLAGS.use_pallas on/off.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import rnn
from paddle_tpu.platform.flags import FLAGS


@pytest.fixture(autouse=True)
def f32_math():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def _data(rng, B=4, T=7, D=6, H=8):
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    lengths = rng.randint(2, T + 1, size=B)
    mask = jnp.asarray(np.arange(T)[None, :] < lengths[:, None])
    w_x = jnp.asarray(rng.randn(D, 4 * H).astype(np.float32) * 0.3)
    w_h = jnp.asarray(rng.randn(H, 4 * H).astype(np.float32) * 0.3)
    bias = jnp.asarray(rng.randn(4 * H).astype(np.float32) * 0.1)
    return x, mask, w_x, w_h, bias


@pytest.mark.parametrize("reverse", [False, True])
def test_fused_matches_plain(rng, reverse):
    x, mask, w_x, w_h, bias = _data(rng)

    def run():
        hs, final = rnn.lstm_scan(x, mask, w_x, w_h, bias, reverse=reverse)
        return hs, final

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        hs_f, fin_f = run()
        FLAGS.use_pallas = False
        hs_p, fin_p = run()
    finally:
        FLAGS.use_pallas = old
    np.testing.assert_allclose(np.asarray(hs_f), np.asarray(hs_p), atol=1e-5)
    np.testing.assert_allclose(np.asarray(fin_f.c), np.asarray(fin_p.c),
                               atol=1e-5)


def test_fused_grads_match_plain(rng):
    x, mask, w_x, w_h, bias = _data(rng)

    def loss(x, w_x, w_h, bias):
        hs, _ = rnn.lstm_scan(x, mask, w_x, w_h, bias)
        return jnp.sum(jnp.tanh(hs))

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        g_f = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w_x, w_h, bias)
        FLAGS.use_pallas = False
        g_p = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w_x, w_h, bias)
    finally:
        FLAGS.use_pallas = old
    for a, b in zip(g_f, g_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_fused_no_bias_and_custom_acts_fallback(rng):
    """bias=None works on the fused path; non-default activations fall
    back to the plain cell (identical API either way)."""
    x, mask, w_x, w_h, _ = _data(rng)
    hs1, _ = rnn.lstm_scan(x, mask, w_x, w_h, None)
    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = False
        hs2, _ = rnn.lstm_scan(x, mask, w_x, w_h, None)
    finally:
        FLAGS.use_pallas = old
    np.testing.assert_allclose(np.asarray(hs1), np.asarray(hs2), atol=1e-5)
    # custom activation -> plain path, still correct
    hs3, _ = rnn.lstm_scan(x, mask, w_x, w_h, None, cell_act=jax.nn.relu)
    assert np.isfinite(np.asarray(hs3)).all()


def test_vmem_guard_and_tiling_coverage():
    """Hidden sizes beyond the single-block VMEM budget now use the
    hidden-tiled kernel when a lane-aligned tile divides H; otherwise the
    guard still falls back to plain XLA instead of failing to compile."""
    small_wh = jnp.zeros((128, 4 * 128), jnp.float32)
    assert rnn._use_fused(64, small_wh, jax.nn.sigmoid, jnp.tanh, jnp.tanh)
    # 2048 = 16*128: too big for one block, but tiles at t=256
    big_wh = jnp.zeros((2048, 4 * 2048), jnp.float32)
    assert rnn._fused_vmem_ok(big_wh, 64, 17) is False
    assert rnn._lstm_tile(2048, 64) == 256
    assert rnn._use_fused(64, big_wh, jax.nn.sigmoid, jnp.tanh, jnp.tanh)
    # 1000 has no multiple-of-128 divisor: genuine plain-XLA fallback
    odd_wh = jnp.zeros((1000, 4 * 1000), jnp.float32)
    assert not rnn._use_fused(64, odd_wh, jax.nn.sigmoid, jnp.tanh, jnp.tanh)


@pytest.mark.parametrize("reverse", [False, True])
def test_fused_gru_matches_plain(rng, reverse):
    B, T, D, H = 4, 6, 5, 8
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    lengths = rng.randint(2, T + 1, size=B)
    mask = jnp.asarray(np.arange(T)[None, :] < lengths[:, None])
    w_x = jnp.asarray(rng.randn(D, 3 * H).astype(np.float32) * 0.3)
    w_h = jnp.asarray(rng.randn(H, 3 * H).astype(np.float32) * 0.3)
    bias = jnp.asarray(rng.randn(3 * H).astype(np.float32) * 0.1)

    def loss(x, w_x, w_h, bias):
        hs, _ = rnn.gru_scan(x, mask, w_x, w_h, bias, reverse=reverse)
        return jnp.sum(jnp.tanh(hs))

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        hs_f, fin_f = rnn.gru_scan(x, mask, w_x, w_h, bias, reverse=reverse)
        g_f = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w_x, w_h, bias)
        FLAGS.use_pallas = False
        hs_p, fin_p = rnn.gru_scan(x, mask, w_x, w_h, bias, reverse=reverse)
        g_p = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w_x, w_h, bias)
    finally:
        FLAGS.use_pallas = old
    np.testing.assert_allclose(np.asarray(hs_f), np.asarray(hs_p), atol=1e-5)
    np.testing.assert_allclose(np.asarray(fin_f), np.asarray(fin_p),
                               atol=1e-5)
    for a, b in zip(g_f, g_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_fused_tiled_large_hidden_matches_plain(rng):
    """h=1280-class cells: w_h alone exceeds the single-block VMEM budget,
    so the hidden-tiled grid kernel runs — values AND grads must still
    match the plain path (covers the reference RNN benchmark's h=1280 row)."""
    B, T, D, H = 3, 3, 5, 1280
    assert rnn._lstm_tile(H, B) == 256  # tiled path actually engages
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    mask = jnp.asarray(np.ones((B, T), bool))
    w_x = jnp.asarray(rng.randn(D, 4 * H).astype(np.float32) * 0.1)
    w_h = jnp.asarray((rng.randn(H, 4 * H) * 0.02).astype(np.float32))
    bias = jnp.asarray(rng.randn(4 * H).astype(np.float32) * 0.1)

    def loss(w_h):
        hs, _ = rnn.lstm_scan(x, mask, w_x, w_h, bias)
        return jnp.sum(hs ** 2)

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        assert rnn._use_fused(B, w_h, jax.nn.sigmoid, jnp.tanh, jnp.tanh)
        hs_f, _ = rnn.lstm_scan(x, mask, w_x, w_h, bias)
        g_f = jax.grad(loss)(w_h)
        FLAGS.use_pallas = False
        hs_p, _ = rnn.lstm_scan(x, mask, w_x, w_h, bias)
        g_p = jax.grad(loss)(w_h)
    finally:
        FLAGS.use_pallas = old
    np.testing.assert_allclose(np.asarray(hs_f), np.asarray(hs_p), atol=1e-5)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_p), atol=1e-4)


def test_fused_gru_tiled_large_hidden_matches_plain(rng):
    """Large-hidden GRU runs the two-phase tiled kernels; values AND grads
    must match the plain path."""
    B, T, D, H = 3, 3, 5, 1280
    assert rnn._gru_tile(H, B) is not None
    assert not rnn._fused_vmem_ok(jnp.zeros((H, 3 * H)), B, 11)
    x = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    mask = jnp.asarray(np.ones((B, T), bool))
    w_x = jnp.asarray(rng.randn(D, 3 * H).astype(np.float32) * 0.1)
    w_h = jnp.asarray((rng.randn(H, 3 * H) * 0.02).astype(np.float32))
    bias = jnp.asarray(rng.randn(3 * H).astype(np.float32) * 0.1)

    def loss(w_h):
        hs, _ = rnn.gru_scan(x, mask, w_x, w_h, bias)
        return jnp.sum(hs ** 2)

    old = FLAGS.use_pallas
    try:
        FLAGS.use_pallas = True
        hs_f, _ = rnn.gru_scan(x, mask, w_x, w_h, bias)
        g_f = jax.grad(loss)(w_h)
        FLAGS.use_pallas = False
        hs_p, _ = rnn.gru_scan(x, mask, w_x, w_h, bias)
        g_p = jax.grad(loss)(w_h)
    finally:
        FLAGS.use_pallas = old
    np.testing.assert_allclose(np.asarray(hs_f), np.asarray(hs_p), atol=1e-5)
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_p), atol=1e-4)
