"""GoogLeNet + SmallNet model builders (reference benchmark table rows,
BASELINE.md): geometry, forward shape, and a training step on tiny images.
"""

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer, trainer
from paddle_tpu.models import googlenet, smallnet


def _one_step(build_fn, img, n_classes, batch, rng, **kw):
    paddle.topology.reset_name_scope()
    images, label, logits, cost = build_fn(img_size=img,
                                           num_classes=n_classes, **kw)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Momentum(momentum=0.9,
                                                         learning_rate=0.01))
    step = sgd._build_step()
    feeds = {
        "image": jax.device_put(
            rng.randn(batch, img, img, 3).astype(np.float32)),
        "label": jax.device_put(
            rng.randint(0, n_classes, size=batch).astype(np.int32)),
    }
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(3):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    return logits, losses


def test_smallnet_trains(rng):
    logits, losses = _one_step(smallnet.build, 32, 10, 16, rng)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_googlenet_geometry_and_step(rng):
    # tiny 64px input: exercises every inception stage; final map 2x2
    logits, losses = _one_step(googlenet.build, 64, 20, 4, rng)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_googlenet_channel_counts():
    paddle.topology.reset_name_scope()
    images, label, logits, cost = googlenet.build(img_size=224,
                                                  num_classes=1000)
    # inception 5b output: 384+384+128+128 = 1024 channels at 7x7
    from paddle_tpu.topology import Topology

    topo = Topology([cost])
    concats = [n for n in topo.nodes if n.layer_type == "concat"]
    assert len(concats) == 9
    assert concats[-1].img_shape == (7, 7, 1024)
