"""Fluid op tests: the OpTest harness analog.

Reference: python/paddle/v2/framework/tests/op_test.py — build the op in a
small program, check forward output against a numpy reference
(check_output_with_place, op_test.py:286) and analytic-vs-numeric gradients
(get_numeric_gradient op_test.py:97, check_grad :388). 96 per-op test files
collapse here into one harness + table-driven cases.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.framework import grad_name


class OpTest:
    """Run one op in a fresh program; check outputs and gradients."""

    def __init__(self, op_type, inputs, attrs=None, out_slots=("Out",)):
        self.op_type = op_type
        self.inputs = inputs            # slot -> np array or list of arrays
        self.attrs = attrs or {}
        self.out_slots = out_slots

    def _build(self):
        prog = fluid.Program()
        with fluid.program_guard(prog):
            in_vars, feed = {}, {}
            for slot, arrs in self.inputs.items():
                arrs_l = arrs if isinstance(arrs, list) else [arrs]
                vs = []
                for i, a in enumerate(arrs_l):
                    name = f"{slot.lower()}_{i}"
                    if isinstance(a, fluid.LoDArray):
                        v = layers.data(name, a.data.shape,
                                        dtype=str(a.data.dtype),
                                        lod_level=len(a.lod),
                                        append_batch_size=False)
                    else:
                        v = layers.data(name, a.shape, dtype=str(a.dtype),
                                        append_batch_size=False)
                    v.stop_gradient = False
                    vs.append(v)
                    feed[name] = a
                in_vars[slot] = vs
            outs = {s: prog.global_block().create_var()
                    for s in self.out_slots}
            prog.global_block().append_op(
                self.op_type, inputs=in_vars,
                outputs={s: [v] for s, v in outs.items()},
                attrs=self.attrs)
        return prog, feed, in_vars, outs

    def check_output(self, expect, atol=1e-5, slot=None):
        prog, feed, _, outs = self._build()
        slot = slot or self.out_slots[0]
        exe = fluid.Executor()
        (got,) = exe.run(prog, feed=feed, fetch_list=[outs[slot]],
                         scope=fluid.Scope())
        np.testing.assert_allclose(got, expect, atol=atol, rtol=1e-4)
        return got

    def check_grad(self, wrt, out_slot=None, delta=5e-3, atol=2e-3):
        """Numeric-vs-analytic gradient of mean(out) w.r.t. input `wrt`."""
        prog, feed, in_vars, outs = self._build()
        out_slot = out_slot or self.out_slots[0]
        with fluid.program_guard(prog):
            loss = layers.mean(outs[out_slot])
        slot, idx = wrt if isinstance(wrt, tuple) else (wrt, 0)
        target = in_vars[slot][idx]
        fluid.append_backward(loss, parameter_list=[])
        exe = fluid.Executor()
        scope = fluid.Scope()
        analytic = exe.run(prog, feed=feed,
                           fetch_list=[grad_name(target.name)],
                           scope=scope)[0]

        base = feed[target.name].astype(np.float64)
        numeric = np.zeros_like(base)

        def eval_loss(arr):
            f2 = dict(feed)
            f2[target.name] = arr.astype(feed[target.name].dtype)
            return float(exe.run(prog, feed=f2, fetch_list=[loss],
                                 scope=scope)[0])

        flat = base.reshape(-1)
        num_flat = numeric.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + delta
            up = eval_loss(base)
            flat[i] = orig - delta
            down = eval_loss(base)
            flat[i] = orig
            num_flat[i] = (up - down) / (2 * delta)
        np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=1e-2)


RNG = np.random.RandomState(7)


def test_elementwise_ops():
    x = RNG.randn(4, 5).astype(np.float32)
    y = RNG.randn(4, 5).astype(np.float32)
    OpTest("elementwise_add", {"X": x, "Y": y}).check_output(x + y)
    OpTest("elementwise_mul", {"X": x, "Y": y}).check_output(x * y)
    OpTest("elementwise_max", {"X": x, "Y": y}).check_output(
        np.maximum(x, y))
    OpTest("elementwise_min", {"X": x, "Y": y}).check_output(
        np.minimum(x, y))


def test_elementwise_broadcast_axis():
    x = RNG.randn(2, 3, 4).astype(np.float32)
    y = RNG.randn(3,).astype(np.float32)
    OpTest("elementwise_add", {"X": x, "Y": y}, {"axis": 1}).check_output(
        x + y[None, :, None])


def test_mul_and_grad():
    x = RNG.randn(3, 4).astype(np.float32)
    w = RNG.randn(4, 5).astype(np.float32)
    t = OpTest("mul", {"X": x, "Y": w})
    t.check_output(x @ w)
    t.check_grad("X")
    t.check_grad("Y")


def test_activation_grads():
    x = (RNG.randn(3, 4) * 2).astype(np.float32)
    OpTest("sigmoid", {"X": x}).check_output(1 / (1 + np.exp(-x)))
    OpTest("tanh", {"X": x}).check_grad("X")
    OpTest("square", {"X": x}).check_grad("X")
    OpTest("stanh", {"X": x}).check_grad("X")
    OpTest("logsigmoid", {"X": x}).check_output(
        np.log(1 / (1 + np.exp(-x))), atol=1e-4)
    OpTest("softplus", {"X": x}).check_grad("X")
    OpTest("softsign", {"X": x}).check_output(x / (1 + np.abs(x)))
    OpTest("leaky_relu", {"X": x}, {"alpha": 0.1}).check_output(
        np.where(x > 0, x, 0.1 * x))
    OpTest("relu6", {"X": x * 4}).check_output(np.clip(x * 4, 0, 6))
    OpTest("hard_shrink", {"X": x}, {"threshold": 0.5}).check_output(
        np.where(np.abs(x) > 0.5, x, 0))
    OpTest("soft_shrink", {"X": x}, {"lambda": 0.5}).check_output(
        np.sign(x) * np.maximum(np.abs(x) - 0.5, 0))
    OpTest("ceil", {"X": x}).check_output(np.ceil(x))
    OpTest("floor", {"X": x}).check_output(np.floor(x))


def test_softmax_cross_entropy():
    logits = RNG.randn(4, 6).astype(np.float32)
    label = RNG.randint(0, 6, (4, 1)).astype(np.int64)
    t = OpTest("softmax_with_cross_entropy",
               {"Logits": logits, "Label": label},
               out_slots=("Softmax", "Loss"))
    m = logits - logits.max(-1, keepdims=True)
    p = np.exp(m) / np.exp(m).sum(-1, keepdims=True)
    expect = -np.log(p[np.arange(4), label.ravel()])[:, None]
    t.check_output(expect, slot="Loss")
    t.check_grad("Logits", out_slot="Loss")


def test_conv2d_and_grad():
    x = RNG.randn(2, 3, 8, 8).astype(np.float32)
    w = RNG.randn(4, 3, 3, 3).astype(np.float32)
    t = OpTest("conv2d", {"Input": x, "Filter": w},
               {"strides": 1, "paddings": 1}, out_slots=("Output",))
    t.check_grad("Filter", out_slot="Output", delta=1e-2, atol=5e-3)


def test_pool2d():
    x = RNG.randn(2, 3, 6, 6).astype(np.float32)
    t = OpTest("pool2d", {"X": x},
               {"ksize": 2, "strides": 2, "pooling_type": "max"})
    expect = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
    t.check_output(expect)


def test_reduce_and_shape_ops():
    x = RNG.randn(3, 4).astype(np.float32)
    OpTest("reduce_sum", {"X": x}, {"dim": 1, "reduce_all": False}
           ).check_output(x.sum(1))
    OpTest("reduce_max", {"X": x}, {"dim": 0, "reduce_all": False}
           ).check_output(x.max(0))
    OpTest("reduce_min", {"X": x}, {"dim": 1, "reduce_all": False}
           ).check_output(x.min(1))
    OpTest("reshape", {"X": x}, {"shape": [4, 3]}).check_output(
        x.reshape(4, 3))
    OpTest("transpose", {"X": x}, {"axis": [1, 0]}).check_output(x.T)
    OpTest("pad", {"X": x}, {"paddings": [1, 0, 0, 2]}).check_output(
        np.pad(x, ((1, 0), (0, 2))))


def test_top_k_accuracy():
    x = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]], np.float32)
    label = np.array([[1], [2]], np.int64)
    t = OpTest("top_k", {"X": x}, {"k": 1}, out_slots=("Out", "Indices"))
    t.check_output(np.array([[1], [0]]), slot="Indices")


def test_lookup_table_grad():
    w = RNG.randn(10, 4).astype(np.float32)
    ids = np.array([[1], [3], [1]], np.int64)
    t = OpTest("lookup_table", {"W": w, "Ids": ids})
    t.check_output(w[[1, 3, 1]])
    t.check_grad("W")


def test_lstm_gru_units():
    x = RNG.randn(3, 16).astype(np.float32)
    c = RNG.randn(3, 4).astype(np.float32)
    t = OpTest("lstm_unit", {"X": x, "C_prev": c}, out_slots=("C", "H"))
    t.check_grad("X", out_slot="H")

    xi = RNG.randn(3, 12).astype(np.float32)
    h = RNG.randn(3, 4).astype(np.float32)
    w = RNG.randn(4, 12).astype(np.float32)
    t = OpTest("gru_unit", {"Input": xi, "HiddenPrev": h, "Weight": w},
               out_slots=("Gate", "ResetHiddenPrev", "Hidden"))
    t.check_grad("Weight", out_slot="Hidden")


def test_batch_norm_forward():
    x = RNG.randn(4, 3, 5, 5).astype(np.float32)
    scale = np.ones(3, np.float32)
    bias = np.zeros(3, np.float32)
    mean = np.zeros(3, np.float32)
    var = np.ones(3, np.float32)
    t = OpTest("batch_norm",
               {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
                "Variance": var},
               out_slots=("Y", "MeanOut", "VarianceOut", "SavedMean",
                          "SavedVariance"))
    mu = x.mean(axis=(0, 2, 3))
    v = x.var(axis=(0, 2, 3))
    expect = (x - mu[None, :, None, None]) / np.sqrt(
        v[None, :, None, None] + 1e-5)
    t.check_output(expect, slot="Y", atol=1e-4)


def test_optimizer_ops_numeric():
    p = RNG.randn(4).astype(np.float32)
    g = RNG.randn(4).astype(np.float32)
    lr = np.array([0.1], np.float32)
    OpTest("sgd", {"Param": p, "Grad": g, "LearningRate": lr},
           out_slots=("ParamOut",)).check_output(p - 0.1 * g)

    v = np.zeros(4, np.float32)
    OpTest("momentum",
           {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr},
           {"mu": 0.9}, out_slots=("ParamOut", "VelocityOut")
           ).check_output(p - 0.1 * g)


def test_sequence_pool_lod():
    data = np.arange(10, dtype=np.float32).reshape(5, 2)
    lod = ((0, 2, 5),)
    t = OpTest("sequence_pool", {"X": fluid.LoDArray(data, lod)},
               {"pooltype": "SUM"})
    expect = np.stack([data[0:2].sum(0), data[2:5].sum(0)])
    t.check_output(expect)


def test_sequence_softmax_lod():
    data = RNG.randn(6, 1).astype(np.float32)
    lod = ((0, 2, 6),)
    t = OpTest("sequence_softmax", {"X": fluid.LoDArray(data, lod)})
    d = data.ravel()
    e = np.exp(d - np.array([d[:2].max()] * 2 + [d[2:].max()] * 4))
    expect = (e / np.array([e[:2].sum()] * 2 + [e[2:].sum()] * 4)
              ).reshape(6, 1)
    t.check_output(expect)


def test_registry_inventory():
    """The op registry must cover the reference's major op families
    (paddle/operators — SURVEY.md §2.2)."""
    ops = set(fluid.registered_ops())
    required = {
        "elementwise_add", "elementwise_sub", "elementwise_mul",
        "elementwise_div", "elementwise_pow", "mul", "matmul", "conv2d",
        "conv2d_transpose", "conv3d", "pool2d", "pool2d_with_index",
        "batch_norm", "softmax", "softmax_with_cross_entropy",
        "cross_entropy", "sigmoid_cross_entropy_with_logits",
        "lookup_table", "lstm_unit", "gru_unit", "recurrent",
        "sequence_concat", "sequence_pool", "sequence_softmax",
        "sequence_expand", "reduce_sum", "reduce_mean", "reshape",
        "transpose", "pad", "crop", "clip", "split", "concat", "scale",
        "cast", "top_k", "accuracy", "sgd", "momentum", "adam", "adamax",
        "adagrad", "adadelta", "rmsprop", "proximal_gd", "decayed_adagrad",
        "uniform_random", "gaussian_random", "fill_constant",
        "fill_zeros_like", "mean", "sum", "minus", "squared_l2_norm",
        "squared_l2_distance", "rank_loss", "margin_rank_loss",
        "smooth_l1_loss", "huber_loss", "dropout", "gather", "scatter",
        "sigmoid", "tanh", "relu", "sqrt", "abs", "reciprocal", "log",
        "square", "brelu", "soft_relu", "pow", "stanh", "lrn",
    }
    missing = required - ops
    assert not missing, f"missing op families: {sorted(missing)}"


def test_shape_ops_squeeze_unsqueeze():
    x = RNG.randn(3, 1, 4, 1).astype(np.float32)
    t = OpTest("squeeze", {"X": x}, {"axes": [1, 3]})
    t.check_output(x.reshape(3, 4))
    t.check_grad("X")
    y = RNG.randn(3, 4).astype(np.float32)
    t2 = OpTest("unsqueeze", {"X": y}, {"axes": [0, 2]})
    t2.check_output(y.reshape(1, 3, 1, 4))
    t2.check_grad("X")


def test_layer_norm_op():
    x = RNG.randn(4, 6).astype(np.float32)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5)
    t = OpTest("layer_norm", {"X": x}, out_slots=("Y",))
    t.check_output(want, atol=1e-4)
    t.check_grad("X", out_slot="Y")


def test_argmax_increment_ops():
    x = RNG.randn(4, 6).astype(np.float32)
    OpTest("argmax", {"X": x}).check_output(
        x.argmax(-1).astype(np.int32))
    OpTest("argmax", {"X": x}, {"axis": 0}).check_output(
        x.argmax(0).astype(np.int32))
    OpTest("increment", {"X": x}, {"step": 2.5}).check_output(x + 2.5)


def test_beta_pow_update_op():
    b1 = np.asarray([0.9 ** 3], np.float32)
    b2 = np.asarray([0.999 ** 3], np.float32)
    t = OpTest("beta_pow_update", {"Beta1Pow": b1, "Beta2Pow": b2},
               {"beta1": 0.9, "beta2": 0.999},
               out_slots=("Beta1PowOut", "Beta2PowOut"))
    t.check_output(b1 * 0.9, slot="Beta1PowOut")


def test_every_registered_op_is_exercised():
    """Registry-breadth gate (the reference ships one OpTest file per op,
    python/paddle/v2/framework/tests/): every registered fluid op must be
    named by some fluid test so new ops can't land untested."""
    import glob
    import os

    from paddle_tpu.fluid.ops import registered_ops

    here = os.path.dirname(os.path.abspath(__file__))
    corpus = "".join(open(p).read()
                     for p in glob.glob(os.path.join(here, "test_fluid*.py")))
    missing = [op for op in registered_ops() if op not in corpus]
    assert not missing, f"fluid ops with no test mention: {missing}"
