"""CLI + utils tests (dump_config golden check, diagram, torch import).

Reference analog: the `paddle` subcommand surface
(scripts/submit_local.sh.in:96-104), trainer_config_helpers' golden
config snapshot tests (tests/configs + ProtobufEqualMain.cpp), and
python/paddle/utils (make_model_diagram, torch2paddle).
"""

import json
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import cli, layer, utils
from paddle_tpu.topology import Topology

CONFIG = """
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import layer, optimizer

x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
y = layer.data(name="y", type=paddle.data_type.integer_value(3))
hidden = layer.fc(x, size=16, act="relu", name="hidden")
logits = layer.fc(hidden, size=3, name="logits")
cost = layer.classification_cost(input=logits, label=y)
outputs = logits
optimizer = optimizer.Sgd(learning_rate=0.1)
batch_size = 16

_rng = np.random.RandomState(0)
_data = []
for _ in range(64):
    _y = int(_rng.randint(0, 3))
    _x = (_rng.randn(8) * 0.2).astype(np.float32)
    _x[_y * 2] += 1.0
    _data.append((_x, _y))


def reader():
    return iter(_data)
"""


@pytest.fixture
def config_file(tmp_path):
    p = tmp_path / "conf.py"
    p.write_text(CONFIG)
    return str(p)


def test_dump_config_structure(config_file, capsys):
    assert cli.main(["dump_config", "--config", config_file]) == 0
    cfg = json.loads(capsys.readouterr().out)
    types = {l["name"]: l["type"] for l in cfg["layers"]}
    assert types["x"] == "data" and types["hidden"] == "fc"
    pnames = {p["name"] for p in cfg["parameters"]}
    assert "hidden.w0" in pnames and "logits.b" in pnames
    assert cfg["input_layers"] == ["x", "y"]

    # golden-snapshot style determinism: two dumps are identical
    paddle.topology.reset_name_scope()
    assert cli.main(["dump_config", "--config", config_file]) == 0
    cfg2 = json.loads(capsys.readouterr().out)
    assert cfg == cfg2


def test_model_diagram_dot(config_file, capsys):
    assert cli.main(["dump_config", "--config", config_file,
                     "--format", "dot"]) == 0
    dot = capsys.readouterr().out
    assert "digraph" in dot and '"hidden" -> "logits"' in dot


def test_cli_train_and_merge(config_file, tmp_path, capsys):
    save = str(tmp_path / "ckpt")
    assert cli.main(["train", "--config", config_file,
                     "--num_passes", "2", "--save_dir", save]) == 0
    out_model = str(tmp_path / "m.ptm")
    assert cli.main(["merge_model", "--config", config_file,
                     "--model_dir", save, "--output", out_model]) == 0
    from paddle_tpu import export as pexport
    m = pexport.load_merged_model(out_model)
    (probs,) = m.infer({"x": np.zeros((2, 8), np.float32)})
    assert probs.shape == (2, 3)


def test_cli_version(capsys):
    assert cli.main(["version"]) == 0
    assert "paddle_tpu" in capsys.readouterr().out


def test_torch2paddle_import(rng):
    torch = pytest.importorskip("torch")
    from paddle_tpu.platform.flags import FLAGS

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
    out = layer.fc(x, size=4, name="lin")
    topo = Topology([out])
    params = paddle.Parameters.from_topology(topo, seed=0)

    tmod = torch.nn.Linear(6, 4)
    imported = utils.torch2paddle(
        tmod.state_dict(), params,
        name_map={"weight": "lin.w0", "bias": "lin.b"})
    assert set(imported) == {"lin.w0", "lin.b"}
    np.testing.assert_allclose(
        np.asarray(params["lin.w0"]),
        tmod.weight.detach().numpy().T, atol=1e-6)

    # forward parity with torch (f32 kernels for an exact comparison)
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        xb = rng.randn(3, 6).astype(np.float32)
        state = topo.init_state()
        got, _ = topo.forward(params.as_dict(), state, {"x": xb},
                              train=False)
        expect = tmod(torch.from_numpy(xb)).detach().numpy()
        np.testing.assert_allclose(np.asarray(got[0]), expect, atol=1e-4)
    finally:
        FLAGS.use_bf16 = old


def test_param_text_round_trip(rng, tmp_path):
    """paraconvert.py analog: text dump <-> load round trip."""
    from paddle_tpu import utils

    table = rng.randn(7, 5).astype("float32")
    path = str(tmp_path / "emb.txt")
    utils.param_to_text(table, path)
    back = utils.text_to_param(path, dim=5)
    assert back.shape == (7, 5)
    import numpy as np

    np.testing.assert_allclose(back, table, atol=1e-6)
    # header count mismatch is detected
    lines = open(path).read().splitlines()
    open(path, "w").write("\n".join([lines[0]] + lines[2:]) + "\n")
    import pytest

    with pytest.raises(ValueError):
        utils.text_to_param(path, dim=5)


def test_extract_embedding_rows(rng):
    """extract_para.py analog: slice trained embedding rows by word id."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import layer, utils

    paddle.topology.reset_name_scope()
    words = layer.data(name="w",
                       type=paddle.data_type.integer_value_sequence(50))
    emb = layer.embedding(input=words, size=8, name="emb")
    fc = layer.fc(input=layer.pooling(
        input=emb, pooling_type=paddle.pooling.AvgPooling()), size=2)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([fc]), seed=0)
    got = utils.extract_embedding(params, "emb.w", [3, 1, 4])
    table = np.asarray(params["emb.w"])
    np.testing.assert_allclose(got, table[[3, 1, 4]])


def test_cli_job_test_evaluates_saved_model(config_file, tmp_path, capsys):
    """`paddle train --job=test` (Tester analog): train with save_dir,
    then evaluate the checkpoint and print the test cost."""
    from paddle_tpu import cli

    save = str(tmp_path / "out")
    assert cli.main(["train", "--config", config_file, "--num_passes", "2",
                     "--save_dir", save]) == 0
    capsys.readouterr()
    assert cli.main(["train", "--config", config_file, "--job", "test",
                     "--save_dir", save]) == 0
    out = capsys.readouterr().out
    assert "Test cost=" in out
    cost = float(out.split("Test cost=")[1].split()[0])
    # the trained model must beat untrained ~log(3)
    assert cost < 0.9


def test_cli_job_test_missing_checkpoint_exits_2(config_file, tmp_path, capsys):
    """A save_dir with no checkpoint (or a corrupt tar) is a config mistake:
    one-line stderr message and exit code 2, not a traceback."""
    from paddle_tpu import cli

    assert cli.main(["train", "--config", config_file, "--job", "test",
                     "--save_dir", str(tmp_path / "nothing-here")]) == 2
    assert "cannot load checkpoint" in capsys.readouterr().err
    bad_tar = tmp_path / "bad.tar"
    bad_tar.write_bytes(b"not a tar at all")
    assert cli.main(["train", "--config", config_file, "--job", "test",
                     "--init_model_tar", str(bad_tar)]) == 2
    assert "cannot load model tar" in capsys.readouterr().err


def test_gradient_check_passes_and_catches_corruption(rng, monkeypatch):
    """utils.gradient_check: numeric == analytic on a small net, and a
    genuinely wrong analytic gradient is caught."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import layer, utils
    from paddle_tpu.platform.enforce import EnforceError

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
    y = layer.data(name="y", type=paddle.data_type.integer_value(3))
    h = layer.fc(input=x, size=8, act="tanh")
    cost = layer.classification_cost(input=layer.fc(input=h, size=3),
                                     label=y)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    feeds = {
        "x": jax.numpy.asarray(rng.randn(4, 6).astype("float32")),
        "y": jax.numpy.asarray(rng.randint(0, 3, size=(4, 1))),
    }
    report = utils.gradient_check(cost, params, feeds)
    assert report and all(v <= 2e-2 for v in report.values())

    # corrupt the ANALYTIC side for real: scale jax.grad's output 2x —
    # the numeric side is untouched, so detection must fire
    import pytest

    real_grad = jax.grad

    def bad_grad(f, *a, **kw):
        g = real_grad(f, *a, **kw)
        return lambda p: jax.tree.map(lambda x: 2.0 * x, g(p))

    monkeypatch.setattr(jax, "grad", bad_grad)
    with pytest.raises(EnforceError):
        utils.gradient_check(cost, params, feeds)
