"""quick_start (7 text-classification archs) + traffic_prediction demos.

Reference: v1_api_demo/quick_start/trainer_config.*.py and
v1_api_demo/traffic_prediction/trainer_config.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.models import quick_start, traffic_prediction

DICT = 100


def _text_samples(rng, n=24, bow=False):
    """Class-separable synthetic text: class 0 uses low ids, 1 high ids."""
    out = []
    for i in range(n):
        y = i % 2
        length = int(rng.randint(4, 12))
        ids = rng.randint(0 if y == 0 else DICT // 2,
                          DICT // 2 if y == 0 else DICT, size=length)
        if bow:
            vec = np.zeros(DICT, np.float32)
            vec[ids] = 1.0
            out.append((vec, y))
        else:
            out.append((ids.tolist(), y))
    return out


@pytest.mark.parametrize("arch", quick_start.ARCHS)
def test_quick_start_arch_trains(rng, arch):
    paddle.topology.reset_name_scope()
    word, label, output, cost = quick_start.build(
        arch=arch, dict_size=DICT, emb_size=16)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=5e-3))
    step = sgd._build_step()
    feeds = sgd._make_feeder({"word": 0, "label": 1}).feed(
        _text_samples(rng, bow=(arch == "lr")))
    import jax

    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(25):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (arch, losses[0], losses[-1])


def test_traffic_prediction_shared_weights_train(rng):
    paddle.topology.reset_name_scope()
    link, labels, scores, costs = traffic_prediction.build(
        forecasting_num=4, emb_size=8)
    topo = paddle.topology.Topology(costs)
    params = paddle.Parameters.from_topology(topo, seed=0)
    # cross-head weight sharing: ONE parameter backs all head projections
    assert "_link_vec.w" in params.names()
    assert not any(n.startswith("link_vec_") and n.endswith(".w0")
                   for n in params.names())
    sgd = trainer.SGD(cost=costs, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))
    step = sgd._build_step()
    samples = []
    for _ in range(32):
        x = rng.randn(traffic_prediction.TERM_NUM).astype(np.float32)
        ys = [int(x[: 6 * (i + 1)].sum() > 0) for i in range(4)]
        samples.append(tuple([x] + ys))
    feeding = {"link_encode": 0}
    feeding.update({f"label_{(i + 1) * 5}min": i + 1 for i in range(4)})
    feeds = sgd._make_feeder(feeding).feed(samples)
    import jax

    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    w0 = np.asarray(p["_link_vec.w"]).copy()  # step donates its inputs
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(30):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8
    # the shared weight received updates
    moved = np.abs(np.asarray(p["_link_vec.w"]) - w0).max()
    assert moved > 1e-4
