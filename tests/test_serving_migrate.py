"""Page-migration plane (round 16): live KV chain handoff, cross-replica
prefix seeding, disaggregated prefill/decode routing, and MIGRATE-LEAK
conservation — all on injected clocks, no wall-clock sleeps.

The roundtrip tests move STORED bytes: an int8 page migrates as its int8
payload plus f32 scales with no re-quantization, so the destination's
pages compare bit-identical to the source's.  The fleet tests replay the
same seeded traces disaggregated vs unified and demand token-identical
streams — migration is a placement optimization, never a semantics
change.
"""

import jax
import numpy as np
import pytest

from paddle_tpu.platform.enforce import EnforceError
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (DecoderLM, FleetFaultPlan, FleetRouter,
                                ManualClock, ReplicaState, RequestStatus,
                                ServingEngine, check_migration_conservation,
                                export_chain, export_prefix,
                                greedy_decode_reference, import_chain,
                                import_prefix)
from paddle_tpu.serving.kv_cache import read_pages

from conftest import assert_serving_drained as assert_drained  # noqa: E402

serving = pytest.mark.serving
migrate_mark = pytest.mark.migrate

pytestmark = [serving, migrate_mark]

PAGE = 4
EOS = 1


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


@pytest.fixture(scope="module")
def model_params():
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=128)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, **kw):
    base = dict(eos_id=EOS, page_size=PAGE, num_pages=32,
                max_pages_per_seq=8, max_slots=4, buckets=(8, 16))
    base.update(kw)
    return ServingEngine(model, params, **base)


def _run_until_migratable(eng, rid, max_ticks=50):
    for _ in range(max_ticks):
        if rid in eng.migratable_rids():
            return
        eng.step()
    raise AssertionError(f"rid {rid} never became migratable")


def _drain(eng, max_ticks=200):
    for _ in range(max_ticks):
        if not eng.has_work:
            return
        eng.step()
    raise AssertionError("engine failed to drain")


def _page_bytes(kv, pages):
    return tuple(None if a is None else np.asarray(a).tobytes()
                 for a in read_pages(kv, pages))


def _make_fleet(model, params, n, plan=None, **kw):
    if plan is None:
        plan = FleetFaultPlan(clock=ManualClock(tick_s=0.01))
    engine_kw = dict(eos_id=EOS, page_size=PAGE, num_pages=32,
                     max_pages_per_seq=8, max_slots=4, buckets=(8, 16))
    engine_kw.update(kw.pop("engine_kw", {}))
    kw.setdefault("heartbeat_s", 0.05)
    kw.setdefault("resubmit_budget", 2)

    def mk(i, time_fn):
        return ServingEngine(model, params, time_fn=time_fn, **engine_kw)

    return FleetRouter(mk, n, faults=plan, **kw), plan


def _drain_fleet(fl, max_ticks=800):
    out = fl.run(max_ticks=max_ticks)
    assert not fl.has_work, "fleet failed to drain"
    return out


# ---------------------------------------------------------------------------
# export/import roundtrip: bit-identical stored bytes, every pool dtype
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", ["float32", "bfloat16", "int8"])
def test_chain_roundtrip_bit_identical(model_params, kv_dtype):
    model, params = model_params
    src = _engine(model, params, kv_dtype=kv_dtype)
    dst = _engine(model, params, kv_dtype=kv_dtype)
    prompt = list(range(2, 12))                 # 10 tokens: partial tail
    rid = src.submit(prompt, max_tokens=8)
    _run_until_migratable(src, rid)
    blob = export_chain(src, rid)
    assert blob.kind == "chain" and blob.num_pages >= 1
    assert blob.cache_len % PAGE != 0           # tail page in flight
    if kv_dtype == "int8":
        assert blob.quantized and blob.k_scale is not None
    rid2 = import_chain(dst, blob)
    assert rid2 is not None
    req2 = dst._requests[rid2]
    # the destination's spliced pages hold the EXACT bytes the source
    # stored — no requantization, no dtype round-trip
    got = _page_bytes(dst._kv, req2.pages[:blob.num_pages])
    want = tuple(None if a is None else np.asarray(a).tobytes()
                 for a in (blob.k, blob.v, blob.k_scale, blob.v_scale))
    assert got == want
    # mid-migration: BOTH pools conserve while both copies are live
    src.check_page_conservation()
    dst.check_page_conservation()
    src.cancel(rid)
    _drain(dst)
    full = req2.generated
    assert dst.status(rid2) is RequestStatus.COMPLETED
    if kv_dtype == "float32":                   # exact paths only
        ref = greedy_decode_reference(model, params, prompt, 8, EOS)
        assert full == ref
    _drain(src)
    assert_drained(src)
    assert_drained(dst)


def test_import_chain_refuses_geometry_mismatch(model_params):
    model, params = model_params
    src = _engine(model, params)
    dst = _engine(model, params, page_size=8, buckets=(8, 16))
    rid = src.submit(list(range(2, 12)), max_tokens=4)
    _run_until_migratable(src, rid)
    blob = export_chain(src, rid)
    with pytest.raises(EnforceError):
        import_chain(dst, blob)
    dst.check_page_conservation()               # refusal leaks nothing
    _drain(src)
    assert_drained(src)


def test_import_chain_returns_none_when_dest_full(model_params):
    model, params = model_params
    src = _engine(model, params)
    dst = _engine(model, params, max_slots=1)
    blocker = dst.submit(list(range(2, 10)), max_tokens=12)
    _run_until_migratable(dst, blocker)         # the one slot is taken
    rid = src.submit(list(range(2, 12)), max_tokens=4)
    _run_until_migratable(src, rid)
    blob = export_chain(src, rid)
    before = dst.pool.num_free
    assert import_chain(dst, blob) is None
    assert dst.pool.num_free == before          # no slot -> no pages held
    dst.check_page_conservation()
    _drain(src)
    _drain(dst)
    assert_drained(src)
    assert_drained(dst)


def test_cow_shared_chain_survives_migration(model_params):
    """Two requests sharing a cached prefix on the source: migrating one
    must not disturb the sharer's pages or its token stream."""
    model, params = model_params
    src = _engine(model, params)
    dst = _engine(model, params)
    shared = list(range(2, 10))                 # 2 full pages
    warm = src.submit(shared + [20, 21], max_tokens=2)
    _drain(src)                                 # prefix now cached
    assert src.status(warm) is RequestStatus.COMPLETED
    a = src.submit(shared + [22, 23], max_tokens=6)
    b = src.submit(shared + [24, 25], max_tokens=6)
    _run_until_migratable(src, a)
    blob = export_chain(src, a)
    rid2 = import_chain(dst, blob)
    assert rid2 is not None
    src.cancel(a)                               # the handoff's source exit
    _drain(src)
    _drain(dst)
    # the sharer kept decoding on the source, unperturbed
    ref_b = greedy_decode_reference(model, params, shared + [24, 25], 6, EOS)
    assert src.result(b) == ref_b
    ref_a = greedy_decode_reference(model, params, shared + [22, 23], 6, EOS)
    assert dst._requests[rid2].generated == ref_a
    assert_drained(src)
    assert_drained(dst)


# ---------------------------------------------------------------------------
# prefix seeding
# ---------------------------------------------------------------------------


def test_prefix_seed_warms_peer_cache(model_params):
    model, params = model_params
    a = _engine(model, params)
    b = _engine(model, params)
    shared = list(range(2, 14))                 # 3 full pages
    _drain_rid = a.submit(shared + [20], max_tokens=2)
    _drain(a)
    blob = export_prefix(a, shared + [30, 31])
    assert blob is not None and blob.kind == "prefix"
    blocks, nbytes = import_prefix(b, blob)
    assert blocks == 3 and nbytes > 0
    # seeded pages are parked RECLAIMABLE — cached, not held
    assert b.pool.total_refs == 0
    b.check_page_conservation()
    # a same-prefix prompt on B stitches instead of re-prefilling
    rid = b.submit(shared + [32, 33], max_tokens=4)
    _drain(b)
    assert b.metrics.prefill_tokens_saved >= 3 * PAGE - 1
    ref = greedy_decode_reference(model, params, shared + [32, 33], 4, EOS)
    assert b.result(rid) == ref
    assert_drained(a)
    assert_drained(b)


def test_prefix_seed_transfers_only_missing_tail(model_params):
    model, params = model_params
    a = _engine(model, params)
    b = _engine(model, params)
    shared = list(range(2, 14))                 # 3 full pages
    a.submit(shared + [20], max_tokens=2)
    _drain(a)
    b.submit(shared[:PAGE] + [21], max_tokens=2)   # B caches block 0
    _drain(b)
    blob = export_prefix(a, shared)
    blocks, _ = import_prefix(b, blob)
    assert blocks == 2                          # only blocks 1..2 moved
    # idempotent: a second import finds nothing missing
    assert import_prefix(b, blob) == (0, 0)
    assert_drained(a)
    assert_drained(b)


# ---------------------------------------------------------------------------
# scheduler backlog probe (the O(1) signal disagg routing balances on)
# ---------------------------------------------------------------------------


def test_backlog_probe_matches_recompute_and_surfaces(model_params):
    model, params = model_params
    eng = _engine(model, params, role="prefill")
    rng = np.random.RandomState(0)
    sched = eng.scheduler
    assert sched.prefill_backlog_tokens == 0
    rids = [eng.submit(rng.randint(2, 50, size=rng.randint(5, 15)).tolist(),
                       max_tokens=4) for _ in range(6)]
    assert sched.prefill_backlog_tokens == sched.recompute_backlog() > 0
    assert eng.load()["prefill_backlog_tokens"] == \
        sched.prefill_backlog_tokens
    assert eng.load()["role"] == "prefill"
    assert eng.healthz()["role"] == "prefill"
    for _ in range(60):
        eng.step()
        # the incremental probe never drifts from ground truth
        assert sched.prefill_backlog_tokens == sched.recompute_backlog()
        if not eng.has_work:
            break
    assert not eng.has_work
    assert sched.prefill_backlog_tokens == 0
    assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
    assert_drained(eng)


# ---------------------------------------------------------------------------
# disaggregated fleet: routing, handoff, fallback, re-adopt — end to end
# ---------------------------------------------------------------------------


def _trace(rng, n, shared=8):
    sysp = rng.randint(2, 50, size=shared).tolist()
    return [sysp + rng.randint(2, 50, size=4).tolist() for _ in range(n)]


def test_disagg_outputs_token_identical_to_unified(model_params):
    model, params = model_params
    prompts = _trace(np.random.RandomState(0), 8)
    outs = []
    for roles in (None, ("prefill", "prefill", "decode", "decode")):
        kw = {} if roles is None else {"roles": roles}
        fl, _ = _make_fleet(model, params, n=4, migrate_budget=8, **kw)
        frids = [fl.submit(p, max_tokens=6) for p in prompts]
        _drain_fleet(fl)
        check_migration_conservation(fl)
        snap = fl.snapshot()
        if roles is None:
            assert snap["fleet_migrations_started"] == 0   # paths dormant
        else:
            assert snap["fleet_migrations_applied"] > 0
            # prompts only ever dispatch to prefill-class replicas
            for fr in fl._requests.values():
                pass                             # bindings already moved
        outs.append([fl.result(f) for f in frids])
    assert outs[0] == outs[1]                    # migration changed WHERE,
    #                                              never WHAT
    ref = greedy_decode_reference(model, params, prompts[0], 6, EOS)
    assert outs[0][0] == ref


def test_disagg_decode_replicas_never_take_prompts(model_params):
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=3,
                        roles=("prefill", "decode", "decode"),
                        migrate_budget=8)
    seen = []
    orig = fl._dispatch

    def spy(freq, now):
        ok = orig(freq, now)
        if ok and freq.replica is not None:
            seen.append(freq.replica)
        return ok

    fl._dispatch = spy
    for p in _trace(np.random.RandomState(1), 6):
        fl.submit(p, max_tokens=4)
    _drain_fleet(fl)
    assert seen and set(seen) == {0}             # only the prefill replica
    check_migration_conservation(fl)


def test_migration_drop_falls_back_exactly_once(model_params):
    model, params = model_params
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                          drop_migration_at={0, 2})
    fl, _ = _make_fleet(model, params, n=4, plan=plan,
                        roles=("prefill", "prefill", "decode", "decode"),
                        migrate_budget=8)
    prompts = _trace(np.random.RandomState(2), 6)
    streams = {}

    def cb_for(i):
        def cb(tok):
            streams.setdefault(i, []).append(tok)
        return cb

    frids = [fl.submit(p, max_tokens=6, on_token=cb_for(i))
             for i, p in enumerate(prompts)]
    _drain_fleet(fl)
    check_migration_conservation(fl)
    snap = fl.snapshot()
    assert snap["fleet_migration_fallbacks"] == 2
    assert snap["fleet_duplicate_completions"] == 0
    for i, f in enumerate(frids):
        assert fl.status(f) is RequestStatus.COMPLETED
        # exactly-once: the dropped blob's re-prefill replays silently
        # under the high-water fence — streamed == final, no dups
        assert streams[i] == fl.result(f)
        ref = greedy_decode_reference(model, params, prompts[i], 6, EOS)
        assert streams[i] == ref


def test_kill_decode_readopts_surviving_pages(model_params):
    model, params = model_params
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                          kill_at={5: 2})
    fl, _ = _make_fleet(model, params, n=4, plan=plan,
                        roles=("prefill", "prefill", "decode", "decode"),
                        migrate_budget=8)
    prompts = _trace(np.random.RandomState(0), 6)
    frids = [fl.submit(p, max_tokens=6) for p in prompts]
    _drain_fleet(fl)
    check_migration_conservation(fl)
    snap = fl.snapshot()
    assert fl.replicas[2].state is ReplicaState.DEAD
    assert snap["fleet_migrations_applied"] > 0
    # the killed decoder's rids re-dispatched AND re-adopted cached
    # prefix pages from a surviving replica through the page plane
    assert snap["fleet_resubmits"] > 0
    assert snap["fleet_migration_resubmits"] > 0
    assert snap["fleet_seed_pages"] > 0
    for f, p in zip(frids, prompts):
        assert fl.status(f) is RequestStatus.COMPLETED
        assert fl.result(f) == greedy_decode_reference(model, params, p,
                                                       6, EOS)


def test_affinity_seeding_warms_the_chosen_prefill(model_params):
    """Second-wave prompts whose prefix owner is a decode replica (the
    chain migrated there) seed the prefill target instead of letting it
    re-prefill cold."""
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=4,
                        roles=("prefill", "prefill", "decode", "decode"),
                        migrate_budget=8)
    rng = np.random.RandomState(0)
    sysp = rng.randint(2, 50, size=8).tolist()
    frids = [fl.submit(sysp + rng.randint(2, 50, size=4).tolist(),
                       max_tokens=6) for _ in range(6)]
    for _ in range(4):        # wave 1's chains migrate; owners now live
        fl.step()             # on the decode side
    frids += [fl.submit(sysp + rng.randint(2, 50, size=4).tolist(),
                        max_tokens=6) for _ in range(3)]
    _drain_fleet(fl)
    check_migration_conservation(fl)
    snap = fl.snapshot()
    assert snap["fleet_cross_replica_seeds"] > 0
    assert snap["fleet_seed_bytes"] > 0
    assert all(fl.status(f).terminal for f in frids)


def test_int8_migration_bytes_fraction_of_f32():
    """The acceptance arithmetic: an int8 page moves its stored int8
    payload + f32 scales.  Per token-head that is D + 4 bytes against
    f32's 4D, so at the bench geometry (D=16) the ratio is exactly
    20/64 = 0.3125 — under the 0.35 acceptance bar.  (At D=8 the scale
    overhead would be 0.375: the bound is geometry-specific, which is
    why this test pins the bench's head_dim.)"""
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2,
                      head_dim=16, max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    per = {}
    for kv_dtype in ("float32", "int8"):
        fl, _ = _make_fleet(model, params, n=2,
                            roles=("prefill", "decode"), migrate_budget=8,
                            engine_kw=dict(kv_dtype=kv_dtype))
        prompts = _trace(np.random.RandomState(0), 4)
        for p in prompts:
            fl.submit(p, max_tokens=6)
        _drain_fleet(fl)
        check_migration_conservation(fl)
        snap = fl.snapshot()
        assert snap["fleet_migrations_applied"] > 0
        assert snap["fleet_pages_migrated"] > 0
        per[kv_dtype] = (snap["fleet_migration_bytes"] /
                         snap["fleet_pages_migrated"])
    assert per["int8"] / per["float32"] <= 0.35


def test_migrate_selfcheck_gate_is_green(model_params):
    from paddle_tpu.serving.migrate import main
    assert main(["check"]) == 0
