"""Hierarchical KV cache (round 21): host-RAM spill tier with verified
swap-in, graceful degradation under memory pressure, and crash-warm
restart — all on injected clocks, no wall-clock sleeps.

The load-bearing invariants:

- a page swapped in from host memory produces TOKEN-IDENTICAL output to
  a cold re-prefill (the tier is a placement optimization, never a
  semantics change);
- a torn spill or a seeded bit-flip is ALWAYS caught by the per-page
  checksum at swap-in and degrades to a miss + ``HOSTTIER-CORRUPT`` —
  a corrupt page is never served;
- pages conserve across THREE states (device / host / dropped): the
  ``HOSTTIER-LEAK`` ledger balances at any tick, and rides every
  suite's ``assert_serving_drained`` via ``check_page_conservation``;
- the degradation ladder is ordered: device exhaustion spills harder,
  a full host tier LRU-drops its own pages, and only then does the
  engine shed/preempt;
- ``restart_replica`` re-adopts a dead replica's host tier (verified
  page by page) instead of starting cold, composed with the
  lease/fence/resubmit lifecycle and the exactly-once stream fence.

rid counters are GLOBAL (module-level), so cross-engine parity always
compares by submission order within one engine, never by rid.
"""

import jax
import numpy as np
import pytest

from paddle_tpu.platform.enforce import EnforceError
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving.engine import DecoderLM, ServingEngine
from paddle_tpu.serving.faults import (FaultPlan, FleetFaultPlan,
                                       ManualClock, PageLeakError)
from paddle_tpu.serving.fleet import FleetRouter, ReplicaState
from paddle_tpu.serving.kv_cache import (_CHAIN_SEED, HostPageTier,
                                         page_checksum)

from conftest import assert_serving_drained as assert_drained  # noqa: E402

pytestmark = [pytest.mark.serving, pytest.mark.hosttier]

PAGE = 4
EOS = 1


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


@pytest.fixture(scope="module")
def model_params():
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=128)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, **kw):
    base = dict(eos_id=EOS, page_size=PAGE, num_pages=16,
                max_pages_per_seq=8, max_slots=2, buckets=(8, 16),
                host_tier_bytes=1 << 20, swap_in_budget=4)
    base.update(kw)
    if "faults" not in base:
        base["faults"] = FaultPlan(seed=0, clock=ManualClock(tick_s=0.01))
    return ServingEngine(model, params, **base)


def _prompt(n=16, seed=0):
    return np.random.RandomState(seed).randint(2, 50, size=n).tolist()


def _payload(fill=1.0):
    """One synthetic page payload shaped like read_pages output."""
    k = np.full((1, 1, PAGE, 2, 8), fill, np.float32)
    v = np.full((1, 1, PAGE, 2, 8), fill + 0.5, np.float32)
    return k, v, None, None


# ---------------------------------------------------------------------------
# HostPageTier unit tests
# ---------------------------------------------------------------------------


class TestHostTierUnit:
    def test_depth_one_writer(self):
        """spill() stages; the NEXT spill (or pump/flush) commits — at
        most one write is ever in flight, exactly the checkpointer's
        pipelined-writer discipline."""
        tier = HostPageTier(1 << 20)
        tier.spill(1, _CHAIN_SEED, (1, 2, 3, 4), _payload())
        assert len(tier) == 0 and tier.spills == 1   # staged, not resident
        tier.spill(2, 1, (5, 6, 7, 8), _payload(2.0))
        assert len(tier) == 1                        # first committed
        assert tier.pump(tick=0) == 1
        assert len(tier) == 2 and tier.pump(tick=1) == 0
        tier.check()

    def test_checksum_roundtrip_and_verify(self):
        tier = HostPageTier(1 << 20)
        k, v, _, _ = _payload()
        tier.spill(7, _CHAIN_SEED, (9, 9, 9, 9), (k, v, None, None))
        tier.flush()
        rec = tier.take_verified(7, _CHAIN_SEED, (9, 9, 9, 9))
        assert rec is not None and tier.swap_ins == 1
        np.testing.assert_array_equal(rec.k, k)
        np.testing.assert_array_equal(rec.v, v)
        assert rec.checksum == page_checksum(rec.k, rec.v)
        tier.check()

    def test_tampered_bytes_degrade_to_miss(self):
        """Corruption after commit is caught at swap-in: the record is
        consumed as HOSTTIER-CORRUPT, never returned."""
        tier = HostPageTier(1 << 20)
        tier.spill(7, _CHAIN_SEED, (9, 9, 9, 9), _payload())
        tier.flush()
        rec = next(iter(tier._index.values()))
        rec.v.reshape(-1)[0] += 1.0          # bit rot
        assert tier.take_verified(7, _CHAIN_SEED, (9, 9, 9, 9)) is None
        assert tier.corrupt == 1 and tier.swap_ins == 0
        tier.check()

    def test_peek_is_pure(self):
        tier = HostPageTier(1 << 20)
        tier.spill(7, _CHAIN_SEED, (9, 9, 9, 9), _payload())
        tier.flush()
        assert tier.peek(7, _CHAIN_SEED, (9, 9, 9, 9)) is not None
        assert tier.peek(7, _CHAIN_SEED, (9, 9, 9, 8)) is None  # wrong toks
        assert tier.peek(7, 123, (9, 9, 9, 9)) is None          # wrong prev
        assert len(tier) == 1 and tier.swap_ins == 0
        tier.check()

    def test_lru_drop_at_capacity(self):
        """Host tier full -> the OLDEST host page drops (ladder rung 3);
        the ledger still balances."""
        one = sum(x.nbytes for x in _payload()[:2])
        tier = HostPageTier(2 * one)
        for i in range(4):
            tier.spill(10 + i, _CHAIN_SEED, (i,) * PAGE, _payload(float(i)))
        tier.flush()
        assert len(tier) == 2 and tier.dropped == 2
        assert tier.peek(10, _CHAIN_SEED, (0,) * PAGE) is None   # oldest out
        assert tier.peek(13, _CHAIN_SEED, (3,) * PAGE) is not None
        assert tier.resident_bytes <= tier.capacity_bytes
        tier.check()

    def test_forget_and_adopt(self):
        """forget() drops named keys; adopt() re-verifies a dead tier's
        pages into a fresh one, balancing BOTH ledgers (handed_off on
        the donor, adopted/restored on the successor)."""
        old = HostPageTier(1 << 20)
        for i in range(3):
            old.spill(20 + i, _CHAIN_SEED, (i,) * PAGE, _payload(float(i)))
        old.flush()
        old.forget([21])
        assert old.dropped == 1 and len(old) == 2
        # corrupt one survivor: adoption must catch it
        next(iter(old._index.values())).k.reshape(-1)[0] += 9.0
        new = HostPageTier(1 << 20)
        new.adopt(old)
        assert new.restored == 1 and new.corrupt == 1
        assert len(old) == 0 and old.handed_off == 2
        old.check()
        new.check()

    def test_ledger_violation_raises(self):
        tier = HostPageTier(1 << 20)
        tier.spill(1, _CHAIN_SEED, (1,) * PAGE, _payload())
        tier.flush()
        tier.spills += 1                      # cook the books
        with pytest.raises(PageLeakError, match="HOSTTIER-LEAK"):
            tier.check()


# ---------------------------------------------------------------------------
# engine-level: spill, verified swap-in, parity, degradation
# ---------------------------------------------------------------------------


def _roundtrip(eng, prompt, max_tokens=6):
    """cold serve -> flush (spill everything) -> warm serve on the SAME
    engine; returns (cold, warm) token lists."""
    r1 = eng.submit(list(prompt), max_tokens=max_tokens)
    eng.run()
    cold = eng.result(r1)
    eng.cache.flush()
    r2 = eng.submit(list(prompt), max_tokens=max_tokens)
    eng.run()
    return cold, eng.result(r2)


class TestEngineSwapIn:
    def test_swap_in_parity_vs_cold_prefill(self, model_params):
        """The tentpole parity pin: an evicted-then-spilled prefix served
        back through verified swap-in is token-identical to the cold
        serve, and the second serve barely re-prefills."""
        eng = _engine(*model_params)
        cold, warm = _roundtrip(eng, _prompt())
        assert warm == cold
        snap = eng.host_tier.snapshot()
        assert snap["host_swap_outs"] >= 4     # 4 full pages spilled
        assert snap["host_swap_ins"] >= 4      # ... and all came back
        assert snap["host_corrupt"] == 0
        assert eng._host_hits >= 1
        hz = eng.healthz()
        assert hz["host_swap_ins"] == snap["host_swap_ins"]
        assert_drained(eng)

    def test_swap_in_budget_bounds_per_tick(self, model_params):
        """swap_in_budget=1 swaps exactly ONE page ahead of admission —
        the rest of the prefix re-prefills normally (swap-in never
        delays admission to finish the chain) — and stays
        token-identical.  The unswapped host pages remain resident."""
        eng = _engine(*model_params, swap_in_budget=1)
        cold, warm = _roundtrip(eng, _prompt())
        assert warm == cold
        snap = eng.host_tier.snapshot()
        assert snap["host_swap_ins"] == 1
        assert snap["pages_host"] >= 2        # chain tail stayed on host
        assert_drained(eng)

    def test_torn_spill_degrades_to_miss(self, model_params):
        """Fault rung: the FIRST spill commits torn (tail half of V
        zeroed after the checksum was taken).  Swap-in must catch it —
        HOSTTIER-CORRUPT, a plain re-prefill, identical tokens."""
        eng = _engine(*model_params,
                      faults=FaultPlan(seed=0,
                                       clock=ManualClock(tick_s=0.01),
                                       torn_spill_at={0}))
        cold, warm = _roundtrip(eng, _prompt())
        assert warm == cold                    # never served corrupt KV
        assert eng.host_tier.corrupt >= 1
        assert_drained(eng)

    def test_bitflip_caught_never_hittable(self, model_params):
        """A seeded one-byte flip in K is caught by the checksum; the
        corrupt record is consumed (miss), never hittable again."""
        eng = _engine(*model_params,
                      faults=FaultPlan(seed=0,
                                       clock=ManualClock(tick_s=0.01),
                                       bitflip_spill_at={0}))
        cold, warm = _roundtrip(eng, _prompt())
        assert warm == cold
        assert eng.host_tier.corrupt >= 1
        # the corrupted chain head is gone for good: a third serve of the
        # same prompt cannot re-hit the corrupt record
        before = eng.host_tier.corrupt
        r3 = eng.submit(_prompt(), max_tokens=6)
        eng.run()
        assert eng.result(r3) == cold
        assert eng.host_tier.corrupt == before
        assert_drained(eng)

    def test_slow_host_io_stalls_writer_not_decode(self, model_params):
        """A slow-host-I/O window leaves the staged spill pending
        (spill_stall_ticks counts the wait) but decode keeps running and
        drain flushes it — nothing lost, nothing leaked."""
        eng = _engine(*model_params,
                      faults=FaultPlan(seed=0,
                                       clock=ManualClock(tick_s=0.01),
                                       slow_host_io=(0, 10_000)))
        cold, warm = _roundtrip(eng, _prompt())
        assert warm == cold
        assert eng.host_tier.spill_stall_ticks > 0
        assert_drained(eng)

    def test_int8_host_dtype_parity(self, model_params):
        """host_kv_dtype="int8" transcodes float pages on spill (~4x
        host capacity) and dequantizes on swap-in; greedy decode over a
        tiny model stays token-identical."""
        eng = _engine(*model_params, host_kv_dtype="int8")
        cold, warm = _roundtrip(eng, _prompt())
        assert warm == cold
        snap = eng.host_tier.snapshot()
        assert snap["host_swap_ins"] >= 1
        assert_drained(eng)

    def test_pressure_ladder_ordering(self, model_params):
        """Graceful degradation: a pool too small for the working set
        spills on eviction (rung 2), a host tier sized for ~2 pages
        LRU-drops its own oldest pages (rung 3) — and the engine never
        had to shed or preempt (rung 4 stays dry)."""
        one_page = 2 * (1 * 1 * PAGE * 2 * 8 * 4)     # k+v f32 bytes
        eng = _engine(*model_params, num_pages=12,
                      host_tier_bytes=2 * one_page + one_page // 2)
        outs = []
        for s in range(6):
            rid = eng.submit(_prompt(12, seed=s), max_tokens=4)
            eng.run()
            outs.append(eng.result(rid))
            eng.cache.flush()                 # force demotion pressure
        snap = eng.host_tier.snapshot()
        assert snap["host_swap_outs"] >= 6    # rung 2: spilling hard
        assert snap["host_dropped"] >= 1      # rung 3: host LRU-drop
        assert eng.metrics.shed == 0          # rung 4: never reached
        assert eng.metrics.preemptions == 0
        assert all(o is not None for o in outs)
        assert_drained(eng)

    def test_three_state_conservation_rides_drain_check(self, model_params):
        """check_page_conservation now covers the host ledger: cooking
        the tier's books makes the ENGINE check raise HOSTTIER-LEAK."""
        eng = _engine(*model_params)
        _roundtrip(eng, _prompt())
        eng.check_page_conservation()         # clean first
        eng.host_tier.spills += 3
        with pytest.raises(PageLeakError, match="HOSTTIER-LEAK"):
            eng.check_page_conservation()
        eng.host_tier.spills -= 3
        assert_drained(eng)

    def test_gauges_in_load_healthz_and_tenants(self, model_params):
        eng = _engine(*model_params)
        r1 = eng.submit(_prompt(), max_tokens=4, tenant="acme")
        eng.run()
        eng.cache.flush()
        eng.host_tier.flush()                 # commit the staged spill
        assert eng.load()["pages_host"] >= 4
        hz = eng.healthz()
        assert hz["pages_host"] >= 4
        assert hz["host_swap_outs"] >= 4
        assert eng.tenant_counts()["acme"]["pages_host"] >= 4
        r2 = eng.submit(_prompt(), max_tokens=4, tenant="acme")
        eng.run()
        assert eng.result(r2) == eng.result(r1)
        assert eng.healthz()["host_swap_ins"] >= 1
        assert_drained(eng)

    def test_tier_off_is_inert(self, model_params):
        """host_tier_bytes=0 (the default flag) keeps the classic
        engine: no tier object, zeroed gauges, identical behavior."""
        eng = _engine(*model_params, host_tier_bytes=0)
        assert eng.host_tier is None
        cold, warm = _roundtrip(eng, _prompt())
        assert warm == cold
        assert eng.healthz()["pages_host"] == 0
        assert_drained(eng)


# ---------------------------------------------------------------------------
# fleet-level: crash-warm restart, exactly-once, migration compose
# ---------------------------------------------------------------------------


def _mk_fleet(model, params, n=2, *, plan=None, tier=1 << 20, **kw):
    plan = plan or FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01))

    def mk(i, time_fn):
        return ServingEngine(model, params, eos_id=EOS, page_size=PAGE,
                             num_pages=32, max_pages_per_seq=8, max_slots=4,
                             buckets=(8, 16), time_fn=time_fn,
                             host_tier_bytes=tier, swap_in_budget=4)

    return FleetRouter(mk, n, heartbeat_s=0.05, resubmit_budget=2,
                       faults=plan, **kw)


class TestFleetWarmRestart:
    def test_restart_replica_adopts_host_tier(self, model_params):
        """Kill a replica whose host tier holds spilled pages; the warm
        successor re-adopts them (verified) and serves the same prompt
        token-identically with real swap-ins — not a cold start."""
        fleet = _mk_fleet(*model_params)
        prompt = _prompt()
        f1 = fleet.submit(list(prompt), max_tokens=6)
        fleet.run(max_ticks=200)
        cold = fleet.result(f1)
        victim = next(r.idx for r in fleet.replicas
                      if r.engine.cache is not None and len(r.engine.cache))
        fleet.replicas[victim].engine.cache.flush()
        fleet.kill_replica(victim)
        new_idx = fleet.restart_replica(victim)
        assert fleet.metrics.warm_restarts == 1
        assert fleet.metrics.pages_restored >= 4
        fleet.drain_replica(1 - victim)       # force traffic to successor
        for _ in range(5):
            fleet.step()
        assert fleet.replica_state(new_idx) is ReplicaState.READY
        f2 = fleet.submit(list(prompt), max_tokens=6)
        fleet.run(max_ticks=200)
        assert fleet.result(f2) == cold
        succ = fleet.replicas[new_idx].engine
        assert succ.host_tier.snapshot()["host_swap_ins"] >= 1
        assert fleet.metrics.duplicate_completions == 0
        fleet.check_fleet_conservation()

    def test_restart_requires_dead(self, model_params):
        fleet = _mk_fleet(*model_params)
        with pytest.raises(EnforceError):
            fleet.restart_replica(0)

    def test_kill_mid_flight_exactly_once_with_restart(self, model_params):
        """A kill mid-decode resubmits to the survivor; the exactly-once
        fence dedups the replay; restart_replica afterwards neither
        duplicates completions nor corrupts the stream."""
        plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                              kill_at={3: 0})
        fleet = _mk_fleet(*model_params, plan=plan)
        streams = {}
        frids = []
        for s in range(4):
            p = _prompt(12, seed=s)
            streams[s] = []
            frids.append(fleet.submit(
                p, max_tokens=10,
                on_token=lambda t, s=s: streams[s].append(t)))
        fleet.run(max_ticks=400)
        # the injected kill fenced replica 0: restart it warm
        dead = [r.idx for r in fleet.replicas
                if r.state is ReplicaState.DEAD]
        assert dead
        fleet.restart_replica(dead[0])
        for _ in range(3):
            fleet.step()
        for s, frid in enumerate(frids):
            res = fleet.result(frid)
            if res is not None:               # completed (not shed)
                assert streams[s] == res      # exactly-once, in order
        assert fleet.metrics.duplicate_completions == 0
        fleet.check_fleet_conservation()

    def test_migrated_chain_source_host_pages_forgotten(self, model_params):
        """Spill + migration compose: when a chain hands off to a decode
        replica, any host copies the source spilled for that chain are
        forgotten — a later warm restart of the source cannot re-adopt
        pages the migration already moved (no double-adopt)."""
        model, params = model_params
        plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01))

        def mk(i, time_fn):
            return ServingEngine(model, params, eos_id=EOS, page_size=PAGE,
                                 num_pages=32, max_pages_per_seq=8,
                                 max_slots=4, buckets=(8, 16),
                                 time_fn=time_fn, host_tier_bytes=1 << 20,
                                 swap_in_budget=4)

        fleet = FleetRouter(mk, 2, heartbeat_s=0.05, resubmit_budget=2,
                            faults=plan, roles=["prefill", "decode"],
                            migrate_budget=64)
        prompt = _prompt()
        src = fleet.replicas[0].engine
        frid = fleet.submit(list(prompt), max_tokens=6)
        # tick until the handoff is pending, then plant host copies of
        # the chain on the source BEFORE the pump applies it
        for _ in range(50):
            fleet.step()
            if frid in fleet._mig_pending:
                break
        assert frid in fleet._mig_pending
        keys = src.cache.chain_keys(prompt)
        for i, key in enumerate(keys):
            prev = _CHAIN_SEED if i == 0 else keys[i - 1]
            src.host_tier.spill(key, prev,
                                tuple(prompt[i * PAGE:(i + 1) * PAGE]),
                                _payload(float(i)))
        src.host_tier.flush()
        assert len(src.host_tier) == len(keys)
        fleet.run(max_ticks=200)
        assert fleet.metrics.migrations_applied >= 1
        # every chain key was forgotten at apply time
        for i, key in enumerate(keys):
            prev = _CHAIN_SEED if i == 0 else keys[i - 1]
            assert src.host_tier.peek(
                key, prev, tuple(prompt[i * PAGE:(i + 1) * PAGE])) is None
        assert src.host_tier.dropped >= len(keys)
        # ... so a warm restart of the source re-adopts NONE of them
        fleet.kill_replica(0)
        fleet.restart_replica(0)
        assert fleet.metrics.pages_restored == 0
        assert fleet.metrics.duplicate_completions == 0
        fleet.check_fleet_conservation()

    def test_fleet_healthz_reports_pages_host(self, model_params):
        fleet = _mk_fleet(*model_params)
        f1 = fleet.submit(_prompt(), max_tokens=4, tenant="acme")
        fleet.run(max_ticks=200)
        for rep in fleet.replicas:
            if rep.engine.cache is not None:
                rep.engine.cache.flush()
            if rep.engine.host_tier is not None:
                rep.engine.host_tier.flush()
        hz = fleet.healthz()
        assert sum(r["pages_host"] for r in hz["replicas"].values()) >= 4
        assert "pages_host" in hz["tenants"]["acme"]
        assert hz["tenants"]["acme"]["pages_host"] >= 4
        fleet.check_fleet_conservation()
