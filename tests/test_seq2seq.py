"""seq2seq NMT integration — trains on a toy copy task and checks the
generator shares trained weights (reference analog: seqToseq demo +
test_recurrent_machine_generation)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import event, optimizer, trainer
from paddle_tpu.models import seq2seq
from paddle_tpu.platform.flags import FLAGS

V = 20
BOS, EOS = 0, 1


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def _copy_task(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        ln = int(rng.randint(2, 6))
        src = [int(t) for t in rng.randint(2, V, ln)]
        yield src, [BOS] + src, src + [EOS]


def test_seq2seq_trains_and_generates():
    paddle.topology.reset_name_scope()
    cost, probs = seq2seq.build_train(src_dict_size=V, trg_dict_size=V,
                                      embed_size=16, hidden=16)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=4)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))

    data = list(_copy_task(96, seed=0))
    costs = []
    sgd.train(paddle.batch(lambda: iter(data), 16), num_passes=8,
              event_handler=lambda ev: costs.append(float(ev.cost))
              if isinstance(ev, event.EndIteration) else None)
    assert np.isfinite(costs).all()
    assert np.mean(costs[-6:]) < np.mean(costs[:6]) * 0.9, \
        f"no learning: {np.mean(costs[:6])} -> {np.mean(costs[-6:])}"

    # generator topology shares parameter keys with training topology
    paddle.topology.reset_name_scope()
    beam = seq2seq.build_generator(src_dict_size=V, trg_dict_size=V,
                                   embed_size=16, hidden=16, bos_id=BOS,
                                   eos_id=EOS, beam_size=3, max_length=8)
    gen_topo = paddle.topology.Topology([beam])
    gen_keys = set(gen_topo.param_specs().keys())
    train_keys = set(topo.param_specs().keys())
    missing = gen_keys - train_keys
    assert not missing, f"generator params missing from training: {missing}"

    # run generation with the TRAINED parameters
    inf = paddle.Inference(output_layer=beam, parameters=params)
    src_batch = [([3, 4, 5],), ([7, 8],)]
    results = list(inf.iter_infer([src_batch]))
    tokens, lengths, scores = results[0][0]
    tokens = np.asarray(tokens)
    assert tokens.shape == (2, 3, 8)
    assert np.asarray(scores).shape == (2, 3)
    assert ((tokens >= 0) & (tokens < V)).all()
