"""Pruning hook + SparseMomentum + per-param grad stats tests.

Reference analogs: ParameterUpdaterHook.cpp:39-104 (StaticPruningHook),
FirstOrderOptimizer.h:61-125 (SparseMomentumParameterOptimizer),
TrainerInternal.cpp:80-110 (show_param_stats_period avg/max abs grad).
"""

import logging

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.attr import HookAttr, ParamAttr
from paddle_tpu.platform.flags import FLAGS


def _build(hooked=False):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(16))
    y = layer.data(name="y", type=paddle.data_type.integer_value(4))
    pa = ParamAttr(update_hooks=HookAttr("pruning", sparsity_ratio=0.75)) \
        if hooked else None
    h = layer.fc(input=x, size=32, act="relu", param_attr=pa)
    cost = layer.classification_cost(input=layer.fc(input=h, size=4), label=y)
    return cost


def _data(rng, n=64, dim=16, classes=4):
    return [(rng.randn(dim).astype(np.float32), int(rng.randint(classes)))
            for _ in range(n)]


def test_pruning_hook_masks_stay_zero():
    """75%-sparsified fc weight: pruned entries are zero at init AND stay
    zero through momentum training (StaticPruningHook semantics)."""
    rng = np.random.RandomState(0)
    cost = _build(hooked=True)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=1)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Momentum(
                          momentum=0.9, learning_rate=0.1,
                          regularization=optimizer.L2Regularization(1e-3)))
    wname = [n for n in params.names() if n.startswith("fc_0") and ".w" in n][0]
    mask = np.asarray(sgd.opt_state["prune_masks"][wname])
    frac = mask.mean()
    assert 0.2 < frac < 0.3, frac          # ~25% kept

    reader = paddle.batch(lambda: iter(_data(rng)), 16)
    sgd.train(reader, num_passes=3, event_handler=lambda ev: None)
    w = np.asarray(sgd.parameters[wname])
    assert np.all(w[mask == 0] == 0.0), "pruned weights resurrected"
    assert np.abs(w[mask == 1]).sum() > 0   # kept weights trained


def test_unhooked_params_have_no_masks():
    cost = _build(hooked=False)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=1)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Sgd(learning_rate=0.1))
    assert "prune_masks" not in sgd.opt_state


def test_sparse_momentum_equals_momentum():
    """decay_rate=0: the lazy u/v scheme reproduces heavy-ball momentum
    exactly (the equivalence the reference's scheme is built on)."""
    rng = np.random.RandomState(42)
    p0 = {"w": rng.randn(8, 4).astype(np.float32)}
    grads = [{"w": rng.randn(8, 4).astype(np.float32)} for _ in range(6)]

    om = optimizer.Momentum(momentum=0.9, learning_rate=0.05)
    osm = optimizer.SparseMomentum(momentum=0.9, learning_rate=0.05)
    pm, sm_ = dict(p0), om.init_state(p0)
    ps, ss = dict(p0), osm.init_state(p0)
    for g in grads:
        pm, sm_ = om.apply(pm, g, sm_)
        ps, ss = osm.apply(ps, g, ss)
        np.testing.assert_allclose(np.asarray(ps["w"]), np.asarray(pm["w"]),
                                   rtol=2e-5, atol=2e-6)


def test_sparse_momentum_restart_is_seamless():
    """Force the alpha>threshold restart every few steps: trajectory must
    stay (approximately) the plain-momentum one across the reset."""
    rng = np.random.RandomState(1)
    p0 = {"w": rng.randn(10).astype(np.float32)}
    grads = [{"w": rng.randn(10).astype(np.float32)} for _ in range(12)]

    om = optimizer.Momentum(momentum=0.5, learning_rate=0.1)
    # momentum 0.5 -> alpha doubles per step; threshold 8 restarts ~every 3
    osm = optimizer.SparseMomentum(momentum=0.5, learning_rate=0.1,
                                   threshold=8.0)
    pm, sm_ = dict(p0), om.init_state(p0)
    ps, ss = dict(p0), osm.init_state(p0)
    for g in grads:
        pm, sm_ = om.apply(pm, g, sm_)
        ps, ss = osm.apply(ps, g, ss)
    # restart drops a tiny u/alpha residue; bounded, not exact
    np.testing.assert_allclose(np.asarray(ps["w"]), np.asarray(pm["w"]),
                               rtol=0.05, atol=0.05)


def test_param_grad_stats_logged(caplog):
    rng = np.random.RandomState(2)
    cost = _build()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=1)
    FLAGS.update(show_parameter_stats_period=2)
    try:
        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Sgd(learning_rate=0.1))
        reader = paddle.batch(lambda: iter(_data(rng)), 16)
        # plog's logger doesn't propagate to root; attach caplog's handler
        handler = caplog.handler
        plog_logger = logging.getLogger("paddle_tpu")
        plog_logger.addHandler(handler)
        try:
            sgd.train(reader, num_passes=1, event_handler=lambda ev: None)
        finally:
            plog_logger.removeHandler(handler)
    finally:
        FLAGS.update(show_parameter_stats_period=0)
    stats_lines = [r.getMessage() for r in caplog.records
                   if "avgAbsGrad" in r.getMessage()]
    assert stats_lines, "no param stats logged"
    # one line per parameter per logging point, finite values
    assert any("fc_0" in ln for ln in stats_lines)
    for ln in stats_lines:
        assert "nan" not in ln.lower()
