"""Layer/stage placement model parallelism (ParallelNeuralNetwork analog).

Reference bar: paddle/gserver/gradientmachines/ParallelNeuralNetwork.h:15-70
lets a model too big for one device train by placing layers on devices. The
TPU-native equivalent (parallel/placement.py) shards each stage's weights
AND activations over the 'model' mesh axis — verified here on the virtual
8-device mesh: weights are genuinely distributed (1/8 of the bytes per
device), training runs and converges, and the result matches an identical
unsharded model bit-for-bit within tolerance.
"""

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.parallel import make_mesh, model_parallel_mlp


HIDDEN = [512, 512]
IN_DIM, OUT_DIM = 64, 10


def _build(mp: bool):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(IN_DIM))
    y = layer.data(name="y", type=paddle.data_type.integer_value(OUT_DIM))
    if mp:
        logits = model_parallel_mlp(x, HIDDEN, OUT_DIM, axis="model")
    else:
        net = x
        for i, h in enumerate(HIDDEN):
            net = layer.fc(input=net, size=h, act="relu", name=f"mp_fc{i}")
        logits = layer.fc(input=net, size=OUT_DIM, name="mp_out")
    cost = layer.classification_cost(input=logits, label=y)
    return cost


_LABEL_W = np.random.RandomState(99).randn(IN_DIM, OUT_DIM)


def _batch(rng, n=32):
    """Learnable task: label = argmax of a fixed random projection."""
    xs = rng.randn(n, IN_DIM).astype(np.float32)
    ys = np.argmax(xs @ _LABEL_W, axis=1)
    return [(xs[i], int(ys[i])) for i in range(n)]


def test_model_parallel_weights_are_distributed():
    mesh = make_mesh((8,), ("model",))
    cost = _build(mp=True)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=3)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=3e-3),
                      mesh=mesh)
    # every stage weight is sharded: per-device shard holds 1/8 of bytes —
    # the "too big to replicate" capability (no full copy anywhere)
    for pname in ["mp_fc0.w0", "mp_fc1.w0", "mp_out.w0"]:
        v = sgd.parameters[pname]
        shard = v.addressable_shards[0].data
        assert shard.nbytes * 8 == v.nbytes, \
            f"{pname} not distributed: {shard.shape} vs {v.shape}"
        # optimizer slots inherit the sharding AT INIT (params are placed
        # before slot creation — no transient full replica on one device)
        for sname, tree in sgd.opt_state["slots"].items():
            sv = tree[pname]
            assert sv.addressable_shards[0].data.nbytes * 8 == sv.nbytes, \
                f"slot {sname}[{pname}] not sharded at init"

    rng = np.random.RandomState(0)
    costs = []
    sgd.train(lambda: iter([_batch(rng) for _ in range(80)]), num_passes=1,
              event_handler=lambda ev: costs.append(float(ev.cost))
              if isinstance(ev, paddle.event.EndIteration) else None)
    assert np.mean(costs[-5:]) < np.mean(costs[:5]) / 2, \
        "model-parallel training failed to learn"

    # params remain sharded after training (no silent gather)
    v = sgd.parameters["mp_fc0.w0"]
    assert v.addressable_shards[0].data.nbytes * 8 == v.nbytes


def test_model_parallel_matches_single_device():
    """Same seed, same data: the TP-sharded model must compute the same
    updates as the plain replicated model (test_NetworkCompare analog)."""
    rng_data = np.random.RandomState(7)
    batches = [_batch(rng_data) for _ in range(5)]

    def run(mp, mesh):
        cost = _build(mp)
        params = paddle.Parameters.from_topology(
            paddle.topology.Topology([cost]), seed=11)
        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Adam(learning_rate=1e-2),
                          mesh=mesh)
        sgd.train(lambda: iter(list(batches)), num_passes=1)
        return {k: np.asarray(sgd.parameters[k])
                for k in sgd.parameters.names()}

    ref = run(False, None)
    got = run(True, make_mesh((8,), ("model",)))
    assert set(ref) == set(got)
    # SPMD partitioning reassociates reductions; Adam's per-param rescale
    # (g/sqrt(v)) amplifies the roundoff wherever v is tiny, so parity is
    # close-but-not-bitwise.  Documented bound instead of a hard-coded
    # guess: Adam moves each element at most ~lr per step regardless of
    # gradient scale, so over the 5 training steps at lr=1e-2 a roundoff-
    # flipped element can drift by at most the 5-step envelope 5*lr =
    # 5e-2; atol takes half that (trajectories drift apart, not in
    # lockstep opposition — observed worst case on this jax/CPU combo is
    # 1.3e-2, a handful of near-zero elements).  The aggregate bound
    # below keeps the test's power: WIDESPREAD divergence (a real TP
    # bug, not reassociation roundoff) still fails loudly.
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=5e-3, atol=2.5e-2,
                                   err_msg=k)
        mean_drift = float(np.mean(np.abs(got[k] - ref[k])))
        assert mean_drift < 5e-4, \
            f"{k}: mean |tp - ref| = {mean_drift:.2e} — systematic " \
            "divergence, not per-element Adam roundoff"


def test_stage_activation_sharding_constraint_in_hlo():
    """The compiled step must contain the activation sharding (custom call
    Sharding / all-reduce from the row-parallel stage)."""
    mesh = make_mesh((8,), ("model",))
    cost = _build(mp=True)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=3)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Momentum(
                          momentum=0.9, learning_rate=0.1), mesh=mesh)
    feeds = sgd._make_feeder(None).feed(_batch(np.random.RandomState(1)))
    feeds = sgd._shard_feeds(feeds)
    step = sgd._build_step()
    args = (sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state,
            jax.random.PRNGKey(0), feeds)
    txt = step.lower(*args).compile().as_text()
    assert "all-reduce" in txt, "row-parallel psum missing from HLO"


def test_checkpoint_resume_preserves_sharding(tmp_path):
    """load_checkpoint hands back host arrays; the trainer must re-place
    params AND optimizer slots on the mesh, or a resume silently
    replicates 'too big to replicate' weights on every device."""
    mesh = make_mesh((8,), ("model",))
    cost = _build(mp=True)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=3)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=3e-3),
                      mesh=mesh)
    rng = np.random.RandomState(0)
    sgd.train(lambda: iter([_batch(rng) for _ in range(3)]), num_passes=1,
              save_dir=str(tmp_path))

    cost2 = _build(mp=True)
    params2 = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost2]), seed=4)
    sgd2 = trainer.SGD(cost=cost2, parameters=params2,
                       update_equation=optimizer.Adam(learning_rate=3e-3),
                       mesh=mesh)
    sgd2.load_checkpoint(str(tmp_path))
    for pname in ["mp_fc0.w0", "mp_fc1.w0", "mp_out.w0"]:
        v = sgd2.parameters[pname]
        assert v.addressable_shards[0].data.nbytes * 8 == v.nbytes, \
            f"{pname} replicated after resume"
        for sname, tree in sgd2.opt_state["slots"].items():
            sv = tree[pname]
            assert sv.addressable_shards[0].data.nbytes * 8 == sv.nbytes, \
                f"slot {sname}[{pname}] replicated after resume"
    # resumed values match the checkpointed ones
    np.testing.assert_allclose(np.asarray(sgd2.parameters["mp_out.w0"]),
                               np.asarray(sgd.parameters["mp_out.w0"]),
                               rtol=1e-6)
