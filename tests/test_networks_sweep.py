"""networks.py helper coverage: the composite-network builders the
reference ships in trainer_config_helpers/networks.py, each built, run
forward, and (where cheap) gradient-sanity-checked — plus a breadth gate
so every exported helper stays exercised somewhere in tests/ or models/.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import layer, networks
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import Topology

RNG = np.random.RandomState(41)


def forward(node, feeds, seed=0, train=False, rng=None):
    topo = Topology([node])
    params = paddle.Parameters.from_topology(topo, seed=seed)
    outs, _ = topo.forward(params.as_dict(), topo.init_state(), feeds,
                           train=train, rng=rng)
    return outs[0], params, topo


def _seq(dim, lens, seed=3):
    rng = np.random.RandomState(seed)
    return SequenceBatch.from_list(
        [rng.randn(l, dim).astype(np.float32) * 0.5 for l in lens])


def test_img_conv_group_shapes_and_bn():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3 * 8 * 8),
                   height=8, width=8)
    out = networks.img_conv_group(x, conv_num_filter=[4, 4],
                                  conv_with_batchnorm=True, num_channels=3)
    fx = RNG.randn(2, 3 * 8 * 8).astype(np.float32)
    got, _, topo = forward(out, {"x": fx})
    assert np.asarray(got).reshape(2, -1).shape == (2, 4 * 4 * 4)
    assert np.isfinite(np.asarray(got)).all()
    # BN state threads through the group (moving stats namespaces exist)
    assert topo.init_state(), "batch_norm state expected"


def test_vgg_16_network_builds_and_runs():
    paddle.topology.reset_name_scope()
    x = layer.data(name="img", type=paddle.data_type.dense_vector(3 * 32 * 32),
                   height=32, width=32)
    out = networks.vgg_16_network(x, num_channels=3, num_classes=10)
    fx = RNG.randn(1, 3 * 32 * 32).astype(np.float32)
    got, _, _ = forward(out, {"img": fx})
    probs = np.asarray(got)
    assert probs.shape == (1, 10)
    np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-4)


def test_bidirectional_gru_matches_two_directions():
    paddle.topology.reset_name_scope()
    H, D = 3, 4
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(D))
    bi = networks.bidirectional_gru(s, size=H, name="bg")
    sb = _seq(D, [3, 2])
    got, params, _ = forward(bi, {"s": sb}, seed=5)
    # same weights, run the two directions separately and concat by hand
    paddle.topology.reset_name_scope()
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(D))
    fwd = networks.simple_gru(s, size=H, reverse=False, name="bg_fwd")
    bwd = networks.simple_gru(s, size=H, reverse=True, name="bg_bwd")
    topo2 = Topology([fwd, bwd])
    p2 = paddle.Parameters.from_topology(topo2, seed=5)
    p2.update_from({k: np.asarray(v) for k, v in params.as_dict().items()
                    if k in dict(p2.as_dict())})
    outs, _ = topo2.forward(p2.as_dict(), topo2.init_state(), {"s": sb})
    want = np.concatenate([np.asarray(outs[0].data),
                           np.asarray(outs[1].data)], axis=-1)
    np.testing.assert_allclose(np.asarray(got.data), want, rtol=1e-5,
                               atol=1e-6)
    # return_seq=False variant: last fwd + first bwd states
    paddle.topology.reset_name_scope()
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(D))
    pooled = networks.bidirectional_gru(s, size=H, return_seq=False,
                                        name="bg2")
    got2, _, _ = forward(pooled, {"s": sb}, seed=5)
    assert np.asarray(got2).shape == (2, 2 * H)


def test_every_network_helper_is_exercised():
    """Breadth gate over networks.py public helpers (reference:
    trainer_config_helpers/networks.py surface)."""
    import inspect

    names = [n for n, o in vars(networks).items()
             if not n.startswith("_") and inspect.isfunction(o)
             and o.__module__ == "paddle_tpu.networks"]
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    corpus = ""
    for p in (glob.glob(os.path.join(here, "*.py"))
              + glob.glob(os.path.join(repo, "paddle_tpu", "models", "*.py"))):
        corpus += open(p).read()
    missing = [n for n in names if n not in corpus]
    assert not missing, f"network helpers with no usage: {missing}"
