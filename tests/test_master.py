"""Elastic input master tests (reference:
go/master/service_internal_test.go — task lifecycle incl. timeout and
failure requeue; client_internal_test.go — end-to-end with in-mem store)."""

import os

import pytest

from paddle_tpu.master import (MasterClient, MasterServer, Service,
                               recordio_index, recordio_read_chunk,
                               recordio_write)
from paddle_tpu.reader import creator


@pytest.fixture
def dataset(tmp_path):
    paths = []
    for i in range(2):
        p = str(tmp_path / f"part-{i}.rio")
        recordio_write(p, [f"rec-{i}-{j}".encode() for j in range(10)])
        paths.append(p)
    return paths


def test_recordio_roundtrip(tmp_path):
    p = str(tmp_path / "x.rio")
    recs = [b"a", b"bb" * 100, b""]
    assert recordio_write(p, recs) == 3
    offs = recordio_index(p)
    assert len(offs) == 3
    assert recordio_read_chunk(p, offs[0], 3) == recs
    assert recordio_read_chunk(p, offs[1], 1) == [recs[1]]


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_task_lifecycle_and_timeout(dataset):
    clock = FakeClock()
    svc = Service(chunks_per_task=4, timeout_s=10.0, time_fn=clock)
    n = svc.set_dataset(dataset)
    assert n == 6  # 20 records / 4 per chunk-task... 3 chunks per file
    # second set_dataset is a no-op (racing trainers)
    assert svc.set_dataset(dataset) == 6

    t1 = svc.get_task()
    assert t1 is not None and t1.chunks
    assert svc.task_finished(t1.id)
    assert not svc.task_finished(t1.id)  # not pending anymore

    t2 = svc.get_task()
    clock.t += 11.0  # expire the lease
    t3 = svc.get_task()
    assert t3 is not None
    # eventually the timed-out t2 comes back around
    seen = {t3.id}
    while True:
        t = svc.get_task()
        if t is None:
            break
        seen.add(t.id)
        svc.task_finished(t.id)
    assert t2.id in seen
    svc.task_finished(t3.id)
    assert svc.all_done()


def test_failure_cap_discards(dataset):
    svc = Service(chunks_per_task=100, max_failures=2)
    svc.set_dataset(dataset[:1])  # one task
    t = svc.get_task()
    svc.task_failed(t.id)     # 1st failure -> requeued
    t = svc.get_task()
    assert t is not None
    svc.task_failed(t.id)     # 2nd failure -> discarded as done
    assert svc.get_task() is None
    assert svc.all_done()


def test_new_pass_recycles(dataset):
    svc = Service(chunks_per_task=100)
    svc.set_dataset(dataset[:1])
    t = svc.get_task()
    svc.task_finished(t.id)
    assert svc.all_done()
    svc.new_pass()
    t2 = svc.get_task()
    assert t2 is not None and t2.epoch == 1


def test_snapshot_recover(dataset, tmp_path):
    snap = str(tmp_path / "state.json")
    svc = Service(chunks_per_task=4, snapshot_path=snap)
    svc.set_dataset(dataset)
    t = svc.get_task()      # leave one pending at "crash" time
    svc2 = Service(chunks_per_task=4, snapshot_path=snap)
    # pending task returned to todo on recovery; dataset not re-partitioned
    assert svc2.set_dataset(dataset) == 6
    ids = set()
    while True:
        t2 = svc2.get_task()
        if t2 is None:
            break
        ids.add(t2.id)
        svc2.task_finished(t2.id)
    assert t.id in ids and len(ids) == 6


def test_save_model_dedup():
    clock = FakeClock()
    svc = Service(time_fn=clock)
    assert svc.request_save_model(60.0)
    assert not svc.request_save_model(60.0)
    clock.t += 61
    assert svc.request_save_model(60.0)


def test_tcp_server_end_to_end(dataset):
    srv = MasterServer().start()
    try:
        c = MasterClient(srv.address)
        c.set_dataset(dataset)
        got = []
        while True:
            r = c.next_record()
            if r is None:
                break
            got.append(r)
        assert sorted(got) == sorted(
            f"rec-{i}-{j}".encode() for i in range(2) for j in range(10))
        c.close()
    finally:
        srv.stop()


def test_cloud_reader_inproc(dataset):
    reader = creator.cloud_reader(dataset)
    got = list(reader())
    assert sorted(got) == sorted(
        f"rec-{i}-{j}".encode() for i in range(2) for j in range(10))


def test_concurrent_trainers_consume_each_record_once(tmp_path):
    """4 trainer threads over ONE TCP master: every record of the pass is
    delivered exactly once across the fleet (the reference's multi-trainer
    dispatch invariant, go/master/service.go todo/pending/done)."""
    import threading

    paths = []
    for i in range(3):
        p = str(tmp_path / f"c{i}.rio")
        recordio_write(p, [f"r-{i}-{j}".encode() for j in range(40)])
        paths.append(p)

    # pin BOTH leases long: the trainer TTL below and the task timeout
    # here — a CI pause past the default 60s task lease would requeue a
    # held task and spuriously fail the exactly-once assertion
    svc = Service(chunks_per_task=7, timeout_s=1e6)
    srv = MasterServer(service=svc).start()
    try:
        boot = MasterClient(srv.address)
        boot.set_dataset(paths)
        boot.close()

        got = []
        lock = threading.Lock()
        errs = []

        def worker():
            try:
                c = MasterClient(srv.address)
                c.register(ttl_s=1e6)
                while True:
                    rec = c.next_record()
                    if rec is None:
                        break
                    with lock:
                        got.append(rec)
                c.close()
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "worker thread hung"
        assert not errs, errs
        want = sorted(f"r-{i}-{j}".encode() for i in range(3)
                      for j in range(40))
        assert sorted(got) == want, (
            f"{len(got)} records delivered, {len(want)} expected "
            "(duplicates or losses under concurrency)")
    finally:
        srv.stop()
