"""Elastic input master tests (reference:
go/master/service_internal_test.go — task lifecycle incl. timeout and
failure requeue; client_internal_test.go — end-to-end with in-mem store),
plus client-side retry behavior: capped exponential backoff with
decorrelated jitter, reconnect through a flaky server, and a clear
error when the retry budget runs out (all with an injected sleep_fn —
no wall-clock sleeping)."""

import os
import socket
import threading

import pytest

from paddle_tpu.master import (MasterClient, MasterRetryExhausted,
                               MasterServer, Service, recordio_index,
                               recordio_read_chunk, recordio_write)
from paddle_tpu.master.server import recv_msg, send_msg
from paddle_tpu.master.service import dispatch
from paddle_tpu.reader import creator


@pytest.fixture
def dataset(tmp_path):
    paths = []
    for i in range(2):
        p = str(tmp_path / f"part-{i}.rio")
        recordio_write(p, [f"rec-{i}-{j}".encode() for j in range(10)])
        paths.append(p)
    return paths


def test_recordio_roundtrip(tmp_path):
    p = str(tmp_path / "x.rio")
    recs = [b"a", b"bb" * 100, b""]
    assert recordio_write(p, recs) == 3
    offs = recordio_index(p)
    assert len(offs) == 3
    assert recordio_read_chunk(p, offs[0], 3) == recs
    assert recordio_read_chunk(p, offs[1], 1) == [recs[1]]


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_task_lifecycle_and_timeout(dataset):
    clock = FakeClock()
    svc = Service(chunks_per_task=4, timeout_s=10.0, time_fn=clock)
    n = svc.set_dataset(dataset)
    assert n == 6  # 20 records / 4 per chunk-task... 3 chunks per file
    # second set_dataset is a no-op (racing trainers)
    assert svc.set_dataset(dataset) == 6

    t1 = svc.get_task()
    assert t1 is not None and t1.chunks
    assert svc.task_finished(t1.id)
    assert not svc.task_finished(t1.id)  # not pending anymore

    t2 = svc.get_task()
    clock.t += 11.0  # expire the lease
    t3 = svc.get_task()
    assert t3 is not None
    # eventually the timed-out t2 comes back around
    seen = {t3.id}
    while True:
        t = svc.get_task()
        if t is None:
            break
        seen.add(t.id)
        svc.task_finished(t.id)
    assert t2.id in seen
    svc.task_finished(t3.id)
    assert svc.all_done()


def test_failure_cap_discards(dataset):
    svc = Service(chunks_per_task=100, max_failures=2)
    svc.set_dataset(dataset[:1])  # one task
    t = svc.get_task()
    svc.task_failed(t.id)     # 1st failure -> requeued
    t = svc.get_task()
    assert t is not None
    svc.task_failed(t.id)     # 2nd failure -> discarded as done
    assert svc.get_task() is None
    assert svc.all_done()


def test_new_pass_recycles(dataset):
    svc = Service(chunks_per_task=100)
    svc.set_dataset(dataset[:1])
    t = svc.get_task()
    svc.task_finished(t.id)
    assert svc.all_done()
    svc.new_pass()
    t2 = svc.get_task()
    assert t2 is not None and t2.epoch == 1


def test_snapshot_recover(dataset, tmp_path):
    snap = str(tmp_path / "state.json")
    svc = Service(chunks_per_task=4, snapshot_path=snap)
    svc.set_dataset(dataset)
    t = svc.get_task()      # leave one pending at "crash" time
    svc2 = Service(chunks_per_task=4, snapshot_path=snap)
    # pending task returned to todo on recovery; dataset not re-partitioned
    assert svc2.set_dataset(dataset) == 6
    ids = set()
    while True:
        t2 = svc2.get_task()
        if t2 is None:
            break
        ids.add(t2.id)
        svc2.task_finished(t2.id)
    assert t.id in ids and len(ids) == 6


def test_corrupt_snapshot_recovers_clean(dataset, tmp_path, capsys):
    """A torn/corrupt snapshot (truncated mid-write by a pre-hardening
    kill, or disk damage) must rebuild the queue from a clean state —
    loudly — instead of crashing the master at boot; the next
    set_dataset re-partitions like a first boot."""
    snap = str(tmp_path / "state.json")
    svc = Service(chunks_per_task=4, snapshot_path=snap)
    svc.set_dataset(dataset)
    svc.get_task()
    body = open(snap).read()
    for garbage in (body[:len(body) // 2],    # truncated mid-write
                    '{"todo": [',             # syntactically torn
                    '{"done": []}'):          # valid JSON, missing keys
        with open(snap, "w") as f:
            f.write(garbage)
        svc2 = Service(chunks_per_task=4, snapshot_path=snap)
        assert "MASTER-SNAPSHOT-CORRUPT" in capsys.readouterr().out
        assert svc2.set_dataset(dataset) == 6, "clean re-partition"
        t = svc2.get_task()
        assert t is not None and svc2.task_finished(t.id)
        # the recovered service keeps snapshotting atomically: its own
        # writes produce a loadable file again (5 todo: one task done)
        svc3 = Service(chunks_per_task=4, snapshot_path=snap)
        assert svc3.set_dataset(dataset) == 5  # idempotent: state kept
        assert capsys.readouterr().out == ""


def test_snapshot_has_no_fixed_tmp_name(dataset, tmp_path):
    """The snapshot tempfile is unique per write (mkstemp), so two
    services pointed at one path — or a write racing a crash-restart —
    can never clobber each other's half-written tmp; only complete
    renames land."""
    snap = str(tmp_path / "state.json")
    svc = Service(chunks_per_task=4, snapshot_path=snap)
    svc.set_dataset(dataset)
    assert not os.path.exists(snap + ".tmp")
    leftovers = [f for f in os.listdir(str(tmp_path))
                 if f.endswith(".tmp")]
    assert leftovers == []


def test_progress_reports_queue_position(dataset):
    svc = Service(chunks_per_task=4)
    assert svc.progress() == {"pass_no": 0, "todo": 0, "pending": 0,
                              "done": 0}
    svc.set_dataset(dataset)
    t = svc.get_task()
    assert svc.progress() == {"pass_no": 0, "todo": 5, "pending": 1,
                              "done": 0}
    svc.task_finished(t.id)
    assert svc.progress()["done"] == 1
    c = MasterClient(service=svc)
    assert c.progress()["todo"] == 5


def test_save_model_dedup():
    clock = FakeClock()
    svc = Service(time_fn=clock)
    assert svc.request_save_model(60.0)
    assert not svc.request_save_model(60.0)
    clock.t += 61
    assert svc.request_save_model(60.0)


def test_tcp_server_end_to_end(dataset):
    srv = MasterServer().start()
    try:
        c = MasterClient(srv.address)
        c.set_dataset(dataset)
        got = []
        while True:
            r = c.next_record()
            if r is None:
                break
            got.append(r)
        assert sorted(got) == sorted(
            f"rec-{i}-{j}".encode() for i in range(2) for j in range(10))
        c.close()
    finally:
        srv.stop()


def test_cloud_reader_inproc(dataset):
    reader = creator.cloud_reader(dataset)
    got = list(reader())
    assert sorted(got) == sorted(
        f"rec-{i}-{j}".encode() for i in range(2) for j in range(10))


class _FlakyMaster:
    """A TCP master that accepts-and-closes the first ``drop_first_n``
    connections, then speaks the real protocol against a Service — the
    crash-looping-master stand-in for the client's reconnect path."""

    def __init__(self, svc: Service, drop_first_n: int):
        self.svc = svc
        self.drops_left = drop_first_n
        # methods to execute server-side ONCE and then drop the
        # connection WITHOUT replying — the lost-response case
        self.lose_response_once = set()
        self._lsock = socket.socket()
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(8)
        self.address = f"127.0.0.1:{self._lsock.getsockname()[1]}"
        self._stop = False
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop:
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            if self.drops_left > 0:
                self.drops_left -= 1
                conn.close()
                continue
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        while True:
            try:
                req = recv_msg(conn)
            except (ConnectionError, OSError):
                return
            if req is None:
                return
            try:
                result = dispatch(self.svc, req.get("method"),
                                  req.get("params"))
                resp = {"ok": True, "result": result}
            except Exception as e:
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            if req.get("method") in self.lose_response_once:
                self.lose_response_once.discard(req.get("method"))
                conn.close()           # executed, but the reply is lost
                return
            try:
                send_msg(conn, resp)
            except (ConnectionError, OSError):
                return

    def stop(self):
        self._stop = True
        try:
            self._lsock.close()
        except OSError:
            pass


def test_client_reconnects_through_flaky_server(dataset):
    fm = _FlakyMaster(Service(chunks_per_task=100), drop_first_n=3)
    sleeps = []
    try:
        c = MasterClient(fm.address, poll_interval_s=0.001, retry_budget=20,
                         sleep_fn=sleeps.append)
        c.set_dataset(dataset[:1])      # rides through the dropped conns
        recs = []
        while True:
            r = c.next_record()
            if r is None:
                break
            recs.append(r)
        assert sorted(recs) == sorted(
            f"rec-0-{j}".encode() for j in range(10))
        assert fm.drops_left == 0       # the drops actually happened
        assert sleeps                   # and backoff absorbed them
        assert all(s <= 2.0 for s in sleeps)
        c.close()
    finally:
        fm.stop()


def test_retry_budget_exhausted_raises_clear_error():
    fm = _FlakyMaster(Service(), drop_first_n=10 ** 9)   # always drops
    sleeps = []
    try:
        c = MasterClient(fm.address, poll_interval_s=0.001, retry_budget=3,
                         sleep_fn=sleeps.append)
        with pytest.raises(MasterRetryExhausted):
            c.set_dataset(["/nonexistent"])
        assert len(sleeps) == 3         # the whole budget, then the error
    finally:
        fm.stop()


def test_poll_backoff_budget_when_peers_hold_tasks(dataset):
    svc = Service(chunks_per_task=100, timeout_s=1e6)
    svc.set_dataset(dataset[:1])
    held = svc.get_task()               # a "peer" holds the only task
    assert held is not None
    sleeps = []
    c = MasterClient(service=svc, poll_interval_s=0.001, retry_budget=5,
                     sleep_fn=sleeps.append)
    with pytest.raises(MasterRetryExhausted):
        c.next_record()
    assert len(sleeps) == 5
    # the peer crashes (task requeued): a fresh client gets the task
    svc.task_failed(held.id)
    c2 = MasterClient(service=svc)
    assert c2.next_record() is not None


def test_lost_get_task_response_is_not_blindly_resent(dataset):
    # the master leases task A but the reply is lost in a connection
    # drop: the client must NOT blind-resend get_task (that would lease
    # a second task while A burns failure budget) — it reports "nothing
    # available", and A requeues through the normal lease timeout, so
    # every record still arrives exactly once
    svc = Service(chunks_per_task=100, timeout_s=0.05)
    fm = _FlakyMaster(svc, drop_first_n=0)
    try:
        c = MasterClient(fm.address, poll_interval_s=0.001,
                         sleep_fn=lambda s: None)
        c.set_dataset(dataset[:1])
        fm.lose_response_once.add("get_task")
        recs = []
        while True:
            r = c.next_record()
            if r is None:
                break
            recs.append(r)
        assert sorted(recs) == sorted(
            f"rec-0-{j}".encode() for j in range(10))
        assert not fm.lose_response_once      # the drop really happened
        c.close()
    finally:
        fm.stop()


def test_close_fails_fast_against_dead_master():
    # shutdown must NOT sit out the transport retry budget: one attempt,
    # zero backoff sleeps, then give up quietly
    fm = _FlakyMaster(Service(), drop_first_n=10 ** 9)
    sleeps = []
    try:
        c = MasterClient(fm.address, poll_interval_s=0.001,
                         sleep_fn=sleeps.append)
        c._task_id = 7                  # pretend a task is in flight
        c.close()                       # swallowed single failure
        assert sleeps == []
        assert c._task_id is None
    finally:
        fm.stop()


def test_poll_wait_public_api_for_elastic_trainer(dataset):
    # the elastic trainer's empty-queue wait goes through poll_wait /
    # poll_reset (it used to reach into master._poll for a fixed sleep)
    svc = Service(chunks_per_task=100, timeout_s=1e6)
    svc.set_dataset(dataset[:1])
    held = svc.get_task()               # a peer holds the only task
    assert held is not None
    sleeps = []
    c = MasterClient(service=svc, poll_interval_s=0.001, retry_budget=2,
                     sleep_fn=sleeps.append)
    status, got = c.try_next_task()
    assert status == "empty" and got is None
    c.poll_wait()
    c.poll_wait()
    with pytest.raises(MasterRetryExhausted):
        c.poll_wait()                   # budget of 2 spent
    c.poll_reset()
    c.poll_wait()                       # refunded
    assert len(sleeps) == 3


def test_backoff_is_jittered_capped_and_resets():
    from paddle_tpu.master.client import _Backoff

    sleeps = []
    b = _Backoff(0.01, 0.5, budget=None, seed=3, sleep_fn=sleeps.append)
    for _ in range(50):
        b.sleep()
    assert 0.01 <= min(sleeps) and max(sleeps) <= 0.5
    assert len(set(sleeps)) > 10        # decorrelated, not a fixed ladder
    b.reset()
    b.sleep()
    assert sleeps[-1] <= 3 * 0.01       # reset returned to the base range


def test_dead_master_trips_default_transport_budget():
    # no explicit retry_budget: a master that is simply GONE must still
    # surface as MasterRetryExhausted (finite default transport budget),
    # not spin forever
    from paddle_tpu.master.client import DEFAULT_TRANSPORT_RETRY_BUDGET

    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    port = lsock.getsockname()[1]
    lsock.close()                   # nothing listens here anymore
    sleeps = []
    with pytest.raises(MasterRetryExhausted):
        MasterClient(f"127.0.0.1:{port}", poll_interval_s=0.001,
                     sleep_fn=sleeps.append)
    assert len(sleeps) == DEFAULT_TRANSPORT_RETRY_BUDGET


def test_backoff_decorrelates_across_clients():
    # unseeded clients must NOT share a jitter sequence (a fleet in
    # lockstep would thunder back at a restarting master together)
    from paddle_tpu.master.client import _Backoff

    s1, s2 = [], []
    b1 = _Backoff(0.01, 0.5, sleep_fn=s1.append)
    b2 = _Backoff(0.01, 0.5, sleep_fn=s2.append)
    for _ in range(8):
        b1.sleep()
        b2.sleep()
    assert s1 != s2


def test_concurrent_trainers_consume_each_record_once(tmp_path):
    """4 trainer threads over ONE TCP master: every record of the pass is
    delivered exactly once across the fleet (the reference's multi-trainer
    dispatch invariant, go/master/service.go todo/pending/done)."""
    import threading

    paths = []
    for i in range(3):
        p = str(tmp_path / f"c{i}.rio")
        recordio_write(p, [f"r-{i}-{j}".encode() for j in range(40)])
        paths.append(p)

    # pin BOTH leases long: the trainer TTL below and the task timeout
    # here — a CI pause past the default 60s task lease would requeue a
    # held task and spuriously fail the exactly-once assertion
    svc = Service(chunks_per_task=7, timeout_s=1e6)
    srv = MasterServer(service=svc).start()
    try:
        boot = MasterClient(srv.address)
        boot.set_dataset(paths)
        boot.close()

        got = []
        lock = threading.Lock()
        errs = []

        def worker():
            try:
                c = MasterClient(srv.address)
                c.register(ttl_s=1e6)
                while True:
                    rec = c.next_record()
                    if rec is None:
                        break
                    with lock:
                        got.append(rec)
                c.close()
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "worker thread hung"
        assert not errs, errs
        want = sorted(f"r-{i}-{j}".encode() for i in range(3)
                      for j in range(40))
        assert sorted(got) == want, (
            f"{len(got)} records delivered, {len(want)} expected "
            "(duplicates or losses under concurrency)")
    finally:
        srv.stop()


def test_zombie_token_heartbeat_rejected_end_to_end(dataset):
    """The lease-token fence, end-to-end through MasterClient.heartbeat:
    trainer A's lease lapses, trainer B reclaims the SAME slot number,
    and A's renewal — racing the reclamation with a stale token — must
    return False (A is a zombie: it re-registers, its in-flight task
    already requeued).  B's own heartbeat keeps working."""
    clk = FakeClock()
    svc = Service(chunks_per_task=1, timeout_s=10.0, time_fn=clk)
    a = MasterClient(None, service=svc)
    a.set_dataset(dataset)
    slot_a = a.register(ttl_s=5.0)
    task = svc.get_task(owner=slot_a)       # A holds a task lease too
    assert task is not None

    clk.t += 6.0                            # A's lease lapses silently
    b = MasterClient(None, service=svc)
    slot_b = b.register(ttl_s=5.0)
    assert slot_b == slot_a                 # the slot number is REUSED

    # the zombie's renewal races the reclamation: same slot, stale token
    assert a.heartbeat() is False
    # the client noticed it was declared dead and dropped its identity
    assert a._slot is None and a._token is None
    # the new owner is untouched by the zombie's attempt
    assert b.heartbeat() is True
    # A's task requeued when its lease expired — the next fetch re-serves
    # it instead of losing it
    ids = set()
    while True:
        t = svc.get_task(owner=slot_b)
        if t is None:
            break
        ids.add(t.id)
    assert task.id in ids


def test_lease_lapse_inside_inner_sweep_still_requeues(dataset):
    """A lease that lapses BETWEEN Service's own expiry sweep and the
    sweep LeaseTable runs internally (inside heartbeat/register/members)
    must still requeue the dead member's in-flight tasks promptly — the
    freed slot is not silently discarded by the inner sweep, leaving the
    task to the slow per-task timeout path."""
    class SteppingClock:
        # advances a little on EVERY read, like a real clock: that is
        # exactly what opens the window between the two sweeps
        def __init__(self):
            self.now = 0.0
            self.step = 0.0

        def __call__(self):
            self.now += self.step
            return self.now

    clk = SteppingClock()
    svc = Service(chunks_per_task=1, timeout_s=1000.0, time_fn=clk)
    svc.set_dataset(dataset)
    ttl = svc.lease_ttl_s                    # 3 * timeout_s = 3000
    slot_a, tok_a = svc.register()           # deadline_a = ttl
    slot_b, tok_b = svc.register()
    task = svc.get_task(owner=slot_a)
    assert task is not None
    clk.now = 10.0
    assert svc.heartbeat(slot_b, tok_b)      # B renews: deadline ~ttl+10

    # park just short of A's deadline and arm the per-read step so the
    # deadline falls between Service._expire_members (A still alive)
    # and the inner LeaseTable sweep (A lapsed)
    clk.now = ttl - 0.5
    clk.step = 0.3
    assert svc.heartbeat(slot_b, tok_b)      # B fine; A dies INSIDE here
    clk.step = 0.0

    assert svc.heartbeat(slot_a, tok_a) is False   # A is gone
    # the requeue happened inside that heartbeat call, not lazily later:
    # A's task is already back in todo with a failure charged
    assert task.id not in svc._pending
    assert svc._todo and svc._todo[0].id == task.id
    assert svc._todo[0].num_failures == 1


def test_lease_table_on_expire_fires_on_internal_sweeps():
    """The on_expire hook runs on EVERY sweep, including the ones
    register/heartbeat/members do internally, so no freed slot is ever
    dropped on the floor."""
    from paddle_tpu.master import LeaseTable

    clk = FakeClock()
    freed = []
    lt = LeaseTable(ttl_s=5.0, time_fn=clk, on_expire=freed.append)
    slot, _tok = lt.register()
    clk.t += 6.0
    slot2, _tok2 = lt.register()             # internal sweep frees `slot`
    assert freed == [slot]
    assert slot2 == slot                     # and the slot is reusable


def test_lease_table_heartbeat_never_resurrects_expired_lease():
    """LeaseTable.heartbeat re-checks the deadline itself: a renewal
    arriving exactly when the lease lapsed is refused even though the
    slot has not been reclaimed by anyone yet."""
    from paddle_tpu.master import LeaseTable

    clk = FakeClock()
    lt = LeaseTable(ttl_s=5.0, time_fn=clk)
    slot, token = lt.register()
    assert lt.heartbeat(slot, token) is True
    clk.t += 5.0                            # dl <= now: lapsed, unswept
    assert lt.heartbeat(slot, token) is False
    assert lt.members() == []
    # re-registering mints a fresh token on the same slot; the old token
    # stays dead forever
    slot2, token2 = lt.register()
    assert slot2 == slot
    assert lt.heartbeat(slot, token) is False
    assert lt.heartbeat(slot2, token2) is True
    assert lt.drop(slot2, token) is False   # stale token can't evict
    assert lt.drop(slot2, token2) is True
