"""Pipeline-parallel trainer: SGD(pipeline=PipelineConfig) on the
virtual-8 mesh — loss-trajectory parity against the sequential DSL path,
remat invariance, ZeRO composition with optimizer-slot conservation,
cross-layout checkpoint resume, and the MoE model-zoo wiring.

Tolerance note: the sequential path runs attention through the flash
kernel while the pipeline stage_fn uses mha_reference — a ~0.07%
per-token forward difference that Adam's per-element rescale amplifies
over steps. Losses are pinned at rtol=5e-3; params at aggregate mean
drift (the test_model_parallel idiom) rather than elementwise."""

import re

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer, trainer
from paddle_tpu.models import transformer
from paddle_tpu.parallel.pipeline import PipelineConfig

VOCAB, D, L, H, T = 32, 16, 4, 2, 8


def _samples(n=8, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        toks = rng.randint(0, VOCAB, size=T)
        out.append((toks.tolist(), list(range(T)),
                    np.roll(toks, -1).tolist()))
    return out


def _build_cost():
    paddle.topology.reset_name_scope()
    _, _, _, _, cost = transformer.build(
        vocab_size=VOCAB, d_model=D, n_layers=L, n_heads=H, max_len=T)
    return cost


def _run(pipeline=None, steps=3, zero=None, samples=None):
    """Train ``steps`` Adam steps; returns (losses, params, sgd)."""
    cost = _build_cost()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    kw = {}
    if pipeline is not None:
        kw["pipeline"] = pipeline
    if zero is not None:
        kw["zero"] = zero
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2),
                      **kw)
    step = sgd._build_step()
    feeder = sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2})
    feeds = sgd._shard_feeds(feeder.feed(samples or _samples()))
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(steps):
        loss, p, o, m = [*step(p, o, m, key, feeds)][:4]
        losses.append(float(loss))
    return losses, p, sgd


def _pcfg(**kw):
    base = dict(num_stages=4, microbatches=4, n_layers=L, n_heads=H)
    base.update(kw)
    return PipelineConfig(**base)


def _assert_param_parity(pipe_p, seq_p, mean_tol=2e-3):
    """Unstack pipe_body.* back to blk{i}_* and pin the aggregate drift."""
    drifts = []
    for name, v in seq_p.items():
        mt = re.match(r"^blk(\d+)_(.+)$", name)
        if mt:
            got = np.asarray(
                pipe_p[f"pipe_body.{mt.group(2)}"])[int(mt.group(1))]
        else:
            got = np.asarray(pipe_p[name])
        drifts.append(float(np.mean(np.abs(got - np.asarray(v)))))
    assert max(drifts) < mean_tol, f"max param mean-drift {max(drifts)}"


def test_pipeline_loss_parity_vs_sequential():
    seq_losses, seq_p, _ = _run()
    pipe_losses, pipe_p, sgd = _run(pipeline=_pcfg())
    np.testing.assert_allclose(pipe_losses, seq_losses, rtol=5e-3)
    assert pipe_losses[-1] < pipe_losses[0], "pipeline trainer not learning"
    _assert_param_parity(pipe_p, seq_p)
    # stage weights genuinely sharded: each device holds S-th of the
    # stacked block dim
    v = sgd.parameters["pipe_body.attn.wq"]
    shard = v.addressable_shards[0].data
    assert shard.shape[0] * 4 == v.shape[0]


def test_pipeline_remat_matches_norematerialized():
    # remat changes the backward schedule, not the math
    base, _, _ = _run(pipeline=_pcfg(), steps=2)
    remat, _, _ = _run(pipeline=_pcfg(remat=True), steps=2)
    np.testing.assert_allclose(remat, base, rtol=1e-5)


def test_pipeline_zero_composition_slots_conserved():
    pipe_losses, _, pipe_sgd = _run(pipeline=_pcfg(), steps=2)
    pz_losses, _, pz_sgd = _run(pipeline=_pcfg(), steps=2, zero=1)
    # ZeRO reshards optimizer state only — identical update math
    np.testing.assert_allclose(pz_losses, pipe_losses, rtol=1e-6)

    def _slot_arrays(sgd):
        return {f"{k}/{n}": v
                for k, sl in sgd.opt_state["slots"].items()
                for n, v in sl.items()}

    plain, sharded = _slot_arrays(pipe_sgd), _slot_arrays(pz_sgd)
    assert set(plain) == set(sharded)
    some_sharded = False
    for k, v in sharded.items():
        # conservation: resharding must not change the global element
        # count (the zero plan stores its sharded slots flattened)
        assert v.size == plain[k].size, k
        if not k.startswith("pipe_body."):
            frac = v.addressable_shards[0].data.size / max(1, v.size)
            some_sharded = some_sharded or frac < 1.0
    assert some_sharded, "zero=1 sharded no optimizer slots"


def test_pipeline_cross_layout_checkpoint_resume(tmp_path):
    # layout independence: pipe_body.* is stacked [L, ...] regardless of
    # S, so an S=4 checkpoint resumes on an S=2 mesh byte-for-byte
    _, p4, sgd4 = _run(pipeline=_pcfg(), steps=2)
    sgd4.parameters.update_from(p4)
    sgd4.save_checkpoint(str(tmp_path), 0)

    cost = _build_cost()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=1)
    sgd2 = trainer.SGD(cost=cost, parameters=params,
                       update_equation=optimizer.Adam(learning_rate=1e-2),
                       pipeline=_pcfg(num_stages=2, microbatches=2))
    sgd2.load_checkpoint(str(tmp_path))
    for name, v in p4.items():
        np.testing.assert_array_equal(np.asarray(sgd2.parameters[name]),
                                      np.asarray(v), err_msg=name)
    # and the restored S=2 trainer still steps
    step = sgd2._build_step()
    feeder = sgd2._make_feeder({"tokens": 0, "pos": 1, "target": 2})
    feeds = sgd2._shard_feeds(feeder.feed(_samples()))
    loss = float(step(sgd2.parameters.as_dict(), sgd2.opt_state,
                      sgd2.model_state, jax.random.PRNGKey(0), feeds)[0])
    assert np.isfinite(loss)


def test_pipeline_rejects_bad_config():
    cost = _build_cost()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    with pytest.raises(Exception, match="divi|stage"):
        trainer.SGD(cost=cost, parameters=params,
                    update_equation=optimizer.Adam(learning_rate=1e-2),
                    pipeline=_pcfg(num_stages=3))


def test_transformer_moe_top2_trains():
    # model-zoo leg: top-2 routing through layer.moe_ffn (dense path on
    # the meshless trainer), multi-cost with the balance aux
    paddle.topology.reset_name_scope()
    _, _, _, _, costs = transformer.build(
        vocab_size=VOCAB, d_model=D, n_layers=2, n_heads=H, max_len=T,
        moe_experts=4, moe_top_k=2)
    assert isinstance(costs, list) and len(costs) == 3
    topo = paddle.topology.Topology(costs)
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=costs, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))
    step = sgd._build_step()
    feeder = sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2})
    feeds = sgd._shard_feeds(feeder.feed(_samples(4)))
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    losses = []
    for _ in range(4):
        loss, p, o, m = [*step(p, o, m, jax.random.PRNGKey(0), feeds)][:4]
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_moe_config_declares_expert_sharding():
    # MoEConfig resolves through the one placement layer: the zoo
    # layer's expert weights carry leading-dim expert-axis sharding,
    # the router stays replicated
    from paddle_tpu import layer
    from paddle_tpu.parallel.moe import MoEConfig

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(D))
    out, aux = layer.moe_ffn(x, config=MoEConfig(num_experts=4,
                                                 expert_hidden=8, top_k=2),
                             name="m")
    topo = paddle.topology.Topology([out, aux])
    specs = topo.param_specs()
    assert specs["m.w1"].attr.sharding == ("expert", None, None)
    assert specs["m.b2"].attr.sharding == ("expert", None)
    assert specs["m.router"].attr.sharding is None
