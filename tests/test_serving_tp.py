"""Tensor-parallel serving: megatron-sharded decode/prefill over a
`model` mesh axis (round 13).

What is pinned here:

- **greedy parity**: tp in {1, 2, 4} engines produce token-identical
  outputs to the replicated engine AND the non-paged oracle on the
  virtual-8 mesh — sharding the heads/FFN columns changes the placement,
  never the trajectory;
- **per-chip byte accounting**: ``PagedKVConfig.bytes_per_page`` /
  ``pages_for_budget`` charge each chip 1/tp of every page (int8 scale
  arrays shard with their KV heads), asserted to the exact byte;
- **actionable validation**: every divisibility failure (query heads,
  KV heads, the GQA tp>KV-heads corner, FFN width) names the bad number
  and a fix, from BOTH ``ServingEngine(mesh=)`` and ``shard_plan()``;
- **cache semantics survive sharding**: COW fork + prefix-cache hits on
  a sharded pool, chaos/fault spot-run with tp=2, 0 page/ref leaks;
- **no new compile dimension**: a sealed TP steady state still compiles
  exactly once per (decode_bucket, prefill_bucket) pair;
- **reduce-not-gather, statically**: the sharding auditor over the real
  TP ``serving.step`` reports 0 ERRORs and a collective estimate equal
  to the closed-form megatron budget (2 row-parallel psums per layer,
  ``2*b*(N-1)/N`` each) — no implicit all-gather on the decode hot path;
- **one placement story**: ``shard_plan`` composes with ZeRO via
  ``plan_param_attrs`` (TP weights keep their layout, the replicated
  remainder still ZeRO-shards), and the fleet's replica unit becomes a
  mesh slice (``FleetRouter.over_mesh_slices``).
"""

import numpy as np
import jax
import pytest

from paddle_tpu.platform.enforce import EnforceError
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.analysis.retrace import auditor
from paddle_tpu.parallel.mesh import make_mesh, mesh_slices
from paddle_tpu.serving import DecoderLM, FaultPlan, ServingEngine
from paddle_tpu.serving.engine import greedy_decode_reference, validate_tp
from paddle_tpu.serving.kv_cache import PagedKVConfig, pages_for_budget

from conftest import assert_serving_drained as assert_drained  # noqa: E402

pytestmark = [pytest.mark.serving, pytest.mark.shard]

EOS = 1


def _model(num_heads=4, num_kv_heads=None, head_dim=8, layers=2):
    return DecoderLM(vocab_size=64, num_layers=layers,
                     num_heads=num_heads, num_kv_heads=num_kv_heads,
                     head_dim=head_dim, max_positions=128)


def _mesh(tp):
    return make_mesh((tp,), ("model",), jax.devices()[:tp])


def _engine(model, params, mesh=None, **kw):
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 64)
    kw.setdefault("max_pages_per_seq", 12)
    kw.setdefault("max_slots", 4)
    kw.setdefault("buckets", (4, 8, 16))
    kw.setdefault("prefill_chunk", 8)
    return ServingEngine(model, params, eos_id=EOS, mesh=mesh, **kw)


# ---------------------------------------------------------------------------
# per-chip byte accounting (pool budget is PER CHIP under TP)
# ---------------------------------------------------------------------------


def test_bytes_per_page_exact_per_chip_f32_and_int8():
    base = dict(num_layers=2, num_heads=4, head_dim=16, page_size=16,
                num_pages=8, max_pages_per_seq=4, num_kv_heads=2)
    f32 = PagedKVConfig(dtype=np.float32, **base)
    # K+V, 2 layers, 16 tokens, 2 KV heads, 16 dims, 4 bytes
    assert f32.bytes_per_page() == 2 * 2 * 16 * 2 * 16 * 4 == 8192
    # tp=2: ONE KV head per chip — exactly half the bytes on each chip
    f32_tp = PagedKVConfig(dtype=np.float32, tp=2, **base)
    assert f32_tp.bytes_per_page() == 4096
    assert f32_tp.kv_bytes() == 8 * 4096
    # int8: values 1 byte + per-token f32 scales, scales shard with
    # their KV heads too
    i8 = PagedKVConfig(dtype=np.int8, tp=2, **base)
    assert i8.bytes_per_page() == \
        2 * (2 * 16 * 1 * 16 * 1 + 2 * 16 * 1 * 4) == 1280
    assert PagedKVConfig(dtype=np.int8, **base).bytes_per_page() == 2560


def test_pages_for_budget_is_per_chip_and_multiplies_with_tp():
    args = dict(num_layers=2, num_heads=4, head_dim=16, page_size=16,
                num_kv_heads=2)
    budget = 64 * 8192                    # 64 f32 pages at tp=1
    assert pages_for_budget(budget, dtype="float32", **args) == 64
    # the same PER-CHIP budget buys tp x the pages: each chip stores
    # only its 1/tp KV-head shard of every page
    assert pages_for_budget(budget, dtype="float32", tp=2, **args) == 128
    # and int8 compounds on top (4x values minus the f32 scale overhead)
    assert pages_for_budget(budget, dtype="int8", tp=2, **args) == \
        budget // 1280


def test_engine_pool_bytes_budget_accounts_tp(rng):
    model = _model()
    params = model.init_params(jax.random.PRNGKey(0))
    budget = 48 * PagedKVConfig(
        num_layers=model.num_layers, num_heads=model.num_heads,
        head_dim=model.head_dim, page_size=4, num_pages=2,
        max_pages_per_seq=1).bytes_per_page()
    rep = _engine(model, params, num_pages=None, pool_bytes=budget)
    tp2 = _engine(model, params, mesh=_mesh(2), num_pages=None,
                  pool_bytes=budget)
    assert rep.pool.num_usable == 47          # 48 minus the null page
    assert tp2.pool.num_usable == 95          # 2x pages, same chip bytes
    assert tp2.kv_cfg.kv_bytes() <= budget
    assert tp2.healthz()["tp"] == 2


# ---------------------------------------------------------------------------
# validation: actionable errors from both construction paths
# ---------------------------------------------------------------------------


def test_validation_num_heads_not_divisible():
    model = _model(num_heads=3, head_dim=8)
    with pytest.raises(EnforceError, match="num_heads .3.*divides 3"):
        validate_tp(model, 2)
    with pytest.raises(EnforceError, match="num_heads"):
        model.shard_plan(tp=2)
    params = model.init_params(jax.random.PRNGKey(0))
    with pytest.raises(EnforceError, match="num_heads"):
        _engine(model, params, mesh=_mesh(2))


def test_validation_gqa_corner_tp_exceeds_kv_heads():
    model = _model(num_heads=4, num_kv_heads=2)
    with pytest.raises(EnforceError, match="GQA corner.*lower tp"):
        model.shard_plan(tp=4)
    params = model.init_params(jax.random.PRNGKey(0))
    with pytest.raises(EnforceError, match="GQA corner"):
        _engine(model, params, mesh=_mesh(4))


def test_validation_kv_heads_not_divisible():
    # tp=2 <= kvh=3 passes the corner check but 3 % 2 != 0
    model = _model(num_heads=6, num_kv_heads=3)
    with pytest.raises(EnforceError, match="num_kv_heads .3."):
        validate_tp(model, 2)


def test_validation_ffn_width_not_divisible():
    model = _model(num_heads=4)
    model.ffn_dim = 6                       # force a bad width
    with pytest.raises(EnforceError, match="FFN width .6."):
        validate_tp(model, 4)


def test_validation_mesh_without_model_axis():
    model = _model()
    params = model.init_params(jax.random.PRNGKey(0))
    mesh = make_mesh((2,), ("data",), jax.devices()[:2])
    with pytest.raises(EnforceError, match="no 'model' axis"):
        _engine(model, params, mesh=mesh)


def test_kv_config_rejects_tp_not_dividing_kv_heads():
    with pytest.raises(EnforceError, match="shards whole KV heads"):
        PagedKVConfig(num_layers=1, num_heads=4, head_dim=8, page_size=4,
                      num_pages=8, max_pages_per_seq=2, num_kv_heads=2,
                      tp=4)


# ---------------------------------------------------------------------------
# greedy parity + cache semantics on the sharded pool
# ---------------------------------------------------------------------------


def _run_prompts(eng, prompts, max_tokens=8, max_ticks=500):
    rids = [eng.submit(p, max_tokens=max_tokens) for p in prompts]
    res = eng.run(max_ticks=max_ticks)
    assert_drained(eng)
    return [res[r] for r in rids]


def test_greedy_parity_tp_1_2_4_vs_replicated_oracle(rng):
    model = _model(num_heads=4, head_dim=8)
    params = model.init_params(jax.random.PRNGKey(0))
    prompts = [rng.randint(2, 64, size=rng.randint(4, 20)).tolist()
               for _ in range(5)]
    rep = _run_prompts(_engine(model, params), prompts)
    oracle = [greedy_decode_reference(model, params, p, 8, EOS)
              for p in prompts]
    assert rep == oracle
    for tp in (1, 2, 4):
        eng = _engine(model, params, mesh=_mesh(tp))
        assert eng.tp == tp
        assert _run_prompts(eng, prompts) == rep, f"tp={tp} diverged"


def test_cow_fork_and_prefix_hit_on_sharded_pool(rng):
    model = _model(num_heads=4, num_kv_heads=2)
    params = model.init_params(jax.random.PRNGKey(0))
    shared = rng.randint(2, 64, size=8).tolist()    # two FULL pages
    tail = rng.randint(2, 64, size=9).tolist()

    def run(mesh):
        eng = _engine(model, params, mesh=mesh)
        r1 = eng.submit(shared, max_tokens=6)
        eng.run(max_ticks=300)
        r2 = eng.submit(shared, max_tokens=6)       # full cover: COW
        eng.run(max_ticks=300)
        r3 = eng.submit(shared + tail, max_tokens=6)  # mid-prompt hit
        res = eng.run(max_ticks=400)
        assert_drained(eng)
        snap = eng.metrics.snapshot()
        assert snap["cow_forks"] >= 1
        assert snap["prefix_hit_rate"] > 0
        return [res[r] for r in (r1, r2, r3)]

    rep = run(None)
    assert rep[0] == rep[1]                         # cache parity
    assert run(_mesh(2)) == rep


def test_chaos_spot_run_tp2_conserves_pages_and_refs(rng):
    model = _model(num_heads=4, num_kv_heads=2)
    params = model.init_params(jax.random.PRNGKey(0))
    faults = FaultPlan(decode_errors={3: 1}, page_pressure=(2, 8, 12))
    eng = _engine(model, params, mesh=_mesh(2), kv_dtype="int8",
                  faults=faults)
    rids = [eng.submit(rng.randint(2, 64, size=rng.randint(4, 24)).tolist(),
                       max_tokens=8) for _ in range(6)]
    eng.step()
    faults.poison_nan(rids[2])                      # sharded FAILED scrub
    eng.run(max_ticks=800)
    assert_drained(eng)
    statuses = {r: str(eng.status(r)) for r in rids}
    assert statuses[rids[2]] == "failed"
    assert all(eng.status(r).terminal for r in rids)
    assert eng.metrics.retries >= 1                 # transient absorbed


# ---------------------------------------------------------------------------
# compile discipline: TP adds no compile dimension
# ---------------------------------------------------------------------------


def test_sealed_tp_steady_state_one_compile_per_pair(rng):
    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    auditor().reset()
    try:
        model = _model(num_heads=4, num_kv_heads=2)
        params = model.init_params(jax.random.PRNGKey(0))
        eng = _engine(model, params, mesh=_mesh(2))
        # warmup: decode-only + the pair buckets the replay will use
        eng.submit(rng.randint(2, 64, size=4).tolist(), max_tokens=8)
        eng.step()
        eng.submit(rng.randint(2, 64, size=20).tolist(), max_tokens=6)
        eng.run(max_ticks=400)
        compiles = auditor().compile_count("serving.step")
        assert compiles >= 2                  # >1 pair exercised
        auditor().seal()
        eng.submit(rng.randint(2, 64, size=4).tolist(), max_tokens=8)
        eng.step()
        eng.submit(rng.randint(2, 64, size=19).tolist(), max_tokens=6)
        eng.run(max_ticks=400)
        auditor().assert_no_retraces()        # sealed: zero new compiles
        auditor().assert_budget("serving.step", compiles)
    finally:
        FLAGS.jit_audit = old
        auditor().reset()


def test_tp_and_replicated_engines_share_site_without_false_retrace(rng):
    """Same geometry, same shapes, different shardings: jit legitimately
    compiles both, and the sharding-aware signature must keep them
    distinct instead of reporting a same-signature retrace."""
    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    auditor().reset()
    try:
        model = _model(num_heads=4)
        params = model.init_params(jax.random.PRNGKey(0))
        prompts = [rng.randint(2, 64, size=6).tolist()]
        _run_prompts(_engine(model, params), prompts, max_tokens=4)
        _run_prompts(_engine(model, params, mesh=_mesh(2)), prompts,
                     max_tokens=4)
        auditor().assert_no_retraces()
    finally:
        FLAGS.jit_audit = old
        auditor().reset()


# ---------------------------------------------------------------------------
# the sharding gate on the TP hot path: reduce-not-gather, closed form
# ---------------------------------------------------------------------------


def test_tp_step_audits_clean_comm_equals_closed_form():
    from paddle_tpu.analysis import sharding as S

    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    auditor().reset()
    try:
        eng = S.drive_serving_tp_steady_state(tp=2, kv_dtype="int8")
        assert eng is not None
        reps = S.audit_sharding_sites(
            sites=["serving.step", "serving.fork_page",
                   "serving.zero_pages"])
        for name, rep in reps.items():
            assert not rep.errors, (name, [d.message for d in rep.errors])
            assert not any("implicit-all-gather" in d.message
                           for d in rep.diagnostics), name
        # fork/zero stay collective-free even sharded
        assert reps["serving.fork_page"].comm_bytes == 0.0
        assert reps["serving.zero_pages"].comm_bytes == 0.0
        # the audited step estimate IS the closed-form megatron budget:
        # 2 row-parallel psums per layer, 2*b*(N-1)/N each over the
        # [rows, E] f32 activation — for every signature, take the max
        rec = auditor().sites["serving.step"]
        expected = 0.0
        for _sig, cap in rec.captured.items():
            rows = cap.args[2].shape[0] + cap.args[5].shape[0]
            expected = max(expected, eng.tp_step_comm_bytes(rows))
        assert expected > 0.0
        assert reps["serving.step"].comm_bytes == expected
    finally:
        FLAGS.jit_audit = old
        auditor().reset()


def test_replicated_contract_still_pins_zero_comm():
    """The mesh=None baseline contract did NOT silently loosen: specs
    all P(), comm budget 0."""
    model = _model()
    params = model.init_params(jax.random.PRNGKey(0))
    eng = _engine(model, params)
    c = eng._step_contract
    assert c.in_specs == ((),) and c.out_specs == ((),)
    assert c.comm_bytes == 0.0 and c.mesh_axes == ()
    assert eng.tp_step_comm_bytes(100) == 0.0


# ---------------------------------------------------------------------------
# one placement story: ZeRO composition + fleet mesh-slice replicas
# ---------------------------------------------------------------------------


def test_shard_plan_composes_with_zero():
    from paddle_tpu.parallel.api import param_sharding
    from paddle_tpu.parallel.placement import plan_param_attrs
    from paddle_tpu.parallel.zero import build_zero_plan

    model = _model(num_heads=4, num_kv_heads=2)
    params = model.init_params(jax.random.PRNGKey(0))
    specs = plan_param_attrs(model.shard_plan(axis="model", tp=2))
    mesh = make_mesh((4, 2), ("data", "model"), jax.devices())
    ps = param_sharding(mesh, params, specs=specs)
    zp = build_zero_plan(mesh, params, specs=specs, axis="data")
    for l in range(model.num_layers):
        # TP weights keep their declared megatron layout (explicit
        # sharding wins) and are NOT re-sharded by ZeRO
        assert tuple(ps[f"l{l}.wq"].spec) == (None, "model")
        assert tuple(ps[f"l{l}.wo"].spec) == ("model", None)
        assert not zp.is_sharded(f"l{l}.wq")
        assert not zp.is_sharded(f"l{l}.wo")
    # the replicated remainder still gets its optimizer state sharded
    assert zp.is_sharded("emb") and zp.is_sharded("out")


def test_fleet_mesh_slice_replica_unit(rng):
    from paddle_tpu.serving.faults import FleetFaultPlan, ManualClock
    from paddle_tpu.serving.fleet import FleetRouter

    model = _model(num_heads=4, layers=1)
    params = model.init_params(jax.random.PRNGKey(0))
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                          kill_at={6: 0})

    def mk(i, time_fn, mesh):
        return ServingEngine(model, params, eos_id=EOS, page_size=4,
                             num_pages=32, max_pages_per_seq=8,
                             max_slots=4, buckets=(8, 16),
                             time_fn=time_fn, mesh=mesh)

    fleet = FleetRouter.over_mesh_slices(
        mk, tp=2, devices=jax.devices()[:6], heartbeat_s=0.05,
        resubmit_budget=2, faults=plan)
    assert len(fleet.replicas) == 3           # 6 devices / tp=2
    assert all(r.engine.tp == 2 for r in fleet.replicas)
    system = rng.randint(2, 64, size=8).tolist()
    frids = [fleet.submit(system + rng.randint(2, 64, size=4).tolist(),
                          max_tokens=6) for _ in range(9)]
    fleet.run(max_ticks=500)
    fleet.check_fleet_conservation()          # incl. the killed slice
    assert all(fleet.status(f).terminal for f in frids)
    snap = fleet.snapshot()
    assert snap["fleet_duplicate_completions"] == 0
    assert snap["fleet_completed"] >= 8


def test_mesh_slices_partition_and_cap():
    devs = jax.devices()
    slices = mesh_slices(2, devices=devs[:7])     # leftover chip unused
    assert len(slices) == 3
    assert all(s.axis_names == ("model",) for s in slices)
    used = [d for s in slices for d in s.devices.flat]
    assert len(set(used)) == 6                    # disjoint slices
    assert len(mesh_slices(2, devices=devs, max_slices=2)) == 2


# ---------------------------------------------------------------------------
# the kernel path under TP: shard_map over the model axis
# ---------------------------------------------------------------------------


def test_kernel_shard_map_matches_reference(rng):
    from paddle_tpu.serving.decode_attention import (
        BLOCK_ROWS, ragged_paged_attention_reference,
        ragged_paged_attention_tp)

    # block-uniform packing: one sequence per BLOCK_ROWS block (4 real
    # rows + 4 padding each), the contract the engine's packer owns
    h, kvh, d, pages, page = 4, 2, 8, 6, 8
    t = 2 * BLOCK_ROWS
    q = rng.randn(t, h, d).astype(np.float32)
    kp = rng.randn(pages, page, kvh, d).astype(np.float32)
    vp = rng.randn(pages, page, kvh, d).astype(np.float32)
    table = np.array([[1, 2, 3], [4, 5, 0]], np.int32)
    lens = np.array([20, 12], np.int32)
    row_seq = np.repeat(np.arange(2, dtype=np.int32), BLOCK_ROWS)
    qpos = np.full((t,), -1, np.int32)
    qpos[0:4] = np.arange(16, 20)
    qpos[BLOCK_ROWS:BLOCK_ROWS + 4] = np.arange(8, 12)
    want = ragged_paged_attention_reference(q, kp, vp, table, lens,
                                            row_seq, qpos)
    mesh = _mesh(2)
    got = ragged_paged_attention_tp(mesh, "model", q, kp, vp, table,
                                    lens, row_seq, qpos, use_kernel=True,
                                    interpret=True)
    real = qpos >= 0                       # padded rows are undefined
    np.testing.assert_allclose(np.asarray(got)[real],
                               np.asarray(want)[real],
                               rtol=2e-5, atol=2e-5)
    # the auto chooser on CPU routes to the reference fallback — same
    # semantics, no shard_map needed
    auto = ragged_paged_attention_tp(mesh, "model", q, kp, vp, table,
                                     lens, row_seq, qpos)
    np.testing.assert_allclose(np.asarray(auto)[real],
                               np.asarray(want)[real],
                               rtol=2e-5, atol=2e-5)
