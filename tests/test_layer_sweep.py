"""The FULL layer-registry sweep: every name exported by paddle_tpu.layer
is exercised — numeric-gradient-checked when differentiable, value-checked
against a hand oracle when not (argmax/sampling/slicing/decoding layers).

Reference analog: paddle/gserver/tests/test_LayerGrad.cpp — the reference's
core quality gate gradient-checks essentially every registered layer type
(testLayerGrad per type, LayerGradUtil.h:298). ``test_sweep_is_complete``
enforces the breadth: adding a layer without a sweep case fails CI.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import Topology

from test_layer_grad import check_layer_grad, dense, make_seq

RNG = np.random.RandomState(23)


@pytest.fixture(autouse=True)
def f32_math():
    # numeric-vs-analytic comparison needs f32 kernels (same fixture as
    # test_layer_grad; the bf16 MXU policy is benchmarked separately)
    from paddle_tpu.platform.flags import FLAGS
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


CASES = {}


def case(*names):
    def deco(fn):
        for n in names:
            CASES[n] = fn
        return fn
    return deco


def forward(out_node, feeds, seed=3, train=False, rng=None):
    """Build a topology around one node and run it; returns (output, params)."""
    topo = Topology([out_node])
    params = paddle.Parameters.from_topology(topo, seed=seed)
    outs, _ = topo.forward(params.as_dict(), topo.init_state(), feeds,
                           train=train, rng=rng)
    return outs[0], params


def img_data(name, h, w, c, n=3, scale=1.0):
    v = layer.data(name=name, type=paddle.data_type.dense_vector(h * w * c),
                   height=h, width=w)
    return v, (RNG.randn(n, h * w * c) * scale).astype(np.float32)


def int_seq(name, vocab, lengths, capacity=None):
    total = sum(lengths)
    cap = capacity or total
    seg = np.concatenate([np.full(L, i, np.int32)
                          for i, L in enumerate(lengths)])
    v = layer.data(name=name,
                   type=paddle.data_type.integer_value_sequence(vocab))
    sb = SequenceBatch(jnp.asarray(RNG.randint(0, vocab, (cap,)), jnp.int32),
                       jnp.asarray(seg), jnp.asarray(lengths, jnp.int32),
                       max_len=max(lengths))
    return v, sb


# ---------------------------------------------------------------------------
# core dense layers + projections + operators (all ride `mixed`)
# ---------------------------------------------------------------------------


@case("data", "fc")
def _fc():
    x, fx = dense("x", 6)
    check_layer_grad(layer.fc(x, size=5, act="tanh"), {"x": fx},
                     check_inputs=["x"])


@case("embedding")
def _embedding():
    ids = layer.data(name="ids", type=paddle.data_type.integer_value(11))
    feed = RNG.randint(0, 11, (4,)).astype(np.int32)
    check_layer_grad(layer.embedding(ids, size=5), {"ids": feed})


@case("mixed", "full_matrix_projection")
def _full_matrix():
    x, fx = dense("x", 6)
    check_layer_grad(layer.mixed(size=5, input=[
        layer.full_matrix_projection(x, size=5)]), {"x": fx},
        check_inputs=["x"])


@case("trans_full_matrix_projection")
def _trans_full_matrix():
    x, fx = dense("x", 6)
    check_layer_grad(layer.mixed(size=5, input=[
        layer.trans_full_matrix_projection(x, size=5)]), {"x": fx},
        check_inputs=["x"])


@case("identity_projection")
def _identity_proj():
    x, fx = dense("x", 6)
    check_layer_grad(layer.mixed(size=3, input=[
        layer.identity_projection(x, offset=2, size=3)]), {"x": fx},
        check_inputs=["x"])


@case("slice_projection")
def _slice_proj():
    x, fx = dense("x", 6)
    out = layer.mixed(size=4, input=[
        layer.slice_projection(x, slices=[(0, 2), (4, 6)])])
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])
    got, _ = forward(out, {"x": fx})
    np.testing.assert_allclose(np.asarray(got),
                               np.concatenate([fx[:, 0:2], fx[:, 4:6]], 1),
                               rtol=1e-5)


@case("dotmul_projection")
def _dotmul_proj():
    x, fx = dense("x", 6)
    check_layer_grad(layer.mixed(size=6, input=[
        layer.dotmul_projection(x)]), {"x": fx}, check_inputs=["x"])


@case("scaling_projection")
def _scaling_proj():
    x, fx = dense("x", 6)
    check_layer_grad(layer.mixed(size=6, input=[
        layer.scaling_projection(x)]), {"x": fx}, check_inputs=["x"])


@case("table_projection")
def _table_proj():
    ids = layer.data(name="ids", type=paddle.data_type.integer_value(9))
    feed = RNG.randint(0, 9, (4,)).astype(np.int32)
    check_layer_grad(layer.mixed(size=5, input=[
        layer.table_projection(ids, size=5)]), {"ids": feed})


@case("context_projection")
def _context_proj():
    s, fs = make_seq("s", 3, [3, 2])
    check_layer_grad(layer.mixed(size=9, input=[
        layer.context_projection(s, context_len=3, context_start=-1)]),
        {"s": fs})


@case("dotmul_operator")
def _dotmul_op():
    a, fa = dense("a", 6)
    b, fb = dense("b", 6)
    check_layer_grad(layer.mixed(size=6, input=[
        layer.dotmul_operator(a, b, scale=1.5)]), {"a": fa, "b": fb},
        check_inputs=["a", "b"])


@case("conv_operator")
def _conv_op():
    img, fi = img_data("img", 4, 4, 2)
    filt, ff = dense("filt", 3 * 3 * 2 * 2, n=3)
    out = layer.mixed(size=2 * 2 * 2, input=[
        layer.conv_operator(img, filt, filter_size=3, num_filters=2,
                            num_channels=2)])
    check_layer_grad(out, {"img": fi, "filt": ff}, delta=5e-3, rtol=6e-2,
                     check_inputs=["img", "filt"])


# ---------------------------------------------------------------------------
# elementwise / math layers
# ---------------------------------------------------------------------------


@case("addto")
def _addto():
    a, fa = dense("a", 5)
    b, fb = dense("b", 5)
    check_layer_grad(layer.addto([a, b], act="tanh", bias_attr=True),
                     {"a": fa, "b": fb}, check_inputs=["a", "b"])


@case("concat")
def _concat():
    a, fa = dense("a", 3)
    b, fb = dense("b", 4)
    check_layer_grad(layer.concat([a, b], act="sigmoid"),
                     {"a": fa, "b": fb}, check_inputs=["a", "b"])


@case("dotmul")
def _dotmul():
    a, fa = dense("a", 5)
    b, fb = dense("b", 5)
    check_layer_grad(layer.dotmul(a, b), {"a": fa, "b": fb},
                     check_inputs=["a", "b"])


@case("dotmul_bcast")
def _dotmul_bcast():
    a, fa = dense("a", 5)
    w, fw = dense("w", 1)
    check_layer_grad(layer.dotmul_bcast(a, w), {"a": fa, "w": fw},
                     check_inputs=["a", "w"])


@case("interpolation")
def _interpolation():
    a, fa = dense("a", 4)
    b, fb = dense("b", 4)
    w, fw = dense("w", 1)
    fw = np.clip(np.abs(fw), 0.2, 0.8).astype(np.float32)
    out = layer.interpolation(input=[a, b], weight=w)
    check_layer_grad(out, {"a": fa, "b": fb, "w": fw},
                     check_inputs=["a", "b", "w"])
    got, _ = forward(out, {"a": fa, "b": fb, "w": fw})
    np.testing.assert_allclose(np.asarray(got), fw * fa + (1 - fw) * fb,
                               rtol=1e-5)


@case("scaling")
def _scaling():
    x, fx = dense("x", 4)
    w, fw = dense("w", 1)
    check_layer_grad(layer.scaling(input=x, weight=w), {"x": fx, "w": fw},
                     check_inputs=["x", "w"])


@case("power")
def _power():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    fx = (np.abs(RNG.randn(4, 4)) + 0.5).astype(np.float32)
    w, fw = dense("w", 1)
    fw = np.clip(fw, 0.5, 2.0).astype(np.float32)
    check_layer_grad(layer.power(input=x, weight=w), {"x": fx, "w": fw},
                     check_inputs=["x", "w"], delta=5e-4)


@case("slope_intercept")
def _slope_intercept():
    x, fx = dense("x", 4)
    out = layer.slope_intercept(x, slope=2.0, intercept=-1.0)
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])
    got, _ = forward(out, {"x": fx})
    np.testing.assert_allclose(np.asarray(got), 2.0 * fx - 1.0, rtol=1e-5)


@case("sum_to_one_norm")
def _sum_to_one():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    fx = (np.abs(RNG.randn(3, 4)) + 0.1).astype(np.float32)
    out = layer.sum_to_one_norm(x)
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])
    got, _ = forward(out, {"x": fx})
    np.testing.assert_allclose(np.asarray(got).sum(-1), 1.0, rtol=1e-4)


@case("row_l2_norm")
def _row_l2():
    x, fx = dense("x", 4)
    out = layer.row_l2_norm(x)
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])
    got, _ = forward(out, {"x": fx})
    np.testing.assert_allclose(np.linalg.norm(np.asarray(got), axis=-1), 1.0,
                               rtol=1e-4)


@case("cos_sim")
def _cos_sim():
    a, fa = dense("a", 5)
    b, fb = dense("b", 5)
    out = layer.cos_sim(a, b, scale=2.0)
    check_layer_grad(out, {"a": fa, "b": fb}, check_inputs=["a", "b"])
    got, _ = forward(out, {"a": fa, "b": fb})
    want = 2.0 * (fa * fb).sum(-1) / (
        np.linalg.norm(fa, axis=-1) * np.linalg.norm(fb, axis=-1))
    np.testing.assert_allclose(np.asarray(got)[:, 0], want, rtol=1e-4)


@case("clip")
def _clip():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    fx = (RNG.rand(3, 4).astype(np.float32) - 0.5)  # interior of [-2, 2]
    out = layer.clip(x, min=-2.0, max=2.0)
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])
    wide = (RNG.randn(3, 4) * 5).astype(np.float32)
    got, _ = forward(layer.clip(
        layer.data(name="y", type=paddle.data_type.dense_vector(4)),
        min=-1.0, max=1.0), {"y": wide})
    np.testing.assert_allclose(np.asarray(got), np.clip(wide, -1, 1))


@case("resize")
def _resize():
    x, fx = dense("x", 6, n=4)
    out = layer.resize(x, size=3)
    got, _ = forward(out, {"x": fx})
    assert np.asarray(got).shape == (8, 3)
    np.testing.assert_allclose(np.asarray(got), fx.reshape(8, 3))
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])


@case("dropout")
def _dropout():
    x, fx = dense("x", 8, n=6)
    out = layer.dropout(x, dropout_rate=0.5)
    got, _ = forward(out, {"x": fx}, train=False)
    np.testing.assert_allclose(np.asarray(got), fx, rtol=1e-5)
    got_tr, _ = forward(out, {"x": fx}, train=True,
                        rng=jax.random.PRNGKey(4))
    a = np.asarray(got_tr)
    assert (a == 0).any()  # some units dropped
    kept = a != 0
    np.testing.assert_allclose(a[kept], (fx / 0.5)[kept], rtol=1e-5)


@case("data_norm")
def _data_norm():
    x, fx = dense("x", 4)
    mean, std = [1.0, 0.0, -1.0, 2.0], [2.0, 1.0, 0.5, 4.0]
    got, _ = forward(layer.data_norm(x, mean=mean, std=std), {"x": fx})
    np.testing.assert_allclose(np.asarray(got),
                               (fx - np.asarray(mean)) / np.asarray(std),
                               rtol=1e-5)
    got_mm, _ = forward(layer.data_norm(
        layer.data(name="y", type=paddle.data_type.dense_vector(4)),
        mean=mean, std=std, mode="min-max"), {"y": fx})
    np.testing.assert_allclose(np.asarray(got_mm),
                               (fx - np.asarray(mean)) / np.asarray(std),
                               rtol=1e-5)
    got_ds, _ = forward(layer.data_norm(
        layer.data(name="z", type=paddle.data_type.dense_vector(4)),
        std=[9.0, 99.0, 5.0, 1.0], mode="decimal-scaling"), {"z": fx})
    np.testing.assert_allclose(np.asarray(got_ds),
                               fx / np.array([10., 100., 10., 1.]),
                               rtol=1e-5)


@case("trans")
def _trans():
    x, fx = dense("x", 5, n=3)
    got, _ = forward(layer.trans(x), {"x": fx})
    np.testing.assert_allclose(np.asarray(got), fx.T)


@case("switch_order")
def _switch_order():
    h, w, c = 2, 3, 2
    x = layer.data(name="x", type=paddle.data_type.dense_vector(h * w * c),
                   height=h, width=w)
    fx = RNG.randn(2, h * w * c).astype(np.float32)
    got, _ = forward(layer.switch_order(x, reshape_to=("h", "w", "c")),
                     {"x": fx})
    want = fx.reshape(2, c, h, w).transpose(0, 2, 3, 1).reshape(2, -1)
    np.testing.assert_allclose(np.asarray(got), want)


@case("tensor")
def _tensor():
    a, fa = dense("a", 3)
    b, fb = dense("b", 4)
    check_layer_grad(layer.tensor(a, b, size=3), {"a": fa, "b": fb},
                     check_inputs=["a", "b"])


@case("out_prod")
def _out_prod():
    a, fa = dense("a", 3)
    b, fb = dense("b", 4)
    out = layer.out_prod(a, b)
    check_layer_grad(out, {"a": fa, "b": fb}, check_inputs=["a", "b"])
    got, _ = forward(out, {"a": fa, "b": fb})
    np.testing.assert_allclose(
        np.asarray(got),
        np.einsum("bi,bj->bij", fa, fb).reshape(len(fa), -1), rtol=1e-5)


@case("multiplex")
def _multiplex():
    idx = layer.data(name="idx", type=paddle.data_type.integer_value(2))
    fidx = np.array([0, 1, 0, 1], np.int32)
    a, fa = dense("a", 4)
    b, fb = dense("b", 4)
    out = layer.multiplex(idx, [a, b])
    check_layer_grad(out, {"idx": fidx, "a": fa, "b": fb},
                     check_inputs=["a", "b"])
    got, _ = forward(out, {"idx": fidx, "a": fa, "b": fb})
    np.testing.assert_allclose(np.asarray(got),
                               np.where(fidx[:, None] == 0, fa, fb))


@case("conv_shift")
def _conv_shift():
    a, fa = dense("a", 6)
    b, fb = dense("b", 3)
    check_layer_grad(layer.conv_shift(a, b), {"a": fa, "b": fb},
                     check_inputs=["a", "b"])


@case("linear_comb")
def _linear_comb():
    w, fw = dense("w", 3)
    v, fv = dense("v", 3 * 4)
    out = layer.linear_comb(w, v, size=4)
    check_layer_grad(out, {"w": fw, "v": fv}, check_inputs=["w", "v"])
    got, _ = forward(out, {"w": fw, "v": fv})
    np.testing.assert_allclose(
        np.asarray(got),
        np.einsum("bm,bmd->bd", fw, fv.reshape(-1, 3, 4)), rtol=1e-5)


@case("convex_comb")
def _convex_comb():
    w, fw = dense("w", 3)
    v, fv = dense("v", 3 * 4)
    check_layer_grad(layer.convex_comb(w, v, size=4), {"w": fw, "v": fv},
                     check_inputs=["w", "v"])


@case("cos_vm")
def _cos_vm():
    a, fa = dense("a", 4)
    b, fb = dense("b", 3 * 4)
    out = layer.cos_vm(a, b, size=3)
    check_layer_grad(out, {"a": fa, "b": fb}, check_inputs=["a", "b"])


@case("prelu")
def _prelu():
    x, fx = dense("x", 8)
    check_layer_grad(layer.prelu(x, partial_sum=2), {"x": fx},
                     check_inputs=["x"])


@case("scale_shift")
def _scale_shift():
    x, fx = dense("x", 4)
    check_layer_grad(layer.scale_shift(x), {"x": fx}, check_inputs=["x"])


@case("get_output")
def _get_output():
    x, fx = dense("x", 4)
    node = layer.fc(x, size=3, act="tanh", name="base")
    got_direct, _ = forward(node, {"x": fx}, seed=7)
    paddle.topology.reset_name_scope()
    x2, _ = dense("x", 4)
    node2 = layer.fc(x2, size=3, act="tanh", name="base")
    got_wrapped, _ = forward(layer.get_output(node2), {"x": fx}, seed=7)
    np.testing.assert_allclose(np.asarray(got_direct),
                               np.asarray(got_wrapped))


@case("print_layer")
def _print_layer():
    x, fx = dense("x", 4)
    got, _ = forward(layer.print_layer(x), {"x": fx})
    np.testing.assert_allclose(np.asarray(got), fx)


# ---------------------------------------------------------------------------
# image stack
# ---------------------------------------------------------------------------


@case("img_conv")
def _img_conv():
    x, fx = img_data("x", 5, 5, 2)
    check_layer_grad(layer.img_conv(x, filter_size=3, num_filters=3,
                                    num_channels=2, padding=1, act="relu"),
                     {"x": fx}, delta=5e-3, rtol=6e-2)


@case("img_pool")
def _img_pool():
    x, fx = img_data("x", 4, 4, 2)
    check_layer_grad(layer.img_pool(x, pool_size=2), {"x": fx},
                     check_inputs=["x"])


@case("spp")
def _spp():
    x, fx = img_data("x", 4, 4, 2)
    out = layer.spp(x, pyramid_height=2)
    assert out.size == (1 + 4) * 2
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])


@case("maxout")
def _maxout():
    x, fx = img_data("x", 3, 3, 4)
    out = layer.maxout(x, groups=2)
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])


@case("batch_norm")
def _batch_norm():
    x, fx = img_data("x", 4, 4, 2)
    bn = layer.batch_norm(layer.img_conv(
        x, filter_size=3, num_filters=2, num_channels=2, padding=1))
    check_layer_grad(bn, {"x": fx}, delta=5e-3, rtol=8e-2)


@case("layer_norm")
def _layer_norm():
    x, fx = dense("x", 6)
    check_layer_grad(layer.layer_norm(x), {"x": fx}, check_inputs=["x"],
                     delta=5e-3, rtol=6e-2)


@case("img_cmrnorm")
def _img_cmrnorm():
    x, fx = img_data("x", 4, 4, 2)
    check_layer_grad(layer.img_cmrnorm(x, size=3), {"x": fx},
                     check_inputs=["x"])


@case("bilinear_interp")
def _bilinear():
    x, fx = img_data("x", 3, 3, 2)
    out = layer.bilinear_interp(x, out_size_x=5, out_size_y=5)
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])


@case("pad")
def _pad():
    x, fx = img_data("x", 3, 3, 2)
    out = layer.pad(x, pad_c=(1, 1), pad_h=(0, 1), pad_w=(1, 0))
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])


@case("crop")
def _crop():
    x, fx = img_data("x", 4, 4, 2)
    out = layer.crop(x, offset_h=1, offset_w=1, crop_h=2, crop_w=2)
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])


@case("rotate")
def _rotate():
    h, w, c = 2, 3, 2
    x, fx = img_data("x", h, w, c, n=2)
    got, _ = forward(layer.rotate(x), {"x": fx})
    # dense image slots are CHW-flat (reference PyDataProvider2 layout)
    nhwc = fx.reshape(2, c, h, w).transpose(0, 2, 3, 1)
    want = np.rot90(nhwc, k=1, axes=(1, 2))
    np.testing.assert_allclose(np.asarray(got).reshape(want.shape), want)


@case("block_expand")
def _block_expand():
    x, fx = img_data("x", 4, 4, 2)
    out = layer.block_expand(x, block_x=2, block_y=2, stride_x=2, stride_y=2)
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])


@case("img_conv3d")
def _img_conv3d():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3 * 3 * 3 * 1))
    fx = RNG.randn(2, 27).astype(np.float32)
    out = layer.img_conv3d(x, filter_size=2, num_filters=2, num_channels=1,
                           depth=3, height=3, width=3)
    check_layer_grad(out, {"x": fx}, delta=5e-3, rtol=6e-2)


@case("img_pool3d")
def _img_pool3d():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3 * 3 * 3))
    fx = RNG.randn(2, 27).astype(np.float32)
    conv = layer.img_conv3d(x, filter_size=2, num_filters=2, num_channels=1,
                            depth=3, height=3, width=3)  # sets vol_shape
    out = layer.img_pool3d(conv, pool_size=2,
                           pool_type=paddle.pooling.AvgPooling())
    check_layer_grad(out, {"x": fx}, delta=5e-3, rtol=6e-2)


@case("mdlstmemory")
def _mdlstm():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(2 * 2 * 2))
    fx = RNG.randn(2, 8).astype(np.float32)
    out = layer.mdlstmemory(x, size=2, height=2, width=2)
    check_layer_grad(out, {"x": fx}, delta=5e-3, rtol=8e-2)


@case("featmap_expand")
def _featmap_expand():
    x, fx = dense("x", 3)
    out = layer.featmap_expand(x, num_filters=2)
    assert out.size == 6
    check_layer_grad(out, {"x": fx}, check_inputs=["x"])
    got, _ = forward(out, {"x": fx})
    np.testing.assert_allclose(np.asarray(got), np.tile(fx, (1, 2)))


# ---------------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------------


@case("pooling")
def _pooling():
    s, fs = make_seq("s", 3, [3, 2])
    check_layer_grad(layer.pooling(s), {"s": fs})


@case("last_seq")
def _last_seq():
    s, fs = make_seq("s", 3, [3, 2])
    check_layer_grad(layer.last_seq(s), {"s": fs})


@case("first_seq")
def _first_seq():
    s, fs = make_seq("s", 3, [3, 2])
    check_layer_grad(layer.first_seq(s), {"s": fs})


@case("expand")
def _expand():
    s, fs = make_seq("s", 3, [3, 2])
    check_layer_grad(layer.expand(layer.pooling(s), s), {"s": fs})


@case("seq_concat")
def _seq_concat():
    a, fa = make_seq("a", 3, [2, 2])
    b, fb = make_seq("b", 3, [1, 2])
    check_layer_grad(layer.seq_concat(a, b), {"a": fa, "b": fb})


@case("seq_reshape")
def _seq_reshape():
    s, fs = make_seq("s", 4, [2, 2])
    out = layer.seq_reshape(s, reshape_size=2)
    check_layer_grad(out, {"s": fs})
    got, _ = forward(out, {"s": fs})
    assert got.data.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(got.lengths), [4, 4])


@case("seq_slice")
def _seq_slice():
    s, fs = make_seq("s", 3, [4, 3])
    starts = layer.data(name="st", type=paddle.data_type.integer_value(8))
    ends = layer.data(name="en", type=paddle.data_type.integer_value(8))
    fst = np.array([1, 0], np.int32)
    fen = np.array([3, 2], np.int32)
    out = layer.seq_slice(s, starts=starts, ends=ends)
    got, _ = forward(out, {"s": fs, "st": fst, "en": fen})
    np.testing.assert_allclose(np.asarray(got.lengths), [2, 2])
    # kept slots hold tokens with start <= pos < end
    pos = np.concatenate([np.arange(4), np.arange(3)])
    seg = np.asarray(fs.segment_ids)
    keep = (pos >= fst[seg]) & (pos < fen[seg])
    np.testing.assert_allclose(np.asarray(got.data)[keep],
                               np.asarray(fs.data)[keep])
    assert (np.asarray(got.data)[~keep] == 0).all()


@case("subseq")
def _subseq():
    s, fs = make_seq("s", 3, [4, 3])
    offs = layer.data(name="of", type=paddle.data_type.integer_value(8))
    sizes = layer.data(name="sz", type=paddle.data_type.integer_value(8))
    out = layer.subseq(s, offs, sizes)
    got, _ = forward(out, {"s": fs, "of": np.array([1, 0], np.int32),
                           "sz": np.array([2, 2], np.int32)})
    np.testing.assert_allclose(np.asarray(got.lengths), [2, 2])


@case("kmax_seq_score")
def _kmax():
    s = layer.data(name="s",
                   type=paddle.data_type.dense_vector_sequence(1))
    scores = np.array([0.1, 0.9, 0.5, 0.3, 0.8, 0.2], np.float32)
    seg = np.array([0, 0, 0, 1, 1, 1], np.int32)
    sb = SequenceBatch(jnp.asarray(scores[:, None]), jnp.asarray(seg),
                       jnp.asarray([3, 3], np.int32), max_len=3)
    got, _ = forward(layer.kmax_seq_score(s, beam_size=2), {"s": sb})
    np.testing.assert_array_equal(np.asarray(got), [[1, 2], [1, 0]])


@case("sub_nested_seq")
def _sub_nested():
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(2))
    data = RNG.randn(5, 2).astype(np.float32)
    sb = SequenceBatch(jnp.asarray(data),
                       jnp.asarray([0, 0, 0, 1, 1], np.int32),
                       jnp.asarray([3, 2], np.int32),
                       sub_segment_ids=jnp.asarray([0, 0, 1, 0, 0], np.int32),
                       max_len=3)
    sel = layer.data(name="sel", type=paddle.data_type.integer_value(4))
    fsel = np.array([[0], [0]], np.int32)   # keep inner seq 0 of each
    got, _ = forward(layer.sub_nested_seq(s, sel), {"s": sb, "sel": fsel})
    np.testing.assert_allclose(np.asarray(got.lengths), [2, 2])
    got_d = np.asarray(got.data)
    np.testing.assert_allclose(got_d[[0, 1, 3, 4]], data[[0, 1, 3, 4]])
    assert (got_d[2] == 0).all()


@case("max_id")
def _max_id():
    x, fx = dense("x", 6)
    got, _ = forward(layer.max_id(x), {"x": fx})
    np.testing.assert_array_equal(np.asarray(got), fx.argmax(-1))


@case("sampling_id")
def _sampling_id():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    peaked = np.zeros((5, 4), np.float32)
    peaked[:, 2] = 1.0   # all mass on id 2
    got, _ = forward(layer.sampling_id(x), {"x": peaked})
    np.testing.assert_array_equal(np.asarray(got), np.full(5, 2))


@case("eos")
def _eos():
    s = layer.data(name="s",
                   type=paddle.data_type.integer_value_sequence(10))
    toks = np.array([4, 7, 1, 3, 5, 5, 7, 2], np.int32)  # eos id = 7
    seg = np.array([0, 0, 0, 0, 1, 1, 1, 1], np.int32)
    sb = SequenceBatch(jnp.asarray(toks), jnp.asarray(seg),
                       jnp.asarray([4, 4], np.int32), max_len=4)
    got, _ = forward(layer.eos(s, eos_id=7), {"s": sb})
    np.testing.assert_allclose(np.asarray(got.lengths), [1, 2])


# ---------------------------------------------------------------------------
# recurrent stack (memories, step cells, groups)
# ---------------------------------------------------------------------------


@case("lstmemory")
def _lstmemory():
    s, fs = make_seq("s", 4, [3, 2])
    check_layer_grad(layer.lstmemory(layer.fc(s, size=16)), {"s": fs},
                     delta=5e-3, rtol=8e-2)


@case("grumemory")
def _grumemory():
    s, fs = make_seq("s", 4, [3, 2])
    check_layer_grad(layer.grumemory(layer.fc(s, size=12)), {"s": fs},
                     delta=5e-3, rtol=8e-2)


@case("gated_recurrent")
def _gated_recurrent():
    assert layer.gated_recurrent is layer.grumemory


@case("recurrent")
def _recurrent():
    s, fs = make_seq("s", 4, [4, 2])
    check_layer_grad(layer.recurrent(s), {"s": fs}, delta=5e-3)


@case("SubsequenceInput")
def _subsequence_input():
    # hierarchical group: outer loop over inner sequences (oracle-matched
    # in test_recurrent_group; here the grad path is swept)
    D, H = 3, 3
    x = layer.data(name="x",
                   type=paddle.data_type.dense_vector_sub_sequence(D))

    def step(sentence):
        pooled = layer.pooling(input=sentence,
                               pooling_type=paddle.pooling.AvgPooling())
        m = layer.memory(name="hs", size=H)
        return layer.fc(input=[pooled, m], size=H, act="tanh", name="hs")

    grp = layer.recurrent_group(
        step=step, input=layer.SubsequenceInput(x, max_inner=3,
                                                max_inner_len=4),
        name="rg_sweep_nest")
    toks = RNG.randn(7, D).astype(np.float32) * 0.5
    sb = SequenceBatch(
        jnp.asarray(toks), jnp.asarray([0, 0, 0, 0, 0, 1, 1], np.int32),
        jnp.asarray([5, 2], np.int32),
        sub_segment_ids=jnp.asarray([0, 0, 1, 1, 1, 0, 0], np.int32),
        max_len=5)
    check_layer_grad(layer.pooling(grp), {"x": sb}, delta=5e-3, rtol=8e-2)


@case("recurrent_group", "memory", "gru_step")
def _group_gru():
    H = 3
    s, fs = make_seq("s", 3 * H, [3, 2])

    def step(frame):
        m = layer.memory(name="g", size=H)
        return layer.gru_step(input=frame, output_mem=m, size=H, name="g")

    grp = layer.recurrent_group(step=step, input=s, name="rg_sweep")
    check_layer_grad(layer.pooling(grp), {"s": fs}, delta=5e-3, rtol=8e-2)


@case("lstm_step", "lstm_step_output", "lstm_step_state", "StaticInput")
def _group_lstm():
    H = 3
    s, fs = make_seq("s", 4 * H, [3, 2])
    bias, fb = dense("bias", H, n=2)

    def step(frame, static_bias):
        c_mem = layer.memory(name="c_out", size=H)
        h_mem = layer.memory(name="h_out", size=H)
        st = layer.lstm_step(input=frame, state_mem=c_mem,
                             output_mem=h_mem, size=H, name="cell")
        h = layer.lstm_step_output(st, name="h_out")
        c = layer.get_output(st, arg_name="state", name="c_out")
        out = layer.addto([h, static_bias])
        return [out, c]

    outs = layer.recurrent_group(
        step=step, input=[s, layer.StaticInput(bias)], name="rg_lstm_sweep")
    h_seq = outs[0] if isinstance(outs, (list, tuple)) else outs
    check_layer_grad(layer.pooling(h_seq), {"s": fs, "bias": fb},
                     delta=5e-3, rtol=8e-2, check_inputs=["bias"])


@case("row_conv")
def _row_conv():
    s, fs = make_seq("s", 3, [3, 2])
    check_layer_grad(layer.row_conv(s, context_len=2), {"s": fs})


@case("multi_head_attention")
def _mha():
    s, fs = make_seq("s", 8, [3, 2])
    out = layer.multi_head_attention(s, num_heads=2)
    check_layer_grad(layer.pooling(out), {"s": fs}, delta=5e-3, rtol=8e-2)


@case("selective_fc")
def _selective_fc():
    x, fx = dense("x", 6)
    check_layer_grad(layer.selective_fc(x, size=5), {"x": fx})


# ---------------------------------------------------------------------------
# classification-with-sampling costs + structured costs
# ---------------------------------------------------------------------------


@case("nce")
def _nce():
    x, fx = dense("x", 6)
    lab = layer.data(name="lab", type=paddle.data_type.integer_value(8))
    flab = RNG.randint(0, 8, (4,)).astype(np.int32)
    check_layer_grad(layer.nce(x, lab, num_classes=8, num_neg_samples=3),
                     {"x": fx, "lab": flab}, check_inputs=["x"])


@case("hsigmoid")
def _hsigmoid():
    x, fx = dense("x", 6)
    lab = layer.data(name="lab", type=paddle.data_type.integer_value(8))
    flab = RNG.randint(0, 8, (4,)).astype(np.int32)
    check_layer_grad(layer.hsigmoid(x, lab, num_classes=8),
                     {"x": fx, "lab": flab}, check_inputs=["x"])


@case("crf")
def _crf():
    s, fs = make_seq("s", 3, [3, 2])
    lab = layer.data(name="lab",
                     type=paddle.data_type.integer_value_sequence(3))
    flab = SequenceBatch(
        jnp.asarray(RNG.randint(0, 3, (5,)).astype(np.int32)),
        fs.segment_ids, fs.lengths, max_len=fs.max_len)
    check_layer_grad(layer.crf(input=layer.fc(s, size=3), label=lab, size=3),
                     {"s": fs, "lab": flab}, delta=5e-3, rtol=8e-2)


@case("crf_decoding")
def _crf_decoding():
    # emissions dominate the (small random-init) transitions ⇒ the decode
    # must equal per-token argmax
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(3))
    em = np.zeros((5, 3), np.float32)
    best = np.array([2, 0, 1, 1, 2])
    em[np.arange(5), best] = 100.0
    sb = SequenceBatch(jnp.asarray(em),
                       jnp.asarray([0, 0, 0, 1, 1], np.int32),
                       jnp.asarray([3, 2], np.int32), max_len=3)
    got, _ = forward(layer.crf_decoding(s, size=3), {"s": sb})
    d = np.asarray(got.data).reshape(-1)
    np.testing.assert_array_equal(d[:5], best)


@case("ctc")
def _ctc():
    s, fs = make_seq("s", 4, [4, 4])     # 3 symbols + blank
    lab, flab = int_seq("lab", 3, [2, 1], capacity=3)
    flab = flab.with_data(jnp.clip(flab.data, 1, 2))  # avoid blank id 0
    check_layer_grad(layer.ctc(s, lab, blank=0), {"s": fs, "lab": flab},
                     delta=5e-3, rtol=8e-2)


@case("warp_ctc")
def _warp_ctc():
    s, fs = make_seq("s", 4, [4, 4])
    lab, flab = int_seq("lab", 3, [2, 1], capacity=3)
    flab = flab.with_data(jnp.clip(flab.data, 1, 2))
    got_w, _ = forward(layer.warp_ctc(s, lab, blank=0),
                       {"s": fs, "lab": flab}, seed=2)
    paddle.topology.reset_name_scope()
    s2, _ = make_seq("s", 4, [4, 4])
    lab2 = layer.data(name="lab",
                      type=paddle.data_type.integer_value_sequence(3))
    got_c, _ = forward(layer.ctc(s2, lab2, blank=0),
                       {"s": fs, "lab": flab}, seed=2)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(got_c),
                               rtol=1e-6)


@case("classification_cost")
def _classification_cost():
    x, fx = dense("x", 5)
    lab = layer.data(name="lab", type=paddle.data_type.integer_value(5))
    flab = RNG.randint(0, 5, (4,)).astype(np.int32)
    check_layer_grad(
        layer.classification_cost(input=layer.fc(x, size=5), label=lab),
        {"x": fx, "lab": flab}, check_inputs=["x"])


@case("cross_entropy_cost")
def _cross_entropy_cost():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(5))
    raw = RNG.rand(4, 5).astype(np.float32) + 0.2
    probs = (raw / raw.sum(-1, keepdims=True)).astype(np.float32)
    lab = layer.data(name="lab", type=paddle.data_type.integer_value(5))
    flab = RNG.randint(0, 5, (4,)).astype(np.int32)
    check_layer_grad(layer.cross_entropy_cost(x, lab),
                     {"x": probs, "lab": flab}, check_inputs=["x"])


@case("cross_entropy_with_selfnorm_cost")
def _selfnorm_cost():
    x, fx = dense("x", 5)
    lab = layer.data(name="lab", type=paddle.data_type.integer_value(5))
    flab = RNG.randint(0, 5, (4,)).astype(np.int32)
    check_layer_grad(layer.cross_entropy_with_selfnorm_cost(x, lab),
                     {"x": fx, "lab": flab}, check_inputs=["x"])


@case("square_error_cost")
def _square_error():
    x, fx = dense("x", 5)
    t, ft = dense("t", 5)
    check_layer_grad(layer.square_error_cost(input=x, label=t),
                     {"x": fx, "t": ft}, check_inputs=["x"])


@case("regression_cost")
def _regression_cost():
    assert layer.regression_cost is layer.square_error_cost


@case("multi_binary_label_cross_entropy_cost")
def _multi_binary():
    x, fx = dense("x", 5)
    lab = layer.data(name="lab", type=paddle.data_type.dense_vector(5))
    flab = (RNG.rand(4, 5) > 0.5).astype(np.float32)
    check_layer_grad(
        layer.multi_binary_label_cross_entropy_cost(x, lab),
        {"x": fx, "lab": flab}, check_inputs=["x"])


@case("soft_binary_class_cross_entropy_cost")
def _soft_binary():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(5))
    fx = np.clip(RNG.rand(4, 5), 0.2, 0.8).astype(np.float32)
    lab = layer.data(name="lab", type=paddle.data_type.dense_vector(5))
    flab = RNG.rand(4, 5).astype(np.float32)
    check_layer_grad(
        layer.soft_binary_class_cross_entropy_cost(x, lab),
        {"x": fx, "lab": flab}, check_inputs=["x"])


@case("rank_cost")
def _rank_cost():
    left, fl = dense("left", 1)
    right, fr = dense("right", 1)
    lab = layer.data(name="lab", type=paddle.data_type.dense_vector(1))
    flab = (RNG.rand(4, 1) > 0.5).astype(np.float32)
    check_layer_grad(layer.rank_cost(left, right, lab),
                     {"left": fl, "right": fr, "lab": flab},
                     check_inputs=["left", "right"])


@case("lambda_cost")
def _lambda_cost():
    s, fs = make_seq("s", 1, [4, 3])
    rel = layer.data(name="rel",
                     type=paddle.data_type.dense_vector_sequence(1))
    frel = fs.with_data(jnp.asarray(
        RNG.randint(0, 3, (7, 1)).astype(np.float32)))
    check_layer_grad(layer.lambda_cost(s, rel, NDCG_num=3),
                     {"s": fs, "rel": frel}, delta=5e-3, rtol=8e-2)


@case("huber_regression_cost")
def _huber_regression():
    x, fx = dense("x", 1)
    t, ft = dense("t", 1)
    check_layer_grad(layer.huber_regression_cost(input=x, label=t),
                     {"x": fx, "t": ft}, check_inputs=["x"])


@case("huber_classification_cost")
def _huber_classification():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(1))
    fx = (RNG.rand(4, 1).astype(np.float32) - 0.5)  # away from the ±1 kinks
    lab = layer.data(name="lab", type=paddle.data_type.dense_vector(1))
    flab = (RNG.rand(4, 1) > 0.5).astype(np.float32)
    check_layer_grad(layer.huber_classification_cost(x, lab),
                     {"x": fx, "lab": flab}, check_inputs=["x"])


@case("smooth_l1_cost")
def _smooth_l1():
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    t = layer.data(name="t", type=paddle.data_type.dense_vector(4))
    fx = (RNG.rand(3, 4).astype(np.float32) * 0.6 - 0.3)
    ft = (RNG.rand(3, 4).astype(np.float32) * 0.6 - 0.3)  # |diff| < 1 kink
    check_layer_grad(layer.smooth_l1_cost(x, t), {"x": fx, "t": ft},
                     check_inputs=["x"])


@case("moe_ffn")
def _moe_ffn_layer():
    x, fx = dense("x", 6)
    out, aux = layer.moe_ffn(x, num_experts=4, expert_hidden=8,
                             capacity_factor=8.0)
    check_layer_grad(out, {"x": fx}, delta=5e-3, rtol=8e-2)
    got_aux, _ = forward(aux, {"x": fx})
    assert np.asarray(got_aux).shape == (1,)
    assert np.isfinite(np.asarray(got_aux)).all()


@case("lm_head_cost")
def _lm_head_cost():
    x, fx = dense("x", 6)
    lab = layer.data(name="lab", type=paddle.data_type.integer_value(11))
    flab = RNG.randint(0, 11, (4,)).astype(np.int32)
    check_layer_grad(layer.lm_head_cost(x, lab, vocab_size=11, block_size=4),
                     {"x": fx, "lab": flab}, check_inputs=["x"])


@case("sum_cost")
def _sum_cost():
    x, fx = dense("x", 5)
    check_layer_grad(layer.sum_cost(x), {"x": fx}, check_inputs=["x"])


@case("cross_entropy_over_beam", "BeamInput")
def _beam_cost():
    scores = layer.data(name="scores", type=paddle.data_type.dense_vector(6))
    fscores = RNG.randn(1, 6).astype(np.float32)
    sel = layer.data(name="sel", type=paddle.data_type.integer_value(6))
    fsel = np.array([[0, 2, 4]], np.int32)
    gold = layer.data(name="gold", type=paddle.data_type.integer_value(6))
    fgold = np.array([2], np.int32)
    beam = layer.BeamInput(candidate_scores=scores,
                           selected_candidates=sel, gold=gold)
    out = layer.cross_entropy_over_beam(beam)
    feeds = {"scores": fscores, "sel": fsel, "gold": fgold}
    check_layer_grad(out, feeds, check_inputs=["scores"])
    got, _ = forward(out, feeds)
    assert float(np.asarray(got).sum()) > 0.0


# ---------------------------------------------------------------------------
# detection stack
# ---------------------------------------------------------------------------


def _ssd_graph():
    feat, _ = img_data("feat", 2, 2, 3)
    pb = layer.priorbox(feat, image_size=32, min_size=8, max_size=16,
                        aspect_ratio=(2.0,))
    num_p = pb.num_priors
    loc = layer.data(name="loc", type=paddle.data_type.dense_vector(num_p * 4))
    conf = layer.data(name="conf",
                      type=paddle.data_type.dense_vector(num_p * 3))
    return feat, pb, loc, conf, num_p


@case("priorbox")
def _priorbox():
    feat, pb, *_rest, num_p = _ssd_graph()
    got, _ = forward(pb, {"feat": np.zeros((1, 12), np.float32)})
    a = np.asarray(got).reshape(-1)
    assert a.shape[0] == num_p * 8
    boxes = a[: num_p * 4].reshape(num_p, 4)
    assert (boxes >= 0).all() and (boxes <= 1).all()
    assert (boxes[:, 2] > boxes[:, 0]).all()  # xmax > xmin


@case("multibox_loss")
def _multibox_loss():
    feat, pb, loc, conf, num_p = _ssd_graph()
    gt = layer.data(name="gt", type=paddle.data_type.dense_vector(2 * 5))
    cost = layer.multibox_loss(loc, conf, pb, gt, num_classes=3, max_boxes=2)
    fgt = np.array([[1, 0.1, 0.1, 0.5, 0.5, -1, 0, 0, 0, 0]], np.float32)
    floc = np.zeros((1, num_p * 4), np.float32)
    fconf_good = np.zeros((1, num_p, 3), np.float32)
    fconf_good[:, :, 1] = 4.0   # confident in the gt class everywhere
    fconf_bad = np.zeros((1, num_p, 3), np.float32)
    fconf_bad[:, :, 2] = 4.0    # confident in the wrong class
    feeds = {"feat": np.zeros((1, 12), np.float32), "loc": floc, "gt": fgt}
    good, _ = forward(cost, {**feeds, "conf": fconf_good.reshape(1, -1)})
    paddle.topology.reset_name_scope()
    feat, pb, loc, conf, num_p = _ssd_graph()
    gt = layer.data(name="gt", type=paddle.data_type.dense_vector(2 * 5))
    cost = layer.multibox_loss(loc, conf, pb, gt, num_classes=3, max_boxes=2)
    bad, _ = forward(cost, {**feeds, "conf": fconf_bad.reshape(1, -1)})
    assert float(np.asarray(good).sum()) < float(np.asarray(bad).sum())


@case("detection_output")
def _detection_output():
    feat, pb, loc, conf, num_p = _ssd_graph()
    det = layer.detection_output(loc, conf, pb, num_classes=3, keep_top_k=4)
    floc = np.zeros((1, num_p * 4), np.float32)
    fconf = np.full((1, num_p, 3), -8.0, np.float32)
    fconf[0, 0, 1] = 8.0        # one confident detection on prior 0
    got, _ = forward(det, {"feat": np.zeros((1, 12), np.float32),
                           "loc": floc, "conf": fconf.reshape(1, -1)})
    rows = np.asarray(got).reshape(4, 6)
    kept = rows[rows[:, 0] >= 0]
    assert len(kept) >= 1
    assert int(kept[0, 0]) == 1 and kept[0, 1] > 0.9


# ---------------------------------------------------------------------------
# completeness gates
# ---------------------------------------------------------------------------


def test_sweep_is_complete():
    """Every name layer.py exports has a sweep case (test_LayerGrad breadth)."""
    missing = sorted(set(layer.__all__) - set(CASES))
    assert not missing, f"layers with no sweep case: {missing}"


_UNIQUE = {}
for _n, _f in CASES.items():
    _UNIQUE.setdefault(_f, []).append(_n)


@pytest.mark.parametrize(
    "fn", list(_UNIQUE),
    ids=["+".join(sorted(ns)) for ns in _UNIQUE.values()])
def test_layer(fn):
    paddle.topology.reset_name_scope()
    fn()
