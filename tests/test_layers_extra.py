"""Tests for the completeness batch of v2 layers (prelu, tensor, multiplex,
detection suite, 3-D convs, MDLSTM, ...).

Reference analog: paddle/gserver/tests/test_LayerGrad.cpp — every layer is
run forward and (for parametric layers) gradient-checked numerically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import Topology


def forward(out_layers, feeds, seed=0):
    paddle.topology
    topo = Topology(out_layers if isinstance(out_layers, list)
                    else [out_layers])
    params = paddle.Parameters.from_topology(topo, seed=seed)
    state = topo.init_state()
    outs, _ = topo.forward(params.as_dict(), state, feeds, train=False)
    return outs, params, topo


def numeric_grad_check(cost_node, feeds, rtol=5e-2, atol=5e-3, delta=1e-3):
    """testLayerGrad analog: analytic d(cost)/d(param) vs central difference."""
    topo = Topology([cost_node])
    params = paddle.Parameters.from_topology(topo, seed=1)
    state = topo.init_state()
    pdict = {k: np.asarray(v, np.float64).astype(np.float32)
             for k, v in params.as_dict().items()}

    def loss_fn(p):
        outs, _ = topo.forward(p, state, feeds, train=False)
        return jnp.mean(outs[0])

    analytic = jax.grad(loss_fn)(pdict)
    for name, val in pdict.items():
        flat = np.asarray(val).ravel()
        take = min(4, flat.size)
        idxs = np.linspace(0, flat.size - 1, take).astype(int)
        for i in idxs:
            pu = {k: np.array(v, np.float32) for k, v in pdict.items()}
            pu[name].ravel()[i] += delta
            up = float(loss_fn(pu))
            pd_ = {k: np.array(v, np.float32) for k, v in pdict.items()}
            pd_[name].ravel()[i] -= delta
            down = float(loss_fn(pd_))
            num = (up - down) / (2 * delta)
            ana = float(np.asarray(analytic[name]).ravel()[i])
            assert abs(num - ana) <= atol + rtol * abs(num), \
                (name, i, num, ana)


def make_seq(rng, lengths, dim, capacity=None):
    total = sum(lengths)
    capacity = capacity or total
    data = np.zeros((capacity, dim), np.float32)
    data[:total] = rng.randn(total, dim)
    seg = np.full(capacity, len(lengths), np.int32)
    pos = 0
    for i, L in enumerate(lengths):
        seg[pos:pos + L] = i
        pos += L
    return SequenceBatch(jnp.asarray(data), jnp.asarray(seg),
                         jnp.asarray(np.asarray(lengths, np.int32)),
                         max_len=max(lengths))


def test_prelu_forward_and_grad(rng):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    out = layer.prelu(x, partial_sum=4)
    cost = layer.mixed(input=layer.identity_projection(out), size=8)
    feeds = {"x": rng.randn(3, 8).astype(np.float32)}
    outs, params, _ = forward(out, feeds)
    # slopes init: verify negative side scaled by slope
    numeric_grad_check(out, feeds)


def test_scale_shift_and_data_norm(rng):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(5))
    ss = layer.scale_shift(x)
    dn = layer.data_norm(x, mean=np.ones(5, np.float32),
                         std=2 * np.ones(5, np.float32))
    xb = rng.randn(4, 5).astype(np.float32)
    outs, params, _ = forward([ss, dn], {"x": xb})
    w = float(params[ss.name + ".w"][0])
    b = float(params[ss.name + ".b"][0])
    np.testing.assert_allclose(np.asarray(outs[0]), xb * w + b, atol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]), (xb - 1) / 2, atol=1e-5)


def test_tensor_out_prod_cos_vm(rng):
    paddle.topology.reset_name_scope()
    a = layer.data(name="a", type=paddle.data_type.dense_vector(3))
    b = layer.data(name="b", type=paddle.data_type.dense_vector(4))
    t = layer.tensor(a, b, size=5)
    op = layer.out_prod(a, b)
    ab = rng.randn(2, 3).astype(np.float32)
    bb = rng.randn(2, 4).astype(np.float32)
    outs, params, _ = forward([t, op], {"a": ab, "b": bb})
    w = np.asarray(params[t.name + ".w"])
    expect_t = np.einsum("bi,kij,bj->bk", ab, w, bb)
    np.testing.assert_allclose(np.asarray(outs[0]), expect_t, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(outs[1]),
        np.einsum("bi,bj->bij", ab, bb).reshape(2, -1), atol=1e-5)

    paddle.topology.reset_name_scope()
    v = layer.data(name="v", type=paddle.data_type.dense_vector(3))
    m = layer.data(name="m", type=paddle.data_type.dense_vector(6))
    cv = layer.cos_vm(v, m, size=2)
    vb = rng.randn(2, 3).astype(np.float32)
    mb = rng.randn(2, 6).astype(np.float32)
    outs, _, _ = forward(cv, {"v": vb, "m": mb})
    mm = mb.reshape(2, 2, 3)
    expect = np.einsum("bd,bmd->bm", vb, mm) / (
        np.linalg.norm(vb, axis=1, keepdims=True)
        * np.linalg.norm(mm, axis=2))
    np.testing.assert_allclose(np.asarray(outs[0]), expect, atol=1e-5)


def test_multiplex_and_conv_shift(rng):
    paddle.topology.reset_name_scope()
    idx = layer.data(name="idx", type=paddle.data_type.integer_value(2))
    a = layer.data(name="a", type=paddle.data_type.dense_vector(4))
    b = layer.data(name="b", type=paddle.data_type.dense_vector(4))
    mx = layer.multiplex(idx, [a, b])
    ab = rng.randn(3, 4).astype(np.float32)
    bb = rng.randn(3, 4).astype(np.float32)
    ib = np.array([0, 1, 0], np.int32)
    outs, _, _ = forward(mx, {"idx": ib, "a": ab, "b": bb})
    expect = np.where(ib[:, None] == 0, ab, bb)
    np.testing.assert_allclose(np.asarray(outs[0]), expect, atol=1e-6)

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(5))
    k = layer.data(name="k", type=paddle.data_type.dense_vector(3))
    cs = layer.conv_shift(x, k)
    xb = rng.randn(2, 5).astype(np.float32)
    kb = rng.randn(2, 3).astype(np.float32)
    outs, _, _ = forward(cs, {"x": xb, "k": kb})
    expect = np.zeros((2, 5), np.float32)
    for bi in range(2):
        for m in range(5):
            for j in range(3):
                expect[bi, m] += xb[bi, (m + j - 1) % 5] * kb[bi, j]
    np.testing.assert_allclose(np.asarray(outs[0]), expect, atol=1e-5)


def test_linear_comb_featmap_expand_trans(rng):
    paddle.topology.reset_name_scope()
    w = layer.data(name="w", type=paddle.data_type.dense_vector(3))
    v = layer.data(name="v", type=paddle.data_type.dense_vector(6))
    lc = layer.linear_comb(w, v, size=2)
    fe = layer.featmap_expand(w, num_filters=2)
    wb = rng.randn(2, 3).astype(np.float32)
    vb = rng.randn(2, 6).astype(np.float32)
    outs, _, _ = forward([lc, fe], {"w": wb, "v": vb})
    expect = np.einsum("bm,bmd->bd", wb, vb.reshape(2, 3, 2))
    np.testing.assert_allclose(np.asarray(outs[0]), expect, atol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]),
                               np.tile(wb, (1, 2)), atol=1e-6)

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    tr = layer.trans(x)
    xb = rng.randn(3, 4).astype(np.float32)
    outs, _, _ = forward(tr, {"x": xb})
    np.testing.assert_allclose(np.asarray(outs[0]), xb.T, atol=1e-6)


def test_row_conv_and_subseq(rng):
    paddle.topology.reset_name_scope()
    seq = layer.data(name="s",
                     type=paddle.data_type.dense_vector_sequence(3))
    rc = layer.row_conv(seq, context_len=2)
    sb = make_seq(rng, [3, 2], 3)
    outs, params, _ = forward(rc, {"s": sb})
    w = np.asarray(params[rc.name + ".w"])
    x = np.asarray(sb.data)
    # sequence 0 rows 0..2: y[i] = x[i]*w[0] + x[i+1]*w[1] (within seq)
    y0 = x[0] * w[0] + x[1] * w[1]
    y2 = x[2] * w[0]          # last row of seq 0: no lookahead
    got = np.asarray(outs[0].data)
    np.testing.assert_allclose(got[0], y0, atol=1e-5)
    np.testing.assert_allclose(got[2], y2, atol=1e-5)

    paddle.topology.reset_name_scope()
    seq2 = layer.data(name="s2",
                      type=paddle.data_type.dense_vector_sequence(3))
    offs = layer.data(name="offs", type=paddle.data_type.integer_value(10))
    sizes = layer.data(name="sizes", type=paddle.data_type.integer_value(10))
    ss = layer.subseq(seq2, offs, sizes)
    sb2 = make_seq(rng, [4, 3], 3)
    outs, _, _ = forward(ss, {"s2": sb2,
                              "offs": np.array([1, 0], np.int32),
                              "sizes": np.array([2, 2], np.int32)})
    out_sb = outs[0]
    lens = np.asarray(out_sb.lengths)
    np.testing.assert_array_equal(lens, [2, 2])


def test_get_output_and_print(rng, capsys):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    go = layer.get_output(x)
    pr = layer.print_layer(go)
    xb = rng.randn(2, 4).astype(np.float32)
    outs, _, _ = forward(pr, {"x": xb})
    np.testing.assert_allclose(np.asarray(outs[0]), xb, atol=1e-6)


def test_switch_order(rng):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(2 * 3 * 4),
                   height=2, width=3)
    so = layer.switch_order(x, reshape_to=("c", "h", "w"))
    xb = rng.randn(1, 24).astype(np.float32)
    outs, _, _ = forward(so, {"x": xb})
    expect = xb.reshape(1, 2, 3, 4).transpose(0, 3, 1, 2).reshape(1, -1)
    np.testing.assert_allclose(np.asarray(outs[0]), expect, atol=1e-6)


def test_img_conv3d_pool3d(rng):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x",
                   type=paddle.data_type.dense_vector(4 * 4 * 4 * 2))
    c3 = layer.img_conv3d(x, filter_size=3, num_filters=3, num_channels=2,
                          padding=1, depth=4, height=4, width=4,
                          act="relu")
    p3 = layer.img_pool3d(c3, pool_size=2)
    xb = rng.randn(2, 128).astype(np.float32)
    outs, _, _ = forward(p3, {"x": xb})
    assert np.asarray(outs[0]).shape == (2, 2 * 2 * 2 * 3)
    assert c3.size == 4 * 4 * 4 * 3

    paddle.topology.reset_name_scope()
    xd = layer.data(name="xd", type=paddle.data_type.dense_vector(8 * 2))
    d3 = layer.img_conv3d(xd, filter_size=2, num_filters=1, num_channels=2,
                          stride=2, depth=2, height=2, width=2, trans=True)
    xdb = rng.randn(1, 16).astype(np.float32)
    outs, _, _ = forward(d3, {"xd": xdb})
    assert np.asarray(outs[0]).shape == (1, 4 * 4 * 4 * 1)


def test_mdlstm_forward_shape_and_grad(rng):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(3 * 3 * 2))
    md = layer.mdlstmemory(x, size=4, height=3, width=3)
    xb = rng.randn(2, 18).astype(np.float32)
    outs, _, _ = forward(md, {"x": xb})
    assert np.asarray(outs[0]).shape == (2, 3 * 3 * 4)
    numeric_grad_check(md, {"x": xb}, delta=5e-3, rtol=8e-2, atol=8e-3)


def test_detection_suite(rng):
    from paddle_tpu.ops import detection as pdet

    # iou sanity
    a = jnp.array([[0.0, 0.0, 0.5, 0.5]])
    b = jnp.array([[0.25, 0.25, 0.75, 0.75], [0.6, 0.6, 0.9, 0.9]])
    iou = np.asarray(pdet.iou_matrix(a, b))
    np.testing.assert_allclose(iou[0, 0], 0.0625 / 0.4375, atol=1e-5)
    assert iou[0, 1] == 0.0

    # encode/decode roundtrip
    priors = jnp.array([[0.1, 0.1, 0.4, 0.5], [0.3, 0.2, 0.9, 0.8]])
    var = jnp.full((2, 4), 0.1)
    gt = jnp.array([[0.15, 0.12, 0.45, 0.55], [0.28, 0.25, 0.85, 0.75]])
    enc = pdet.encode_boxes(gt, priors, var)
    dec = pdet.decode_boxes(enc, priors, var)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(gt), atol=1e-5)

    # full layer path
    paddle.topology.reset_name_scope()
    feat = layer.data(name="feat",
                      type=paddle.data_type.dense_vector(2 * 2 * 4),
                      height=2, width=2)
    pb = layer.priorbox(feat, image_size=64, min_size=16, max_size=32,
                        aspect_ratio=(2.0,))
    P = pb.num_priors
    loc = layer.data(name="loc", type=paddle.data_type.dense_vector(P * 4))
    conf = layer.data(name="conf",
                      type=paddle.data_type.dense_vector(P * 3))
    gt_l = layer.data(name="gt", type=paddle.data_type.dense_vector(2 * 5))
    loss = layer.multibox_loss(loc, conf, pb, gt_l, num_classes=3,
                               max_boxes=2)
    det = layer.detection_output(loc, conf, pb, num_classes=3,
                                 keep_top_k=5)
    B = 2
    feeds = {
        "feat": rng.randn(B, 16).astype(np.float32),
        "loc": np.zeros((B, P * 4), np.float32),
        "conf": rng.randn(B, P * 3).astype(np.float32) * 0.1,
        "gt": np.tile(np.array([[1, 0.1, 0.1, 0.45, 0.5,
                                 -1, 0, 0, 0, 0]], np.float32), (B, 1)),
    }
    outs, _, _ = forward([loss, det], feeds)
    lv = np.asarray(outs[0])
    assert lv.shape == (B, 1) and np.all(np.isfinite(lv)) and np.all(lv > 0)
    dv = np.asarray(outs[1]).reshape(B, 5, 6)
    # at least one detection slot filled, scores in [0,1]
    filled = dv[dv[:, :, 0] >= 0]
    assert filled.size > 0
    assert np.all(filled[:, 1] >= 0) and np.all(filled[:, 1] <= 1)


def test_nms_suppresses_overlaps():
    from paddle_tpu.ops import detection as pdet
    boxes = jnp.array([[0.0, 0.0, 0.4, 0.4],
                       [0.02, 0.02, 0.42, 0.42],   # overlaps box 0
                       [0.6, 0.6, 0.9, 0.9]])
    scores = jnp.array([0.9, 0.8, 0.7])
    keep, ok = pdet.nms(boxes, scores, iou_threshold=0.5, max_keep=3)
    kept = set(np.asarray(keep)[np.asarray(ok)].tolist())
    assert kept == {0, 2}


def test_error_clipping_threshold_clips_backward():
    """ExtraAttr.error_clipping_threshold: identity forward, clipped
    backward (reference Layer.cpp backwardActivation error clipping)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import layer

    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(4))
    h = layer.fc(input=x, size=4, act=None, bias_attr=False,
                 param_attr=paddle.attr.ParamAttr(initializer=lambda key, shape, dtype: jnp.eye(4)),
                 layer_attr=paddle.attr.ExtraAttr(error_clipping_threshold=0.5))
    cost = layer.sum_cost(input=h)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)

    def loss(p, scale):
        outs, _ = topo.forward(p, {}, {"x": jnp.ones((2, 4)) * scale},
                               train=True)
        return jnp.sum(outs[0]) * scale

    g = jax.grad(lambda p: loss(p, 10.0))(params.as_dict())
    w_grad = g[[k for k in g if k.endswith(".w0")][0]]
    # upstream grad is 10 per element; clipped to 0.5 before the matmul
    # backward -> |dW| <= 0.5 * sum(|x|) = 0.5 * 2 * 10
    assert float(jnp.max(jnp.abs(w_grad))) <= 0.5 * 2 * 10 + 1e-5
    # forward value unchanged by the clip
    outs, _ = topo.forward(params.as_dict(), {}, {"x": jnp.ones((2, 4))},
                           train=False)
    assert float(jnp.sum(outs[0])) == 8.0
