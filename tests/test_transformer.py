"""Transformer LM (models/transformer.py): trains end-to-end on packed
variable-length sequences, and the per-token loss starts near log(vocab).
"""

import math

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.models import transformer


def _feeds(sgd, rng, vocab, lens):
    samples = []
    for n in lens:
        toks = rng.randint(0, vocab, size=n)
        samples.append((toks.tolist(), list(range(n)),
                        np.roll(toks, -1).tolist()))
    feeder = sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2})
    return feeder.feed(samples)


def test_transformer_lm_trains(rng):
    vocab, d, layers, heads = 101, 32, 2, 4
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
        max_len=64)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))
    step = sgd._build_step()
    feeds = _feeds(sgd, rng, vocab, lens=(11, 7, 16))
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    import jax

    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(30):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    # cost semantics are per-sequence token-sum averaged over sequences
    # (trainer._reduce_cost, the reference's summed-cost/batch-size): the
    # untrained value is ~ mean_len * log(vocab); memorizing 3 tiny
    # sequences must cut it way down
    mean_len = (11 + 7 + 16) / 3
    assert abs(losses[0] - mean_len * math.log(vocab)) < 0.25 * mean_len * math.log(vocab)
    assert losses[-1] < losses[0] * 0.5


def test_transformer_causality(rng):
    """Changing a future token must not change earlier positions' logits."""
    vocab, d = 53, 16
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=1, n_heads=2, max_len=32)
    topo = paddle.topology.Topology([logits])
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Sgd())
    p = sgd.parameters.as_dict()
    needed = {k: p[k] for k in topo.param_specs()}

    toks = rng.randint(0, vocab, size=12)
    variant = toks.copy()
    variant[-1] = (variant[-1] + 1) % vocab

    def run(t):
        feeder = sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2})
        feeds = feeder.feed([(t.tolist(), list(range(len(t))),
                              np.roll(t, -1).tolist())])
        outs, _ = topo.forward(needed, {}, feeds, train=False)
        return np.asarray(outs[0].data)

    a, b = run(toks), run(variant)
    # rows are the PACKED buffer (capacity-padded): the live sequence is
    # rows [0, 12); only the changed position (row 11) may move
    n = len(toks)
    np.testing.assert_allclose(a[:n - 1], b[:n - 1], atol=2e-5)
    assert np.abs(a[n - 1] - b[n - 1]).max() > 1e-4


def test_transformer_trains_on_mesh8_zero(rng):
    """Flagship-on-mesh smoke: the transformer LM trains data-parallel on
    the 8-device mesh with ZeRO weight/slot sharding, and the big weights
    are actually sharded (no device holds a full replica)."""
    import jax

    from paddle_tpu.parallel import make_mesh

    vocab, d = 128, 64
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=2, n_heads=4, max_len=32)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    mesh = make_mesh((8,), ("data",))
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2),
                      mesh=mesh, zero_axis="data")
    step = sgd._build_step()
    samples = []
    for _ in range(8):
        t = rng.randint(0, vocab, size=16)
        samples.append((t.tolist(), list(range(16)), np.roll(t, -1).tolist()))
    feeds = sgd._shard_feeds(
        sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2}).feed(samples))
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(10):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # ZeRO: the LM head stayed sharded through the steps
    w = p["lm_head.w0"]
    assert w.addressable_shards[0].data.size < w.size


def test_transformer_bf16_dense_activations(rng):
    """FLAGS.bf16_dense_activations: the residual stream rides bf16 but
    the LM still learns, and the loss tracks the f32 path closely early
    in training."""
    from paddle_tpu.platform.flags import FLAGS

    vocab = 101

    def losses_with(flag):
        old_bf16, old_flag = FLAGS.use_bf16, FLAGS.bf16_dense_activations
        FLAGS.use_bf16, FLAGS.bf16_dense_activations = True, flag
        try:
            paddle.topology.reset_name_scope()
            r = np.random.RandomState(7)
            tokens, pos, target, logits, cost = transformer.build(
                vocab_size=vocab, d_model=32, n_layers=2, n_heads=4,
                max_len=64)
            topo = paddle.topology.Topology([cost])
            params = paddle.Parameters.from_topology(topo, seed=0)
            sgd = trainer.SGD(cost=cost, parameters=params,
                              update_equation=optimizer.Adam(
                                  learning_rate=1e-2))
            step = sgd._build_step()
            feeds = _feeds(sgd, r, vocab, lens=(11, 7, 16))
            import jax

            p, o, m = (sgd.parameters.as_dict(), sgd.opt_state,
                       sgd.model_state)
            key = jax.random.PRNGKey(0)
            out = []
            for _ in range(20):
                loss, p, o, m, _ = step(p, o, m, key, feeds)
                out.append(float(loss))
            return out
        finally:
            FLAGS.use_bf16, FLAGS.bf16_dense_activations = old_bf16, old_flag

    f32 = losses_with(False)
    bf16 = losses_with(True)
    assert np.isfinite(bf16).all()
    assert bf16[-1] < bf16[0] * 0.6           # still learns
    # same start (loss reduces in f32 either way), close early trajectory
    assert abs(bf16[0] - f32[0]) / f32[0] < 0.05


def test_transformer_generate_matches_iterative_forward(rng):
    """KV-cache decode == greedy argmax over repeated full forwards."""
    import jax

    vocab, d, layers, heads = 67, 32, 2, 4
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
        max_len=64)
    topo_logits = paddle.topology.Topology([logits])
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=3)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Sgd())
    pdict = sgd.parameters.as_dict()
    needed = {k: pdict[k] for k in topo_logits.param_specs()}

    prompt = rng.randint(0, vocab, size=5).tolist()
    max_new = 6

    # oracle: full forward on the sequence so far, argmax of last position
    seq = list(prompt)
    for _ in range(max_new):
        feeder = sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2})
        feeds = feeder.feed([(seq, list(range(len(seq))),
                              [0] * len(seq))])
        outs, _ = topo_logits.forward(needed, {}, feeds, train=False)
        lg = np.asarray(outs[0].data)[len(seq) - 1]
        seq.append(int(np.argmax(lg)))
    want = seq[len(prompt):]

    got = transformer.generate(pdict, prompt, max_new, n_layers=layers,
                               n_heads=heads, max_len=64)
    assert got.tolist() == want, (got.tolist(), want)


def test_transformer_generate_eos_padding():
    """After eos is produced, subsequent positions repeat eos."""
    vocab, d, layers, heads = 13, 16, 1, 2
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
        max_len=32)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    pdict = {k: v for k, v in params.items()}
    first = int(transformer.generate(pdict, [1, 2], 1, n_layers=layers,
                                     n_heads=heads, max_len=32)[0])
    out = transformer.generate(pdict, [1, 2], 8, n_layers=layers,
                               n_heads=heads, max_len=32, eos_id=first)
    # the first generated token IS the eos we chose; everything after
    # must repeat it
    assert all(t == first for t in out.tolist())


def test_beam1_matches_greedy(rng):
    """beam_size=1 beam search must equal greedy KV-cache decode."""
    vocab, d, layers, heads = 41, 24, 2, 3
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
        max_len=32)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=11)
    pdict = {k: v for k, v in params.items()}
    prompt = rng.randint(0, vocab, size=4).tolist()
    greedy = transformer.generate(pdict, prompt, 7, n_layers=layers,
                                  n_heads=heads, max_len=32)
    beam, score = transformer.beam_generate(pdict, prompt, 7,
                                            n_layers=layers, n_heads=heads,
                                            beam_size=1, max_len=32)
    assert beam.tolist() == greedy.tolist()
    assert np.isfinite(score)


def test_beam_finds_higher_likelihood_than_greedy(rng):
    """A wider beam's sum-log-prob must be >= the greedy sequence's."""
    import jax
    import jax.numpy as jnp

    vocab, d, layers, heads = 29, 24, 1, 3
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
        max_len=32)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=5)
    pdict = {k: v for k, v in params.items()}
    topo_logits = paddle.topology.Topology([logits])
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Sgd())
    needed = {k: pdict[k] for k in topo_logits.param_specs()}

    def seq_logprob(seq):
        """Sum log P(seq[i] | seq[:i]) for i >= len(prompt)."""
        feeder = sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2})
        feeds = feeder.feed([(seq, list(range(len(seq))), [0] * len(seq))])
        outs, _ = topo_logits.forward(needed, {}, feeds, train=False)
        lg = np.asarray(outs[0].data)[: len(seq)]
        lp = np.asarray(jax.nn.log_softmax(jnp.asarray(lg), axis=-1))
        return sum(lp[i - 1, seq[i]] for i in range(4, len(seq)))

    prompt = rng.randint(0, vocab, size=4).tolist()
    greedy = transformer.generate(pdict, prompt, 6, n_layers=layers,
                                  n_heads=heads, max_len=32)
    beam, score = transformer.beam_generate(pdict, prompt, 6,
                                            n_layers=layers, n_heads=heads,
                                            beam_size=8, max_len=32)
    lp_greedy = seq_logprob(prompt + greedy.tolist())
    lp_beam = seq_logprob(prompt + beam.tolist())
    # NOTE: beam >= greedy is not guaranteed in general (the greedy path
    # can be pruned); it holds for this fixed seed/config and mainly
    # guards against gross scoring bugs. The load-bearing assertion is
    # the next one: the reported score must equal the true sequence
    # log-prob computed by an independent full forward.
    assert lp_beam >= lp_greedy - 1e-4
    # tolerance reflects the flash-attention precision model: softmax probs
    # ride the MXU in bf16 (ops/attention.py), and the decode path
    # blocks/rounds differently from the one-shot scoring forward, so the
    # two log-probs agree to ~1e-3 RELATIVE (observed 7.8e-3 on a -7.65
    # score) — hence rtol, keeping the absolute slack at the original 2e-3.
    np.testing.assert_allclose(score, lp_beam, rtol=1.5e-3, atol=2e-3)


def test_seq2seq_transformer_learns_copy_task(rng):
    """Encoder-decoder transformer: cross-attention lets the decoder copy
    the source — loss collapses on a copy task, and a corrupted source
    hurts the prediction (the decoder really reads the memory)."""
    import jax

    vocab = 41
    paddle.topology.reset_name_scope()
    src, src_pos, trg, trg_pos, label, logits, cost = \
        transformer.build_seq2seq(src_vocab=vocab, trg_vocab=vocab,
                                  d_model=32, n_layers=1, n_heads=4,
                                  max_len=32)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=5e-3))
    step = sgd._build_step()
    feeding = {"src": 0, "src_pos": 1, "trg": 2, "trg_pos": 3, "label": 4}

    def sample(r):
        n = int(r.randint(5, 10))
        s = r.randint(2, vocab, size=n)
        # trg = <bos>=1 + gold[:-1]; label = gold (copy of src)
        return (s.tolist(), list(range(n)),
                [1] + s[:-1].tolist(), list(range(n)), s.tolist())

    samples = [sample(rng) for _ in range(8)]
    feeds = sgd._make_feeder(feeding).feed(samples)
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(60):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])

    # memory ablation: corrupt the SOURCE of one sample; its predictions
    # must change (cross-attention is live, not bypassed)
    topo_logits = paddle.topology.Topology([logits])
    needed = {k: p[k] for k in topo_logits.param_specs()}
    good = samples[0]
    bad = ((np.array(good[0]) % (vocab - 2) + 2).tolist(),) + good[1:]

    def run(smp):
        feeds1 = sgd._make_feeder(feeding).feed([smp])
        outs, _ = topo_logits.forward(needed, {}, feeds1, train=False)
        return np.asarray(outs[0].data)[: len(smp[0])]

    a, b = run(good), run(bad)
    assert np.abs(a - b).max() > 1e-3


def test_fused_head_training_parity(rng):
    """fused_head=True (blockwise lm_head_cost, logits never materialized)
    must follow the SAME training trajectory as the unfused
    fc -> classification_cost head: identical init (shared param names),
    per-step losses equal to f32 tolerance."""
    import jax

    vocab, d = 97, 16

    def run(fused):
        paddle.topology.reset_name_scope()
        tokens, pos, target, logits, cost = transformer.build(
            vocab_size=vocab, d_model=d, n_layers=1, n_heads=2,
            max_len=32, fused_head=fused)
        topo = paddle.topology.Topology([cost])
        params = paddle.Parameters.from_topology(topo, seed=3)
        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Sgd(learning_rate=0.1))
        step = sgd._build_step()
        feeds = _feeds(sgd, np.random.RandomState(5), vocab, lens=(9, 6))
        p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
        key = jax.random.PRNGKey(0)
        losses = []
        for _ in range(5):
            loss, p, o, m, _ = step(p, o, m, key, feeds)
            losses.append(float(loss))
        return losses, {k: np.asarray(v) for k, v in p.items()}

    from paddle_tpu.platform.flags import FLAGS
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        l_plain, p_plain = run(False)
        l_fused, p_fused = run(True)
    finally:
        FLAGS.use_bf16 = old
    np.testing.assert_allclose(l_fused, l_plain, rtol=1e-4)
    np.testing.assert_allclose(p_fused["lm_head.w0"], p_plain["lm_head.w0"],
                               rtol=1e-3, atol=1e-6)


def test_beam_generate_control_hooks(rng):
    """The transformer beam decode honors the same user hooks as the RNN
    beam path: identity hooks change nothing; a token ban is respected;
    stop_condition EOS-freezes all beams from that step on."""
    import jax.numpy as jnp

    vocab, d = 37, 16
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=1, n_heads=2, max_len=32)
    params = {k: np.asarray(v) for k, v in paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=9).as_dict().items()}
    prompt = [3, 5, 7]
    kw = dict(n_layers=1, n_heads=2, max_len=32, beam_size=3, eos_id=0)

    base_toks, base_score = transformer.beam_generate(
        params, prompt, 6, **kw)
    ident_toks, ident_score = transformer.beam_generate(
        params, prompt, 6, candidate_adjust=lambda lp, beam: lp,
        path_filter=lambda beam: jnp.ones_like(beam.finished),
        **kw)
    np.testing.assert_array_equal(ident_toks, base_toks)
    assert abs(ident_score - base_score) < 1e-5

    banned = int(base_toks[0])
    ban_toks, _ = transformer.beam_generate(
        params, prompt, 6,
        candidate_adjust=lambda lp, beam: lp.at[:, banned].set(-1e30),
        **kw)
    assert banned not in ban_toks

    stop_toks, _ = transformer.beam_generate(
        params, prompt, 6,
        stop_condition=lambda beam: beam.t >= 1, **kw)
    # steps 0 and 1 produced real tokens; everything after is eos padding
    assert (stop_toks[2:] == 0).all()
    np.testing.assert_array_equal(stop_toks[:2], base_toks[:2])


def test_fused_head_trains_on_mesh8_zero(rng):
    """The blockwise lm_head_cost (custom_vjp + scan + dynamic slices)
    must partition under pjit: train on the 8-device mesh with ZeRO
    sharding, finite decreasing loss, head weight still sharded."""
    import jax

    from paddle_tpu.parallel import make_mesh

    vocab, d = 128, 64
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=2, n_heads=4, max_len=32,
        fused_head=True)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    mesh = make_mesh((8,), ("data",))
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2),
                      mesh=mesh, zero_axis="data")
    step = sgd._build_step()
    samples = []
    for _ in range(8):
        t = rng.randint(0, vocab, size=16)
        samples.append((t.tolist(), list(range(16)), np.roll(t, -1).tolist()))
    feeds = sgd._shard_feeds(
        sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2}).feed(samples))
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(6):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    w = p["lm_head.w0"]
    assert w.addressable_shards[0].data.size < w.size


def test_beam_generate_batch_matches_individual(rng):
    """Batched beam decode (one compiled vmap) equals per-prompt runs."""
    vocab, d = 43, 16
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=1, n_heads=2, max_len=32)
    params = {k: np.asarray(v) for k, v in paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=11).as_dict().items()}
    prompts = [[3, 5, 7], [9, 2, 4], [1, 1, 8]]
    kw = dict(n_layers=1, n_heads=2, max_len=32, beam_size=3, eos_id=0)
    bt, bs = transformer.beam_generate_batch(params, prompts, 5, **kw)
    assert bt.shape == (3, 5)
    for i, p in enumerate(prompts):
        ti, si = transformer.beam_generate(params, p, 5, **kw)
        np.testing.assert_array_equal(bt[i], ti)
        assert abs(float(bs[i]) - si) < 1e-5
    import pytest as _pytest
    with _pytest.raises(ValueError):
        transformer.beam_generate_batch(params, [[1, 2], [1, 2, 3]], 4,
                                        **kw)


def test_remat_training_parity(rng):
    """remat=True (per-block jax.checkpoint via topology.remat_scope) must
    follow the SAME training trajectory as remat=False — checkpoint changes
    memory scheduling, not math. Dropout is on so the segment's rng plumbing
    is exercised (per-node streams must derive identically inside the
    rematted segment)."""
    import jax

    vocab, d = 89, 16

    def run(remat):
        paddle.topology.reset_name_scope()
        tokens, pos, target, logits, cost = transformer.build(
            vocab_size=vocab, d_model=d, n_layers=2, n_heads=2,
            max_len=32, dropout=0.15, remat=remat)
        topo = paddle.topology.Topology([cost])
        params = paddle.Parameters.from_topology(topo, seed=7)
        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Adam(learning_rate=3e-3))
        step = sgd._build_step()
        feeds = _feeds(sgd, np.random.RandomState(2), vocab, lens=(10, 6, 13))
        p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
        key = jax.random.PRNGKey(4)
        losses = []
        for _ in range(6):
            loss, p, o, m, _ = step(p, o, m, key, feeds)
            losses.append(float(loss))
        return losses

    # Force full-f32 compute (matmuls AND the flash kernels' softmax-prob
    # path): in bf16 the prob rounding sits at quantization boundaries that
    # the ~1e-7 backward-rescheduling noise can flip, which drifts the Adam
    # trajectories apart chaotically and forced a 1000x-loosened rtol. In
    # f32 the only difference is XLA scheduling of the recomputed backward,
    # so the original tight tolerance holds and the test guards remat math
    # again. (bf16 remat numerics are covered by test_remat_moe_trains.)
    from paddle_tpu.platform.flags import FLAGS

    old_bf16 = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        l_plain = run(False)
        l_remat = run(True)
    finally:
        FLAGS.use_bf16 = old_bf16
    np.testing.assert_allclose(l_remat, l_plain, rtol=1e-6)


def test_remat_moe_trains(rng):
    """remat composes with the MoE block (aux-loss node crosses the remat
    segment boundary as an external output)."""
    import jax

    vocab, d = 61, 16
    paddle.topology.reset_name_scope()
    tokens, pos, target, logits, cost = transformer.build(
        vocab_size=vocab, d_model=d, n_layers=2, n_heads=2, max_len=32,
        moe_experts=2, remat=True)
    topo = paddle.topology.Topology(cost if isinstance(cost, list) else [cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))
    step = sgd._build_step()
    feeds = _feeds(sgd, np.random.RandomState(0), vocab, lens=(8, 12))
    p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(12):
        loss, p, o, m, _ = step(p, o, m, key, feeds)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_remat_scope_batch_norm_state(rng):
    """A stateful layer (batch_norm moving stats) inside a remat_scope must
    still publish its state updates identically to the un-rematted graph."""
    import jax

    from paddle_tpu import topology as topo_mod

    def build(remat):
        paddle.topology.reset_name_scope()
        x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
        import contextlib
        scope = (topo_mod.remat_scope("seg") if remat
                 else contextlib.nullcontext())
        with scope:
            h = layer.fc(input=x, size=8, act="relu", name="seg_fc")
            h = layer.batch_norm(input=h, name="seg_bn")
        y = layer.fc(input=h, size=4, name="head")
        lbl = layer.data(name="lbl",
                         type=paddle.data_type.integer_value(4))
        cost = layer.classification_cost(input=y, label=lbl)
        return cost

    xs = rng.randn(6, 8).astype(np.float32)
    ys = rng.randint(0, 4, size=6)

    def run(remat):
        cost = build(remat)
        topo = paddle.topology.Topology([cost])
        params = paddle.Parameters.from_topology(topo, seed=1)
        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Sgd(learning_rate=0.1))
        step = sgd._build_step()
        feeder = sgd._make_feeder({"x": 0, "lbl": 1})
        feeds = feeder.feed([(xs[i].tolist(), int(ys[i]))
                             for i in range(6)])
        p, o, m = sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state
        key = jax.random.PRNGKey(0)
        for _ in range(3):
            loss, p, o, m, _ = step(p, o, m, key, feeds)
        return float(loss), {k: {s: np.asarray(v) for s, v in d.items()}
                             for k, d in m.items()}

    loss_plain, state_plain = run(False)
    loss_remat, state_remat = run(True)
    assert abs(loss_plain - loss_remat) < 1e-6
    assert "seg_bn" in state_remat and state_remat["seg_bn"]
    for slot, v in state_plain["seg_bn"].items():
        np.testing.assert_allclose(state_remat["seg_bn"][slot], v,
                                   rtol=1e-6, atol=1e-7)
