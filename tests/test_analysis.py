"""paddle_tpu.analysis tests: the program verifier catches every
seeded diagnostic class on hand-built bad programs and stays silent on
real training programs and every ``paddle_tpu.models`` network; the
retrace auditor counts exactly one compile for a steady-state serving
decode loop (one per bucket for prefill) and flags an injected
shape-churn loop; the linter rules fire on synthetic snippets, honor
the ``# lint: allow(<rule>)`` escape hatch, and find nothing in the
repo itself.
"""

import jax
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.analysis.diagnostics import Severity
from paddle_tpu.analysis.lint import lint_source, run_lint
from paddle_tpu.analysis.program_check import (verify_program,
                                               verify_topology)
from paddle_tpu.analysis.retrace import (RetraceError, audit_jit, auditor)
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.platform.flags import FLAGS

pytestmark = pytest.mark.analysis


def codes(diags, severity=None):
    return sorted({d.code for d in diags
                   if severity is None or d.severity is severity})


def errors(diags):
    return [d for d in diags if d.severity is Severity.ERROR]


# ---------------------------------------------------------------------------
# program verifier: clean real programs
# ---------------------------------------------------------------------------


def _fit_a_line():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [13])
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1, bias_attr=True)
        loss = layers.mean(layers.square_error_cost(pred, y))
        optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
    return prog, [loss.name], ["x", "y"]


def _mlp_with_metrics():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        img = layers.data("img", [64])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(img, size=32, act="relu")
        logits = layers.fc(h, size=4)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(logits, label)
        optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)
    return prog, [loss.name, acc.name], ["img", "label"]


def _convnet():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        img = layers.data("img", [1, 12, 12])
        label = layers.data("label", [1], dtype="int64")
        c = layers.conv2d(img, num_filters=4, filter_size=3, act="relu")
        c = layers.batch_norm(c)
        p = layers.pool2d(c, pool_size=2, pool_type="max")
        logits = layers.fc(p, size=4)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return prog, [loss.name], ["img", "label"]


def _static_rnn():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [6, 4, 8], append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(shape=(4, 16), init_value=0.0)
            h = layers.fc([x_t, h_prev], size=16, act="tanh",
                          bias_attr=False)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
        loss = layers.mean(out)
        optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return prog, [loss.name], ["x"]


@pytest.mark.parametrize("build", [_fit_a_line, _mlp_with_metrics,
                                   _convnet, _static_rnn])
def test_verifier_silent_on_real_training_programs(build):
    prog, fetches, feeds = build()
    diags = verify_program(prog, fetch_names=fetches, feed_names=feeds)
    assert diags == [], [str(d) for d in diags]


def test_verified_program_still_trains():
    """strict mode on a GOOD program changes nothing — it compiles and
    converges exactly as before."""
    prog, fetches, feeds = _fit_a_line()
    old = FLAGS.fluid_verify
    FLAGS.fluid_verify = "strict"
    try:
        rng = np.random.RandomState(0)
        true_w = rng.randn(13, 1).astype(np.float32)
        exe = fluid.Executor()
        scope = fluid.Scope()
        losses = []
        for _ in range(40):
            xb = rng.randn(16, 13).astype(np.float32)
            (l,) = exe.run(prog, feed={"x": xb, "y": xb @ true_w},
                           fetch_list=fetches, scope=scope)
            losses.append(float(l))
        assert losses[-1] < 0.1 * losses[0]
    finally:
        FLAGS.fluid_verify = old


# ---------------------------------------------------------------------------
# program verifier: each seeded-bad-program class
# ---------------------------------------------------------------------------


def test_def_before_use():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var("a", shape=(4,))
    b.create_var("b", shape=(4,))
    b.create_var("c", shape=(4,))
    # reads `b` before the op that defines it
    b.append_op("relu", inputs={"X": "b"}, outputs={"Out": "c"})
    b.append_op("tanh", inputs={"X": "a"}, outputs={"Out": "b"})
    diags = verify_program(prog, feed_names=["a"])
    assert "def-before-use" in codes(errors(diags))


def test_undefined_var():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var("out", shape=(4,))
    b.append_op("relu", inputs={"X": "never_declared"},
                outputs={"Out": "out"})
    diags = verify_program(prog)
    assert "undefined-var" in codes(errors(diags))


def test_dangling_fetch_and_unknown_feed():
    prog, _, _ = _fit_a_line()
    diags = verify_program(prog, fetch_names=["no_such_var"],
                           feed_names=["x", "y", "typo"])
    cs = codes(errors(diags))
    assert "dangling-fetch" in cs and "unknown-feed" in cs


def test_dead_var_warning():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [8])
        used = layers.relu(x)
        dead = layers.tanh(x)          # never fetched, never read
        out = layers.mean(used)
    diags = verify_program(prog, fetch_names=[out.name], feed_names=["x"])
    dead_diags = [d for d in diags if d.code == "dead-var"]
    assert dead_diags and dead_diags[0].severity is Severity.WARNING
    assert any(dead.name in d.vars for d in dead_diags)
    assert not errors(diags)


def test_duplicate_writer():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var("x", shape=(4,))
    b.create_var("o", shape=(4,))
    b.append_op("relu", inputs={"X": "x"}, outputs={"Out": "o"})
    b.append_op("tanh", inputs={"X": "x"}, outputs={"Out": "o"})
    diags = verify_program(prog, feed_names=["x"])
    assert "duplicate-writer" in codes(errors(diags))


def test_gradient_fan_in_is_not_duplicate_writer():
    """@GRAD accumulation and stateful batch_norm outputs are the
    sanctioned multi-writer aliases — a program with parameter fan-out
    (two consumers of one fc output) must verify clean."""
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [8])
        h = layers.fc(x, size=8, act="relu")
        a = layers.fc(h, size=4)
        bvar = layers.fc(h, size=4)          # h fans out -> h@GRAD summed
        loss = layers.mean(a + bvar)
        optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    diags = verify_program(prog, fetch_names=[loss.name], feed_names=["x"])
    assert diags == [], [str(d) for d in diags]


def test_shape_mismatch_matmul_and_elementwise():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var("a", shape=(-1, 4))
    b.create_parameter("w", shape=(7, 3))
    b.create_var("o", shape=(-1, 3))
    b.append_op("mul", inputs={"X": "a", "Y": "w"}, outputs={"Out": "o"})
    diags = verify_program(prog, feed_names=["a"])
    assert "shape-mismatch" in codes(errors(diags))

    prog2 = fluid.Program()
    b2 = prog2.global_block()
    b2.create_var("p", shape=(8, 4))
    b2.create_var("q", shape=(8, 5))
    b2.create_var("r", shape=(8, 4))
    b2.append_op("elementwise_add", inputs={"X": "p", "Y": "q"},
                 outputs={"Out": "r"})
    diags2 = verify_program(prog2, feed_names=["p", "q"])
    assert "shape-mismatch" in codes(errors(diags2))


def test_shape_mismatch_conv_channels_and_reshape():
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var("img", shape=(-1, 3, 8, 8))
    b.create_parameter("w", shape=(4, 5, 3, 3))     # expects 5 channels
    b.create_var("o", shape=())
    b.append_op("conv2d", inputs={"Input": "img", "Filter": "w"},
                outputs={"Output": "o"}, attrs={"strides": 1,
                                                "paddings": 0})
    diags = verify_program(prog, feed_names=["img"])
    assert "shape-mismatch" in codes(errors(diags))

    prog2 = fluid.Program()
    b2 = prog2.global_block()
    b2.create_var("x", shape=(6, 4))
    b2.create_var("y", shape=())
    b2.append_op("reshape", inputs={"X": "x"}, outputs={"Out": "y"},
                 attrs={"shape": [5, 5]})           # 24 -> 25 elements
    diags2 = verify_program(prog2, feed_names=["x"])
    assert "shape-mismatch" in codes(errors(diags2))


def test_unknown_batch_broadcast_stays_unknown():
    """Broadcasting an unknown (batch) dim against a literal 1 must NOT
    infer 1: [None,8] + [1,8] -> [None,8], so a later reshape that is
    valid at runtime (batch=4 here) raises no false conflict."""
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var("x", shape=(-1, 8))
    b.create_var("one", shape=(1, 8))
    b.create_var("s", shape=(-1, 8))
    b.create_var("r", shape=(4, 8))
    b.append_op("elementwise_add", inputs={"X": "x", "Y": "one"},
                outputs={"Out": "s"})
    b.append_op("reshape", inputs={"X": "s"}, outputs={"Out": "r"},
                attrs={"shape": [4, 8]})
    diags = verify_program(prog, feed_names=["x", "one"])
    assert errors(diags) == [], [str(d) for d in errors(diags)]


def test_dtype_mismatch():
    # float + int arithmetic without a cast
    prog = fluid.Program()
    b = prog.global_block()
    b.create_var("f", shape=(4,), dtype="float32")
    b.create_var("i", shape=(4,), dtype="int64")
    b.create_var("o", shape=(4,))
    b.append_op("elementwise_add", inputs={"X": "f", "Y": "i"},
                outputs={"Out": "o"})
    diags = verify_program(prog, feed_names=["f", "i"])
    assert "dtype-mismatch" in codes(errors(diags))

    # hard labels must be integers
    prog2 = fluid.Program()
    with fluid.program_guard(prog2):
        logits = layers.data("logits", [4])
        label = layers.data("label", [1], dtype="float32")
        layers.softmax_with_cross_entropy(logits, label)
    diags2 = verify_program(prog2, feed_names=["logits", "label"])
    assert "dtype-mismatch" in codes(errors(diags2))


def test_executor_strict_mode_raises_on_bad_program():
    from paddle_tpu.platform.enforce import EnforceError

    prog = fluid.Program()
    b = prog.global_block()
    b.create_var("a", shape=(-1, 4))
    b.create_parameter("w", shape=(7, 3))
    b.create_var("o", shape=(-1, 3))
    b.append_op("mul", inputs={"X": "a", "Y": "w"}, outputs={"Out": "o"})
    old = FLAGS.fluid_verify
    FLAGS.fluid_verify = "strict"
    try:
        exe = fluid.Executor()
        with pytest.raises(EnforceError, match="shape-mismatch"):
            exe.run(prog, feed={"a": np.zeros((2, 4), np.float32)},
                    fetch_list=["o"], scope=fluid.Scope())
    finally:
        FLAGS.fluid_verify = old


def test_executor_validates_feed_fetch_up_front():
    from paddle_tpu.platform.enforce import EnforceError

    prog, fetches, _ = _fit_a_line()
    exe = fluid.Executor()
    scope = fluid.Scope()
    feed = {"x": np.zeros((4, 13), np.float32),
            "y": np.zeros((4, 1), np.float32)}
    with pytest.raises(EnforceError, match="fetch 'nope'"):
        exe.run(prog, feed=feed, fetch_list=["nope"], scope=scope)
    with pytest.raises(EnforceError, match="feed 'typo'"):
        exe.run(prog, feed={**feed, "typo": np.zeros((4, 1), np.float32)},
                fetch_list=fetches, scope=scope)
    # both problems reported in ONE error, not the first encountered
    with pytest.raises(EnforceError,
                       match=r"(?s)(feed 'typo'.*fetch 'nope'"
                             r"|fetch 'nope'.*feed 'typo')"):
        exe.run(prog, feed={**feed, "typo": np.zeros((4, 1), np.float32)},
                fetch_list=["nope"], scope=scope)


def test_program_cli(tmp_path):
    from paddle_tpu.analysis.cli import main

    good = tmp_path / "good.py"
    good.write_text(
        "from paddle_tpu.fluid import layers\n"
        "x = layers.data('x', [4])\n"
        "loss = layers.mean(layers.relu(x))\n"
        "FETCH = loss.name\n")
    assert main(["program", str(good)]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import paddle_tpu.fluid as fluid\n"
        "prog = fluid.Program()\n"
        "b = prog.global_block()\n"
        "b.create_var('a', shape=(-1, 4))\n"
        "b.create_parameter('w', shape=(7, 3))\n"
        "b.create_var('o', shape=(-1, 3))\n"
        "b.append_op('mul', inputs={'X': 'a', 'Y': 'w'},"
        " outputs={'Out': 'o'})\n")
    assert main(["program", str(bad)]) == 1
    assert main(["program", str(bad), "--fetch", "o", "--feed", "a"]) == 1
    # --fetch binds to the default program only: a module-level pruned
    # Program that does not produce the fetch target must not fail
    multi = tmp_path / "multi.py"
    multi.write_text(
        "import paddle_tpu.fluid as fluid\n"
        "from paddle_tpu.fluid import layers\n"
        "from paddle_tpu.fluid.framework import default_main_program\n"
        "b = default_main_program().global_block()\n"
        "b.create_var('x', shape=(-1, 4))\n"
        "b.create_var('y', shape=(-1, 4))\n"
        "b.append_op('relu', inputs={'X': 'x'}, outputs={'Out': 'y'})\n"
        "other = fluid.Program()\n"
        "with fluid.program_guard(other):\n"
        "    z = layers.data('z', [4])\n"
        "    layers.tanh(z)\n")
    # 'y' exists only in the DEFAULT program; binding --fetch to every
    # program would fabricate a dangling-fetch on `other` and exit 1
    assert main(["program", str(multi), "--fetch", "y", "--feed", "x"]) == 0


def test_inline_verify_skips_per_run_dead_var(caplog):
    """A per-run fetch list is not the program's sink set: running with
    a partial fetch under the default warn mode must not log dead-var
    for ops another run fetches."""
    import logging

    prog, _, _ = _mlp_with_metrics()
    loss_name = None
    for op in prog.global_block().ops:
        if op.type == "mean":
            loss_name = op.output("Out")[0]
    exe = fluid.Executor()
    feed = {"img": np.zeros((4, 64), np.float32),
            "label": np.zeros((4, 1), np.int64)}
    with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
        exe.run(prog, feed=feed, fetch_list=[loss_name],  # not accuracy
                scope=fluid.Scope())
    assert "dead-var" not in caplog.text


# ---------------------------------------------------------------------------
# program verifier: the models zoo
# ---------------------------------------------------------------------------


def _model_builders():
    import paddle_tpu.models as zoo

    out = []
    for name in ("lenet", "smallnet", "alexnet", "googlenet", "resnet",
                 "text_lstm", "deepfm", "gan", "vae", "sequence_tagging",
                 "srl", "quick_start", "traffic_prediction", "transformer",
                 "seq2seq"):
        mod = getattr(zoo, name)
        for fn_name in ("build", "build_train", "build_seq2seq"):
            fn = getattr(mod, fn_name, None)
            if fn is not None:
                out.append(pytest.param(fn, id=f"{name}.{fn_name}"))
    return out


@pytest.mark.parametrize("build", _model_builders())
def test_models_verify_with_zero_errors(build):
    from paddle_tpu.topology import LayerOutput

    result = build()
    nodes = [r for r in (result if isinstance(result, tuple) else (result,))
             if isinstance(r, LayerOutput)]
    assert nodes, "build returned no LayerOutputs"
    diags = verify_topology(nodes)
    assert errors(diags) == [], [str(d) for d in errors(diags)]


def test_topology_verifier_catches_duplicate_names_and_bad_params():
    from paddle_tpu.attr import ParamAttr
    from paddle_tpu.topology import LayerOutput, ParamSpec

    a = LayerOutput("dup", "fc", [], fn=lambda ctx, p, ins: ins[0])
    bad = LayerOutput("dup", "fc", [a], fn=lambda ctx, p, ins: ins[0])
    diags = verify_topology(bad)
    assert errors(diags)

    p = LayerOutput("p", "fc", [], fn=lambda ctx, p, ins: 0,
                    params={"w": ParamSpec(shape=(-1, 4),
                                           attr=ParamAttr())})
    diags2 = verify_topology(p)
    assert "shape-mismatch" in codes(errors(diags2))


# ---------------------------------------------------------------------------
# retrace auditor
# ---------------------------------------------------------------------------


@pytest.fixture
def audit():
    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    auditor().reset()
    yield auditor()
    FLAGS.jit_audit = old
    auditor().reset()


def test_audit_counts_compiles_exactly(audit):
    import jax.numpy as jnp

    f = audit_jit(lambda x: x * 2, site="t.basic")
    for _ in range(5):
        f(jnp.ones((4,)))
    assert audit.compile_count("t.basic") == 1
    assert audit.call_count("t.basic") == 5
    f(jnp.ones((8,)))                       # new shape: a real compile
    assert audit.compile_count("t.basic") == 2
    assert audit.diagnostics == []          # warmup: nothing flagged
    audit.assert_budget("t.basic", 2)
    with pytest.raises(RetraceError, match="RETRACE"):
        audit.assert_budget("t.basic", 1)


def test_audit_flags_shape_churn_after_seal(audit):
    import jax.numpy as jnp

    f = audit_jit(lambda x: x + 1, site="t.churn")
    f(jnp.ones((4,)))
    audit.seal("t.churn")
    for n in (5, 6, 7):                     # injected shape churn
        f(jnp.ones((n,)))
    retraces = [d for d in audit.diagnostics if d.code == "RETRACE"]
    assert len(retraces) == 3
    assert all(d.severity is Severity.ERROR for d in retraces)
    with pytest.raises(RetraceError, match="RETRACE"):
        audit.assert_no_retraces()


def test_zero_identity_jit_is_cached_per_sharding(audit):
    """The ZeRO placement identities must not re-wrap (and so re-trace)
    per call — one compile per (sharding, site)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.zero import _identity_jit

    _identity_jit.cache_clear()
    mesh = make_mesh((8,), ("data",))
    sh = NamedSharding(mesh, P())
    try:
        for _ in range(4):
            _identity_jit(sh, "zero.reshard")(jnp.ones((8, 4)))
        assert audit.compile_count("zero.reshard") == 1
        assert not any(d.code == "RETRACE" for d in audit.diagnostics)
    finally:
        _identity_jit.cache_clear()


def test_audit_flags_fresh_wrapper_for_same_signature(audit):
    import jax.numpy as jnp

    # the classic hidden retrace: re-wrapping the "same" computation in
    # a new jit callable recompiles for an identical signature
    audit_jit(lambda x: x - 1, site="t.rewrap")(jnp.ones((4,)))
    audit_jit(lambda x: x - 1, site="t.rewrap")(jnp.ones((4,)))
    assert audit.compile_count("t.rewrap") == 2
    assert any(d.code == "RETRACE" for d in audit.diagnostics)


def test_seal_covers_sites_created_after_seal(audit):
    """Lazily-built jits (per-bucket prefill/chunk wrappers) may first
    wrap AFTER warmup is declared over — a global seal() must cover
    them, or post-seal compiles at a fresh bucket escape detection."""
    import jax.numpy as jnp

    audit_jit(lambda x: x, site="t.warm")(jnp.ones((4,)))
    audit.seal()                             # global: warmup over
    late = audit_jit(lambda x: x * 2, site="t.late")   # born sealed
    late(jnp.ones((4,)))
    assert any(d.code == "RETRACE" and "t.late" in d.vars
               for d in audit.diagnostics)
    with pytest.raises(RetraceError, match="RETRACE"):
        audit.assert_no_retraces()


def test_reset_keeps_live_wrappers_counted(audit):
    """reset() must zero counters IN PLACE: wrappers built before the
    reset keep reporting, instead of incrementing orphaned records
    while every later assert reads 0."""
    import jax.numpy as jnp

    f = audit_jit(lambda x: x + 1, site="t.live")
    f(jnp.ones((4,)))
    audit.reset()                            # discard warmup counts
    f(jnp.ones((8,)))                        # steady state: a compile!
    assert audit.compile_count("t.live") == 1
    assert audit.call_count("t.live") == 1
    with pytest.raises(RetraceError):
        audit.assert_budget("t.live", 0)


def test_audit_off_is_plain_jit():
    assert not FLAGS.jit_audit
    before = dict(auditor().snapshot())
    f = audit_jit(lambda x: x * 3, site="t.off")
    f(np.ones((4,), np.float32))
    assert "t.off" not in auditor().snapshot()
    assert auditor().snapshot() == before


@pytest.mark.serving
def test_serving_step_compiles_once_per_bucket_pair_steady_state(audit, rng):
    """The unified step (round 12) compiles exactly once per
    (decode_bucket, prefill_bucket) pair — the decode bucket is the
    fixed max_slots row count, so the ladder is one jit per prefill
    bucket plus the decode-only pb=0 — and a SEALED mixed
    prefill+decode steady state never compiles again.  The v1
    serving.decode / serving.prefill / serving.chunk_prefill sites are
    retired; serving.step is their one successor."""
    from paddle_tpu.serving import DecoderLM, ServingEngine

    old_bf16 = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        model = DecoderLM(vocab_size=50, num_layers=2, num_heads=2,
                          head_dim=8, max_positions=128)
        params = model.init_params(jax.random.PRNGKey(0))
        eng = ServingEngine(model, params, eos_id=1, page_size=4,
                            num_pages=40, max_pages_per_seq=10,
                            max_slots=4, buckets=(4, 8, 16),
                            prefill_chunk=8)
        # warm the pair ladder deterministically: a lone short prompt
        # (pb=4), then decode-only ticks (pb=0) to completion...
        eng.submit(rng.randint(2, 50, size=3).tolist(), max_tokens=8)
        eng.run(max_ticks=100)
        # ...then a MIXED steady state: a long prompt chunks (8-row
        # chunks -> pb=8) while short batchmates decode in the same
        # fused dispatch
        eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=12)
        eng.step()
        eng.submit(rng.randint(2, 50, size=20).tolist(), max_tokens=8)
        eng.run(max_ticks=300)
        pairs = audit.compile_count("serving.step")
        assert pairs == len(eng._step_fns)    # exactly one compile per pair
        assert pairs == 3                     # pb in {0, 4, 8}
        assert audit.compile_count("serving.decode") == 0   # site retired
        assert audit.compile_count("serving.prefill") == 0
        assert audit.compile_count("serving.chunk_prefill") == 0
        # steady state: same pair shapes must not compile AGAIN (the
        # same arrival pattern, so the packer reproduces the same
        # buckets — a new pattern could legitimately mint a new pair)
        audit.seal()
        eng.submit(rng.randint(2, 50, size=2).tolist(), max_tokens=8)
        eng.run(max_ticks=100)
        eng.submit(rng.randint(2, 50, size=4).tolist(), max_tokens=12)
        eng.step()
        eng.submit(rng.randint(2, 50, size=17).tolist(), max_tokens=8)
        eng.run(max_ticks=300)
        audit.assert_budget("serving.step", pairs)
        audit.assert_no_retraces()
        snap = audit.snapshot()
        assert snap["serving.step"]["calls"] > \
            snap["serving.step"]["compiles"]
    finally:
        FLAGS.use_bf16 = old_bf16


# ---------------------------------------------------------------------------
# linter rules on synthetic snippets
# ---------------------------------------------------------------------------


def _codes_of(findings):
    return sorted({d.code for d in findings})


def test_lint_wall_clock_scoped_to_serving_and_master():
    src = "import time\n\ndef tick():\n    return time.monotonic()\n"
    assert _codes_of(lint_source(src, "paddle_tpu/serving/x.py")) \
        == ["wall-clock"]
    assert _codes_of(lint_source(src, "paddle_tpu/master/x.py")) \
        == ["wall-clock"]
    assert lint_source(src, "paddle_tpu/reader/x.py") == []
    # passing the clock as an injectable default is the sanctioned form
    ok = "import time\n\ndef f(time_fn=time.monotonic):\n    return time_fn()\n"
    assert lint_source(ok, "paddle_tpu/serving/x.py") == []
    # aliased imports cannot smuggle the call past the rule
    alias1 = "import time as t\n\ndef tick():\n    return t.monotonic()\n"
    assert _codes_of(lint_source(alias1, "paddle_tpu/serving/x.py")) \
        == ["wall-clock"]
    alias2 = ("from time import monotonic\n\ndef tick():\n"
              "    return monotonic()\n")
    assert _codes_of(lint_source(alias2, "paddle_tpu/serving/x.py")) \
        == ["wall-clock"]


def test_lint_allowlist_escape_hatch():
    src = ("import time\n\ndef tick():\n"
           "    return time.monotonic()  # lint: allow(wall-clock)\n")
    assert lint_source(src, "paddle_tpu/serving/x.py") == []
    # the line ABOVE also covers (comment-then-statement style)
    src2 = ("import time\n\ndef tick():\n"
            "    # lint: allow(wall-clock)\n"
            "    return time.monotonic()\n")
    assert lint_source(src2, "paddle_tpu/serving/x.py") == []
    # allowing a DIFFERENT rule does not suppress
    src3 = ("import time\n\ndef tick():\n"
            "    return time.monotonic()  # lint: allow(host-sync)\n")
    assert _codes_of(lint_source(src3, "paddle_tpu/serving/x.py")) \
        == ["wall-clock"]


def test_lint_scopes_rules_from_resolved_path(tmp_path, monkeypatch):
    """Dir-scoped rules must fire when a file is linted by bare
    filename from inside its directory — scoping resolves the path."""
    from paddle_tpu.analysis.lint import lint_file

    d = tmp_path / "serving"
    d.mkdir()
    f = d / "x.py"
    f.write_text("import time\n\ndef tick():\n    return time.monotonic()\n")
    monkeypatch.chdir(d)
    assert _codes_of(lint_file("x.py")) == ["wall-clock"]


def test_lint_unseeded_random():
    bad = "import numpy as np\n\ndef f():\n    return np.random.randn(3)\n"
    assert _codes_of(lint_source(bad, "paddle_tpu/utils.py")) \
        == ["unseeded-random"]
    ok = ("import numpy as np\n\ndef f(seed):\n"
          "    return np.random.RandomState(seed).randn(3)\n")
    assert lint_source(ok, "paddle_tpu/utils.py") == []


def test_lint_host_sync_in_serving_loops():
    bad = ("import numpy as np\n\ndef step(rows):\n"
           "    for r in rows:\n"
           "        v = np.asarray(r)\n"
           "        w = r.item()\n")
    found = lint_source(bad, "paddle_tpu/serving/x.py")
    assert _codes_of(found) == ["host-sync"] and len(found) == 2
    # same code outside a loop, or outside serving/: clean
    ok = "import numpy as np\n\ndef step(r):\n    return np.asarray(r)\n"
    assert lint_source(ok, "paddle_tpu/serving/x.py") == []
    assert lint_source(bad, "paddle_tpu/reader/x.py") == []
    # float() over a jax expression inside the loop
    bad2 = ("import jax.numpy as jnp\n\ndef step(rows):\n"
            "    out = []\n    for r in rows:\n"
            "        out.append(float(jnp.mean(r)))\n    return out\n")
    assert _codes_of(lint_source(bad2, "paddle_tpu/serving/x.py")) \
        == ["host-sync"]


def test_lint_mutable_default():
    bad = "def f(x, acc=[]):\n    acc.append(x)\n    return acc\n"
    assert _codes_of(lint_source(bad, "paddle_tpu/utils.py")) \
        == ["mutable-default"]
    ok = "def f(x, acc=None):\n    return (acc or []) + [x]\n"
    assert lint_source(ok, "paddle_tpu/utils.py") == []


def test_lint_import_time_flags():
    bad = ("from paddle_tpu.platform.flags import FLAGS\n"
           "PERIOD = FLAGS.log_period\n")
    assert _codes_of(lint_source(bad, "paddle_tpu/x.py")) \
        == ["import-time-flags"]
    bad2 = ("from paddle_tpu.platform.flags import FLAGS\n"
            "def f(period=FLAGS.log_period):\n    return period\n")
    assert _codes_of(lint_source(bad2, "paddle_tpu/x.py")) \
        == ["import-time-flags"]
    ok = ("from paddle_tpu.platform.flags import FLAGS\n"
          "FLAGS.define('x', 1, 'help')\n"
          "def f():\n    return FLAGS.log_period\n")
    assert lint_source(ok, "paddle_tpu/x.py") == []
    # a def nested in a module-level if/try runs at CALL time — its body
    # must not be treated as an import-time read...
    ok2 = ("from paddle_tpu.platform.flags import FLAGS\n"
           "try:\n"
           "    def f():\n        return FLAGS.log_period\n"
           "except ImportError:\n    pass\n"
           "if True:\n"
           "    def g():\n        return FLAGS.seed\n")
    assert lint_source(ok2, "paddle_tpu/x.py") == []
    # ...but a bare read inside a module-level `if` IS import time
    bad3 = ("from paddle_tpu.platform.flags import FLAGS\n"
            "if True:\n    PERIOD = FLAGS.log_period\n")
    assert _codes_of(lint_source(bad3, "paddle_tpu/x.py")) \
        == ["import-time-flags"]


def test_repo_lints_clean():
    """The acceptance bar: the linter lands clean on its own repo (real
    findings fixed, justified ones allowlisted inline)."""
    findings = run_lint()
    assert findings == [], [d.message for d in findings]
