"""layer.multi_head_attention: packed-sequence flash attention as a layer.

Oracle: each sequence unpacked and run through dense mha_reference —
packed segment masking must match per-sequence attention exactly.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.ops import attention as pattn
from paddle_tpu.platform.flags import FLAGS


@pytest.fixture(autouse=True)
def f32_math():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


def _build(dim, heads, causal):
    paddle.topology.reset_name_scope()
    x = layer.data(name="x",
                   type=paddle.data_type.dense_vector_sequence(dim))
    mha = layer.multi_head_attention(x, num_heads=heads, causal=causal,
                                     name="mha")
    return x, mha


@pytest.mark.parametrize("causal", [False, True])
def test_mha_layer_matches_per_sequence_reference(rng, causal):
    dim, heads = 16, 4
    x, mha = _build(dim, heads, causal)
    topo = paddle.topology.Topology([mha])
    cost = layer.sum_cost(input=layer.fc(input=mha, size=1))
    sgd = trainer.SGD(cost=cost,
                      parameters=paddle.Parameters.from_topology(
                          paddle.topology.Topology([cost]), seed=0),
                      update_equation=optimizer.Sgd())

    seqs = [rng.randn(int(n), dim).astype(np.float32) for n in (5, 9, 3)]
    feeder = sgd._make_feeder({"x": 0})
    feeds = feeder.feed([(s,) for s in seqs])
    p = sgd.parameters.as_dict()
    outs, _ = topo.forward({k: p[k] for k in topo.param_specs()},
                           {}, {"x": feeds["x"]}, train=False)
    sb = outs[0]
    got = np.asarray(sb.data)

    # oracle: per-sequence dense attention with the same projections
    wq, wk, wv, wo = (np.asarray(p["mha.wq"]), np.asarray(p["mha.wk"]),
                      np.asarray(p["mha.wv"]), np.asarray(p["mha.wo"]))
    off = 0
    for s in seqs:
        n = s.shape[0]
        q = (s @ wq).reshape(1, n, heads, -1)
        k = (s @ wk).reshape(1, n, heads, -1)
        v = (s @ wv).reshape(1, n, heads, -1)
        ref = pattn.mha_reference(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=causal)
        want = np.asarray(ref).reshape(n, -1) @ wo
        np.testing.assert_allclose(got[off:off + n], want, atol=2e-4)
        off += n


def test_mha_layer_trains(rng):
    """Self-attention classifier learns a token-lookup task."""
    dim, heads, vocab = 16, 4, 30
    paddle.topology.reset_name_scope()
    words = layer.data(name="w",
                       type=paddle.data_type.integer_value_sequence(vocab))
    y = layer.data(name="y", type=paddle.data_type.integer_value(2))
    emb = layer.embedding(input=words, size=dim)
    att = layer.multi_head_attention(emb, num_heads=heads)
    pooled = layer.pooling(input=att,
                           pooling_type=paddle.pooling.AvgPooling())
    cost = layer.classification_cost(input=layer.fc(input=pooled, size=2),
                                     label=y)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=5e-3))

    def reader():
        for _ in range(25):
            batch = []
            for _ in range(16):
                n = int(rng.randint(4, 12))
                toks = rng.randint(0, vocab, size=n)
                batch.append(([int(t) for t in toks],
                              int(toks.min() < vocab // 3)))
            yield batch

    costs = []
    sgd.train(reader, num_passes=3,
              event_handler=lambda ev: costs.append(float(ev.cost))
              if isinstance(ev, paddle.event.EndIteration) else None)
    assert np.mean(costs[-5:]) < np.mean(costs[:5]) / 2
