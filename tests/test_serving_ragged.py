"""Ragged paged attention v2 (round 12): one pallas kernel for mixed
prefill+decode batches, GQA head-group packing, int8-quantized KV pages.

Covers the kernel/reference parity matrix (mixed batches, ragged
lengths, offset masks, GQA, int8), the single dispatch chooser, the
bytes-per-page accounting behind ``FLAGS.serving_kv_dtype`` and
``ServingEngine(pool_bytes=...)``, the unified-step engine (fused vs
v1-shaped split ticks, token-identical), GQA greedy parity against a
head-replicated MHA oracle, int8 chaos conservation, and the
QUANT-DRIFT parity harness the tier-1 ladder greps (exit 7).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (BLOCK_ROWS, DecoderLM, FaultPlan,
                                ManualClock, PagedKVConfig, Request,
                                RequestStatus, ServingEngine,
                                attention_path, greedy_decode_reference,
                                pack_prefill_chunks, pages_for_budget,
                                quantize_kv, ragged_paged_attention,
                                ragged_paged_attention_reference)
from paddle_tpu.serving.decode_attention import (QUANT_DRIFT_BOUND,
                                                 _ragged_pallas,
                                                 check_quant_drift,
                                                 quant_parity_error)
from paddle_tpu.ops.attention import mha_reference

from conftest import assert_serving_drained as assert_drained  # noqa: E402

ragged = pytest.mark.ragged
serving = pytest.mark.serving


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


# ---------------------------------------------------------------------------
# mixed-batch construction helpers
# ---------------------------------------------------------------------------


def _build_mixed(rng, seqs, page, pm, num_pages, kvh, d, h):
    """Build a sequence-packed mixed batch.  ``seqs`` is a list of
    (kv_len, q_rows, q_start): q_rows == 1 models a decode slot (its
    query sits at position kv_len-1), q_rows > 1 a prefill chunk whose
    rows occupy positions q_start..q_start+q_rows-1 (so kv_len ==
    q_start + q_rows).  Rows are padded per-sequence to BLOCK_ROWS (the
    kernel's packing contract).  Returns (q, k_pages, v_pages, table,
    kv_lens, row_seq, qpos, contig_k, contig_v)."""
    s = len(seqs)
    kc = rng.randn(s, pm * page, kvh, d).astype(np.float32)
    vc = rng.randn(s, pm * page, kvh, d).astype(np.float32)
    kp = rng.randn(num_pages, page, kvh, d).astype(np.float32)  # garbage
    vp = rng.randn(num_pages, page, kvh, d).astype(np.float32)
    table = np.zeros((s, pm), np.int32)
    free = list(range(1, num_pages))
    rng.shuffle(free)
    for i, (n, _, _) in enumerate(seqs):
        for j in range(-(-int(n) // page)):
            pg = free.pop()
            table[i, j] = pg
            kp[pg] = kc[i, j * page:(j + 1) * page]
            vp[pg] = vc[i, j * page:(j + 1) * page]
    rows, row_seq, qpos = [], [], []
    for i, (n, qr, qs) in enumerate(seqs):
        blocks = -(-qr // BLOCK_ROWS)
        pos = [qs + r for r in range(qr)] if qr > 1 else [n - 1]
        pos += [-1] * (blocks * BLOCK_ROWS - qr)
        qpos += pos
        row_seq += [i] * blocks * BLOCK_ROWS
        rows.append(blocks * BLOCK_ROWS)
    t = sum(rows)
    q = rng.randn(t, h, d).astype(np.float32)
    return (q, kp, vp, table, np.asarray([n for n, _, _ in seqs], np.int32),
            np.asarray(row_seq, np.int32), np.asarray(qpos, np.int32),
            kc, vc)


def _oracle(q, kc, vc, kv_lens, row_seq, qpos, h):
    """Per-row mha_reference oracle over the CONTIGUOUS ground-truth
    K/V (never touches pages), with the causal/offset mask expressed as
    a kv-length slice per row."""
    t = q.shape[0]
    out = np.zeros_like(q)
    for r in range(t):
        if qpos[r] < 0:
            continue
        s = row_seq[r]
        upto = qpos[r] + 1          # row sees tokens 0..qpos inclusive
        o = mha_reference(jnp.asarray(q[r:r + 1][:, None]),
                          jnp.asarray(kc[s][None, :upto]),
                          jnp.asarray(vc[s][None, :upto]))
        out[r] = np.asarray(o)[0, 0]
    return out


MIXED_CASES = [
    # (kv_len, q_rows, q_start) per sequence; page=8, pm=4
    [(13, 1, 0), (9, 5, 4), (20, 1, 0)],          # decode + offset chunk
    [(8, 8, 0), (1, 1, 0), (32, 1, 0)],           # page-exact chunk, len-1
    [(27, 11, 16), (5, 1, 0), (17, 17, 0)],       # multi-block chunks
]


@ragged
@serving
@pytest.mark.parametrize("kvh,h", [(2, 2), (2, 4)])   # MHA and GQA
@pytest.mark.parametrize("case", MIXED_CASES)
def test_ragged_mixed_batch_matches_oracle(rng, case, kvh, h):
    page, pm, num_pages, d = 8, 4, 32, 16
    q, kp, vp, table, kv_lens, row_seq, qpos, kc, vc = _build_mixed(
        rng, case, page, pm, num_pages, kvh, d, h)
    want = _oracle(q, kc, vc, kv_lens, row_seq, qpos, h)
    real = qpos >= 0

    ref = np.asarray(ragged_paged_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(kv_lens), jnp.asarray(row_seq),
        jnp.asarray(qpos)))
    np.testing.assert_allclose(ref[real], want[real], rtol=2e-5, atol=2e-5)

    ker = np.asarray(_ragged_pallas(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp), None, None,
        jnp.asarray(table), jnp.asarray(kv_lens), jnp.asarray(row_seq),
        jnp.asarray(qpos), float(d) ** -0.5, True))
    np.testing.assert_allclose(ker[real], want[real], rtol=2e-5, atol=2e-5)

    # public entry, kernel forced (interpret on CPU)
    pub = np.asarray(ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(kv_lens), jnp.asarray(row_seq),
        jnp.asarray(qpos), use_kernel=True))
    np.testing.assert_allclose(pub[real], want[real], rtol=2e-5, atol=2e-5)


@ragged
@serving
def test_blocked_reference_matches_oracle(rng):
    """The engine's row-blocked fallback (bounded per-row K/V gather)
    is the oracle applied blockwise — identical results on a row stack
    spanning several blocks, pad rows included."""
    from paddle_tpu.serving.decode_attention import \
        _ragged_reference_blocked
    page, pm, num_pages, kvh, h, d = 8, 4, 32, 2, 4, 16
    q, kp, vp, table, kv_lens, row_seq, qpos, _, _ = _build_mixed(
        rng, MIXED_CASES[2], page, pm, num_pages, kvh, d, h)
    args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(kv_lens),
            jnp.asarray(row_seq), jnp.asarray(qpos))
    want = np.asarray(ragged_paged_attention_reference(*args))
    got = np.asarray(_ragged_reference_blocked(*args, block=16))
    real = qpos >= 0
    np.testing.assert_allclose(got[real], want[real], rtol=1e-6, atol=1e-6)


@ragged
@serving
def test_cancel_from_chunk_callback_skips_batchmate_chunk(rng):
    """A request cancelled by a BATCHMATE's on_token (fired from the
    same unified step's chunk walk) must not have its own chunk results
    applied: no cache insert on released pages, no resurrection of the
    terminal status, and conservation holds at drain."""
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = _engine(model, params)
    victim_rid = {}

    def assassin(tok):
        eng.cancel(victim_rid["b"])

    # both prompts fit one chunk, so both finish prefill — and emit
    # their first token through the chunk walk — in the SAME tick;
    # slot order makes A's callback run before B's chunk bookkeeping
    a = eng.submit(rng.randint(2, 50, size=5).tolist(), max_tokens=4,
                   on_token=assassin)
    b = eng.submit(rng.randint(2, 50, size=6).tolist(), max_tokens=4)
    victim_rid["b"] = b
    eng.step()
    assert eng.status(b) is RequestStatus.CANCELLED
    assert eng.status(a) is RequestStatus.RUNNING
    eng.run(max_ticks=100)
    assert eng.status(a) is RequestStatus.COMPLETED
    assert eng.status(b) is RequestStatus.CANCELLED
    assert eng.result(b) is None        # never resurrected to COMPLETED
    assert_drained(eng)


@ragged
@serving
def test_ragged_kernel_int8_reads_what_reference_reads(rng):
    """Kernel and gather-fallback dequantize the SAME stored int8
    values — their outputs agree to float tolerance (the quantization
    error itself cancels out of this comparison)."""
    page, pm, num_pages, kvh, h, d = 8, 4, 32, 2, 4, 16
    q, kp, vp, table, kv_lens, row_seq, qpos, _, _ = _build_mixed(
        rng, MIXED_CASES[0], page, pm, num_pages, kvh, d, h)
    kq, ks = quantize_kv(jnp.asarray(kp))
    vq, vs = quantize_kv(jnp.asarray(vp))
    args = (jnp.asarray(table), jnp.asarray(kv_lens), jnp.asarray(row_seq),
            jnp.asarray(qpos))
    ref = np.asarray(ragged_paged_attention_reference(
        jnp.asarray(q), kq, vq, *args, k_scale=ks, v_scale=vs))
    ker = np.asarray(_ragged_pallas(
        jnp.asarray(q), kq, vq, ks, vs, *args, float(d) ** -0.5, True))
    real = qpos >= 0
    np.testing.assert_allclose(ker[real], ref[real], rtol=2e-5, atol=2e-5)


@ragged
@serving
def test_int8_quant_parity_harness_within_bound(rng):
    """THE QUANT-DRIFT gate: the int8 roundtrip must stay inside its
    logit-error bound on a mixed ragged batch.  If quantization ever
    regresses (wrong scale axis, missing dequant, clipped range), this
    raises with the grep-able QUANT-DRIFT tag and tools_tier1.sh exits
    7."""
    page, pm, num_pages, kvh, h, d = 8, 4, 32, 2, 4, 16
    q, kp, vp, table, kv_lens, row_seq, qpos, _, _ = _build_mixed(
        rng, MIXED_CASES[2], page, pm, num_pages, kvh, d, h)
    err = check_quant_drift(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(kv_lens), jnp.asarray(row_seq),
        jnp.asarray(qpos))
    assert 0.0 <= err <= QUANT_DRIFT_BOUND
    # and the tag actually fires when the bound is violated (an
    # impossible bound stands in for a broken quant path; pytest.raises
    # swallows the message so the tier-1 grep never sees a passing run)
    with pytest.raises(AssertionError, match="QUANT-DRIFT"):
        check_quant_drift(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(kv_lens),
            jnp.asarray(row_seq), jnp.asarray(qpos), bound=0.0)
    assert quant_parity_error(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(table), jnp.asarray(kv_lens), jnp.asarray(row_seq),
        jnp.asarray(qpos)) == err


# ---------------------------------------------------------------------------
# the single dispatch chooser
# ---------------------------------------------------------------------------


@ragged
@serving
def test_attention_path_single_chooser():
    # forced answers win over everything
    assert attention_path(7, 3, use_kernel=True) == "kernel"
    assert attention_path(128, 128, use_kernel=False) == "reference"
    # interpret (the CPU default) rides the reference path
    assert attention_path(128, 128, interpret=True) == "reference"
    # native gate: lane-aligned head dim, sublane-aligned pages
    assert attention_path(128, 128, interpret=False) == "kernel"
    assert attention_path(96, 128, interpret=False) == "reference"
    assert attention_path(128, 12, interpret=False) == "reference"
    # int8 additionally wants lane-aligned pages for its scale vectors
    assert attention_path(128, 128, quantized=True,
                          interpret=False) == "kernel"
    assert attention_path(128, 64, quantized=True,
                          interpret=False) == "reference"
    # mismatched head grouping falls back
    assert attention_path(128, 128, num_heads=6, num_kv_heads=4,
                          interpret=False) == "reference"
    assert attention_path(128, 128, num_heads=8, num_kv_heads=4,
                          interpret=False) == "kernel"


# ---------------------------------------------------------------------------
# bytes-per-page accounting + pool byte budgets (serving_kv_dtype)
# ---------------------------------------------------------------------------


def _cfg(dtype, kvh=None):
    return PagedKVConfig(num_layers=2, num_heads=4, head_dim=16,
                         page_size=8, num_pages=10, max_pages_per_seq=4,
                         dtype=dtype, num_kv_heads=kvh)


@ragged
@serving
def test_bytes_per_page_accounting():
    f32, bf16, i8 = (_cfg(jnp.float32), _cfg(jnp.bfloat16), _cfg(jnp.int8))
    # exact arithmetic: 2 (K+V) * L * page * H_kv * D * itemsize
    assert f32.bytes_per_page() == 2 * 2 * 8 * 4 * 16 * 4
    assert bf16.bytes_per_page() == f32.bytes_per_page() // 2
    # int8 = 1 byte/elem + one f32 scale per (layer, token, kv head)
    assert i8.bytes_per_page() == 2 * 2 * 8 * 4 * (16 * 1 + 4)
    assert f32.kv_bytes() == 10 * f32.bytes_per_page()
    # GQA halves the pool bytes when kv heads halve
    assert _cfg(jnp.float32, kvh=2).bytes_per_page() == \
        f32.bytes_per_page() // 2
    # the acceptance arithmetic: at one byte budget, int8 admits the
    # pages the smaller footprint buys — >= 1.8x f32 even with the
    # scale overhead (exactly 3.2x at D=16)
    budget = 1 << 20
    pages = {d: pages_for_budget(budget, 2, 4, 16, 8, d)
             for d in ("float32", "bfloat16", "int8")}
    assert pages["int8"] >= int(1.8 * pages["float32"])
    assert pages["bfloat16"] == 2 * pages["float32"]
    assert pages["int8"] == int(budget // _cfg(jnp.int8).bytes_per_page())


@ragged
@serving
def test_bf16_kv_pool_via_flag_and_param(rng):
    """Satellite: serving_kv_dtype plumbs through the cache config —
    bf16 KV works end to end even without int8."""
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    old = FLAGS.serving_kv_dtype
    try:
        FLAGS.serving_kv_dtype = "bfloat16"
        eng = ServingEngine(model, params, eos_id=1, page_size=4,
                            num_pages=20, max_pages_per_seq=5, max_slots=2,
                            buckets=(4, 8))
    finally:
        FLAGS.serving_kv_dtype = old
    assert eng.kv_cfg.dtype == jnp.bfloat16
    assert eng._kv.k.dtype == jnp.bfloat16 and eng._kv.k_scale is None
    rid = eng.submit(rng.randint(2, 50, size=6).tolist(), max_tokens=6)
    res = eng.run(max_ticks=100)
    assert eng.status(rid) is RequestStatus.COMPLETED and len(res[rid]) >= 1
    assert eng.healthz()["kv_dtype"] == "bfloat16"
    assert_drained(eng)
    # explicit param wins over the flag
    eng2 = ServingEngine(model, params, eos_id=1, page_size=4,
                         num_pages=20, max_pages_per_seq=5, max_slots=2,
                         buckets=(4, 8), kv_dtype="int8")
    assert eng2.kv_cfg.quantized and eng2._kv.k_scale is not None


@ragged
@serving
def test_pool_bytes_budget_doubles_int8_admission(rng):
    """The scheduler charges admission in pages, so the int8 page
    multiplier IS an admission multiplier: at the same pool_bytes the
    int8 engine owns >= 1.8x the f32 pages."""
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    budget = 64 * 1024
    engines = {d: ServingEngine(model, params, eos_id=1, page_size=4,
                                num_pages=None, pool_bytes=budget,
                                max_pages_per_seq=5, max_slots=2,
                                buckets=(4, 8), kv_dtype=d)
               for d in ("float32", "int8")}
    f32p = engines["float32"].pool.num_usable
    i8p = engines["int8"].pool.num_usable
    assert i8p >= int(1.8 * f32p)
    hz = engines["int8"].healthz()
    assert hz["pages_total"] == i8p and hz["kv_dtype"] == "int8"


# ---------------------------------------------------------------------------
# packer policy
# ---------------------------------------------------------------------------


def _fake_req(n_tokens, done=0):
    r = Request(prompt=list(range(2, 2 + n_tokens)), max_tokens=4)
    r.cache_len = done
    return r


@ragged
@serving
def test_pack_prefill_chunks_budget_align_and_oversize():
    a, b, c = _fake_req(20), _fake_req(20), _fake_req(4)
    sel, total = pack_prefill_chunks([a, b, c], chunk=8, align=8, budget=16)
    # greedy in order until the budget: a and b fit, c is crowded out
    assert [(r.rid, s, n, rows) for r, s, n, rows in sel] == \
        [(a.rid, 0, 8, 8), (b.rid, 0, 8, 8)]
    assert total == 16
    # alignment pads partial chunks to whole blocks
    sel, total = pack_prefill_chunks([c], chunk=8, align=8, budget=16)
    assert sel == [(c, 0, 4, 8)] and total == 8
    # the first chunk packs even when it alone exceeds the budget
    big = _fake_req(40)
    sel, total = pack_prefill_chunks([big], chunk=0, align=1, budget=16)
    assert sel == [(big, 0, 40, 40)] and total == 40
    # resume point honors prior progress; finished requests are skipped
    sel, _ = pack_prefill_chunks([_fake_req(20, done=17),
                                  _fake_req(6, done=6)],
                                 chunk=8, align=1, budget=16)
    assert [(s, n) for _, s, n, _ in sel] == [(17, 3)]


# ---------------------------------------------------------------------------
# unified-step engine: kernel parity, fused-vs-split, GQA, int8
# ---------------------------------------------------------------------------


def _mixed_traffic(eng, rng_seed=0):
    """Mixed long-prefill/heavy-decode traffic: long prompts chunking
    while short ones decode — the shape the v1 tick interleave handled
    worst.  Deterministic; returns outputs in submit order."""
    rng = np.random.RandomState(rng_seed)
    prompts = [rng.randint(2, 50, size=n).tolist()
               for n in (3, 26, 5, 19, 2, 11)]
    rids = []
    for i, p in enumerate(prompts):
        rids.append(eng.submit(p, max_tokens=10 if len(p) < 8 else 4))
        if i % 2:
            eng.step()              # interleave arrivals with ticks
    eng.run(max_ticks=400)
    return prompts, rids, [eng.result(r) for r in rids]


def _engine(model, params, **kw):
    kw.setdefault("eos_id", 1)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 60)
    kw.setdefault("max_pages_per_seq", 10)
    kw.setdefault("max_slots", 4)
    kw.setdefault("buckets", (8, 16, 32))
    kw.setdefault("prefill_chunk", 8)
    return ServingEngine(model, params, **kw)


@ragged
@serving
def test_engine_kernel_fallback_parity_mixed(rng):
    """CPU fallback parity for the ragged kernel at ENGINE level: the
    same mixed prefill+decode traffic (ragged lengths, offset masks via
    chunked prefill) through use_kernel=True (pallas, interpret on CPU)
    and the reference path produces token-identical outputs, and both
    match the non-paged oracle."""
    model = DecoderLM(vocab_size=50, num_layers=2, num_heads=2, head_dim=8,
                      max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    prompts, _, out_ref = _mixed_traffic(_engine(model, params,
                                                 use_kernel=False))
    _, _, out_ker = _mixed_traffic(_engine(model, params, use_kernel=True))
    assert out_ker == out_ref
    for p, toks in zip(prompts, out_ref):
        mt = 10 if len(p) < 8 else 4
        assert toks == greedy_decode_reference(model, params, p, mt, 1)


@ragged
@serving
def test_fused_vs_split_tick_token_identical(rng):
    """fuse_tick=False reproduces the v1 two-dispatch tick shape as the
    bench A/B control: token-identical outputs, strictly more
    dispatches for the same work."""
    model = DecoderLM(vocab_size=50, num_layers=2, num_heads=2, head_dim=8,
                      max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    fused = _engine(model, params)
    split = _engine(model, params, fuse_tick=False)
    _, _, out_f = _mixed_traffic(fused)
    _, _, out_s = _mixed_traffic(split)
    assert out_f == out_s
    assert split.metrics.step_dispatches > fused.metrics.step_dispatches
    assert fused.metrics.prefill_rows == split.metrics.prefill_rows
    assert_drained(fused)
    assert_drained(split)


@ragged
@serving
def test_gqa_engine_parity_vs_head_replicated_mha_oracle(rng):
    """Satellite: a GQA DecoderLM (num_kv_heads < num_heads) decodes
    token-identically to (a) the non-paged greedy oracle on its own
    weights and (b) an MHA DecoderLM whose K/V projections replicate
    each KV head across its query group — the algebraic identity GQA
    packing must preserve."""
    gqa = DecoderLM(vocab_size=50, num_layers=2, num_heads=4, head_dim=8,
                    num_kv_heads=2, max_positions=128)
    gp = gqa.init_params(jax.random.PRNGKey(3))
    assert gp["l0.wk"].shape == (32, 16)          # E x (H_kv * D)
    # head-replicated MHA twin: KV head g serves query heads 2g, 2g+1
    mha = DecoderLM(vocab_size=50, num_layers=2, num_heads=4, head_dim=8,
                    max_positions=128)
    mp = dict(gp)
    group = gqa.num_heads // gqa.num_kv_heads
    for l in range(2):
        for w in ("wk", "wv"):
            m = gp[f"l{l}.{w}"].reshape(32, gqa.num_kv_heads, 8)
            mp[f"l{l}.{w}"] = jnp.repeat(m, group, axis=1).reshape(32, 32)
    prompts = [np.random.RandomState(7).randint(2, 50, size=n).tolist()
               for n in (3, 9, 14)]
    for p in prompts:
        want = greedy_decode_reference(mha, mp, p, 8, 1)
        assert greedy_decode_reference(gqa, gp, p, 8, 1) == want
    eng = _engine(gqa, gp)
    rids = [eng.submit(p, max_tokens=8) for p in prompts]
    res = eng.run(max_ticks=200)
    for p, rid in zip(prompts, rids):
        assert res[rid] == greedy_decode_reference(mha, mp, p, 8, 1)
    # the pool really stores only the KV heads
    assert eng._kv.k.shape[3] == 2
    assert_drained(eng)


@ragged
@serving
def test_int8_engine_completes_with_prefix_cow_and_conservation(rng):
    """int8 pages through the full engine: chunked prefill, prefix
    cache hits, a COW fork (scales must fork with the values), and the
    REF-LEAK/PAGE-LEAK conservation checks at drain.  Determinism:
    resubmitting an identical prompt (now a full-cover cache hit that
    decodes from forked int8 pages) reproduces the first answer
    token-for-token."""
    model = DecoderLM(vocab_size=50, num_layers=2, num_heads=2, head_dim=8,
                      max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = _engine(model, params, kv_dtype="int8")
    sys_p = rng.randint(2, 50, size=12).tolist()
    a = eng.submit(sys_p, max_tokens=6)
    eng.run(max_ticks=100)
    b = eng.submit(sys_p, max_tokens=6)          # full-cover hit -> COW
    c = eng.submit(sys_p + [9, 8], max_tokens=6)  # partial hit
    res = eng.run(max_ticks=200)
    assert res[b] == res[a]
    assert eng.metrics.cow_forks >= 1
    assert eng.metrics.prefill_tokens_saved > 0
    assert len(res[c]) >= 1
    assert_drained(eng)


@ragged
@serving
@pytest.mark.faults
def test_int8_chaos_keeps_conservation_and_terminal_statuses(rng):
    """Acceptance: 0 PAGE-LEAK / REF-LEAK under the chaos plan with
    int8 pages enabled — pressure, transient decode errors, a NaN rid,
    preemption and eviction all running over quantized pages."""
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=128)
    params = model.init_params(jax.random.PRNGKey(0))
    clock = ManualClock(tick_s=0.02)
    plan = FaultPlan(seed=0, clock=clock, decode_error_rate=0.1,
                     page_pressure=(3, 12, 10))
    # eos outside the vocab: every request really decodes its full
    # max_tokens, so the poisoned rid is guaranteed to meet the NaN
    # injection at a decode tick (a first token emitted straight from
    # prefill could otherwise complete it before poisoning applies)
    eng = _engine(model, params, kv_dtype="int8", num_pages=24,
                  max_pages_per_seq=8, faults=plan, watchdog_ticks=32,
                  eos_id=51)
    prompts = [rng.randint(2, 50, size=rng.randint(2, 14)).tolist()
               for _ in range(8)]
    rids = [eng.submit(p, max_tokens=8) for p in prompts]
    plan.poison_nan(rids[3])
    eng.run(max_ticks=500)
    assert eng.status(rids[3]) is RequestStatus.FAILED
    for r in rids:
        assert eng.status(r).terminal
    assert_drained(eng)
