"""Network-equivalence pairs through utils.compare_topologies.

Reference analog: paddle/gserver/tests/test_NetworkCompare.cpp and
trainer/tests/test_CompareTwoNets.cpp — the same computation expressed as
two different configs must produce identical outputs AND gradients. Each
test here is one such pair, with weights linked by ParamAttr name.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer, networks
from paddle_tpu.attr import ParamAttr
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.utils import compare_topologies

RNG = np.random.RandomState(31)


def _seq(dim, lens, cap=None, seed=5):
    rng = np.random.RandomState(seed)
    return SequenceBatch.from_list(
        [rng.randn(l, dim).astype(np.float32) * 0.5 for l in lens],
        capacity=cap or sum(lens))


def test_fc_vs_mixed_projection():
    """fc == mixed([full_matrix_projection]) with the same weight."""
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
    a = layer.fc(x, size=5, act="tanh", bias_attr=False,
                 param_attr=ParamAttr(name="cmp_w"))
    b = layer.mixed(size=5, act="tanh", input=[
        layer.full_matrix_projection(x, size=5,
                                     param_attr=ParamAttr(name="cmp_w"))])
    fx = RNG.randn(4, 6).astype(np.float32)
    compare_topologies(a, b, {"x": fx}, check_inputs=("x",))


def test_fc_two_inputs_vs_mixed_two_projections():
    """Multi-input fc == mixed of two full_matrix_projections."""
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
    y = layer.data(name="y", type=paddle.data_type.dense_vector(3))
    a = layer.fc([x, y], size=5, act="sigmoid", bias_attr=False,
                 param_attr=[ParamAttr(name="wx"), ParamAttr(name="wy")])
    b = layer.mixed(size=5, act="sigmoid", input=[
        layer.full_matrix_projection(x, size=5,
                                     param_attr=ParamAttr(name="wx")),
        layer.full_matrix_projection(y, size=5,
                                     param_attr=ParamAttr(name="wy"))])
    feeds = {"x": RNG.randn(4, 6).astype(np.float32),
             "y": RNG.randn(4, 3).astype(np.float32)}
    compare_topologies(a, b, feeds, check_inputs=("x", "y"))


def test_lstmemory_vs_recurrent_group_lstm_step():
    """lstmemory == recurrent_group over lstm_step with linked weights
    (the reference's test_RecurrentLayer strategy, one scan vs explicit
    per-frame steps)."""
    paddle.topology.reset_name_scope()
    H = 4
    s = layer.data(name="s",
                   type=paddle.data_type.dense_vector_sequence(4 * H))
    a = layer.lstmemory(s, size=H, param_attr=ParamAttr(name="lstm_w"),
                        bias_attr=ParamAttr(name="lstm_b"))

    def step(frame):
        c_mem = layer.memory(name="c_out", size=H)
        h_mem = layer.memory(name="h_out", size=H)
        st = layer.lstm_step(input=frame, state_mem=c_mem, output_mem=h_mem,
                             size=H, param_attr=ParamAttr(name="lstm_w"),
                             bias_attr=ParamAttr(name="lstm_b"), name="cell")
        h = layer.lstm_step_output(st, name="h_out")
        c = layer.lstm_step_state(st, name="c_out")
        return [h, c]

    outs = layer.recurrent_group(step=step, input=s, name="rg_cmp")
    b = outs[0]
    sb = _seq(4 * H, [3, 5], cap=8)
    compare_topologies(a, b, {"s": sb})


def test_recurrent_vs_group_elman():
    """layer.recurrent == recurrent_group(fc-on-memory + addto) — the flat
    built-in vs the user-composed group."""
    paddle.topology.reset_name_scope()
    H = 6
    x = layer.data(name="x", type=paddle.data_type.dense_vector_sequence(H))
    a = layer.recurrent(input=x, size=H, act="tanh", bias_attr=False,
                        param_attr=ParamAttr(name="shared_w"))

    def step(frame):
        m = layer.memory(name="h_out", size=H)
        proj = layer.fc(input=m, size=H, bias_attr=False,
                        param_attr=ParamAttr(name="shared_w"), name="h_proj")
        return layer.addto(input=[frame, proj], act="tanh", name="h_out")

    b = layer.recurrent_group(step=step, input=x, name="rg_elman")
    compare_topologies(a, b, {"x": _seq(H, [3, 5], cap=8)})


def test_flash_vs_plain_attention_kernels():
    """The SAME attention topology under the pallas flash kernel vs the
    plain-XLA fallback must agree in outputs and every projection grad —
    kernel choice is an implementation detail, not semantics."""
    paddle.topology.reset_name_scope()
    D = 8
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(D))
    # same layer NAME on both sides links wq/wk/wv/wo automatically
    a = layer.multi_head_attention(s, num_heads=2, name="attn")
    paddle.topology.reset_name_scope()
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(D))
    b = layer.multi_head_attention(s, num_heads=2, name="attn")
    sb = _seq(D, [4, 3], cap=8)
    compare_topologies(a, b, {"s": sb},
                       flags_a={"use_pallas": True},
                       flags_b={"use_pallas": False},
                       rtol=2e-4, atol=2e-5)


def test_img_conv_vs_conv_operator():
    """img_conv (static filter parameter) == conv_operator in mixed (filter
    arriving as a layer value) when the operator is fed the conv's weight."""
    paddle.topology.reset_name_scope()
    fs, C, F, HW = 3, 2, 2, 4
    x = layer.data(name="x", type=paddle.data_type.dense_vector(HW * HW * C),
                   height=HW, width=HW)
    a = layer.img_conv(x, filter_size=fs, num_filters=F, num_channels=C,
                       padding=0, bias_attr=False,
                       param_attr=ParamAttr(name="conv_w"), name="ca")
    out = (HW - fs + 1)
    filt = layer.data(name="filt",
                      type=paddle.data_type.dense_vector(fs * fs * C * F))
    b = layer.mixed(size=out * out * F, input=[
        layer.conv_operator(x, filt, filter_size=fs, num_filters=F,
                            num_channels=C)])

    # the operator needs the SAME filter values the parameter got at init:
    # rebuild A's topology at the same seed and extract them
    wv = np.asarray(paddle.Parameters.from_topology(
        paddle.topology.Topology([a]), seed=0)["conv_w"]).reshape(1, -1)
    n = 3
    fx = RNG.randn(n, HW * HW * C).astype(np.float32)
    ffilt = np.tile(wv, (n, 1)).astype(np.float32)
    compare_topologies(a, b, {"x": fx}, {"x": fx, "filt": ffilt},
                       check_inputs=("x",), rtol=2e-4, atol=2e-5)


def test_simple_lstm_vs_explicit_fc_lstmemory():
    """networks.simple_lstm == fc(4H) -> lstmemory built by hand."""
    paddle.topology.reset_name_scope()
    H, D = 4, 6
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(D))
    # same layer names on both sides link every parameter automatically
    a = networks.simple_lstm(input=s, size=H, name="lm")
    paddle.topology.reset_name_scope()
    s = layer.data(name="s", type=paddle.data_type.dense_vector_sequence(D))
    b = layer.lstmemory(
        layer.fc(s, size=4 * H, bias_attr=True, name="lm_input_proj"),
        size=H, name="lm")
    compare_topologies(a, b, {"s": _seq(D, [4, 2], cap=8)})


def test_compare_catches_inequivalent_networks():
    """The harness must FAIL when the two configs genuinely differ."""
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(6))
    a = layer.fc(x, size=5, act="tanh", bias_attr=False,
                 param_attr=ParamAttr(name="cmp_w"))
    b = layer.fc(x, size=5, act="sigmoid", bias_attr=False,
                 param_attr=ParamAttr(name="cmp_w"))
    fx = RNG.randn(4, 6).astype(np.float32)
    with pytest.raises(AssertionError):
        compare_topologies(a, b, {"x": fx})


def test_lm_head_cost_vs_unfused_pair():
    """Fused blockwise LM-head xent == fc(vocab) -> classification_cost
    with the same weights, outputs AND grads (incl. through the input)."""
    paddle.topology.reset_name_scope()
    V, D = 37, 6   # 37 % 8 != 0 exercises the padded last block
    x = layer.data(name="x", type=paddle.data_type.dense_vector(D))
    lab = layer.data(name="lab", type=paddle.data_type.integer_value(V))
    a = layer.classification_cost(
        input=layer.fc(x, size=V, param_attr=ParamAttr(name="head_w"),
                       bias_attr=ParamAttr(name="head_b")), label=lab)
    b = layer.lm_head_cost(x, lab, vocab_size=V,
                           param_attr=ParamAttr(name="head_w"),
                           bias_attr=ParamAttr(name="head_b"), block_size=8)
    fx = RNG.randn(5, D).astype(np.float32)
    flab = RNG.randint(0, V, (5,)).astype(np.int32)
    compare_topologies(a, b, {"x": fx, "lab": flab}, check_inputs=("x",))
