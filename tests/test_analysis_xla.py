"""Jaxpr-level compiled-path auditor (paddle_tpu.analysis.xla): one
seeded-bad jaxpr per rule class — undonated big buffer, silent f32
upcast, callback-in-tick, const-captured weights, collective-in-decode,
busted budget — plus clean-run pins over the real sealed serving.step
and trainer sites, the retrace capture/donation-strip plumbing, the
obs-registry compile-count publish, and the extended host-sync lint.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.analysis import xla as X
from paddle_tpu.analysis.diagnostics import Severity
from paddle_tpu.analysis.lint import lint_source
from paddle_tpu.analysis.retrace import SiteContract, audit_jit, auditor
from paddle_tpu.platform.flags import FLAGS

pytestmark = [pytest.mark.xla, pytest.mark.analysis]


@pytest.fixture
def audit():
    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    auditor().reset()
    yield auditor()
    FLAGS.jit_audit = old
    auditor().reset()


def _report(site):
    reps = X.audit_sites(sites=[site])
    assert site in reps, f"site {site} captured nothing"
    return reps[site]


def _errors(rep):
    return [d for d in rep.diagnostics if d.severity is Severity.ERROR]


# ---------------------------------------------------------------------------
# capture plumbing
# ---------------------------------------------------------------------------


def test_site_captures_jaxpr_and_requested_kwargs(audit):
    f = audit_jit(lambda a: a * 2, site="t.cap", donate_argnums=(0,))
    f(jnp.ones((4, 4)))
    rec = audit.sites["t.cap"]
    # the REQUESTED kwargs survive even though CPU cannot donate
    assert rec.jit_kwargs == {"donate_argnums": (0,)}
    assert len(rec.captured) == 1
    cap = next(iter(rec.captured.values()))
    # each capture is self-contained (fn + kwargs + contract): two
    # engines sharing a site name replay through their OWN closures
    assert cap.jit_kwargs == {"donate_argnums": (0,)}
    closed = X.materialize_jaxpr(cap)
    assert [e.primitive.name for e in closed.jaxpr.eqns] == ["mul"]
    # captures hold ShapeDtypeStructs, never device buffers
    assert isinstance(cap.args[0], jax.ShapeDtypeStruct)
    # materialization never pollutes the compile count
    assert audit.compile_count("t.cap") == 1


def test_donation_declared_on_cpu_is_stripped_not_warned(audit):
    """The engine.py:372 gap, closed: sites declare the TPU donation
    contract unconditionally; audit_jit strips it before the CPU
    jax.jit so the run is warning-free, while the auditor checks the
    requested kwargs."""
    f = audit_jit(lambda a: a + 1, site="t.strip", donate_argnums=(0,))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = f(jnp.ones((8,)))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert audit.sites["t.strip"].jit_kwargs["donate_argnums"] == (0,)


def test_reset_clears_captures_in_place(audit):
    f = audit_jit(lambda a: a * 2, site="t.reset")
    f(jnp.ones((4,)))
    rec = audit.sites["t.reset"]
    assert rec.captured
    audit.reset()
    assert rec.captured == {}          # same record object, cleared
    # reset() is also the memory reclamation path: the fn references
    # (which can pin a whole engine via the step closure) are dropped
    assert rec.fn is None and rec.jit_kwargs == {}
    f(jnp.ones((4,)))                  # live wrapper keeps recording
    assert len(rec.captured) == 1
    # ...and its capture is self-contained, so the audit still works
    assert X.audit_sites(sites=["t.reset"])["t.reset"].signatures == 1


# ---------------------------------------------------------------------------
# seeded-bad jaxprs, one per rule class
# ---------------------------------------------------------------------------


def test_donation_contract_violation_flagged(audit):
    f = audit_jit(lambda kv, x: (kv + x, x), site="t.donbad",
                  xla_contract=SiteContract(donate=(0,)))
    f(jnp.ones((32, 32)), jnp.ones((32, 32)))
    errs = _errors(_report("t.donbad"))
    assert len(errs) == 1
    msg = errs[0].message
    assert "donation-contract" in msg and "t.donbad" in msg
    assert "arg 0" in msg


def test_donation_contract_satisfied_is_clean(audit):
    f = audit_jit(lambda kv, x: (kv + x, x), site="t.donok",
                  donate_argnums=(0,),
                  xla_contract=SiteContract(donate=(0,)))
    f(jnp.ones((32, 32)), jnp.ones((32, 32)))
    assert _errors(_report("t.donok")) == []


def test_undonated_big_buffer_reported_as_candidate(audit):
    big = jnp.ones((512, 512))                     # 1 MiB
    f = audit_jit(lambda a: a + 1.0, site="t.candidate")
    f(big)
    rep = _report("t.candidate")
    assert _errors(rep) == []                      # candidate = WARNING
    warns = [d for d in rep.diagnostics
             if d.severity is Severity.WARNING]
    assert len(warns) == 1 and "not donated" in warns[0].message


def test_silent_f32_upcast_flagged_and_allowlistable(audit):
    def fn(x, w):
        return x.astype(jnp.float32) @ w

    f = audit_jit(fn, site="t.upcast")
    f(jnp.ones((8, 8), jnp.bfloat16), jnp.ones((8, 8)))
    errs = _errors(_report("t.upcast"))
    assert len(errs) == 1
    assert "dtype-promotion-drift" in errs[0].message
    assert "dot_general" in errs[0].message        # names the eqn
    assert "t.upcast" in errs[0].message           # names the site

    g = audit_jit(fn, site="t.upcast_ok",
                  xla_contract=SiteContract(allow_upcast=("bfloat16",)))
    g(jnp.ones((8, 8), jnp.bfloat16), jnp.ones((8, 8)))
    assert _errors(_report("t.upcast_ok")) == []


def test_int8_dequant_chain_tracked_through_elementwise(audit):
    """The real drift shape: int8 pages -> convert -> scale-mul ->
    matmul.  The origin must survive the elementwise mul."""
    def fn(pages, scale, q):
        deq = pages.astype(jnp.float32) * scale
        return q @ deq

    f = audit_jit(fn, site="t.dequant")
    f(jnp.ones((8, 8), jnp.int8), jnp.ones((8, 8)), jnp.ones((4, 8)))
    errs = _errors(_report("t.dequant"))
    assert len(errs) == 1 and "int8" in errs[0].message


def test_drift_origin_survives_literal_operands_into_branches(audit):
    """cond-style eqns mix Literal and array operands; the origin map
    must align POSITIONALLY onto the branch jaxpr's invars (filtering
    literals first shifted every origin onto the wrong inner operand)."""
    def fn(pred, x, w):
        return jax.lax.cond(
            pred,
            lambda a, b, c: a.astype(jnp.float32) @ b + c,
            lambda a, b, c: jnp.zeros((8, 8)) + c,
            x, w, 1.0)

    f = audit_jit(fn, site="t.branchdrift")
    f(jnp.asarray(True), jnp.ones((8, 8), jnp.bfloat16),
      jnp.ones((8, 8)))
    errs = _errors(_report("t.branchdrift"))
    assert len(errs) == 1 and "bfloat16" in errs[0].message


def test_callback_in_per_tick_site_is_error(audit):
    def fn(x):
        return x + jax.pure_callback(
            lambda v: np.asarray(v),
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    f = audit_jit(fn, site="t.cb",
                  xla_contract=SiteContract(per_tick=True))
    f(jnp.ones((4,)))
    errs = _errors(_report("t.cb"))
    assert len(errs) == 1
    assert "host-transfer" in errs[0].message
    assert "pure_callback" in errs[0].message and "eqn" in errs[0].message

    # outside a per-tick site the same eqn is informational
    g = audit_jit(fn, site="t.cb_info")
    g(jnp.ones((4,)))
    rep = _report("t.cb_info")
    assert _errors(rep) == []
    assert any(d.severity is Severity.INFO and "host-transfer"
               in d.message for d in rep.diagnostics)


def test_const_captured_weights_flagged(audit):
    weights = jnp.ones((256, 256))                 # 256 KiB const
    f = audit_jit(lambda x: x @ weights, site="t.const")
    f(jnp.ones((4, 256)))
    errs = _errors(_report("t.const"))
    assert len(errs) == 1
    msg = errs[0].message
    assert "const-capture" in msg and "(256, 256)" in msg

    # passed as an argument, the same math is clean
    g = audit_jit(lambda x, w: x @ w, site="t.const_ok")
    g(jnp.ones((4, 256)), weights)
    assert _errors(_report("t.const_ok")) == []


def test_collective_in_decode_site_is_error(audit):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("i",))

    def fn(x):
        return shard_map(lambda v: jax.lax.psum(v, "i"), mesh=mesh,
                         in_specs=P("i"), out_specs=P())(x)

    f = audit_jit(fn, site="t.coll",
                  xla_contract=SiteContract(per_tick=True))
    f(jnp.ones((4,)))
    errs = _errors(_report("t.coll"))
    assert len(errs) == 1
    assert "collective-placement" in errs[0].message
    assert "psum" in errs[0].message

    # where collectives are the point (ZeRO), the same eqn is INFO
    g = audit_jit(fn, site="t.coll_ok",
                  xla_contract=SiteContract(allow_collectives=True))
    g(jnp.ones((4,)))
    rep = _report("t.coll_ok")
    assert _errors(rep) == []
    assert any("collective-placement" in d.message
               for d in rep.diagnostics)


def test_busted_budget_flagged(audit):
    f = audit_jit(lambda x: x @ x, site="t.budget",
                  xla_contract=SiteContract(peak_bytes=64, flops=10.0))
    f(jnp.ones((8, 8)))
    errs = _errors(_report("t.budget"))
    assert len(errs) == 2                      # bytes AND flops busted
    assert all("budget" in d.message for d in errs)

    g = audit_jit(lambda x: x @ x, site="t.budget_ok",
                  xla_contract=SiteContract(peak_bytes=1 << 20,
                                            flops=1e9))
    g(jnp.ones((8, 8)))
    assert _errors(_report("t.budget_ok")) == []


def test_estimator_pins_exact_numbers(audit):
    f = audit_jit(lambda a, b: a @ b, site="t.est")
    f(jnp.ones((8, 8)), jnp.ones((8, 8)))
    rec = audit.sites["t.est"]
    closed = X.materialize_jaxpr(next(iter(rec.captured.values())))
    peak, flops = X.estimate_jaxpr(closed)
    assert flops == 2 * 8 * 8 * 8              # 2*M*N*K
    assert peak == 3 * 8 * 8 * 4               # two operands + result


def test_diagnostics_carry_the_grepable_tag(audit):
    f = audit_jit(lambda kv: kv + 1, site="t.tag",
                  xla_contract=SiteContract(donate=(0,)))
    f(jnp.ones((4,)))
    errs = _errors(_report("t.tag"))
    assert errs and all("XLA-AUDIT" in str(d) for d in errs)


# ---------------------------------------------------------------------------
# clean-run pins over the REAL sites
# ---------------------------------------------------------------------------


@pytest.mark.serving
def test_sealed_serving_steady_state_audits_clean(audit):
    """The acceptance pin: a sealed mixed steady-state run (int8 KV,
    prefix cache on) audits with zero ERROR diagnostics at every
    serving site, the donation contract is REQUESTED on CPU, and the
    sealed replay produced no RETRACE diagnostics."""
    old_bf16 = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        X.drive_serving_steady_state(kv_dtype="int8", seal=True)
    finally:
        FLAGS.use_bf16 = old_bf16
    reps = X.audit_sites()
    # ALL contract-bearing serving sites captured — incl. zero_pages,
    # whose scrub only runs on the poisoned-request fault path
    assert {"serving.step", "serving.fork_page",
            "serving.zero_pages"} <= set(reps)
    for name, rep in reps.items():
        assert _errors(rep) == [], \
            f"{name}: {[str(d) for d in _errors(rep)]}"
    # the step compiled one pair per prefill bucket seen (0, 4|8, 16)
    step = reps["serving.step"]
    assert step.signatures >= 2
    assert step.peak_bytes > 0 and step.flops > 0
    # donation is requested even though this run is on CPU
    assert 1 in audit.sites["serving.step"].jit_kwargs["donate_argnums"]
    assert audit.diagnostics == []             # sealed replay: 0 RETRACE


@pytest.mark.serving
def test_float32_pool_audits_clean_without_allowlist(audit):
    """An f32 pool needs no allow_upcast: the contract must not carry a
    stale int8 entry (the allowlist is derived from the actual pool
    dtype) and the audit stays clean."""
    eng = X.drive_serving_steady_state(kv_dtype="float32", seal=False)
    assert eng._step_contract.allow_upcast == ()
    reps = X.audit_sites(sites=["serving.step"])
    assert _errors(reps["serving.step"]) == []


def test_trainer_step_audits_clean(audit):
    """One real train pass: trainer.train_step audits clean, with the
    (0, 1, 2) donation contract requested and verified."""
    X.drive_trainer_step()
    rep = _report("trainer.train_step")
    assert _errors(rep) == [], [str(d) for d in _errors(rep)]
    rec = auditor().sites["trainer.train_step"]
    assert rec.jit_kwargs["donate_argnums"] == (0, 1, 2)
    assert rec.contract is not None and rec.contract.donate == (0, 1, 2)


def test_trainer_step_with_dropped_donation_is_caught(audit):
    """The failure the rule exists for: donation silently dropped from
    the jit kwargs while the contract still declares it."""
    X.drive_trainer_step(batches=1, batch_size=8)
    rec = auditor().sites["trainer.train_step"]
    for cap in rec.captured.values():          # simulate the drop
        cap.jit_kwargs = {}
    rep = X.audit_record("trainer.train_step", rec)
    errs = _errors(rep)
    assert len(errs) == 3                      # args 0, 1, 2
    assert all("donation-contract" in d.message for d in errs)


# ---------------------------------------------------------------------------
# obs satellite: compile counts on the scrape surface
# ---------------------------------------------------------------------------


@pytest.mark.serving
@pytest.mark.obs
def test_compile_counts_published_to_registry(audit):
    from paddle_tpu.serving import DecoderLM, ServingEngine

    model = DecoderLM(vocab_size=32, num_layers=1, num_heads=2,
                      head_dim=8, max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=1, page_size=4,
                        num_pages=16, max_pages_per_seq=4, max_slots=2,
                        buckets=(4, 8), prefill_chunk=0)
    eng.submit([3, 4, 5], max_tokens=4)
    eng.run(max_ticks=50)
    snap = eng.healthz()["metrics"]
    key = "jit_compiles_total{site=serving.step}"
    assert key in snap and snap[key] >= 1
    assert snap["jit_calls_total{site=serving.step}"] >= snap[key]
    # Prometheus exposition carries the same series
    assert 'jit_compiles_total{site="serving.step"}' \
        in eng.registry.to_text()


# ---------------------------------------------------------------------------
# lint satellite: block_until_ready is a host sync
# ---------------------------------------------------------------------------


def test_lint_flags_block_until_ready_method_and_function():
    src = "def f(x):\n    x.block_until_ready()\n"
    for d in ("serving", "obs", "platform"):
        out = lint_source(src, f"paddle_tpu/{d}/bad.py",
                          rules=["host-sync"])
        assert len(out) == 1 and out[0].code == "host-sync", d
    fn_form = "import jax\n\ndef f(x):\n    jax.block_until_ready(x)\n"
    out = lint_source(fn_form, "paddle_tpu/platform/bad.py",
                      rules=["host-sync"])
    assert len(out) == 1
    # outside the covered layers the rule does not apply
    assert lint_source(src, "paddle_tpu/models/x.py",
                       rules=["host-sync"]) == []
    # ...and the escape hatch works (stats.py's timing sync)
    allowed = ("def f(x):\n"
               "    x.block_until_ready()  # lint: allow(host-sync)\n")
    assert lint_source(allowed, "paddle_tpu/platform/stats2.py",
                       rules=["host-sync"]) == []


def test_stats_timer_block_records_honest_window():
    from paddle_tpu.platform.stats import StatSet

    ss = StatSet()
    out = {}
    with ss.timer("step", block=lambda: out["y"]):
        out["y"] = jnp.ones((64, 64)) @ jnp.ones((64, 64))
    e = ss.get("step")
    assert e is not None and e.count == 1 and e.total > 0.0
    # direct-value form works too
    arr = jnp.ones((8,))
    with ss.timer("step", block=arr):
        arr = arr + 1
    assert ss.get("step").count == 2


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def test_cli_rejects_unknown_rule():
    from paddle_tpu.analysis.cli import main

    assert main(["xla", "--rule", "nope"]) == 2


def test_audit_sites_skips_uncaptured(audit):
    # a site wrapped but never called has nothing to audit
    audit_jit(lambda x: x, site="t.never")
    assert "t.never" not in X.audit_sites()


def test_two_wrappers_one_site_audit_through_own_closures(audit):
    """Two engines sharing a site name wrap DIFFERENT closures; each
    captured signature must replay through the closure that traced it
    (a site-level fn would shape-crash or silently cross-audit)."""
    n1, n2 = 4, 7

    f1 = audit_jit(lambda x: x[:n1] * 2, site="t.shared",
                   xla_contract=SiteContract(flops=1e6))
    f2 = audit_jit(lambda x: x[:n2] * 2, site="t.shared",
                   xla_contract=SiteContract(flops=0.5))
    f1(jnp.ones((n1,)))
    f2(jnp.ones((n2,)))
    rep = _report("t.shared")
    assert rep.signatures == 2              # both materialized fine
    errs = _errors(rep)
    # only the second wrap's busted budget fires — contracts are
    # per-capture, not last-wrap-wins
    assert len(errs) == 1 and "budget" in errs[0].message


@pytest.mark.serving
@pytest.mark.obs
def test_compile_counts_published_unlabeled(audit):
    """The auditor is process-global, so its gauges publish WITHOUT
    per-engine labels — a replica must not appear to own the whole
    fleet's compiles."""
    from paddle_tpu.serving import DecoderLM, ServingEngine

    model = DecoderLM(vocab_size=32, num_layers=1, num_heads=2,
                      head_dim=8, max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=1, page_size=4,
                        num_pages=16, max_pages_per_seq=4, max_slots=2,
                        buckets=(4, 8), prefill_chunk=0)
    eng.set_registry(eng.registry, replica="3")
    eng.submit([3, 4, 5], max_tokens=4)
    eng.run(max_ticks=50)
    snap = eng.healthz()["metrics"]
    assert "jit_compiles_total{site=serving.step}" in snap
    # match the label SYNTAX, not the bare substring: the site name
    # "zero.replicate" (whose record legitimately persists across an
    # in-place auditor reset) must not trip the replica-label check
    assert not any("jit_compiles_total" in k and "replica=" in k
                   for k in snap)


def test_stats_timer_block_never_masks_the_real_error():
    """timer(block=) must not evaluate block() when the timed body
    raised — the result usually doesn't exist, and a KeyError from the
    finally clause would mask the real failure."""
    from paddle_tpu.platform.stats import StatSet

    ss = StatSet()
    out = {}
    with pytest.raises(RuntimeError, match="the real error"):
        with ss.timer("step", block=lambda: out["y"]):
            raise RuntimeError("the real error")
    assert ss.get("step").count == 1        # window still recorded
