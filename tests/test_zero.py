"""ZeRO-1 shard-plan edge cases and checkpoint layout independence.

Companion to tests/test_dp_parity.py::test_zero1_matches_zero0 (trajectory
parity + the 8x state reduction); here: the per-tensor plan on scalar /
non-divisible shapes, precedence passthrough, and the stage-crossing
checkpoint round trips the plan's gather/scatter guarantees.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import checkpoint as ckpt
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.zero import build_zero_plan


@pytest.fixture
def mesh():
    return make_mesh((8,), ("data",))


# ---------------------------------------------------------------------------
# shard plan
# ---------------------------------------------------------------------------


def test_shard_plan_edge_shapes_roundtrip(mesh):
    """Scalars, non-divisible shapes (padding), and exactly-divisible
    shapes all survive shard_tree -> gather_tree bit-exactly."""
    import jax.numpy as jnp

    params = {
        "scalar": jnp.asarray(3.5),                       # size 1 -> pad 8
        "odd": jnp.arange(15, dtype=jnp.float32).reshape(3, 5),  # pad 16
        "exact": jnp.arange(16, dtype=jnp.float32),       # no padding
        "big": jnp.asarray(np.random.RandomState(0).randn(7, 9)
                           .astype(np.float32)),          # 63 -> pad 64
    }
    plan = build_zero_plan(mesh, params)
    assert plan.entries["scalar"].padded == 8
    assert plan.entries["odd"].padded == 16
    assert plan.entries["exact"].padded == 16
    assert plan.entries["big"].padded == 64
    flat = plan.shard_tree(params)
    for name, v in flat.items():
        assert v.shape == (plan.entries[name].padded,), name
        # physically sharded: 1/8 of the padded flat size per device
        assert np.prod(v.sharding.shard_shape(v.shape)) == v.size // 8, name
    back = plan.gather_tree(flat)
    for name in params:
        np.testing.assert_array_equal(np.asarray(back[name]),
                                      np.asarray(params[name]), err_msg=name)


def test_shard_plan_respects_declared_sharding_and_static(mesh):
    """ParamAttr.sharding precedence and static params pass through: their
    state keeps the declared layout instead of the flat 1/N view."""
    import jax.numpy as jnp

    from paddle_tpu.attr import ParamAttr
    from paddle_tpu.topology import ParamSpec

    params = {"plain": jnp.zeros((16, 8)), "placed": jnp.zeros((16, 8)),
              "frozen": jnp.zeros((16, 8))}
    specs = {
        "placed": ParamSpec(shape=(16, 8),
                            attr=ParamAttr(sharding=("data", None))),
        "frozen": ParamSpec(shape=(16, 8), attr=ParamAttr(is_static=True)),
    }
    plan = build_zero_plan(mesh, params, specs=specs)
    assert plan.is_sharded("plain")
    assert not plan.is_sharded("placed")
    assert not plan.is_sharded("frozen")


def test_reused_optimizer_does_not_leak_plan(mesh):
    """An optimizer instance reused across trainers must not carry the
    previous trainer's shard plan: the second (zero=0) trainer clears it
    and its slots come out full-shape replicated."""
    cost = _build()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=7)
    opt = optimizer.Momentum(momentum=0.9, learning_rate=0.05)
    trainer.SGD(cost=cost, parameters=params, update_equation=opt,
                mesh=mesh, zero=1)
    assert opt._zero_plan is not None
    cost2 = _build()
    params2 = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost2]), seed=7)
    sgd2 = trainer.SGD(cost=cost2, parameters=params2, update_equation=opt,
                       mesh=mesh, zero=0)
    assert opt._zero_plan is None
    for slot in sgd2.opt_state["slots"].values():
        for name, arr in slot.items():
            assert arr.shape == np.asarray(params2[name]).shape, name


# ---------------------------------------------------------------------------
# checkpoint round trips
# ---------------------------------------------------------------------------


def _build():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(16))
    y = layer.data(name="y", type=paddle.data_type.integer_value(4))
    h = layer.fc(input=x, size=30, act="relu")  # 30-wide bias: pad path
    return layer.classification_cost(input=layer.fc(input=h, size=4), label=y)


def _batches(seed, n_batches=3, batch=32):
    r = np.random.RandomState(seed)
    return [[(r.randn(16).astype(np.float32), int(r.randint(4)))
             for _ in range(batch)] for _ in range(n_batches)]


def _make(zero):
    cost = _build()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=7)
    return trainer.SGD(cost=cost, parameters=params,
                       update_equation=optimizer.Adam(learning_rate=1e-2),
                       mesh=make_mesh((8,), ("data",)), zero=zero)


def _run(sgd, batches):
    sgd.train(lambda: iter(batches), num_passes=1,
              event_handler=lambda ev: None)


@pytest.mark.resilience
@pytest.mark.parametrize("z_save,z_load", [(1, 0), (0, 1)],
                         ids=["zero1_to_zero0", "zero0_to_zero1"])
def test_step_cursor_resume_across_zero_stages(tmp_path, z_save, z_load):
    """Cross-layout resume under chaos, STEP-granular: a zero_stage=z
    run is killed MID-PASS between step checkpoints; a trainer under the
    OTHER zero stage resumes via the cursor (pass, step-in-pass, rng)
    and the post-resume loss trajectory + final params match the
    replicated run that never died — the layout-independence guarantee
    extended from pass boundaries to arbitrary step cuts."""
    from paddle_tpu.resilience import InjectedTrainerDeath, TrainFaultPlan

    batches = _batches(0, n_batches=6)
    costs_ref, costs_b = [], []

    def recorder(out):
        def handler(ev):
            if isinstance(ev, paddle.event.EndIteration):
                out.append((ev.batch_id, float(ev.cost)))
        return handler

    ref = _make(0)
    ref.train(lambda: iter(batches), num_passes=1,
              event_handler=recorder(costs_ref))

    save = str(tmp_path / "ck")
    a = _make(z_save)
    a._faults = TrainFaultPlan(kill_at={4})
    with pytest.raises(InjectedTrainerDeath):
        # checkpoints after steps 2 and 4; the kill fires BEFORE step 4
        # runs, so the newest durable cursor is (pass 0, step 4)... the
        # save after step 3 (save_period_steps=2 -> after b1, b3)
        a.train(lambda: iter(batches), num_passes=1, save_dir=save,
                save_period_steps=2, resume=True, async_save=False)

    b = _make(z_load)
    b.train(lambda: iter(batches), num_passes=1, save_dir=save,
            save_period_steps=2, resume=True, async_save=False,
            event_handler=recorder(costs_b))
    # post-resume trajectory: b re-ran exactly steps 4 and 5
    assert [bid for bid, _ in costs_b] == [4, 5]
    ref_tail = dict(costs_ref)
    for bid, c in costs_b:
        np.testing.assert_allclose(c, ref_tail[bid], rtol=1e-6, atol=1e-8,
                                   err_msg=f"loss at step {bid}")
    for k in ref.parameters.names():
        np.testing.assert_allclose(np.asarray(b.parameters[k]),
                                   np.asarray(ref.parameters[k]),
                                   rtol=1e-6, atol=1e-8, err_msg=k)


@pytest.mark.parametrize("z_save,z_load", [(1, 0), (0, 1), (1, 1)],
                         ids=["zero1_to_zero0", "zero0_to_zero1",
                              "zero1_to_zero1"])
def test_checkpoint_roundtrip_across_zero_stages(tmp_path, z_save, z_load):
    """Checkpoints are layout-independent: save under one zero stage, load
    under another, and the continued trajectory is bit-identical to the
    replicated run that never checkpointed."""
    first, second = _batches(0), _batches(1)
    ref = _make(0)
    _run(ref, first)
    _run(ref, second)

    a = _make(z_save)
    _run(a, first)
    a.save_checkpoint(str(tmp_path), 0)
    # the artifact itself must hold FULL tensor shapes, not flat shards
    _, st, _, _ = ckpt.load_checkpoint(str(tmp_path), 0)
    for slot in st["slots"].values():
        for name, arr in slot.items():
            assert arr.shape == np.asarray(a.parameters[name]).shape, name

    b = _make(z_load)
    b.load_checkpoint(str(tmp_path), 0)
    _run(b, second)
    for k in ref.parameters.names():
        np.testing.assert_allclose(np.asarray(b.parameters[k]),
                                   np.asarray(ref.parameters[k]),
                                   rtol=1e-6, atol=1e-8, err_msg=k)
