"""Static GSPMD sharding-propagation auditor (paddle_tpu.analysis.
sharding): one seeded-bad jaxpr per rule class — declared/inferred spec
mismatch, one-side-sharded contraction, accidental replication of
weight-shaped consts/args, mesh-axis double consumption, busted
collective budget — plus exact closed-form pins for the 2112.09017
cost model (reduce-scatter/all-gather pair, psum-at-output, the ZeRO
placement all-gather on virtual-8), clean-run pins over the real zero
placement / mesh+ZeRO train step / sealed serving.step (f32 AND int8
pools), the pipeline/MoE stub-contract notices, and the
``comm_bytes_total`` registry publish.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.analysis import sharding as S
from paddle_tpu.analysis.diagnostics import Severity
from paddle_tpu.analysis.retrace import (SiteContract, audit_jit, auditor,
                                         declare_site)
from paddle_tpu.platform.flags import FLAGS

pytestmark = [pytest.mark.shard, pytest.mark.analysis]

AX8 = (("data", 8),)


@pytest.fixture
def audit():
    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    auditor().reset()
    yield auditor()
    FLAGS.jit_audit = old
    auditor().reset()


@pytest.fixture
def mesh8():
    return Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))


def _report(site, rules=None):
    reps = S.audit_sharding_sites(sites=[site], rules=rules)
    assert site in reps, f"site {site} captured nothing"
    return reps[site]


def _errors(rep):
    return [d for d in rep.diagnostics if d.severity is Severity.ERROR]


# ---------------------------------------------------------------------------
# spec plumbing
# ---------------------------------------------------------------------------


def test_normalize_spec_accepts_partition_spec_and_tuples():
    assert S.normalize_spec(P("data", None)) == ("data", None)
    assert S.normalize_spec(("data",)) == ("data",)
    assert S.normalize_spec(()) == ()
    assert S.normalize_spec(None) is None
    assert S.normalize_spec((("x", "y"), None)) == ("x", None)


def test_apply_spec_divisibility_and_ndim_fall_back_to_replicated():
    axes = dict(AX8)
    vs, probs = S.apply_spec(("data",), (16, 4), axes)
    assert vs.dims == ("data", None) and probs == []
    # non-divisible leading dim: replicated, no error (the broadcast-
    # over-leaves semantics — optimizer scalars must not explode)
    vs, probs = S.apply_spec(("data",), (15, 4), axes)
    assert vs.dims == (None, None) and probs == []
    vs, probs = S.apply_spec(("data",), (), axes)
    assert vs.dims == () and probs == []
    # unknown axis IS a contract error
    _, probs = S.apply_spec(("model",), (16,), axes)
    assert probs and probs[0][0] == "contract-mismatch"
    # one axis for two dims IS a collision
    _, probs = S.apply_spec(("data", "data"), (16, 16), axes)
    assert probs and probs[0][0] == "axis-collision"


# ---------------------------------------------------------------------------
# seeded-bad jaxprs, one per rule class
# ---------------------------------------------------------------------------


def test_contract_mismatch_flagged(audit):
    f = audit_jit(lambda x: x * 2, site="t.mismatch",
                  xla_contract=SiteContract(in_specs=(("data",),),
                                            out_specs=((),),
                                            mesh_axes=AX8))
    f(jnp.ones((16, 4)))
    errs = _errors(_report("t.mismatch"))
    assert len(errs) == 1
    msg = errs[0].message
    assert "contract-mismatch" in msg and "t.mismatch" in msg
    assert "SHARD-AUDIT" in str(errs[0])


def test_contract_mismatch_on_unknown_mesh_axis(audit):
    f = audit_jit(lambda x: x + 1, site="t.badaxis",
                  xla_contract=SiteContract(in_specs=(("model",),),
                                            mesh_axes=AX8))
    f(jnp.ones((16,)))
    errs = _errors(_report("t.badaxis"))
    assert len(errs) == 1 and "mesh_axes" in errs[0].message


def test_implicit_all_gather_on_one_side_sharded_contraction(audit):
    f = audit_jit(lambda x, w: x @ w, site="t.gather",
                  xla_contract=SiteContract(
                      in_specs=((None, "data"), ()), mesh_axes=AX8))
    f(jnp.ones((4, 16)), jnp.ones((16, 4)))
    rep = _report("t.gather")
    errs = _errors(rep)
    assert len(errs) == 1
    msg = errs[0].message
    assert "implicit-all-gather" in msg and "eqn" in msg
    assert "dot_general" in msg and "t.gather" in msg
    # the materialized bytes ride the message AND the comm estimate:
    # 4*16*4 = 256 bytes, all-gather cost 256 * 7/8 = 224
    assert "224" in msg
    assert rep.comm_bytes == 224.0


def test_implicit_all_gather_on_conflicting_elementwise(audit):
    f = audit_jit(lambda a, b: a + b, site="t.conflict",
                  xla_contract=SiteContract(
                      in_specs=(("data", None), (None, "data")),
                      mesh_axes=AX8))
    f(jnp.ones((8, 8)), jnp.ones((8, 8)))
    errs = _errors(_report("t.conflict"))
    assert len(errs) == 1 and "implicit-all-gather" in errs[0].message


def test_implicit_all_gather_on_sharded_reshape_split(audit):
    f = audit_jit(lambda x: x.reshape(4, 4, 8), site="t.reshape",
                  xla_contract=SiteContract(in_specs=(("data",),),
                                            mesh_axes=AX8))
    f(jnp.ones((16, 8)))
    errs = _errors(_report("t.reshape"))
    assert len(errs) == 1
    assert "implicit-all-gather" in errs[0].message
    assert "reshape" in errs[0].message


def test_accidental_replication_expect_sharded(audit):
    f = audit_jit(lambda x: x + 1, site="t.repl",
                  xla_contract=SiteContract(in_specs=((),),
                                            expect_sharded=(0,),
                                            mesh_axes=AX8))
    f(jnp.ones((16,)))
    errs = _errors(_report("t.repl"))
    assert len(errs) == 1
    assert "accidental-replication" in errs[0].message


def test_accidental_replication_weight_shaped_const(audit):
    weights = jnp.ones((512, 512))                 # 1 MiB const
    f = audit_jit(lambda x: x @ weights, site="t.const",
                  xla_contract=SiteContract(
                      in_specs=(("data", None),), mesh_axes=AX8,
                      big_arg_bytes=65536))
    f(jnp.ones((16, 512)))
    errs = _errors(_report("t.const"))
    assert any("accidental-replication" in d.message
               and "const" in d.message for d in errs)
    # the same const in a site that shards NOTHING is not a finding
    # (the xla const-capture rule owns the plain capture case)
    g = audit_jit(lambda x: x @ weights, site="t.const_ok",
                  xla_contract=SiteContract(in_specs=((),),
                                            big_arg_bytes=65536))
    g(jnp.ones((16, 512)))
    assert not any("accidental-replication" in d.message
                   for d in _report("t.const_ok").diagnostics)


def test_axis_collision_in_contraction(audit):
    f = audit_jit(lambda x, y: x @ y, site="t.collide",
                  xla_contract=SiteContract(
                      in_specs=(("data", None), (None, "data")),
                      mesh_axes=AX8))
    f(jnp.ones((8, 4)), jnp.ones((4, 8)))
    errs = _errors(_report("t.collide"))
    assert len(errs) == 1
    msg = errs[0].message
    assert "axis-collision" in msg and "eqn" in msg and "data" in msg


def test_axis_collision_in_declared_spec(audit):
    f = audit_jit(lambda x: x + 1, site="t.dupspec",
                  xla_contract=SiteContract(in_specs=(("data", "data"),),
                                            mesh_axes=AX8))
    f(jnp.ones((16, 16)))
    errs = _errors(_report("t.dupspec"))
    assert len(errs) == 1 and "axis-collision" in errs[0].message


def test_comm_budget_busted_and_within(audit, mesh8):
    flat = NamedSharding(mesh8, P("data"))

    def fn(x):
        return jax.lax.with_sharding_constraint(x, flat)

    # replicated -> sharded is a free slice; sharded -> replicated on
    # the way OUT via out_shardings costs the all-gather
    f = audit_jit(fn, site="t.commbust",
                  out_shardings=NamedSharding(mesh8, P()),
                  xla_contract=SiteContract(
                      allow_collectives=True, in_specs=((),),
                      mesh_axes=AX8, comm_bytes=10.0))
    f(jnp.ones((64,)))
    errs = _errors(_report("t.commbust"))
    assert len(errs) == 1
    assert "comm-budget" in errs[0].message
    assert "exceed" in errs[0].message

    g = audit_jit(fn, site="t.commok",
                  out_shardings=NamedSharding(mesh8, P()),
                  xla_contract=SiteContract(
                      allow_collectives=True, in_specs=((),),
                      mesh_axes=AX8, comm_bytes=1000.0))
    g(jnp.ones((64,)))
    rep = _report("t.commok")
    assert _errors(rep) == []
    assert any("within the declared" in d.message
               for d in rep.diagnostics)


def test_rule_restriction_filters_findings(audit):
    f = audit_jit(lambda x: x * 2, site="t.filter",
                  xla_contract=SiteContract(in_specs=(("data",),),
                                            out_specs=((),),
                                            mesh_axes=AX8))
    f(jnp.ones((16,)))
    rep = _report("t.filter", rules=["axis-collision"])
    assert rep.diagnostics == []           # mismatch filtered out
    rep = _report("t.filter", rules=["contract-mismatch"])
    assert len(_errors(rep)) == 1


# ---------------------------------------------------------------------------
# collective cost model: exact closed-form pins (virtual-8)
# ---------------------------------------------------------------------------


def test_cost_model_closed_forms():
    assert S.all_gather_bytes(256, 8) == 224.0       # b*(n-1)/n
    assert S.reduce_scatter_bytes(256, 8) == 224.0
    assert S.all_reduce_bytes(256, 8) == 448.0       # 2*b*(n-1)/n
    assert S.all_to_all_bytes(256, 8) == 224.0


def test_zero_rs_ag_pair_pinned_to_closed_form(audit, mesh8):
    """THE ZeRO shape: grad contraction over the sharded batch dim
    (partial sums) -> flat constraint (reduce-scatter) -> elementwise
    update -> replicated constraint (all-gather).  64 floats = 256
    bytes; rs + ag = 2 * 256 * 7/8 = 448 exactly."""
    flat = NamedSharding(mesh8, P("data"))
    repl = NamedSharding(mesh8, P())

    def zero_like_step(x, m):
        g = x.T @ x                                   # [8,8] partials
        gf = jax.lax.with_sharding_constraint(g.reshape(-1), flat)
        m2 = 0.9 * m + gf
        w = jax.lax.with_sharding_constraint(m2 * 0.1, repl)
        return w, m2

    f = audit_jit(zero_like_step, site="t.zero",
                  xla_contract=SiteContract(
                      allow_collectives=True,
                      in_specs=(("data",), ("data",)),
                      mesh_axes=AX8, comm_bytes=1000.0))
    f(jnp.ones((16, 8)), jnp.zeros((64,)))
    rep = _report("t.zero")
    assert _errors(rep) == []
    assert rep.comm_bytes == 448.0


def test_pending_psum_materializes_at_output(audit):
    """A partial sum that reaches the outputs un-constrained is a full
    all-reduce: 2 * 256 * 7/8 = 448 (the replicated-DP grad psum)."""
    f = audit_jit(lambda x: x.T @ x, site="t.psum",
                  xla_contract=SiteContract(
                      allow_collectives=True, in_specs=(("data",),),
                      mesh_axes=AX8))
    f(jnp.ones((16, 8)))
    assert _report("t.psum").comm_bytes == 448.0


# ---------------------------------------------------------------------------
# clean-run pins over the REAL sites
# ---------------------------------------------------------------------------


def test_zero_placement_compiles_and_audits_clean(audit):
    """The gather-on-save / re-place paths go through the compiled
    zero.replicate / zero.reshard identities on virtual-8 and audit
    with zero ERRORs; the replicate all-gather is pinned to the closed
    form (w: 64 floats -> 256 bytes * 7/8 = 224)."""
    plan = S.drive_zero_placement()
    assert plan is not None
    reps = S.audit_sharding_sites()
    assert {"zero.replicate", "zero.reshard"} <= set(reps)
    for name in ("zero.replicate", "zero.reshard"):
        assert _errors(reps[name]) == [], name
    assert reps["zero.replicate"].comm_bytes == 224.0
    assert reps["zero.reshard"].comm_bytes == 0.0     # free local slice
    assert auditor().compile_count("zero.replicate") >= 1
    assert auditor().compile_count("zero.reshard") >= 1


def test_zero_place_flat_handles_off_mesh_committed_arrays(audit):
    """A flat state tensor committed to ONE device (a checkpoint
    staging buffer) must not crash the compiled-reshard fast path with
    'incompatible devices' — off-mesh arrays take the host placement
    path, mesh-resident ones the compiled identity."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.zero import _identity_jit, build_zero_plan

    _identity_jit.cache_clear()    # earlier tests' cached wrappers
    devs = jax.devices()
    mesh = make_mesh((8,), ("data",), devs[:8])
    plan = build_zero_plan(mesh, {"w": np.zeros((8, 8), np.float32)})
    e = plan.entries["w"]
    staged = jax.device_put(jnp.ones((e.padded,)), devs[3])
    placed = plan.place_flat("w", staged)            # must not raise
    assert placed.shape == (e.padded,)
    np.testing.assert_allclose(np.asarray(placed), 1.0)
    # and a mesh-resident flat array still rides the compiled reshard
    plan.place_flat("w", placed)
    assert auditor().compile_count("zero.reshard") >= 1


def test_mesh_zero_train_step_audits_clean(audit, mesh8):
    """One real ZeRO train pass on virtual-8: the sharding walk sees
    the grad partial sums turn into reduce-scatters and the weight
    gather into all-gathers, with ZERO error findings and the comm
    estimate pinned to the closed form: 7 bytes/padded-element over
    rs+ag (2 * 4 * 7/8) for the 200 padded params, plus the 7-byte
    loss-scalar psum."""
    import paddle_tpu as paddle
    from paddle_tpu import layer, optimizer, trainer as trainer_mod

    x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = layer.data(name="y", type=paddle.data_type.integer_value(3))
    h = layer.fc(x, size=16, act="relu")
    logits = layer.fc(h, size=3)
    cost = layer.classification_cost(input=logits, label=y)
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=0)
    sgd = trainer_mod.SGD(cost=cost, parameters=params, mesh=mesh8,
                          zero=1, update_equation=optimizer.Momentum(
                              momentum=0.9, learning_rate=0.05))
    rng = np.random.RandomState(0)
    data = [(rng.randn(8).astype(np.float32) * 0.1,
             int(rng.randint(0, 3))) for _ in range(32)]
    sgd.train(paddle.batch(lambda: iter(data), 16), num_passes=1)
    rep = _report("trainer.train_step")
    assert _errors(rep) == [], [str(d) for d in _errors(rep)]
    padded = sum(e.padded for e in sgd._zero_plan.entries.values())
    assert padded == 200                     # 128 + 16 + 48 + pad(3->8)
    assert rep.comm_bytes == 7.0 * padded + 7.0
    # the estimate lands under the trainer's derived budget
    contract = auditor().sites["trainer.train_step"].contract
    assert contract.comm_bytes is not None
    assert rep.comm_bytes <= contract.comm_bytes


@pytest.mark.serving
def test_sealed_serving_step_audits_clean_int8(audit):
    """The acceptance pin: the sealed mixed steady state (int8 KV,
    prefix cache, COW fork, poison scrub) audits with zero ERRORs at
    every serving site and ZERO estimated collective bytes — the
    explicit replicated baseline contract the TP PR will flip."""
    from paddle_tpu.analysis.xla import drive_serving_steady_state

    old_bf16 = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        drive_serving_steady_state(kv_dtype="int8", seal=True)
    finally:
        FLAGS.use_bf16 = old_bf16
    reps = S.audit_sharding_sites()
    assert {"serving.step", "serving.fork_page",
            "serving.zero_pages"} <= set(reps)
    for name, rep in reps.items():
        assert _errors(rep) == [], \
            f"{name}: {[str(d) for d in _errors(rep)]}"
        assert rep.comm_bytes == 0.0, name
    assert auditor().diagnostics == []       # sealed replay: 0 RETRACE


@pytest.mark.serving
def test_serving_step_audits_clean_f32(audit):
    """Same pin on a float32 pool (shorter unsealed drive)."""
    from paddle_tpu.serving import DecoderLM, ServingEngine

    model = DecoderLM(vocab_size=32, num_layers=1, num_heads=2,
                      head_dim=8, max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=1, page_size=4,
                        num_pages=16, max_pages_per_seq=4, max_slots=2,
                        buckets=(4, 8), prefill_chunk=4,
                        kv_dtype="float32")
    eng.submit([3, 4, 5, 6, 7], max_tokens=4)
    eng.run(max_ticks=50)
    rep = _report("serving.step")
    assert _errors(rep) == []
    assert rep.comm_bytes == 0.0
    contract = auditor().sites["serving.step"].contract
    assert contract.comm_bytes == 0.0        # the derived baseline


# ---------------------------------------------------------------------------
# pipeline / MoE: REAL closed-form contracts + audited capture
# ---------------------------------------------------------------------------


def test_real_contracts_budget_equals_estimate(audit, mesh8):
    """The stub contracts are gone: pipeline and MoE declare closed-form
    comm budgets computed at wrap time from the dispatch geometry, and
    the budget EQUALS the audited estimate — any extra collective that
    sneaks into either program trips the comm-budget rule."""
    from paddle_tpu.parallel import moe as pmoe
    from paddle_tpu.parallel import pipeline as ppipe
    from paddle_tpu.parallel.mesh import make_mesh

    assert not hasattr(ppipe, "stub_contract")
    assert not hasattr(pmoe, "stub_contract")

    mesh = make_mesh((4,), ("stage",), jax.devices()[:4])
    p = [{"w": jnp.eye(4) * (i + 1)} for i in range(4)]
    stacked = ppipe.stack_stage_params(p, mesh, "stage")
    ppipe.pipeline_apply(mesh, lambda prm, x: x @ prm["w"], stacked,
                         jnp.ones((3, 2, 4)))
    rep = _report("parallel.pipeline")
    assert _errors(rep) == []
    contract = auditor().sites["parallel.pipeline"].contract
    assert contract.comm_bytes == rep.comm_bytes > 0

    x = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)
    params = pmoe.init_moe_params(jax.random.PRNGKey(0), 8, 16, 8)
    pmoe.moe_ffn(mesh8, x, params, axis="data", capacity_factor=8.0,
                 top_k=2, return_stats=True)
    rep = _report("parallel.moe")
    assert _errors(rep) == []
    contract = auditor().sites["parallel.moe"].contract
    assert contract.comm_bytes == rep.comm_bytes > 0


def test_pipeline_capture_audits_with_collective_costs(audit, mesh8):
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline import pipeline_apply

    mesh = make_mesh((4,), ("stage",), jax.devices()[:4])
    p = [{"w": jnp.eye(4) * (i + 1)} for i in range(4)]
    from paddle_tpu.parallel.pipeline import stack_stage_params

    stacked = stack_stage_params(p, mesh, "stage")
    mbs = jnp.ones((3, 2, 4))
    out = pipeline_apply(mesh, lambda prm, x: x @ prm["w"], stacked, mbs)
    assert out.shape == (3, 2, 4)
    rep = _report("parallel.pipeline")
    assert _errors(rep) == []                # allow_collectives stub
    assert rep.comm_bytes > 0                # ppermute/psum hops costed


def test_moe_capture_audits_clean(audit, mesh8):
    from paddle_tpu.parallel.moe import init_moe_params, moe_ffn

    x = jnp.asarray(np.random.RandomState(0).randn(16, 8),
                    jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), 8, 16, 8)
    y, aux = moe_ffn(mesh8, x, params, axis="data", capacity_factor=8.0)
    assert y.shape == (16, 8)
    rep = _report("parallel.moe")
    assert _errors(rep) == []
    assert rep.comm_bytes > 0                # the two all_to_alls


# ---------------------------------------------------------------------------
# obs satellite: comm bytes on the scrape surface
# ---------------------------------------------------------------------------


def test_comm_bytes_published_to_registry(audit):
    from paddle_tpu.obs.registry import MetricsRegistry

    f = audit_jit(lambda x: x.T @ x, site="t.pub",
                  xla_contract=SiteContract(
                      allow_collectives=True, in_specs=(("data",),),
                      mesh_axes=AX8))
    f(jnp.ones((16, 8)))
    S.audit_sharding_sites()                 # stamps rec.comm_bytes
    reg = MetricsRegistry()
    auditor().publish(reg)
    snap = reg.snapshot()
    assert snap["comm_bytes_total{site=t.pub}"] == 448.0
    # the gauge is lazy: a fresh auditor with no audit publishes none
    auditor().reset()
    f(jnp.ones((16, 8)))
    reg2 = MetricsRegistry()
    auditor().publish(reg2)
    assert not any("comm_bytes_total" in k for k in reg2.snapshot())


@pytest.mark.serving
@pytest.mark.obs
def test_comm_bytes_rides_engine_healthz(audit):
    from paddle_tpu.serving import DecoderLM, ServingEngine

    model = DecoderLM(vocab_size=32, num_layers=1, num_heads=2,
                      head_dim=8, max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=1, page_size=4,
                        num_pages=16, max_pages_per_seq=4, max_slots=2,
                        buckets=(4, 8), prefill_chunk=0)
    eng.submit([3, 4, 5], max_tokens=4)
    eng.run(max_ticks=50)
    S.audit_sharding_sites(sites=["serving.step"])
    snap = eng.healthz()["metrics"]
    assert snap["comm_bytes_total{site=serving.step}"] == 0.0


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


def test_cli_rejects_unknown_rule():
    from paddle_tpu.analysis.cli import main

    assert main(["sharding", "--rule", "nope"]) == 2


def test_audit_skips_uncaptured_sites(audit):
    audit_jit(lambda x: x, site="t.never",
              xla_contract=SiteContract(in_specs=((),)))
    assert "t.never" not in S.audit_sharding_sites()


def test_reset_clears_comm_stamp(audit):
    f = audit_jit(lambda x: x.T @ x, site="t.stamp",
                  xla_contract=SiteContract(
                      allow_collectives=True, in_specs=(("data",),),
                      mesh_axes=AX8))
    f(jnp.ones((16, 8)))
    S.audit_sharding_sites()
    rec = auditor().sites["t.stamp"]
    assert rec.comm_bytes == 448.0
    auditor().reset()
    assert rec.comm_bytes is None
