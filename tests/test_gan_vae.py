"""GAN / VAE training tests.

Reference analog: v1_api_demo/gan/gan_trainer.py (alternating two-network
training) and v1_api_demo/vae/vae_train.py.
"""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer, trainer
from paddle_tpu.models import gan, vae


def test_gan_alternating_training(rng):
    paddle.topology.reset_name_scope()
    noise_dim, data_dim = 8, 2
    noise, real, fake, d_cost, g_cost = gan.build(
        noise_dim=noise_dim, data_dim=data_dim,
        gen_dims=(16,), dis_dims=(16,))
    # one shared parameter store spanning both graphs
    topo_all = paddle.topology.Topology([d_cost, g_cost])
    params = paddle.Parameters.from_topology(topo_all, seed=0)

    t = trainer.MultiTaskTrainer(
        [trainer.TaskSpec("d", d_cost, optimizer.Adam(learning_rate=2e-3),
                          trainable="dis_"),
         trainer.TaskSpec("g", g_cost, optimizer.Adam(learning_rate=2e-3),
                          trainable="gen_")],
        params)

    def real_batch(n=32):
        # ring of radius 2
        theta = rng.rand(n) * 2 * np.pi
        return np.stack([2 * np.cos(theta), 2 * np.sin(theta)],
                        -1).astype(np.float32)

    bs = 32
    ones = np.ones((bs, 1), np.float32)
    zeros = np.zeros((bs, 1), np.float32)

    snap_gen = {k: np.asarray(v) for k, v in params.as_dict().items()
                if k.startswith("gen_")}
    snap_dis = {k: np.asarray(v) for k, v in params.as_dict().items()
                if k.startswith("dis_")}

    d_losses, g_losses = [], []
    for step in range(30):
        z = rng.randn(bs, noise_dim).astype(np.float32)
        d_losses.append(t.step("d", {"noise": z, "pixel": real_batch(bs),
                                     "label_one": ones,
                                     "label_zero": zeros}))
        z = rng.randn(bs, noise_dim).astype(np.float32)
        g_losses.append(t.step("g", {"noise": z, "label_one": ones}))

    assert all(np.isfinite(d_losses)) and all(np.isfinite(g_losses))
    # d step must not touch gen params and vice versa — verify masking by
    # checking both subsets actually changed only via their own tasks
    after = params.as_dict()
    gen_moved = any(not np.allclose(np.asarray(after[k]), snap_gen[k])
                    for k in snap_gen)
    dis_moved = any(not np.allclose(np.asarray(after[k]), snap_dis[k])
                    for k in snap_dis)
    assert gen_moved and dis_moved
    # discriminator should be learning something: loss below the 2*ln2
    # chance level it starts at
    assert np.mean(d_losses[-5:]) < np.mean(d_losses[:3])


def test_gan_param_masking(rng):
    """One d step leaves gen params bit-identical (and vice versa)."""
    paddle.topology.reset_name_scope()
    noise, real, fake, d_cost, g_cost = gan.build(
        noise_dim=4, data_dim=2, gen_dims=(8,), dis_dims=(8,))
    topo_all = paddle.topology.Topology([d_cost, g_cost])
    params = paddle.Parameters.from_topology(topo_all, seed=1)
    t = trainer.MultiTaskTrainer(
        [trainer.TaskSpec("d", d_cost, optimizer.Sgd(learning_rate=0.1),
                          trainable="dis_"),
         trainer.TaskSpec("g", g_cost, optimizer.Sgd(learning_rate=0.1),
                          trainable="gen_")],
        params)
    before = {k: np.asarray(v) for k, v in params.as_dict().items()}
    bs = 8
    t.step("d", {"noise": rng.randn(bs, 4).astype(np.float32),
                 "pixel": rng.randn(bs, 2).astype(np.float32),
                 "label_one": np.ones((bs, 1), np.float32),
                 "label_zero": np.zeros((bs, 1), np.float32)})
    after = params.as_dict()
    for k in before:
        if k.startswith("gen_"):
            np.testing.assert_array_equal(np.asarray(after[k]), before[k]), k
        if k.startswith("dis_"):
            assert not np.allclose(np.asarray(after[k]), before[k]), k


def test_vae_trains(rng):
    paddle.topology.reset_name_scope()
    D = 16
    x, recon, cost = vae.build(data_dim=D, hidden=(32,), latent_dim=4)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=1e-2))

    # two-cluster binary data
    protos = (rng.rand(2, D) > 0.5).astype(np.float32)

    def reader():
        for _ in range(128):
            p = protos[rng.randint(0, 2)]
            flip = rng.rand(D) < 0.05
            yield (np.abs(p - flip.astype(np.float32)),)

    costs = []

    def handler(ev):
        from paddle_tpu import event
        if isinstance(ev, event.EndIteration):
            costs.append(ev.cost)

    sgd.train(paddle.batch(reader, 32), num_passes=15, event_handler=handler)
    assert costs[-1] < 0.8 * costs[0], (costs[0], costs[-1])
