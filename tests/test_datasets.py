"""Dataset pipeline tests: the REAL parse paths exercised on fabricated
fixture archives (no egress in CI), plus the offline synthetic fallbacks.

Mirrors the reference's approach of bundling mini-datasets for trainer
tests (paddle/trainer/tests/mnist_bin_part etc.): each test builds a tiny
archive in the reference's on-disk format and runs the same parser the
download path uses.
"""

import gzip
import io
import os
import re
import socket
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu import image as pimage
from paddle_tpu.dataset import (common, conll05, flowers, imdb, imikolov,
                                movielens, mq2007, sentiment, voc2012, wmt14)


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


def _add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


def _png_bytes(arr):
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def _jpg_bytes(arr):
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG")
    return buf.getvalue()


# ---------------------------------------------------------------------------
# imdb
# ---------------------------------------------------------------------------


def _imdb_tar(tmp_path):
    path = str(tmp_path / "aclImdb.tar.gz")
    docs = {
        "aclImdb/train/pos/0_9.txt": b"A great, GREAT movie!",
        "aclImdb/train/pos/1_8.txt": b"great fun; truly great",
        "aclImdb/train/neg/0_2.txt": b"terrible movie. boring",
        "aclImdb/train/neg/1_1.txt": b"boring and terrible...",
        "aclImdb/test/pos/0_10.txt": b"great",
        "aclImdb/test/neg/0_1.txt": b"terrible",
    }
    with tarfile.open(path, "w:gz") as tf:
        for name, data in docs.items():
            _add_bytes(tf, name, data)
    return path


def test_imdb_tokenize_and_dict(tmp_path):
    tar = _imdb_tar(tmp_path)
    docs = list(imdb.tokenize(re.compile(r"aclImdb/train/pos/.*\.txt$"),
                              tar_path=tar))
    assert docs[0] == ["a", "great", "great", "movie"]  # punctuation stripped
    d = imdb.build_dict(re.compile(r"aclImdb/train/.*\.txt$"), cutoff=1,
                        tar_path=tar)
    # freq: great=4; boring/movie/terrible=2 -> alphabetical tiebreak
    assert list(d)[:4] == ["great", "boring", "movie", "terrible"]
    assert d["<unk>"] == len(d) - 1


def test_imdb_reader_interleaves_labels(tmp_path):
    tar = _imdb_tar(tmp_path)
    d = imdb.build_dict(re.compile(r"aclImdb/train/.*\.txt$"), 0, tar_path=tar)
    samples = list(imdb._real_reader(r"aclImdb/train/pos/.*\.txt$",
                                     r"aclImdb/train/neg/.*\.txt$", d,
                                     tar_path=tar)())
    assert [lab for _, lab in samples] == [0, 1, 0, 1]  # pos=0 neg=1
    assert all(isinstance(ids, list) and ids for ids, _ in samples)


# ---------------------------------------------------------------------------
# imikolov (PTB)
# ---------------------------------------------------------------------------


def test_imikolov_parse_ngram_and_seq():
    word_idx = imikolov.build_dict_from_files(
        [b"the cat sat", b"the dog sat"], [b"the cat ran"], min_word_freq=0)
    # freq: the=3,<s>=3,<e>=3 sat=2 cat=2 dog=1 ran=1 -> alphabetic ties
    assert word_idx["<unk>"] == len(word_idx) - 1
    grams = list(imikolov.parse_lines([b"the cat sat"], word_idx, 2,
                                      imikolov.DataType.NGRAM))
    # <s> the cat sat <e> -> 4 bigrams
    assert len(grams) == 4 and all(len(g) == 2 for g in grams)
    seqs = list(imikolov.parse_lines([b"the cat sat"], word_idx, 0,
                                     imikolov.DataType.SEQ))
    src, trg = seqs[0]
    assert src[0] == word_idx["<s>"] and trg[-1] == word_idx["<e>"]
    assert src[1:] == trg[:-1]


# ---------------------------------------------------------------------------
# wmt14
# ---------------------------------------------------------------------------


def _wmt_tar(tmp_path):
    path = str(tmp_path / "wmt14.tgz")
    src_dict = b"<s>\n<e>\n<unk>\nle\nchat\n"
    trg_dict = b"<s>\n<e>\n<unk>\nthe\ncat\n"
    train = b"le chat\tthe cat\nle inconnu\tthe cat\n"
    with tarfile.open(path, "w:gz") as tf:
        _add_bytes(tf, "wmt14/train/src.dict", src_dict)
        _add_bytes(tf, "wmt14/train/trg.dict", trg_dict)
        _add_bytes(tf, "wmt14/train/train", train)
    return path


def test_wmt14_parse(tmp_path):
    tar = _wmt_tar(tmp_path)
    src_d, trg_d = wmt14.read_dicts_from_tar(tar, 30000)
    assert src_d["chat"] == 4 and trg_d["cat"] == 4
    with tarfile.open(tar) as f:
        lines = list(f.extractfile("wmt14/train/train"))
    samples = list(wmt14.parse_lines(lines, src_d, trg_d))
    src_ids, trg_ids, trg_next = samples[0]
    assert src_ids == [0, 3, 4, 1]           # <s> le chat <e>
    assert trg_ids == [0, 3, 4]              # <s> the cat
    assert trg_next == [3, 4, 1]             # the cat <e>
    # unknown source word -> UNK_IDX
    assert samples[1][0] == [0, 3, wmt14.UNK_IDX, 1]


# ---------------------------------------------------------------------------
# conll05
# ---------------------------------------------------------------------------


def test_conll05_props_to_bio_and_sample():
    words = [b"He", b"ate", b"rice", b""]
    props = [b"-  *", b"eat  (V*)", b"-  (A1*)", b""]
    # column-major: verbs column ['-','eat','-'], one arg layer
    out = list(conll05.corpus_reader(words, props))
    assert len(out) == 1
    sentence, verb, tags = out[0]
    assert sentence == ["He", "ate", "rice"]
    assert verb == "eat"
    assert tags == ["O", "B-V", "B-A1"]

    wd = {"He": 1, "ate": 2, "rice": 3, "bos": 4, "eos": 5}
    vd = {"eat": 0}
    ld = {"O": 0, "B-V": 1, "B-A1": 2}
    sample = conll05.make_sample(sentence, verb, tags, wd, vd, ld)
    word_ids, n2, n1, c0, p1, p2, pred, mark, labels = sample
    assert word_ids == [1, 2, 3]
    assert c0 == [2, 2, 2]            # predicate word broadcast
    assert n1 == [1, 1, 1] and n2 == [wd["bos"]] * 3
    assert p1 == [3, 3, 3] and p2 == [wd["eos"]] * 3
    assert mark == [1, 1, 1]          # +-2 window covers all 3 tokens
    assert labels == [0, 1, 2]


def test_conll05_multi_predicate_bracket_span():
    cols = [["-", "run", "-", "jump"],
            ["(A0*", "*", "*)", "*"],      # spans tokens 0-2
            ["*", "(A1*)", "*", "(V*)"]]
    out = list(conll05.props_to_bio(cols))
    assert out[0] == ("run", ["B-A0", "I-A0", "I-A0", "O"])
    assert out[1] == ("jump", ["O", "B-A1", "O", "B-V"])


# ---------------------------------------------------------------------------
# movielens
# ---------------------------------------------------------------------------


def test_movielens_parsers():
    movies = movielens.parse_movies(
        [b"1::Toy Story (1995)::Animation|Comedy",
         b"2::Jumanji (1995)::Adventure"])
    assert movies[1].title == "Toy Story"
    assert movies[1].categories == ["Animation", "Comedy"]
    users = movielens.parse_users([b"1::F::1::10::48067",
                                   b"2::M::56::16::70072"])
    assert users[1].is_male is False and users[1].age == 0
    assert users[2].age == movielens.AGE_TABLE.index(56)
    assert users[2].value() == [2, 0, 6, 16]


# ---------------------------------------------------------------------------
# mq2007
# ---------------------------------------------------------------------------


def _letor_line(rel, qid, seed):
    rng = np.random.RandomState(seed)
    feats = " ".join(f"{i + 1}:{rng.rand():.6f}"
                     for i in range(mq2007.FEATURE_DIM))
    return f"{rel} qid:{qid} {feats} #docid = G{qid}-{seed}"


def test_mq2007_letor_parse_and_generators():
    lines = [_letor_line(2, 10, 1), _letor_line(0, 10, 2),
             _letor_line(1, 10, 3), _letor_line(1, 20, 4),
             _letor_line(0, 20, 5)]
    parsed = mq2007.parse_letor_line(lines[0])
    assert parsed is not None
    rel, qid, feats = parsed
    assert (rel, qid) == (2, 10) and feats.shape == (46,)

    groups = list(mq2007.group_by_query(lines))
    assert [len(g) for g in groups] == [3, 2]
    assert [r for r, _ in groups[0]] == [2, 1, 0]  # best-first
    pairs = list(mq2007.gen_pair(groups[0]))
    assert len(pairs) == 3                          # C(3,2), all ordered
    points = list(mq2007.gen_point(groups[1]))
    assert [p[0] for p in points] == [1, 0]
    assert mq2007.parse_letor_line("# comment only") is None
    assert mq2007.parse_letor_line("1 qid:3 1:0.5") is None  # wrong arity


def test_mq2007_synthetic_fallback_shapes():
    sample = next(iter(mq2007.train(format="pairwise")()))
    assert sample[0].shape == (46,) and sample[2] == 1.0
    group = next(iter(mq2007.train(format="listwise")()))
    assert all(f.shape == (46,) for _, f in group)


# ---------------------------------------------------------------------------
# sentiment
# ---------------------------------------------------------------------------


def _reviews_zip(tmp_path):
    path = str(tmp_path / "movie_reviews.zip")
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("movie_reviews/neg/cv000.txt", "bad awful bad")
        z.writestr("movie_reviews/neg/cv001.txt", "awful")
        z.writestr("movie_reviews/pos/cv000.txt", "good nice good")
        z.writestr("movie_reviews/pos/cv001.txt", "nice")
    return path


def test_sentiment_zip_parse(tmp_path):
    path = _reviews_zip(tmp_path)
    docs = list(sentiment.iter_documents(path))
    assert [lab for _, lab in docs] == [0, 1, 0, 1]  # neg/pos interleaved
    d = sentiment.build_word_dict(path)
    # freq: bad=2,good=2 (alpha ties), awful=2, nice=2
    assert set(list(d)[:4]) == {"awful", "bad", "good", "nice"}
    assert docs[0][0] == ["bad", "awful", "bad"]


# ---------------------------------------------------------------------------
# flowers / voc2012 / image utils
# ---------------------------------------------------------------------------


def test_flowers_real_parse(tmp_path):
    import scipy.io as scio

    rng = np.random.RandomState(0)
    tar_path = str(tmp_path / "102flowers.tgz")
    with tarfile.open(tar_path, "w:gz") as tf:
        for i in (1, 2, 3):
            img = (rng.rand(40, 52, 3) * 255).astype(np.uint8)
            _add_bytes(tf, f"jpg/image_{i:05d}.jpg", _jpg_bytes(img))
    label_mat = str(tmp_path / "imagelabels.mat")
    setid_mat = str(tmp_path / "setid.mat")
    scio.savemat(label_mat, {"labels": np.array([[5, 7, 9]])})
    scio.savemat(setid_mat, {"tstid": np.array([[1, 3]]),
                             "trnid": np.array([[2]])})

    img2label = flowers.split_img2label(label_mat, setid_mat, "tstid")
    assert img2label == {"jpg/image_00001.jpg": 5, "jpg/image_00003.jpg": 9}

    reader = flowers._reader_creator(
        tar_path, label_mat, setid_mat, "tstid",
        flowers.test_mapper, use_xmap=False)
    samples = list(reader())
    assert len(samples) == 2
    img, label = samples[0]
    assert img.shape == (224 * 224 * 3,) and label == 4  # 0-based


def test_voc2012_real_parse(tmp_path):
    rng = np.random.RandomState(1)
    tar_path = str(tmp_path / "voc.tar")
    img = (rng.rand(24, 32, 3) * 255).astype(np.uint8)
    seg = rng.randint(0, 21, (24, 32)).astype(np.uint8)
    with tarfile.open(tar_path, "w") as tf:
        _add_bytes(tf, voc2012.SET_FILE.format("train"), b"img0\n")
        _add_bytes(tf, voc2012.DATA_FILE.format("img0"), _jpg_bytes(img))
        _add_bytes(tf, voc2012.LABEL_FILE.format("img0"), _png_bytes(seg))
    samples = list(voc2012.reader_creator(tar_path, "train")())
    assert len(samples) == 1
    got_img, got_seg = samples[0]
    assert got_img.shape == (24, 32, 3)
    np.testing.assert_array_equal(got_seg, seg)  # png is lossless


def test_image_transform_pipeline():
    rng = np.random.RandomState(2)
    im = (rng.rand(60, 80, 3) * 255).astype(np.uint8)
    short = pimage.resize_short(im, 30)
    assert min(short.shape[:2]) == 30 and short.shape[1] == 40
    crop = pimage.center_crop(short, 24)
    assert crop.shape[:2] == (24, 24)
    flipped = pimage.left_right_flip(crop)
    np.testing.assert_array_equal(flipped[:, 0], crop[:, -1])
    chw = pimage.to_chw(crop)
    assert chw.shape == (3, 24, 24)
    np.testing.assert_array_equal(pimage.to_hwc(chw), crop)
    out = pimage.simple_transform(im, 32, 24, is_train=False,
                                  mean=[1.0, 2.0, 3.0])
    assert out.shape == (24, 24, 3) and out.dtype == np.float32
    out_chw = pimage.simple_transform(im, 32, 24, is_train=False,
                                      layout="CHW")
    assert out_chw.shape == (3, 24, 24)
    # decode round-trip (png lossless)
    decoded = pimage.load_image_bytes(_png_bytes(im))
    assert decoded.shape == im.shape


# ---------------------------------------------------------------------------
# download smoke test — runs only when the environment has egress
# ---------------------------------------------------------------------------


def _has_egress(host="storage.googleapis.com", timeout=3.0):
    try:
        socket.create_connection((host, 80), timeout=timeout).close()
        return True
    except OSError:
        return False


@pytest.mark.skipif(not _has_egress(), reason="no network egress")
def test_download_smoke(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    from paddle_tpu.dataset import mnist

    path = common.download(mnist.URL_PREFIX + mnist.TEST_LABEL[0], "mnist",
                           mnist.TEST_LABEL[1])
    assert os.path.exists(path)
    assert common.md5file(path) == mnist.TEST_LABEL[1]


# ---------------------------------------------------------------------------
# device-prefetch pipeline
# ---------------------------------------------------------------------------


def test_device_prefetch_matches_sequential():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import layer, optimizer, trainer
    from paddle_tpu.reader.prefetch import device_prefetch

    rng = np.random.RandomState(0)
    batches = [[(rng.randn(8).astype(np.float32), int(rng.randint(2)))
                for _ in range(16)] for _ in range(6)]

    def run(prefetch):
        paddle.topology.reset_name_scope()
        x = layer.data(name="x", type=paddle.data_type.dense_vector(8))
        y = layer.data(name="y", type=paddle.data_type.integer_value(2))
        cost = layer.classification_cost(
            input=layer.fc(input=x, size=2), label=y)
        params = paddle.Parameters.from_topology(
            paddle.topology.Topology([cost]), seed=3)
        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Momentum(
                              momentum=0.9, learning_rate=0.1))
        sgd.train(lambda: iter(list(batches)), num_passes=2,
                  prefetch=prefetch)
        return {k: np.asarray(sgd.parameters[k])
                for k in sgd.parameters.names()}

    ref = run(0)
    got = run(2)
    for k in ref:
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)


def test_device_prefetch_propagates_reader_errors():
    import numpy as np
    import pytest as _pytest

    from paddle_tpu.reader.prefetch import device_prefetch

    def bad_iter():
        yield {"x": np.zeros((2, 2), np.float32)}
        raise RuntimeError("boom")

    it = device_prefetch(bad_iter(), size=1)
    next(it)
    with _pytest.raises(RuntimeError, match="boom"):
        list(it)
