"""Pipeline parallelism (parallel/pipeline.py): GPipe microbatch schedule
over a 'stage' mesh axis.

Oracle: running the stages sequentially on one device. The pipeline must
match it exactly in forward AND gradients (autodiff through scan+ppermute),
and a pipelined train loop must learn.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.parallel import make_mesh, pipeline_apply, stack_stage_params

S, M, MB, D = 4, 6, 8, 16  # stages, microbatches, microbatch size, width


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _make_params(rng):
    return [(jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.5),
             jnp.asarray(rng.randn(D).astype(np.float32) * 0.1))
            for _ in range(S)]


def _sequential(param_list, mbs):
    out = []
    for i in range(mbs.shape[0]):
        x = mbs[i]
        for p in param_list:
            x = _stage_fn(p, x)
        out.append(x)
    return jnp.stack(out)


def test_pipeline_matches_sequential(rng):
    mesh = make_mesh((S,), ("stage",), jax.devices()[:S])
    param_list = _make_params(rng)
    stacked = stack_stage_params(param_list, mesh)
    mbs = jnp.asarray(rng.randn(M, MB, D).astype(np.float32))
    got = pipeline_apply(mesh, _stage_fn, stacked, mbs)
    want = _sequential(param_list, mbs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    # stage params are actually distributed: each device holds 1/S
    w = jax.tree.leaves(stacked)[0]
    assert w.addressable_shards[0].data.shape[0] == 1


def test_pipeline_grads_match_sequential(rng):
    mesh = make_mesh((S,), ("stage",), jax.devices()[:S])
    param_list = _make_params(rng)
    stacked = stack_stage_params(param_list, mesh)
    mbs = jnp.asarray(rng.randn(M, MB, D).astype(np.float32))
    tgt = jnp.asarray(rng.randn(M, MB, D).astype(np.float32))

    def pipe_loss(p):
        out = pipeline_apply(mesh, _stage_fn, p, mbs)
        return jnp.mean((out - tgt) ** 2)

    def seq_loss(plist):
        out = _sequential(plist, mbs)
        return jnp.mean((out - tgt) ** 2)

    g_pipe = jax.grad(pipe_loss)(stacked)
    g_seq = jax.grad(seq_loss)(param_list)
    g_seq_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *g_seq)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_pipeline_training_learns(rng):
    """SGD on the pipelined loss drives it down (pp training end-to-end)."""
    mesh = make_mesh((S,), ("stage",), jax.devices()[:S])
    stacked = stack_stage_params(_make_params(rng), mesh)
    mbs = jnp.asarray(rng.randn(M, MB, D).astype(np.float32))
    tgt = _sequential(_make_params(np.random.RandomState(123)), mbs)

    @jax.jit
    def step(p):
        def loss(p):
            return jnp.mean((pipeline_apply(mesh, _stage_fn, p, mbs) - tgt) ** 2)

        l, g = jax.value_and_grad(loss)(p)
        return l, jax.tree.map(lambda w, gw: w - 0.3 * gw, p, g)

    losses = []
    p = stacked
    for _ in range(80):
        l, p = step(p)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_pipeline_over_transformer_blocks(rng):
    """GPipe over the FLAGSHIP architecture: 4 real decoder blocks as
    pipeline stages must match applying the same trained blocks
    sequentially — forward and grads — and the functional block must match
    the layer-DSL training graph it mirrors."""
    import paddle_tpu as paddle
    from paddle_tpu.models import transformer
    from paddle_tpu.platform.flags import FLAGS

    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    try:
        vocab, d, layers, heads = 31, 16, 4, 2
        paddle.topology.reset_name_scope()
        tokens, pos, target, logits, cost = transformer.build(
            vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
            max_len=16)
        topo = paddle.topology.Topology([cost])
        params = paddle.Parameters.from_topology(topo, seed=2)
        pdict = {k: v for k, v in params.items()}
        blocks = transformer.stage_params(pdict, layers)

        # tie the functional block to the DSL graph: embedding -> blocks
        # sequentially == topology forward up to final_ln's input
        toks = rng.randint(0, vocab, size=10)
        feeder = paddle.DataFeeder(
            [(n.name, n.input_type) for n in topo.data_nodes],
            {"tokens": 0, "pos": 1, "target": 2})
        feeds = feeder.feed([(toks.tolist(), list(range(10)),
                              np.roll(toks, -1).tolist())])
        topo_body = paddle.topology.Topology(
            [topo.by_name[f"blk{layers - 1}_res2"]])
        needed = {k: pdict[k] for k in topo_body.param_specs()}
        outs, _ = topo_body.forward(needed, {}, feeds, train=False)
        want_body = np.asarray(outs[0].data)[:10]

        x = (np.asarray(pdict["tok_embed.w"])[toks]
             + np.asarray(pdict["pos_embed.w"])[:10])
        seq = jnp.asarray(x, jnp.float32)
        for bp in blocks:
            seq = transformer.block_apply(bp, seq, n_heads=heads)
        np.testing.assert_allclose(np.asarray(seq), want_body,
                                   atol=2e-4, rtol=1e-3)

        # GPipe over the blocks == sequential blocks (fwd + grads)
        mesh = make_mesh((layers,), ("stage",), jax.devices()[:layers])
        stacked = stack_stage_params(blocks, mesh)
        mbs = jnp.asarray(
            rng.randn(5, 10, d).astype(np.float32))  # 5 microbatches

        def stage_fn(p, xb):
            return transformer.block_apply(p, xb, n_heads=heads)

        got = pipeline_apply(mesh, stage_fn, stacked, mbs)
        want = []
        for i in range(mbs.shape[0]):
            xb = mbs[i]
            for bp in blocks:
                xb = transformer.block_apply(bp, xb, n_heads=heads)
            want.append(xb)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(jnp.stack(want)),
                                   atol=1e-4, rtol=1e-3)

        def pipe_loss(p):
            return jnp.sum(pipeline_apply(mesh, stage_fn, p, mbs) ** 2)

        def seq_loss(plist):
            tot = 0.0
            for i in range(mbs.shape[0]):
                xb = mbs[i]
                for bp in plist:
                    xb = transformer.block_apply(bp, xb, n_heads=heads)
                tot = tot + jnp.sum(xb ** 2)
            return tot

        g_pipe = jax.grad(pipe_loss)(stacked)
        g_seq = jax.grad(seq_loss)(blocks)
        g_seq_st = jax.tree.map(lambda *xs: jnp.stack(xs), *g_seq)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq_st)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=1e-2)
    finally:
        FLAGS.use_bf16 = old
