"""Sparse/embedding distribution parity tests.

Reference analog: trainer/tests/test_CompareSparse.cpp:139-209 — dense vs
sparse vs remote-sparse training must converge to identical parameters.
Here: dense lookup/update vs mesh-sharded owner-computes lookup and
row-sparse updates on the 8-device CPU mesh must match bit-for-bit-ish.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel import sparse as sp


@pytest.fixture
def mesh():
    return make_mesh((2, 4), ("data", "model"))


def test_sharded_lookup_matches_dense(rng, mesh):
    vocab, dim = 32, 6
    table = rng.randn(vocab, dim).astype(np.float32)
    ids = rng.randint(0, vocab, (16,)).astype(np.int32)
    sharded = sp.shard_table(mesh, jnp.asarray(table), axis="model")
    got = np.asarray(sp.sharded_lookup(mesh, sharded, jnp.asarray(ids),
                                       axis="model"))
    np.testing.assert_allclose(got, table[ids], atol=1e-6)


def test_sharded_lookup_batch_sharded(rng, mesh):
    vocab, dim = 16, 4
    table = rng.randn(vocab, dim).astype(np.float32)
    ids = rng.randint(0, vocab, (8,)).astype(np.int32)
    sharded = sp.shard_table(mesh, jnp.asarray(table), axis="model")
    got = np.asarray(sp.sharded_lookup(mesh, sharded, jnp.asarray(ids),
                                       axis="model", batch_axis="data"))
    np.testing.assert_allclose(got, table[ids], atol=1e-6)


def test_alltoall_lookup(rng):
    mesh = make_mesh((4,), ("model",))
    vocab, dim = 16, 4
    table = rng.randn(vocab, dim).astype(np.float32)
    ids = rng.randint(0, vocab, (8,)).astype(np.int32)
    sharded = sp.shard_table(mesh, jnp.asarray(table), axis="model")
    got = np.asarray(sp.alltoall_lookup(mesh, sharded, jnp.asarray(ids),
                                        axis="model"))
    np.testing.assert_allclose(got, table[ids], atol=1e-6)


def test_selected_rows_grad_and_update(rng):
    vocab, dim = 10, 3
    table = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    ids = jnp.asarray(np.array([1, 3, 1], np.int32))   # duplicate id
    target = jnp.asarray(rng.randn(3, dim).astype(np.float32))

    def loss_fn(rows):
        return jnp.sum(jnp.square(rows - target))

    loss, grad = sp.embedding_grad(table, ids, loss_fn)
    assert isinstance(grad, sp.SelectedRows)
    # dense reference
    def dense_loss(t):
        return loss_fn(jnp.take(t, ids, axis=0))
    dense_g = jax.grad(dense_loss)(table)
    np.testing.assert_allclose(np.asarray(grad.to_dense()),
                               np.asarray(dense_g), atol=1e-5)

    lr = 0.1
    updated = sp.sgd_update_rows(table, grad, lr)
    np.testing.assert_allclose(np.asarray(updated),
                               np.asarray(table - lr * dense_g), atol=1e-5)
    # untouched rows unchanged
    np.testing.assert_array_equal(np.asarray(updated[0]),
                                  np.asarray(table[0]))


def test_sharded_row_update_matches_dense(rng, mesh):
    vocab, dim = 32, 4
    table = rng.randn(vocab, dim).astype(np.float32)
    ids = np.array([0, 5, 17, 31, 5], np.int32)
    rows = rng.randn(5, dim).astype(np.float32)
    grad = sp.SelectedRows(jnp.asarray(ids), jnp.asarray(rows), vocab)
    sharded = sp.shard_table(mesh, jnp.asarray(table), axis="model")
    got = np.asarray(sp.sharded_row_update(mesh, sharded, grad, 0.5,
                                           axis="model"))
    expect = table.copy()
    for i, r in zip(ids, rows):
        expect[i] -= 0.5 * r
    np.testing.assert_allclose(got, expect, atol=1e-5)


def test_compare_sparse_training_parity(rng, mesh):
    """The test_CompareSparse analog: N steps of embedding regression
    trained (a) dense and (b) sharded + row-sparse must agree."""
    vocab, dim, bs = 16, 4, 8
    table0 = rng.randn(vocab, dim).astype(np.float32)
    steps = [(rng.randint(0, vocab, (bs,)).astype(np.int32),
              rng.randn(bs, dim).astype(np.float32)) for _ in range(10)]
    lr = 0.05

    # (a) dense jax.grad training
    dense = jnp.asarray(table0)
    for ids, tgt in steps:
        g = jax.grad(lambda t: jnp.mean(jnp.square(
            jnp.take(t, jnp.asarray(ids), axis=0) - tgt)))(dense)
        dense = dense - lr * g

    # (b) sharded lookup + SelectedRows + sharded row update
    sharded = sp.shard_table(mesh, jnp.asarray(table0), axis="model")
    for ids, tgt in steps:
        rows = sp.sharded_lookup(mesh, sharded, jnp.asarray(ids),
                                 axis="model")
        _, d_rows = jax.value_and_grad(
            lambda r: jnp.mean(jnp.square(r - tgt)))(rows)
        grad = sp.SelectedRows(jnp.asarray(ids), d_rows, vocab)
        sharded = sp.sharded_row_update(mesh, sharded, grad, lr,
                                        axis="model")

    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense),
                               atol=1e-5, rtol=1e-5)


def test_adagrad_rows(rng):
    vocab, dim = 8, 3
    table = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    accum = jnp.zeros((vocab, dim), jnp.float32)
    ids = jnp.asarray(np.array([2, 6], np.int32))
    rows = jnp.asarray(rng.randn(2, dim).astype(np.float32))
    grad = sp.SelectedRows(ids, rows, vocab)
    t2, a2 = sp.adagrad_update_rows(table, accum, grad, lr=0.1)
    np.testing.assert_array_equal(np.asarray(t2[0]), np.asarray(table[0]))
    assert float(jnp.sum(jnp.abs(a2[2]))) > 0
    assert float(jnp.sum(jnp.abs(a2[0]))) == 0


def test_deepfm_trains(rng):
    """DeepFM CTR gate model (BASELINE config #4 analog): synthetic CTR
    data must reach decreasing loss."""
    from paddle_tpu import optimizer, trainer
    from paddle_tpu.models import deepfm

    paddle.topology.reset_name_scope()
    F, V = 4, 64
    fields, label, prob, cost = deepfm.build(num_fields=F, vocab_size=V,
                                             factor_dim=4,
                                             deep_layers=(16,))
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Adam(learning_rate=0.02))

    # clicks correlate with low field-0 ids
    def sample():
        f = rng.randint(0, V, (F,))
        y = 1 if f[0] < V // 2 else 0
        return tuple(int(x) for x in f) + (y,)

    data = [sample() for _ in range(256)]

    def reader():
        for row in data:
            yield row

    costs = []

    def handler(ev):
        from paddle_tpu import event
        if isinstance(ev, event.EndIteration):
            costs.append(ev.cost)

    sgd.train(paddle.batch(reader, 32), num_passes=8, event_handler=handler)
    first = np.mean(costs[:8])
    last = np.mean(costs[-8:])
    assert last < 0.75 * first, (first, last)


def test_sparse_embedding_updater(rng):
    """Marked params update only touched rows and match the dense step on
    them (duplicate ids must not double-count)."""
    vocab, dim = 12, 3
    p = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
    g = jnp.zeros((vocab, dim), jnp.float32).at[jnp.asarray([2, 5])].set(1.0)
    upd = sp.SparseEmbeddingUpdater(sparse_params=("emb",))
    ids = jnp.asarray(np.array([2, 5, 2], np.int32))   # 2 repeated
    out = upd.apply({"emb": p}, {"emb": g}, lr=0.1, ids={"emb": ids})["emb"]
    expect = np.asarray(p).copy()
    expect[2] -= 0.1
    expect[5] -= 0.1
    np.testing.assert_allclose(np.asarray(out), expect, atol=1e-6)
    # unmarked param: dense step
    out2 = upd.apply({"w": p}, {"w": g}, lr=0.1)["w"]
    np.testing.assert_allclose(np.asarray(out2), np.asarray(p - 0.1 * g),
                               atol=1e-6)
