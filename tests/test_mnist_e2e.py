"""End-to-end slice test — the reference's test_TrainerOnePass analog:
train a small model for one pass on (synthetic) MNIST and assert the cost
drops and accuracy beats chance; checkpoint round-trip; inference."""

import io

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer, evaluator, optimizer, trainer, event


def _mlp_topology():
    images = layer.data(name="pixel", type=paddle.data_type.dense_vector(784))
    label = layer.data(name="label", type=paddle.data_type.integer_value(10))
    hidden = layer.fc(input=images, size=64, act="relu", name="hidden")
    logits = layer.fc(input=hidden, size=10, name="logits")
    cost = layer.classification_cost(input=logits, label=label, name="cost")
    err = evaluator.classification_error(input=logits, label=label, name="err")
    return images, label, logits, cost, err


def test_mnist_one_pass_converges():
    paddle.topology.reset_name_scope()
    _, _, logits, cost, err = _mlp_topology()
    params = paddle.Parameters.from_topology(paddle.topology.Topology([cost, err]),
                                             seed=7)
    opt = optimizer.Momentum(momentum=0.9, learning_rate=0.05)
    sgd = trainer.SGD(cost=cost, parameters=params, update_equation=opt,
                      extra_layers=[err])

    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.mnist.train(), buf_size=2048),
        batch_size=64)

    seen = {"costs": [], "errs": []}

    def handler(ev):
        if isinstance(ev, event.EndIteration):
            seen["costs"].append(ev.cost)
            seen["errs"].append(ev.metrics["err"])

    sgd.train(train_reader, num_passes=1, event_handler=handler)

    first = np.mean(seen["costs"][:10])
    last = np.mean(seen["costs"][-10:])
    assert last < first * 0.7, f"cost did not drop: {first} -> {last}"
    assert np.mean(seen["errs"][-10:]) < 0.5, "error rate stuck at chance"

    # test() path
    test_reader = paddle.batch(paddle.dataset.mnist.test(), batch_size=64)
    result = sgd.test(test_reader)
    assert result.metrics["err"] < 0.5

    # checkpoint round-trip
    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    loaded = paddle.Parameters.from_tar(buf)
    for name in params.names():
        np.testing.assert_allclose(np.asarray(params[name]),
                                   np.asarray(loaded[name]))

    # inference
    probs = paddle.infer(output_layer=logits, parameters=params,
                         input=[(np.zeros(784, np.float32),)])
    assert probs.shape == (1, 10)


def test_lenet_conv_one_batch():
    """Conv path compiles and trains one batch (LeNet-ish)."""
    paddle.topology.reset_name_scope()
    images = layer.data(name="pixel", type=paddle.data_type.dense_vector(784),
                        height=28, width=28)
    label = layer.data(name="label", type=paddle.data_type.integer_value(10))
    conv1 = paddle.networks.simple_img_conv_pool(
        input=images, filter_size=5, num_filters=8, pool_size=2,
        num_channel=1, act="relu")
    conv2 = paddle.networks.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=16, pool_size=2, act="relu")
    logits = layer.fc(input=conv2, size=10)
    cost = layer.classification_cost(input=logits, label=label)

    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=3)
    opt = optimizer.Adam(learning_rate=1e-3)
    sgd = trainer.SGD(cost=cost, parameters=params, update_equation=opt)

    data = [(np.random.RandomState(0).randn(784).astype(np.float32), i % 10)
            for i in range(32)]

    def reader():
        yield from data

    costs = []

    def handler(ev):
        if isinstance(ev, event.EndIteration):
            costs.append(ev.cost)

    sgd.train(paddle.batch(reader, 16), num_passes=8, event_handler=handler)
    assert len(costs) == 16
    assert np.isfinite(costs).all()
    assert np.mean(costs[-4:]) < np.mean(costs[:4])
