"""Fluid control-flow (cond, dynamic_recurrent) + save/restore IO tests.

Reference analogs: paddle/operators/cond_op.h (if-else over row subsets),
dynamic_recurrent_op.cc (LoD-aware RNN), save_restore_op.cc (+ its python
test test_save_restore_op.py roundtrip).
"""

import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.fluid.ops import LoDArray


# ---------------------------------------------------------------------------
# cond
# ---------------------------------------------------------------------------


def test_cond_forward():
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [3])
        pred = layers.data("pred", [1])
        out = layers.cond(pred,
                          lambda: layers.scale(x, scale=2.0),
                          lambda: layers.scale(x, scale=0.5))

    exe = fluid.Executor()
    xb = np.arange(12, dtype=np.float32).reshape(4, 3)
    pb = np.array([1.0, 0.0, 1.0, 0.0], np.float32)
    (o,) = exe.run(prog, feed={"x": xb, "pred": pb}, fetch_list=[out],
                   scope=fluid.Scope())
    want = np.where(pb[:, None] > 0, xb * 2.0, xb * 0.5)
    np.testing.assert_allclose(o, want, rtol=1e-6)


def test_cond_trains_both_branches():
    """Gradients flow into parameters used by BOTH branches (masked-merge
    semantics: each row trains the branch its pred selected)."""
    rng = np.random.RandomState(0)
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [4])
        pred = layers.data("pred", [1])
        y = layers.data("y", [1])
        out = layers.cond(pred,
                          lambda: layers.fc(x, size=1, bias_attr=True),
                          lambda: layers.fc(x, size=1, bias_attr=True))
        loss = layers.mean(layers.square_error_cost(out, y))
        optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    exe = fluid.Executor()
    scope = fluid.Scope()
    # rows with pred=1 follow w_true, rows with pred=0 follow w_false
    w_t = rng.randn(4, 1).astype(np.float32)
    w_f = -w_t
    losses = []
    for _ in range(80):
        xb = rng.randn(16, 4).astype(np.float32)
        pb = (rng.rand(16) > 0.5).astype(np.float32)
        yb = np.where(pb[:, None] > 0, xb @ w_t, xb @ w_f)
        (l,) = exe.run(prog, feed={"x": xb, "pred": pb, "y": yb},
                       fetch_list=[loss], scope=scope)
        losses.append(float(l))
    assert losses[-1] < 0.1 * losses[0], losses[::20]


# ---------------------------------------------------------------------------
# dynamic_recurrent
# ---------------------------------------------------------------------------


def _ragged_input(rng, lens, dim):
    offs = np.concatenate([[0], np.cumsum(lens)])
    data = rng.randn(int(offs[-1]), dim).astype(np.float32)
    return LoDArray(data, (tuple(int(o) for o in offs),))


def test_dynamic_recurrent_matches_oracle():
    """Running-sum RNN over ragged sequences == per-sequence numpy scan."""
    rng = np.random.RandomState(0)
    lens = [3, 1, 4, 2]
    dim = 5
    x_lod = _ragged_input(rng, lens, dim)

    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [dim], lod_level=1)
        drnn = layers.DynamicRNN()
        with drnn.step():
            x_t = drnn.step_input(x)
            h = drnn.memory(shape=(len(lens), dim))
            s = layers.elementwise_add(x_t, h)
            drnn.update_memory(h, s)
            drnn.step_output(s)
        out = drnn()

    exe = fluid.Executor()
    (o,) = exe.run(prog, feed={"x": x_lod}, fetch_list=[out],
                   scope=fluid.Scope())

    offs = np.asarray(x_lod.lod[0])
    want = np.concatenate([np.cumsum(x_lod.data[offs[i]:offs[i + 1]], axis=0)
                           for i in range(len(lens))])
    np.testing.assert_allclose(o, want, rtol=1e-5, atol=1e-6)


def test_dynamic_recurrent_reverse():
    """reverse=True: suffix sums per sequence (backward recurrence)."""
    rng = np.random.RandomState(1)
    lens = [2, 3]
    dim = 3
    x_lod = _ragged_input(rng, lens, dim)

    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [dim], lod_level=1)
        drnn = layers.DynamicRNN(reverse=True)
        with drnn.step():
            x_t = drnn.step_input(x)
            h = drnn.memory(shape=(len(lens), dim))
            s = layers.elementwise_add(x_t, h)
            drnn.update_memory(h, s)
            drnn.step_output(s)
        out = drnn()

    exe = fluid.Executor()
    (o,) = exe.run(prog, feed={"x": x_lod}, fetch_list=[out],
                   scope=fluid.Scope())

    offs = np.asarray(x_lod.lod[0])
    want = np.concatenate(
        [np.cumsum(x_lod.data[offs[i]:offs[i + 1]][::-1], axis=0)[::-1]
         for i in range(len(lens))])
    np.testing.assert_allclose(o, want, rtol=1e-5, atol=1e-6)


def test_dynamic_recurrent_trains():
    """A learned recurrent projection trains through the LoD scan: fit a
    target that is the per-sequence running MEAN of inputs (needs the
    recurrence + the trained projection)."""
    rng = np.random.RandomState(2)
    dim = 4

    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [dim], lod_level=1)
        tgt = layers.data("tgt", [dim], lod_level=1)
        drnn = layers.DynamicRNN()
        with drnn.step():
            x_t = drnn.step_input(x)
            h = drnn.memory(shape=(4, dim))
            s = layers.elementwise_add(layers.fc(x_t, size=dim), h)
            drnn.update_memory(h, s)
            drnn.step_output(s)
        out = drnn()
        loss = layers.mean(layers.square_error_cost(out, tgt))
        optimizer.AdamOptimizer(learning_rate=0.02).minimize(loss)

    exe = fluid.Executor()
    scope = fluid.Scope()
    lens = [3, 2, 4, 1]
    losses = []
    for _ in range(60):
        x_lod = _ragged_input(rng, lens, dim)
        offs = np.asarray(x_lod.lod[0])
        # target: running sum of 0.5*x  (the fc must learn 0.5*I)
        t = np.concatenate(
            [np.cumsum(0.5 * x_lod.data[offs[i]:offs[i + 1]], axis=0)
             for i in range(len(lens))])
        (l,) = exe.run(prog, feed={"x": x_lod,
                                   "tgt": LoDArray(t, x_lod.lod)},
                       fetch_list=[loss], scope=scope)
        losses.append(float(l))
    assert losses[-1] < 0.1 * losses[0], losses[::15]


# ---------------------------------------------------------------------------
# save / restore
# ---------------------------------------------------------------------------


def _train_once(prog_holder):
    rng = np.random.RandomState(3)
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [4])
        y = layers.data("y", [1])
        pred = layers.fc(x, size=1, bias_attr=True)
        loss = layers.mean(layers.square_error_cost(pred, y))
        optimizer.MomentumOptimizer(learning_rate=0.05,
                                    momentum=0.9).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    for _ in range(10):
        xb = rng.randn(16, 4).astype(np.float32)
        exe.run(prog, feed={"x": xb, "y": xb.sum(1, keepdims=True)},
                fetch_list=[loss], scope=scope)
    prog_holder.append(prog)
    return exe, scope, loss


def test_save_restore_roundtrip(tmp_path):
    holder = []
    exe, scope, _ = _train_once(holder)
    prog = holder[0]
    d = str(tmp_path / "ckpt")
    fluid.io.save_persistables(exe, d, main_program=prog, scope=scope)

    saved = {n: np.asarray(v).copy() for n, v in scope.values.items()}
    for n in scope.values:
        scope.values[n] = np.zeros_like(np.asarray(scope.values[n]))

    fluid.io.load_persistables(exe, d, main_program=prog, scope=scope)
    for n, want in saved.items():
        np.testing.assert_array_equal(np.asarray(scope.values[n]), want)
    # files are one .npy per var
    assert sorted(f[:-4] for f in os.listdir(d)) == sorted(saved)


def test_save_params_subset(tmp_path):
    holder = []
    exe, scope, _ = _train_once(holder)
    prog = holder[0]
    d = str(tmp_path / "params")
    fluid.io.save_params(exe, d, main_program=prog, scope=scope)
    n_params = sum(isinstance(v, fluid.Parameter)
                   for v in prog.global_block().vars.values())
    assert len(os.listdir(d)) == n_params
    assert 0 < n_params < len(scope.values)  # strictly params, not slots


def test_io_programs_must_be_pure(tmp_path):
    prog = fluid.Program()
    with fluid.program_guard(prog):
        x = layers.data("x", [2])
        v = prog.global_block().create_var(name="w", shape=(2,),
                                           persistable=True)
        layers.scale(x, scale=2.0)
        prog.global_block().append_op(
            "save", inputs={"X": [v]}, outputs={},
            attrs={"path": str(tmp_path)})
    exe = fluid.Executor()
    try:
        exe.run(prog, feed={"x": np.ones((1, 2), np.float32)},
                fetch_list=[], scope=fluid.Scope())
        raise AssertionError("mixed IO program must be rejected")
    except Exception as e:
        assert "IO-only" in str(e)
