"""1-device vs N-device data-parallel training parity for the main
SGD(mesh=...) path.

Reference analog: paddle/trainer/tests/test_TrainerOnePass.cpp:80-122
(trainerOnePassTest with num_gpus 1/2/4 — same config, same data, the
multi-GPU MultiGradientMachine must land on the same parameters).

On a mesh, feeds shard over 'data' and XLA inserts the grad psum; with the
same global batch the mean-gradient is identical, so parameters must match
the single-device run to float tolerance.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer, optimizer, trainer
from paddle_tpu.parallel import make_mesh


def _build():
    paddle.topology.reset_name_scope()
    x = layer.data(name="x", type=paddle.data_type.dense_vector(16))
    y = layer.data(name="y", type=paddle.data_type.integer_value(4))
    h = layer.fc(input=x, size=32, act="relu")
    cost = layer.classification_cost(input=layer.fc(input=h, size=4), label=y)
    return cost


def _batches(seed, n_batches=8, batch=32):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        out.append([(rng.randn(16).astype(np.float32), int(rng.randint(4)))
                    for _ in range(batch)])
    return out


def _train(mesh, batches, opt_factory, zero=None, return_sgd=False):
    cost = _build()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]), seed=7)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=opt_factory(), mesh=mesh, zero=zero)

    def reader():
        return iter(batches)

    sgd.train(reader, num_passes=1, event_handler=lambda ev: None)
    out = {k: np.asarray(sgd.parameters[k]) for k in params.names()}
    return (out, sgd) if return_sgd else out


@pytest.mark.parametrize("opt_factory", [
    lambda: optimizer.Momentum(momentum=0.9, learning_rate=0.05),
    lambda: optimizer.Adam(learning_rate=1e-2),
], ids=["momentum", "adam"])
def test_mesh8_matches_single_device(opt_factory):
    batches = _batches(0)
    p1 = _train(None, batches, opt_factory)
    p8 = _train(make_mesh((8,), ("data",)), batches, opt_factory)
    assert p1.keys() == p8.keys()
    for k in p1:
        np.testing.assert_allclose(p8[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


def test_mesh2x4_dp_axis_matches_single_device():
    """DP over the first axis of a 2-D mesh (model axis unused by this
    model) still reproduces the single-device trajectory."""
    batches = _batches(1)
    p1 = _train(None, batches,
                lambda: optimizer.Momentum(momentum=0.9, learning_rate=0.05))
    p24 = _train(make_mesh((2, 4), ("data", "model")), batches,
                 lambda: optimizer.Momentum(momentum=0.9, learning_rate=0.05))
    for k in p1:
        np.testing.assert_allclose(p24[k], p1[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)


@pytest.mark.parametrize("opt_factory", [
    lambda: optimizer.Momentum(momentum=0.9, learning_rate=0.05),
    lambda: optimizer.Adam(learning_rate=1e-2),
    lambda: optimizer.SparseMomentum(momentum=0.9, learning_rate=0.05),
], ids=["momentum", "adam", "sparse_momentum"])
def test_zero1_matches_zero0(opt_factory):
    """ZeRO-1 (sharded optimizer state + reduce-scatter/all-gather weight
    update, arXiv 2004.13336) must follow the SAME f32 training trajectory
    as the replicated update — the shard view changes layout, not math
    (8 batches ≥ the ≥5-step acceptance bar)."""
    batches = _batches(5)
    p0 = _train(make_mesh((8,), ("data",)), batches, opt_factory, zero=0)
    p1 = _train(make_mesh((8,), ("data",)), batches, opt_factory, zero=1)
    assert p0.keys() == p1.keys()
    for k in p0:
        np.testing.assert_allclose(p1[k], p0[k], rtol=1e-5, atol=1e-7,
                                   err_msg=k)


def test_zero1_shards_optimizer_state_8x():
    """Per-replica optimizer-state bytes drop ~8x on the 8-way mesh (exact
    8x minus padding of the non-divisible bias vectors), and the slots are
    physically flat 1/N shards, never replicated."""
    from paddle_tpu.parallel import opt_state_bytes_per_device

    batches = _batches(6, n_batches=2)
    opt = lambda: optimizer.Adam(learning_rate=1e-2)
    _, s0 = _train(make_mesh((8,), ("data",)), batches, opt, zero=0,
                   return_sgd=True)
    _, s1 = _train(make_mesh((8,), ("data",)), batches, opt, zero=1,
                   return_sgd=True)
    b0 = opt_state_bytes_per_device(s0.opt_state["slots"])
    b1 = opt_state_bytes_per_device(s1.opt_state["slots"])
    assert b0 / b1 > 7.5, (b0, b1)
    for slot in s1.opt_state["slots"].values():
        for name, arr in slot.items():
            assert arr.ndim == 1, (name, arr.shape)  # flat shard layout
            assert s1._zero_plan.is_sharded(name)


def test_hybrid_mesh_dp_parity():
    """2-slice x 4-chip hybrid 'data' mesh trains identically to a single
    device (the multi-slice DCN analog on the virtual mesh fallback)."""
    from paddle_tpu.parallel import hybrid_mesh

    mesh = hybrid_mesh((4,), (2,), ("data",))
    assert tuple(mesh.devices.shape) == (8,)
    opt = lambda: optimizer.Momentum(momentum=0.9, learning_rate=0.05)
    batches = _batches(3, n_batches=4)
    ref = _train(None, batches, opt)
    got = _train(mesh, batches, opt)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=2e-5, atol=2e-6,
                                   err_msg=k)
