"""Speculative decoding + real sampling (round 18).

Covers: SamplingParams warping/seeded draws, the n-gram and draft-model
proposers, the accept/rollback walk units, engine-level greedy parity
(spec on == spec off == oracle) with real acceptance, bit-reproducible
sampled replays, page-pressure suspension, verify-time COW forks on
shared tail pages, NaN-mid-verify isolation, injected-error retry,
lookahead page grant/rollback conservation, the sealed retrace pin
(one compile per (prefill_bucket, k+1) pair — speculation adds the k
dimension and nothing else), and fleet kill/resubmit exactly-once
streams when a tick emits multiple accepted tokens.
"""

import numpy as np
import jax
import pytest

from paddle_tpu.analysis.retrace import auditor
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (DecoderLM, DraftProposer, FaultPlan,
                                FleetFaultPlan, FleetRouter, ManualClock,
                                NGramProposer, RequestStatus,
                                SamplingParams, ServingEngine,
                                accept_tokens, greedy_decode_reference,
                                next_token, warp_probs)
from paddle_tpu.serving.kv_cache import pages_spanned
from paddle_tpu.serving.speculate import position_rng

from conftest import assert_serving_drained as assert_drained  # noqa: E402

pytestmark = [pytest.mark.spec, pytest.mark.serving]

EOS = 1


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


@pytest.fixture(scope="module")
def model_params():
    model = DecoderLM(vocab_size=64, num_layers=2, num_heads=2,
                      head_dim=8, max_positions=256)
    return model, model.init_params(jax.random.PRNGKey(0))


def _engine(model, params, **kw):
    kw.setdefault("eos_id", EOS)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 96)
    kw.setdefault("max_pages_per_seq", 16)
    kw.setdefault("max_slots", 4)
    kw.setdefault("buckets", (8, 16, 32))
    return ServingEngine(model, params, **kw)


def _run_all(eng, prompts, max_tokens=20, sampling=None):
    rids = [eng.submit(p, max_tokens=max_tokens, sampling=sampling)
            for p in prompts]
    res = eng.run()
    return rids, res


# ---------------------------------------------------------------------------
# sampling units
# ---------------------------------------------------------------------------


def test_sampling_params_validation():
    with pytest.raises(Exception):
        SamplingParams(temperature=-1.0)
    with pytest.raises(Exception):
        SamplingParams(top_p=0.0)
    with pytest.raises(Exception):
        SamplingParams(top_k=-1)
    assert SamplingParams().greedy
    assert not SamplingParams(temperature=0.7).greedy


def test_warp_probs_restricts_support():
    logits = np.array([4.0, 3.0, 2.0, 1.0, 0.0])
    s = SamplingParams(temperature=1.0, top_k=2)
    p = warp_probs(logits, s)
    assert p[2:].sum() == 0.0 and abs(p.sum() - 1.0) < 1e-12
    s = SamplingParams(temperature=1.0, top_p=0.5)
    p = warp_probs(logits, s)
    assert p[0] > 0.0 and p[2:].sum() == 0.0   # top token alone covers 0.5
    # top_p always keeps at least the argmax
    p = warp_probs(logits, SamplingParams(temperature=1.0, top_p=1e-9))
    assert p[0] == 1.0


def test_next_token_greedy_and_seeded():
    logits = np.array([0.1, 2.0, 0.3, 0.4])
    assert next_token(logits, None, 0) == 1
    assert next_token(logits, SamplingParams(), 5) == 1
    s = SamplingParams(temperature=1.0, seed=9)
    draws = {next_token(logits, s, pos) for pos in range(50)}
    assert len(draws) > 1                      # actually random over pos
    for pos in range(10):                      # but pure in (seed, pos)
        assert next_token(logits, s, pos) == next_token(logits, s, pos)
    # different seeds decorrelate
    s2 = SamplingParams(temperature=1.0, seed=10)
    assert any(next_token(logits, s, p) != next_token(logits, s2, p)
               for p in range(20))


def test_position_rng_is_counter_based():
    a = position_rng(3, 7).random_sample()
    b = position_rng(3, 7).random_sample()
    c = position_rng(3, 8).random_sample()
    assert a == b and a != c


# ---------------------------------------------------------------------------
# proposer units
# ---------------------------------------------------------------------------


def test_ngram_proposer_matches_most_recent():
    p = NGramProposer(n=2)
    #       0  1  2  3  4  5  6  7  8
    hist = [5, 6, 9, 9, 5, 6, 7, 5, 6]
    # suffix [5, 6] matched at its most recent earlier occurrence with
    # a full-k continuation (ending index 6) -> what followed: [7, 5]
    assert p.propose_one(hist, 2) == [7, 5]
    # k=4: the recent match only continues 3 tokens; the earlier full
    # match (ending index 2) wins with all 4
    assert p.propose_one(hist, 4) == [9, 9, 5, 6]
    # inside a constant run the nearest match is truncated — a full-k
    # proposal still comes from one period earlier
    assert p.propose_one([7, 3, 3, 3, 3, 3], 3) == [3, 3, 3]


def test_ngram_proposer_suffix_fallback_and_miss():
    p = NGramProposer(n=3)
    assert p.propose_one([1, 2, 3, 4], 2) == []          # nothing repeats
    # 3-gram misses, 1-gram [4] hits at index 1 -> proposes [9]
    assert p.propose_one([4, 9, 7, 4], 2) == [9, 7]
    assert p.propose_one([4], 2) == []                   # too short
    assert p.propose_one([4, 4], 0) == []                # k = 0


def test_pages_spanned():
    assert list(pages_spanned(0, 1, 8)) == [0]
    assert list(pages_spanned(7, 1, 8)) == [0]
    assert list(pages_spanned(7, 2, 8)) == [0, 1]
    assert list(pages_spanned(8, 5, 8)) == [1]
    assert list(pages_spanned(6, 12, 8)) == [0, 1, 2]
    assert list(pages_spanned(4, 0, 8)) == []


# ---------------------------------------------------------------------------
# accept walk units
# ---------------------------------------------------------------------------


def _rows(*argmaxes, v=16):
    out = np.full((len(argmaxes), v), -5.0)
    for i, a in enumerate(argmaxes):
        out[i, a] = 5.0
    return out


def test_accept_greedy_full_acceptance_emits_bonus():
    rows = _rows(3, 4, 5)
    emitted, acc = accept_tokens(rows, [3, 4], None, None, 0, EOS)
    assert emitted == [3, 4, 5] and acc == 2


def test_accept_greedy_rejection_emits_target_token():
    rows = _rows(3, 7, 5)
    emitted, acc = accept_tokens(rows, [3, 4], None, None, 0, EOS)
    assert emitted == [3, 7] and acc == 1      # draft 4 != target 7


def test_accept_greedy_immediate_reject_is_plain_decode():
    rows = _rows(9)
    emitted, acc = accept_tokens(rows, [], None, None, 0, EOS)
    assert emitted == [9] and acc == 0
    emitted, acc = accept_tokens(_rows(9, 2), [3], None, None, 0, EOS)
    assert emitted == [9] and acc == 0


def test_accept_greedy_eos_stops_walk():
    rows = _rows(EOS, 4, 5)
    emitted, acc = accept_tokens(rows, [EOS, 4], None, None, 0, EOS)
    assert emitted == [EOS] and acc == 1       # accepted EOS: no bonus


def test_accept_rejection_sampling_point_mass():
    s = SamplingParams(temperature=1.0, seed=0)
    # target puts ~all mass on 3; draft proposes 3 -> accept w.p. ~1
    rows = _rows(3, 6)
    emitted, acc = accept_tokens(rows, [3], None, s, 0, EOS)
    assert emitted[0] == 3 and acc == 1
    # target mass on 2, draft proposes 3 (point mass): p(3)/q(3) ~ 0 ->
    # reject; the residual zeroes the draft token, so the sample != 3
    rows = _rows(2, 6)
    emitted, acc = accept_tokens(rows, [3], None, s, 0, EOS)
    assert acc == 0 and emitted[0] != 3
    # deterministic across calls (counter-based RNG)
    again, acc2 = accept_tokens(rows, [3], None, s, 0, EOS)
    assert again == emitted and acc2 == acc


# ---------------------------------------------------------------------------
# engine: parity, acceptance, tick reduction
# ---------------------------------------------------------------------------


def _prompts(rng, n=6, lo=4, hi=20, vocab=64):
    return [rng.randint(2, vocab, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]


def test_ngram_greedy_token_identical_and_fewer_ticks(model_params):
    model, params = model_params
    prompts = _prompts(np.random.RandomState(0))

    def replay(mode):
        eng = _engine(model, params, spec_mode=mode, spec_k=4)
        rids, res = _run_all(eng, prompts, max_tokens=24)
        assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
        assert_drained(eng)
        return [res[r] for r in rids], eng.metrics.snapshot()

    off, snap_off = replay("off")
    on, snap_on = replay("ngram")
    assert on == off                           # token-identical
    assert snap_on["spec_tokens_accepted"] > 0  # speculation really ran
    assert snap_on["ticks"] < snap_off["ticks"]
    assert snap_on["spec_rollbacks"] > 0       # rejects exercised too
    want = greedy_decode_reference(model, params, prompts[0], 24, EOS)
    assert on[0] == want


def test_spec_off_signature_unchanged(model_params):
    """A spec-off engine builds k1=1 steps — one verify row per slot,
    the exact pre-speculation shape."""
    model, params = model_params
    eng = _engine(model, params)
    assert eng._k1 == 1 and eng._proposer is None
    eng.submit([3, 4, 5], max_tokens=3)
    eng.run()
    assert all(k1 == 1 for (_pb, k1) in eng._step_fns)
    assert_drained(eng)


def test_draft_proposer_greedy_parity(model_params):
    """Draft model == target model: near-total acceptance, and the
    emitted stream stays token-identical (greedy acceptance is exact
    match, so ANY draft model preserves parity — a perfect one just
    accepts more)."""
    model, params = model_params
    prompts = _prompts(np.random.RandomState(1), n=4)
    off_eng = _engine(model, params)
    _, off = _run_all(off_eng, prompts, max_tokens=16)
    eng = _engine(model, params, spec_mode="draft", spec_k=3,
                  draft_model=model, draft_params=params)
    rids, res = _run_all(eng, prompts, max_tokens=16)
    assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
    snap = eng.metrics.snapshot()
    assert [res[r] for r in rids] == list(off.values())
    # a perfect draft accepts (nearly) everything it proposes
    assert snap["spec_acceptance_rate"] > 0.9
    assert snap["draft_steps"] > 0
    assert_drained(eng)                        # draft pool checked too
    assert eng._proposer.pool.total_refs == 0  # draft states released


def test_draft_model_vocab_mismatch_rejected(model_params):
    model, params = model_params
    bad = DecoderLM(vocab_size=32, num_layers=1, num_heads=2, head_dim=8)
    with pytest.raises(Exception, match="vocab"):
        _engine(model, params, spec_mode="draft", draft_model=bad,
                draft_params=bad.init_params(jax.random.PRNGKey(0)))
    with pytest.raises(Exception, match="draft_model"):
        _engine(model, params, spec_mode="draft")


def test_sampled_replays_bit_identical(model_params):
    model, params = model_params
    prompts = _prompts(np.random.RandomState(2), n=4)
    sp = SamplingParams(temperature=0.8, top_k=20, top_p=0.95, seed=42)

    def replay(mode):
        eng = _engine(model, params, spec_mode=mode, spec_k=3)
        rids, res = _run_all(eng, prompts, max_tokens=16, sampling=sp)
        assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
        assert_drained(eng)
        return [res[r] for r in rids], eng.metrics.snapshot()

    a1, _ = replay("ngram")
    a2, _ = replay("ngram")
    assert a1 == a2                            # bit-reproducible
    g_eng = _engine(model, params)
    _, g = _run_all(g_eng, prompts, max_tokens=16)
    assert a1 != list(g.values())              # actually sampled
    b1, _ = replay("off")
    b2, _ = replay("off")
    assert b1 == b2


def test_per_request_seeds_decorrelate(model_params):
    model, params = model_params
    eng = _engine(model, params)
    prompt = [7, 9, 11, 13]
    r1 = eng.submit(prompt, max_tokens=12,
                    sampling=SamplingParams(temperature=1.0, seed=1))
    r2 = eng.submit(prompt, max_tokens=12,
                    sampling=SamplingParams(temperature=1.0, seed=2))
    res = eng.run()
    assert res[r1] != res[r2]
    assert_drained(eng)


# ---------------------------------------------------------------------------
# page pressure, lookahead charging, rollback, COW
# ---------------------------------------------------------------------------


def test_lookahead_grant_and_rollback_pages(model_params):
    model, params = model_params
    eng = _engine(model, params, spec_mode="ngram", spec_k=4)
    rid = eng.submit([3, 4] * 4, max_tokens=2)
    eng.step()
    req = eng._requests[rid]
    assert req.slot is not None
    base = len(req.pages)
    live0 = eng.pool.num_live
    granted = eng.scheduler.grant_lookahead(req, 16)
    assert granted >= 1
    assert len(req.pages) > base               # lookahead pages charged
    assert eng.pool.num_live == live0 + (len(req.pages) - base)
    freed = eng.scheduler.rollback_pages(req)
    # rolled back to exactly the next-append charge admission makes
    assert freed > 0
    assert len(req.pages) == max(
        1, -(-(req.cache_len + 1) // eng.kv_cfg.page_size))
    assert eng.pool.num_live == live0
    eng.run()
    assert_drained(eng)


def test_speculation_suspended_under_page_pressure(model_params):
    """A pool with zero slack: growth preemption and/or a dry free list
    suspends speculation (spec_suspended counts), everything still
    completes with parity and no leaks."""
    model, params = model_params
    rng = np.random.RandomState(3)
    # repetitive 12-token prompts: the n-gram proposer WANTS to draft,
    # but once both running slots grow to 3 pages they hold all 6
    # usable pages, and the dry free list suspends speculation
    prompts = [rng.randint(2, 64, size=3).tolist() * 4 for _ in range(4)]
    ctrl = _engine(model, params)
    _, off = _run_all(ctrl, prompts, max_tokens=12)
    eng = _engine(model, params, num_pages=7, max_pages_per_seq=6,
                  max_slots=2, spec_mode="ngram", spec_k=4,
                  prefix_cache=False)
    rids, res = _run_all(eng, prompts, max_tokens=12)
    assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
    assert [res[r] for r in rids] == list(off.values())
    assert eng.metrics.spec_suspended > 0
    assert_drained(eng)


def test_cow_guard_forks_shared_verify_page(model_params):
    """A shared tail page (simulated second holder) is COW-forked
    before the verify writes into it: the sharer's K/V bytes stay
    bit-identical, the fork is counted, and refcounts conserve."""
    model, params = model_params
    eng = _engine(model, params, spec_mode="ngram", spec_k=3)
    rid = eng.submit([5, 6] * 3, max_tokens=16)
    for _ in range(4):
        eng.step()
    req = eng._requests[rid]
    assert req.status is RequestStatus.RUNNING and not req.prefilling
    tail_idx = req.cache_len // eng.kv_cfg.page_size
    shared = req.pages[tail_idx]
    eng.pool.ref([shared])                     # simulate a sharer
    before = np.asarray(eng._kv.k[:, shared]).copy()
    snap0 = eng.metrics.spec_cow_forks
    for _ in range(6):
        eng.step()
    assert eng.metrics.spec_cow_forks > snap0
    assert req.pages[tail_idx] != shared       # table entry swapped
    after = np.asarray(eng._kv.k[:, shared])
    np.testing.assert_array_equal(before, after)
    assert eng.pool.refcount(shared) == 1      # only the sharer's ref
    eng.pool.free([shared])                    # release the fake sharer
    eng.run()
    assert_drained(eng)


# ---------------------------------------------------------------------------
# chaos: NaN mid-verify, injected errors, preemption
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_nan_mid_verify_fails_only_poisoned(model_params):
    model, params = model_params
    prompts = _prompts(np.random.RandomState(4), n=4)
    ctrl = _engine(model, params)
    _, off = _run_all(ctrl, prompts, max_tokens=14)
    plan = FaultPlan(clock=ManualClock(tick_s=0.01))
    eng = _engine(model, params, spec_mode="ngram", spec_k=3,
                  faults=plan)
    rids = [eng.submit(p, max_tokens=14) for p in prompts]
    eng.step()
    eng.step()
    plan.poison_nan(rids[1])                   # NaN lands mid-verify
    res = eng.run()
    assert eng.status(rids[1]) is RequestStatus.FAILED
    for j, rid in enumerate(rids):
        if j == 1:
            continue
        assert eng.status(rid) is RequestStatus.COMPLETED
        assert res[rid] == list(off.values())[j]   # batchmates keep parity
    assert_drained(eng)


@pytest.mark.faults
def test_transient_decode_errors_retried_with_spec(model_params):
    model, params = model_params
    prompts = _prompts(np.random.RandomState(5), n=3)
    ctrl = _engine(model, params)
    _, off = _run_all(ctrl, prompts, max_tokens=12)
    plan = FaultPlan(clock=ManualClock(tick_s=0.01),
                     decode_errors={2: 1, 5: 2})
    eng = _engine(model, params, spec_mode="ngram", spec_k=3,
                  faults=plan)
    rids, res = _run_all(eng, prompts, max_tokens=12)
    assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
    assert [res[r] for r in rids] == list(off.values())
    assert eng.metrics.retries >= 2
    assert_drained(eng)


@pytest.mark.faults
def test_preemption_with_spec_keeps_parity(model_params):
    """Fault-plan page pressure forces preemption + re-prefill while
    speculating: the replayed stream is still token-identical."""
    model, params = model_params
    prompts = _prompts(np.random.RandomState(6), n=4, lo=6, hi=16)
    ctrl = _engine(model, params)
    _, off = _run_all(ctrl, prompts, max_tokens=12)
    plan = FaultPlan(clock=ManualClock(tick_s=0.01),
                     page_pressure=(3, 12, 30))
    eng = _engine(model, params, num_pages=48, max_pages_per_seq=8,
                  max_slots=2, spec_mode="ngram", spec_k=3, faults=plan)
    rids, res = _run_all(eng, prompts, max_tokens=12)
    assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
    assert [res[r] for r in rids] == list(off.values())
    assert_drained(eng)


@pytest.mark.faults
def test_preemption_releases_draft_state(model_params):
    """A preempted request's draft-model cache is released immediately
    (not at terminal), so preemption churn cannot pin draft-pool pages
    and starve the slots that are still running."""
    model, params = model_params
    # the pressure window drains the free list, which first SUSPENDS
    # speculation (opportunistic lookahead never preempts) and then
    # forces the plain growth path to preempt the youngest slot
    plan = FaultPlan(clock=ManualClock(tick_s=0.01),
                     page_pressure=(2, 30, 40))
    eng = _engine(model, params, num_pages=10, max_pages_per_seq=8,
                  max_slots=2, prefix_cache=False, spec_mode="draft",
                  spec_k=2, draft_model=model, draft_params=params,
                  draft_pool_pages=64, faults=plan)
    rids = [eng.submit([6, 7] * 4, max_tokens=12) for _ in range(4)]
    saw_preempt = False
    for _ in range(60):
        eng.step()
        for rid in rids:
            req = eng._requests[rid]
            if req.status is RequestStatus.PREEMPTED:
                saw_preempt = True
                assert rid not in eng._proposer._state
        if not eng.has_work:
            break
    assert saw_preempt, "pressure window produced no preemption"
    eng.run()
    assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
    assert eng._proposer.pool.total_refs == 0
    assert_drained(eng)


# ---------------------------------------------------------------------------
# retrace: one compile per (bucket, k+1)
# ---------------------------------------------------------------------------


@pytest.fixture
def audit():
    old = FLAGS.jit_audit
    FLAGS.jit_audit = True
    auditor().reset()
    yield auditor()
    FLAGS.jit_audit = old
    auditor().reset()


def test_sealed_spec_step_one_compile_per_bucket_k(audit, model_params):
    """The acceptance pin: a sealed speculative steady state compiles
    serving.step exactly once per (prefill_bucket, k1) pair — the k
    dimension is the ONLY thing speculation adds — and a fresh replay
    over the same shapes compiles nothing new."""
    model, params = model_params
    rng = np.random.RandomState(7)
    eng = _engine(model, params, spec_mode="ngram", spec_k=3,
                  prefill_chunk=8)

    def burst():
        eng.submit((rng.randint(2, 64, size=3).tolist()) * 3,
                   max_tokens=10)
        eng.step()
        eng.submit(rng.randint(2, 64, size=6).tolist(), max_tokens=8)
        eng.run(max_ticks=300)

    burst()
    pairs = audit.compile_count("serving.step")
    assert pairs == len(eng._step_fns)         # one compile per pair
    assert all(k1 == eng._k1 == 4 for (_pb, k1) in eng._step_fns)
    audit.seal()
    burst()                                    # steady state: no compiles
    audit.assert_budget("serving.step", pairs)
    assert audit.diagnostics == []
    assert_drained(eng)


def test_draft_site_audited(audit, model_params):
    model, params = model_params
    eng = _engine(model, params, spec_mode="draft", spec_k=2,
                  draft_model=model, draft_params=params)
    eng.submit([9, 8] * 3, max_tokens=8)
    eng.run(max_ticks=200)
    assert audit.compile_count("serving.draft") >= 1
    rec = audit.sites["serving.draft"]
    assert rec.contract is not None
    assert 1 in rec.jit_kwargs["donate_argnums"]
    assert_drained(eng)


def test_spec_metrics_published(model_params):
    model, params = model_params
    eng = _engine(model, params, spec_mode="ngram", spec_k=3)
    rid = eng.submit([4, 5] * 4, max_tokens=12)
    eng.run()
    hz = eng.healthz()
    snap = hz["metrics"]
    assert "serving_spec_tokens_proposed" in snap
    assert "serving_spec_acceptance_rate" in snap
    assert "serving_spec_rollbacks" in snap
    req = eng._requests[rid]
    assert req.spec_proposed >= req.spec_accepted >= 0
    assert hz["ok"]


# ---------------------------------------------------------------------------
# fleet: exactly-once with multi-token ticks
# ---------------------------------------------------------------------------


@pytest.mark.fleet
@pytest.mark.faults
def test_fleet_kill_resubmit_exactly_once_with_spec(model_params):
    """A replica dies mid-decode while its slots speculate (multiple
    accepted tokens per tick): the resubmitted replay's on_token stream
    stays exactly-once (high-water mark — no token re-emitted, none
    skipped) and matches the final results token-for-token."""
    model, params = model_params
    plan = FleetFaultPlan(clock=ManualClock(tick_s=0.01), kill_at={6: 0})

    def mk(i, time_fn):
        return ServingEngine(model, params, eos_id=EOS, page_size=8,
                             num_pages=64, max_pages_per_seq=12,
                             max_slots=2, buckets=(8, 16),
                             spec_mode="ngram", spec_k=3,
                             time_fn=time_fn)

    fl = FleetRouter(mk, 2, faults=plan, heartbeat_s=0.05,
                     resubmit_budget=2)
    rng = np.random.RandomState(8)
    streams = {}
    frids = []
    for j in range(6):
        prompt = (rng.randint(2, 64, size=3).tolist()) * 3
        stream = []
        frid = fl.submit(prompt, max_tokens=14,
                         on_token=stream.append)
        streams[frid] = (prompt, stream)
        frids.append(frid)
    res = fl.run(max_ticks=500)
    assert fl.metrics.duplicate_completions == 0
    assert fl.metrics.resubmits >= 1           # the kill displaced work
    spec_accepted = sum(
        rep.engine.metrics.spec_tokens_accepted for rep in fl.replicas)
    assert spec_accepted > 0                   # multi-token ticks happened
    for frid in frids:
        assert fl.status(frid) is RequestStatus.COMPLETED
        prompt, stream = streams[frid]
        assert res[frid] == stream             # exactly-once, in order
        want = greedy_decode_reference(model, params, prompt, 14, EOS)
        assert res[frid] == want
    fl.check_fleet_conservation()
