"""Fault-tolerant prefix-aware serving fleet (round 11).

Every replica lifecycle transition, death-retry routing, and the
fleet-level conservation contract, driven end-to-end on ONE injected
clock — no wall-clock sleeps anywhere (the lint wall-clock rule holds
on ``fleet.py`` with zero escapes), mirroring how the single-engine
chaos suite drives its FaultPlan.
"""

import jax
import numpy as np
import pytest

from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving import (DecoderLM, FleetFaultPlan, FleetRouter,
                                ManualClock, PageLeakError, ReplicaState,
                                RequestStatus, ServingEngine,
                                greedy_decode_reference,
                                prefix_chain_hashes)
from paddle_tpu.serving.kv_cache import PrefixCache

from conftest import assert_serving_drained as assert_drained  # noqa: E402

serving = pytest.mark.serving
faults = pytest.mark.faults
fleet_mark = pytest.mark.fleet

pytestmark = [serving, faults, fleet_mark]

PAGE = 4
EOS = 1


@pytest.fixture(autouse=True)
def f32():
    old = FLAGS.use_bf16
    FLAGS.use_bf16 = False
    yield
    FLAGS.use_bf16 = old


@pytest.fixture(scope="module")
def model_params():
    model = DecoderLM(vocab_size=50, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=128)
    return model, model.init_params(jax.random.PRNGKey(0))


def _make_fleet(model, params, n=2, plan=None, **kw):
    if plan is None:
        plan = FleetFaultPlan(clock=ManualClock(tick_s=0.01))
    engine_kw = dict(eos_id=EOS, page_size=PAGE, num_pages=32,
                     max_pages_per_seq=8, max_slots=2, buckets=(4, 8))
    engine_kw.update(kw.pop("engine_kw", {}))
    kw.setdefault("heartbeat_s", 0.05)
    kw.setdefault("resubmit_budget", 2)

    def mk(i, time_fn):
        return ServingEngine(model, params, time_fn=time_fn, **engine_kw)

    return FleetRouter(mk, n, faults=plan, **kw), plan


def _prompts(rng, n, shared=0, lo=3, hi=9):
    sysp = rng.randint(2, 50, size=shared).tolist() if shared else []
    return [sysp + rng.randint(2, 50, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]


def _drain_all(fl, max_ticks=400):
    out = fl.run(max_ticks=max_ticks)
    assert not fl.has_work, "fleet failed to drain"
    return out


def _assert_fleet_drained(fl):
    fl.check_fleet_conservation()
    for rep in fl.replicas:
        assert rep.engine.pool.total_refs == 0
        if rep.state is not ReplicaState.DEAD:
            assert_drained(rep.engine)
    assert fl.metrics.duplicate_completions == 0


# ---------------------------------------------------------------------------
# replica lifecycle: every transition
# ---------------------------------------------------------------------------


def test_initial_replicas_come_up_ready(model_params):
    fl, _ = _make_fleet(*model_params, n=3)
    assert [r.state for r in fl.replicas] == [ReplicaState.READY] * 3
    hz = fl.healthz()
    assert hz["ok"] and hz["ready"] == 3


def test_join_is_observable_then_promoted(model_params):
    fl, _ = _make_fleet(*model_params, n=1)
    idx = fl.add_replica()
    assert fl.replica_state(idx) is ReplicaState.JOINING   # JOINING tick
    fl.step()
    assert fl.replica_state(idx) is ReplicaState.READY     # -> READY


def test_drain_stops_routing_finishes_work_then_dead(model_params):
    model, params = model_params
    rng = np.random.RandomState(0)
    fl, _ = _make_fleet(model, params, n=2)
    prompts = _prompts(rng, 4)
    frids = [fl.submit(p, max_tokens=4) for p in prompts]
    target = 0
    fl.drain_replica(target)
    assert fl.replica_state(target) is ReplicaState.DRAINING
    # new traffic only lands on the survivor
    extra = fl.submit(_prompts(rng, 1)[0], max_tokens=3)
    assert fl._requests[extra].replica == 1
    _drain_all(fl)
    # READY -> DRAINING -> DEAD (clean retirement), work all finished
    assert fl.replica_state(target) is ReplicaState.DEAD
    assert fl.replicas[target].dead_reason == "drained"
    for f in frids + [extra]:
        assert fl.status(f) is RequestStatus.COMPLETED
    assert fl.metrics.replicas_drained == 1
    _assert_fleet_drained(fl)


def test_drain_join_elasticity_round_trip(model_params):
    """Drain one replica out, join a fresh one, keep serving: the fleet
    shape changes under live traffic without losing a request."""
    model, params = model_params
    rng = np.random.RandomState(1)
    fl, _ = _make_fleet(model, params, n=2)
    first = [fl.submit(p, max_tokens=3) for p in _prompts(rng, 3)]
    for _ in range(2):
        fl.step()
    fl.drain_replica(0)
    idx = fl.add_replica()
    for _ in range(2):
        fl.step()
    assert fl.replica_state(idx) is ReplicaState.READY
    second = [fl.submit(p, max_tokens=3) for p in _prompts(rng, 3)]
    # the drained replica takes no new bindings
    assert all(fl._requests[f].replica != 0 for f in second)
    _drain_all(fl)
    for f in first + second:
        assert fl.status(f) is RequestStatus.COMPLETED
    assert fl.replica_state(0) is ReplicaState.DEAD
    _assert_fleet_drained(fl)


def test_missed_heartbeats_mark_replica_dead(model_params):
    """READY -> DEAD via lease expiry: a heartbeat partition longer than
    the TTL kills the replica without any explicit kill call."""
    model, params = model_params
    plan = FleetFaultPlan(clock=ManualClock(tick_s=0.01),
                          partitions={0: (1, 10_000)})
    fl, _ = _make_fleet(model, params, n=2, plan=plan, heartbeat_s=0.03)
    # long enough to still be decoding when the TTL (3 heartbeats ~ 9
    # ticks) lapses — the death must catch it in flight
    frid = fl.submit([5, 6, 7], max_tokens=25)
    assert fl._requests[frid].replica == 0   # least-loaded pick is 0
    for _ in range(15):
        fl.step()
        if fl.replica_state(0) is ReplicaState.DEAD:
            break
    assert fl.replica_state(0) is ReplicaState.DEAD
    assert not fl._requests[frid].finished, \
        "setup: the request must outlive its replica"
    assert "lease" in fl.replicas[0].dead_reason
    _drain_all(fl)
    # the request survived its replica's death via resubmission
    assert fl.status(frid) is RequestStatus.COMPLETED
    assert fl.metrics.resubmits >= 1
    _assert_fleet_drained(fl)


def test_zombie_lease_token_cannot_ack_after_reclaim(model_params):
    """The master's zombie-fencing semantics, at fleet level: a DEAD
    replica's (slot, token) can never heartbeat again — even after a
    new replica reclaims the same slot number."""
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=2)
    slot, token = fl.replicas[0].slot, fl.replicas[0].token
    fl.kill_replica(0)
    assert fl.replica_state(0) is ReplicaState.DEAD
    assert fl._lease.heartbeat(slot, token) is False      # lease dropped
    idx = fl.add_replica()                                # reclaims slot 0
    assert fl.replicas[idx].slot == slot
    assert fl._lease.heartbeat(slot, token) is False      # token mismatch
    assert fl._lease.heartbeat(fl.replicas[idx].slot,
                               fl.replicas[idx].token) is True


# ---------------------------------------------------------------------------
# death-retry routing
# ---------------------------------------------------------------------------


def test_kill_mid_decode_resubmits_token_identical(model_params):
    """The headline robustness claim: kill the replica holding running
    decodes mid-trace; every request still completes with EXACTLY the
    tokens a single healthy engine (and the non-paged oracle) produces,
    and nothing completes twice."""
    model, params = model_params
    rng = np.random.RandomState(2)
    fl, plan = _make_fleet(model, params, n=2)
    prompts = _prompts(rng, 4, shared=PAGE)   # one shared full page
    frids = [fl.submit(p, max_tokens=6) for p in prompts]
    for _ in range(3):
        fl.step()                             # decode is mid-flight
    victim = fl._requests[frids[0]].replica
    in_flight = [f for f in frids
                 if fl._requests[f].replica == victim
                 and not fl._requests[f].finished]
    assert in_flight, "setup: victim replica must hold live requests"
    fl.kill_replica(victim, "kill mid-decode")
    results = _drain_all(fl)
    assert fl.metrics.resubmits >= len(in_flight)
    for f, p in zip(frids, prompts):
        assert fl.status(f) is RequestStatus.COMPLETED
        want = greedy_decode_reference(model, params, p, 6, EOS)
        assert results[f] == want, "kill-resubmit broke greedy parity"
    _assert_fleet_drained(fl)


def test_on_token_stream_is_exactly_once_across_kill(model_params):
    model, params = model_params
    rng = np.random.RandomState(3)
    fl, _ = _make_fleet(model, params, n=2)
    # deterministically pick a prompt whose greedy trajectory doesn't
    # hit EOS early — the kill must land mid-stream
    prompt = want = None
    while True:
        cand = _prompts(rng, 1, shared=PAGE)[0]
        ref = greedy_decode_reference(model, params, cand, 12, EOS)
        if len(ref) >= 8:
            prompt, want = cand, ref
            break
    seen = []
    frid = fl.submit(prompt, max_tokens=12, on_token=seen.append)
    for _ in range(3):
        fl.step()
    assert seen, "setup: some tokens must stream before the kill"
    assert not fl._requests[frid].finished, \
        "setup: the stream must be mid-flight at the kill"
    fl.kill_replica(fl._requests[frid].replica)
    _drain_all(fl)
    # the replayed prefix was NOT re-delivered: one copy of each token
    assert seen == want
    assert fl.result(frid) == want
    _assert_fleet_drained(fl)


def test_resubmit_budget_exhaustion_ends_failed(model_params):
    """Serial kills burn the budget; the request ends FAILED — a real
    terminal status, not an infinite kill->resubmit loop."""
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=3, resubmit_budget=1)
    frid = fl.submit([3, 4, 5, 6], max_tokens=8)
    fl.step()
    fl.kill_replica(fl._requests[frid].replica)      # resubmit #1
    assert not fl._requests[frid].finished
    fl.step()
    fl.kill_replica(fl._requests[frid].replica)      # budget burned
    assert fl.status(frid) is RequestStatus.FAILED
    # only the re-dispatch that actually happened is counted; the
    # refused second one is not
    assert fl._requests[frid].resubmits == 1
    assert fl.metrics.resubmits == 1
    _drain_all(fl)
    _assert_fleet_drained(fl)


def test_correlated_deaths_fence_before_resubmit(model_params):
    """Two replicas lapse on the SAME lease sweep (one partition taking
    out both): the displaced request must not burn its resubmit budget
    on a dispatch to the other doomed replica — every death in the
    sweep is fenced first, then resubmission sees only true survivors."""
    model, params = model_params
    plan = FleetFaultPlan(clock=ManualClock(tick_s=0.01),
                          partitions={0: (1, 10_000), 1: (1, 10_000)})
    fl, _ = _make_fleet(model, params, n=3, plan=plan, heartbeat_s=0.03,
                        resubmit_budget=1)
    # victim on replica 0 (first least-loaded pick), a short filler on 1
    # that FINISHES before the sweep (so 1 looks idle — the tempting
    # wrong resubmit target), a long filler keeping 2 busy (so the
    # survivor looks WORSE by load than the doomed idle replica)
    frid = fl.submit([5, 6, 7], max_tokens=25)
    f_short = fl.submit([8, 9, 10], max_tokens=1)
    f_long = fl.submit([11, 12, 13], max_tokens=20)
    assert [fl._requests[f].replica for f in (frid, f_short, f_long)] \
        == [0, 1, 2]
    for _ in range(15):
        fl.step()
        if fl.replica_state(0) is ReplicaState.DEAD:
            break
    # both lapsed on the same sweep
    assert fl.replica_state(0) is ReplicaState.DEAD
    assert fl.replica_state(1) is ReplicaState.DEAD
    assert not fl._requests[frid].finished, \
        "setup: the victim must outlive its replica"
    # ONE resubmit, straight to the sole survivor — budget intact
    assert fl._requests[frid].replica == 2
    assert fl._requests[frid].resubmits == 1
    _drain_all(fl)
    assert fl.status(frid) is RequestStatus.COMPLETED
    _assert_fleet_drained(fl)


def test_no_ready_replica_rejects_submit(model_params):
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=2, resubmit_budget=0)
    fl.kill_replica(0)
    fl.kill_replica(1)
    frid = fl.submit([2, 3, 4], max_tokens=2)
    assert fl.status(frid) is RequestStatus.REJECTED
    _drain_all(fl)
    _assert_fleet_drained(fl)


def test_deadline_carries_over_resubmit_no_fresh_budget(model_params):
    """A request resubmitted after its replica dies keeps its ORIGINAL
    absolute deadline: the re-prefill cannot mint a new time budget, so
    an unmeetable deadline ends TIMED_OUT/shed, never COMPLETED late."""
    model, params = model_params
    plan = FleetFaultPlan(clock=ManualClock(tick_s=0.01))
    fl, _ = _make_fleet(model, params, n=2, plan=plan)
    # 20 tokens at ~1 token/tick (0.01s): ~0.2s of work against a 0.08s
    # deadline, doomed only AFTER the kill forces a restart
    frid = fl.submit([2, 3, 4, 5], max_tokens=20, deadline_s=0.12)
    for _ in range(4):
        fl.step()
    fl.kill_replica(fl._requests[frid].replica)
    _drain_all(fl)
    assert fl.status(frid) in (RequestStatus.TIMED_OUT,
                               RequestStatus.REJECTED)
    assert fl._requests[frid].terminal_transitions == 1
    _assert_fleet_drained(fl)


# ---------------------------------------------------------------------------
# routing: prefix affinity, load balancing, overflow
# ---------------------------------------------------------------------------


def test_shared_prefix_routes_to_owner_replica(model_params):
    model, params = model_params
    rng = np.random.RandomState(4)
    # a high overflow limit isolates pure affinity (all 5 submits land
    # before a single tick runs, so the owner's queue is briefly deep)
    fl, _ = _make_fleet(model, params, n=3, overflow_queue_depth=32)
    sysp = rng.randint(2, 50, size=2 * PAGE).tolist()
    frids = [fl.submit(sysp + rng.randint(2, 50, size=3).tolist(),
                       max_tokens=2) for _ in range(5)]
    owners = {fl._requests[f].replica for f in frids}
    assert len(owners) == 1, f"shared prefix split across {owners}"
    assert fl.metrics.affinity_hits >= 4     # all but the first submit
    _drain_all(fl)
    # the owner's engine saw real prefix-cache hits from the co-routing
    owner = owners.pop()
    assert fl.replicas[owner].engine.metrics.prefix_hit_rate() > 0.3
    _assert_fleet_drained(fl)


def test_routing_key_is_the_prefix_cache_key(model_params):
    """The router and the cache agree by construction: the chain hashes
    the router keys on are exactly the keys a PrefixCache would index
    the same tokens under."""
    from paddle_tpu.serving.kv_cache import PagePool

    rng = np.random.RandomState(5)
    toks = rng.randint(2, 50, size=3 * PAGE + 2).tolist()
    hashes = prefix_chain_hashes(toks, PAGE)
    assert len(hashes) == 3                  # full pages only
    pool = PagePool(8)
    cache = PrefixCache(pool, PAGE)
    pages = pool.alloc(3)
    cache.insert(toks, pages, 3 * PAGE)
    assert [cache._index[h].page for h in hashes] == pages


def test_distinct_prefixes_balance_by_load(model_params):
    model, params = model_params
    rng = np.random.RandomState(6)
    fl, _ = _make_fleet(model, params, n=2)
    frids = [fl.submit(p, max_tokens=2) for p in _prompts(rng, 6)]
    used = {fl._requests[f].replica for f in frids}
    assert used == {0, 1}, "no-affinity traffic should spread"
    _drain_all(fl)
    _assert_fleet_drained(fl)


def test_affinity_overflows_to_least_loaded_when_saturated(model_params):
    model, params = model_params
    rng = np.random.RandomState(7)
    fl, _ = _make_fleet(model, params, n=2, overflow_queue_depth=2)
    sysp = rng.randint(2, 50, size=PAGE).tolist()
    frids = [fl.submit(sysp + rng.randint(2, 50, size=3).tolist(),
                       max_tokens=2) for _ in range(8)]
    used = {fl._requests[f].replica for f in frids}
    assert len(used) == 2, "owner saturated: overflow must spill"
    _drain_all(fl)
    _assert_fleet_drained(fl)


def test_round_robin_control_policy_spreads_evenly(model_params):
    model, params = model_params
    rng = np.random.RandomState(8)
    fl, _ = _make_fleet(model, params, n=2, routing="round_robin")
    sysp = rng.randint(2, 50, size=PAGE).tolist()
    frids = [fl.submit(sysp + rng.randint(2, 50, size=3).tolist(),
                       max_tokens=2) for _ in range(6)]
    by_rep = [sum(1 for f in frids if fl._requests[f].replica == i)
              for i in range(2)]
    assert by_rep == [3, 3]
    assert fl.metrics.affinity_hits == 0
    _drain_all(fl)
    _assert_fleet_drained(fl)


def test_slow_replica_fault_and_fleet_still_drains(model_params):
    """A slow replica (steps every 3rd fleet tick) stretches the drain
    in FLEET ticks — its per-engine work is unchanged, it just runs
    less often — and nothing is lost."""
    model, params = model_params

    def ticks_to_drain(plan):
        rng = np.random.RandomState(9)
        fl, _ = _make_fleet(model, params, n=2, plan=plan)
        frids = [fl.submit(p, max_tokens=3) for p in _prompts(rng, 6)]
        _drain_all(fl)
        for f in frids:
            assert fl.status(f) is RequestStatus.COMPLETED
        _assert_fleet_drained(fl)
        return fl._tick

    fast = ticks_to_drain(FleetFaultPlan(clock=ManualClock(tick_s=0.01)))
    slow = ticks_to_drain(FleetFaultPlan(clock=ManualClock(tick_s=0.01),
                                         slow_replicas={1: 3}))
    assert slow > fast, (slow, fast)


# ---------------------------------------------------------------------------
# conservation + seeded chaos
# ---------------------------------------------------------------------------


def test_conservation_check_catches_duplicate_completion(model_params):
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=1)
    frid = fl.submit([2, 3, 4], max_tokens=2)
    _drain_all(fl)
    fl.metrics.duplicate_completions = 1     # seeded violation
    with pytest.raises(PageLeakError, match="FLEET-LEAK"):
        fl.check_fleet_conservation()


def test_conservation_check_catches_nonterminal_rid(model_params):
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=1)
    fl.submit([2, 3, 4], max_tokens=4)       # still in flight
    with pytest.raises(PageLeakError, match="FLEET-LEAK"):
        fl.check_fleet_conservation()


def test_seeded_fleet_chaos_conserves_everything(model_params):
    """The kitchen sink on one injected clock: Poisson arrivals with a
    shared prefix, one scheduled kill, one slow replica, one heartbeat
    partition — every fleet rid reaches exactly one terminal status and
    no page or ref leaks anywhere, including the dead replicas."""
    model, params = model_params
    rng = np.random.RandomState(10)
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                          kill_at={8: 0}, slow_replicas={2: 2},
                          partitions={1: (2, 10_000)})
    fl, _ = _make_fleet(model, params, n=4, plan=plan, heartbeat_s=0.03)
    arrivals = np.cumsum(rng.exponential(0.01, 12))
    prompts = _prompts(rng, 12, shared=PAGE)
    frids = []
    i = 0
    while i < len(prompts) or fl.has_work:
        while i < len(prompts) and arrivals[i] <= plan.clock():
            frids.append(fl.submit(prompts[i], max_tokens=4))
            i += 1
        fl.step()
        assert fl._tick < 2000, "chaos fleet failed to drain"
    _assert_fleet_drained(fl)
    assert fl.replica_state(0) is ReplicaState.DEAD      # scheduled kill
    assert fl.replica_state(1) is ReplicaState.DEAD      # partition
    statuses = [fl.status(f) for f in frids]
    assert all(s.terminal for s in statuses)
    assert all(fl._requests[f].terminal_transitions == 1 for f in frids)
    # completions are token-exact even after the chaos
    for f, p in zip(frids, prompts):
        if fl.status(f) is RequestStatus.COMPLETED:
            assert fl.result(f) == greedy_decode_reference(
                model, params, p, 4, EOS)


def test_fleet_metrics_snapshot_shape(model_params):
    model, params = model_params
    fl, _ = _make_fleet(model, params, n=2)
    frid = fl.submit([2, 3, 4, 5], max_tokens=3)
    _drain_all(fl)
    snap = fl.snapshot()
    assert snap["fleet_completed"] == 1
    assert snap["fleet_duplicate_completions"] == 0
    assert snap["fleet_tokens_emitted"] == len(fl.result(frid))
    assert snap["fleet_tokens_per_s"] > 0
    assert len(snap["per_replica_prefix_hit_rate"]) == 2
    assert snap["replica_states"] == ["ready", "ready"]
    hz = fl.healthz()
    assert hz["ok"] and hz["in_flight"] == 0
