"""Concurrency auditor (paddle_tpu.analysis.concurrency): one
seeded-bad case per rule class — unguarded access to a declared field,
empty serialized justification, malformed annotation, REQUIRES call
site outside the lock, undeclared enum assignment sites, broken
checkpoint phase order, undeclared runtime transitions, and the
order-sensitive ToyOrderDrive the schedule explorer must catch — plus
clean pins over the real repo (guard check, static tables, a real
chaos drive under a small schedule budget) and exact
explored-schedule-count pins for the enumerator.
"""

import textwrap

import pytest

from paddle_tpu.analysis.concurrency import RULE_NAMES, guards, lifecycle
from paddle_tpu.analysis.concurrency import schedules as S
from paddle_tpu.analysis.concurrency.guards import (check_guards_source,
                                                    run_guard_check)
from paddle_tpu.analysis.concurrency.lifecycle import (
    MACHINES, record_transition, recorder, reset_recorder,
    run_static_check, runtime_diagnostics)
from paddle_tpu.analysis.concurrency.schedules import (ToyOrderDrive,
                                                       enumerate_schedules,
                                                       explore_drive)
from paddle_tpu.analysis.diagnostics import Severity
from paddle_tpu.platform.flags import FLAGS

pytestmark = [pytest.mark.conc, pytest.mark.analysis]


def _src(body: str) -> str:
    return textwrap.dedent(body)


# ---------------------------------------------------------------------------
# CONC-AUDIT: the guarded_by lock-discipline checker
# ---------------------------------------------------------------------------


class TestGuards:
    def test_unguarded_access_fires(self):
        diags, n = check_guards_source(_src("""\
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0          # guarded_by(_lock)

                def bump(self):
                    self._n += 1
            """), path="t.py")
        assert n == 1
        assert len(diags) == 1
        assert diags[0].code == "CONC-AUDIT"
        assert "guarded_by(_lock)" in diags[0].message
        assert "t.py:9" in diags[0].message

    def test_with_lock_and_init_access_clean(self):
        diags, n = check_guards_source(_src("""\
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0          # guarded_by(_lock)
                    self._n += 1         # __init__ is pre-publication

                def bump(self):
                    with self._lock:
                        self._n += 1
            """), path="t.py")
        assert n == 1
        assert diags == []

    def test_allow_escape_suppresses(self):
        diags, _ = check_guards_source(_src("""\
            class C:
                def __init__(self):
                    self._n = 0          # guarded_by(_lock)

                def peek(self):
                    # racy read is tolerable: monotonic counter, display only
                    return self._n       # lint: allow(guarded-by)
            """), path="t.py")
        assert diags == []

    def test_empty_serialized_justification_fires(self):
        diags, _ = check_guards_source(_src("""\
            class C:
                def __init__(self):
                    self._n = 0          # guarded_by(serialized:)
            """), path="t.py")
        assert len(diags) == 1
        assert "needs a justification" in diags[0].message

    def test_malformed_annotation_fires(self):
        diags, _ = check_guards_source(_src("""\
            class C:
                def __init__(self):
                    self._n = 0          # guarded_by(the lock over there)
            """), path="t.py")
        assert len(diags) == 1
        assert "malformed" in diags[0].message

    def test_cross_object_serialized_access_fires(self):
        diags, _ = check_guards_source(_src("""\
            class Tier:
                def __init__(self):
                    self._index = {}     # guarded_by(serialized: tick loop owns the tier)

            class Engine:
                def adopt(self, other):
                    return dict(other._index)
            """), path="t.py")
        assert len(diags) == 1
        assert "cross-object access" in diags[0].message

    def test_caller_form_checks_call_sites_not_body(self):
        diags, n = check_guards_source(_src("""\
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0          # guarded_by(_lock)

                # guarded_by(caller: _lock)
                def _bump_locked(self):
                    self._n += 1         # body proves under REQUIRES

                def good(self):
                    with self._lock:
                        self._bump_locked()

                def bad(self):
                    self._bump_locked()
            """), path="t.py")
        assert n == 2
        assert len(diags) == 1
        assert "_bump_locked" in diags[0].message
        assert "t.py:17" in diags[0].message

    def test_repo_guard_check_clean(self):
        assert run_guard_check() == []

    def test_coverage_rule_fires_for_unannotated_module(self, monkeypatch):
        monkeypatch.setattr(
            guards, "REQUIRED_MODULES",
            guards.REQUIRED_MODULES + ("paddle_tpu/platform/flags.py",))
        diags = run_guard_check()
        assert len(diags) == 1
        assert "declares no guarded_by" in diags[0].message
        assert "platform/flags.py" in diags[0].message


# ---------------------------------------------------------------------------
# PROTO-AUDIT static: declared tables vs assignment sites
# ---------------------------------------------------------------------------


class TestLifecycleStatic:
    def test_repo_static_check_clean(self):
        assert run_static_check() == []

    def test_machine_tables_are_closed(self):
        for spec in MACHINES.values():
            for src, dst in spec.edges:
                assert src in spec.states, (spec.name, src)
                assert dst in spec.states, (spec.name, dst)
            assert spec.initial in spec.states
            for term in spec.terminal:
                outgoing = [e for e in spec.edges if e[0] == term]
                # replica_lifecycle's dead is terminal for conservation
                # purposes but re-enters through restart_replica
                allowed = [("dead", "joining")] \
                    if spec.name == "replica_lifecycle" else []
                assert outgoing == allowed, \
                    f"{spec.name}: terminal {term} has outgoing {outgoing}"

    def test_undeclared_replica_state_fires(self):
        diags = lifecycle._check_replica_lifecycle(
            {"paddle_tpu/serving/fleet.py":
             "rep.state = ReplicaState.ZOMBIE\n"})
        assert len(diags) == 1
        assert diags[0].code == "PROTO-AUDIT"
        assert "ZOMBIE" in diags[0].message

    def test_undeclared_status_and_terminal_drift_fire(self):
        diags = lifecycle._check_request_status(
            {"paddle_tpu/serving/scheduler.py": _src("""\
                req.status = RequestStatus.LIMBO
                _TERMINAL = frozenset({RequestStatus.COMPLETED})
                """)})
        msgs = "\n".join(d.message for d in diags)
        assert len(diags) == 2
        assert "LIMBO" in msgs
        assert "drifted" in msgs

    def test_migration_marker_probes_fire(self):
        diags = lifecycle._check_migration_transfer(
            {"paddle_tpu/serving/fleet.py": _src("""\
                m.on_migration_start()
                m.on_migration_applied()
                m.on_migration_vanished()
                """)})
        msgs = "\n".join(d.message for d in diags)
        # missing fallback + aborted terminals, one undeclared marker
        assert len(diags) == 3
        assert "fallback" in msgs and "aborted" in msgs
        assert "on_migration_vanished" in msgs

    def test_checkpoint_phase_order_violation_fires(self):
        diags = lifecycle._check_checkpoint_commit(
            {"paddle_tpu/resilience/checkpointer.py": _src("""\
                ckpt.write_checkpoint(root)
                ckpt.snapshot_checkpoint(params)
                ckpt.prune_checkpoints(root)
                """)})
        assert len(diags) == 1
        assert "phase order" in diags[0].message


# ---------------------------------------------------------------------------
# PROTO-AUDIT dynamic: the transition recorder
# ---------------------------------------------------------------------------


@pytest.fixture
def fresh_recorder():
    reset_recorder()
    yield
    reset_recorder()


class TestRecorder:
    def test_declared_edge_clean(self, fresh_recorder):
        assert record_transition("replica_lifecycle", "joining", "ready")
        assert runtime_diagnostics() == []

    def test_undeclared_edge_fires(self, fresh_recorder):
        assert not record_transition("replica_lifecycle", "ready",
                                     "joining")
        diags = runtime_diagnostics()
        assert len(diags) == 1
        assert diags[0].severity is Severity.ERROR
        assert "replica_lifecycle: ready -> joining" in diags[0].message

    def test_self_loop_skipped(self, fresh_recorder):
        assert record_transition("request_status", "running", "running")
        assert recorder().counts() == {}

    def test_unknown_machine_is_undeclared(self, fresh_recorder):
        assert not record_transition("coffee_machine", "idle", "brewing")
        assert len(runtime_diagnostics()) == 1

    def test_duplicate_undeclared_edges_deduplicated(self, fresh_recorder):
        record_transition("migration_transfer", "applied", "started")
        record_transition("migration_transfer", "applied", "started")
        assert len(runtime_diagnostics()) == 1

    def test_registry_counters_published(self, fresh_recorder):
        from paddle_tpu.obs import MetricsRegistry
        reg = MetricsRegistry()
        record_transition("replica_lifecycle", "joining", "ready",
                          registry=reg)
        record_transition("replica_lifecycle", "ready", "joining",
                          registry=reg)
        snap = reg.snapshot()
        assert snap["lifecycle_transitions_total{dst=ready,"
                    "machine=replica_lifecycle,src=joining}"] == 1.0
        assert snap["lifecycle_undeclared_total"
                    "{machine=replica_lifecycle}"] == 1.0


# ---------------------------------------------------------------------------
# SCHED-AUDIT: the schedule-permutation explorer
# ---------------------------------------------------------------------------


class TestScheduleEnumeration:
    def test_site_perms_deterministic_and_capped(self):
        assert S._site_perms(("a", "b")) == [("b", "a")]
        perms = S._site_perms(("a", "b", "c"))
        assert len(perms) == 5          # 3! - canonical = 5, under cap
        assert perms[0] == ("a", "c", "b")
        assert len(S._site_perms(tuple("abcd"))) == 5   # capped

    def test_exact_schedule_counts(self):
        sites = [("phases", 0, ("a", "b", "c")), ("replicas", 1, (0, 1))]
        # singles: 5 perms for the 3-name site + 1 swap = 6; pairs:
        # cross-site only (one order per ordering point) = 5 * 1 = 5
        assert len(enumerate_schedules(sites, budget=100)) == 11
        assert len(enumerate_schedules(sites, budget=8)) == 8
        assert enumerate_schedules([], budget=8) == []

    def test_singles_come_before_pairs(self):
        sites = [("phases", 0, ("a", "b")), ("phases", 1, ("a", "b"))]
        scheds = enumerate_schedules(sites, budget=10)
        assert [len(d) for d in scheds] == [1, 1, 2]


class TestToyDrive:
    def test_divergence_caught_with_minimal_delta(self):
        explored, diags = explore_drive(ToyOrderDrive(), budget=16)
        # max_findings=3 stops the walk after three divergent singles
        assert explored == 3
        assert len(diags) == 3
        assert all(d.severity is Severity.ERROR for d in diags)
        assert all(d.code == "SCHED-AUDIT" for d in diags)
        assert "tick 0 phases order ['dbl', 'inc']" in diags[0].message
        assert "diverged" in diags[0].message

    def test_commuting_twin_clean_but_coverage_warns(self):
        explored, diags = explore_drive(ToyOrderDrive(commuting=True),
                                        budget=16)
        assert explored == 6            # 3 singles + 3 cross-tick pairs
        assert len(diags) == 1
        assert diags[0].severity is Severity.WARNING
        assert "coverage bar is 50" in diags[0].message

    def test_budget_truncates_exploration(self):
        explored, diags = explore_drive(ToyOrderDrive(commuting=True),
                                        budget=2)
        assert explored == 2
        assert diags == []              # bar relaxes to min(50, budget)


class TestFleetDrives:
    def test_flag_default_covers_the_bar(self):
        assert int(FLAGS.conc_audit_max_schedules) == 64
        assert S.MIN_SCHEDULES_PER_DRIVE == 50

    def test_kill_partition_drive_clean_under_small_budget(self):
        drive = S._drive_fleet_kill_partition()
        explored, diags = explore_drive(drive, budget=4)
        assert explored == 4
        assert diags == []

    def test_invalid_delta_is_ignored_not_applied(self):
        drive = S._drive_fleet_kill_partition()
        base, sites = drive.record()
        assert len(sites) >= 2          # kill+partition overlap is hot
        kind, tick, names = sites[0]
        # not a permutation of the canonical names: replay must keep
        # the canonical order rather than drop/duplicate replicas
        fp = drive.replay({(kind, tick): tuple(names) + (names[0],)})
        assert fp == base

    @pytest.mark.slow
    def test_all_drives_clean_at_full_budget(self):
        for drive in S.default_drives():
            explored, diags = explore_drive(drive)
            assert explored == 64, (drive.name, explored)
            assert diags == [], (drive.name, diags)


# ---------------------------------------------------------------------------
# the CLI
# ---------------------------------------------------------------------------


class TestCli:
    def test_unknown_rule_exits_2(self, capsys):
        from paddle_tpu.analysis.cli import main
        assert main(["concurrency", "--rule", "bogus"]) == 2
        assert "unknown rule" in capsys.readouterr().err

    def test_static_rules_exit_0_on_clean_repo(self, capsys):
        from paddle_tpu.analysis.cli import main
        rc = main(["concurrency", "--rule", "guarded-by",
                   "--rule", "state-table"])
        assert rc == 0
        assert "concurrency audit ok" in capsys.readouterr().out

    def test_rule_names_cover_all_families(self):
        assert RULE_NAMES == ("guarded-by", "state-table",
                              "transition-runtime", "schedule-permute")
