#!/bin/bash
# One-command on-chip capture for the moment the axon relay returns.
# Runs bench workers in headline-priority order, each in a subprocess
# with a hard timeout (the relay's failure mode is a HANG), appending
# every JSON line to /tmp/onchip_results.jsonl. Then update
# LAST_ONCHIP.json + BENCH_NOTES from those lines.
set -u
cd "$(dirname "$0")"
OUT=/tmp/onchip_results.jsonl
date >> "$OUT"
if ! timeout 120 python bench.py --worker probe >> "$OUT" 2>/tmp/onchip_err.txt; then
  echo "probe failed -- relay still down" | tee -a "$OUT"; exit 1
fi
# order = what's missing or stale first: the transformer re-measures the
# streaming-kernel bs8 tier (BENCH_FULL_SWEEP covers the bs8 best-combo
# the ~0.40-MFU headline needs), attention re-measures at auto-512
# tiles, moe has never produced a row; the already-fresh tables go
# last. Workers with full-table sweeps get a bigger budget (every row
# prints incrementally, so a timeout only loses not-yet-measured rows).
for spec in transformer:900 matmul:300 attention:600 moe:600 resnet50:600 lstm:900 convnets:900 alexnet:900; do
  w="${spec%%:*}"; t="${spec##*:}"
  echo "== $w ==" >> "$OUT"
  BENCH_FULL_SWEEP=1 timeout "$t" python bench.py --worker "$w" >> "$OUT" 2>>/tmp/onchip_err.txt
  echo "rc=$? for $w" >> "$OUT"
done
# pipeline + MoE EP train workers (ISSUE 19): mesh-shape workers that
# want exactly 8 devices — run them on the virtual-8 host mesh so the
# capture works on any chip count (same numbers the cpu bench pass
# reports; the on-chip tokens/s rows come from the workers above).
for spec in train_pipeline:600 train_moe:300; do
  w="${spec%%:*}"; t="${spec##*:}"
  echo "== $w (virtual-8) ==" >> "$OUT"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    timeout "$t" python bench.py --worker "$w" >> "$OUT" 2>>/tmp/onchip_err.txt
  echo "rc=$? for $w" >> "$OUT"
done
echo "done; results in $OUT"
