#!/usr/bin/env python
"""Benchmark suite. Prints exactly ONE JSON line.

Primary metric: ResNet-50 224x224 training throughput, images/sec/chip,
with achieved FLOP/s and MFU (BASELINE.json's north-star metric). The
reference publishes no ResNet-50 number, so ``vs_baseline`` is computed
from the one apples-to-apples headline it does publish: AlexNet bs=128
train ms/batch (PaddlePaddle on K40m: 334 ms — reference
benchmark/README.md:33-38). vs_baseline > 1 means faster by that factor.

Also measured (reported as extra fields on the same line):
  - alexnet_ms_per_batch       (vs 334 ms, K40m)
  - lstm_ms_per_batch          IMDB 2xLSTM h=512 bs=64 seq=100
                               (vs 184 ms, K40m — benchmark/README.md:114-119)
  - scaling_virtual8           1-vs-8-device step-time ratio at FIXED global
                               batch on a serialized virtual CPU mesh: pure
                               collective/partition overhead (compute is
                               identical), the tracked scaling-efficiency
                               number until multi-chip hardware exists.

Robustness (round-1 postmortem: the TPU tunnel can HANG in jax.devices(),
not just raise UNAVAILABLE): every measurement runs in a subprocess with
its own timeout; init is retried with backoff while the global deadline
allows; one JSON line is ALWAYS emitted, with an error record if the
hardware never came up.
"""

import json
import os
import subprocess
import sys
import time

GLOBAL_DEADLINE_S = 900.0


def _full_sweep() -> bool:
    """Deep-measurement mode, on only when BENCH_FULL_SWEEP=1 (set by
    tools_onchip_capture.sh, whose per-worker budgets fit it): the extra
    reference-table rows (AlexNet bs sweep, SmallNet/GoogLeNet extra
    batches, LSTM bs128 column) AND the transformer diagnostics beyond
    the headline + bf16-resid variant (fused head, seq2048/seq8192
    long-context tiers, best-combo, L4 ablation). The driver's plain
    `python bench.py` keeps its original duration so the 900s global
    deadline still reaches every worker."""
    return os.environ.get("BENCH_FULL_SWEEP", "") == "1"


ALEXNET_BASELINE_MS = 334.0   # reference Paddle, AlexNet bs=128, K40m
LSTM_BASELINE_MS = 184.0      # reference Paddle, IMDB LSTM h=512 bs=64, K40m

# bf16 peak FLOP/s per chip (compute path runs bf16 matmuls, fp32 accum)
PEAK_FLOPS = {
    "TPU v2": 45e12, "TPU v3": 123e12, "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 197e12,
    "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def _peak_for(kind: str) -> float:
    for k, v in PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v
    return 197e12


def _time_steps(step, args, iters):
    """Time ``iters`` chained train steps; a concrete value fetch is the
    completion barrier (block_until_ready is optimistic over the relay)."""
    p, opt_state, mstate, key, feeds = args
    loss, p, opt_state, mstate, _ = step(p, opt_state, mstate, key, feeds)
    float(loss)  # compile + warmup
    loss, p, opt_state, mstate, _ = step(p, opt_state, mstate, key, feeds)
    float(loss)
    start = time.perf_counter()
    for _ in range(iters):
        loss, p, opt_state, mstate, _ = step(p, opt_state, mstate, key, feeds)
    float(loss)
    return (time.perf_counter() - start) / iters


def _init_paddle():
    import paddle_tpu as paddle

    paddle.init()
    return paddle


def _make_sgd(cost, params, opt=None):
    from paddle_tpu import optimizer, trainer

    return trainer.SGD(cost=cost, parameters=params,
                       update_equation=opt or optimizer.Momentum(
                           momentum=0.9, learning_rate=0.01))


def _dense_feeds(sgd, batch, dim, n_classes, seed=0):
    import numpy as np

    rng = np.random.RandomState(seed)
    samples = [(rng.randn(dim).astype(np.float32), int(rng.randint(n_classes)))
               for _ in range(batch)]
    return sgd._make_feeder(None).feed(samples)


def _step_args(sgd, feeds):
    import jax

    return (sgd.parameters.as_dict(), sgd.opt_state, sgd.model_state,
            jax.random.PRNGKey(0), feeds)


def _aot_compile(step, args):
    """Compile ONCE via AOT lowering; returns (callable, flops-or-None).

    The compiled object is used directly for timing so the program isn't
    compiled a second time by the first traced call — for the big workers
    (resnet sweep, transformer) that halves the compile budget."""
    try:
        compiled = step.lower(*args).compile()
    except Exception:
        return step, None
    try:  # a cost-analysis failure must not discard the compile
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", 0.0))
        return compiled, (f if f > 0 else None)
    except Exception:
        return compiled, None


# ---------------------------------------------------------------------------
# workers — each prints one JSON line on success
# ---------------------------------------------------------------------------


def _measure_image_model(build_fn, img, batch, iters=20, with_flops=False,
                         **build_kw):
    """Shared image-model measurement harness: build -> SGD -> device-resident
    NHWC feeds (layer._to_nhwc passes 4-D through, so no per-step layout
    change) -> timed chained steps. Returns sec or (sec, flops)."""
    import jax
    import numpy as np

    import paddle_tpu as paddle

    rng = np.random.RandomState(0)
    paddle.topology.reset_name_scope()
    images, label, logits, cost = build_fn(img_size=img, **build_kw)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = _make_sgd(cost, params)
    feeds = {
        "image": jax.device_put(
            rng.randn(batch, img, img, 3).astype(np.float32)),
        "label": jax.device_put(
            rng.randint(0, logits.size, size=batch).astype(np.int32)),
    }
    step = sgd._build_step()
    args = _step_args(sgd, feeds)
    if with_flops:
        step, flops = _aot_compile(step, args)
        return _time_steps(step, args, iters=iters), flops
    return _time_steps(step, args, iters=iters)


def worker_resnet50():
    """ResNet-50 train step, images/sec/chip + MFU. Batch sweep picks the
    best throughput; activations ride bf16 (FLAGS.bf16_activations)."""
    import jax

    paddle = _init_paddle()
    from paddle_tpu.models import resnet

    img = 224

    def measure(batch, iters=20):
        return _measure_image_model(resnet.build, img, batch, iters=iters,
                                    with_flops=True, depth=50,
                                    num_classes=1000)

    kind = jax.devices()[0].device_kind
    peak = _peak_for(kind)

    def emit(results, first_err):
        batch, (sec, flops) = max(
            results.items(), key=lambda kv: kv[0] / kv[1][0])
        flops_source = "xla_cost_analysis"
        if flops is None:
            # analytic: ResNet-50 fwd ~4.09 GFLOP/img (2*MACs); ~3x train
            flops = 3 * 4.089e9 * batch
            flops_source = "analytic"
        achieved = flops / sec
        extra = ({"batch_sweep_error": repr(first_err)} if first_err else {})
        print(json.dumps({
            **extra,
            "resnet50_images_per_sec_per_chip": round(batch / sec, 1),
            "resnet50_ms_per_batch": round(sec * 1000, 2),
            "resnet50_achieved_tflops": round(achieved / 1e12, 2),
            "resnet50_mfu": round(achieved / peak, 4),
            "resnet50_flops_per_step": flops,
            "flops_source": flops_source,
            "device_kind": kind,
            "peak_tflops_assumed": peak / 1e12,
            "batch": batch,
            "batch_sweep": {str(b): round(b / s, 1)
                            for b, (s, _) in results.items()},
            "feed_layout": "NHWC device-resident",
        }), flush=True)

    results = {}
    first_err = None
    for batch in (128, 256):
        try:
            results[batch] = measure(batch)
        except Exception as e:  # keep the smaller-batch result if any
            first_err = e
            break
        # print after EVERY successful size: a hang in the next sweep
        # step can only lose the sweep, never the measured headline
        emit(results, first_err)
    if not results:
        raise first_err  # surface the root cause, not an empty-max error
    if first_err is not None:
        emit(results, first_err)


def worker_alexnet():
    """AlexNet train ms/batch across the reference's full batch sweep
    (BASELINE.md:15-18 — 195/334/602/1629 ms on K40m). bs=128 first: it
    is the vs_baseline headline basis."""
    paddle = _init_paddle()
    from paddle_tpu.models import alexnet

    img = 227

    def measure(batch, iters=30):
        paddle.topology.reset_name_scope()
        images, label, logits, cost = alexnet.build(img_size=img)
        topo = paddle.topology.Topology([cost])
        params = paddle.Parameters.from_topology(topo, seed=0)
        sgd = _make_sgd(cost, params)
        feeds = _dense_feeds(sgd, batch, 3 * img * img, 1000)
        return _time_steps(sgd._build_step(), _step_args(sgd, feeds),
                           iters=iters)

    out = {"alexnet_ms_per_batch": round(measure(128) * 1000, 3)}
    out["alexnet_bs128_vs_baseline"] = round(
        ALEXNET_BASELINE_MS / out["alexnet_ms_per_batch"], 1)
    print(json.dumps(out), flush=True)  # headline before the sweep
    sweep = ((64, 195.0), (256, 602.0), (512, 1629.0)) if _full_sweep() \
        else ()
    for batch, base in sweep:
        try:
            ms = round(measure(batch, iters=20) * 1000, 3)
        except Exception as e:
            out[f"alexnet_bs{batch}_error"] = repr(e)
            print(json.dumps(out), flush=True)  # error rows print too
            continue
        out[f"alexnet_bs{batch}_ms"] = ms
        out[f"alexnet_bs{batch}_vs_baseline"] = round(base / ms, 1)
        print(json.dumps(out), flush=True)
    print(json.dumps(out), flush=True)


def worker_lstm():
    """IMDB benchmark config: 2xLSTM h=512 + fc, bs=64, seq len 100,
    dict 30k (reference benchmark/paddle/rnn/rnn.py)."""
    import numpy as np

    paddle = _init_paddle()
    from paddle_tpu.models import text_lstm

    from paddle_tpu.platform.flags import FLAGS

    batch, seq_len, hidden = 64, 100, 512
    rng = np.random.RandomState(0)

    def measure(use_pallas, iters=20, hidden=hidden, batch=batch):
        FLAGS.use_pallas = use_pallas
        paddle.topology.reset_name_scope()
        words, label, logits, cost = text_lstm.build(hidden=hidden)
        topo = paddle.topology.Topology([cost])
        params = paddle.Parameters.from_topology(topo, seed=0)
        sgd = _make_sgd(cost, params)
        samples = [(rng.randint(0, 30000, size=seq_len).tolist(),
                    int(rng.randint(2))) for _ in range(batch)]
        feeds = sgd._make_feeder(None).feed(samples)
        return _time_steps(sgd._build_step(), _step_args(sgd, feeds),
                           iters=iters)

    # headline (shipping default, use_pallas on) FIRST, and PRINT it
    # before the diagnostic runs: the relay's failure mode is a HANG, not
    # a raise (module docstring), and the orchestrator keeps the last
    # JSON line — so a hang in the plain-XLA comparison can only lose the
    # comparison, never the already-emitted headline
    sec_fused = measure(True)
    out = {
        "lstm_ms_per_batch": round(sec_fused * 1000, 3),
        "lstm_fused_pallas_ms": round(sec_fused * 1000, 3),
        "lstm_config": f"h={hidden} bs={batch} seq={seq_len}",
    }
    print(json.dumps(out), flush=True)
    try:
        out["lstm_plain_xla_ms"] = round(measure(False, iters=8) * 1000, 3)
    except Exception as e:
        out["lstm_plain_xla_error"] = repr(e)
    print(json.dumps(out), flush=True)
    # more rows of the reference RNN table (BASELINE.md: h=1280 bs=64 ->
    # 641 ms, h=512 bs=256 -> 414 ms on K40m), printed incrementally so a
    # relay hang loses at most the not-yet-measured rows
    lstm_rows = [("lstm_h1280_bs64_ms", 1280, 64, 641.0),
                 ("lstm_h256_bs64_ms", 256, 64, 83.0),
                 ("lstm_h512_bs256_ms", 512, 256, 414.0)]
    if _full_sweep():
        # bs=128 column + the largest cell (BASELINE.md:40-42)
        lstm_rows += [("lstm_h256_bs128_ms", 256, 128, 110.0),
                      ("lstm_h512_bs128_ms", 512, 128, 261.0),
                      ("lstm_h1280_bs128_ms", 1280, 128, 1007.0),
                      ("lstm_h1280_bs256_ms", 1280, 256, 1655.0)]
    for key, h, b, base in lstm_rows:
        try:
            out[key] = round(measure(True, iters=10, hidden=h, batch=b)
                             * 1000, 3)
            out[key.replace("_ms", "_vs_baseline")] = round(base / out[key], 1)
        except Exception as e:
            # rows are independent configs (a h=1280 OOM must not skip
            # the h=512 bs=256 row); a relay hang can't reach here anyway
            out[key.replace("_ms", "_error")] = repr(e)
            print(json.dumps(out), flush=True)  # error rows print too
            continue
        print(json.dumps(out), flush=True)
    print(json.dumps(out), flush=True)


def worker_convnets():
    """GoogleNet + SmallNet train ms/batch at the reference's benchmark
    batch sizes (BASELINE.md: GoogleNet 613 ms bs=64 / 1149 ms bs=128,
    SmallNet 10.46 ms bs=64 — all K40m)."""
    _init_paddle()
    from paddle_tpu.models import googlenet, smallnet

    rows = [("googlenet_bs64", googlenet.build, 224, 64, 15, 613.0),
            ("smallnet_bs64", smallnet.build, 32, 64, 30, 10.463),
            ("googlenet_bs128", googlenet.build, 224, 128, 15, 1149.0)]
    if _full_sweep():
        # remaining cells of the reference table (BASELINE.md:19-25)
        rows += [("googlenet_bs256", googlenet.build, 224, 256, 10, 2348.0),
                 ("smallnet_bs128", smallnet.build, 32, 128, 30, 18.184),
                 ("smallnet_bs256", smallnet.build, 32, 256, 30, 33.113),
                 ("smallnet_bs512", smallnet.build, 32, 512, 30, 63.039)]
    out = {}
    for key, build_fn, img, batch, iters, base in rows:
        try:  # rows are independent; isolate errors per measurement
            ms = round(_measure_image_model(build_fn, img, batch,
                                            iters=iters) * 1000, 3)
        except Exception as e:
            out[f"{key}_error"] = repr(e)
            print(json.dumps(out), flush=True)  # error rows print too
            continue
        out[f"{key}_ms"] = ms
        out[f"{key}_vs_baseline"] = round(base / ms, 1)
        print(json.dumps(out), flush=True)  # incremental (relay hang rule)
    print(json.dumps(out), flush=True)


def worker_transformer():
    """Decoder-only transformer LM (models/transformer.py): tokens/sec and
    MFU. The high-MFU headline: all FLOPs are large bf16 MXU matmuls, so
    this is where the framework's compute efficiency shows without the
    HBM-roofline ceiling that bounds ResNet-50's BN traffic (BENCH_NOTES)."""
    import jax
    import numpy as np

    paddle = _init_paddle()
    from paddle_tpu.models import transformer

    rng = np.random.RandomState(0)
    kind = jax.devices()[0].device_kind
    peak = _peak_for(kind)

    def measure(d, layers, heads, seq, bs, vocab=32768, iters=6,
                fused_head=False, remat=False):
        paddle.topology.reset_name_scope()
        tokens, pos, target, logits, cost = transformer.build(
            vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
            max_len=seq, fused_head=fused_head, remat=remat)
        topo = paddle.topology.Topology([cost])
        params = paddle.Parameters.from_topology(topo, seed=0)
        sgd = _make_sgd(cost, params)
        samples = []
        for _ in range(bs):
            t = rng.randint(0, vocab, size=seq)
            samples.append((t.tolist(), list(range(seq)),
                            np.roll(t, -1).tolist()))
        feeds = sgd._make_feeder(
            {"tokens": 0, "pos": 1, "target": 2}).feed(samples)
        step = sgd._build_step()
        args = _step_args(sgd, feeds)
        step, flops = _aot_compile(step, args)
        sec = _time_steps(step, args, iters=iters)
        out = {
            "transformer_tokens_per_sec": round(bs * seq / sec, 1),
            "transformer_ms_per_batch": round(sec * 1000, 2),
            "transformer_config": f"d{d} L{layers} h{heads} seq{seq} "
                                  f"bs{bs} vocab{vocab}"
                                  + (" remat" if remat else ""),
        }
        if flops:
            out["transformer_mfu"] = round(flops / sec / peak, 4)
            out["transformer_achieved_tflops"] = round(flops / sec / 1e12, 2)
        return out

    # ~400M-param config sized for one v5e chip (params+momentum+grads
    # ~6.5GB f32, saved activations ~4GB at 4096 tokens). bs=8 is tried
    # FIRST: more tokens/step amortize the fixed per-step overhead
    # (optimizer update, dispatch) so MFU is strictly better if it fits;
    # fall back to bs=4, then to the half-width model
    fallback_reason = None
    d_used = 2048
    out = None
    bs_used = 4
    remat_used = False
    # bs=8 plain first (highest MFU if it fits), then bs=8 with per-block
    # remat (trades ~1 extra forward of FLOPs for the ~4GB of saved
    # activations — the tier that used to OOM into bs=4), then smaller
    for d_try, bs_try, remat_try in ((2048, 8, False), (2048, 8, True),
                                     (2048, 4, False), (1024, 4, False)):
        try:
            out = measure(d=d_try, layers=8, heads=16, seq=1024, bs=bs_try,
                          remat=remat_try)
            d_used, bs_used, remat_used = d_try, bs_try, remat_try
            if fallback_reason:
                out["transformer_fallback_reason"] = fallback_reason
            break
        except Exception as e:
            # record and keep going: e.__traceback__ pins the failed
            # attempt's frame (its device buffers included); the next
            # attempt must allocate after those are droppable
            fallback_reason = repr(e)
            out = None
    if out is None:
        raise RuntimeError(f"all transformer configs failed: "
                           f"{fallback_reason}")
    print(json.dumps(out), flush=True)  # headline before the variants
    # The tier ladder + bf16-resid variant run in EVERY path; the other
    # variants (fused head, long-context tiers, best-combo, ablation —
    # ~6 more compiles) only under BENCH_FULL_SWEEP: in the driver's
    # plain bench.py the worker has a 420s attempt budget and burning it
    # on variants would starve the resnet50 headline behind it.
    if _full_sweep():
        try:  # fused blockwise LM-head xent (layer.lm_head_cost): logits
            # never reach HBM; candidate replacement headline if faster
            fh = measure(d=d_used, layers=8, heads=16, seq=1024, bs=bs_used,
                         fused_head=True, remat=remat_used)
            out["transformer_fused_head_tokens_per_sec"] = \
                fh["transformer_tokens_per_sec"]
            if "transformer_mfu" in fh:
                out["transformer_fused_head_mfu"] = fh["transformer_mfu"]
        except Exception as e:
            out["transformer_fused_head_error"] = repr(e)
        print(json.dumps(out), flush=True)
    try:  # bf16 residual-stream variant (FLAGS.bf16_dense_activations)
        from paddle_tpu.platform.flags import FLAGS

        FLAGS.bf16_dense_activations = True
        try:
            bf = measure(d=d_used, layers=8, heads=16, seq=1024,
                         bs=bs_used, remat=remat_used)
        finally:
            FLAGS.bf16_dense_activations = False
        out["transformer_bf16_resid_tokens_per_sec"] = \
            bf["transformer_tokens_per_sec"]
        if "transformer_mfu" in bf:
            out["transformer_bf16_resid_mfu"] = bf["transformer_mfu"]
    except Exception as e:
        out["transformer_bf16_resid_error"] = repr(e)
    print(json.dumps(out), flush=True)
    if _full_sweep():
        try:  # long-context tier: seq=2048 only fits with per-block remat
            # (saved activations scale with tokens; checkpoint caps them at
            # one block's boundary per layer)
            lc = measure(d=d_used, layers=8, heads=16, seq=2048,
                         bs=max(bs_used // 2, 2), remat=True, iters=4)
            out["transformer_seq2048_remat_tokens_per_sec"] = \
                lc["transformer_tokens_per_sec"]
            if "transformer_mfu" in lc:
                out["transformer_seq2048_remat_mfu"] = lc["transformer_mfu"]
        except Exception as e:
            out["transformer_seq2048_remat_error"] = repr(e)
        print(json.dumps(out), flush=True)
        try:  # single-sequence long-context tier: 8192 tokens in ONE segment
            # (not 8 packed ones), the shape the streamed flash kernels
            # unlocked — the round-4 kernels hit the 16MB scoped-vmem wall
            # here; remat caps saved activations per block
            lc8 = measure(d=d_used, layers=8, heads=16, seq=8192, bs=1,
                          remat=True, iters=4)
            out["transformer_seq8192_remat_tokens_per_sec"] = \
                lc8["transformer_tokens_per_sec"]
            if "transformer_mfu" in lc8:
                out["transformer_seq8192_remat_mfu"] = lc8["transformer_mfu"]
        except Exception as e:
            out["transformer_seq8192_remat_error"] = repr(e)
        print(json.dumps(out), flush=True)
        try:  # best-known combo for the MFU headline: the largest batch with
            # the bf16 residual stream (halves saved activations, so plain
            # bs8 may fit where f32 OOM'd; measured faster at bs4 both
            # windows), falling back to +remat. Reported as transformer_best_*
            # with its exact config — the number to quote for the >=0.40 gate.
            from paddle_tpu.platform.flags import FLAGS

            # candidate pool: the bf16-resid variant already measured at the
            # headline config, plus the d2048 bs8 attempts (skipping any combo
            # the variant already covers so 'best' can never silently be a
            # strictly worse config)
            cands = []
            if "transformer_bf16_resid_tokens_per_sec" in out:
                cands.append((out.get("transformer_bf16_resid_mfu"),
                              out["transformer_bf16_resid_tokens_per_sec"],
                              f"d{d_used} bs{bs_used} bf16resid"
                              + (" remat" if remat_used else "")))
            FLAGS.bf16_dense_activations = True
            try:
                for bs_b, remat_b in ((8, False), (8, True)):
                    if d_used == 2048 and bs_b == bs_used \
                            and remat_b == remat_used and cands:
                        # the bf16-resid variant above IS this combo — but
                        # only skip when it actually measured (cands
                        # non-empty); if it failed, measure it here
                        continue
                    try:
                        r = measure(d=2048, layers=8, heads=16, seq=1024,
                                    bs=bs_b, remat=remat_b, iters=6)
                        cands.append((r.get("transformer_mfu"),
                                      r["transformer_tokens_per_sec"],
                                      f"d2048 bs{bs_b} bf16resid"
                                      + (" remat" if remat_b else "")))
                        break
                    except Exception as e:
                        out["transformer_best_attempt_error"] = repr(e)
            finally:
                FLAGS.bf16_dense_activations = False
            if cands:
                # the gate metric is MFU; tokens/sec breaks ties (and orders
                # candidates whose cost analysis failed)
                mfu_b, tps_b, cfg_b = max(
                    cands, key=lambda c: (c[0] if c[0] is not None else -1.0,
                                          c[1]))
                out["transformer_best_tokens_per_sec"] = tps_b
                out["transformer_best_config"] = cfg_b
                if mfu_b is not None:
                    out["transformer_best_mfu"] = mfu_b
        except Exception as e:
            out["transformer_best_error"] = repr(e)
        print(json.dumps(out), flush=True)
        try:  # layer ablation: (t8 - t4)/4 = marginal ms per block, and
            # t8 - 8*marginal = fixed cost (embedding + LM head + optimizer +
            # dispatch). The profiler-free split of where the step time goes
            # (traces hang the relay — BENCH_NOTES methodology). L=4 rather
            # than L=16 so the ablation never OOMs a config the headline fit.
            l4 = measure(d=d_used, layers=4, heads=16, seq=1024, bs=bs_used,
                         remat=remat_used, iters=4)
            t8 = out["transformer_ms_per_batch"]
            t4 = l4["transformer_ms_per_batch"]
            per_block = (t8 - t4) / 4.0
            out["transformer_ablation_ms_per_block"] = round(per_block, 2)
            out["transformer_ablation_fixed_ms"] = round(t8 - 8 * per_block, 2)
        except Exception as e:
            out["transformer_ablation_error"] = repr(e)
        print(json.dumps(out), flush=True)



def worker_attention():
    """Flash-attention BACKWARD: pallas dQ/dKV kernels vs the plain-JAX
    blockwise fallback (FLAGS.use_pallas toggle), long-context shape."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    _init_paddle()
    from paddle_tpu.ops import attention
    from paddle_tpu.platform.flags import FLAGS

    B, S, H, D = 4, 4096, 8, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32),
                    dtype=jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32),
                    dtype=jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32),
                    dtype=jnp.bfloat16)

    def fetch(out):
        # concrete value fetch: the completion barrier that works over the
        # relay (block_until_ready is optimistic there — see _time_steps)
        leaf = jax.tree.leaves(out)[0]
        return float(jnp.asarray(leaf).ravel()[0])

    def timeit(fn, iters=10):
        fetch(fn(q, k, v))
        start = time.perf_counter()
        for _ in range(iters):
            out = fn(q, k, v)
        fetch(out)
        return (time.perf_counter() - start) / iters

    @jax.jit
    def fwd_fn(q, k, v):
        return attention.flash_attention(q, k, v, causal=True)

    t_fwd = timeit(fwd_fn)

    def time_grad(use_pallas):
        FLAGS.use_pallas = use_pallas

        @jax.jit
        def grad_fn(q, k, v):
            def loss(q, k, v):
                o = attention.flash_attention(q, k, v, causal=True)
                return jnp.sum(o.astype(jnp.float32))

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        return timeit(grad_fn)

    t_plain = time_grad(False)
    t_pallas = time_grad(True)
    # the forward (same pallas kernel both ways) is subtracted so the
    # ratio compares the BACKWARD implementations, not fwd+bwd totals
    bwd_pallas = max(t_pallas - t_fwd, 1e-9)
    bwd_plain = max(t_plain - t_fwd, 1e-9)
    print(json.dumps({
        "attention_bwd": {
            "shape": f"B{B}xS{S}xH{H}xD{D} bf16 causal",
            "fwd_ms": round(t_fwd * 1000, 3),
            "pallas_fwdbwd_ms": round(t_pallas * 1000, 3),
            "plain_jax_fwdbwd_ms": round(t_plain * 1000, 3),
            "bwd_pallas_ms": round(bwd_pallas * 1000, 3),
            "bwd_plain_jax_ms": round(bwd_plain * 1000, 3),
            "bwd_speedup": round(bwd_plain / bwd_pallas, 2),
        }}), flush=True)


def worker_scaling():
    """Fixed-GLOBAL-batch 1-vs-8-device DP step time for a ResNet train
    step on the serialized virtual CPU mesh (the headline model family,
    not a toy MLP).

    Method note: the virtual mesh shares ONE host core, so the 8-device
    run executes the 8 partitions serially — total compute is identical
    to the 1-device run and t1/t8 isolates partition + collective
    overhead, a LOWER bound on real-chip scaling efficiency (real ICI
    runs partitions concurrently and overlaps the psum). Measured
    breakdown (resnet18@48px bs=64, this host): 8x the bs/8 single-dev
    step = 18.2s of pure per-shard compute vs t8 = 22.1s, i.e. ~22%
    partition+collective overhead; with a toy 3-layer MLP the same
    harness reports 0.29-0.43 "efficiency" because per-partition
    dispatch overhead dominates its tiny matmuls — that artifact, not
    collectives, produced round 2's 0.43."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import resnet
    from paddle_tpu.parallel import make_mesh

    batch, img, depth = 64, 48, 18

    def build_and_time(mesh, iters=2):
        import numpy as np

        paddle.topology.reset_name_scope()
        images, label, logits, cost = resnet.build(depth=depth, img_size=img,
                                                   num_classes=100)
        params = paddle.Parameters.from_topology(
            paddle.topology.Topology([cost]), seed=0)
        from paddle_tpu import optimizer, trainer

        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Momentum(
                              momentum=0.9, learning_rate=0.01),
                          mesh=mesh)
        rng = np.random.RandomState(0)
        feeds = sgd._shard_feeds({
            "image": jax.device_put(
                rng.randn(batch, img, img, 3).astype(np.float32)),
            "label": jax.device_put(
                rng.randint(0, 100, size=batch).astype(np.int32)),
        })
        step = sgd._build_step()
        p, o, m, key, f = _step_args(sgd, feeds)
        loss, p, o, m, _ = step(p, o, m, key, f)  # compile + warmup
        float(loss)
        # min over iters: the single shared core is contended, and min is
        # the standard de-noised estimator for that regime
        best = float("inf")
        for _ in range(iters):
            start = time.perf_counter()
            loss, p, o, m, _ = step(p, o, m, key, f)
            float(loss)
            best = min(best, time.perf_counter() - start)
        return best

    devs = jax.devices()
    assert len(devs) >= 8, f"need 8 virtual devices, have {len(devs)}"
    N_MIN = 3
    t1 = build_and_time(None, iters=N_MIN)
    t8 = build_and_time(make_mesh((8,), ("data",), devs[:8]), iters=N_MIN)
    print(json.dumps({
        "scaling_virtual8": {
            "model": f"resnet{depth}_img{img}_bs{batch}",
            "t_step_1dev_ms": round(t1 * 1000, 3),
            "t_step_8dev_ms": round(t8 * 1000, 3),
            "efficiency_fixed_global_batch": round(t1 / t8, 3),
            "min_of": N_MIN,
            "method": "serialized 1-core virtual mesh, min-of-"
                      f"{N_MIN} steps: t1/t8 isolates partition+collective "
                      "overhead. PROXY ONLY — a contended single host core, "
                      "not chip timing; a lower bound on real-chip DP "
                      "efficiency. This JSON field is the one canonical "
                      "number for this metric (BENCH_NOTES quotes it).",
        }}), flush=True)


def worker_zero1():
    """ZeRO-1 sharded weight update (arXiv 2004.13336) vs the replicated
    optimizer path on the serialized virtual-8 CPU mesh: same ResNet DP
    train step, zero_stage 0 vs 1. Reports per-chip optimizer-state bytes
    (exact, from the slot arrays' shard shapes — the N x HBM headroom
    claim) and the step-time delta (PROXY ONLY on the contended single
    host core: the 8 partitions run serially, so the reduce-scatter/
    all-gather pair shows up as overhead here while on real ICI it
    REPLACES the grad all-reduce)."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import optimizer, trainer
    from paddle_tpu.models import resnet
    from paddle_tpu.parallel import make_mesh, opt_state_bytes_per_device

    batch, img, depth = 32, 48, 18
    devs = jax.devices()
    assert len(devs) >= 8, f"need 8 virtual devices, have {len(devs)}"

    def build(zero, opt_factory):
        paddle.topology.reset_name_scope()
        images, label, logits, cost = resnet.build(depth=depth, img_size=img,
                                                   num_classes=100)
        params = paddle.Parameters.from_topology(
            paddle.topology.Topology([cost]), seed=0)
        return trainer.SGD(cost=cost, parameters=params,
                           update_equation=opt_factory(),
                           mesh=make_mesh((8,), ("data",), devs[:8]),
                           zero=zero)

    def time_step(sgd, iters=3):
        rng = np.random.RandomState(0)
        feeds = sgd._shard_feeds({
            "image": rng.randn(batch, img, img, 3).astype(np.float32),
            "label": rng.randint(0, 100, size=batch).astype(np.int32),
        })
        args = _step_args(sgd, feeds)
        step, _ = _aot_compile(sgd._build_step(), args)
        return _time_steps(step, args, iters=iters)

    momentum = lambda: optimizer.Momentum(momentum=0.9, learning_rate=0.01)
    out = {"zero1_model": f"resnet{depth}_img{img}_bs{batch}_mesh8"}
    s0 = build(0, momentum)
    out["zero0_opt_state_bytes_per_chip"] = opt_state_bytes_per_device(
        s0.opt_state["slots"])
    out["zero0_step_ms"] = round(time_step(s0) * 1000, 3)
    print(json.dumps(out), flush=True)  # headline before the zero1 twin
    del s0
    s1 = build(1, momentum)
    out["zero1_opt_state_bytes_per_chip"] = opt_state_bytes_per_device(
        s1.opt_state["slots"])
    out["zero1_step_ms"] = round(time_step(s1) * 1000, 3)
    out["zero1_opt_state_reduction"] = round(
        out["zero0_opt_state_bytes_per_chip"]
        / max(1, out["zero1_opt_state_bytes_per_chip"]), 2)
    print(json.dumps(out), flush=True)
    del s1
    # Adam doubles the slot set — the config where the N x matters most
    adam = lambda: optimizer.Adam(learning_rate=1e-3)
    out["zero0_adam_opt_state_bytes_per_chip"] = opt_state_bytes_per_device(
        build(0, adam).opt_state["slots"])
    out["zero1_adam_opt_state_bytes_per_chip"] = opt_state_bytes_per_device(
        build(1, adam).opt_state["slots"])
    print(json.dumps(out), flush=True)


def worker_serving():
    """Paged-KV continuous-batching serving engine under a Poisson
    arrival trace on the virtual-8 host: 24 ragged-length requests
    (prompts 4..48 tokens, 16 generated each) stream into a
    DecoderLM-backed ServingEngine with a page pool sized to force real
    multiplexing.  Reports end-to-end tokens/s (prefill + decode
    emissions over the first-submit..last-token window), time-to-first-
    token, and page-pool occupancy — the serving analog of the training
    workers' step-time numbers.  CPU timings are PROXY ONLY (interpret-
    mode host math); the structure (fused decode batch, admission,
    growth, preemption) is what's being exercised."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import DecoderLM, ServingEngine

    paddle.init()
    rng = np.random.RandomState(0)
    vocab, eos = 512, 1
    model = DecoderLM(vocab_size=vocab, num_layers=2, num_heads=2,
                      head_dim=16, max_positions=256)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=eos, page_size=16,
                        num_pages=64, max_pages_per_seq=8, max_slots=8,
                        buckets=(16, 32, 48))
    n_req, rate = 24, 50.0          # Poisson arrivals, ~50 req/s offered
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
    prompts = [rng.randint(2, vocab, size=rng.randint(4, 49)).tolist()
               for _ in range(n_req)]

    # warm every prefill bucket + the fused decode step outside the
    # measured window (compile time would otherwise swamp TTFT on the
    # CPU proxy), then reset counters — the pages all come back, so the
    # measured run starts from an empty pool
    from paddle_tpu.serving import ServingMetrics

    for warm_len in (8, 20, 40):    # buckets 16 / 32 / 48
        eng.submit(rng.randint(2, vocab, size=warm_len).tolist(),
                   max_tokens=2)
    eng.run()
    # warmup pages may stay parked in the prefix cache (reclaimable);
    # zero live refs is the no-leak invariant
    assert eng.pool.total_refs == 0
    eng.metrics = ServingMetrics(pool_pages=eng.pool.num_usable)
    eng._results.clear()

    t0 = time.monotonic()
    i = 0
    while i < n_req or eng.has_work:
        now = time.monotonic() - t0
        while i < n_req and arrivals[i] <= now:
            eng.submit(prompts[i], max_tokens=16)
            i += 1
        had_work = eng.step()
        if not had_work and i < n_req:
            time.sleep(max(0.0, min(arrivals[i] - (time.monotonic() - t0),
                                    0.002)))
    snap = eng.metrics.snapshot()
    out = {
        "serving_model": "decoderlm_L2_H2_D16_v512_page16_pool64_slots8",
        "serving_tokens_per_s": snap["tokens_per_s"],
        "serving_ttft_ms": snap["ttft_ms_mean"],
        "serving_ttft_ms_p95": snap["ttft_ms_p95"],
        "serving_page_occupancy_peak": snap["page_occupancy_peak"],
        "serving_preemptions": snap["preemptions"],
        "serving_requests_completed": snap["requests_completed"],
        "serving_tokens_generated": snap["tokens_generated"],
        "serving_ticks": snap["ticks"],
    }
    print(json.dumps(out), flush=True)


def worker_serving_chaos():
    """worker_serving's Poisson trace re-run under the default seeded
    FaultPlan — page-pool pressure, one NaN-poisoned rid, random
    transient decode errors, and slow ticks — on the INJECTED clock (no
    wall-clock dependence, so the numbers replay bit-identically).  The
    SLO contract is asserted, not just reported: every non-poisoned
    request completes within its deadline or is shed with a terminal
    status, the poisoned rid ends FAILED while its fused batchmates keep
    greedy parity with the non-paged oracle, and the free-list
    conservation check passes at drain (a violation raises PageLeakError
    and fails the worker)."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import (DecoderLM, FaultPlan, ManualClock,
                                    RequestStatus, ServingEngine,
                                    greedy_decode_reference)

    paddle.init()
    rng = np.random.RandomState(0)
    vocab, eos = 512, 1
    model = DecoderLM(vocab_size=vocab, num_layers=2, num_heads=2,
                      head_dim=16, max_positions=256)
    params = model.init_params(jax.random.PRNGKey(0))
    clock = ManualClock(tick_s=0.02)
    plan = FaultPlan(seed=0, clock=clock,
                     decode_error_rate=0.05,          # transient, retried
                     slow_ticks={7: 0.3, 19: 0.5},    # injected tail ticks
                     page_pressure=(6, 26, 44))       # squeeze the pool
    eng = ServingEngine(model, params, eos_id=eos, page_size=16,
                        num_pages=64, max_pages_per_seq=8, max_slots=8,
                        buckets=(16, 32, 48), faults=plan,
                        watchdog_ticks=32, preempt_budget=3)
    n_req, rate = 24, 50.0          # same offered trace as worker_serving
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
    prompts = [rng.randint(2, vocab, size=rng.randint(4, 49)).tolist()
               for _ in range(n_req)]
    poison_idx, deadline_s = 5, 10.0

    rids = [None] * n_req
    i = 0
    while i < n_req or eng.has_work:
        while i < n_req and arrivals[i] <= clock():
            rids[i] = eng.submit(prompts[i], max_tokens=16,
                                 deadline_s=deadline_s)
            if i == poison_idx:
                plan.poison_nan(rids[i])
            i += 1
        eng.step()                  # advances the injected clock
        assert eng.metrics.ticks < 5000, "chaos trace failed to drain"
    results = eng.run(max_ticks=1)  # drained: runs the conservation check

    parity_checked = parity_ok = 0
    terminal_ok = True
    for j, rid in enumerate(rids):
        st = eng.status(rid)
        if j == poison_idx:
            assert st is RequestStatus.FAILED, f"poisoned rid: {st}"
            continue
        if st is RequestStatus.COMPLETED:
            parity_checked += 1
            want = greedy_decode_reference(model, params, prompts[j], 16,
                                           eos)
            parity_ok += int(results[rid] == want)
        else:
            # shed, not wedged: only terminal statuses are acceptable
            terminal_ok &= st in (RequestStatus.TIMED_OUT,
                                  RequestStatus.REJECTED,
                                  RequestStatus.CANCELLED)
    assert terminal_ok, "non-terminal survivor after drain"
    assert parity_checked == parity_ok, "greedy parity broke under chaos"
    leaked = eng.pool.total_refs          # live refs after a drain = leaks
    assert leaked == 0, f"{leaked} page refs leaked"

    snap = eng.metrics.snapshot()
    hz = eng.healthz()
    out = {
        "serving_chaos_model": "decoderlm_L2_H2_D16_v512_page16_pool64"
                               "_slots8_faultplan_seed0",
        "serving_chaos_completed": snap["requests_completed"],
        "serving_chaos_timed_out": snap["requests_timed_out"],
        "serving_chaos_shed": snap["requests_shed"],
        "serving_chaos_failed": snap["requests_failed"],
        "serving_chaos_retries": snap["retries"],
        "serving_chaos_preemptions": snap["preemptions"],
        "serving_chaos_deadline_miss_rate": snap["deadline_miss_rate"],
        "serving_chaos_queue_wait_ms_p95": snap["queue_wait_ms_p95"],
        "serving_chaos_page_leaks": leaked,
        "serving_chaos_parity_ok": parity_ok,
        "serving_chaos_parity_checked": parity_checked,
        "serving_chaos_healthz_ok": int(bool(hz["ok"])),
        "serving_chaos_ticks": snap["ticks"],
    }
    print(json.dumps(out), flush=True)


def worker_serving_prefix():
    """Automatic prefix caching A/B: the Poisson trace re-shaped so every
    request shares a 256-token system prompt (16 full pages at page 16)
    ahead of a unique 4..16-token tail, replayed TWICE on the same
    injected clock and seed — cache OFF then cache ON.  Chunked prefill
    (64-token chunks) runs in both, so the delta isolates the cache.
    Asserts, not just reports: token-identical outputs between the runs
    (and vs the non-paged oracle on a spot-check), prefix_hit_rate >
    0.5, prefill_tokens_saved > 0, and zero page-ref leaks at both
    drains.  Reports hit rate, tokens saved, COW forks, and TTFT p95
    on/off in injected-clock ms (replays bit-identically)."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import (DecoderLM, FaultPlan, ManualClock,
                                    RequestStatus, ServingEngine,
                                    greedy_decode_reference)

    paddle.init()
    rng = np.random.RandomState(0)
    vocab, eos = 512, 1
    model = DecoderLM(vocab_size=vocab, num_layers=2, num_heads=2,
                      head_dim=16, max_positions=512)
    params = model.init_params(jax.random.PRNGKey(0))
    n_req, rate = 24, 50.0
    system = rng.randint(2, vocab, size=256).tolist()   # 16 full pages
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
    prompts = [system + rng.randint(2, vocab,
                                    size=rng.randint(4, 17)).tolist()
               for _ in range(n_req)]

    def replay(prefix_cache):
        clock = ManualClock(tick_s=0.02)
        eng = ServingEngine(model, params, eos_id=eos, page_size=16,
                            num_pages=192, max_pages_per_seq=20,
                            max_slots=8, buckets=(16, 32, 64),
                            prefill_chunk=64, prefix_cache=prefix_cache,
                            faults=FaultPlan(clock=clock))
        rids = [None] * n_req
        i = 0
        while i < n_req or eng.has_work:
            while i < n_req and arrivals[i] <= clock():
                rids[i] = eng.submit(prompts[i], max_tokens=16)
                i += 1
            eng.step()
            assert eng.metrics.ticks < 5000, "prefix trace failed to drain"
        results = eng.run(max_ticks=1)      # drained: conservation check
        assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
        assert eng.pool.total_refs == 0, "page refs leaked"
        return [results[r] for r in rids], eng.metrics.snapshot()

    outs_off, snap_off = replay(False)
    outs_on, snap_on = replay(True)

    # greedy parity: token-identical with the cache on, and the oracle
    # agrees on a spot-check (the full sweep would dominate the worker)
    assert outs_on == outs_off, "prefix caching broke greedy parity"
    for j in (0, 7, 23):
        want = greedy_decode_reference(model, params, prompts[j], 16, eos)
        assert outs_on[j] == want, f"oracle parity broke on request {j}"
    assert snap_on["prefix_hit_rate"] > 0.5, snap_on["prefix_hit_rate"]
    assert snap_on["prefill_tokens_saved"] > 0
    assert snap_off["prefill_tokens_saved"] == 0

    out = {
        "serving_prefix_model": "decoderlm_L2_H2_D16_v512_page16_pool192"
                                "_slots8_sys256_chunk64",
        "serving_prefix_hit_rate": snap_on["prefix_hit_rate"],
        "serving_prefix_tokens_saved": snap_on["prefill_tokens_saved"],
        "serving_prefix_prefill_tokens_on": snap_on["prefill_tokens"],
        "serving_prefix_prefill_tokens_off": snap_off["prefill_tokens"],
        "serving_prefix_cow_forks": snap_on["cow_forks"],
        "serving_prefix_cache_evictions": snap_on["cache_evictions"],
        "serving_prefix_ttft_ms_p95_on": snap_on["ttft_ms_p95"],
        "serving_prefix_ttft_ms_p95_off": snap_off["ttft_ms_p95"],
        "serving_prefix_ticks_on": snap_on["ticks"],
        "serving_prefix_ticks_off": snap_off["ticks"],
        "serving_prefix_completed": snap_on["requests_completed"],
        "serving_prefix_parity_ok": int(outs_on == outs_off),
    }
    print(json.dumps(out), flush=True)


def worker_serving_mixed():
    """Ragged-paged-attention-v2 A/B (round 12) on the trace shape the
    v1 tick interleave handled worst: mixed long-prefill/heavy-decode
    Poisson traffic — long shared-prefix prompts chunking while short
    chatty requests decode.  Four deterministic replays on one injected
    arrival clock:

    1. ``fuse_tick=False`` f32 — the v1 two-dispatch tick shape (the
       baseline control: same math, prefill and decode as separate
       dispatches);
    2. ``fuse_tick=True``  f32 — the unified step (one dispatch, one
       ragged softmax pass per tick);
    3. unified + prefix cache, f32  — at a FIXED pool byte budget;
    4. unified + prefix cache, int8 — same byte budget, ~3x the pages.

    Asserts, not just reports: 1 and 2 token-identical with 2 paying
    strictly fewer dispatches; int8 admits >= 1.8x the f32 pages at the
    same pool bytes; every replay completes everything with 0 page/ref
    leaks.  Wall-clock tokens/s is CPU PROXY ONLY (the 1.3x unified-vs-
    interleave acceptance target is a chip number); the structure —
    dispatch counts, prefill rows, hit rates, effective pages — replays
    bit-identically on the injected clock."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import (DecoderLM, FaultPlan, ManualClock,
                                    RequestStatus, ServingEngine,
                                    greedy_decode_reference)

    paddle.init()
    rng = np.random.RandomState(0)
    vocab, eos = 512, 1
    model = DecoderLM(vocab_size=vocab, num_layers=2, num_heads=2,
                      head_dim=16, max_positions=512)
    params = model.init_params(jax.random.PRNGKey(0))
    pool_bytes = 96 * 16384     # 96 f32 pages at page 16 (L2, H2, D16)

    system = rng.randint(2, vocab, size=64).tolist()   # 4 shared pages
    n_long, n_short = 8, 16
    reqs = []                   # (prompt, max_tokens)
    for _ in range(n_long):     # long prefill, short decode
        tail = rng.randint(2, vocab, size=int(rng.randint(96, 160))).tolist()
        reqs.append((system + tail, 6))
    for _ in range(n_short):    # short prefill, heavy decode
        reqs.append((rng.randint(2, vocab,
                                 size=int(rng.randint(4, 13))).tolist(), 32))
    order = rng.permutation(len(reqs))
    arrivals = np.cumsum(rng.exponential(1.0 / 40.0, len(reqs)))

    def replay(fuse, kv_dtype, prefix_cache):
        clock = ManualClock(tick_s=0.02)
        eng = ServingEngine(model, params, eos_id=eos, page_size=16,
                            num_pages=None, pool_bytes=pool_bytes,
                            max_pages_per_seq=16, max_slots=8,
                            buckets=(32, 64, 128), prefill_chunk=64,
                            fuse_tick=fuse, kv_dtype=kv_dtype,
                            prefix_cache=prefix_cache,
                            faults=FaultPlan(clock=clock))
        rids = [None] * len(reqs)
        t0 = time.monotonic()
        i = 0
        while i < len(reqs) or eng.has_work:
            while i < len(reqs) and arrivals[i] <= clock():
                p, mt = reqs[order[i]]
                rids[order[i]] = eng.submit(p, max_tokens=mt)
                i += 1
            eng.step()
            assert eng.metrics.ticks < 8000, "mixed trace failed to drain"
        wall = time.monotonic() - t0
        results = eng.run(max_ticks=1)      # drained: conservation check
        assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
        assert eng.pool.total_refs == 0, "page refs leaked"
        outs = [results[r] for r in rids]
        snap = eng.metrics.snapshot()
        return outs, snap, wall, eng.pool.num_usable

    outs_base, snap_base, wall_base, _ = replay(False, "float32", False)
    outs_fuse, snap_fuse, wall_fuse, pages_f32 = replay(True, "float32",
                                                        False)
    assert outs_fuse == outs_base, "unified step broke greedy parity"
    assert snap_fuse["step_dispatches"] < snap_base["step_dispatches"]
    for j in (0, n_long, n_long + n_short - 1):   # oracle spot-check
        p, mt = reqs[j]
        assert outs_fuse[j] == greedy_decode_reference(model, params, p,
                                                       mt, eos)
    outs_f32c, snap_f32c, _, _ = replay(True, "float32", True)
    assert outs_f32c == outs_base, "prefix cache broke greedy parity"
    outs_i8c, snap_i8c, _, pages_i8 = replay(True, "int8", True)
    assert pages_i8 >= int(1.8 * pages_f32), (pages_i8, pages_f32)
    i8_agree = sum(int(a == b) for a, b in zip(outs_i8c, outs_base))

    out = {
        "serving_mixed_model": "decoderlm_L2_H2_D16_v512_page16_"
                               f"{pool_bytes >> 10}KiB_slots8_chunk64",
        "serving_mixed_tokens_per_s_interleave": round(
            snap_base["tokens_generated"] / max(wall_base, 1e-9), 2),
        "serving_mixed_tokens_per_s_unified": round(
            snap_fuse["tokens_generated"] / max(wall_fuse, 1e-9), 2),
        "serving_mixed_unified_speedup": round(wall_base /
                                               max(wall_fuse, 1e-9), 3),
        "serving_mixed_dispatches_interleave": snap_base["step_dispatches"],
        "serving_mixed_dispatches_unified": snap_fuse["step_dispatches"],
        "serving_mixed_ticks": snap_fuse["ticks"],
        "serving_mixed_prefill_rows": snap_fuse["prefill_rows"],
        "serving_mixed_ttft_ms_p95_interleave": snap_base["ttft_ms_p95"],
        "serving_mixed_ttft_ms_p95_unified": snap_fuse["ttft_ms_p95"],
        "serving_mixed_pages_f32": pages_f32,
        "serving_mixed_pages_int8": pages_i8,
        "serving_mixed_capacity_ratio": round(pages_i8 / pages_f32, 2),
        "serving_mixed_hit_rate_f32": snap_f32c["prefix_hit_rate"],
        "serving_mixed_hit_rate_int8": snap_i8c["prefix_hit_rate"],
        "serving_mixed_ttft_ms_p95_int8_cache": snap_i8c["ttft_ms_p95"],
        "serving_mixed_parity_ok": int(outs_fuse == outs_base),
        "serving_mixed_int8_token_agreement": round(i8_agree / len(reqs),
                                                    4),
        "serving_mixed_completed": snap_i8c["requests_completed"],
    }
    print(json.dumps(out), flush=True)


def worker_serving_tp():
    """Tensor-parallel serving A/B (round 13): the mixed long-prefill /
    heavy-decode Poisson trace replayed THREE times on one injected
    clock — replicated (mesh=None), tp=2 and tp=4 over a `model` mesh
    axis of the virtual-8 host — with ``FLAGS.jit_audit`` on so every
    replay's ``serving.step`` is captured and statically audited by the
    sharding-propagation auditor (paddle_tpu.analysis.sharding).

    Asserts, not just reports: tp=2 and tp=4 greedy outputs are
    TOKEN-IDENTICAL to the replicated control, every replay completes
    everything with 0 page/ref leaks, the audited
    ``comm_bytes_total{site=serving.step}`` equals the closed-form
    megatron psum budget (2 row-parallel psums per layer, 2*b*(N-1)/N
    each) with ZERO sharding-audit errors (no implicit all-gather on
    the decode hot path), and the same per-chip pool byte budget admits
    tp x the pages.  Wall-clock tokens/s is CPU PROXY ONLY (GSPMD over
    virtual CPU devices pays host-thread collectives; the per-chip
    speedup is a chip number) — the structure is what's pinned."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.analysis import sharding as shard_audit
    from paddle_tpu.analysis.retrace import auditor
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.platform.flags import FLAGS
    from paddle_tpu.serving import (DecoderLM, FaultPlan, ManualClock,
                                    RequestStatus, ServingEngine)

    paddle.init()
    rng = np.random.RandomState(0)
    vocab, eos = 512, 1
    model = DecoderLM(vocab_size=vocab, num_layers=2, num_heads=4,
                      head_dim=16, max_positions=512)
    params = model.init_params(jax.random.PRNGKey(0))
    pool_bytes = 96 * _tp_page_bytes(model)       # per-CHIP budget

    system = rng.randint(2, vocab, size=32).tolist()   # 2 shared pages
    reqs = []
    for _ in range(6):          # long prefill, short decode
        tail = rng.randint(2, vocab, size=int(rng.randint(48, 81))).tolist()
        reqs.append((system + tail, 6))
    for _ in range(10):         # short prefill, heavy decode
        reqs.append((rng.randint(2, vocab,
                                 size=int(rng.randint(4, 13))).tolist(), 16))
    order = rng.permutation(len(reqs))
    arrivals = np.cumsum(rng.exponential(1.0 / 40.0, len(reqs)))

    old_audit = FLAGS.jit_audit
    FLAGS.jit_audit = True

    def replay(tp):
        auditor().reset()
        mesh = None if tp == 1 else make_mesh((tp,), ("model",),
                                              jax.devices()[:tp])
        clock = ManualClock(tick_s=0.02)
        eng = ServingEngine(model, params, eos_id=eos, page_size=16,
                            num_pages=None, pool_bytes=pool_bytes,
                            max_pages_per_seq=16, max_slots=8,
                            buckets=(32, 64, 128), prefill_chunk=64,
                            kv_dtype="float32", prefix_cache=True,
                            faults=FaultPlan(clock=clock), mesh=mesh)
        rids = [None] * len(reqs)
        t0 = time.monotonic()
        i = 0
        while i < len(reqs) or eng.has_work:
            while i < len(reqs) and arrivals[i] <= clock():
                p, mt = reqs[order[i]]
                rids[order[i]] = eng.submit(p, max_tokens=mt)
                i += 1
            eng.step()
            assert eng.metrics.ticks < 8000, "tp trace failed to drain"
        wall = time.monotonic() - t0
        results = eng.run(max_ticks=1)      # drained: conservation check
        assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
        assert eng.pool.total_refs == 0, "page refs leaked"
        reps = shard_audit.audit_sharding_sites(sites=["serving.step"])
        rep = reps["serving.step"]
        assert not rep.errors, [d.message for d in rep.errors]
        rec = auditor().sites["serving.step"]
        budget = max((eng.tp_step_comm_bytes(cap.args[2].shape[0]
                                             + cap.args[5].shape[0])
                      for cap in rec.captured.values()), default=0.0)
        assert rep.comm_bytes == budget, (rep.comm_bytes, budget)
        outs = [results[r] for r in rids]
        snap = eng.metrics.snapshot()
        return outs, snap, wall, eng.pool.num_usable, rep.comm_bytes

    try:
        outs_rep, snap_rep, wall_rep, pages_rep, comm_rep = replay(1)
        outs_tp2, snap_tp2, wall_tp2, pages_tp2, comm_tp2 = replay(2)
        outs_tp4, snap_tp4, wall_tp4, pages_tp4, comm_tp4 = replay(4)
    finally:
        FLAGS.jit_audit = old_audit
        auditor().reset()
    assert outs_tp2 == outs_rep, "tp=2 broke greedy parity"
    assert outs_tp4 == outs_rep, "tp=4 broke greedy parity"
    assert comm_rep == 0.0
    assert pages_tp2 >= 2 * pages_rep and pages_tp4 >= 4 * pages_rep

    def per_chip(snap, wall, tp):
        return round(snap["tokens_generated"] / max(wall, 1e-9) / tp, 2)

    out = {
        "serving_tp_model": "decoderlm_L2_H4_D16_v512_page16_"
                            f"{pool_bytes >> 10}KiB_per_chip_slots8",
        "serving_tp_tokens_per_s_per_chip_rep": per_chip(snap_rep,
                                                         wall_rep, 1),
        "serving_tp_tokens_per_s_per_chip_tp2": per_chip(snap_tp2,
                                                         wall_tp2, 2),
        "serving_tp_tokens_per_s_per_chip_tp4": per_chip(snap_tp4,
                                                         wall_tp4, 4),
        "serving_tp_ttft_ms_p95_rep": snap_rep["ttft_ms_p95"],
        "serving_tp_ttft_ms_p95_tp2": snap_tp2["ttft_ms_p95"],
        "serving_tp_ttft_ms_p95_tp4": snap_tp4["ttft_ms_p95"],
        "serving_tp_comm_bytes_step_rep": comm_rep,
        "serving_tp_comm_bytes_step_tp2": comm_tp2,
        "serving_tp_comm_bytes_step_tp4": comm_tp4,
        "serving_tp_pages_per_chip_budget_rep": pages_rep,
        "serving_tp_pages_per_chip_budget_tp2": pages_tp2,
        "serving_tp_pages_per_chip_budget_tp4": pages_tp4,
        "serving_tp_parity_ok": int(outs_tp2 == outs_rep
                                    and outs_tp4 == outs_rep),
        "serving_tp_hit_rate_tp2": snap_tp2["prefix_hit_rate"],
        "serving_tp_completed": snap_tp4["requests_completed"],
    }
    print(json.dumps(out), flush=True)


def worker_serving_spec():
    """Speculative decoding A/B (round 18): a CHATTY Poisson trace —
    short repetitive prompts (a shared greeting + a repeated phrase),
    short replies — replayed THREE times on one injected clock:
    spec-off (control), n-gram/prompt-lookup speculation, and
    draft-model speculation (a 1-layer draft with its own paged pool).
    All greedy, so the control IS the oracle trajectory.

    Asserts, not just reports: the n-gram replay is token-identical to
    the spec-off control; decode ticks per emitted token drop >= 1.5x
    under n-gram speculation at the measured acceptance rate; and all
    three replays drain with 0 page/ref leaks (draft pool included).
    Wall-clock tokens/s is CPU PROXY ONLY; ticks-per-token, acceptance
    rate and TTFT replay bit-identically on the injected clock."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import (DecoderLM, FaultPlan, ManualClock,
                                    RequestStatus, ServingEngine)

    paddle.init()
    rng = np.random.RandomState(0)
    vocab, eos, gen = 512, 1, 24
    model = DecoderLM(vocab_size=vocab, num_layers=2, num_heads=2,
                      head_dim=16, max_positions=256)
    params = model.init_params(jax.random.PRNGKey(0))
    # the draft: a 1-layer model wearing the target's embeddings, first
    # layer and head — the "distilled draft" stand-in (random draft
    # weights would accept ~nothing and say nothing about the machinery)
    draft = DecoderLM(vocab_size=vocab, num_layers=1, num_heads=2,
                      head_dim=16, max_positions=256)
    dparams = {k: params[k] for k in
               ("emb", "pos", "out", "l0.wq", "l0.wk", "l0.wv",
                "l0.wo", "l0.w1", "l0.w2")}
    n_req, rate = 24, 50.0
    greeting = rng.randint(2, vocab, size=6).tolist()
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
    prompts = []
    for _ in range(n_req):
        phrase = rng.randint(2, vocab, size=3).tolist()
        prompts.append(greeting + phrase * 3 +
                       rng.randint(2, vocab, size=2).tolist())

    def replay(mode, **kw):
        clock = ManualClock(tick_s=0.02)
        eng = ServingEngine(model, params, eos_id=eos, page_size=16,
                            num_pages=96, max_pages_per_seq=8,
                            max_slots=8, buckets=(16, 32),
                            spec_mode=mode, spec_k=4,
                            faults=FaultPlan(clock=clock), **kw)
        rids = [None] * n_req
        i = 0
        t0 = time.monotonic()
        while i < n_req or eng.has_work:
            while i < n_req and arrivals[i] <= clock():
                rids[i] = eng.submit(prompts[i], max_tokens=gen)
                i += 1
            eng.step()
            assert eng.metrics.ticks < 5000, "spec trace failed to drain"
        wall = time.monotonic() - t0
        eng.run(max_ticks=1)          # drained: conservation check
        assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
        assert eng.pool.total_refs == 0, "page refs leaked"
        snap = eng.metrics.snapshot()
        results = [eng.result(r) for r in rids]
        # decode ticks per emitted decode token: each request's verify-
        # tick participations (decode_slots: one per running slot per
        # step) over the tokens those ticks emitted (first tokens come
        # from prefill, not a decode tick)
        decode_tokens = snap["tokens_generated"] - len(rids)
        tpt = snap["decode_slots"] / max(1, decode_tokens)
        return results, snap, tpt, wall

    outs_off, snap_off, tpt_off, wall_off = replay("off")
    outs_ng, snap_ng, tpt_ng, wall_ng = replay("ngram")
    outs_dr, snap_dr, tpt_dr, wall_dr = replay(
        "draft", draft_model=draft, draft_params=dparams)

    assert outs_ng == outs_off, "ngram speculation broke greedy parity"
    assert outs_dr == outs_off, "draft speculation broke greedy parity"
    assert snap_ng["spec_tokens_accepted"] > 0
    reduction = tpt_off / max(tpt_ng, 1e-9)
    assert reduction >= 1.5, (
        f"decode ticks/token only improved {reduction:.2f}x "
        f"(acceptance {snap_ng['spec_acceptance_rate']})")

    out = {
        "serving_spec_model": "decoderlm_L2_H2_D16_v512_page16_pool96"
                              "_slots8_chatty24_k4",
        "serving_spec_ticks_per_token_off": round(tpt_off, 4),
        "serving_spec_ticks_per_token_ngram": round(tpt_ng, 4),
        "serving_spec_ticks_per_token_draft": round(tpt_dr, 4),
        "serving_spec_reduction_ngram": round(reduction, 4),
        "serving_spec_acceptance_ngram": snap_ng["spec_acceptance_rate"],
        "serving_spec_acceptance_draft": snap_dr["spec_acceptance_rate"],
        "serving_spec_rollbacks_ngram": snap_ng["spec_rollbacks"],
        "serving_spec_suspended_ngram": snap_ng["spec_suspended"],
        "serving_spec_draft_steps": snap_dr["draft_steps"],
        "serving_spec_draft_time_s": snap_dr["draft_time_s"],
        "serving_spec_tokens_per_s_off": round(
            snap_off["tokens_generated"] / max(wall_off, 1e-9), 2),
        "serving_spec_tokens_per_s_ngram": round(
            snap_ng["tokens_generated"] / max(wall_ng, 1e-9), 2),
        "serving_spec_tokens_per_s_draft": round(
            snap_dr["tokens_generated"] / max(wall_dr, 1e-9), 2),
        "serving_spec_ttft_ms_p95_off": snap_off["ttft_ms_p95"],
        "serving_spec_ttft_ms_p95_ngram": snap_ng["ttft_ms_p95"],
        "serving_spec_ticks_off": snap_off["ticks"],
        "serving_spec_ticks_ngram": snap_ng["ticks"],
        "serving_spec_completed": snap_ng["requests_completed"],
        "serving_spec_parity_ok": int(outs_ng == outs_off
                                      and outs_dr == outs_off),
    }
    print(json.dumps(out), flush=True)


def _tp_page_bytes(model):
    """f32 bytes one tp=1 page costs for ``model`` at page 16 — the
    per-chip pool budget unit worker_serving_tp sizes with."""
    from paddle_tpu.serving.kv_cache import PagedKVConfig

    return PagedKVConfig(num_layers=model.num_layers,
                         num_heads=model.num_heads,
                         head_dim=model.head_dim, page_size=16,
                         num_pages=2, max_pages_per_seq=1).bytes_per_page()


def worker_serving_fleet():
    """Fleet-level serving A/B: FOUR ServingEngine replicas behind a
    FleetRouter on one injected clock, a Poisson trace of SIX tenants —
    each tenant's requests share a 128-token system prompt (8 full
    pages) ahead of unique 4..16 token tails — and replica 0 KILLED
    mid-trace; replayed twice with the same seed, prefix-affinity
    routing vs round-robin.  The pool is sized so ONE replica cannot
    cache every tenant's prefix (6 x 8 = 48 prefix pages vs ~20 spare):
    round-robin makes every replica serve every tenant, so caches churn
    under LRU eviction and the PR 4 hit rate collapses under fan-out,
    while affinity gives each prefix one home (arXiv 2604.15464).  The
    robustness contract is asserted, not just reported: every request
    reaches a terminal status under both policies, nothing completes
    twice (duplicate_completions == 0), the fleet conservation check
    passes at both drains (0 page/ref leaks across ALL replicas, dead
    one included), and requests completed under both policies are
    token-identical (greedy parity survives the kill-resubmit path).
    The A/B claim: affinity beats round-robin on aggregate
    prefix_hit_rate AND deadline_miss_rate on this shared-prefix
    trace."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import (DecoderLM, FleetFaultPlan, FleetRouter,
                                    ManualClock, RequestStatus,
                                    ServingEngine)

    paddle.init()
    rng = np.random.RandomState(0)
    vocab, eos = 512, 1
    model = DecoderLM(vocab_size=vocab, num_layers=2, num_heads=2,
                      head_dim=16, max_positions=512)
    params = model.init_params(jax.random.PRNGKey(0))
    n_req, rate, n_tenants = 36, 50.0, 6
    systems = [rng.randint(2, vocab, size=128).tolist()
               for _ in range(n_tenants)]              # 8 full pages each
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
    prompts = [systems[j % n_tenants] +
               rng.randint(2, vocab, size=rng.randint(4, 17)).tolist()
               for j in range(n_req)]
    # 0.8 injected-seconds sits between the two policies' tail latencies
    # on this trace (affinity completes everything by ~0.66; round-robin's
    # cache-churn tail runs to ~0.80, and its kill-victim's resubmission
    # pays a full cache-miss re-prefill it can no longer afford): tight
    # enough that round-robin sheds, loose enough that affinity serves all
    deadline_s, kill_tick = 0.8, 25

    def replay(routing):
        from paddle_tpu.obs import MetricsRegistry, Tracer

        clock = ManualClock(tick_s=0.02)
        plan = FleetFaultPlan(seed=0, clock=clock,
                              kill_at={kill_tick: 0})   # 1-of-4 dies

        def mk(i, time_fn):
            return ServingEngine(model, params, eos_id=eos, page_size=16,
                                 num_pages=56, max_pages_per_seq=12,
                                 max_slots=4, buckets=(16, 64),
                                 prefill_chunk=64, time_fn=time_fn)

        # obs: one explicit tracer + registry per replay (same injected
        # clock), so the bench ships a trace artifact and a per-stage
        # latency breakdown without touching the global FLAGS gate
        registry = MetricsRegistry()
        tracer = Tracer(time_fn=clock, registry=registry)
        fleet = FleetRouter(mk, 4, heartbeat_s=0.1, resubmit_budget=2,
                            routing=routing, faults=plan, tracer=tracer,
                            registry=registry)
        rids = []
        i = 0
        while i < n_req or fleet.has_work:
            while i < n_req and arrivals[i] <= clock():
                rids.append(fleet.submit(prompts[i], max_tokens=16,
                                         deadline_s=deadline_s))
                i += 1
            fleet.step()
            assert fleet._tick < 5000, "fleet trace failed to drain"
        fleet.run(max_ticks=1)      # drained: fleet conservation check
        statuses = [fleet.status(r) for r in rids]
        assert all(s.terminal for s in statuses), "non-terminal survivor"
        snap = fleet.snapshot()
        assert snap["fleet_duplicate_completions"] == 0
        outs = {j: fleet.result(r) for j, r in enumerate(rids)
                if fleet.status(r) is RequestStatus.COMPLETED}
        return outs, snap, fleet

    outs_aff, snap_aff, fleet_aff = replay("affinity")
    outs_rr, snap_rr, _ = replay("round_robin")

    # per-stage latency attribution (injected-clock seconds) from the
    # unified registry — the baseline future kernel PRs diff against:
    # where does a request's time go, queue vs prefill vs decode, and
    # how much re-dispatch churn did the kill cause
    def stage_ms(fleet):
        stages = {}
        hist = fleet.registry.histogram("serving_stage_seconds")
        for key, s in hist.series():
            stage = dict(key)["stage"]
            tot, cnt = stages.get(stage, (0.0, 0))
            stages[stage] = (tot + s.sum, cnt + s.count)
        return {stage: round(1000.0 * tot / cnt, 2) if cnt else 0.0
                for stage, (tot, cnt) in stages.items()}

    stages_aff = stage_ms(fleet_aff)

    # trace artifact: the affinity replay's full timeline as
    # Chrome-trace JSON (open in ui.perfetto.dev), next to the numbers
    from paddle_tpu.obs import save_chrome_trace
    from paddle_tpu.platform.flags import FLAGS as _FLAGS

    os.makedirs(str(_FLAGS.obs_dump_dir), exist_ok=True)
    trace_path = os.path.join(str(_FLAGS.obs_dump_dir),
                              "worker_serving_fleet_trace.json")
    save_chrome_trace(fleet_aff.tracer.events, trace_path)

    # greedy parity across policies: a request completed under BOTH saw
    # token-identical output no matter which replicas computed it (and
    # no matter whether the kill forced a resubmission)
    common = sorted(set(outs_aff) & set(outs_rr))
    assert common, "no common completions to compare"
    assert all(outs_aff[j] == outs_rr[j] for j in common), \
        "fleet routing broke greedy parity"
    assert snap_aff["fleet_prefix_hit_rate"] > \
        snap_rr["fleet_prefix_hit_rate"], (
        snap_aff["fleet_prefix_hit_rate"], snap_rr["fleet_prefix_hit_rate"])
    assert snap_aff["fleet_deadline_miss_rate"] < \
        snap_rr["fleet_deadline_miss_rate"], (
        snap_aff["fleet_deadline_miss_rate"],
        snap_rr["fleet_deadline_miss_rate"])

    out = {
        "serving_fleet_model": "decoderlm_L2_H2_D16_v512_page16_pool56x4"
                               "_slots4_sys128x6tenants_chunk64_kill1of4",
        "serving_fleet_hit_rate_affinity": snap_aff["fleet_prefix_hit_rate"],
        "serving_fleet_hit_rate_rr": snap_rr["fleet_prefix_hit_rate"],
        "serving_fleet_miss_rate_affinity":
            snap_aff["fleet_deadline_miss_rate"],
        "serving_fleet_miss_rate_rr": snap_rr["fleet_deadline_miss_rate"],
        "serving_fleet_tokens_per_s_affinity":
            snap_aff["fleet_tokens_per_s"],
        "serving_fleet_tokens_per_s_rr": snap_rr["fleet_tokens_per_s"],
        "serving_fleet_completed_affinity": snap_aff["fleet_completed"],
        "serving_fleet_completed_rr": snap_rr["fleet_completed"],
        "serving_fleet_resubmits_affinity": snap_aff["fleet_resubmits"],
        "serving_fleet_resubmits_rr": snap_rr["fleet_resubmits"],
        "serving_fleet_shed_affinity": snap_aff["fleet_shed"],
        "serving_fleet_shed_rr": snap_rr["fleet_shed"],
        "serving_fleet_duplicate_completions": 0,
        "serving_fleet_parity_ok": int(all(outs_aff[j] == outs_rr[j]
                                           for j in common)),
        "serving_fleet_parity_checked": len(common),
        # per-stage breakdown (affinity replay, injected-ms means) +
        # the exported trace artifact — the latency-attribution
        # baseline for ROADMAP item 2's kernel work
        "serving_fleet_stage_queue_ms": stages_aff.get("queue", 0.0),
        "serving_fleet_stage_prefill_ms": stages_aff.get("prefill", 0.0),
        "serving_fleet_stage_decode_ms": stages_aff.get("decode", 0.0),
        "serving_fleet_trace_path": trace_path,
        "serving_fleet_trace_events": len(fleet_aff.tracer.events),
    }
    print(json.dumps(out), flush=True)


def worker_serving_disagg():
    """Disaggregated prefill/decode fleet A/B (round 16): the SAME
    seeded hot-tenant trace — one 128-token system prompt behind ~70%
    of requests plus three 64-token cold tenants, Poisson arrivals —
    replayed through four replicas unified vs disaggregated (2 prefill
    + 2 decode with live KV chain migration) on one injected clock.

    The mechanism under test: unified prefix-affinity pins the hot
    tenant to ONE owner replica, so its prompts queue head-of-line
    behind that replica's busy decode slots while other replicas sit
    idle; disaggregation routes prompts by the O(1)
    ``prefill_backlog_tokens`` probe across BOTH prefill replicas and
    keeps the hit rate via cross-replica prefix seeding, then hands
    finished prefills to the decode side through the page plane.
    Asserted, not just reported: token-identical outputs across the two
    deployments (migration changes WHERE, never WHAT), TTFT p95
    improved >= 1.2x, decode ticks/token no worse, chain migrations
    actually ran, 0 leaks (fleet + migration conservation at both
    drains).  Two follow-up replays measure the interconnect: int8
    pages migrate stored-bytes + scales at (D+4)/4D = 0.3125x the f32
    bytes per request (asserted <= 0.35), and a kill-one-decode chaos
    replay must re-adopt surviving prefix pages through the page plane
    (migration_resubmits > 0) instead of re-prefilling from scratch."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import (DecoderLM, FleetFaultPlan, FleetRouter,
                                    ManualClock, ServingEngine)
    from paddle_tpu.serving.migrate import check_migration_conservation

    paddle.init()
    vocab, eos = 512, 1
    model = DecoderLM(vocab_size=vocab, num_layers=2, num_heads=2,
                      head_dim=16, max_positions=512)
    params = model.init_params(jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    n_req, rate, hot_w = 32, 60.0, 0.7
    hot = rng.randint(2, vocab, size=128).tolist()       # 8 full pages
    cold = [rng.randint(2, vocab, size=64).tolist() for _ in range(3)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
    prompts = []
    for _ in range(n_req):
        sysp = hot if rng.random_sample() < hot_w else cold[rng.randint(3)]
        prompts.append(sysp +
                       rng.randint(2, vocab, size=rng.randint(4, 17))
                       .tolist())
    roles_disagg = ("prefill", "prefill", "decode", "decode")

    def replay(roles, kv_dtype="float32", kill=None):
        clock = ManualClock(tick_s=0.02)
        plan = FleetFaultPlan(seed=0, clock=clock, kill_at=(kill or {}))

        def mk(i, time_fn):
            return ServingEngine(model, params, eos_id=eos, page_size=16,
                                 num_pages=72, max_pages_per_seq=14,
                                 max_slots=4, buckets=(16, 64),
                                 prefill_chunk=64, kv_dtype=kv_dtype,
                                 time_fn=time_fn)

        kw = {"roles": roles} if roles else {}
        fleet = FleetRouter(mk, 4, heartbeat_s=0.1, resubmit_budget=2,
                            faults=plan, migrate_budget=16, **kw)
        sub_t, first_t = {}, {}
        rids = []
        i = 0
        while i < n_req or fleet.has_work:
            while i < n_req and arrivals[i] <= clock():
                frid = fleet.submit(prompts[i], max_tokens=24)

                def cb_for(f):
                    def cb(tok):
                        first_t.setdefault(f, clock())
                    return cb

                # TTFT on the injected clock: submit -> first EMITTED
                # token (the exactly-once stream's, replay-safe)
                fleet._requests[frid].on_token = cb_for(frid)
                sub_t[frid] = clock()
                rids.append(frid)
                i += 1
            fleet.step()
            assert fleet._tick < 8000, "disagg trace failed to drain"
        fleet.run(max_ticks=1)      # drained: fleet conservation check
        check_migration_conservation(fleet)
        snap = fleet.snapshot()
        assert snap["fleet_duplicate_completions"] == 0
        assert all(fleet.status(r).terminal for r in rids)
        ttft = sorted(first_t[f] - sub_t[f] for f in rids if f in first_t)
        p95 = ttft[int(0.95 * (len(ttft) - 1))] if ttft else 0.0
        toks = sum(len(fleet.result(r) or []) for r in rids)
        outs = [fleet.result(r) for r in rids]
        return {"p95": p95, "ticks": fleet._tick, "tokens": toks,
                "outs": outs, "snap": snap}

    uni = replay(None)
    dis = replay(roles_disagg)

    # migration is a placement optimization: byte-for-byte the same
    # greedy streams, no matter which replica computed which token
    assert uni["outs"] == dis["outs"], "disaggregation broke parity"
    assert dis["snap"]["fleet_migrations_applied"] > 0
    assert uni["snap"]["fleet_migrations_started"] == 0   # paths dormant
    ttft_ratio = uni["p95"] / max(dis["p95"], 1e-9)
    tpt_uni = uni["ticks"] / max(uni["tokens"], 1)
    tpt_dis = dis["ticks"] / max(dis["tokens"], 1)
    assert ttft_ratio >= 1.2, (uni["p95"], dis["p95"])
    assert tpt_dis <= tpt_uni * 1.05, (tpt_dis, tpt_uni)

    # interconnect arithmetic: int8 chains move stored int8 payload +
    # f32 scales — (D+4)/4D of the f32 bytes at D=16
    bytes_per_req = {}
    for kv_dtype in ("float32", "int8"):
        s = replay(roles_disagg, kv_dtype=kv_dtype)["snap"]
        assert s["fleet_migrations_applied"] > 0
        bytes_per_req[kv_dtype] = (s["fleet_migration_bytes"] /
                                   s["fleet_migrations_applied"])
    int8_ratio = bytes_per_req["int8"] / bytes_per_req["float32"]
    assert int8_ratio <= 0.35, int8_ratio

    # chaos: kill one decode replica mid-trace — its in-flight chains
    # resubmit AND re-adopt surviving prefix pages through the page
    # plane (seeded from whichever replica still holds them) instead of
    # re-prefilling from token 0
    chaos = replay(roles_disagg, kill={30: 3})
    cs = chaos["snap"]
    assert cs["fleet_resubmits"] > 0
    assert cs["fleet_migration_resubmits"] > 0
    assert cs["fleet_seed_pages"] > 0
    assert cs["fleet_completed"] == n_req

    out = {
        "serving_disagg_model": "decoderlm_L2_H2_D16_v512_page16_pool72x4"
                                "_slots4_hot128_w0.7_2p2d_budget16",
        "serving_disagg_ttft_p95_s_unified": round(uni["p95"], 4),
        "serving_disagg_ttft_p95_s_disagg": round(dis["p95"], 4),
        "serving_disagg_ttft_p95_ratio": round(ttft_ratio, 3),
        "serving_disagg_ticks_per_token_unified": round(tpt_uni, 4),
        "serving_disagg_ticks_per_token_disagg": round(tpt_dis, 4),
        "serving_disagg_parity_ok": int(uni["outs"] == dis["outs"]),
        "serving_disagg_migrations_applied":
            dis["snap"]["fleet_migrations_applied"],
        "serving_disagg_pages_migrated":
            dis["snap"]["fleet_pages_migrated"],
        "serving_disagg_cross_replica_seeds":
            dis["snap"]["fleet_cross_replica_seeds"],
        "serving_disagg_hit_rate_unified":
            uni["snap"]["fleet_prefix_hit_rate"],
        "serving_disagg_hit_rate_disagg":
            dis["snap"]["fleet_prefix_hit_rate"],
        "serving_disagg_bytes_per_req_f32":
            round(bytes_per_req["float32"], 1),
        "serving_disagg_bytes_per_req_int8":
            round(bytes_per_req["int8"], 1),
        "serving_disagg_int8_bytes_ratio": round(int8_ratio, 4),
        "serving_disagg_chaos_resubmits": cs["fleet_resubmits"],
        "serving_disagg_chaos_migration_resubmits":
            cs["fleet_migration_resubmits"],
        "serving_disagg_chaos_seed_pages": cs["fleet_seed_pages"],
        "serving_disagg_chaos_completed": cs["fleet_completed"],
        "serving_disagg_duplicate_completions": 0,
    }
    print(json.dumps(out), flush=True)


def worker_serving_control():
    """Multi-tenant control-plane A/B (round 17): the six-tenant
    shared-prefix trace of worker_serving_fleet, sharpened into an
    adversarial 10x swing — one batch-class tenant storms at ten times
    the polite tenants' rate (FleetFaultPlan.tenant_storm, its own
    seeded RNG stream) while two interactive and three standard tenants
    submit steadily under their SLO-class deadlines.  The SAME arrivals
    replay twice through two replicas: weighted-fair queuing ON vs OFF
    (FIFO dispatch, the control).  The claim is isolation, asserted
    per tenant and not on averages: with WFQ on, EVERY non-storming
    tenant finishes with zero deadline misses — the storm's backlog is
    charged to the storming tenant's own virtual-time queue — while the
    FIFO control makes polite interactive tenants miss behind the
    storm's head-of-line burst.  The storm tenant is also token-bucket
    metered, so the admission ledger shows real quota_deferred work
    (identical across replays: the bucket sees the same costs at the
    same injected times).  A third replay turns the autoscaler on and
    KILLS a replica mid-storm: the fleet grows under the kill (join
    races death), shrinks back once drained, and the exactly-once +
    CONTROL-LEAK contracts hold through every scaling event — ledger
    partitions per tenant, no duplicate completions, zero page/ref
    leaks on every replica including the killed and drained ones.  A
    static fleet pinned at the autoscaler's max handles the same trace
    for the efficiency claim: the elastic fleet spends fewer
    replica-ticks at token-identical outputs (greedy parity — scaling
    changes WHERE, never WHAT)."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import (AutoscalePolicy, DecoderLM,
                                    FleetFaultPlan, FleetRouter,
                                    ManualClock, RequestStatus,
                                    ServingEngine, TenantRegistry,
                                    check_control_conservation)

    paddle.init()
    vocab, eos = 256, 1
    model = DecoderLM(vocab_size=vocab, num_layers=1, num_heads=2,
                      head_dim=16, max_positions=256)
    params = model.init_params(jax.random.PRNGKey(0))

    tenants = ["web", "chat", "app", "api", "etl", "storm"]
    classes = {"web": "interactive", "chat": "interactive",
               "app": "standard", "api": "standard", "etl": "standard",
               "storm": "batch"}
    rng0 = np.random.RandomState(0)
    systems = {t: rng0.randint(2, vocab, size=32).tolist()
               for t in tenants}                    # 2 full pages each
    storm_mult, window_end = 10, 10

    def mk_registry():
        reg = TenantRegistry()
        for t in tenants:
            if t == "storm":
                # metered: the storm pays for its own burst at the
                # bucket, before it can even reach the WFQ
                reg.register(t, classes[t], quota_tokens_per_s=3000.0,
                             burst_tokens=800.0)
            else:
                reg.register(t, classes[t])
        return reg

    def replay(wfq, autoscale=None, n=2, kill=None, idle_tail=0):
        clock = ManualClock(tick_s=0.02)
        plan = FleetFaultPlan(seed=0, clock=clock, kill_at=(kill or {}),
                              tenant_storm=("storm", 0, window_end,
                                            storm_mult))

        def mk(i, time_fn):
            return ServingEngine(model, params, eos_id=eos, page_size=16,
                                 num_pages=48, max_pages_per_seq=6,
                                 max_slots=4, buckets=(16, 64),
                                 prefill_chunk=32, time_fn=time_fn)

        fleet = FleetRouter(mk, n, heartbeat_s=0.1, resubmit_budget=2,
                            faults=plan, tenants=mk_registry(), wfq=wfq,
                            autoscale=autoscale)
        rng = np.random.RandomState(1)
        rids = []
        tick = 0
        while tick < window_end or fleet.has_work:
            if tick < window_end and tick % 2 == 0:
                for t in tenants:
                    for _ in range(plan.storm_factor(tick, t)):
                        prompt = systems[t] + rng.randint(
                            2, vocab, size=int(rng.randint(4, 10))).tolist()
                        rids.append((t, fleet.submit(prompt, max_tokens=6,
                                                     tenant=t)))
            fleet.step()
            tick += 1
            assert tick < 5000, "control trace failed to drain"
        snap_at_drain = fleet.snapshot()
        for _ in range(idle_tail):      # cold ticks: let scale-downs land
            fleet.step()
        check_control_conservation(fleet)
        assert all(fleet.status(r).terminal for _, r in rids)
        snap = fleet.snapshot()
        assert snap["fleet_duplicate_completions"] == 0
        # keyed by submission index, NOT frid: the frid counter is
        # process-global, so only the arrival order lines replays up
        outs = {j: fleet.result(frid) for j, (_, frid) in enumerate(rids)
                if fleet.status(frid) is RequestStatus.COMPLETED}
        hz = fleet.healthz()
        led = fleet.ledger.snapshot()
        # a polite tenant's misses live in two places: engine-side
        # timeouts (healthz aggregation) and router-side WFQ sheds
        # (ledger) — isolation must hold across BOTH
        misses = {t: hz["tenants"].get(t, {}).get("deadline_misses", 0) +
                  led.get(t, {}).get("shed", 0) for t in tenants}
        return {"outs": outs, "snap": snap, "snap_at_drain": snap_at_drain,
                "misses": misses, "ledger": led, "ticks": tick,
                "fleet": fleet}

    on = replay(wfq=True)
    off = replay(wfq=False)

    polite = [t for t in tenants if t != "storm"]
    # THE isolation claim, per tenant: WFQ keeps every polite tenant at
    # zero misses under the 10x storm; FIFO lets the storm starve them
    assert all(on["misses"][t] == 0 for t in polite), on["misses"]
    assert sum(off["misses"][t] for t in polite) > 0, off["misses"]
    # the bucket metered the storm identically in both replays — same
    # costs at the same injected times, WFQ on or off
    assert on["ledger"]["storm"]["quota_deferred"] > 0
    assert (on["ledger"]["storm"]["quota_deferred"] ==
            off["ledger"]["storm"]["quota_deferred"])
    # greedy parity on common completions: queuing policy changes WHEN
    # a request runs, never WHAT it decodes
    common = sorted(set(on["outs"]) & set(off["outs"]))
    assert common and all(on["outs"][f] == off["outs"][f] for f in common)

    # elastic replay: kill replica 0 mid-storm with the autoscaler live
    policy = AutoscalePolicy(min_replicas=2, max_replicas=4,
                             buffered_hi=4, cooldown_ticks=3)
    auto = replay(wfq=True, autoscale=policy, kill={4: 0}, idle_tail=20)
    scaler = auto["fleet"].autoscaler
    assert auto["snap"]["fleet_replicas_dead"] >= 1
    assert scaler.scale_ups >= 1, "fleet never grew under the kill"
    assert scaler.scale_downs >= 1, "fleet never shrank after the storm"
    # static control pinned at the autoscaler's ceiling, same arrivals
    static = replay(wfq=True, n=policy.max_replicas)
    elastic_common = sorted(set(auto["outs"]) & set(static["outs"]))
    assert elastic_common and all(
        auto["outs"][j] == static["outs"][j] for j in elastic_common), \
        "autoscaling broke greedy parity"
    auto_rt = auto["snap_at_drain"]["control_replica_ticks"]
    static_rt = policy.max_replicas * static["ticks"]
    assert auto_rt < static_rt, (auto_rt, static_rt)

    out = {
        "serving_control_model": "decoderlm_L1_H2_D16_v256_page16_pool48"
                                 "_slots4_6tenants_storm10x_sys32",
        "serving_control_requests": (len(on["outs"]) +
                                     sum(v["quota_deferred"]
                                         for v in on["ledger"].values())),
        "serving_control_polite_misses_wfq":
            sum(on["misses"][t] for t in polite),
        "serving_control_polite_misses_fifo":
            sum(off["misses"][t] for t in polite),
        "serving_control_storm_quota_deferred":
            on["ledger"]["storm"]["quota_deferred"],
        "serving_control_storm_submitted":
            on["ledger"]["storm"]["submitted"],
        "serving_control_parity_ok": int(all(on["outs"][f] == off["outs"][f]
                                             for f in common)),
        "serving_control_parity_checked": len(common),
        "serving_control_scale_ups": scaler.scale_ups,
        "serving_control_scale_downs": scaler.scale_downs,
        "serving_control_replica_ticks_auto": auto_rt,
        "serving_control_replica_ticks_static": static_rt,
        "serving_control_replica_ticks_saved":
            round(1.0 - auto_rt / max(1, static_rt), 4),
        "serving_control_chaos_resubmits":
            auto["snap"]["fleet_resubmits"],
        "serving_control_duplicate_completions": 0,
    }
    print(json.dumps(out), flush=True)


def worker_serving_hosttier():
    """Hierarchical KV cache A/B (round 21): a tenant-count sweep whose
    per-tenant system prefixes OVERFLOW the device pool — each tenant's
    cached prefix is evicted before its next request arrives — replayed
    tier-off vs tier-on on the same injected clock and trace.  Tier-off,
    every revisit re-prefills the full prefix; tier-on, eviction spills
    the pages (checksummed) to host RAM and the revisit swaps them back
    in under the per-tick budget.  Asserts, not just reports:
    token-identical outputs between the replays at every tenant count,
    hit rate strictly higher and prefill tokens strictly lower with the
    tier on, zero HOSTTIER-CORRUPT pages, and clean three-state page
    conservation at both drains.  Then the crash-warm restart replay: a
    fleet replica whose host tier holds spilled pages is killed at a
    tick and ``restart_replica`` rebuilds it; asserts pages_restored >
    0, token parity on the re-served prompt, and 0 duplicate
    completions.  Reports hit rate / TTFT p95 / prefill tokens per
    tenant count, swap traffic, and the restart numbers."""
    import numpy as np

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.serving import (DecoderLM, FaultPlan, FleetFaultPlan,
                                    FleetRouter, ManualClock,
                                    RequestStatus, ServingEngine)

    paddle.init()
    vocab, eos, page = 256, 1, 8
    model = DecoderLM(vocab_size=vocab, num_layers=1, num_heads=2,
                      head_dim=16, max_positions=256)
    params = model.init_params(jax.random.PRNGKey(0))
    out = {"serving_hosttier_model":
           "decoderlm_L1_H2_D16_v256_page8_pool28_slots2_sys64_chunk32"}

    def replay(n_tenants, host_bytes, rng_seed=0):
        rng = np.random.RandomState(rng_seed)
        systems = [rng.randint(2, vocab, size=64).tolist()   # 8 pages each
                   for _ in range(n_tenants)]
        prompts, tenants = [], []
        for rnd in range(3):                # 3 visits per tenant
            for t in range(n_tenants):
                prompts.append(systems[t] +
                               rng.randint(2, vocab, size=8).tolist())
                tenants.append(f"t{t}")
        clock = ManualClock(tick_s=0.02)
        eng = ServingEngine(model, params, eos_id=eos, page_size=page,
                            num_pages=28, max_pages_per_seq=12,
                            max_slots=2, buckets=(16, 32),
                            prefill_chunk=32,
                            faults=FaultPlan(seed=0, clock=clock),
                            host_tier_bytes=host_bytes, swap_in_budget=10)
        rids = [None] * len(prompts)
        i = 0
        # paced arrivals: one request every 2 ticks, so each tenant's
        # prefix is long evicted (pool 28 pages, working set
        # n_tenants*9) before its next visit
        while i < len(prompts) or eng.has_work:
            if i < len(prompts) and eng.metrics.ticks % 2 == 0:
                rids[i] = eng.submit(prompts[i], max_tokens=8,
                                     tenant=tenants[i])
                i += 1
            eng.step()
            assert eng.metrics.ticks < 20000, "hosttier trace stuck"
        results = eng.run(max_ticks=1)      # drained: conservation check
        assert all(eng.status(r) is RequestStatus.COMPLETED for r in rids)
        eng.check_page_conservation()
        return [results[r] for r in rids], eng.metrics.snapshot()

    for n_tenants in (3, 5):
        outs_off, off = replay(n_tenants, host_bytes=0)
        outs_on, on = replay(n_tenants, host_bytes=1 << 22)
        assert outs_on == outs_off, \
            f"host tier broke greedy parity at {n_tenants} tenants"
        assert on["host_corrupt"] == 0
        assert on["host_swap_ins"] > 0, "tier never swapped in"
        assert on["prefix_hit_rate"] > off["prefix_hit_rate"], \
            (on["prefix_hit_rate"], off["prefix_hit_rate"])
        assert on["prefill_tokens"] < off["prefill_tokens"]
        tag = f"serving_hosttier_t{n_tenants}"
        out.update({
            f"{tag}_hit_rate_on": on["prefix_hit_rate"],
            f"{tag}_hit_rate_off": off["prefix_hit_rate"],
            f"{tag}_ttft_ms_p95_on": on["ttft_ms_p95"],
            f"{tag}_ttft_ms_p95_off": off["ttft_ms_p95"],
            f"{tag}_prefill_tokens_on": on["prefill_tokens"],
            f"{tag}_prefill_tokens_off": off["prefill_tokens"],
            f"{tag}_swap_ins": on["host_swap_ins"],
            f"{tag}_swap_outs": on["host_swap_outs"],
            f"{tag}_host_hits": on["host_hits"],
            f"{tag}_parity_ok": int(outs_on == outs_off),
        })

    # crash-warm restart replay: spill -> kill at a tick -> restart ->
    # the successor serves the same prompt from adopted host pages
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.02))

    def mk(i, time_fn):
        return ServingEngine(model, params, eos_id=eos, page_size=page,
                             num_pages=48, max_pages_per_seq=12,
                             max_slots=4, buckets=(16, 32),
                             time_fn=time_fn, host_tier_bytes=1 << 22,
                             swap_in_budget=10)

    fleet = FleetRouter(mk, 2, heartbeat_s=0.1, resubmit_budget=2,
                        faults=plan)
    rng = np.random.RandomState(7)
    prompt = rng.randint(2, vocab, size=64).tolist()
    f1 = fleet.submit(list(prompt), max_tokens=8)
    fleet.run(max_ticks=400)
    cold = fleet.result(f1)
    victim = next(r.idx for r in fleet.replicas
                  if r.engine.cache is not None and len(r.engine.cache))
    fleet.replicas[victim].engine.cache.flush()
    kill_tick = fleet._tick
    fleet.kill_replica(victim)
    new_idx = fleet.restart_replica(victim)
    fleet.drain_replica(1 - victim)
    for _ in range(5):
        fleet.step()
    f2 = fleet.submit(list(prompt), max_tokens=8)
    fleet.run(max_ticks=400)
    warm = fleet.result(f2)
    assert warm == cold, "warm restart broke greedy parity"
    assert fleet.metrics.pages_restored > 0, "restart restored 0 pages"
    assert fleet.metrics.duplicate_completions == 0
    fleet.check_fleet_conservation()
    succ = fleet.replicas[new_idx].engine.host_tier.snapshot()
    out.update({
        "serving_hosttier_restart_kill_tick": kill_tick,
        "serving_hosttier_restart_pages_restored":
            fleet.metrics.pages_restored,
        "serving_hosttier_restart_swap_ins": succ["host_swap_ins"],
        "serving_hosttier_restart_parity_ok": int(warm == cold),
        "serving_hosttier_restart_duplicate_completions":
            fleet.metrics.duplicate_completions,
    })
    print(json.dumps(out), flush=True)


def worker_moe():
    """MoE transformer LM vs its dense twin on one chip: single-chip
    Switch-style MoE (top-1 routing, dense dispatch formulation) at the
    same d_model/L/seq as a dense FFN model — the active FLOPs per token
    match, so moe_vs_dense_tokens_ratio isolates the routing +
    dispatch/combine overhead (the single-chip analog of the EP
    all_to_all cost; cross-chip EP needs the mesh the driver doesn't
    have)."""
    import jax
    import numpy as np

    paddle = _init_paddle()
    from paddle_tpu.models import transformer

    rng = np.random.RandomState(0)
    d, layers, heads, seq, bs, vocab, experts = (1024, 8, 16, 1024, 4,
                                                 32768, 8)
    samples = []
    for _ in range(bs):
        t = rng.randint(0, vocab, size=seq)
        samples.append((t.tolist(), list(range(seq)),
                        np.roll(t, -1).tolist()))

    def measure(n_experts, n_layers=layers):
        paddle.topology.reset_name_scope()
        tokens, pos, target, logits, costs = transformer.build(
            vocab_size=vocab, d_model=d, n_layers=n_layers, n_heads=heads,
            max_len=seq, moe_experts=n_experts)
        topo = paddle.topology.Topology(
            costs if isinstance(costs, list) else [costs])
        params = paddle.Parameters.from_topology(topo, seed=0)
        sgd = _make_sgd(costs, params)
        feeds = sgd._make_feeder({"tokens": 0, "pos": 1, "target": 2}).feed(
            samples)
        step = sgd._build_step()
        args = _step_args(sgd, feeds)
        step, flops = _aot_compile(step, args)
        sec = _time_steps(step, args, iters=6)
        return sec, flops

    # a small fast-compiling config FIRST: the relay window can die during
    # a big first compile (round-5 capture: this worker's L8 config
    # produced nothing in 600s), and a printed small row beats an
    # unprinted big one
    out = {}
    try:
        sec_s, _ = measure(experts, n_layers=2)
        out["moe_small_tokens_per_sec"] = round(bs * seq / sec_s, 1)
        out["moe_small_config"] = f"d{d} L2 E{experts} seq{seq} bs{bs}"
        print(json.dumps(out), flush=True)
        dense_s, _ = measure(0, n_layers=2)
        # > 1.0 means the MoE model moves FEWER tokens/sec than its dense
        # twin; the excess is routing + dispatch/combine overhead
        out["moe_small_vs_dense_step_ratio"] = round(sec_s / dense_s, 3)
        print(json.dumps(out), flush=True)
    except Exception as e:
        out["moe_small_error"] = repr(e)
        print(json.dumps(out), flush=True)

    sec, flops = measure(experts)
    out.update({
        "moe_tokens_per_sec": round(bs * seq / sec, 1),
        "moe_ms_per_batch": round(sec * 1000, 2),
        "moe_config": f"d{d} L{layers} E{experts} seq{seq} bs{bs}",
    })
    if flops:
        kind = jax.devices()[0].device_kind
        out["moe_achieved_tflops"] = round(flops / sec / 1e12, 2)
        out["moe_mfu"] = round(flops / sec / _peak_for(kind), 4)
    print(json.dumps(out), flush=True)  # full config before the dense twin
    try:
        dense_sec, _ = measure(0)
        out["moe_dense_twin_tokens_per_sec"] = round(bs * seq / dense_sec, 1)
        # > 1.0 means the MoE model moves FEWER tokens/sec than its dense
        # twin; the excess is routing + dispatch/combine overhead
        out["moe_vs_dense_step_ratio"] = round(sec / dense_sec, 3)
    except Exception as e:
        out["moe_dense_twin_error"] = repr(e)
    print(json.dumps(out), flush=True)


def worker_train_chaos():
    """Fault-tolerant training runtime under seeded chaos (ISSUE 14,
    cpu pass): the shared ``resilience.chaos.seeded_chaos`` replay —
    kill-at-step deaths, a kill between blob write and meta commit,
    injected NaN gradients (skipped in-graph by the bad-step guard),
    a slow-step window on the injected clock, step-granular ASYNC
    checkpoints — restarted by the resume supervisor and pinned
    bit-identical (final params + optimizer slots + per-step loss
    trajectory) against an uninterrupted control running the same
    poison schedule.  Also measures the async-save win directly: the
    train-loop stall (snapshot + pipeline waits) vs a fully synchronous
    save of the same state, plus the guarded step's overhead vs the
    unguarded step."""
    import shutil
    import tempfile
    import time as _t

    _init_paddle()
    import paddle_tpu as paddle
    from paddle_tpu import checkpoint as ckpt
    from paddle_tpu.resilience.chaos import (_build_trainer, _dataset,
                                             seeded_chaos)
    from paddle_tpu.resilience.guard import BadStepGuard

    root = tempfile.mkdtemp(prefix="bench_train_chaos_")
    try:
        out = seeded_chaos(root + "/chaos")
        problems = out.pop("problems")
        out["train_chaos_ok"] = int(not problems)
        if problems:
            out["train_chaos_problems"] = problems[:4]
        print(json.dumps(out), flush=True)  # headline before diagnostics

        # async-save win: stall the loop actually paid vs the same
        # checkpoint written synchronously
        sgd = _build_trainer(BadStepGuard())
        data = _dataset(0, 64)
        sgd.train(paddle.batch(lambda: iter(data), 8), num_passes=1)
        t0 = _t.perf_counter()
        ckpt.save_checkpoint(root + "/sync", 0, sgd.parameters,
                             opt_state=sgd.opt_state,
                             model_state=sgd.model_state)
        sync_s = _t.perf_counter() - t0
        t0 = _t.perf_counter()
        host = ckpt.snapshot_checkpoint(sgd.parameters,
                                        opt_state=sgd.opt_state,
                                        model_state=sgd.model_state)
        snap_s = _t.perf_counter() - t0
        del host
        out["train_ckpt_sync_save_ms"] = round(sync_s * 1000, 3)
        out["train_ckpt_snapshot_stall_ms"] = round(snap_s * 1000, 3)
        out["train_ckpt_async_stall_fraction"] = round(
            snap_s / max(sync_s, 1e-9), 3)
        print(json.dumps(out), flush=True)

        # guard overhead: guarded vs unguarded step time on one model
        def time_train(guard):
            s = _build_trainer(guard)
            r = paddle.batch(lambda: iter(data), 8)
            s.train(r, num_passes=1)          # compile + warm
            t0 = _t.perf_counter()
            for _ in range(3):
                s.train(r, num_passes=1)
            return (_t.perf_counter() - t0) / (3 * 8)

        guarded = time_train(BadStepGuard())
        plain = time_train(None)
        out["train_guard_step_overhead"] = round(
            guarded / max(plain, 1e-9), 3)
        out["train_guard_step_us"] = round(guarded * 1e6, 1)
        print(json.dumps(out), flush=True)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def worker_train_pipeline():
    """Pipeline-parallel train step (ISSUE 19, cpu pass) on the
    virtual-8 host: SGD(pipeline=PipelineConfig) over a 4-stage
    transformer.  Two probes:

    Parity — the first-2-step loss trajectory vs the sequential DSL
    baseline (rtol 5e-3: flash kernel vs mha_reference forward delta
    under Adam), plus tokens/s for both.

    Bubble — the GPipe schedule runs M+S-1 ticks, all of which execute
    full stage compute (fill/drain ticks chew on masked garbage), so on
    a SERIALIZED host (the virtual devices share one core; wall time =
    summed work) the wasted fraction is directly (S-1)/(M+S-1).  The
    baseline is an S=1 PIPELINE at the same M/batch — identical
    mha_reference kernels, identical microbatching, zero fill/drain —
    so measured_bubble = 1 - T(S=1)/T(S=4) isolates the schedule (a
    dense baseline would smuggle in the flash-vs-reference kernel
    difference).  The bubble probe uses a longer sequence than the
    parity probe so per-tick compute dwarfs the M-independent overhead
    (Adam update + grad psums, ~100ms) that would otherwise dilute the
    measurement.  ISSUE acceptance pin: within 10% of the closed
    form."""
    import jax
    import numpy as np

    paddle = _init_paddle()
    from paddle_tpu import optimizer, trainer
    from paddle_tpu.models import transformer
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.pipeline import PipelineConfig

    devs = jax.devices()
    assert len(devs) >= 8, f"need 8 virtual devices, have {len(devs)}"
    vocab, d, layers, heads = 512, 128, 4, 4
    micro, mb_size = 4, 2
    bs = micro * mb_size
    rng = np.random.RandomState(0)

    def _samples(seq):
        out = []
        for _ in range(bs):
            t = rng.randint(0, vocab, size=seq)
            out.append((t.tolist(), list(range(seq)),
                        np.roll(t, -1).tolist()))
        return out

    def build(stages, seq):
        paddle.topology.reset_name_scope()
        _, _, _, _, cost = transformer.build(
            vocab_size=vocab, d_model=d, n_layers=layers, n_heads=heads,
            max_len=seq)
        params = paddle.Parameters.from_topology(
            paddle.topology.Topology([cost]), seed=0)
        kw = {}
        if stages:
            kw["pipeline"] = PipelineConfig(
                num_stages=stages, microbatches=micro, n_layers=layers,
                n_heads=heads)
            kw["mesh"] = make_mesh((stages,), ("stage",), devs[:stages])
        sgd = trainer.SGD(cost=cost, parameters=params,
                          update_equation=optimizer.Adam(
                              learning_rate=1e-2), **kw)
        feeds = sgd._shard_feeds(sgd._make_feeder(
            {"tokens": 0, "pos": 1, "target": 2}).feed(_samples(seq)))
        return sgd, feeds

    def measure(stages, seq, iters=4):
        sgd, feeds = build(stages, seq)
        args = _step_args(sgd, feeds)
        step, _ = _aot_compile(sgd._build_step(), args)
        # 2-step loss pin alongside the timing
        p, o, m, key, f = args
        losses = []
        for _ in range(2):
            loss, p, o, m = [x for x in step(p, o, m, key, f)][:4]
            losses.append(float(loss))
        return _time_steps(step, args, iters=iters), losses

    parity_seq = 64
    seq_s, seq_losses = measure(0, parity_seq)
    pipe_s, pipe_losses = measure(4, parity_seq)
    out = {
        "pipeline_config": (f"d{d} L{layers} S4 M{micro} "
                            f"seq{parity_seq} bs{bs}"),
        "pipeline_tokens_per_sec": round(bs * parity_seq / pipe_s, 1),
        "pipeline_dense_tokens_per_sec": round(
            bs * parity_seq / seq_s, 1),
        "pipeline_loss_parity_ok": int(bool(np.allclose(
            pipe_losses, seq_losses, rtol=5e-3))),
        "pipeline_losses_2step": [round(x, 4) for x in pipe_losses],
    }
    print(json.dumps(out), flush=True)  # parity headline before bubble
    bubble_seq = 192
    s1_s, _ = measure(1, bubble_seq, iters=3)
    s4_s, _ = measure(4, bubble_seq, iters=3)
    closed = (4 - 1) / (micro + 4 - 1)
    measured = 1.0 - s1_s / max(s4_s, 1e-9)
    out.update({
        "pipeline_bubble_config": (f"d{d} L{layers} S4vsS1 M{micro} "
                                   f"seq{bubble_seq} bs{bs}"),
        "pipeline_bubble_measured": round(measured, 4),
        "pipeline_bubble_closed_form": round(closed, 4),
        "pipeline_bubble_rel_err": round(
            abs(measured - closed) / closed, 4),
    })
    print(json.dumps(out), flush=True)


def worker_train_moe():
    """Expert-parallel MoE dispatch (ISSUE 19, cpu pass) on the
    virtual-8 expert mesh: parallel.moe.moe_ffn (all_to_all dispatch/
    combine, top-2 gates renormalized) against moe_ffn_reference at
    generous capacity — outputs must agree to fp32 tolerance when
    nothing is dropped — plus the drop-rate stats the metrics registry
    records and EP tokens/s vs the dense reference formulation."""
    import jax
    import numpy as np

    _init_paddle()
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel import moe as pmoe

    devs = jax.devices()
    assert len(devs) >= 8, f"need 8 virtual devices, have {len(devs)}"
    n, d, hidden, tokens = 8, 64, 256, 512
    mesh = make_mesh((n,), ("expert",), devs[:n])
    params = pmoe.init_moe_params(jax.random.PRNGKey(0), d_model=d,
                                  hidden=hidden, num_experts=n)
    x = jax.random.normal(jax.random.PRNGKey(1), (tokens, d))

    yr, _ = pmoe.moe_ffn_reference(x, params, capacity_factor=float(n),
                                   top_k=2)
    ye, _, _ = pmoe.moe_ffn(mesh, x, params, capacity_factor=float(n),
                            top_k=2, return_stats=True)
    parity = float(np.max(np.abs(np.asarray(ye) - np.asarray(yr))))
    # drop-rate stats at the PRODUCTION capacity factor, recorded on the
    # metrics registry the way the zoo layer does
    _, _, stats = pmoe.moe_ffn(mesh, x, params, capacity_factor=1.25,
                               top_k=2, return_stats=True)
    pmoe.record_moe_stats(stats)
    drop = float(np.asarray(stats["drop_rate"]))

    def time_fn(fn, iters=8):
        fn()  # warm/compile
        import time as _t
        t0 = _t.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn())
        return (_t.perf_counter() - t0) / iters

    ep = jax.jit(lambda v: pmoe.moe_ffn(mesh, v, params,
                                        capacity_factor=1.25, top_k=2)[0])
    ref = jax.jit(lambda v: pmoe.moe_ffn_reference(
        v, params, capacity_factor=1.25, top_k=2)[0])
    ep_s, ref_s = time_fn(lambda: ep(x)), time_fn(lambda: ref(x))
    out = {
        "moe_ep_config": f"E{n} d{d} h{hidden} tok{tokens} top2 mesh8",
        "moe_ep_parity_max_abs": round(parity, 6),
        "moe_ep_parity_ok": int(parity < 1e-4),
        "moe_ep_tokens_per_sec": round(tokens / ep_s, 1),
        "moe_ep_vs_reference_step_ratio": round(ep_s / ref_s, 3),
    }
    out["moe_ep_drop_rate_cap1.25"] = round(drop, 4)
    out["moe_ep_stats_recorded"] = 1
    print(json.dumps(out), flush=True)


def worker_probe():
    """Fast TPU liveness check: init + one tiny matmul."""
    import jax
    import jax.numpy as jnp

    kind = jax.devices()[0].device_kind
    x = jnp.ones((256, 256), jnp.bfloat16)
    v = float((x @ x).sum())
    print(json.dumps({"probe_device_kind": kind, "probe_ok": v > 0}),
          flush=True)


def worker_matmul():
    """Achievable dense-MFU ceiling on this chip: chained bf16 matmuls at
    the transformer's dominant shapes. Calibrates the roofline the model
    MFU numbers are judged against — if [4096,2048]x[2048,8192] tops out
    at X, a model step cannot beat X and the gap model-vs-X is what
    optimization can actually recover."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    _init_paddle()
    kind = jax.devices()[0].device_kind
    peak = _peak_for(kind)
    rng = np.random.RandomState(0)
    out = {}
    for label, (m, k_, n) in (("ffn", (4096, 2048, 8192)),
                              ("proj", (4096, 2048, 2048)),
                              ("lmhead", (4096, 2048, 32768))):
        a = jnp.asarray(rng.randn(m, k_).astype(np.float32),
                        dtype=jnp.bfloat16)
        b = jnp.asarray(rng.randn(k_, n).astype(np.float32),
                        dtype=jnp.bfloat16)

        @jax.jit
        def chain(a, b):
            # 8 dependent matmuls so dispatch/transfer amortizes; the next
            # input reduces over ALL output columns (n is a multiple of k)
            # so XLA cannot dead-code-eliminate any part of the dot — a
            # plain slice would let it compute only the kept columns
            x = a
            for _ in range(8):
                y = jax.lax.dot(x, b, preferred_element_type=jnp.float32)
                x = y.reshape(m, n // k_, k_).sum(axis=1).astype(jnp.bfloat16)
            return x

        float(jnp.asarray(chain(a, b)).ravel()[0])  # compile
        float(jnp.asarray(chain(a, b)).ravel()[0])  # warm
        iters = 5
        start = time.perf_counter()
        for _ in range(iters):
            x = chain(a, b)
        float(jnp.asarray(x).ravel()[0])
        sec = (time.perf_counter() - start) / iters
        flops = 8 * 2.0 * m * k_ * n
        out[f"matmul_{label}_tflops"] = round(flops / sec / 1e12, 1)
        out[f"matmul_{label}_mfu"] = round(flops / sec / peak, 3)
        print(json.dumps(out), flush=True)
    print(json.dumps(out), flush=True)


WORKERS = {
    "probe": worker_probe,
    "matmul": worker_matmul,
    "resnet50": worker_resnet50,
    "alexnet": worker_alexnet,
    "lstm": worker_lstm,
    "convnets": worker_convnets,
    "transformer": worker_transformer,
    "attention": worker_attention,
    "scaling": worker_scaling,
    "zero1": worker_zero1,
    "serving": worker_serving,
    "serving_chaos": worker_serving_chaos,
    "serving_prefix": worker_serving_prefix,
    "serving_mixed": worker_serving_mixed,
    "serving_spec": worker_serving_spec,
    "serving_tp": worker_serving_tp,
    "serving_fleet": worker_serving_fleet,
    "serving_disagg": worker_serving_disagg,
    "serving_control": worker_serving_control,
    "serving_hosttier": worker_serving_hosttier,
    "train_chaos": worker_train_chaos,
    "train_pipeline": worker_train_pipeline,
    "train_moe": worker_train_moe,
    "moe": worker_moe,
}


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


def _last_json_line(text):
    """Parse the last JSON object line from worker stdout (or None)."""
    if isinstance(text, bytes):
        text = text.decode(errors="ignore")
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                pass
    return None


def _run_worker(name, deadline, cpu=False, attempt_timeout=420,
                max_attempts=3):
    """Run one worker in a subprocess with retry/backoff under the global
    deadline. Returns (dict-or-None, error-string-or-None)."""
    last_err = None
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining < 30:
            return None, last_err or "global deadline exhausted"
        attempt += 1
        env = dict(os.environ)
        if cpu:
            from paddle_tpu.platform.virtual import virtual_cpu_env

            env = virtual_cpu_env(
                env, 8,
                extra_pythonpath=os.path.dirname(os.path.abspath(__file__)))
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker", name],
                env=env, timeout=min(remaining - 10, attempt_timeout),
                capture_output=True, text=True)
        except subprocess.TimeoutExpired as te:
            # salvage a partial result: workers print their headline JSON
            # early (before diagnostics) exactly so a later hang doesn't
            # lose the measurement — but MARK the run as cut short
            got = _last_json_line(te.stdout)
            if got is not None:
                got["salvaged_after"] = "timeout"
                return got, None
            last_err = f"{name}: timeout (attempt {attempt})"
            if attempt >= max_attempts:
                return None, last_err
            continue
        if r.returncode == 0:
            got = _last_json_line(r.stdout)
            if got is not None:
                return got, None
            last_err = f"{name}: no JSON in output"
        else:
            # a crash AFTER the early headline print still keeps the
            # measurement (annotated) instead of burning retries
            got = _last_json_line(r.stdout)
            if got is not None:
                got["salvaged_after"] = f"rc={r.returncode}"
                return got, None
            tail = (r.stderr or r.stdout or "").strip().splitlines()[-3:]
            last_err = f"{name}: rc={r.returncode} {' | '.join(tail)}"
        if attempt >= max_attempts:
            return None, last_err
        # transient backend unavailability: back off before retrying
        time.sleep(min(15 * attempt, max(0.0, deadline - time.monotonic())))


def main():
    deadline = time.monotonic() + GLOBAL_DEADLINE_S
    record = {}
    errors = {}

    # cheap + hardware-independent first: never starved by a dead tunnel
    for cpu_worker in ("scaling", "zero1", "serving", "serving_chaos",
                       "serving_prefix", "serving_mixed", "serving_spec",
                       "serving_tp",
                       "serving_fleet", "serving_disagg", "serving_control",
                       "serving_hosttier", "train_chaos",
                       "train_pipeline", "train_moe"):
        out, err = _run_worker(cpu_worker, deadline, cpu=True,
                               attempt_timeout=380, max_attempts=1)
        if out:
            record.update(out)
        else:
            errors[cpu_worker] = err

    # fast liveness probe: a dead TPU tunnel HANGS (round-1 failure mode);
    # fail it fast rather than crawling through per-model retries
    probe, perr = _run_worker("probe", deadline, attempt_timeout=120,
                              max_attempts=3)
    if probe:
        record.update(probe)
        # the transformer MFU is THE round-4 headline (VERDICT r3 item 1)
        # and the relay can flap: measure it first, then the other
        # headline families, diagnostics last
        for name in ("transformer", "resnet50", "lstm", "convnets",
                     "alexnet", "attention", "moe"):
            out, err = _run_worker(name, deadline)
            if out:
                record.update(out)
            else:
                errors[name] = err
            _emit_result(record, errors, final=False)
    else:
        errors["tpu"] = f"unreachable: {perr}"

    if errors or "salvaged_after" in record:
        # LAST_ONCHIP.json carries provenance-marked numbers measured on
        # the real chip in an earlier capture window (it documents
        # when/what inside itself and is maintained as a data artifact,
        # not code): attached NOT-fresh, clearly labeled, whenever the
        # relay was unreachable OR some workers couldn't run within the
        # deadline — a partial bench run doesn't erase what was actually
        # measured. Fresh top-level fields take precedence.
        try:
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "LAST_ONCHIP.json")) as f:
                record["last_onchip_measurements"] = json.load(f)
        except Exception:
            pass

    _emit_result(record, errors, final=True)
    return 0


def _emit_result(record, errors, *, final):
    """Assemble and print the aggregate result line. Called after EVERY
    worker (not just at the end): if the driver kills this process before
    all workers finish, the last printed line is still a complete,
    parseable result with everything measured so far."""
    value = record.get("resnet50_images_per_sec_per_chip")
    alex = record.get("alexnet_ms_per_batch")
    result = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": value if value is not None else 0.0,
        "unit": "images/sec/chip",
        # only published reference headline: AlexNet bs=128, 334 ms on K40m
        "vs_baseline": (round(ALEXNET_BASELINE_MS / alex, 3)
                        if alex else 0.0),
        "vs_baseline_basis": "alexnet_bs128_ms_per_batch_K40m_334ms",
    }
    if record.get("lstm_ms_per_batch"):
        result["lstm_vs_baseline"] = round(
            LSTM_BASELINE_MS / record["lstm_ms_per_batch"], 3)
    result.update(record)
    if errors:
        result["errors"] = dict(errors)
    if not final:
        result["partial"] = True
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        WORKERS[sys.argv[2]]()
        sys.exit(0)
    sys.exit(main())
