#!/usr/bin/env python
"""Benchmark: AlexNet bs=128 train step on one TPU chip vs the reference's
headline number (PaddlePaddle on K40m: 334 ms/batch — BASELINE.md,
reference benchmark/README.md:33-38).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms/batch", "vs_baseline": N}
vs_baseline > 1 means faster than the reference by that factor.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import optimizer, trainer
    from paddle_tpu.models import alexnet

    paddle.init()
    batch_size = 128
    img_size = 227

    paddle.topology.reset_name_scope()
    images, label, logits, cost = alexnet.build(img_size=img_size)
    topo = paddle.topology.Topology([cost])
    params = paddle.Parameters.from_topology(topo, seed=0)
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer.Momentum(momentum=0.9,
                                                         learning_rate=0.01))

    rng = np.random.RandomState(0)
    feeds_np = [
        (rng.randn(3 * img_size * img_size).astype(np.float32), int(rng.randint(1000)))
        for _ in range(batch_size)
    ]
    feeder = sgd._make_feeder(None)
    feeds = feeder.feed(feeds_np)

    step = sgd._build_step()
    p = params.as_dict()
    opt_state = sgd.opt_state
    mstate = sgd.model_state
    key = jax.random.PRNGKey(0)

    # warmup / compile; a concrete value fetch is the only reliable
    # completion barrier over the remote-TPU relay (block_until_ready
    # returns optimistically there)
    loss, p, opt_state, mstate, _ = step(p, opt_state, mstate, key, feeds)
    float(loss)

    iters = 50
    start = time.perf_counter()
    for i in range(iters):
        loss, p, opt_state, mstate, _ = step(p, opt_state, mstate, key, feeds)
    float(loss)  # forces the whole dependent step chain to complete
    elapsed = time.perf_counter() - start
    ms_per_batch = elapsed / iters * 1000.0

    baseline_ms = 334.0  # reference Paddle, AlexNet bs=128, K40m
    print(json.dumps({
        "metric": "alexnet_bs128_train_ms_per_batch",
        "value": round(ms_per_batch, 3),
        "unit": "ms/batch",
        "vs_baseline": round(baseline_ms / ms_per_batch, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
