"""Fluid-analog python layer builders — append ops/vars to the Program.

Reference analog: python/paddle/v2/framework/layers.py (fc/embedding/conv2d/
pool2d/cross_entropy/StaticRNN; auto-generated op wrappers `_create_op_func_`
layers.py:98) and layer_helper.py.

These only BUILD the Program; execution is Executor (one jitted XLA program).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu.fluid.framework import (Parameter, Program, Variable,
                                        default_main_program)
from paddle_tpu.platform.enforce import EnforceError, enforce_that


def _block():
    return default_main_program().current_block()


def _tmp(shape=(), dtype="float32", lod_level=0):
    return _block().create_var(shape=shape, dtype=dtype, lod_level=lod_level)


def _to_var(x, like: Variable) -> Variable:
    """Literal scalars become fill_constant vars (expression sugar)."""
    if isinstance(x, Variable):
        return x
    out = _tmp(shape=(1,), dtype=like.dtype)
    _block().append_op("fill_constant", outputs={"Out": out},
                       attrs={"shape": [1], "value": float(x),
                              "dtype": like.dtype})
    return out


# ---------------------------------------------------------------------------
# data / parameters
# ---------------------------------------------------------------------------


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0, append_batch_size: bool = True) -> Variable:
    """Feed placeholder (v2/framework/layers.py data)."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    b = default_main_program().global_block()
    v = b.create_var(name=name, shape=shape, dtype=dtype,
                     lod_level=lod_level)
    v.stop_gradient = True
    return v


def create_parameter(shape, dtype="float32", name=None, initializer=None,
                     trainable=True) -> Parameter:
    return default_main_program().global_block().create_parameter(
        name=name, shape=shape, dtype=dtype, initializer=initializer,
        trainable=trainable)



def _conv_out(hw, k, stride, pad, dil=1):
    return (hw + 2 * pad - dil * (k - 1) - 1) // stride + 1


def _pair2(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v), int(v))

# ---------------------------------------------------------------------------
# core layers
# ---------------------------------------------------------------------------


def fc(input, size: int, act: Optional[str] = None, bias_attr=True,
       num_flatten_dims: int = 1, param_initializer=None,
       name: Optional[str] = None) -> Variable:
    inputs = input if isinstance(input, (list, tuple)) else [input]
    prog = default_main_program()
    name = name or prog.unique_name("fc")
    mul_outs = []
    for i, inp in enumerate(inputs):
        in_dim = int(np.prod(inp.shape[num_flatten_dims:]))
        w = create_parameter((in_dim, size), dtype=inp.dtype,
                             name=f"{name}.w_{i}",
                             initializer=param_initializer)
        out = _tmp(shape=tuple(inp.shape[:num_flatten_dims]) + (size,),
                   lod_level=inp.lod_level)
        _block().append_op("mul", inputs={"X": inp, "Y": w},
                           outputs={"Out": out},
                           attrs={"x_num_col_dims": num_flatten_dims,
                                  "y_num_col_dims": 1})
        mul_outs.append(out)
    pre = mul_outs[0]
    if len(mul_outs) > 1:
        s = _tmp(shape=pre.shape)
        _block().append_op("sum", inputs={"X": mul_outs},
                           outputs={"Out": s})
        pre = s
    if bias_attr:
        b = create_parameter((size,), dtype=pre.dtype, name=f"{name}.b",
                             initializer={"type": "constant", "value": 0.0})
        out = _tmp(shape=pre.shape, lod_level=pre.lod_level)
        _block().append_op("elementwise_add", inputs={"X": pre, "Y": b},
                           outputs={"Out": out}, attrs={"axis": -1})
        pre = out
    return _apply_act(pre, act)


def embedding(input, size, dtype="float32", param_name=None,
              name=None) -> Variable:
    vocab, dim = size
    w = create_parameter((vocab, dim), dtype=dtype,
                         name=param_name
                         or default_main_program().unique_name("emb.w"),
                         initializer={"type": "uniform", "low": -0.1,
                                      "high": 0.1})
    out = _tmp(shape=(-1, dim), dtype=dtype, lod_level=input.lod_level)
    _block().append_op("lookup_table", inputs={"W": w, "Ids": input},
                       outputs={"Out": out})
    return out


def conv2d(input, num_filters: int, filter_size, stride=1, padding=0,
           dilation=1, groups: int = 1, act: Optional[str] = None,
           bias_attr=True, name: Optional[str] = None) -> Variable:
    prog = default_main_program()
    name = name or prog.unique_name("conv2d")
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    in_ch = int(input.shape[1])
    w = create_parameter((num_filters, in_ch // groups, k[0], k[1]),
                         dtype=input.dtype, name=f"{name}.w")
    ins = {"Input": input, "Filter": w}
    if bias_attr:
        ins["Bias"] = create_parameter(
            (num_filters,), dtype=input.dtype, name=f"{name}.b",
            initializer={"type": "constant", "value": 0.0})
    st, pd = _pair2(stride), _pair2(padding)
    dl = _pair2(dilation)
    h, w_ = int(input.shape[2]), int(input.shape[3])
    out = _tmp(shape=(input.shape[0], num_filters,
                      _conv_out(h, k[0], st[0], pd[0], dl[0]),
                      _conv_out(w_, k[1], st[1], pd[1], dl[1])))
    _block().append_op("conv2d", inputs=ins, outputs={"Output": out},
                       attrs={"strides": stride, "paddings": padding,
                              "dilations": dilation, "groups": groups})
    return _apply_act(out, act)


def conv2d_transpose(input, num_filters: int, filter_size, stride=1,
                     padding=0, name=None) -> Variable:
    prog = default_main_program()
    name = name or prog.unique_name("conv2d_transpose")
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    in_ch = int(input.shape[1])
    w = create_parameter((in_ch, num_filters, k[0], k[1]),
                         dtype=input.dtype, name=f"{name}.w")
    st, pd = _pair2(stride), _pair2(padding)
    h, w_ = int(input.shape[2]), int(input.shape[3])
    out = _tmp(shape=(input.shape[0], num_filters,
                      (h - 1) * st[0] - 2 * pd[0] + k[0],
                      (w_ - 1) * st[1] - 2 * pd[1] + k[1]))
    _block().append_op("conv2d_transpose",
                       inputs={"Input": input, "Filter": w},
                       outputs={"Output": out},
                       attrs={"strides": stride, "paddings": padding})
    return out


def pool2d(input, pool_size=2, pool_type="max", pool_stride=None,
           pool_padding=0, global_pooling=False) -> Variable:
    if global_pooling:
        shape = (input.shape[0], input.shape[1], 1, 1)
    else:
        k, st = _pair2(pool_size), _pair2(pool_stride or pool_size)
        pd = _pair2(pool_padding)
        shape = (input.shape[0], input.shape[1],
                 _conv_out(int(input.shape[2]), k[0], st[0], pd[0]),
                 _conv_out(int(input.shape[3]), k[1], st[1], pd[1]))
    out = _tmp(shape=shape)
    _block().append_op("pool2d", inputs={"X": input}, outputs={"Out": out},
                       attrs={"ksize": pool_size,
                              "strides": pool_stride or pool_size,
                              "paddings": pool_padding,
                              "pooling_type": pool_type,
                              "global_pooling": global_pooling})
    return out


def batch_norm(input, act: Optional[str] = None, momentum=0.9, epsilon=1e-5,
               data_layout="NCHW", name=None) -> Variable:
    prog = default_main_program()
    name = name or prog.unique_name("batch_norm")
    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    g = prog.global_block()
    scale = create_parameter((c,), input.dtype, f"{name}.scale",
                             initializer={"type": "constant", "value": 1.0})
    bias = create_parameter((c,), input.dtype, f"{name}.bias",
                            initializer={"type": "constant", "value": 0.0})
    mean = g.create_var(name=f"{name}.mean", shape=(c,), dtype=input.dtype,
                        persistable=True)
    mean.initializer = {"type": "constant", "value": 0.0}
    var = g.create_var(name=f"{name}.variance", shape=(c,),
                       dtype=input.dtype, persistable=True)
    var.initializer = {"type": "constant", "value": 1.0}
    y = _tmp(shape=input.shape)
    saved_m, saved_v = _tmp(shape=(c,)), _tmp(shape=(c,))
    _block().append_op(
        "batch_norm",
        inputs={"X": input, "Scale": scale, "Bias": bias, "Mean": mean,
                "Variance": var},
        outputs={"Y": y, "MeanOut": mean, "VarianceOut": var,
                 "SavedMean": saved_m, "SavedVariance": saved_v},
        attrs={"momentum": momentum, "epsilon": epsilon,
               "data_layout": data_layout})
    return _apply_act(y, act)


def dropout(x, dropout_prob=0.5, is_test=False) -> Variable:
    out = _tmp(shape=x.shape, lod_level=x.lod_level)
    mask = _tmp(shape=x.shape)
    _block().append_op("dropout", inputs={"X": x},
                       outputs={"Out": out, "Mask": mask},
                       attrs={"dropout_prob": dropout_prob,
                              "is_test": is_test})
    return out


# ---------------------------------------------------------------------------
# losses / metrics
# ---------------------------------------------------------------------------


def cross_entropy(input, label, soft_label=False) -> Variable:
    out = _tmp(shape=(input.shape[0], 1), lod_level=input.lod_level)
    _block().append_op("cross_entropy", inputs={"X": input, "Label": label},
                       outputs={"Y": out},
                       attrs={"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False):
    softmax = _tmp(shape=logits.shape)
    loss = _tmp(shape=(logits.shape[0], 1))
    _block().append_op("softmax_with_cross_entropy",
                       inputs={"Logits": logits, "Label": label},
                       outputs={"Softmax": softmax, "Loss": loss},
                       attrs={"soft_label": soft_label})
    return loss


def square_error_cost(input, label) -> Variable:
    sub = _tmp(shape=input.shape)
    out = _tmp(shape=(input.shape[0], 1))
    _block().append_op("squared_l2_distance",
                       inputs={"X": input, "Y": label},
                       outputs={"sub_result": sub, "Out": out})
    return out


def accuracy(input, label, k: int = 1) -> Variable:
    topk_out, topk_idx = _tmp(), _tmp(dtype="int64")
    _block().append_op("top_k", inputs={"X": input},
                       outputs={"Out": topk_out, "Indices": topk_idx},
                       attrs={"k": k})
    acc = _tmp()
    correct = _tmp(dtype="int64")
    total = _tmp(dtype="int64")
    _block().append_op("accuracy",
                       inputs={"Out": topk_idx, "Label": label},
                       outputs={"Accuracy": acc, "Correct": correct,
                                "Total": total})
    acc.stop_gradient = True
    return acc


def mean(x) -> Variable:
    out = _tmp(shape=())
    _block().append_op("mean", inputs={"X": x}, outputs={"Out": out})
    return out


def sums(inputs) -> Variable:
    inputs = list(inputs)
    out = _tmp(shape=inputs[0].shape)
    _block().append_op("sum", inputs={"X": list(inputs)},
                       outputs={"Out": out})
    return out


# ---------------------------------------------------------------------------
# auto-generated unary / misc wrappers (`_create_op_func_` analog)
# ---------------------------------------------------------------------------


def _make_unary(op_type):
    def f(x, **attrs):
        out = _tmp(shape=getattr(x, "shape", ()),
                   lod_level=getattr(x, "lod_level", 0))
        _block().append_op(op_type, inputs={"X": x}, outputs={"Out": out},
                           attrs=attrs)
        return out
    f.__name__ = op_type
    return f


for _op in ["sigmoid", "logsigmoid", "exp", "relu", "tanh", "sqrt", "abs",
            "reciprocal", "log", "square", "softsign", "brelu", "soft_relu",
            "pow", "stanh", "leaky_relu", "relu6", "softplus", "elu", "sign",
            "floor", "ceil", "round", "softmax"]:
    globals()[_op] = _make_unary(_op)


def _elementwise(op_type, x, y, axis=-1):
    y = _to_var(y, x) if not isinstance(y, Variable) else y
    x = _to_var(x, y) if not isinstance(x, Variable) else x
    shape = x.shape if len(x.shape) >= len(y.shape) else y.shape
    out = _tmp(shape=shape, lod_level=max(x.lod_level, y.lod_level))
    _block().append_op(op_type, inputs={"X": x, "Y": y},
                       outputs={"Out": out}, attrs={"axis": axis})
    return out


def elementwise_add(x, y, axis=-1):
    return _elementwise("elementwise_add", x, y, axis)


def elementwise_sub(x, y, axis=-1):
    return _elementwise("elementwise_sub", x, y, axis)


def elementwise_mul(x, y, axis=-1):
    return _elementwise("elementwise_mul", x, y, axis)


def elementwise_div(x, y, axis=-1):
    return _elementwise("elementwise_div", x, y, axis)


def scale(x, scale=1.0, bias=0.0) -> Variable:
    out = _tmp(shape=x.shape, lod_level=x.lod_level)
    _block().append_op("scale", inputs={"X": x}, outputs={"Out": out},
                       attrs={"scale": scale, "bias": bias})
    return out


def cast(x, dtype) -> Variable:
    out = _tmp(shape=x.shape, dtype=dtype, lod_level=x.lod_level)
    _block().append_op("cast", inputs={"X": x}, outputs={"Out": out},
                       attrs={"out_dtype": dtype})
    return out


def clip(x, min, max) -> Variable:
    out = _tmp(shape=x.shape, lod_level=x.lod_level)
    _block().append_op("clip", inputs={"X": x}, outputs={"Out": out},
                       attrs={"min": min, "max": max})
    return out


def concat(inputs, axis=0) -> Variable:
    out = _tmp()
    _block().append_op("concat", inputs={"X": list(inputs)},
                       outputs={"Out": out}, attrs={"axis": axis})
    return out


def reshape(x, shape) -> Variable:
    out = _tmp(shape=tuple(shape))
    _block().append_op("reshape", inputs={"X": x}, outputs={"Out": out},
                       attrs={"shape": list(shape)})
    return out


def transpose(x, perm) -> Variable:
    out = _tmp(shape=tuple(x.shape[p] for p in perm) if x.shape else ())
    _block().append_op("transpose", inputs={"X": x}, outputs={"Out": out},
                       attrs={"axis": list(perm)})
    return out


def crop(x, offsets, shape) -> Variable:
    out = _tmp(shape=tuple(shape))
    _block().append_op("crop", inputs={"X": x}, outputs={"Out": out},
                       attrs={"offsets": list(offsets),
                              "shape": list(shape)})
    return out


def pad(x, paddings, pad_value=0.0) -> Variable:
    out = _tmp()
    _block().append_op("pad", inputs={"X": x}, outputs={"Out": out},
                       attrs={"paddings": list(paddings),
                              "pad_value": pad_value})
    return out


def split(x, num_or_sections, axis=0) -> List[Variable]:
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "axis": axis}
    else:
        n = len(num_or_sections)
        attrs = {"sections": list(num_or_sections), "axis": axis}
    outs = [_tmp() for _ in range(n)]
    _block().append_op("split", inputs={"X": x}, outputs={"Out": outs},
                       attrs=attrs)
    return outs


def topk(x, k=1):
    vals, idx = _tmp(), _tmp(dtype="int64")
    _block().append_op("top_k", inputs={"X": x},
                       outputs={"Out": vals, "Indices": idx},
                       attrs={"k": k})
    return vals, idx


def reduce_sum(x, dim=None, keep_dim=False) -> Variable:
    out = _tmp()
    _block().append_op("reduce_sum", inputs={"X": x}, outputs={"Out": out},
                       attrs={"dim": dim, "keep_dim": keep_dim,
                              "reduce_all": dim is None})
    return out


def reduce_mean(x, dim=None, keep_dim=False) -> Variable:
    out = _tmp()
    _block().append_op("reduce_mean", inputs={"X": x}, outputs={"Out": out},
                       attrs={"dim": dim, "keep_dim": keep_dim,
                              "reduce_all": dim is None})
    return out


def sequence_pool(input, pool_type="average") -> Variable:
    out = _tmp(shape=input.shape)
    _block().append_op("sequence_pool", inputs={"X": input},
                       outputs={"Out": out},
                       attrs={"pooltype": pool_type.upper()})
    return out


def sequence_softmax(input) -> Variable:
    out = _tmp(lod_level=input.lod_level)
    _block().append_op("sequence_softmax", inputs={"X": input},
                       outputs={"Out": out})
    return out


def sequence_expand(x, y) -> Variable:
    out = _tmp(lod_level=max(1, y.lod_level))
    _block().append_op("sequence_expand", inputs={"X": x, "Y": y},
                       outputs={"Out": out})
    return out


def _apply_act(x: Variable, act: Optional[str]) -> Variable:
    if act is None:
        return x
    enforce_that(act in ("sigmoid", "relu", "tanh", "softmax", "sqrt",
                         "abs", "log", "exp", "square", "brelu",
                         "soft_relu", "stanh", "leaky_relu", "softsign"),
                 f"unknown activation {act!r}", context="fluid")
    return globals()[act](x)


# ---------------------------------------------------------------------------
# StaticRNN (layers.py:333 analog) — builds a sub-block lowered to lax.scan
# ---------------------------------------------------------------------------


class StaticRNN:
    """Time-major static RNN.

    Usage::

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: [T, B, D]
            h_prev = rnn.memory(shape=(B, H), init_value=0.)
            h = some_layers(x_t, h_prev)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        outs = rnn()                          # [T, B, H]
    """

    def __init__(self):
        self.program = default_main_program()
        self.sub_block = None
        self._seq_inputs: List[Variable] = []       # outer [T, ...] vars
        self._step_inputs: List[Variable] = []      # sub-block per-step vars
        self._init_states: List[Variable] = []
        self._state_in: List[Variable] = []
        self._state_out: List[Optional[Variable]] = []
        self._step_outputs: List[Variable] = []
        self._built = False

    class _Guard:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            self.rnn.sub_block = self.rnn.program.create_block()
            return self.rnn

        def __exit__(self, *exc):
            self.rnn.program.rollback()
            return False

    def step(self):
        return self._Guard(self)

    def step_input(self, x: Variable) -> Variable:
        self._seq_inputs.append(x)
        v = self.sub_block.create_var(
            name=self.program.unique_name("rnn_step_in"),
            shape=x.shape[1:], dtype=x.dtype)
        self._step_inputs.append(v)
        return v

    def memory(self, init: Optional[Variable] = None, shape=None,
               init_value: float = 0.0, dtype="float32") -> Variable:
        if init is None:
            enforce_that(shape is not None, "memory needs init or shape",
                         context="StaticRNN")
            g = self.program.global_block()
            init = g.create_var(
                name=self.program.unique_name("rnn_init"),
                shape=shape, dtype=dtype, persistable=True)
            init.initializer = {"type": "constant", "value": init_value}
        self._init_states.append(init)
        v = self.sub_block.create_var(
            name=self.program.unique_name("rnn_mem"),
            shape=init.shape, dtype=init.dtype)
        self._state_in.append(v)
        self._state_out.append(None)
        return v

    def update_memory(self, mem: Variable, new: Variable) -> None:
        i = self._state_in.index(mem)
        self._state_out[i] = new

    def step_output(self, o: Variable) -> None:
        self._step_outputs.append(o)

    def __call__(self):
        enforce_that(not self._built, "StaticRNN already finalized",
                     context="StaticRNN")
        enforce_that(all(s is not None for s in self._state_out),
                     "every memory needs update_memory", context="StaticRNN")
        self._built = True
        # every parent-block var the step graph reads (parameters, biases)
        # is routed through the op's Parameters slot so autodiff sees it
        local = set(self.sub_block.vars)
        used, seen = [], set()
        for op in self.sub_block.ops:
            for n in op.input_names():
                if n not in local and n not in seen:
                    seen.add(n)
                    used.append(self.program.global_block().var(n))
        outs = [self.program.global_block().create_var(
            name=self.program.unique_name("rnn_out"), dtype=o.dtype)
            for o in self._step_outputs]
        finals = [self.program.global_block().create_var(
            name=self.program.unique_name("rnn_final"), dtype=s.dtype)
            for s in self._state_out]
        self.program.global_block().append_op(
            "recurrent",
            inputs={"Inputs": self._seq_inputs,
                    "InitStates": self._init_states,
                    "Parameters": used},
            outputs={"Outputs": outs, "FinalStates": finals},
            attrs={"sub_block": self.sub_block.idx,
                   "step_inputs": [v.name for v in self._step_inputs],
                   "step_states_in": [v.name for v in self._state_in],
                   "step_states_out": [v.name for v in self._state_out],
                   "step_outputs": [v.name for v in self._step_outputs],
                   "param_names": [v.name for v in used]})
        return outs[0] if len(outs) == 1 else outs


def _collect_outer_vars(program, sub_blocks):
    """Parent-block vars read by sub-block ops, routed through the op's
    input slots so program-level autodiff reaches them (StaticRNN-style)."""
    used, seen = [], set()
    for sub in sub_blocks:
        local = set(sub.vars)
        for op in sub.ops:
            for n in op.input_names():
                if n not in local and n not in seen:
                    seen.add(n)
                    used.append(program.global_block().var(n))
    return used


def cond(pred: Variable, true_fn, false_fn):
    """Dynamic if-else (cond_op.h analog): ``pred`` is a per-row [N] mask;
    row i of the output comes from ``true_fn``'s graph where pred[i] else
    ``false_fn``'s. Both branch graphs are built as sub-blocks; on TPU both
    run on the full batch and a masked merge selects rows (static shapes —
    see the cond op docstring in ops.py). Each fn takes no args, reads
    enclosing vars, and returns one Variable (or a list, matched 1:1)."""
    prog = default_main_program()
    tb = prog.create_block()
    t_out = true_fn()
    prog.rollback()
    fb = prog.create_block()
    f_out = false_fn()
    prog.rollback()
    t_outs = t_out if isinstance(t_out, (list, tuple)) else [t_out]
    f_outs = f_out if isinstance(f_out, (list, tuple)) else [f_out]
    enforce_that(len(t_outs) == len(f_outs),
                 "cond branches must return the same number of outputs",
                 context="cond")
    used = _collect_outer_vars(prog, [tb, fb])
    outs = [prog.global_block().create_var(
        name=prog.unique_name("cond_out"), shape=o.shape, dtype=o.dtype)
        for o in t_outs]
    prog.global_block().append_op(
        "cond",
        inputs={"Cond": pred, "Xs": used},
        outputs={"Out": outs},
        attrs={"true_block": tb.idx, "false_block": fb.idx,
               "true_outputs": [v.name for v in t_outs],
               "false_outputs": [v.name for v in f_outs],
               "x_names": [v.name for v in used]})
    return outs[0] if len(outs) == 1 else outs


class DynamicRNN:
    """Variable-length RNN over a LoD input (dynamic_recurrent_op analog).

    Same shape as StaticRNN but ``step_input`` takes a lod_level-1 var
    (ragged rows); the op packs it to padded time-major once, scans with
    mask-gated memories, and returns a LoD output in the input's order::

        drnn = DynamicRNN()
        with drnn.step():
            x_t = drnn.step_input(x)              # x: LoD rows [R, D]
            h_prev = drnn.memory(shape=(B, H))
            h = some_layers(x_t, h_prev)
            drnn.update_memory(h_prev, h)
            drnn.step_output(h)
        out = drnn()                              # LoD rows [R, H]
    """

    def __init__(self, reverse: bool = False):
        self.program = default_main_program()
        self.sub_block = None
        self.reverse = reverse
        self._seq_input: Optional[Variable] = None
        self._step_in: Optional[Variable] = None
        self._init_states: List[Variable] = []
        self._state_in: List[Variable] = []
        self._state_out: List[Optional[Variable]] = []
        self._step_outputs: List[Variable] = []
        self._built = False

    class _Guard:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            self.rnn.sub_block = self.rnn.program.create_block()
            return self.rnn

        def __exit__(self, *exc):
            self.rnn.program.rollback()
            return False

    def step(self):
        return self._Guard(self)

    def step_input(self, x: Variable) -> Variable:
        enforce_that(self._seq_input is None,
                     "DynamicRNN supports one sequence input",
                     context="DynamicRNN")
        enforce_that(x.lod_level >= 1, "DynamicRNN input must be LoD",
                     context="DynamicRNN")
        self._seq_input = x
        v = self.sub_block.create_var(
            name=self.program.unique_name("drnn_step_in"),
            shape=x.shape, dtype=x.dtype)
        self._step_in = v
        return v

    def memory(self, init: Optional[Variable] = None, shape=None,
               init_value: float = 0.0, dtype="float32") -> Variable:
        if init is None:
            enforce_that(shape is not None, "memory needs init or shape",
                         context="DynamicRNN")
            g = self.program.global_block()
            init = g.create_var(
                name=self.program.unique_name("drnn_init"),
                shape=shape, dtype=dtype, persistable=True)
            init.initializer = {"type": "constant", "value": init_value}
        self._init_states.append(init)
        v = self.sub_block.create_var(
            name=self.program.unique_name("drnn_mem"),
            shape=init.shape, dtype=init.dtype)
        self._state_in.append(v)
        self._state_out.append(None)
        return v

    def update_memory(self, mem: Variable, new: Variable) -> None:
        i = self._state_in.index(mem)
        self._state_out[i] = new

    def step_output(self, o: Variable) -> None:
        self._step_outputs.append(o)

    def __call__(self):
        enforce_that(not self._built, "DynamicRNN already finalized",
                     context="DynamicRNN")
        enforce_that(self._seq_input is not None, "no step_input",
                     context="DynamicRNN")
        enforce_that(all(s is not None for s in self._state_out),
                     "every memory needs update_memory", context="DynamicRNN")
        self._built = True
        used = _collect_outer_vars(self.program, [self.sub_block])
        outs = [self.program.global_block().create_var(
            name=self.program.unique_name("drnn_out"), dtype=o.dtype,
            shape=(-1,) + tuple(o.shape[1:]), lod_level=1)
            for o in self._step_outputs]
        finals = [self.program.global_block().create_var(
            name=self.program.unique_name("drnn_final"), dtype=s.dtype)
            for s in self._state_out]
        self.program.global_block().append_op(
            "dynamic_recurrent",
            inputs={"Inputs": self._seq_input,
                    "InitStates": self._init_states,
                    "Parameters": used},
            outputs={"Outputs": outs, "FinalStates": finals},
            attrs={"sub_block": self.sub_block.idx,
                   "step_inputs": [self._step_in.name],
                   "step_states_in": [v.name for v in self._state_in],
                   "step_states_out": [v.name for v in self._state_out],
                   "step_outputs": [v.name for v in self._step_outputs],
                   "param_names": [v.name for v in used],
                   "reverse": self.reverse})
        return outs[0] if len(outs) == 1 else outs
